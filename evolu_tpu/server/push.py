"""Relay-held push subscriptions: wake affected clients on mutation
instead of waiting for their next polling sync round (ISSUE 13,
ROADMAP #4).

The hub gates wakeups on exactly the metadata E2EE exposes to the
relay: the OWNER a batch belongs to, and the AUTHOR NODE of each newly
visible row (the 16-hex-char suffix of its plaintext timestamp — the
same field the serve path's `timestamp NOT LIKE '%' || nodeId`
exclusion reads). Value-level query evaluation stays client-side: a
wakeup only tells the subscriber "rows you don't have may exist; run a
sync round". This is the relay-side twin of the PR-9 changed-set
contract (storage/changes.py): the fast path may only ever
OVER-approximate — "don't know" (`authors=None`) wakes everyone, so
correctness never depends on precision. Merkle anti-entropy stays the
convergence mechanism (arXiv:2004.00107 — delivery timing has zero
correctness surface); push is purely a latency lever, and a missed or
spurious wakeup costs at most one polling interval or one empty sync
round.

Wire shape: long-poll. `GET /push/poll?owner=<id>&node=<16hex>&
cursor=<int>[&timeout=<s>]` parks until the owner's event sequence
advances past `cursor` with at least one row authored by a DIFFERENT
node, then answers `{"wake": true, "cursor": <latest>}`; on timeout it
answers `{"wake": false, "cursor": <latest>}` and the client re-polls
(the parked request IS the subscription; expiry is the timeout;
reconnect-resume is the cursor). A cursor older than the bounded
per-owner event ring can no longer be qualified → conservative
`wake=true` (the client syncs; no wakeup is ever missed). Both
connection tiers serve the same hub: the threaded tier parks a handler
thread on an Event, the event-loop tier (server/conn.py) parks the bare
connection — which is the whole point: 10^4 idle subscriptions cost
file descriptors, not threads.

Wakeup sources (all call `notify` AFTER rows are committed/ACKed, so a
woken client's sync round observes them): the sync POST handler and
`/fleet/forward` serve (server/relay.py), replication ingest
(server/replicate.py — a partition heal wakes subscribers at the
healing relay), and `notify_all` after a whole-store snapshot install.
"""

from __future__ import annotations

import heapq
import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from evolu_tpu.obs import metrics

# Per-owner bounded event ring: enough to qualify any plausibly-live
# cursor; older cursors degrade to a conservative wake (never a miss).
EVENT_RING = 512
# Server-side park ceiling per poll (seconds); clients may ask for
# less, never more (a relay must be able to bound its parked set's
# staleness for shutdown/rebalance).
MAX_POLL_TIMEOUT_S = 55.0
DEFAULT_POLL_TIMEOUT_S = 25.0

NODE_HEX_LEN = 16  # timestamp suffix width (core/timestamp.py)


def _author_nodes(timestamps: Sequence[str]) -> Optional[frozenset]:
    """The set of author node ids for one notify batch, or None when
    any timestamp is too short to carry a node suffix (unknown author
    → conservative: wakes every subscriber)."""
    nodes = set()
    for ts in timestamps:
        if len(ts) < NODE_HEX_LEN:
            return None
        nodes.add(ts[-NODE_HEX_LEN:])
    return frozenset(nodes)


def _event_wakes(authors: Optional[frozenset], ev_tags: Optional[frozenset],
                 node: str, tags: Optional[frozenset]) -> bool:
    """Whether one ring event wakes one subscriber: a foreign-authored
    row (own-write exclusion), AND — when BOTH the subscriber's scope
    lanes and the event's lane tags are known — an overlapping lane.
    Either side unknown → the lane gate passes (over-approximation
    only, same stance as the author gate: a scoped subscriber may get a
    spurious wake, never a missed one)."""
    if authors is not None and not any(a != node for a in authors):
        return False
    if tags is not None and ev_tags is not None and not (tags & ev_tags):
        return False
    return True


class _Channel:
    """One owner's event sequence + bounded (seq, authors, tags) ring."""

    __slots__ = ("seq", "ring")

    def __init__(self):
        self.seq = 0
        self.ring: deque = deque(maxlen=EVENT_RING)

    def floor(self) -> int:
        """Oldest cursor the ring can still qualify exactly."""
        return self.ring[0][0] - 1 if self.ring else self.seq

    def qualifies(self, cursor: int, node: str,
                  tags: Optional[frozenset] = None) -> Optional[bool]:
        """Whether events past `cursor` include a row this subscriber
        can see: foreign-authored AND in one of its scope lanes (when
        both sides know their lanes — see `_event_wakes`).
        None = cursor predates the ring (can't know → caller wakes)."""
        if cursor > self.seq:
            # A cursor AHEAD of this channel was minted by another hub
            # epoch (relay restart, retarget to a different relay) —
            # treating it as "seen everything" would silently skip
            # events until seq catches up (review finding: the missed-
            # wakeup contract violation). Can't know → caller wakes
            # conservatively and the client adopts this epoch's cursor.
            return None
        if cursor == self.seq:
            return False
        if cursor < self.floor():
            return None
        for seq, authors, ev_tags in self.ring:
            if seq <= cursor:
                continue
            if _event_wakes(authors, ev_tags, node, tags):
                return True
        return False


class _Waiter:
    """One parked subscription. The event tier parks a connection
    token; the threaded tier parks its handler thread on the Event."""

    __slots__ = ("owner", "node", "cursor", "deadline", "event",
                 "result", "token", "registered_at", "tags")

    def __init__(self, owner: str, node: str, cursor: int,
                 deadline: float, token=None,
                 tags: Optional[frozenset] = None):
        self.owner = owner
        self.node = node
        self.cursor = cursor
        self.deadline = deadline
        self.token = token  # event-tier connection handle (opaque)
        self.event = threading.Event() if token is None else None
        self.result: Optional[bytes] = None
        self.registered_at = time.monotonic()
        self.tags = tags  # scope lanes this subscriber can see; None = all


def poll_body(wake: bool, cursor: int) -> bytes:
    """The one long-poll response body shape, shared by both tiers
    (tier byte-identity for push rides this single encoder)."""
    return json.dumps({"wake": wake, "cursor": cursor}).encode("utf-8")


class PushHub:
    """Thread-safe subscription registry + wakeup fan-out.

    `on_wake(token, body)` is installed by the event-loop tier: called
    (outside the hub lock) for each parked connection token whose
    response is ready — wakeup, timeout, or shutdown. Threaded-tier
    waiters are resolved through their Event instead.
    """

    def __init__(self, max_subscriptions: int = 1 << 17,
                 default_timeout_s: float = DEFAULT_POLL_TIMEOUT_S):
        self._lock = threading.Lock()
        self._channels: Dict[str, _Channel] = {}
        self._waiters: Dict[str, List[_Waiter]] = {}
        # token → waiter for O(1) cancel on client hangup (review
        # finding: a scan over every waiter list per dropped parked
        # connection is O(n^2) across a mass disconnect, all under
        # the hub lock the wakeup fan-out contends on). Event-tier
        # parks only; threaded waiters have no token.
        self._by_token: Dict[object, _Waiter] = {}
        self._n_waiters = 0
        self.max_subscriptions = int(max_subscriptions)
        self.default_timeout_s = float(default_timeout_s)
        self.on_wake = None  # set by the event tier
        self._closed = False
        # Event-tier park deadlines as a lazy-deletion min-heap of
        # (deadline, tiebreak, waiter): the loop asks for the earliest
        # deadline EVERY tick and expiries fire continuously at scale
        # (10^4 staggered 25s parks expire ~400/s) — both a rebuilt
        # deadline list per tick and a full O(all-waiters) sweep per
        # expiry were visible shares of wake latency under the one hub
        # lock (review findings). Entries whose waiter already
        # resolved are skipped at pop time.
        self._park_heap: List[tuple] = []
        self._park_tiebreak = 0
        # Bumped by notify_all (snapshot installs): lets _admit answer
        # a conservative wake for owners the hub has NEVER seen a
        # notify for — a subscriber between polls at install time has
        # no parked waiter to wake and possibly no channel to bump.
        self._installs = 0

    # -- registration / polling --

    def _clamp_timeout(self, timeout: Optional[float]) -> float:
        t = self.default_timeout_s if timeout is None else float(timeout)
        return max(0.0, min(t, MAX_POLL_TIMEOUT_S))

    def _admit(self, owner: str, node: str, cursor: int,
               timeout: Optional[float], token=None,
               tags: Optional[frozenset] = None):
        """Shared admission: → ("now", body) for an immediately
        answerable poll, ("parked", waiter) otherwise. Caller holds no
        lock. Raises HubFull at the subscription bound."""
        metrics.inc("evolu_push_poll_requests_total")
        with self._lock:
            if self._closed:
                return ("now", poll_body(False, cursor))
            ch = self._channels.get(owner)
            if ch is None and self._installs:
                # A snapshot install happened and this owner has no
                # channel: the install may have landed rows for it
                # with nobody parked to wake (review finding — a
                # subscriber between polls would otherwise miss the
                # install permanently). Mint the channel with ONE
                # unknown-author event: this poll wakes conservatively
                # (once — the returned cursor parks the next one).
                ch = self._channels[owner] = _Channel()
                ch.seq = 1
                ch.ring.append((1, None, None))
            if ch is not None:
                q = ch.qualifies(cursor, node, tags)
                if q is None:
                    # Cursor predates the bounded ring: can't prove the
                    # interim was self-only — wake conservatively.
                    metrics.inc("evolu_push_wakeups_total",
                                reason="stale_cursor")
                    return ("now", poll_body(True, ch.seq))
                if q:
                    metrics.inc("evolu_push_wakeups_total", reason="ready")
                    return ("now", poll_body(True, ch.seq))
            if self._n_waiters >= self.max_subscriptions:
                metrics.inc("evolu_push_rejected_total")
                raise HubFull()
            w = _Waiter(owner, node, cursor,
                        time.monotonic() + self._clamp_timeout(timeout),
                        token=token, tags=tags)
            if token is not None:
                self._park_tiebreak += 1
                heapq.heappush(self._park_heap,
                               (w.deadline, self._park_tiebreak, w))
                self._by_token[token] = w
            self._waiters.setdefault(owner, []).append(w)
            self._n_waiters += 1
            metrics.set_gauge("evolu_push_subscriptions", self._n_waiters)
            return ("parked", w)

    def poll_blocking(self, owner: str, node: str, cursor: int,
                      timeout: Optional[float] = None,
                      tags: Optional[frozenset] = None) -> bytes:
        """Threaded-tier long-poll: park THIS thread until wakeup or
        timeout. → response body bytes."""
        kind, val = self._admit(owner, node, cursor, timeout, tags=tags)
        if kind == "now":
            return val
        w: _Waiter = val
        w.event.wait(max(0.0, w.deadline - time.monotonic()))
        with self._lock:
            if w.result is None:  # timed out parked: resolve ourselves
                self._remove_locked(w)
                ch = self._channels.get(owner)
                w.result = poll_body(False, ch.seq if ch else cursor)
                metrics.inc("evolu_push_timeouts_total")
        return w.result

    def park(self, owner: str, node: str, cursor: int,
             timeout: Optional[float], token,
             tags: Optional[frozenset] = None):
        """Event-tier long-poll: → ("now", body) or ("parked", waiter).
        A parked waiter resolves later via `on_wake(token, body)` —
        from notify, from `expire_due`, or from close()."""
        return self._admit(owner, node, cursor, timeout, token=token,
                           tags=tags)

    def cancel(self, token) -> None:
        """Drop a parked event-tier waiter whose connection died. O(1)
        via the token index."""
        with self._lock:
            w = self._by_token.get(token)
            if w is not None:
                self._remove_locked(w)

    # -- wakeup sources --

    def notify(self, owner: str, timestamps: Optional[Sequence[str]] = None,
               reason: str = "write",
               tags: Optional[frozenset] = None) -> int:
        """Rows for `owner` became newly visible. `timestamps` are the
        batch's plaintext timestamps (their node suffixes gate the
        own-write exclusion); None = authors unknown → wake everyone.
        `tags` are the batch's scope-lane tags when the pushing client
        assigned them; None = lanes unknown → every scoped waiter
        qualifies. OVER-approximation is sound (a spurious wakeup costs
        one empty sync round); UNDER-approximation is not — callers
        must notify on every path that makes rows visible, and may pass
        tags=None whenever lane attribution is uncertain. → waiters
        woken."""
        authors = None if timestamps is None else _author_nodes(timestamps)
        woken: List[_Waiter] = []
        with self._lock:
            ch = self._channels.get(owner)
            if ch is None:
                ch = self._channels[owner] = _Channel()
            ch.seq += 1
            ch.ring.append((ch.seq, authors, tags))
            lst = self._waiters.get(owner)
            if lst:
                keep = []
                for w in lst:
                    if _event_wakes(authors, tags, w.node, w.tags):
                        w.result = poll_body(True, ch.seq)
                        woken.append(w)
                    else:
                        keep.append(w)
                if keep:
                    self._waiters[owner] = keep
                else:
                    del self._waiters[owner]
                self._drop_tokens_locked(woken)
                self._n_waiters -= len(woken)
                metrics.set_gauge("evolu_push_subscriptions", self._n_waiters)
        if woken:
            metrics.inc("evolu_push_wakeups_total", len(woken), reason=reason)
        self._resolve(woken)
        return len(woken)

    def notify_all(self, reason: str = "conservative") -> int:
        """Everything may have changed (snapshot install, owner-scoped
        rebalance cutover): wake every parked subscription AND advance
        every known channel, so a subscriber that is merely BETWEEN
        polls sees the event on its next poll (review finding: bumping
        only waiter-holding owners silently missed exactly the
        subscribers that were mid-response or backing off during the
        install). Owners the hub has never seen get the conservative
        first-poll wake via `_installs` in `_admit`."""
        woken: List[_Waiter] = []
        with self._lock:
            self._installs += 1
            for owner, lst in list(self._waiters.items()):
                if owner not in self._channels:
                    self._channels[owner] = _Channel()
                for w in lst:
                    woken.append(w)
                del self._waiters[owner]
            for ch in self._channels.values():
                ch.seq += 1
                ch.ring.append((ch.seq, None, None))
            for w in woken:
                w.result = poll_body(True, self._channels[w.owner].seq)
            self._drop_tokens_locked(woken)
            self._n_waiters -= len(woken)
            metrics.set_gauge("evolu_push_subscriptions", self._n_waiters)
        if woken:
            metrics.inc("evolu_push_wakeups_total", len(woken), reason=reason)
        self._resolve(woken)
        return len(woken)

    # -- expiry / lifecycle --

    def next_deadline(self) -> Optional[float]:
        """Earliest parked deadline (monotonic; possibly stale-early —
        resolved waiters linger in the heap until popped — never
        stale-late), for the event loop's select timeout."""
        with self._lock:
            return self._park_heap[0][0] if self._park_heap else None

    def expire_due(self, now: Optional[float] = None) -> int:
        """Resolve event-tier waiters past their deadline with
        wake=false (threaded-tier waiters time out on their own
        Event). Lazy-deletion heap pop: O(log n) per expiry, O(1) when
        nothing is due — never a full waiter sweep (review finding:
        staggered timeouts at 10^4 parks expire continuously, and an
        O(n) sweep per expiry re-created the lock contention the
        token index removed). → expired count."""
        now = time.monotonic() if now is None else now
        expired: List[_Waiter] = []
        with self._lock:
            while self._park_heap and self._park_heap[0][0] <= now:
                _d, _t, w = heapq.heappop(self._park_heap)
                if self._by_token.get(w.token) is not w or w.result is not None:
                    continue  # already woken/cancelled: lazy deletion
                ch = self._channels.get(w.owner)
                w.result = poll_body(False, ch.seq if ch else w.cursor)
                self._remove_locked(w)
                expired.append(w)
        if expired:
            metrics.inc("evolu_push_timeouts_total", len(expired))
        self._resolve(expired)
        return len(expired)

    def close(self) -> None:
        """Resolve every parked subscription with wake=false (clients
        re-poll and get connection-refused → their backoff path) and
        refuse new parks."""
        waiters: List[_Waiter] = []
        with self._lock:
            self._closed = True
            for lst in self._waiters.values():
                waiters.extend(lst)
            self._waiters.clear()
            self._by_token.clear()
            self._park_heap.clear()
            self._n_waiters = 0
            metrics.set_gauge("evolu_push_subscriptions", 0)
        for w in waiters:
            if w.result is None:
                ch = self._channels.get(w.owner)
                w.result = poll_body(False, ch.seq if ch else w.cursor)
        self._resolve(waiters)

    def _remove_locked(self, w: _Waiter) -> None:
        if w.token is not None:
            self._by_token.pop(w.token, None)
        lst = self._waiters.get(w.owner)
        if lst and w in lst:
            lst.remove(w)
            if not lst:
                del self._waiters[w.owner]
            self._n_waiters -= 1
            metrics.set_gauge("evolu_push_subscriptions", self._n_waiters)

    def _drop_tokens_locked(self, waiters: List[_Waiter]) -> None:
        for w in waiters:
            if w.token is not None:
                self._by_token.pop(w.token, None)

    def _resolve(self, waiters: List[_Waiter]) -> None:
        """Deliver results outside the hub lock: threaded waiters via
        their Event, event-tier waiters via the installed on_wake."""
        on_wake = self.on_wake
        for w in waiters:
            if w.event is not None:
                w.event.set()
            elif on_wake is not None:
                try:
                    on_wake(w.token, w.result)
                except Exception:  # noqa: BLE001 - a dead connection
                    pass           # must not break the notify fan-out

    # -- observability --

    def stats_payload(self) -> dict:
        with self._lock:
            return {
                "subscriptions": self._n_waiters,
                "owners_with_waiters": len(self._waiters),
                "channels": len(self._channels),
                "wakeups_total": {
                    r: metrics.get_counter("evolu_push_wakeups_total",
                                           reason=r)
                    for r in ("write", "replication", "ready",
                              "stale_cursor", "conservative")
                },
                "timeouts_total": metrics.get_counter(
                    "evolu_push_timeouts_total"),
                "rejected_total": metrics.get_counter(
                    "evolu_push_rejected_total"),
            }


class HubFull(Exception):
    """Subscription registry at capacity: the caller answers 503 +
    Retry-After (the scheduler-backpressure shape — flow control, a
    client degrades to its polling interval and retries)."""

    retry_after = 1.0


def parse_poll_query(
    query: str,
) -> Tuple[str, str, int, Optional[float], Optional[frozenset]]:
    """Decode /push/poll query params → (owner, node, cursor, timeout,
    tags). `tags` (optional, comma-separated opaque scope-lane tags —
    sync/scope.py) scopes the subscription: the hub skips wakes whose
    lane attribution provably misses every listed lane. None = wake on
    everything (the unscoped subscription, unchanged). Raises
    ValueError on malformed input (the relay answers 400 — the
    wire-decoder contract)."""
    from urllib.parse import parse_qs

    q = parse_qs(query, keep_blank_values=True)
    owner = q.get("owner", [""])[0]
    if not owner:
        raise ValueError("push poll needs an owner")
    node = q.get("node", [""])[0]
    if len(node) != NODE_HEX_LEN or any(
            c not in "0123456789abcdef" for c in node):
        raise ValueError("push poll needs node=<16 lowercase hex>")
    try:
        cursor = int(q.get("cursor", ["0"])[0])
    except ValueError:
        raise ValueError("push poll cursor must be an integer")
    timeout: Optional[float] = None
    raw_t = q.get("timeout", [None])[0]
    if raw_t is not None:
        try:
            timeout = float(raw_t)
        except ValueError:
            raise ValueError("push poll timeout must be a number")
        if not timeout >= 0:  # also rejects NaN
            raise ValueError("push poll timeout must be >= 0")
    tags: Optional[frozenset] = None
    raw_tags = q.get("tags", [None])[0]
    if raw_tags:
        from evolu_tpu.sync.protocol import _MAX_SCOPE_TAGS, _MAX_SCOPE_TAG_LEN

        parts = [t for t in raw_tags.split(",") if t]
        if len(parts) > _MAX_SCOPE_TAGS:
            raise ValueError(
                f"push poll caps tags at {_MAX_SCOPE_TAGS}")
        if any(len(t) > _MAX_SCOPE_TAG_LEN for t in parts):
            raise ValueError("push poll tag too long")
        tags = frozenset(parts) or None
    return owner, node, cursor, timeout, tags
