"""The relay: store + sync pipeline + HTTP endpoint.

Reference: apps/server/src/index.ts (258 LoC, Express +
better-sqlite3). Same storage shape (index.ts:64-75), same sync
pipeline (index.ts:204-216), same own-message exclusion
(`timestamp NOT LIKE '%' || nodeId`, index.ts:100), same 20 MB body
limit (index.ts:222), `GET /ping` health check (index.ts:250-252).
The server is E2EE-blind: rows are (timestamp, userId, ciphertext).
Observability extensions (no reference equivalent): `GET /metrics`
(Prometheus v0.0.4 text from the process registry) and `GET /stats`
(JSON: per-shard row counts + request counters + latency percentile
estimates) — see docs/OBSERVABILITY.md. Replication extension (no
reference equivalent): `POST /replicate/summary` + `POST
/replicate/pull`, the Merkle anti-entropy gossip surface between relay
peers (`server/replicate.py`; `RelayServer(peers=[...])`).

`add_messages` keeps the reference's per-row insert (it needs per-row
rowcount for the changes==1 Merkle gate) but aggregates tree updates
into one delta pass; the batched many-owner path lives in
`evolu_tpu.server.engine.BatchReconciler`, which set-diffs in bulk SQL
and hashes on device.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

from evolu_tpu.obs import anatomy, flight, ledger, metrics, trace
from evolu_tpu.utils.log import log

from evolu_tpu.core.merkle import (
    apply_prefix_xors,
    diff_merkle_trees,
    merkle_tree_from_string,
    merkle_tree_to_string,
    minutes_base3,
)
from evolu_tpu.core.murmur import to_int32
from evolu_tpu.core.timestamp import (
    create_sync_timestamp,
    timestamp_from_string,
    timestamp_to_hash,
    timestamp_to_string,
)
from evolu_tpu.core.types import NonCanonicalStoreError
from evolu_tpu.storage.native import open_database
from evolu_tpu.storage.sqlite import configure_shared_file_db
from evolu_tpu.sync import aead, protocol

MAX_BODY_BYTES = 20 * 1024 * 1024  # index.ts:222


def _count_ingest_mix(messages) -> None:
    """Ingest wire-format observability (the relay stays E2EE-blind:
    the 3-byte version magic is framing, not content). v2 records ride
    the store/Merkle/replication paths as opaquely as v1 — these
    counters are how an operator SEES the negotiated fleet actually
    carrying v2 traffic. Call only on the SERVING relay, after any
    fleet routing, so each message counts once fleet-wide."""
    if not messages:
        return
    n_v2 = aead.count_v2(messages)
    if n_v2:
        metrics.inc("evolu_crypto_v2_relay_messages_total", n_v2)
    if n_v2 < len(messages):
        metrics.inc("evolu_crypto_v1_relay_messages_total",
                    len(messages) - n_v2)


# Per-thread serve scope (see serve_single_request): one pending entry
# + a first-wins classification latch per request, so (a) a serve that
# commits the store but fails BEFORE answering posts NOTHING — the
# relay's reject.invalid stays the request's single terminal — and
# (b) the NonCanonicalStoreError object-path fallback, which re-runs
# add_messages idempotently, cannot classify the same messages twice.
_SERVE_SCOPE = threading.local()


def _ledger_store_apply(user_id, new_flags) -> None:
    """Conservation-ledger terminal classification for the OBJECT store
    path (`RelayStore.add_messages`): per-row was-new flags are the
    changes==1 truth — new rows terminate at store.inserted, the rest
    at store.duplicate. Inside a serve scope the counts ride the
    scope's pending entry (committed only when the serve answers,
    first classification wins); outside one (engine sharded-python
    fallback, fleet rebalance install, direct embedder calls) they
    post immediately. ONE seam on purpose: the ledger's negative test
    (tests/test_ledger.py) mis-wires exactly this function to prove the
    audit catches a route that forgets to count."""
    n_new = ledger.flag_sum(new_flags)
    scope = getattr(_SERVE_SCOPE, "scope", None)
    if scope is not None:
        if scope["classified"]:
            return  # fallback re-insert re-classifies; first wins
        scope["classified"] = True
        scope["entry"].count(ledger.STORE_INSERTED, n_new, owner=user_id)
        scope["entry"].count(ledger.STORE_DUPLICATE,
                             len(new_flags) - n_new, owner=user_id)
        return
    ledger.count(ledger.STORE_INSERTED, n_new, owner=user_id)
    ledger.count(ledger.STORE_DUPLICATE, len(new_flags) - n_new,
                 owner=user_id)


def fetch_response_stream(db, user_id, node_id, server_tree, client_tree) -> bytes:
    """The C-served SyncResponse `messages` stream for one request:
    tree diff → since timestamp → `eh_get_messages_wire`. b"" when the
    trees agree; raises NonCanonicalStoreError for a malformed stored
    row (callers degrade that request to the object path). ONE copy of
    this byte-format-coupled composition, shared by
    `RelayStore.sync_wire` and `BatchReconciler._respond_wire` — the
    serve rule must never drift between them (byte-identity with the
    object path is test-pinned at both call sites)."""
    diff = diff_merkle_trees(server_tree, client_tree)
    if diff is None:
        return b""
    since = timestamp_to_string(create_sync_timestamp(diff))
    stream, _n = db.fetch_relay_messages_wire(user_id, since, node_id)
    return stream

def serve_single_request(store, request: "protocol.SyncRequest") -> bytes:
    """ONE copy of the per-request serve recipe: fused C wire path,
    object-path fallback (where non-canonical shapes reach the host
    oracle before any side effect). Shared by the non-batching do_POST
    branch and the scheduler's non-batchable/poison-retry fallbacks —
    the recipes must never drift (the scheduler's responses are pinned
    byte-identical to this path).

    Ledger: the whole serve runs under one scope (see _SERVE_SCOPE) so
    store terminals post exactly once per ANSWERED request — a serve
    that commits add_messages and then fails (e.g. a garbage client
    tree string) aborts the entry and the caller's reject.invalid is
    the single terminal; the NonCanonicalStoreError fallback's second
    add_messages run never double-classifies."""
    scope = {"entry": ledger.pending(), "classified": False}
    _SERVE_SCOPE.scope = scope
    try:
        if getattr(request, "scope", None) is not None:
            # Scoped serve (server/scope.py): ingest runs through the
            # same add_messages path (the ledger seam above fires
            # normally); only the RESPONSE is filtered. Never the fused
            # C wire path — per-row lane filtering can't ride it.
            from evolu_tpu.server import scope as scope_mod

            out = scope_mod.serve_scoped(store, request)
        else:
            out = store.sync_wire(request) if hasattr(store, "sync_wire") \
                else None
            if out is None:
                out = protocol.encode_sync_response(store.sync(request))
    except BaseException:
        scope["entry"].abort()
        raise
    finally:
        _SERVE_SCOPE.scope = None
    scope["entry"].commit()
    return out


def _notify_tags(request: "protocol.SyncRequest"):
    """Lane tags for a push wakeup: the scope clause's per-message lane
    assignment, when the pushing client sent one. None (= wake every
    waiter, the PR-13 over-approximation stance) whenever lanes are
    unknown — v1 pushes, scoped pulls with no pushed rows, untagged
    rows mixed in."""
    s = getattr(request, "scope", None)
    if s is None or not s.push_tags:
        return None
    tags = frozenset(s.push_tags)
    return None if "" in tags else tags


_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class RelayStore:
    """Message + Merkle storage for many users (index.ts:60-105)."""

    def __init__(self, path: str = ":memory:", backend: str = "auto"):
        self.db = open_database(path, backend)
        # File-backed stores may be shared across PROCESSES (the
        # pre-forked MultiprocessRelay, the write-behind's
        # process-per-shard drain children): one shared pragma
        # discipline, see sqlite.configure_shared_file_db (no-op for
        # :memory:).
        configure_shared_file_db(self.db)
        # Uniqueness pair is the reference's (timestamp, userId)
        # (index.ts:64-75); the key ORDER is flipped and the table is
        # WITHOUT ROWID — a deliberate layout improvement: get_messages
        # becomes a pure PK range read (the reference scans), and the
        # batched ingest maintains ONE btree instead of three
        # (rowid table + PK index + the user index this replaced),
        # measured ~2.9× faster at 1M rows. Dedup semantics are
        # identical (INSERT OR IGNORE on the same pair).
        self.db.exec(
            'CREATE TABLE IF NOT EXISTS "message" ('
            '"timestamp" TEXT, "userId" TEXT, "content" BLOB, '
            'PRIMARY KEY ("userId", "timestamp")) WITHOUT ROWID'
        )
        self.db.exec(
            'CREATE TABLE IF NOT EXISTS "merkleTree" ('
            '"userId" TEXT PRIMARY KEY, "merkleTree" TEXT)'
        )

    def get_merkle_tree(self, user_id: str) -> dict:
        """index.ts:121-136 — a user's tree, empty if unseen.
        ('{}' parses to create_initial_merkle_tree(); ONE SELECT lives
        in get_merkle_tree_string — keep them from diverging.)"""
        return merkle_tree_from_string(self.get_merkle_tree_string(user_id))

    def add_messages(
        self, user_id: str, messages: Sequence[protocol.EncryptedCrdtMessage]
    ) -> dict:
        """index.ts:138-171 — INSERT OR IGNORE each message; XOR only
        *newly inserted* timestamps into the tree (the server gates on
        changes==1, unlike the client's always-XOR; index.ts:153-158).
        One transaction; returns the updated tree."""
        with self.db.transaction():
            tree = self.get_merkle_tree(user_id)
            deltas: Dict[str, int] = {}
            if hasattr(self.db, "relay_insert"):
                # C++ backend: bulk insert with per-row was-new flags.
                new_flags = self.db.relay_insert(
                    [(m.timestamp, user_id, m.content) for m in messages]
                )
            else:
                new_flags = [
                    self.db.run(
                        'INSERT OR IGNORE INTO "message" ("timestamp", "userId", "content") '
                        "VALUES (?, ?, ?)",
                        (m.timestamp, user_id, m.content),
                    )
                    == 1
                    for m in messages
                ]
            for m, was_new in zip(messages, new_flags):
                if was_new:
                    t = timestamp_from_string(m.timestamp)
                    key = minutes_base3(t.millis)
                    deltas[key] = to_int32(deltas.get(key, 0) ^ timestamp_to_hash(t))
            tree = apply_prefix_xors(tree, deltas)
            self.db.run(
                'INSERT OR REPLACE INTO "merkleTree" ("userId", "merkleTree") VALUES (?, ?)',
                (user_id, merkle_tree_to_string(tree)),
            )
        # After the transaction committed — a rolled-back batch must
        # post nothing (the scheduler's retry posts once instead).
        _ledger_store_apply(user_id, new_flags)
        return tree

    def get_messages(
        self, user_id: str, node_id: str, server_tree: dict, client_tree: dict
    ) -> Tuple[protocol.EncryptedCrdtMessage, ...]:
        """index.ts:173-202 — if the trees diverge, everything after the
        diff minute except the requester's own messages."""
        diff = diff_merkle_trees(server_tree, client_tree)
        if diff is None:
            return ()
        since = timestamp_to_string(create_sync_timestamp(diff))
        if hasattr(self.db, "fetch_relay_messages"):
            # C++ backend: packed single-call reader. NB the query text
            # lives in BOTH native/evolu_host.cpp::eh_get_messages and
            # the fallback below — change them together
            # (tests assert cross-backend equivalence).
            try:
                rows = self.db.fetch_relay_messages(user_id, since, node_id)
                return tuple(protocol.EncryptedCrdtMessage(t, c) for t, c in rows)
            except NonCanonicalStoreError:
                pass  # a malformed stored width degrades to the SQL path
        rows = self.db.exec_sql_query(
            'SELECT "timestamp", "content" FROM "message" '
            'WHERE "userId" = ? AND "timestamp" > ? AND "timestamp" NOT LIKE \'%\' || ? '
            'ORDER BY "timestamp"',
            (user_id, since, node_id),
        )
        return tuple(
            protocol.EncryptedCrdtMessage(r["timestamp"], r["content"]) for r in rows
        )

    def get_merkle_tree_string(self, user_id: str) -> str:
        """The stored tree TEXT verbatim — response paths reuse it
        instead of parse→re-dump (a ~25KB JSON round-trip per owner is
        the measured cold-sync respond wall, docs/BENCHMARKS.md r4)."""
        rows = self.db.exec_sql_query(
            'SELECT "merkleTree" FROM "merkleTree" WHERE "userId" = ?', (user_id,)
        )
        return rows[0]["merkleTree"] if rows else "{}"

    def owner_trees(self) -> List[Tuple[str, str]]:
        """Every (owner, stored tree TEXT) pair in ONE query — the
        replication summary map (server/replicate.py). Per-owner
        `get_merkle_tree_string` calls would be N+1 SELECTs per gossip
        round."""
        rows = self.db.exec_sql_query('SELECT "userId", "merkleTree" FROM "merkleTree"')
        return [(r["userId"], r["merkleTree"]) for r in rows]

    def replica_messages(
        self, user_id: str, since: str, limit: Optional[int] = None
    ) -> Tuple[protocol.EncryptedCrdtMessage, ...]:
        """Ranged replication read for a PEER RELAY: stored messages
        strictly after `since` in timestamp order — the EARLIEST
        `limit` of them when capped — WITHOUT the own-node exclusion of
        `get_messages` (a relay is not a message author, it needs all
        rows; server/replicate.py). Plain SQL on purpose: the C reader
        bakes in the `NOT LIKE` node filter, and replication volume is
        divergence-bounded, not the per-message hot path."""
        rows = self.db.exec_sql_query(
            'SELECT "timestamp", "content" FROM "message" '
            'WHERE "userId" = ? AND "timestamp" > ? ORDER BY "timestamp" LIMIT ?',
            (user_id, since, -1 if limit is None else int(limit)),
        )
        return tuple(
            protocol.EncryptedCrdtMessage(r["timestamp"], r["content"]) for r in rows
        )

    def sync(self, request: protocol.SyncRequest) -> protocol.SyncResponse:
        """The pure pipeline (index.ts:204-216)."""
        tree = self.add_messages(request.user_id, request.messages)
        client_tree = merkle_tree_from_string(request.merkle_tree)
        messages = self.get_messages(request.user_id, request.node_id, tree, client_tree)
        return protocol.SyncResponse(messages, merkle_tree_to_string(tree))

    def sync_wire(self, request: protocol.SyncRequest) -> Optional[bytes]:
        """`sync` + `encode_sync_response` fused: the response messages
        stream comes straight from ONE C call (zero per-row objects —
        the cold-sync response leg was object-bound, BENCHMARKS r4),
        byte-identical to the pure pipeline's encoding (test-pinned).
        None → caller takes the object path (python backend)."""
        if not hasattr(self.db, "fetch_relay_messages_wire"):
            return None
        tree = self.add_messages(request.user_id, request.messages)
        client_tree = merkle_tree_from_string(request.merkle_tree)
        try:
            stream = fetch_response_stream(
                self.db, request.user_id, request.node_id, tree, client_tree
            )
        except NonCanonicalStoreError:
            # A single malformed stored timestamp must not wedge this
            # owner's sync: serve via the object path, whose
            # get_messages degrades to generic SQL (advisor r4).
            # add_messages above was idempotent, so the caller's
            # sync() re-run is safe.
            return None
        # add_messages just dumped + stored this exact tree: read the
        # stored text back (one small SELECT) instead of a second
        # ~25KB JSON dump per request (review finding).
        return stream + protocol._string(2, self.get_merkle_tree_string(request.user_id))

    def user_ids(self) -> List[str]:
        return [r["userId"] for r in self.db.exec_sql_query('SELECT "userId" FROM "merkleTree"')]

    def stats(self) -> List[dict]:
        """Per-shard row counts for GET /stats (one-element list here;
        ShardedRelayStore returns one entry per shard). Read from the
        store itself, so in a MultiprocessRelay every worker reports
        the same shared-file truth regardless of which worker answers."""
        messages = self.db.exec_sql_query('SELECT COUNT(*) AS n FROM "message"')
        users = self.db.exec_sql_query('SELECT COUNT(*) AS n FROM "merkleTree"')
        return [{"index": 0, "messages": messages[0]["n"], "users": users[0]["n"]}]

    def close(self) -> None:
        self.db.close()


class ShardedRelayStore:
    """Owner-sharded relay storage: N independent SQLite stores, each
    its own single-writer — the storage twin of the owners-over-mesh
    device sharding (owners are independent, SURVEY.md §2.15), and the
    way past SQLite's one-writer throughput wall: the batch reconciler
    ingests every shard in parallel (the C calls drop the GIL).

    Same public surface as RelayStore; userId routes to a shard by a
    stable hash. Per-request semantics are unchanged — a request only
    ever touches its owner's shard."""

    def __init__(self, path: str = ":memory:", backend: str = "auto", shards: int = 8):
        paths = (
            [":memory:"] * shards
            if path == ":memory:"
            else [f"{path}.s{i:02d}" for i in range(shards)]
        )
        self.shards = [RelayStore(p, backend) for p in paths]

    def shard_index(self, user_id: str) -> int:
        import zlib

        return zlib.crc32(user_id.encode("utf-8")) % len(self.shards)

    def shard_of(self, user_id: str) -> RelayStore:
        return self.shards[self.shard_index(user_id)]

    def get_merkle_tree(self, user_id: str) -> dict:
        return self.shard_of(user_id).get_merkle_tree(user_id)

    def get_merkle_tree_string(self, user_id: str) -> str:
        return self.shard_of(user_id).get_merkle_tree_string(user_id)

    def add_messages(self, user_id, messages) -> dict:
        return self.shard_of(user_id).add_messages(user_id, messages)

    def get_messages(self, user_id, node_id, server_tree, client_tree):
        return self.shard_of(user_id).get_messages(user_id, node_id, server_tree, client_tree)

    def sync(self, request: protocol.SyncRequest) -> protocol.SyncResponse:
        return self.shard_of(request.user_id).sync(request)

    def sync_wire(self, request: protocol.SyncRequest) -> Optional[bytes]:
        return self.shard_of(request.user_id).sync_wire(request)

    def owner_trees(self) -> List[Tuple[str, str]]:
        return [p for s in self.shards for p in s.owner_trees()]

    def replica_messages(self, user_id: str, since: str, limit: Optional[int] = None):
        return self.shard_of(user_id).replica_messages(user_id, since, limit)

    def user_ids(self) -> List[str]:
        return [u for s in self.shards for u in s.user_ids()]

    def stats(self) -> List[dict]:
        return [
            {**s.stats()[0], "index": i} for i, s in enumerate(self.shards)
        ]

    def close(self) -> None:
        for s in self.shards:
            s.close()


def mesh_stats_payload() -> dict:
    """The `mesh` section of GET /stats — the `evolu_mesh_*` family
    read back from the metrics registry (docs/OBSERVABILITY.md): device
    count, sharded dispatches, cross-device reduce counts by kind, and
    the occupancy/padding-waste distribution the stable placement
    trades LPT balance for. Pure registry reads — never imports jax."""
    occ = metrics.registry.get_histogram("evolu_mesh_shard_rows")
    waste = metrics.registry.get_histogram("evolu_mesh_padding_waste_rows")
    return {
        "devices": metrics.get_gauge("evolu_mesh_devices"),
        "dispatches_total": metrics.get_counter("evolu_mesh_dispatches_total"),
        "xdev_reduce_total": {
            kind: metrics.get_counter("evolu_mesh_xdev_reduce_total", kind=kind)
            for kind in ("digest", "owner_delta_partials",
                         "winner_minute_partials")
        },
        "shard_rows": {
            "count": (occ or (None, None, 0.0, 0))[3],
            "p50": metrics.quantile("evolu_mesh_shard_rows", 0.50),
            "p99": metrics.quantile("evolu_mesh_shard_rows", 0.99),
        },
        "padding_waste_rows": {
            "count": (waste or (None, None, 0.0, 0))[3],
            "p50": metrics.quantile("evolu_mesh_padding_waste_rows", 0.50),
            "p99": metrics.quantile("evolu_mesh_padding_waste_rows", 0.99),
        },
    }


def relay_stats_payload(store, replication=None, fleet=None,
                        write_behind=None, mesh_engine: bool = False,
                        push_hub=None, conn_tier=None) -> dict:
    """The GET /stats JSON: store-derived row counts per shard (shared
    truth in a MultiprocessRelay — every worker reads the same files)
    plus this process's request counters from the metrics registry
    (per-process by nature; a multiprocess deploy scrapes each worker's
    /metrics or sums /stats over workers). With a ReplicationManager
    attached, a `replication` section reports per-peer gossip health
    (docs/OBSERVABILITY.md)."""
    shards = store.stats() if hasattr(store, "stats") else []
    for s in shards:
        s["requests"] = metrics.get_counter(
            "evolu_relay_shard_requests_total", shard=str(s["index"])
        )
    payload = {
        "shards": shards,
        "messages": sum(s["messages"] for s in shards),
        "users": sum(s["users"] for s in shards),
        "requests_total": metrics.get_counter(
            "evolu_relay_requests_total", endpoint="/"
        ),
        "errors_total": metrics.get_counter("evolu_relay_errors_total"),
        "latency_ms": {
            "count": (metrics.registry.get_histogram("evolu_relay_request_ms") or
                      (None, None, 0.0, 0))[3],
            "p50": metrics.quantile("evolu_relay_request_ms", 0.50),
            "p99": metrics.quantile("evolu_relay_request_ms", 0.99),
        },
    }
    # The conservation ledger's station totals + the in-stream-safe
    # audit (barrier-only equations skipped: /stats must not force a
    # drain barrier; GET /ledger runs the full audit).
    payload["ledger"] = {
        "stations": ledger.totals(),
        "violations": ledger.audit(at_barrier=False),
    }
    if replication is not None:
        payload["replication"] = replication.stats_payload()
    if fleet is not None:
        payload["fleet"] = fleet.stats_payload()
    if write_behind is not None:
        payload["write_behind"] = write_behind.stats_payload()
    if mesh_engine:
        payload["mesh"] = mesh_stats_payload()
    if push_hub is not None:
        payload["push"] = push_hub.stats_payload()
    if conn_tier is not None:
        payload["conn"] = conn_tier.stats_payload()
    # Stage-anatomy section (ISSUE 16): per-stage counts/EWMA/fit/
    # floor/over-floor plus the dispatch/pull/apply runtime shares.
    payload["stages"] = anatomy.stages_payload()
    return payload


# GET /profile single-flight: jax.profiler supports one capture per
# process; a second concurrent request answers 429 instead of racing
# start_trace (which raises — or worse, interleaves captures).
_PROFILE_LOCK = threading.Lock()


def capture_live_profile(duration_ms: float) -> dict:
    """Capture `duration_ms` of live traffic as one loadable
    Chrome-trace JSON document (perfetto/chrome://tracing both open
    it). Three lanes share the timebase:

    - the jax.profiler device+runtime timeline, captured only when jax
      is ALREADY loaded in this process (a relay that never touched
      jax must stay jax-free — the obs import-hygiene contract; many
      relays serve pure-host workloads). PR-4 trace annotations are
      enabled for the window so `kernel:*` span names appear inside
      the profiler timeline too, then restored.
    - the logger span ring (`kernel:*` and sync spans always land
      there), exported as host-lane complete events.
    - sampled obs.trace spans in the window via the PR-10 chrome
      export (same event shape, their own lanes).

    Never raises on profiler trouble: a failed jax capture degrades to
    the host lanes with the error string in metadata — an operator
    profiling a live relay must get *a* trace, not a 500."""
    import gzip
    import shutil
    import sys
    import tempfile

    from evolu_tpu.utils import log as log_mod

    t_start = time.time()
    pid = os.getpid()
    events: List[dict] = []
    meta: Dict[str, object] = {"requested_ms": duration_ms}
    prof_dir = None
    jax_on = False
    annotations_were_on = log_mod._trace_annotation_cls is not None
    if "jax" in sys.modules:
        try:
            import jax  # already in sys.modules — no fresh import

            log_mod.enable_trace_annotations(True)
            prof_dir = tempfile.mkdtemp(prefix="evolu-profile-")
            jax.profiler.start_trace(prof_dir)
            jax_on = True
        except Exception as e:  # noqa: BLE001 - degrade to host lanes
            meta["jax_error"] = f"{type(e).__name__}: {e}"
    time.sleep(max(float(duration_ms), 0.0) / 1e3)
    if jax_on:
        try:
            import jax

            jax.profiler.stop_trace()
            for root, _dirs, files in os.walk(prof_dir):
                for fname in files:
                    if not fname.endswith(".trace.json.gz"):
                        continue
                    with gzip.open(os.path.join(root, fname), "rt",
                                   encoding="utf-8") as f:
                        doc = json.load(f)
                    for ev in doc.get("traceEvents", []):
                        # Real profiler dumps end with a bare {} and may
                        # omit pid on metadata rows — keep the merged
                        # document uniformly loadable.
                        if not isinstance(ev, dict) or not ev.get("ph"):
                            continue
                        ev.setdefault("pid", pid)
                        events.append(ev)
        except Exception as e:  # noqa: BLE001
            meta["jax_error"] = f"{type(e).__name__}: {e}"
            jax_on = False
        finally:
            if not annotations_were_on:
                log_mod.enable_trace_annotations(False)
    if prof_dir is not None:
        shutil.rmtree(prof_dir, ignore_errors=True)
    meta["jax_profiler"] = jax_on
    t_end = time.time()

    # Host lane 1: logger span ring events overlapping the window.
    n_host = 0
    for ev in log_mod.logger.recent_events():
        if ev.duration_ms is None:
            continue
        s0 = ev.t - ev.duration_ms / 1e3
        if ev.t < t_start or s0 > t_end:
            continue
        n_host += 1
        events.append({
            "name": f"{ev.target}|{ev.message}" if ev.message else ev.target,
            "cat": "evolu-host",
            "ph": "X",
            "ts": s0 * 1e6,
            "dur": ev.duration_ms * 1e3,
            "pid": pid,
            "tid": 0,
            "args": {k: str(v) for k, v in ev.fields.items()},
        })
    # Host lane 2: sampled distributed-trace spans in the window (the
    # PR-10 export keeps their per-thread lanes + trace/span ids).
    win_spans = [
        s for s in trace.recorder.dump()
        if s.t_start <= t_end and s.t_start + s.duration_ms / 1e3 >= t_start
    ]
    events.extend(trace.export_chrome(win_spans)["traceEvents"])
    meta.update(captured_at=t_start, wall_ms=(t_end - t_start) * 1e3,
                host_span_events=n_host, trace_span_events=len(win_spans),
                platform=anatomy.get_platform())
    return {"displayTimeUnit": "ms", "traceEvents": events,
            "metadata": meta}


class _Handler(BaseHTTPRequestHandler):
    store: RelayStore  # injected by RelayServer
    scheduler = None  # SyncScheduler when continuous batching is on
    replication = None  # ReplicationManager when the relay has peers
    fleet = None  # FleetManager when the relay is an owner-sharded fleet member
    write_behind = None  # WriteBehindQueue when the PR-11 inversion is on
    mesh_engine = False  # PR-12 sharded engine: adds the /stats mesh section
    push_hub = None  # PushHub when push subscriptions are on (server/push.py)
    conn_tier = None  # EventLoopHTTPServer when that tier serves this relay
    # Capabilities this relay echoes back (intersected with the
    # request's advertised set — sync/protocol.py capability
    # extension). A request with no capabilities gets the v1 wire,
    # byte-identical.
    capabilities = protocol.KNOWN_CAPABILITIES

    def _negotiate_caps(self, request: "protocol.SyncRequest", out: bytes) -> bytes:
        """Append the negotiated capability fields to an encoded sync
        response — AFTER the serve path (fused C wire bytes or object
        path alike; proto3 field order is free). Only fires when the
        client advertised, so capability-less peers round-trip
        byte-identically."""
        caps = tuple(c for c in request.capabilities if c in self.capabilities)
        if not caps:
            return out
        metrics.inc("evolu_crdt_capability_negotiations_total")
        for cap in caps:
            # Per-capability negotiation counts (bounded label set: only
            # capabilities WE serve ever reach here — never raw client
            # strings). `aead-batch-v1` echoes are the relay-side signal
            # that clients may start emitting v2 envelopes.
            metrics.inc("evolu_crypto_capability_echoes_total", capability=cap)
        return out + protocol.encode_response_capabilities(caps)

    def log_message(self, format: str, *args) -> None:
        # Target-gated like every other runtime signal (config.log):
        # quiet by default, switchable via the `dev` target instead of
        # unconditionally discarded. The is_enabled pre-check keeps the
        # disabled-default path allocation-free (this fires per
        # request); _flight=False because per-request access lines
        # would evict the sparse events the flight ring is for.
        from evolu_tpu.utils.log import logger

        if logger.is_enabled("dev"):
            log("dev", f"relay {self.address_string()} {format % args}",
                _flight=False)

    def _body_length(self) -> Optional[int]:
        """Harden Content-Length parsing: a non-numeric header used to
        raise an uncaught ValueError out of `int(...)` (connection
        reset instead of an HTTP answer), and a NEGATIVE value passed
        the `> MAX_BODY_BYTES` check and then `rfile.read(-1)` read
        UNBOUNDED. → the parsed length, or None after answering 400.
        The MAX_BODY_BYTES cap stays at the call sites (413)."""
        raw = self.headers.get("Content-Length", "0")
        try:
            length = int(raw)
        except (TypeError, ValueError):
            length = -1
        if length < 0:
            metrics.inc("evolu_relay_errors_total")
            self.send_error(400, "invalid Content-Length")
            return None
        return length

    def _respond(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _respond_retry_after(self, retry_after: float) -> None:
        """503 + Retry-After: the ONE flow-control answer shape —
        scheduler backpressure, a fleet owner mid-install, a forward
        target briefly down. Clients back off and retry; never counted
        in errors_total."""
        from evolu_tpu.server.scheduler import format_retry_after

        self.send_response(503)
        self.send_header("Retry-After", format_retry_after(retry_after))
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _serve_request(self, request: "protocol.SyncRequest") -> Optional[bytes]:
        """Serve one LOCAL sync request through whichever path this
        relay runs (scheduler vs per-request) — shared by the sync
        POST handler and `/fleet/forward` (the recipes must never
        drift). → response bytes, or None after having answered 503
        backpressure itself."""
        if request.scope is not None and \
                protocol.CAP_SYNC_SCOPE not in (self.capabilities or ()):
            # This relay doesn't serve scopes (capability off): strip
            # the clause and answer the full serve — conservative
            # over-approximation, never an error. A well-behaved client
            # won't send one unnegotiated (emission gate); a hostile
            # one gets exactly the unscoped behavior.
            request = dataclasses.replace(request, scope=None)
        if self.scheduler is not None:
            from evolu_tpu.server.scheduler import SchedulerQueueFull

            try:
                return self.scheduler.submit(request)
            except SchedulerQueueFull as e:
                # Backpressure is flow control, not a pipeline error
                # (errors_total stays an error-rate): tell the client
                # when to come back instead of letting handler threads
                # pile up unboundedly. The shed IS these messages'
                # terminal station — nothing was stored (the engine
                # raises before any ACK/commit on this path).
                metrics.inc("evolu_relay_backpressure_total")
                ledger.count(ledger.SHED_BACKPRESSURE,
                             len(request.messages), owner=request.user_id)
                self._respond_retry_after(e.retry_after)
                return None
        return serve_single_request(self.store, request)

    def _obs_authorized(self) -> bool:
        """Optional token gate for the observability read surface
        (`GET /metrics`, `/stats`, `/trace/*`, `/profile`): with EVOLU_OBS_TOKEN
        set, demand the matching header (constant-time compare — the
        EVOLU_FLEET_RELOAD_TOKEN pattern from /fleet/reload). /stats
        and /trace enumerate owner ids, which the sync path treats as
        capabilities. Unset = open, the trusted-network default,
        unchanged. False → 403 already answered."""
        token = os.environ.get("EVOLU_OBS_TOKEN")
        if not token:
            return True
        import hmac

        got = self.headers.get("X-Evolu-Obs-Token", "")
        # Compare BYTES: compare_digest raises TypeError on non-ASCII
        # str inputs, and a hostile header must answer 403, not crash
        # the handler thread.
        if hmac.compare_digest(got.encode("utf-8", "replace"),
                               token.encode("utf-8")):
            return True
        metrics.inc("evolu_relay_errors_total")
        self.send_error(403, "observability token mismatch")
        return False

    def _do_trace(self) -> None:
        """GET /trace → recent trace ids; GET /trace/<id> → the span
        tree for one trace (fan-in spans included via their links);
        `?format=chrome` → the Chrome-trace export of those spans.
        A non-hex / wrong-length id answers 404 (it can never name a
        trace), never a 500."""
        import urllib.parse

        parts = urllib.parse.urlsplit(self.path)
        fmt = urllib.parse.parse_qs(parts.query).get("format", [""])[0]
        tail = parts.path[len("/trace"):].strip("/")
        if not tail:
            body = json.dumps({
                "recent": trace.recorder.recent_trace_ids(),
                "span_ring": trace.recorder.size(),
            }).encode("utf-8")
        elif len(tail) != 32 or not all(c in "0123456789abcdef" for c in tail):
            self.send_error(404, "not a trace id")
            return
        elif fmt == "chrome":
            body = json.dumps(
                trace.export_chrome(trace.recorder.spans_for(tail))
            ).encode("utf-8")
        else:
            body = json.dumps(trace.serve_trace(tail)).encode("utf-8")
        self._respond(200, body, "application/json")

    def do_GET(self) -> None:  # /ping (index.ts:250-252) + observability
        if self.path == "/ping":
            body = b"ok"
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/metrics":
            metrics.inc("evolu_relay_requests_total", endpoint="/metrics")
            if not self._obs_authorized():
                return
            try:
                # Refresh the process gauges at scrape time (uptime,
                # RSS) — no background sampler thread needed.
                metrics.update_process_gauges()
                body = metrics.render_prometheus().encode("utf-8")
            except Exception as e:  # noqa: BLE001 - scraper gets a clean 500
                metrics.inc("evolu_relay_errors_total")
                self.send_error(500, str(e))
                return
            self._respond(200, body, metrics.PROMETHEUS_CONTENT_TYPE)
        elif self.path == "/ledger" or self.path.startswith("/ledger?"):
            # The conservation-ledger read surface (obs/ledger.py):
            # station totals, owner sub-ledgers, and the audit verdict.
            # With a write-behind queue the audit runs AT a drain
            # barrier (wb.queued == wb.drained must hold there); either
            # way, concurrently in-flight requests can show as
            # transient deltas — the hard zero-violation gate is the
            # model-check episodes' quiescent audit, not a live scrape.
            metrics.inc("evolu_relay_requests_total", endpoint="/ledger")
            if not self._obs_authorized():
                return
            try:
                if self.write_behind is not None:
                    with self.write_behind.drain_barrier():
                        payload = ledger.snapshot(at_barrier=True)
                else:
                    payload = ledger.snapshot(at_barrier=True)
                body = json.dumps(payload).encode("utf-8")
            except Exception as e:  # noqa: BLE001 - reader gets a clean 500
                metrics.inc("evolu_relay_errors_total")
                self.send_error(500, str(e))
                return
            self._respond(200, body, "application/json")
        elif self.path == "/trace" or self.path.startswith("/trace/") \
                or self.path.startswith("/trace?"):
            # One fixed endpoint label — raw paths must never mint
            # registry series (the /replicate 404-before-metric rule).
            metrics.inc("evolu_relay_requests_total", endpoint="/trace")
            if not self._obs_authorized():
                return
            try:
                self._do_trace()
            except Exception as e:  # noqa: BLE001 - reader gets a clean 500
                metrics.inc("evolu_relay_errors_total")
                self.send_error(500, str(e))
            return
        elif self.path == "/stats":
            metrics.inc("evolu_relay_requests_total", endpoint="/stats")
            if not self._obs_authorized():
                return
            try:
                # store.stats() runs SQL: a shard closing mid-scrape
                # must surface as an HTTP 500, not a dropped connection.
                body = json.dumps(
                    relay_stats_payload(self.store, self.replication,
                                        self.fleet, self.write_behind,
                                        mesh_engine=self.mesh_engine,
                                        push_hub=self.push_hub,
                                        conn_tier=self.conn_tier)
                ).encode("utf-8")
            except Exception as e:  # noqa: BLE001
                metrics.inc("evolu_relay_errors_total")
                self.send_error(500, str(e))
                return
            self._respond(200, body, "application/json")
        elif self.path == "/health":
            # Readiness, not liveness (/ping is liveness): "serving"
            # vs "bootstrap/install in progress" via the PR-5 install
            # state machine's persisted phase marker (+ per-owner
            # rebalance state when fleet-configured) — fleet failover
            # probes and the bench must never route to a relay
            # mid-install. 503 while installing so dumb HTTP checks
            # (LB health probes) read it without parsing the body.
            metrics.inc("evolu_relay_requests_total", endpoint="/health")
            try:
                if self.fleet is not None:
                    serving, detail = self.fleet.health_payload()
                else:
                    from evolu_tpu.server.snapshot import install_phase

                    phase = install_phase(self.store)
                    serving = phase is None
                    detail = {
                        "status": "serving" if serving else "installing",
                        "install_phase": phase,
                    }
                if self.scheduler is not None:
                    # Saturation signal for operators / load-aware
                    # probing — readiness itself stays install-driven
                    # (a full queue answers 503 per request already).
                    detail["queue_depth"] = self.scheduler.depth()
                if self.write_behind is not None:
                    # Backlog + drain watermark (PR-11): fleet failover
                    # and the rebalance readiness probe must not route
                    # onto a relay whose materialization backlog is at
                    # its admission bound — a saturated queue IS
                    # not-ready (it would 503 the rerouted traffic
                    # anyway; better to fail over before sending it).
                    wbd = self.write_behind.health_payload()
                    detail["write_behind"] = wbd
                    if wbd["saturated"] or wbd["failing"]:
                        # Saturated OR persistently failing drain: not
                        # ready. The failing case matters because the
                        # backlog may sit BELOW max_rows while every
                        # flush-needing request hangs on the wedged
                        # drain — without this, fleet failover would
                        # keep routing onto a relay that cannot serve.
                        serving = False
                        detail["status"] = (
                            "backlogged" if wbd["saturated"]
                            else "drain-failing"
                        )
            except Exception as e:  # noqa: BLE001 - probe gets a clean 500
                metrics.inc("evolu_relay_errors_total")
                self.send_error(500, str(e))
                return
            self._respond(200 if serving else 503,
                          json.dumps(detail).encode("utf-8"),
                          "application/json")
        elif self.path == "/fleet":
            if self.fleet is None:
                self.send_error(404)
                return
            metrics.inc("evolu_relay_requests_total", endpoint="/fleet")
            try:
                body = json.dumps(self.fleet.stats_payload()).encode("utf-8")
            except Exception as e:  # noqa: BLE001
                metrics.inc("evolu_relay_errors_total")
                self.send_error(500, str(e))
                return
            self._respond(200, body, "application/json")
        elif self.path == "/profile" or self.path.startswith("/profile?"):
            # Live profiling (ISSUE 16): capture ?ms= of real traffic
            # as a loadable chrome/perfetto trace. Token-gated like the
            # rest of the obs surface (span names carry owner ids);
            # single-flight because jax.profiler allows one capture
            # per process.
            metrics.inc("evolu_relay_requests_total", endpoint="/profile")
            if not self._obs_authorized():
                return
            import urllib.parse

            q = urllib.parse.parse_qs(urllib.parse.urlsplit(self.path).query)
            try:
                ms = float(q.get("ms", ["250"])[0])
            except ValueError:
                self.send_error(400, "ms must be a number")
                return
            # Clamp: long enough to catch a batch, short enough that a
            # fat-fingered ms=3600000 cannot park a handler for an hour.
            ms = min(max(ms, 10.0), 30_000.0)
            if not _PROFILE_LOCK.acquire(blocking=False):
                metrics.inc("evolu_relay_profile_busy_total")
                self.send_error(429, "a profile capture is already running")
                return
            try:
                body = json.dumps(capture_live_profile(ms)).encode("utf-8")
            except Exception as e:  # noqa: BLE001 - reader gets a clean 500
                metrics.inc("evolu_relay_errors_total")
                self.send_error(500, str(e))
                return
            finally:
                _PROFILE_LOCK.release()
            self._respond(200, body, "application/json")
        elif self.path.startswith("/push/poll"):
            self._do_push_poll()
        else:
            self.send_error(404)

    def _do_push_poll(self) -> None:
        """GET /push/poll — the long-poll subscription leg
        (server/push.py). On THIS tier the poll parks the handler
        thread on an Event (the reference shape, fine at small scale);
        the event-loop tier (server/conn.py) intercepts the same path
        before the handler pool and parks the bare connection instead.
        This branch is also that tier's byte-identity fallback for the
        shapes it won't answer itself (no hub → 404, malformed query
        → 400). Framing here and in conn.frame_response must stay
        aligned — the twin-relay oracle test pins it."""
        from evolu_tpu.server import push as push_mod

        metrics.inc("evolu_relay_requests_total", endpoint="/push/poll")
        if self.push_hub is None:
            self.send_error(404)
            return
        import urllib.parse

        parts = urllib.parse.urlsplit(self.path)
        try:
            owner, node, cursor, timeout, tags = push_mod.parse_poll_query(
                parts.query)
        except ValueError as e:
            metrics.inc("evolu_relay_errors_total")
            self.send_error(400, str(e))
            return
        if self.fleet is not None:
            # A subscription lives at the owner's PLACED relay — where
            # its mutations are served and hub-notified. 307 even in
            # forward mode: proxying a long-poll would pin a handler
            # (or a poller, on the event tier) for the whole park.
            from evolu_tpu.server.fleet import FleetNotReady

            try:
                action, peer = self.fleet.route(owner)
            except FleetNotReady as e:
                self._respond_retry_after(e.retry_after)
                return
            if action != "local":
                metrics.inc("evolu_push_redirects_total")
                self.send_response(307)
                self.send_header("Location", peer + self.path)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
        try:
            body = self.push_hub.poll_blocking(owner, node, cursor, timeout,
                                               tags=tags)
        except push_mod.HubFull as e:
            self._respond_retry_after(e.retry_after)
            return
        self._respond(200, body, "application/json")

    def do_POST(self) -> None:  # POST / (index.ts:224-248)
        if self.path.startswith("/replicate/"):
            if self.replication is None:
                # Only a relay CONFIGURED for replication exposes the
                # gossip/snapshot surface: /replicate/summary and the
                # snapshot manifest enumerate owner ids, which the sync
                # path treats as capabilities — a plain client-facing
                # relay must not disclose them.
                self.send_error(404)
                return
            self._do_replicate()
            return
        if self.path.startswith("/fleet/"):
            self._do_fleet()
            return
        t0 = time.perf_counter()
        # Count the request BEFORE any reject so errors_total can never
        # exceed requests_total (error-rate = errors/requests must stay
        # a fraction).
        metrics.inc("evolu_relay_requests_total", endpoint="/")
        length = self._body_length()
        if length is None:
            return
        if length > MAX_BODY_BYTES:
            metrics.inc("evolu_relay_errors_total")
            self.send_error(413)
            return
        body = self.rfile.read(length)
        metrics.observe("evolu_relay_request_bytes", len(body),
                        buckets=metrics.SIZE_BUCKETS)
        # Incoming trace context (obs/trace.py): a malformed or
        # oversized traceparent parses to None and the request simply
        # proceeds untraced — NEVER a 4xx/5xx (header-fuzz-pinned).
        tctx = trace.parse_traceparent(
            self.headers.get(trace.TRACEPARENT_HEADER)
        )
        srv_span = trace.start_span("relay.sync", parent=tctx,
                                    attrs={"endpoint": "/"})
        _tok = trace.activate(srv_span.context)
        request = None
        served = False
        try:
            request = protocol.decode_sync_request(body)
            srv_span.set_attr("owner", request.user_id)
            # Ledger ingress at the decode boundary (a body that never
            # decoded never became messages): every message of this
            # delivery attempt must reach exactly one terminal station
            # — store classification, a shed/reject answer, or a fleet
            # egress (obs/ledger.py `server-flow`).
            ledger.count(ledger.INGRESS_SYNC, len(request.messages),
                         owner=request.user_id)
            if self.fleet is not None:
                if not self._route_fleet(request, body):
                    served = True  # egress/shed terminal counted there
                    return  # answered: 307/forwarded/503-not-ready
            shard = (
                self.store.shard_index(request.user_id)
                if hasattr(self.store, "shard_index") else 0
            )
            metrics.inc("evolu_relay_shard_requests_total", shard=str(shard))
            out = self._serve_request(request)
            served = True  # terminals counted (store path or 503 shed)
            if out is None:
                return  # 503 backpressure already answered
            # Ingest-mix counters AFTER routing AND a successful
            # serve: a 307'd/forwarded request never counts at a
            # relay whose store it skips, and a 503-shed or errored
            # round (retried by the client) never counts at all —
            # each message counts once fleet-wide, at the relay that
            # actually ingested it.
            _count_ingest_mix(request.messages)
            if self.push_hub is not None and request.messages:
                # Wake parked subscriptions AFTER the serve committed
                # (a woken client's sync round must observe the rows);
                # the timestamps carry the author-node metadata the
                # hub's own-write exclusion gates on (server/push.py).
                self.push_hub.notify(
                    request.user_id,
                    [m.timestamp for m in request.messages],
                    tags=_notify_tags(request))
        except Exception as e:  # noqa: BLE001 - index.ts:231-233
            # The flight dump rides the exception (server-side only —
            # the wire response stays a bare 500, no event leakage).
            flight.attach(e)
            srv_span.set_attr("error", repr(e))
            metrics.inc("evolu_relay_errors_total")
            if request is not None and not served:
                # Ingressed but never reached a store terminal: the 500
                # answer IS the terminal (the client's retry is a fresh
                # delivery attempt with its own ingress count).
                ledger.count(ledger.REJECT_INVALID, len(request.messages),
                             owner=request.user_id)
            log("dev", "relay sync request failed", error=repr(e))
            self.send_error(500, str(e))
            return
        finally:
            trace.deactivate(_tok)
            srv_span.end()
            metrics.observe(
                "evolu_relay_request_ms", (time.perf_counter() - t0) * 1e3,
                exemplar=srv_span.trace_id,
            )
        if self.replication is not None and request.messages:
            # Debounced write hint: fresh rows should reach peer relays
            # at gossip-debounce latency, not interval latency. The
            # hint carries the write's trace context so the gossip
            # round that ships these rows records into the SAME trace
            # (the fleet-wide convergence trace, obs/trace.py).
            self.replication.hint(origin=srv_span.context)
        # The respond leg gets its own span (explicitly parented — the
        # server span above already closed so the request_ms exemplar
        # and the latency split stay consistent): queue-wait
        # (sched.queue) vs engine (engine.batch, linked) vs respond.
        rspan = trace.start_span("relay.respond", parent=srv_span.context)
        out = self._negotiate_caps(request, out)
        metrics.observe("evolu_relay_response_bytes", len(out),
                        buckets=metrics.SIZE_BUCKETS)
        rspan.set_attr("bytes", len(out))
        # End BEFORE the socket write: the client can race a
        # GET /trace/<id> the instant it reads the response, and the
        # span must already be in the ring (the write itself is the
        # kernel's, not ours to time).
        rspan.end()
        self._respond(200, out, "application/octet-stream")

    def _do_replicate(self) -> None:
        """POST /replicate/{summary,pull,snapshot,snapshot/chunk} — the
        peer gossip + bootstrap surface (server/replicate.py,
        server/snapshot.py). Malformed bodies answer 400 (the wire
        decoders raise ValueError only; unknown/expired snapshot ids
        are a deliberate 400 too — the puller's restart signal);
        anything else is a 500 like the sync path."""
        from evolu_tpu.server import replicate, snapshot

        if self.path not in ("/replicate/summary", "/replicate/pull",
                             "/replicate/snapshot", "/replicate/snapshot/chunk"):
            # 404 BEFORE any metric: the endpoint label must only ever
            # take allowlisted values — counting raw unknown paths
            # would let any caller mint registry series without bound.
            self.send_error(404)
            return
        metrics.inc("evolu_relay_requests_total", endpoint=self.path)
        length = self._body_length()
        if length is None:
            return
        if length > MAX_BODY_BYTES:
            metrics.inc("evolu_relay_errors_total")
            self.send_error(413)
            return
        body = self.rfile.read(length)
        # The gossiping peer's round span context rides the
        # traceparent header; its trace id is the ORIGIN trace of the
        # write that armed the round (replicate.hint) — serving spans
        # here land in the same fleet-wide convergence trace.
        tctx = trace.parse_traceparent(
            self.headers.get(trace.TRACEPARENT_HEADER)
        )
        sspan = trace.start_span(
            "repl.serve", parent=tctx,
            attrs={"leg": self.path.rsplit("/replicate/", 1)[-1]},
        )
        from contextlib import nullcontext

        # Every /replicate serve READS the store (summaries, pulls,
        # snapshot capture): with write-behind on, force a drain first
        # and hold the drain lock for the serve — peers and snapshot
        # pullers must only ever see COMMITTED state (a snapshot of
        # half-materialized rows would install as truth elsewhere).
        barrier = (
            self.write_behind.drain_barrier()
            if self.write_behind is not None else nullcontext()
        )
        try:
            with sspan, trace.use(sspan.context), barrier:
                if self.path == "/replicate/summary":
                    out = replicate.serve_summary(
                        self.store, body, self.replication, origin=tctx
                    )
                elif self.path == "/replicate/pull":
                    out = replicate.serve_pull(
                        self.store, body,
                        per_owner=self.replication.pull_messages_per_owner,
                        per_response=self.replication.pull_messages_per_response,
                    )
                elif self.path == "/replicate/snapshot":
                    out = snapshot.serve_snapshot(self.store, body, self.replication)
                else:
                    out = snapshot.serve_snapshot_chunk(self.store, body, self.replication)
        except ValueError as e:
            metrics.inc("evolu_relay_errors_total")
            self.send_error(400, str(e))
            return
        except Exception as e:  # noqa: BLE001 - peer gets a clean 500
            flight.attach(e)
            metrics.inc("evolu_relay_errors_total")
            log("dev", "relay replicate request failed", error=repr(e))
            self.send_error(500, str(e))
            return
        self._respond(200, out, "application/octet-stream")

    # -- fleet routing (server/fleet.py) --

    def _route_fleet(self, request: "protocol.SyncRequest", body: bytes) -> bool:
        """Owner-sharded placement check for one sync POST. True →
        this relay is placed for the owner and ready: caller serves
        locally. False → already answered: 307 + the authoritative
        peer URL (redirect mode), the peer's proxied response (forward
        mode), or 503 + Retry-After (owner mid-install / target
        briefly unreachable — the client's backoff retries)."""
        from evolu_tpu.server.fleet import FleetNotReady

        n_msgs = len(request.messages)
        try:
            action, target = self.fleet.route(request.user_id)
        except FleetNotReady as e:
            ledger.count(ledger.SHED_BACKPRESSURE, n_msgs,
                         owner=request.user_id)
            self._respond_retry_after(e.retry_after)
            return False
        if action == "local":
            return True
        if action == "redirect":
            metrics.inc("evolu_fleet_redirects_total")
            ledger.count(ledger.EGRESS_REDIRECT, n_msgs,
                         owner=request.user_id)
            # Zero-duration event span: the trace shows WHERE the
            # client was bounced (its own sync.redirect span shows the
            # follow; this one shows the relay that answered 307).
            trace.record_span("fleet.redirect", trace.current(),
                              time.time(), 0.0, {"target": target})
            self.send_response(307)
            self.send_header("Location", target + "/")
            self.send_header("Content-Length", "0")
            self.end_headers()
            return False
        # forward: wrap the UNTOUCHED client body in the hop-guarded
        # envelope and relay the peer's raw response back. The forward
        # POST carries the ambient trace context (headers only — the
        # envelope bytes are exactly the client's).
        metrics.inc("evolu_fleet_forwards_total")
        import urllib.error

        from evolu_tpu.sync.client import _http_post

        env = protocol.encode_fleet_forward(
            protocol.FleetForward(body, self.fleet.self_url, 1)
        )
        fwd_span = trace.start_span("fleet.forward", parent=trace.current(),
                                    attrs={"target": target})
        try:
            with fwd_span:
                # The FORWARD span's context rides the header (not the
                # ambient server span's) so the peer's
                # fleet.forward.serve span parents under this hop —
                # same rule as replicate's per-leg spans.
                out = _http_post(
                    target + "/fleet/forward", env, retries=1,
                    headers=trace.inject_headers(ctx=fwd_span.context))
        except urllib.error.HTTPError as e:
            if e.code in (429, 503):
                # The peer is shedding load: flow control, relayed.
                metrics.inc("evolu_fleet_forward_failures_total")
                ledger.count(ledger.SHED_BACKPRESSURE, n_msgs,
                             owner=request.user_id)
                self._respond_retry_after(0.25)
                return False
            # A DEFINITIVE answer (404 = peer not fleet-enabled, 400 =
            # envelope rejected, 500 = peer pipeline failure) is not
            # transient — masking it as 503 would make clients spin
            # backoff forever while errors_total reads healthy. 502 it.
            metrics.inc("evolu_relay_errors_total")
            metrics.inc("evolu_fleet_forward_failures_total")
            ledger.count(ledger.REJECT_INVALID, n_msgs,
                         owner=request.user_id)
            log("dev", "fleet forward rejected by peer", peer=target,
                code=e.code)
            self.send_error(502, f"fleet forward target answered {e.code}")
            return False
        except Exception as e:  # noqa: BLE001 - target down mid-window:
            # flow control, not an error — the next route() re-probes
            # and fails over.
            metrics.inc("evolu_fleet_forward_failures_total")
            ledger.count(ledger.SHED_BACKPRESSURE, n_msgs,
                         owner=request.user_id)
            log("dev", "fleet forward failed", peer=target, error=repr(e))
            self._respond_retry_after(0.25)
            return False
        # Forwarded and answered by the peer: these messages left this
        # process — egress.forward is their terminal HERE; the peer's
        # ingress.forward accounts them in ITS ledger.
        ledger.count(ledger.EGRESS_FORWARD, n_msgs, owner=request.user_id)
        metrics.observe("evolu_relay_response_bytes", len(out),
                        buckets=metrics.SIZE_BUCKETS)
        self._respond(200, out, "application/octet-stream")
        return False

    def _do_fleet(self) -> None:
        """POST /fleet/{forward,reload} — the fleet peer/operator
        surface. `/fleet/forward` carries a hop-guarded peer envelope
        (octet-stream, ValueError→400 like every wire decoder);
        `/fleet/reload` is the static-config push (JSON body =
        FleetConfig.to_json; a stale version answers 400)."""
        if self.fleet is None or self.path not in ("/fleet/forward",
                                                   "/fleet/reload"):
            # 404 BEFORE any metric: the endpoint label must only ever
            # take allowlisted values.
            self.send_error(404)
            return
        metrics.inc("evolu_relay_requests_total", endpoint=self.path)
        length = self._body_length()
        if length is None:
            return
        if length > MAX_BODY_BYTES:
            metrics.inc("evolu_relay_errors_total")
            self.send_error(413)
            return
        body = self.rfile.read(length)
        request = None
        served = False
        try:
            if self.path == "/fleet/forward":
                env = protocol.decode_fleet_forward(body)
                if env.hops != 1:
                    # The enforced hop guard: forwarders always send
                    # hops=1 and this handler never forwards again, so
                    # anything else is a malformed or replayed
                    # envelope — reject before any side effect.
                    raise ValueError(
                        f"fleet forward from {env.origin!r} carries "
                        f"hops={env.hops}; only single-hop envelopes "
                        "are served"
                    )
                request = protocol.decode_sync_request(env.payload)
                # NO route() here: a forwarded request is served where
                # it lands, even if the rings disagree mid-reload
                # (scoped gossip drains any stray owner).
                metrics.inc("evolu_fleet_forwarded_served_total")
                # Ledger ingress: the forwarding hop counted
                # egress.forward in ITS ledger; these messages enter
                # THIS process here.
                ledger.count(ledger.INGRESS_FORWARD, len(request.messages),
                             owner=request.user_id)
                # The forwarder's span context rode the traceparent
                # header: the serve span here joins the same trace, so
                # GET /trace/<id> on THIS relay shows the hop the
                # client never saw (malformed header → None → fresh
                # trace, never an error).
                tctx = trace.parse_traceparent(
                    self.headers.get(trace.TRACEPARENT_HEADER)
                )
                fspan = trace.start_span(
                    "fleet.forward.serve", parent=tctx,
                    attrs={"owner": request.user_id, "origin": env.origin},
                )
                with fspan, trace.use(fspan.context):
                    out = self._serve_request(request)
                served = True  # terminals counted (store path or shed)
                if out is None:
                    return  # 503 backpressure already answered
                _count_ingest_mix(request.messages)
                if self.push_hub is not None and request.messages:
                    # The forward SERVE is where the owner's rows land
                    # — and where its subscriptions are parked (push
                    # polls 307 to placement): notify here, never at
                    # the forwarding hop.
                    self.push_hub.notify(
                        request.user_id,
                        [m.timestamp for m in request.messages],
                        tags=_notify_tags(request))
                if self.replication is not None and request.messages:
                    self.replication.hint(origin=fspan.context)
                out = self._negotiate_caps(request, out)
                # Recorded before the socket write — see do_POST's
                # respond span.
                trace.start_span("relay.respond", parent=fspan.context,
                                 attrs={"bytes": len(out)}).end()
                self._respond(200, out, "application/octet-stream")
                return
            # /fleet/reload is a control-plane MUTATION on the
            # client-facing port: with EVOLU_FLEET_RELOAD_TOKEN set,
            # demand the matching header (constant-time compare) —
            # else anyone who can reach the sync port could hijack the
            # ring with a high-version config. Unset = open, for
            # trusted-network meshes like the /replicate/* surface
            # (docs/FLEET.md).
            token = os.environ.get("EVOLU_FLEET_RELOAD_TOKEN")
            if token:
                import hmac

                got = self.headers.get("X-Evolu-Fleet-Token", "")
                if not hmac.compare_digest(got, token):
                    metrics.inc("evolu_relay_errors_total")
                    self.send_error(403, "fleet reload token mismatch")
                    return
            cfg_json = json.loads(body.decode("utf-8"))
            from evolu_tpu.utils.config import FleetConfig

            cfg = FleetConfig.from_json(cfg_json)
            rebalancing = self.fleet.apply_config(cfg)
            out = json.dumps({
                "ring_version": self.fleet.config.version,
                "rebalancing": rebalancing,
            }).encode("utf-8")
            self._respond(200, out, "application/json")
        except ValueError as e:
            metrics.inc("evolu_relay_errors_total")
            if request is not None and not served:
                ledger.count(ledger.REJECT_INVALID, len(request.messages),
                             owner=request.user_id)
            self.send_error(400, str(e))
        except Exception as e:  # noqa: BLE001 - clean 500, like sync
            flight.attach(e)
            metrics.inc("evolu_relay_errors_total")
            if request is not None and not served:
                ledger.count(ledger.REJECT_INVALID, len(request.messages),
                             owner=request.user_id)
            log("dev", "relay fleet request failed", error=repr(e))
            self.send_error(500, str(e))


class _RelayHTTPServer(ThreadingHTTPServer):
    # The reference's deploy allows 25 concurrent connections
    # (examples/server-nodejs/fly.toml); socketserver's default listen
    # backlog of 5 resets simultaneous connects well below that.
    request_queue_size = 128


class RelayServer:
    """ThreadingHTTPServer wrapper; `url` once started.

    `batching=True` (or an explicit `scheduler`) routes sync POSTs
    through the continuous-batching scheduler
    (`evolu_tpu.server.scheduler.SyncScheduler`): handler threads
    coalesce into single `BatchReconciler` passes, queue-full answers
    503 + Retry-After, and `stop()` drains in-flight batches before
    the store closes. Default off — the per-request path is the
    reference relay's shape and stays the baseline.

    `peers=[url, ...]` (or an explicit `replication` manager) turns on
    relay↔relay Merkle anti-entropy (`server/replicate.py`): the
    manager gossips per-owner tree summaries with each peer, pulls only
    diverged ranges, and — when this relay also batches — submits the
    pulled messages through the scheduler so replication traffic
    coalesces with live client traffic into the same fused engine
    passes. `peers=[]` (non-None) makes a pure LISTENER: it serves the
    gossip endpoints without polling anyone. Relays NOT configured for
    replication answer 404 on `/replicate/*` — the summary endpoint
    (and the snapshot manifest) enumerate owner ids (capabilities on
    the sync path), so the surface is for peer meshes on trusted
    networks, not for clients. `bootstrap_lag_owners` enables snapshot
    bootstrap (`server/snapshot.py`): an empty peer — or one lacking at
    least that many advertised owners — installs a donor snapshot
    instead of crawling history through capped pulls.

    `checkpoint_interval_s` (with `checkpoint_path`, defaulting to
    `<store path>.checkpoint` for file-backed stores) runs periodic
    local snapshot checkpoints for crash-consistent fast restart
    (`snapshot.write_checkpoint` / `snapshot.restore_checkpoint`).

    `connection_tier` (ISSUE 13, `server/conn.py`): "threaded" (the
    reference-shaped ThreadingHTTPServer — default, and every
    byte-identity pin's baseline) or "eventloop" (one selectors loop
    owns every socket, requests run the same handler on a bounded
    pool, push long-polls park the bare connection — 10^4-10^5 idle
    subscriptions cost FDs, not threads). `push` enables the
    long-poll subscription hub (`server/push.py`, default on — a new
    GET endpoint, zero effect on existing responses) on either tier.
    `start()`/`stop()` own every lifecycle."""

    def __init__(self, store: Optional[RelayStore] = None, host: str = "127.0.0.1",
                 port: int = 0, batching: bool = False, scheduler=None,
                 peers: Optional[Sequence[str]] = None, replication=None,
                 replication_interval_s: float = 30.0,
                 bootstrap_lag_owners: Optional[int] = None,
                 checkpoint_interval_s: Optional[float] = None,
                 checkpoint_path: Optional[str] = None,
                 capabilities: Optional[Sequence[str]] = None,
                 write_behind: Optional[bool] = None,
                 write_behind_log: Optional[str] = None,
                 mesh_engine: Optional[bool] = None,
                 mesh_ctx=None,
                 connection_tier: Optional[str] = None,
                 push: Optional[bool] = None):
        self.store = store or RelayStore()
        # capabilities=() emulates a v1 peer (never echoes the
        # extension — tests pin the byte-identical fallback with it).
        self.capabilities = (
            protocol.KNOWN_CAPABILITIES if capabilities is None
            else tuple(capabilities)
        )
        from evolu_tpu.utils.config import default_config

        # PR-11 storage inversion (docs/WRITE_BEHIND.md): opt-in via
        # constructor arg, EVOLU_WRITE_BEHIND=1, or Config.write_behind
        # — default OFF (the synchronous path is the reference shape
        # and every byte-identity pin's baseline). It rides the
        # batching engine, so enabling it implies batching.
        if write_behind is None:
            env = os.environ.get("EVOLU_WRITE_BEHIND", "")
            if env:
                # A SET env var wins in both directions — an operator
                # must be able to force the synchronous reference path
                # (EVOLU_WRITE_BEHIND=0) over a Config default when
                # bisecting, not just force the inversion on.
                write_behind = env.lower() not in ("0", "false", "no", "off")
            else:
                write_behind = default_config.write_behind
        self.write_behind = None
        if write_behind:
            from evolu_tpu.storage.write_behind import WriteBehindQueue

            if write_behind_log is None:
                shards = getattr(self.store, "shards", None)
                base = getattr(
                    getattr((shards[0] if shards else self.store), "db", None),
                    "path", None,
                )
                if base and base != ":memory:":
                    write_behind_log = base + ".wblog"
            # PR-19 parallel drain knobs (same env-wins-both-ways rule
            # as EVOLU_WRITE_BEHIND): worker count + process-per-shard
            # mode resolve here so an operator can steer a deployed
            # relay without a Config edit.
            env_workers = os.environ.get("EVOLU_WB_DRAIN_WORKERS", "")
            drain_workers = (
                int(env_workers) if env_workers
                else default_config.wb_drain_workers
            )
            env_proc = os.environ.get("EVOLU_WB_DRAIN_PROCESS", "")
            drain_process = (
                env_proc.lower() not in ("0", "false", "no", "off")
                if env_proc else default_config.wb_drain_process
            )
            self.write_behind = WriteBehindQueue(
                self.store, log_path=write_behind_log,
                max_rows=default_config.write_behind_max_rows,
                drain_batch_rows=default_config.write_behind_drain_rows,
                drain_workers=drain_workers,
                drain_process=drain_process,
            )
            batching = True
        # PR-12 mesh-sharded engine (docs/MESH.md): opt-in via
        # constructor arg, EVOLU_MESH_ENGINE, or Config.mesh_engine —
        # default OFF until the parity gate is green in a deployment.
        # It is a property of the ENGINE pass, so enabling it implies
        # batching; the mesh context itself is resolved lazily on the
        # scheduler's dispatcher thread (importing jax here would break
        # the no-backend-at-import contract).
        if mesh_engine is None and mesh_ctx is None:
            env = os.environ.get("EVOLU_MESH_ENGINE", "")
            if env:
                mesh_engine = env.lower() not in ("0", "false", "no", "off")
            else:
                mesh_engine = default_config.mesh_engine
        self.mesh_engine = bool(mesh_engine) or mesh_ctx is not None
        if self.mesh_engine:
            batching = True
        self.scheduler = scheduler
        if batching and scheduler is None:
            from evolu_tpu.server.scheduler import SyncScheduler

            self.scheduler = SyncScheduler(
                self.store, write_behind=self.write_behind,
                mesh_ctx=mesh_ctx, mesh_engine=self.mesh_engine,
            )
        self.replication = replication
        if peers is not None and replication is None:
            from evolu_tpu.server.replicate import ReplicationManager

            self.replication = ReplicationManager(
                self.store, peers, scheduler=self.scheduler,
                interval_s=replication_interval_s,
                bootstrap_lag_owners=bootstrap_lag_owners,
                write_behind=self.write_behind,
            )
        self.checkpointer = None
        if checkpoint_interval_s is None:
            from evolu_tpu.utils.config import default_config

            checkpoint_interval_s = default_config.checkpoint_interval_s
        if checkpoint_interval_s is not None:
            from evolu_tpu.server.snapshot import CheckpointWriter

            if checkpoint_path is None:
                store_path = getattr(getattr(self.store, "db", None), "path", None)
                if not store_path or store_path == ":memory:":
                    raise ValueError(
                        "checkpoint_interval_s needs checkpoint_path for "
                        "non-file-backed stores"
                    )
                checkpoint_path = store_path + ".checkpoint"
            self.checkpointer = CheckpointWriter(
                self.store, checkpoint_path, checkpoint_interval_s,
                barrier=(self.write_behind.drain_barrier
                         if self.write_behind is not None else None),
            )
        self.fleet = None
        # Push subscriptions (ISSUE 13, server/push.py): on by default
        # — a new GET endpoint, zero effect on existing responses.
        # Both connection tiers serve the same hub.
        if push is None:
            push = default_config.push_subscriptions
        self.push_hub = None
        if push:
            from evolu_tpu.server.push import PushHub

            self.push_hub = PushHub(
                max_subscriptions=default_config.push_max_subscriptions,
                default_timeout_s=default_config.push_poll_timeout_s,
            )
            if self.replication is not None and getattr(
                    self.replication, "push_hub", None) is None:
                # Replication ingest is a wakeup source too: rows a
                # gossip round lands (a partition HEALING) must wake
                # this relay's parked subscribers — they will never
                # arrive as a local sync POST.
                self.replication.push_hub = self.push_hub
        # Connection tier (ISSUE 13 tentpole, server/conn.py):
        # "threaded" (the reference-shaped ThreadingHTTPServer,
        # default) or "eventloop" (idle connections cost FDs, not
        # threads). Constructor arg > EVOLU_CONN_TIER > Config.
        if connection_tier is None:
            connection_tier = (os.environ.get("EVOLU_CONN_TIER")
                               or default_config.connection_tier)
        if connection_tier not in ("threaded", "eventloop"):
            raise ValueError(
                f"connection_tier must be 'threaded' or 'eventloop', "
                f"got {connection_tier!r}")
        self.connection_tier = connection_tier
        self._handler_cls = type(
            "BoundHandler", (_Handler,),
            {"store": self.store, "scheduler": self.scheduler,
             "replication": self.replication,
             "capabilities": self.capabilities,
             "write_behind": self.write_behind,
             "mesh_engine": self.mesh_engine,
             "push_hub": self.push_hub},
        )
        if connection_tier == "eventloop":
            from evolu_tpu.server.conn import EventLoopHTTPServer

            self._httpd = EventLoopHTTPServer(
                (host, port), self._handler_cls,
                push_hub=self.push_hub,
                handler_threads=default_config.conn_handler_threads,
                max_pending=default_config.conn_max_pending,
                read_timeout_s=default_config.conn_read_timeout_s,
                write_timeout_s=default_config.conn_write_timeout_s,
                max_header_bytes=default_config.conn_max_header_bytes,
            )
            self._handler_cls.conn_tier = self._httpd
        else:
            self._httpd = _RelayHTTPServer((host, port), self._handler_cls)
        self._thread: Optional[threading.Thread] = None

    def enable_fleet(self, config, self_url: Optional[str] = None):
        """Join an owner-sharded fleet (server/fleet.py): install the
        placement ring, start answering non-placed sync POSTs with
        307/forward, scope this relay's replication gossip to
        placement, and expose `/fleet/reload` + the fleet `/health`
        detail. The server socket binds at CONSTRUCTION, so call this
        between construction and `start()` when the relay has peers:
        the replication loop's first gossip round fires immediately on
        start and must already be placement-scoped (an unscoped first
        round would pull owners this member is not placed for). The
        FleetConfig must be the same object of truth on every member —
        see utils/config.py."""
        from evolu_tpu.server.fleet import FleetManager

        self.fleet = FleetManager(
            self.store, config, self_url or self.url,
            replication=self.replication,
            write_behind=self.write_behind,
        )
        self._handler_cls.fleet = self.fleet
        if self.replication is not None:
            self.replication.fleet = self.fleet
        return self.fleet

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def _publish_build_info(self) -> None:
        """`evolu_build_info` (constant 1, facts in labels): which
        build/topology THIS relay process runs — a fleet dashboard must
        tell a mesh-sharded event-loop relay from a default one without
        SSH. Never raises: identity labels are not worth a failed
        start."""
        try:
            from evolu_tpu import __version__
            from evolu_tpu.utils.config import default_config

            shards = getattr(self.store, "shards", None)
            db = getattr((shards[0] if shards else self.store), "db", None)
            mesh_devices = default_config.mesh_devices
            metrics.set_build_info(
                version=__version__,
                backend=("native" if hasattr(db, "relay_insert_packed")
                         else "python"),
                shards=(len(shards) if shards else 1),
                batching=int(self.scheduler is not None),
                write_behind=int(self.write_behind is not None),
                mesh_engine=int(self.mesh_engine),
                mesh_devices=("auto" if mesh_devices is None
                              else mesh_devices),
                connection_tier=self.connection_tier,
                push=int(self.push_hub is not None),
            )
        except Exception:  # noqa: BLE001,S110 - see docstring
            pass

    def start(self) -> "RelayServer":
        self._publish_build_info()
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True, name="evolu-relay")
        self._thread.start()
        if self.replication is not None:
            self.replication.start()
        if self.checkpointer is not None:
            self.checkpointer.start()
        return self

    def stop(self) -> None:
        if self.push_hub is not None:
            # BEFORE the HTTP server stops: resolve every parked
            # long-poll (wake=false) so threaded-tier handler threads
            # unblock and the event tier can flush the responses in
            # its shutdown drain window.
            self.push_hub.close()
        self._httpd.shutdown()
        if self._thread:
            self._thread.join()
        if self.fleet is not None:
            # Before replication/store teardown: a rebalance thread may
            # still be ingesting through the store (stop joins it).
            self.fleet.stop()
        if self.checkpointer is not None:
            # Before the store closes; a capture in flight finishes its
            # read transactions first (stop joins the loop thread).
            self.checkpointer.stop()
        if self.replication is not None:
            # Before the scheduler drains and WELL before the store
            # closes: an in-flight gossip round may still be submitting
            # pulled messages (stop() joins the loop thread).
            self.replication.stop()
        if self.scheduler is not None:
            # Drain BEFORE the store closes — injected or owned alike
            # (stop() is idempotent): every queued request is served
            # through full-size batches, handler threads blocked in
            # submit() get their responses, and only then does the
            # storage go away. Post-drain submits answer 503.
            self.scheduler.stop()
        if self.write_behind is not None:
            # After the scheduler drained (its final batches appended
            # records), before the store closes: flush everything to
            # SQLite and stop the drain thread. The log is empty at
            # this point — a clean shutdown leaves nothing to replay.
            self.write_behind.close()
        self._httpd.server_close()
        self.store.close()


def serve(path: str = ":memory:", host: str = "0.0.0.0", port: int = 4000) -> RelayServer:
    """The `examples/server-nodejs` entry point analog."""
    server = RelayServer(RelayStore(path), host, port)
    return server.start()


# -- pre-forked multiprocess relay (VERDICT r2 #8) --


def _open_store(path: str, backend: str, shards: int):
    """The one store-construction rule shared by the relay parent (schema
    pre-creation) and its workers — they must agree on the layout."""
    if shards > 1:
        return ShardedRelayStore(path, backend, shards=shards)
    return RelayStore(path, backend)


def _mp_worker_main(host: str, port: int, path: str, shards: int, backend: str) -> None:
    """One relay worker process: bind its own SO_REUSEPORT listening
    socket on the shared port (the kernel load-balances incoming
    connections across the workers' accept queues) and serve the
    SHARED file-backed sharded store — cross-process safety comes from
    SQLite WAL + busy_timeout (set in RelayStore for file paths)."""
    import socket

    store = _open_store(path, backend, shards)
    handler = type("BoundHandler", (_Handler,), {"store": store})

    class _ReuseportServer(_RelayHTTPServer):
        def server_bind(self):
            self.socket.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            super().server_bind()

    httpd = _ReuseportServer((host, port), handler)
    print("READY", flush=True)  # parent waits for every worker's listen()
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:  # pragma: no cover - parent terminates us
        pass


class MultiprocessRelay:
    """Pre-forked relay: N worker PROCESSES accept on one SO_REUSEPORT
    port and share one file-backed (sharded) store. This is the
    multi-core deployment shape — the reference's fly.io deploy runs
    one Node process, this scales the accept path and the Python/HTTP
    work across cores while SQLite WAL serializes per-shard writes.
    Requires a file path (processes cannot share :memory:)."""

    def __init__(self, path: str, workers: int = 2, shards: int = 8,
                 backend: str = "auto", host: str = "127.0.0.1", port: int = 0):
        import socket

        if path == ":memory:":
            raise ValueError("MultiprocessRelay needs a file-backed store")
        self.host = host
        self._path, self._workers, self._shards, self._backend = (
            path, workers, shards, backend,
        )
        self._procs: list = []
        # Reserve the port in the REUSEPORT group (bound, NOT
        # listening, so no connection ever lands here); workers are
        # spawned in start() so a never-started or failed construction
        # leaks nothing but this socket (closed by stop()).
        self._anchor = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._anchor.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        try:
            self._anchor.bind((host, port))
            self.port = self._anchor.getsockname()[1]
            # One store open in the parent creates the schema before
            # any worker races to serve (workers use IF NOT EXISTS too).
            _open_store(path, backend, shards).close()
        except BaseException:
            self._anchor.close()
            raise

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "MultiprocessRelay":
        # Plain subprocesses (`python -m evolu_tpu.server.relay_worker`):
        # no fork of this process's jax/tunnel state, and no
        # multiprocessing-spawn re-import of __main__ (which breaks
        # under pytest/stdin drivers).
        import subprocess
        import sys
        import time
        import urllib.request

        env = dict(os.environ)
        env["PYTHONPATH"] = (
            _REPO_ROOT + (os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        )
        import select

        try:
            self._procs = [
                subprocess.Popen(
                    [sys.executable, "-m", "evolu_tpu.server.relay_worker",
                     self.host, str(self.port), self._path,
                     str(self._shards), self._backend],
                    env=env, stdout=subprocess.PIPE, text=True,
                )
                for _ in range(self._workers)
            ]
            # EVERY worker must report READY (post-listen) — returning
            # on the first responsive worker would let an N-worker
            # config silently run under-provisioned (and skew the
            # per-worker-count benchmark rows).
            waiting = {p.stdout.fileno(): p for p in self._procs}
            deadline = time.time() + 30
            while waiting and time.time() < deadline:
                dead = [p for p in self._procs if p.poll() is not None]
                if dead:
                    raise RuntimeError(
                        f"{len(dead)}/{len(self._procs)} relay workers exited "
                        f"at startup (rc={[p.returncode for p in dead]})"
                    )
                ready, _, _ = select.select(list(waiting), [], [], 0.1)
                for fd in ready:
                    if "READY" in waiting[fd].stdout.readline():
                        del waiting[fd]
            if waiting:
                raise RuntimeError(
                    f"{len(waiting)}/{len(self._procs)} relay workers did not come up"
                )
            with urllib.request.urlopen(self.url + "/ping", timeout=5):
                pass
            return self
        except BaseException:
            self.stop()
            raise

    def stop(self) -> None:
        for p in self._procs:
            if p.poll() is None:
                p.terminate()
        for p in self._procs:
            try:
                p.wait(timeout=5)
            except Exception:  # noqa: BLE001 - wedged: escalate AND reap
                p.kill()
                try:
                    p.wait(timeout=5)
                except Exception:  # noqa: BLE001,S110 - unreapable; parent
                    pass           # exit collects it
        self._procs = []
        self._anchor.close()

