"""Worker-process entry point for `relay.MultiprocessRelay`.

Lives in its own module so `python -m evolu_tpu.server.relay_worker`
does not re-execute relay.py under runpy (which would shadow the
already-imported module and warn)."""

import sys

from evolu_tpu.server.relay import _mp_worker_main


def main() -> None:
    host, port, path, shards, backend = sys.argv[1:6]
    _mp_worker_main(host, int(port), path, int(shards), backend)


if __name__ == "__main__":
    main()
