"""Relay↔relay replication: Merkle anti-entropy between relay peers.

No reference equivalent — the reference relay (apps/server, 258 LoC)
is a single node whose one SQLite file is the whole fleet. This module
turns N relays into a converging cluster using the primitive the
framework already owns: per-owner Merkle trees with base-3 minute keys
(`core/merkle.py`). Merkle-CRDTs (Sanjuán et al., arXiv:2004.00107)
and the anti-entropy literature make this the standard construction:
gossip tree digests, pull only from the diverged minute, and bandwidth
is proportional to DIVERGENCE, not to database size.

One gossip round against one peer:

1. `POST /replicate/summary` carrying MY owner→tree map; the response
   is the PEER's map. (The peer's handler also compares the incoming
   map against its own store and arms its manager's debounced hint on
   divergence, so healing propagates from both directions of a
   partition without waiting out either side's interval.)
2. Host-side `diff_merkle_trees` per owner whose serialized trees
   differ → the earliest diverged minute → a 46-char sync timestamp
   (`create_sync_timestamp`, the same range cursor the client sync
   path uses).
3. `POST /replicate/pull` with the (owner, since) list (chunked at
   `PULL_OWNERS_PER_REQUEST`); the peer answers every stored message
   after `since` per owner — NO node exclusion (a relay is not a
   message author) — plus its tree string at fetch time.
4. Ingest as ordinary `SyncRequest`s: through the PR-2 continuous-
   batching scheduler when the relay runs one (submitted concurrently
   so replication traffic COALESCES with live client traffic into the
   same fused `BatchReconciler.run_batch_wire` passes — one device
   pass covers a whole peer's diverged owner set via the engine's
   `deltas_dispatch`/`owner_minute_deltas` kernels), else through the
   per-request `serve_single_request` path. Either way the request's
   `merkle_tree` field carries the PEER's tree, so a fully-healed
   owner's response is empty — the serve leg stays divergence-bounded
   too. Idempotence is the store's own INSERT OR IGNORE + changes==1
   XOR gate: re-pulling an overlapping range can never double-XOR a
   tree.

Failure handling: offline peers get bounded exponential backoff with
jitter (the PR-2 client backoff shape — `sync/client.py` constants;
`_http_post` itself already retries 429/503/connection blips inside a
round), a per-peer health gauge, and automatic recovery on the first
successful round. The relay stays E2EE-blind throughout: rows are
(timestamp, userId, ciphertext), trees are digests of timestamps.

Observability (docs/OBSERVABILITY.md): rounds/failures/owners-diffed/
messages-pulled counters per (replica, peer), messages-served on the
answering side, a convergence-lag histogram (first divergence
observation → first fully-converged round), and a health gauge —
surfaced by `GET /metrics` and the `replication` section of
`GET /stats`.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from evolu_tpu.core.merkle import diff_merkle_trees, merkle_tree_from_string
from evolu_tpu.core.timestamp import (
    SYNC_NODE_ID,
    create_sync_timestamp,
    iso_to_millis,
    timestamp_to_string,
)
from evolu_tpu.core.types import TimestampParseError
from evolu_tpu.obs import ledger, metrics, trace
from evolu_tpu.sync import aead, protocol
from evolu_tpu.sync.client import _accepts_headers
from evolu_tpu.utils.log import log

# One pull POST covers at most this many owners — bounds request bodies
# (the relay's 20 MB cap applies to peers too) without bounding a
# round's total coverage.
PULL_OWNERS_PER_REQUEST = 256

# serve_pull bounds what one response materializes: at most this many
# messages per owner (the EARLIEST of the range — ingesting them
# advances the diff minute, so the next round's pull resumes exactly
# where this one stopped) and per response in total (owners past the
# budget are omitted entirely). A truncated pull leaves the puller's
# tree differing from the peer's, which re-arms the post-pull hint —
# deep catch-ups proceed incrementally at debounce cadence instead of
# livelocking on one response too large to build or ship inside a
# socket timeout. The engine's batch-bucket shapes stay bounded too.
PULL_MESSAGES_PER_OWNER = 8192
PULL_MESSAGES_PER_RESPONSE = 65536


def owner_tree_map(store) -> List[Tuple[str, str]]:
    """Every owner the store knows, with its STORED tree text verbatim
    (no parse→re-dump; both sides write trees via
    `merkle_tree_to_string`, so string equality IS tree equality). ONE
    bulk query where the store offers it — per-owner reads are N+1
    SELECTs per round; the fallback serves generic stores."""
    if hasattr(store, "owner_trees"):
        return store.owner_trees()
    return [(u, store.get_merkle_tree_string(u)) for u in store.user_ids()]


def serve_summary(store, body: bytes, manager: Optional["ReplicationManager"],
                  origin=None) -> bytes:
    """Handler body for `POST /replicate/summary`: decode the caller's
    summary, arm the local manager's debounced hint if the caller
    advertises anything we diverge from (heal flows both ways), and
    answer with OUR summary. ONE store scan serves both the divergence
    check and the response. `origin` is the caller's trace context
    (obs/trace.py — the relay handler parses it off the traceparent
    header): a divergence-armed hint carries it forward so OUR next
    round records into the same fleet-wide convergence trace. Raises
    ValueError only on malformed input (the wire-decoder contract —
    the handler maps it to 400)."""
    incoming = protocol.decode_replica_summary(body)
    mine = owner_tree_map(store)
    if manager is not None:
        by_owner = dict(mine)
        # "{}" is what get_merkle_tree_string answers for an unseen
        # owner — an owner we lack entirely is divergence too.
        if any(by_owner.get(uid, "{}") != tree for uid, tree in incoming.trees):
            manager.hint(origin=origin)
    fleet = getattr(manager, "fleet", None) if manager is not None else None
    if fleet is not None and incoming.peer_url:
        # Placement-scoped answer (server/fleet.py): the caller told
        # us its URL — advertise only the owners placed on IT, so a
        # converged fleet's summary traffic is O(R), not O(fleet).
        # Owners WE store that belong to the caller are included even
        # if we are not placed for them: that is exactly how a stray
        # owner (written here mid-reload) drains to its placement.
        # An empty peer_url (pre-fleet peers, the bench's oracle
        # reads) still gets everything — interop unchanged.
        mine = [(uid, t) for uid, t in mine
                if fleet.placed_on(uid, incoming.peer_url)]
    return protocol.encode_replica_summary(
        protocol.ReplicaSummary(
            tuple(mine), manager.replica_id if manager is not None else "",
            fleet.self_url if fleet is not None else "",
        )
    )


def serve_pull(store, body: bytes, per_owner: Optional[int] = None,
               per_response: Optional[int] = None) -> bytes:
    """Handler body for `POST /replicate/pull`: ranged per-owner reads
    (strictly after `since`, every node's messages, earliest-first and
    capped — see PULL_MESSAGES_PER_OWNER) + the tree string at fetch
    time. Owners past the response budget are omitted; the puller's
    convergence check treats them as still-diverged and the next round
    resumes. The caps default to the module constants but are
    configurable per relay (`ReplicationManager(pull_messages_per_
    owner=..., pull_messages_per_response=...)` — the bench sweeps
    them honestly). ValueError only on malformed input."""
    cap_owner = PULL_MESSAGES_PER_OWNER if per_owner is None else int(per_owner)
    cap_resp = (
        PULL_MESSAGES_PER_RESPONSE if per_response is None else int(per_response)
    )
    req = protocol.decode_replica_pull(body)
    chunks = []
    served = 0
    for uid, since in req.pulls:
        if served >= cap_resp:
            break
        msgs = store.replica_messages(
            uid, since,
            min(cap_owner, cap_resp - served),
        )
        served += len(msgs)
        chunks.append(
            protocol.OwnerMessages(uid, msgs, store.get_merkle_tree_string(uid))
        )
    # Unlabeled on purpose: the wire `replica_id` is untrusted input —
    # minting a metric label per distinct value would let any caller
    # grow the registry without bound. Per-peer breakdowns live on the
    # PULLING side's counters, whose labels come from configuration.
    metrics.inc("evolu_repl_messages_served_total", served)
    return protocol.encode_replica_pull_response(protocol.ReplicaPullResponse(tuple(chunks)))


class _ManagerStopping(Exception):
    """Raised between a round's HTTP legs once stop() is underway: the
    round aborts promptly (idempotence makes a half-ingested round
    safe) instead of holding the loop thread through more socket
    timeouts while the server tears down."""


class _Peer:
    """Per-peer gossip state machine: due time, consecutive-failure
    count driving the bounded backoff, and the first-divergence mark
    feeding the convergence-lag histogram."""

    __slots__ = ("url", "failures", "next_due", "diverged_since")

    def __init__(self, url: str, now: float):
        self.url = url.rstrip("/")
        self.failures = 0
        self.next_due = now  # gossip immediately on start
        self.diverged_since: Optional[float] = None


class ReplicationManager:
    """Owns the gossip loop for one relay: a background thread runs a
    round against each peer when due (periodic `interval_s`, pulled
    earlier by `hint()` after local writes, pushed later by backoff
    after failures). `run_once()` runs one synchronous round against
    every peer on the calling thread — the unit-test / bench surface.

    `http_post` is injectable (fault-injection tests partition the
    cluster by raising from it); the default is the PR-2 client
    transport `sync.client._http_post` with `retries=0`: the
    round-level peer backoff owns ALL retry pacing — inner transport
    retries would multiply a black-holed peer's socket timeout on the
    single loop thread, head-of-line-blocking gossip to every healthy
    peer."""

    def __init__(
        self,
        store,
        peers: Sequence[str],
        replica_id: Optional[str] = None,
        scheduler=None,
        interval_s: float = 30.0,
        debounce_s: float = 0.05,
        backoff_base_s: Optional[float] = None,
        backoff_max_s: float = 30.0,
        http_post: Optional[Callable[[str, bytes], bytes]] = None,
        rng=None,
        pull_chunk: int = PULL_OWNERS_PER_REQUEST,
        pull_messages_per_owner: Optional[int] = None,
        pull_messages_per_response: Optional[int] = None,
        bootstrap_lag_owners: Optional[int] = None,
        snapshot_chunk_bytes: Optional[int] = None,
        write_behind=None,
        push_hub=None,
    ):
        import functools
        import random

        from evolu_tpu.sync.client import BACKOFF_BASE_S, _http_post
        from evolu_tpu.utils.config import default_config

        # Any knob left at None falls back to the process default_config
        # (utils/config.py) — one place to tune a whole fleet — and only
        # then to the module constants at serve time.
        if pull_messages_per_owner is None:
            pull_messages_per_owner = default_config.pull_messages_per_owner
        if pull_messages_per_response is None:
            pull_messages_per_response = default_config.pull_messages_per_response
        if bootstrap_lag_owners is None:
            bootstrap_lag_owners = default_config.bootstrap_lag_owners

        self.store = store
        self.scheduler = scheduler
        self.replica_id = replica_id or f"relay-{random.getrandbits(48):012x}"
        self.interval_s = float(interval_s)
        self.debounce_s = float(debounce_s)
        self.backoff_base_s = (
            BACKOFF_BASE_S if backoff_base_s is None else float(backoff_base_s)
        )
        self.backoff_max_s = float(backoff_max_s)
        self.pull_chunk = int(pull_chunk)
        # serve_pull caps this relay answers with (None = the module
        # defaults, read at serve time so tests can monkeypatch them).
        self.pull_messages_per_owner = pull_messages_per_owner
        self.pull_messages_per_response = pull_messages_per_response
        # Snapshot bootstrap (server/snapshot.py): None disables the
        # trigger entirely (incremental anti-entropy only — the PR-3
        # behavior and the default). An int N arms it: a peer whose
        # store is EMPTY, or that lacks >= N owners a donor advertises,
        # installs a full snapshot instead of crawling history through
        # capped pulls, then gossips from the manifest watermark.
        self.bootstrap_lag_owners = bootstrap_lag_owners
        self.snapshot_chunk_bytes = snapshot_chunk_bytes
        self._snapshot_cache = None
        self._snapshot_cache_lock = threading.Lock()
        self._post = http_post or functools.partial(_http_post, retries=0)
        self._rng = rng or random.random
        # Trace contexts of recent write hints (origin traces for the
        # fleet-wide convergence trace): drained by the next round,
        # bounded — a write burst keeps the newest few origins, which
        # is exactly what a debounced hint coalesces anyway.
        self._hint_origins: List = []
        # Owner-sharded fleet membership (server/fleet.py), attached by
        # RelayServer.enable_fleet: scopes summaries/pulls to placement
        # (O(R) gossip) and hands the snapshot path to the fleet's
        # owner-granular rebalance (the whole-store bootstrap trigger
        # stays off — a partitioned relay must never install every
        # owner of a donor).
        self.fleet = None
        # PR-11: with a write-behind queue on this relay, outbound
        # gossip summaries read the store directly (owner_trees) — a
        # round starts by draining so we only ever ADVERTISE committed
        # state (a tree advertised ahead of its rows would make peers
        # pull ranges the store cannot yet serve). PR-19: flush() is
        # the COMPOSED barrier — it waits out every shard's drain
        # worker, so the guarantee holds per shard.
        self.write_behind = write_behind
        # ISSUE 13: rows this manager ingests (anti-entropy pulls,
        # partition heals) are newly visible at THIS relay — parked
        # push subscriptions for those owners must wake exactly as for
        # a local client write (server/push.py; attached by
        # RelayServer alongside the hub).
        self.push_hub = push_hub
        now = time.monotonic()
        self._peers = [_Peer(u, now) for u in peers]
        self._swap_checked = False
        self._cv = threading.Condition()
        self._hint_at: Optional[float] = None
        self._stopping = False
        self._thread: Optional[threading.Thread] = None
        self._pool = None
        metrics.set_gauge("evolu_repl_peers", len(self._peers), replica=self.replica_id)
        for p in self._peers:
            metrics.set_gauge(
                "evolu_repl_peer_healthy", 1, replica=self.replica_id, peer=p.url
            )

    # -- lifecycle --

    def start(self) -> "ReplicationManager":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="evolu-replicate"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        """Idempotent; joins the loop thread. `_post_checked` aborts an
        in-flight round at its next HTTP leg, so the join normally
        returns within one socket timeout. If a leg is still blocked
        past the timeout, the daemon thread is left to finish on its
        own — the pool is NOT torn from under it (`_ingest_pool`
        refuses new work while stopping), and a subsequent store close
        surfaces as a clean closed-database error inside `_round`'s
        failure handling, never a crash."""
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=35.0)
            if self._thread.is_alive():
                log("server", "replication loop still blocked at stop; "
                    "leaving the daemon thread", replica=self.replica_id)
                return
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def add_peer(self, url: str) -> None:
        """Register a peer after construction (mutual peering needs
        both relays' URLs, which only exist once both servers bind —
        tests, dynamic topologies, and fleet reloads use this).
        Idempotent under its own lock: racing registrations (two
        concurrent /fleet/reload pushes) must not gossip one peer
        twice per round forever. Gossips immediately."""
        with self._cv:
            if any(p.url == url.rstrip("/") for p in self._peers):
                return
            p = _Peer(url, time.monotonic())
            self._peers.append(p)
            metrics.set_gauge(
                "evolu_repl_peers", len(self._peers), replica=self.replica_id
            )
            metrics.set_gauge(
                "evolu_repl_peer_healthy", 1, replica=self.replica_id, peer=p.url
            )
            self._cv.notify()

    def hint(self, origin=None) -> None:
        """Debounced write hint: a burst of local writes (or a peer's
        summary showing divergence) coalesces into ONE early gossip
        sweep `debounce_s` after the first hint. Peers in failure
        backoff are NOT pulled forward — hints must not defeat the
        bounded backoff. `origin` (the hinting write's trace context,
        obs/trace.py) is remembered — bounded, deduped — so the round
        this hint arms records its spans into the SAME trace the
        client's mutation started: that is the fleet-wide convergence
        trace."""
        with self._cv:
            if self._stopping:
                return
            if origin is not None and origin.sampled:
                if not any(o.trace_id == origin.trace_id
                           for o in self._hint_origins):
                    self._hint_origins.append(origin)
                    del self._hint_origins[:-8]  # keep the newest 8
            if self._hint_at is None:
                self._hint_at = time.monotonic() + self.debounce_s
                metrics.inc("evolu_repl_hints_total", replica=self.replica_id)
                self._cv.notify()

    # -- the loop --

    def _loop(self) -> None:
        while True:
            with self._cv:
                due: List[_Peer] = []
                while not self._stopping:
                    now = time.monotonic()
                    if self._hint_at is not None and now >= self._hint_at:
                        self._hint_at = None
                        for p in self._peers:
                            if p.failures == 0:
                                p.next_due = now
                    due = [p for p in self._peers if p.next_due <= now]
                    if due:
                        break
                    wakes = [p.next_due for p in self._peers]
                    if self._hint_at is not None:
                        wakes.append(self._hint_at)
                    # Cap the sleep so a long interval (or an empty
                    # peer set — peers may be added later) still
                    # notices stop() promptly even without a notify.
                    wake_in = (min(wakes) - now) if wakes else 5.0
                    self._cv.wait(timeout=max(0.0, min(wake_in, 5.0)))
                if self._stopping:
                    return
            for p in due:
                with self._cv:
                    if self._stopping:
                        return
                self._round(p)

    def run_once(self) -> None:
        """One synchronous gossip round against every peer, on the
        calling thread (ignores due times; respects nothing else of the
        loop's pacing). Unit-test / bench / embedding surface."""
        for p in self._peers:
            self._round(p)

    @property
    def snapshot_cache(self):
        """Donor-side snapshot cache, built lazily (only relays whose
        peers actually bootstrap pay the capture memory). Lock-guarded:
        two peers' concurrent first /replicate/snapshot requests (the
        threaded HTTP server) must share ONE instance — a second
        instance would orphan the first peer's snapshot id mid-fetch
        and double the capture cost."""
        with self._snapshot_cache_lock:
            if self._snapshot_cache is None:
                from evolu_tpu.server.snapshot import (
                    SNAPSHOT_CHUNK_BYTES, SnapshotCache,
                )

                self._snapshot_cache = SnapshotCache(
                    self.store,
                    chunk_bytes=self.snapshot_chunk_bytes or SNAPSHOT_CHUNK_BYTES,
                )
            return self._snapshot_cache

    def _post_checked(self, url: str, body: bytes) -> bytes:
        """The round's transport, with a stop check before each leg —
        a multi-leg round against a black-holing peer must not hold
        stop() through every remaining socket timeout. Every leg counts
        one HTTP round-trip (the unit the snapshot-vs-anti-entropy
        acceptance ratio is asserted in)."""
        if self._stopping:
            raise _ManagerStopping()
        leg = url.rsplit("/replicate/", 1)[-1] if "/replicate/" in url else "other"
        metrics.inc(
            "evolu_repl_round_trips_total", replica=self.replica_id, leg=leg
        )
        # Each HTTP leg is a child span of the ambient round span and
        # carries its context as the traceparent header (headers only;
        # the peer wire bytes are untouched) — the serving peer's
        # repl.serve span joins the same convergence trace.
        lspan = trace.start_span(f"repl.{leg}", parent=trace.current())
        with lspan:
            hdrs = trace.inject_headers(ctx=lspan.context)
            # Header support is probed at CALL time (memoized per
            # callable): `_post` is swappable after construction
            # (fault injectors wrap it), and a 2-arg transport must
            # be served without the header rather than broken.
            if hdrs and _accepts_headers(self._post):
                return self._post(url, body, headers=hdrs)
            return self._post(url, body)

    def _finish_pending_swap_once(self) -> None:
        """A crash between shard swaps leaves a verified install half
        swapped in (phase=swap). `_bootstrap` would finish it, but the
        half-swapped live tables may advertise enough owners that the
        bootstrap trigger never fires again — so the FIRST round of any
        manager unconditionally finishes a pending swap. Probe via
        sqlite_master first: a store that never bootstrapped must not
        grow a state table just from being gossiped."""
        if self._swap_checked:
            return
        self._swap_checked = True
        try:
            shard0 = (getattr(self.store, "shards", None) or [self.store])[0]
            have = shard0.db.exec_sql_query(
                "SELECT name FROM sqlite_master WHERE type='table' "
                "AND name='snapshotBootstrapState'"
            )
            if not have:
                return
            from evolu_tpu.server import snapshot as snap

            inst = snap.SnapshotInstaller(self.store)
            st = inst.pending()
            if st is not None and st["phase"] == "swap":
                inst.finish_swap()
                metrics.inc(
                    "evolu_snap_installs_total", result="ok",
                    replica=self.replica_id, peer=st["peer"],
                )
                log("server", "finished stranded snapshot swap",
                    snapshot=st["snapshot_id"], peer=st["peer"])
        except Exception as e:  # noqa: BLE001 - recovery must never
            # block gossip; the pending state stays for the next try.
            self._swap_checked = False
            log("server", "pending snapshot swap check failed", error=repr(e))

    def _round(self, peer: _Peer) -> None:
        self._finish_pending_swap_once()
        labels = {"replica": self.replica_id, "peer": peer.url}
        # Drain the write-hint origins: the round span joins the FIRST
        # origin's trace (the convergence trace the client's mutation
        # started) and LINKS the rest — a span has one trace, extra
        # concurrent writes ride as fan-in links, exactly like the
        # scheduler's batch span. Origins are restored on failure so a
        # retried round still lands in the right trace.
        with self._cv:
            origins, self._hint_origins = self._hint_origins, []
        rspan = trace.start_span(
            "repl.round", parent=origins[0] if origins else None,
            links=origins[1:], attrs={"peer": peer.url},
        )
        try:
            with rspan, trace.use(rspan.context):
                if self.write_behind is not None:
                    # Advertise only committed state (see __init__). A
                    # drain failure lands in the round's failure
                    # handling — peer backoff, never a thread crash.
                    self.write_behind.flush()
                converged, pulled = self._gossip(peer)
        except _ManagerStopping:
            with self._cv:
                self._hint_origins = origins + self._hint_origins
                del self._hint_origins[:-8]
            return  # tearing down — not a peer failure
        except Exception as e:  # noqa: BLE001 - a peer failure must
            # never kill the loop: count, mark unhealthy, back off.
            with self._cv:
                self._hint_origins = origins + self._hint_origins
                del self._hint_origins[:-8]
            peer.failures += 1
            metrics.inc("evolu_repl_peer_failures_total", **labels)
            metrics.inc("evolu_repl_rounds_total", result="error", **labels)
            metrics.set_gauge("evolu_repl_peer_healthy", 0, **labels)
            # Bounded exponential backoff + jitter (the PR-2 shape):
            # delay ∈ [0.5, 1.0] × min(max, base·2^failures) — never
            # zero, so a dead peer cannot be hammered in a hot loop.
            delay = min(
                self.backoff_max_s, self.backoff_base_s * (2 ** min(peer.failures, 20))
            ) * (0.5 + 0.5 * self._rng())
            peer.next_due = time.monotonic() + delay
            log("server", "replication round failed", peer=peer.url,
                error=repr(e), failures=peer.failures, retry_s=round(delay, 3))
            return
        peer.failures = 0
        metrics.inc("evolu_repl_rounds_total", result="ok", **labels)
        metrics.set_gauge("evolu_repl_peer_healthy", 1, **labels)
        if converged and peer.diverged_since is not None:
            metrics.observe(
                "evolu_repl_convergence_lag_ms",
                (time.monotonic() - peer.diverged_since) * 1e3,
                exemplar=rspan.trace_id,
                **labels,
            )
            peer.diverged_since = None
        peer.next_due = time.monotonic() + self.interval_s
        if pulled:
            # Freshly pulled rows may need to travel further (chain
            # topologies — A↔B↔C with no A↔C edge): arm the debounced
            # hint so the next hop leaves at debounce latency, not
            # interval latency. A converged mesh pulls nothing, so the
            # hint chain terminates. The hint carries this round's
            # context so the next hop stays in the convergence trace.
            self.hint(origin=rspan.context)

    # -- one gossip round --

    def _gossip(self, peer: _Peer) -> Tuple[bool, int]:
        """Summary exchange → per-owner diff → ranged pull → ingest.
        → (converged, messages_pulled): converged is True when this
        round ends with every advertised owner byte-identical to the
        peer's snapshot (convergence for lag accounting; the peer may
        of course write more afterwards)."""
        labels = {"replica": self.replica_id, "peer": peer.url}
        local = dict(owner_tree_map(self.store))  # ONE bulk read
        send = local
        if self.fleet is not None:
            # Placement scope (server/fleet.py): advertise to this
            # peer only the owners placed on IT — including strays we
            # store but are not placed for (they drain to placement) —
            # and carry our URL so the peer scopes its answer the same
            # way. Gossip traffic drops from O(fleet) to O(R).
            send = {uid: t for uid, t in local.items()
                    if self.fleet.placed_on(uid, peer.url)}
        mine = protocol.ReplicaSummary(
            tuple(send.items()), self.replica_id,
            self.fleet.self_url if self.fleet is not None else "",
        )
        resp = protocol.decode_replica_summary(
            self._post_checked(peer.url + "/replicate/summary", protocol.encode_replica_summary(mine))
        )
        if self._should_bootstrap(local, resp.trees):
            if peer.diverged_since is None:
                peer.diverged_since = time.monotonic()
            installed = self._bootstrap(peer)
            # Not "converged" yet: the donor may have written past the
            # snapshot watermark — the nonzero return arms the hint so
            # the NEXT round diffs from the watermark at debounce
            # latency and pulls only the post-snapshot tail.
            return False, installed
        diverged: List[Tuple[str, str]] = []  # (owner, since)
        for uid, peer_tree_s in resp.trees:
            if self.fleet is not None and not self.fleet.placed_on(
                    uid, self.fleet.self_url):
                # Not ours to hold: never pull an owner we are not
                # placed for (a scoping peer won't advertise one, but
                # the wire is untrusted — enforce locally too).
                continue
            # Compare and diff the SAME bulk snapshot — no per-owner
            # re-reads (N+1 on a converged mesh), and no chance of
            # diffing a different tree than the one compared. A local
            # write landing mid-round at worst re-pulls rows the
            # ingest's INSERT OR IGNORE already holds — idempotent.
            local_s = local.get(uid, "{}")
            if local_s == peer_tree_s:
                continue
            diff = diff_merkle_trees(
                merkle_tree_from_string(local_s),
                merkle_tree_from_string(peer_tree_s),
            )
            if diff is None:
                continue  # hash-equal roots; nothing to pull
            diverged.append((uid, timestamp_to_string(create_sync_timestamp(diff))))
        if not diverged:
            return True, 0
        if peer.diverged_since is None:
            peer.diverged_since = time.monotonic()
        metrics.inc("evolu_repl_owners_diffed_total", len(diverged), **labels)
        log("server", "replication divergence", peer=peer.url, owners=len(diverged))

        peer_tree_at_pull = {}
        requests: List[protocol.SyncRequest] = []
        freshness: dict = {}  # owner -> newest pulled HLC millis
        pulled = 0
        for i in range(0, len(diverged), self.pull_chunk):
            chunk = diverged[i : i + self.pull_chunk]
            pull = protocol.ReplicaPull(tuple(chunk), self.replica_id)
            pr = protocol.decode_replica_pull_response(
                self._post_checked(peer.url + "/replicate/pull", protocol.encode_replica_pull(pull))
            )
            for om in pr.chunks:
                peer_tree_at_pull[om.user_id] = om.merkle_tree
                pulled += len(om.messages)
                if om.messages:
                    # The peer's tree rides as the request's client
                    # tree: once ingest makes our tree equal it, the
                    # serve diff is None and the (discarded) response
                    # is empty — the serve leg stays divergence-bounded.
                    requests.append(
                        protocol.SyncRequest(
                            om.messages, om.user_id, SYNC_NODE_ID, om.merkle_tree
                        )
                    )
                    try:
                        # Messages arrive timestamp-ordered; the last
                        # one's HLC millis is the owner's watermark.
                        # Rows already carry the clock — no new clocks,
                        # no wire change. Non-canonical timestamps just
                        # skip the gauge (they still ingest through
                        # the host-oracle route like always) —
                        # iso_to_millis raises TimestampParseError on
                        # them, which must never abort the round.
                        freshness[om.user_id] = max(
                            freshness.get(om.user_id, 0),
                            iso_to_millis(om.messages[-1].timestamp[:24]),
                        )
                    except (ValueError, TimestampParseError):
                        pass
        metrics.inc("evolu_repl_messages_pulled_total", pulled, **labels)
        ispan = trace.start_span(
            "repl.ingest", parent=trace.current(),
            attrs={"peer": peer.url, "owners": len(requests),
                   "messages": pulled},
        )
        with ispan:
            self._ingest(requests)
        # The convergence plane (ISSUE 10): per-(owner, peer)
        # freshness watermarks — the newest HLC millis this replica
        # has SEEN from that peer per owner — and the end-to-end
        # write→visible-at-this-replica lag, measured from the HLC
        # millis the rows already carry against this host's wall
        # clock. Gauges are data-labeled, so the registry's
        # label-cardinality bound is what keeps them finite.
        now_ms = time.time() * 1e3
        for uid, newest in freshness.items():
            metrics.set_gauge(
                "evolu_conv_owner_freshness_millis", newest,
                replica=self.replica_id, peer=peer.url, owner=uid,
            )
            metrics.observe(
                "evolu_conv_write_visible_ms", max(0.0, now_ms - newest),
                exemplar=ispan.trace_id,
                replica=self.replica_id, peer=peer.url,
            )
        converged = all(
            self.store.get_merkle_tree_string(uid)
            == peer_tree_at_pull.get(uid, object())
            for uid, _since in diverged
        )
        return converged, pulled

    # -- snapshot bootstrap (server/snapshot.py) --

    def _should_bootstrap(self, local: dict, advertised) -> bool:
        """Arm the O(state) cold-start instead of O(history) capped
        pulls: the local store is empty, or it lacks BOTH at least
        `bootstrap_lag_owners` owners the peer advertises AND the
        majority of the advertised set (a relay restored from an old
        disk). The majority clause keeps routine fleet growth on the
        incremental path: one new owner appearing on a converged
        100-owner mesh is a ranged pull, never a full-store
        re-snapshot, whatever the threshold. None disables (PR-3
        behavior). A FLEET member never whole-store bootstraps: its
        moves are owner-granular through the fleet rebalance
        (server/fleet.py) — installing a donor's full snapshot would
        un-partition the tier."""
        if self.fleet is not None:
            return False
        if self.bootstrap_lag_owners is None or not advertised:
            return False
        if not local:
            return True
        unknown = sum(1 for uid, _t in advertised if uid not in local)
        # max(1, ·): a converged mesh has unknown == 0 and must never
        # re-bootstrap, whatever the configured threshold.
        return (unknown >= max(1, self.bootstrap_lag_owners)
                and unknown * 2 > len(advertised))

    def bootstrap_from(self, peer_url: str) -> int:
        """Run one snapshot bootstrap against `peer_url` on the calling
        thread (the unit-test / bench / operator surface — `run_once`'s
        analog). Returns the number of message rows installed."""
        return self._bootstrap(_Peer(peer_url, time.monotonic()))

    def _bootstrap(self, peer: _Peer) -> int:
        """Manifest → resumable chunk fetches → crash-consistent
        install → golden-parity verify → atomic swap. The chunk
        watermark lives in the STORE (snapshotBootstrapState), so a
        SIGKILL anywhere in the fetch loop resumes from the last
        committed chunk without re-transferring completed ones; a
        donor-side snapshot expiry (HTTP 400 on the chunk leg) drops
        the stale install and the next round restarts fresh.

        With a write-behind queue the whole bootstrap runs behind its
        `drain_barrier` (review finding): the swap replaces shard
        contents, and a record ACKed against the PRE-swap tree base
        would later drain its stale tree string over the installed
        one — permanent tree/message divergence. The barrier makes the
        window airtight, not just drained-at-entry: it clears the
        serve-time tree cache, so any concurrent serve's base-tree
        read blocks on `db_lock` until the swap is complete and then
        reads post-swap truth. (Coarse — whole-store bootstrap is a
        cold-start/operator event, same tradeoff as the fleet owner
        move.)"""
        if self.write_behind is not None:
            with self.write_behind.drain_barrier():
                return self._bootstrap_locked(peer)
        return self._bootstrap_locked(peer)

    def _bootstrap_locked(self, peer: _Peer) -> int:
        import urllib.error

        from evolu_tpu.server import snapshot as snap

        labels = {"replica": self.replica_id, "peer": peer.url}
        inst = snap.SnapshotInstaller(self.store)
        t0 = time.perf_counter()
        manifest, start = None, 0
        st = inst.pending()
        if st is not None and st["phase"] == "swap":
            # Died between shard swaps: finish (idempotent), done — the
            # data was fully verified before the swap began, and the
            # swap is peer-independent (WHICHEVER peer this round
            # targets, aborting would strand already-swapped shards on
            # the snapshot and throw away verified data).
            inst.finish_swap()
            metrics.observe(
                "evolu_snap_install_ms", (time.perf_counter() - t0) * 1e3
            )
            metrics.inc("evolu_snap_installs_total", result="ok", **labels)
            return 0
        if st is not None and st["peer"] != peer.url:
            with self._cv:
                known = any(p.url == st["peer"] for p in self._peers)
            if known:
                # The watermark belongs to ANOTHER configured peer
                # (multi-peer mesh, first round after a crash happened
                # to target a different donor): resume against the
                # original donor instead of discarding completed
                # chunks — only IT still serves this snapshot id.
                peer = _Peer(st["peer"], time.monotonic())
                labels = {"replica": self.replica_id, "peer": peer.url}
            else:
                inst.abort()  # an unconfigured peer's stale install
                st = None
        if st is not None:
            manifest, start = st["manifest"], st["next_chunk"]
            if start:
                metrics.inc("evolu_snap_resumes_total", **labels)
                log("server", "snapshot bootstrap resuming", peer=peer.url,
                    snapshot=manifest.snapshot_id, next_chunk=start,
                    chunks=len(manifest.chunk_sizes))
        if manifest is None:
            body = protocol.encode_snapshot_request(
                protocol.SnapshotRequest(
                    self.replica_id, self.snapshot_chunk_bytes or 0
                )
            )
            manifest = protocol.decode_snapshot_manifest(
                self._post_checked(peer.url + "/replicate/snapshot", body)
            )
            inst.begin(manifest, peer.url)
            log("server", "snapshot bootstrap starting", peer=peer.url,
                snapshot=manifest.snapshot_id, owners=len(manifest.owners),
                rows=manifest.message_count, bytes=manifest.total_bytes,
                chunks=len(manifest.chunk_sizes))
        try:
            for i in range(start, len(manifest.chunk_sizes)):
                req = protocol.encode_snapshot_chunk_request(
                    protocol.SnapshotChunkRequest(
                        manifest.snapshot_id, i, self.replica_id
                    )
                )
                try:
                    raw = self._post_checked(
                        peer.url + "/replicate/snapshot/chunk", req
                    )
                except urllib.error.HTTPError as e:
                    if e.code == 400:
                        # The donor no longer serves this snapshot id:
                        # the persisted watermark is worthless — drop it
                        # so the next round begins a fresh bootstrap.
                        inst.abort()
                        metrics.inc(
                            "evolu_snap_installs_total", result="expired", **labels
                        )
                    raise
                chunk = protocol.decode_snapshot_chunk(raw)
                if (chunk.snapshot_id != manifest.snapshot_id
                        or chunk.index != i
                        or len(chunk.payload) != manifest.chunk_sizes[i]
                        or chunk.crc != manifest.chunk_crcs[i]):
                    raise snap.SnapshotInstallError(
                        f"snapshot chunk {i}: response does not match the "
                        "manifest (id/index/size/crc)"
                    )
                inst.install_chunk(i, chunk.payload,
                                   expected_crc=manifest.chunk_crcs[i])
                metrics.inc("evolu_snap_chunks_fetched_total", **labels)
                metrics.inc(
                    "evolu_snap_bytes_fetched_total", len(chunk.payload), **labels
                )
            inst.verify(manifest)
        except (_ManagerStopping, urllib.error.URLError, OSError):
            # Transport interruptions keep the watermark: resume next
            # round without re-transferring completed chunks.
            raise
        except snap.SnapshotInstallError:
            # Integrity failure: the shipped bytes are not trustworthy —
            # drop everything and refetch fresh. Live tables untouched.
            inst.abort()
            metrics.inc("evolu_snap_installs_total", result="error", **labels)
            raise
        inst.swap()
        metrics.observe(
            "evolu_snap_install_ms", (time.perf_counter() - t0) * 1e3
        )
        metrics.inc("evolu_snap_installs_total", result="ok", **labels)
        log("server", "snapshot bootstrap installed", peer=peer.url,
            snapshot=manifest.snapshot_id, rows=manifest.message_count,
            owners=len(manifest.owners))
        if self.push_hub is not None:
            # A whole-store install changed arbitrarily many owners at
            # once: per-row attribution is gone, so wake everything —
            # the changed-set contract's "don't know escalates" rule.
            self.push_hub.notify_all(reason="conservative")
        return manifest.message_count

    def _ingest(self, requests: List[protocol.SyncRequest]) -> None:
        """Apply pulled messages through the relay's OWN serving paths
        (never a raw insert — the changes==1 Merkle gate and the
        non-canonical host-oracle routing must apply to replication
        exactly as to clients). With a scheduler the requests are
        submitted CONCURRENTLY so the dispatcher coalesces them — with
        each other and with live client traffic — into fused
        `run_batch_wire` engine passes; without one they take the
        per-request path handler threads use."""
        if not requests:
            return
        n_v2 = sum(aead.count_v2(r.messages) for r in requests)
        if n_v2:
            # Peer pulls carry stored ciphertext verbatim — an
            # aead-batch-v1 record replicates as opaquely as an OpenPGP
            # one (never re-encrypted, never downgraded per hop). This
            # counter is how an operator confirms v2 traffic actually
            # crossing the replication surface (docs/OBSERVABILITY.md).
            metrics.inc("evolu_crypto_v2_replicated_messages_total", n_v2)
        if self.scheduler is not None:
            futures = [
                self._ingest_pool().submit(self.scheduler.submit, r) for r in requests
            ]
            first_err: Optional[BaseException] = None
            served = []
            for r, f in zip(requests, futures):
                e = f.exception()
                if e is None:
                    served.append(r)
                first_err = first_err or e
            # Notify BEFORE re-raising: the requests that DID commit
            # made rows visible, and their subscribers must wake even
            # when a batchmate failed (review finding — the raise used
            # to skip the notify for all of them).
            self._notify_push(served)
            self._ledger_ingress(served)
            if first_err is not None:
                raise first_err
            return
        from evolu_tpu.server.relay import serve_single_request

        served = []
        try:
            for r in requests:
                serve_single_request(self.store, r)
                served.append(r)
        finally:
            self._notify_push(served)
            self._ledger_ingress(served)

    @staticmethod
    def _ledger_ingress(served: List[protocol.SyncRequest]) -> None:
        """Ledger ingress for pulled messages that the serve path
        actually landed: the serve posted their store terminals (the
        relay's own paths — changes==1 gate and all), so only
        SUCCESSFULLY served requests ingress. A failed submit posted
        neither side, and the next round's re-pull is a fresh delivery
        attempt."""
        for r in served:
            ledger.count(ledger.INGRESS_REPLICATION, len(r.messages),
                         owner=r.user_id)

    def _notify_push(self, requests: List[protocol.SyncRequest]) -> None:
        """Wake parked push subscriptions for rows replication just
        landed (AFTER the serve committed them). The pulled messages'
        plaintext timestamps carry the ORIGINAL author nodes, so the
        hub's own-write exclusion still holds across relays — a
        subscriber never wakes for rows it authored, whichever relay
        they arrive through."""
        if self.push_hub is None:
            return
        for r in requests:
            if r.messages:
                self.push_hub.notify(
                    r.user_id, [m.timestamp for m in r.messages],
                    reason="replication",
                )

    def _ingest_pool(self):
        if self._stopping:
            # Never mint a fresh executor during teardown: stop() has
            # (or will have) shut the pool down, and a new one here
            # would leak.
            raise _ManagerStopping()
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="evolu-repl-ingest"
            )
        return self._pool

    # -- observability --

    def stats_payload(self) -> dict:
        """The `replication` section of GET /stats: per-peer health +
        the per-(replica, peer) counters from the process registry."""
        peers = []
        for p in self._peers:
            labels = {"replica": self.replica_id, "peer": p.url}
            peers.append({
                "url": p.url,
                "healthy": p.failures == 0,
                "failures": p.failures,
                "rounds_ok": metrics.get_counter(
                    "evolu_repl_rounds_total", result="ok", **labels
                ),
                "rounds_error": metrics.get_counter(
                    "evolu_repl_rounds_total", result="error", **labels
                ),
                "owners_diffed": metrics.get_counter(
                    "evolu_repl_owners_diffed_total", **labels
                ),
                "messages_pulled": metrics.get_counter(
                    "evolu_repl_messages_pulled_total", **labels
                ),
                "convergence_lag_p99_ms": metrics.quantile(
                    "evolu_repl_convergence_lag_ms", 0.99, **labels
                ),
                "snapshot_bootstraps": metrics.get_counter(
                    "evolu_snap_installs_total", result="ok", **labels
                ),
                "snapshot_chunks_fetched": metrics.get_counter(
                    "evolu_snap_chunks_fetched_total", **labels
                ),
                "snapshot_bytes_fetched": metrics.get_counter(
                    "evolu_snap_bytes_fetched_total", **labels
                ),
            })
        return {
            "replica_id": self.replica_id,
            "peers": peers,
            # Donor-side snapshot service (unlabeled — served to
            # whoever asked, like messages_served).
            "snapshot": {
                "captures": metrics.get_counter("evolu_snap_captures_total"),
                "capture_rows": metrics.get_counter(
                    "evolu_snap_capture_rows_total"
                ),
                "capture_bytes": metrics.get_counter(
                    "evolu_snap_capture_bytes_total"
                ),
                "manifests_served": metrics.get_counter(
                    "evolu_snap_manifests_served_total"
                ),
                "chunks_served": metrics.get_counter(
                    "evolu_snap_chunks_served_total"
                ),
                "chunk_bytes_served": metrics.get_counter(
                    "evolu_snap_chunk_bytes_served_total"
                ),
                "checkpoints": metrics.get_counter(
                    "evolu_snap_checkpoints_total"
                ),
                "install_p99_ms": metrics.quantile("evolu_snap_install_ms", 0.99),
            },
        }
