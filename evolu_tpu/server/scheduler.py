"""Continuous-batching sync scheduler: fuse live relay traffic into
single engine passes.

The reference relay services each sync request individually
(apps/server/src/index.ts:148-159), and so did our HTTP relay — one
`RelayStore.sync_wire` store pass per handler thread. The offline
`BatchReconciler` already reconciles a whole batch of SyncRequests in
one fused pass (bulk SQL set-diff + one sharded device Merkle
dispatch), but nothing fed it live traffic. This module is the
admission/dispatch layer between the two: handler threads enqueue
decoded `SyncRequest`s onto a bounded queue and block on per-request
futures; a dispatcher thread closes a micro-batch on whichever comes
first of max-batch-size / max-wait-deadline and runs ONE engine pass
(`BatchReconciler.start_batch`/`finish_batch` on packed stores) whose
wire responses resolve the futures.

Why coalescing is sound (Merkle-CRDTs, arXiv 2004.00107): anti-entropy
is pure set reconciliation — a response depends only on store state
plus that one request, and owners are independent, so a batch of
DISTINCT-owner requests served in one pass is byte-identical to any
sequential order of the same requests. Same-owner requests are NOT
independent (the second's response must see the first's inserts the
way a sequential server would), so a batch never contains two
requests for one owner — the later one stays queued, FIFO order
preserved, and rides the next pass.

Robustness contract:
- queue full → `SchedulerQueueFull` (the relay maps it to 503 +
  `Retry-After`): backpressure instead of unbounded handler threads.
- non-canonical timestamp widths never enter a batch: the engine's
  packed path rejects them batch-wide (`_pack_rows`), so they dispatch
  as singletons through the per-request `sync_wire`/`sync` path, which
  routes them to the host oracle BEFORE any side effect — the r5
  packed-receive contract, kept. Singletons still run ON the
  dispatcher thread: all store writes serialize there, so a fallback
  can never join an engine transaction left open on the shared
  connection.
- a poisoned batch (any engine-pass failure: every shard transaction
  rolled back, nothing committed) is retried ONCE as singletons, so
  one bad request can't fail its batchmates.
- `stop()` drains every queued request through full-size batches
  before the dispatcher exits; post-stop submits are rejected with
  `SchedulerQueueFull` (clients back off and retry elsewhere/later).

Shape stability: the engine pads every device batch to power-of-two
row buckets (`ops.bucket_size`), so varying micro-batch sizes inside a
bucket NEVER recompile the fused jit pipeline — pinned by
`tests/test_scheduler.py` via `engine.merkle_jit_cache_size()`.

Instrumented through `evolu_tpu.obs` (host-side only, no jax at import
time here — the engine, which does import jax, loads lazily on the
first batch): queue depth gauge, batch-size and batch-latency
histograms, coalesce/fallback/poison/reject counters
(docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from evolu_tpu.obs import ledger, metrics, trace
from evolu_tpu.sync import aead, protocol
from evolu_tpu.utils.log import log


class SchedulerQueueFull(Exception):
    """Admission queue at capacity (or scheduler stopping): the caller
    should answer 503 with `retry_after` seconds."""

    def __init__(self, retry_after: float):
        super().__init__(f"sync scheduler queue full; retry after {retry_after}s")
        self.retry_after = retry_after


def _write_behind_full_type():
    """Lazy import for the except clause (evaluated at raise time):
    the scheduler stays importable without touching storage modules."""
    from evolu_tpu.storage.write_behind import WriteBehindFull

    return WriteBehindFull


class _Pending:
    """One enqueued request + its future. `single=True` marks a
    request the engine can't batch: it dispatches alone, still ON the
    dispatcher thread — every store write flows through one thread, so
    a fallback can never join an open engine transaction on the shared
    connection (NativeDatabase.transaction() JOINS when one is already
    open; a handler-thread write acked mid-batch would be rolled back
    with a poisoned batch)."""

    __slots__ = ("request", "single", "t_enqueue", "t_wall", "ctx",
                 "done", "response", "error")

    def __init__(self, request: protocol.SyncRequest, single: bool = False):
        self.request = request
        self.single = single
        self.t_enqueue = time.monotonic()
        self.t_wall = time.time()
        # The submitting handler thread's ambient trace context — the
        # dispatcher records this request's queue-wait span under it
        # and links it from the batch span (fan-in, obs/trace.py).
        self.ctx = trace.current()
        self.done = threading.Event()
        self.response: Optional[bytes] = None
        self.error: Optional[BaseException] = None

    def resolve(self, response: bytes) -> None:
        self.response = response
        self.done.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self.done.set()


def _batchable(request: protocol.SyncRequest) -> bool:
    """Only canonical 46-char timestamps may enter a packed engine
    batch (`engine._pack_rows` rejects batch-wide otherwise); anything
    else takes the per-request path, whose host oracle is the error
    surface. Hex-CASE anomalies at canonical width stay batchable —
    the engine quarantines those owners to the host fold internally.
    Message CONTENT never factors in: the relay is E2EE-blind, so an
    aead-batch-v1 GCM record (sync/aead.py) batches exactly like an
    OpenPGP one — the engine stores and re-serves either verbatim."""
    return all(len(m.timestamp) == 46 for m in request.messages)


class SyncScheduler:
    """Admission + dispatch between relay handler threads and one
    `BatchReconciler`.

    `submit(request)` blocks the calling handler thread until its wire
    response (encoded SyncResponse bytes, byte-identical to the
    per-request `sync_wire` path — test-pinned) is ready, and raises
    `SchedulerQueueFull` when the bounded queue is at capacity.
    """

    def __init__(
        self,
        store,
        engine=None,
        mesh=None,
        max_batch: int = 32,
        max_wait_s: float = 0.005,
        max_queue: int = 256,
        retry_after_s: float = 1.0,
        submit_timeout_s: float = 120.0,
        write_behind=None,
        mesh_ctx=None,
        mesh_engine: bool = False,
    ):
        self.store = store
        # PR-12 sharded-engine wiring: an explicit
        # parallel.mesh.MeshContext (embedders/tests), or
        # mesh_engine=True to resolve the process-wide context lazily
        # on the dispatcher thread (get_mesh_context imports jax — it
        # must never run at relay import time). Several relays handing
        # traffic to one scheduler — or several schedulers sharing one
        # context — share ONE device pool: the mesh object keys every
        # compiled shard_map kernel, and placement is stable
        # process-wide.
        self._mesh_ctx = mesh_ctx
        self._mesh_engine = mesh_engine or (mesh_ctx is not None)
        # PR-11: a storage.write_behind.WriteBehindQueue makes the
        # engine serve from device-derived in-memory state and defer
        # SQLite to the queue's drain workers (one per storage shard
        # since PR-19). The scheduler's jobs:
        # construct the engine with it, convert its backpressure into
        # the 503 + Retry-After answer (queue-full stalls admission,
        # never drops), and run every DIRECT store write (singleton
        # fallbacks — non-batchable shapes, poison retries) behind the
        # queue's drain barrier so sync_wire reads and writes only
        # committed state.
        self._write_behind = write_behind
        self.max_batch = max(1, int(max_batch))
        self.max_wait_s = float(max_wait_s)
        self.max_queue = int(max_queue)
        self.retry_after_s = float(retry_after_s)
        self.submit_timeout_s = float(submit_timeout_s)
        self._mesh = mesh
        self._engine = engine
        self._own_engine = engine is None
        self._engine_broken: Optional[BaseException] = None
        self._cv = threading.Condition()
        self._queue: List[_Pending] = []
        self._stopping = False
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._dispatch_loop, daemon=True, name="evolu-sched"
        )
        self._thread.start()

    # -- admission (handler threads) --

    def depth(self) -> int:
        """Current admission-queue occupancy (0..max_queue) — the
        load signal the fleet `/health` detail exposes so operators
        (and future load-aware placement) can see saturation per
        relay without scraping the full registry."""
        with self._cv:
            return len(self._queue)

    def submit(self, request: protocol.SyncRequest) -> bytes:
        """Serve one request: coalesced through the next engine pass,
        or as a singleton dispatch for shapes the engine can't batch —
        either way serialized on the dispatcher thread (see _Pending)."""
        p = _Pending(request, single=not _batchable(request))
        with self._cv:
            if self._stopping or len(self._queue) >= self.max_queue:
                metrics.inc("evolu_sched_rejected_total")
                raise SchedulerQueueFull(self.retry_after_s)
            self._queue.append(p)
            metrics.set_gauge("evolu_sched_queue_depth", len(self._queue))
            self._cv.notify()
        if not p.done.wait(self.submit_timeout_s):
            raise TimeoutError(
                f"sync scheduler did not serve the request within "
                f"{self.submit_timeout_s}s"
            )
        if p.error is not None:
            raise p.error
        return p.response  # type: ignore[return-value]

    # -- dispatch (one background thread) --

    def _dispatch_loop(self) -> None:
        try:
            while True:
                with self._cv:
                    while not self._queue and not self._stopping:
                        self._cv.wait()
                    if not self._queue:
                        return  # stopping + drained
                    # Deadline from the OLDEST pending's enqueue time:
                    # requests that piled up during the previous engine
                    # pass close a batch immediately — the pass itself
                    # is the coalescing window under load; max_wait_s
                    # only delays a lone request on an idle queue.
                    # stop() waives the wait so the drain runs at full
                    # batch size without deadline stalls.
                    deadline = self._queue[0].t_enqueue + self.max_wait_s
                    while len(self._queue) < self.max_batch and not self._stopping:
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cv.wait(remaining)
                    batch = self._close_batch()
                    metrics.set_gauge("evolu_sched_queue_depth", len(self._queue))
                try:
                    self._run_batch(batch)
                except BaseException:
                    for p in batch:  # already popped — fail, don't hang
                        if not p.done.is_set():
                            p.fail(RuntimeError("sync scheduler dispatcher exited"))
                    raise
        finally:
            # If the loop died abnormally (BaseException out of
            # _run_batch — e.g. KeyboardInterrupt mid-pass), blocked
            # submitters must not hang until their timeout.
            with self._cv:
                dead, self._queue = self._queue, []
                self._stopping = True
            for p in dead:
                p.fail(RuntimeError("sync scheduler dispatcher exited"))
            self._stopped.set()

    def _close_batch(self) -> List[_Pending]:
        """Pop the next dispatch, FIFO, called under the lock. A
        `single` at the queue head dispatches alone; otherwise up to
        max_batch DISTINCT-owner batchable requests. A second request
        for an owner already in the batch stays queued (its response
        must observe the first request's inserts exactly as a
        sequential server's would), and once anything of an owner is
        kept back (same-owner duplicate, single, or capacity), every
        later request of that owner is kept too — per-owner FIFO is
        never reordered."""
        if self._queue[0].single:
            return [self._queue.pop(0)]
        batch: List[_Pending] = []
        owners: set = set()
        keep: List[_Pending] = []
        blocked: set = set()
        for p in self._queue:
            uid = p.request.user_id
            if (p.single or uid in owners or uid in blocked
                    or len(batch) >= self.max_batch):
                blocked.add(uid)
                keep.append(p)
            else:
                owners.add(uid)
                batch.append(p)
        # Anything kept is seen by the next loop iteration's queue
        # check — no new arrival needed to wake the dispatcher.
        self._queue = keep
        return batch

    def _record_queue_waits(self, batch: List[_Pending]) -> float:
        """Per-request queue-wait spans (enqueue → batch close), under
        each request's own trace — one leg of the queue-wait /
        engine-time / respond split the trace surfaces. Returns the
        dispatch instant (monotonic) the waits were measured against."""
        t_dispatch = time.monotonic()
        for p in batch:
            if p.ctx is not None:
                trace.record_span(
                    "sched.queue", p.ctx, p.t_wall,
                    (t_dispatch - p.t_enqueue) * 1e3,
                )
        return t_dispatch

    def _run_batch(self, batch: List[_Pending]) -> None:
        if not batch:
            return
        if batch[0].single:
            p = batch[0]
            metrics.inc("evolu_sched_fallback_total", reason="non_canonical")
            # Ledger TALLY (outside the flow equations — the request's
            # flow still terminates through the store path below): the
            # server-side canonicality bounce.
            ledger.count(ledger.BOUNCE_NON_CANONICAL, len(p.request.messages),
                         owner=p.request.user_id)
            self._record_queue_waits(batch)
            sspan = trace.start_span("sched.single", parent=p.ctx,
                                     attrs={"owner": p.request.user_id})
            try:
                with sspan, trace.use(sspan.context):
                    p.resolve(self._serve_single(p.request))
            except Exception as e:  # noqa: BLE001 - per-request error
                p.fail(e)
            return
        t0 = time.perf_counter()
        metrics.inc("evolu_sched_batches_total")
        metrics.observe(
            "evolu_sched_batch_requests", len(batch), buckets=metrics.COUNT_BUCKETS
        )
        self._record_queue_waits(batch)
        # The fan-in span: ONE engine pass serves N requests from N
        # different traces, so the batch span LINKS the request spans
        # (it cannot parent them — a span has one trace). It roots its
        # own trace, is force-sampled whenever any linked request is
        # sampled, and GET /trace/<request-id> surfaces it through the
        # link index. Kernel spans opened inside the engine pass
        # (utils/log.py span()) nest under it via the ambient context.
        # (start_span already records whenever any sampled link is
        # present — no force_sample needed here.)
        links = [p.ctx for p in batch if p.ctx is not None]
        bspan = trace.start_span(
            "engine.batch", links=links,
            attrs={
                "requests": len(batch),
                "owners": len({p.request.user_id for p in batch}),
            },
        )
        try:
            engine = self._ensure_engine()
            with trace.use(bspan.context):
                outs = engine.run_batch_wire([p.request for p in batch])
            bspan.end()
        except _write_behind_full_type() as e:
            # Write-behind admission backpressure: nothing was served
            # or persisted (the engine raises BEFORE the log ACK).
            # This is flow control, not poison — answer every batch
            # member 503 + Retry-After instead of slamming the
            # singleton path with the very writes the queue stalled.
            bspan.set_attr("backpressure", True)
            bspan.end()
            # Counting: the queue already counted the stall
            # (evolu_wb_stalls_total) and the relay counts the 503
            # answer (evolu_relay_backpressure_total) — no fallback
            # counter here: these requests were NOT served on the
            # per-request path, they were shed as flow control.
            for p in batch:
                p.fail(SchedulerQueueFull(e.retry_after))
            return
        except Exception as e:  # noqa: BLE001 - poison isolation
            # (BaseException — KeyboardInterrupt/SystemExit — is NOT
            # poison: it propagates, and the loop's finally fails any
            # still-queued futures.) Every shard transaction rolled
            # back (engine contract): nothing committed, so the
            # singleton retry is exact — and it isolates the poison to
            # the one request that carries it; batchmates succeed.
            bspan.set_attr("poisoned", True)
            bspan.set_attr("error", repr(e))
            bspan.end()
            metrics.inc("evolu_sched_poisoned_batches_total")
            log("server", "scheduler batch poisoned; retrying as singletons",
                error=repr(e), requests=len(batch))
            for p in batch:
                try:
                    response = self._serve_single(p.request)
                except Exception as pe:  # noqa: BLE001
                    # No ledger terminal here: the relay's 500 answer
                    # counts reject.invalid — the poisoned engine pass
                    # posted nothing (rolled back), and the singleton
                    # store path posts only on commit, so the retry can
                    # never double-count.
                    p.fail(pe)
                else:
                    metrics.inc("evolu_sched_fallback_total", reason="poison_retry")
                    p.resolve(response)
            self._observe_jit_caches(batch)
            metrics.observe("evolu_sched_batch_ms", (time.perf_counter() - t0) * 1e3,
                            exemplar=bspan.trace_id)
            return
        metrics.inc("evolu_sched_coalesced_requests_total", len(batch))
        n_v2 = sum(aead.count_v2(p.request.messages) for p in batch)
        if n_v2:
            # The fused engine pass just carried v2 ciphertext end to
            # end (store + Merkle + response re-serve, all opaque) —
            # the counter operators correlate with the relay-ingest
            # mix to confirm negotiated traffic rides the BATCHED path,
            # not the singleton fallback (docs/OBSERVABILITY.md).
            metrics.inc("evolu_crypto_v2_batched_messages_total", n_v2)
        for p, out in zip(batch, outs):
            p.resolve(out)
        self._observe_jit_caches(batch)
        metrics.observe("evolu_sched_batch_ms", (time.perf_counter() - t0) * 1e3,
                        exemplar=bspan.trace_id)

    def _observe_jit_caches(self, batch) -> None:
        """Recompile sentinel, after each engine pass: diff the merkle/
        mesh jit cache sizes into gauges + a recompiles counter, flight
        event on growth (engine.observe_jit_caches). Skipped until an
        engine exists — importing the engine module here would pull jax
        onto relays that never ran a batch. Never raises."""
        if self._engine is None:
            return
        try:
            from evolu_tpu.server import engine as eng_mod

            eng_mod.observe_jit_caches(
                sum(len(p.request.messages) for p in batch)
            )
        except Exception:  # noqa: BLE001,S110 - sentinel must not fail a batch
            pass

    def _ensure_engine(self):
        """The BatchReconciler, created lazily on the dispatcher thread
        (its import pulls jax — nothing here touches a backend until
        the first batch). A broken engine (e.g. no usable jax backend)
        is remembered so every batch degrades to singletons without
        re-paying the failed construction."""
        if self._engine_broken is not None:
            raise self._engine_broken
        if self._engine is None:
            try:
                from evolu_tpu.server.engine import BatchReconciler

                if self._mesh_engine and self._mesh_ctx is None:
                    from evolu_tpu.parallel.mesh import get_mesh_context
                    from evolu_tpu.utils.config import default_config

                    self._mesh_ctx = get_mesh_context(
                        default_config.mesh_devices
                    )
                self._engine = BatchReconciler(
                    self.store, self._mesh, write_behind=self._write_behind,
                    mesh_ctx=self._mesh_ctx,
                )
            except Exception as e:  # noqa: BLE001
                self._engine_broken = e
                raise
        return self._engine

    def _serve_single(self, request: protocol.SyncRequest) -> bytes:
        """The per-request path — exactly what the relay ran before the
        scheduler existed (ONE recipe, shared with the non-batching
        do_POST branch): fused C wire serve, object-path fallback
        (which is where non-canonical shapes reach the host oracle
        before any side effect). Only ever called on the dispatcher
        thread, so it can never interleave with an open engine
        transaction on the shared store connection."""
        from evolu_tpu.server.relay import serve_single_request

        if self._write_behind is not None:
            # Direct store writes (the host-oracle / non-batchable
            # path) must observe and produce committed state: drain
            # everything, hold the drain lock for the duration, and
            # let the queue's serving caches fall back to SQLite truth.
            with self._write_behind.drain_barrier():
                return serve_single_request(self.store, request)
        return serve_single_request(self.store, request)

    def stop(self) -> None:
        """Drain then shut down (idempotent — the relay and an
        embedding caller may both stop a shared scheduler): everything
        already queued is served (full-size batches, no deadline
        waits); new submits are rejected with `SchedulerQueueFull`."""
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        self._stopped.wait(timeout=max(30.0, self.submit_timeout_s))
        self._thread.join(timeout=5.0)
        if self._own_engine:
            with self._cv:
                engine, self._engine = self._engine, None
            if engine is not None:
                engine.close()


def format_retry_after(seconds: float) -> str:
    """RFC 7231 Retry-After is integer delay-seconds; emit the integer
    form when integral and the bare float otherwise (our client parses
    either — sub-second values matter for tests and local deploys)."""
    f = float(seconds)
    return str(int(f)) if f.is_integer() else repr(f)
