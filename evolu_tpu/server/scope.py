"""Partial replication, relay side: scoped Merkle subtrees + lane
tracking (ISSUE 18).

A scoped SyncRequest (sync/protocol.py `ScopeClause`, negotiated via
`sync-scope-v1`) is answered from a **scoped Merkle subtree**: a
masked minute-fold over exactly the row set the filter matches. The
FULL per-owner tree stays the single source of truth — ingest is
completely unchanged, scoped trees are derived on demand and cached
against the full tree's serialized text (any ingest changes that text,
so a scoped cache entry can never serve stale state; no invalidation
hooks anywhere).

Membership rule for the scoped row set (the convergence contract —
sync/scope.py module doc):

    row in slice  iff  (timestamp >= watermark AND lane served)
                       OR author(row) == requesting node

where "lane served" means the row's lane tag is requested, is the
conservative overflow lane, or is UNKNOWN (rows pushed by v1/full
clients carry no tag) — over-approximation only: the relay may serve
more than the slice, never less. Own-node rows are in the TREE
regardless of filter (they XOR-cancel against the client's local
copies; responses exclude them anyway), which keeps a scoped client
whose own writes fall outside its scope from livelocking on a
permanent tree diff.

The fold runs on device for large canonical batches (the existing
`ops.merkle_ops.merkle_minute_deltas` masked segmented fold — the
watermark/lane mask IS the kernel's xor_mask) and through the shared
host oracle `core.merkle.minute_deltas_host` otherwise — non-canonical
shapes route to the host fold before anything else, per the r5
contract (the fold is side-effect free either way).

Lane state: a relay-local side table `scopeLane(userId, timestamp,
tag)` written only when a scoped push assigns tags. Per-owner distinct
lanes are capped (satellite: lane-cardinality hardening): past
`MAX_OWNER_LANES`, new tags collapse into the `~overflow` lane —
conservatively served to every scope — and `evolu_scope_overflow_total`
counts the fold. A hostile client can therefore never mint unbounded
per-scope state here.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

import numpy as np

from evolu_tpu.core.merkle import (
    apply_prefix_xors,
    diff_merkle_trees,
    merkle_tree_from_string,
    merkle_tree_to_string,
    minute_deltas_host,
)
from evolu_tpu.core.timestamp import create_sync_timestamp, timestamp_to_string
from evolu_tpu.obs import ledger, metrics
from evolu_tpu.sync import protocol

# The conservative overflow lane. Not a valid client tag shape (tags
# from sync/scope.py are hex); a hostile client sending this literal
# string only lands its rows in the always-served lane — sound.
OVERFLOW_TAG = "~overflow"
# Per-owner distinct-lane cap (the PR-10 label-bound pattern).
MAX_OWNER_LANES = 64
# Below this row count the host fold wins (device dispatch overhead);
# module-level so tests can drive the device route with small batches.
SCOPE_DEVICE_FOLD_MIN = 1024
# Derived-tree cache entries (global). Each entry pins the owner's
# full-tree text for exact-match validation.
TREE_CACHE_CAP = 256

_LANE_TABLE_SQL = (
    'CREATE TABLE IF NOT EXISTS "scopeLane" ('
    '"userId" TEXT, "timestamp" TEXT, "tag" TEXT, '
    'PRIMARY KEY ("userId", "timestamp")) WITHOUT ROWID'
)


def _ensure_lane_table(db) -> None:
    db.exec(_LANE_TABLE_SQL)


def record_push_lanes(db, user_id: str, timestamps: Sequence[str],
                      push_tags: Sequence[str],
                      node_id: Optional[str] = None) -> None:
    """Record this push's lane assignment (timestamp → tag), folding
    tags beyond the per-owner lane cap into the overflow lane. No-op
    without assignments. INSERT OR IGNORE: a redelivered row keeps its
    first lane (lanes are advisory bandwidth hints, re-tagging is not a
    correctness event).

    `node_id` set = AUTHOR-ONLY: only rows the pushing node itself
    authored get a lane. A resend relays foreign rows too, and tagging
    those retroactively would (a) let one device censor another's rows
    out of scoped views, and (b) open a livelock window — a row served
    while its lane was unknown, then excluded from the scoped tree by a
    later non-author tag, diverges the client's tree permanently. The
    author's first push races nothing (the lane lands in the same
    request that delivers the row)."""
    pairs = [(t, tag) for t, tag in zip(timestamps, push_tags)
             if tag and (node_id is None or t.endswith(node_id))]
    if not pairs:
        return
    _ensure_lane_table(db)
    rows = db.exec_sql_query(
        'SELECT DISTINCT "tag" FROM "scopeLane" WHERE "userId" = ?',
        (user_id,),
    )
    lanes: Set[str] = {r["tag"] for r in rows}
    overflowed = 0
    out = []
    for ts, tag in pairs:
        if tag not in lanes:
            if len(lanes) >= MAX_OWNER_LANES:
                overflowed += 1
                tag = OVERFLOW_TAG
                if tag not in lanes and len(lanes) < MAX_OWNER_LANES + 1:
                    lanes.add(tag)
            else:
                lanes.add(tag)
        out.append((user_id, ts, tag))
    with db.transaction():
        for uid, ts, tag in out:
            db.run(
                'INSERT OR IGNORE INTO "scopeLane" '
                '("userId", "timestamp", "tag") VALUES (?, ?, ?)',
                (uid, ts, tag),
            )
    if overflowed:
        metrics.inc("evolu_scope_overflow_total", overflowed)
    metrics.observe("evolu_scope_owner_lanes", len(lanes),
                    buckets=metrics.COUNT_BUCKETS)


def excluded_timestamps(db, user_id: str,
                        tags: FrozenSet[str]) -> Set[str]:
    """Timestamps whose lane is KNOWN and not requested — the only rows
    a tag filter may withhold (unknown/overflow lanes serve
    conservatively). Empty without a tag filter."""
    if not tags:
        return set()
    _ensure_lane_table(db)
    served = tuple(tags) + (OVERFLOW_TAG,)
    ph = ",".join("?" * len(served))
    rows = db.exec_sql_query(
        f'SELECT "timestamp" FROM "scopeLane" '
        f'WHERE "userId" = ? AND "tag" NOT IN ({ph})',
        (user_id, *served),
    )
    return {r["timestamp"] for r in rows}


def scoped_minute_deltas(timestamps: Sequence[str],
                         xor_mask) -> Dict[str, int]:
    """The masked minute-fold: per-minute XOR deltas over the rows the
    mask keeps. Large canonical batches run the existing device
    segmented fold (`ops.merkle_ops.merkle_minute_deltas` — the mask is
    consumed ON DEVICE as the kernel's xor_mask); everything else —
    small batches, non-canonical hex case, parse bounces, no usable
    backend — takes the shared host oracle, which is the r5 contract's
    required route for non-canonical shapes."""
    n = len(timestamps)
    if n >= SCOPE_DEVICE_FOLD_MIN:
        try:
            from evolu_tpu.ops.host_parse import parse_timestamp_strings

            millis, counter, node, case_ok = parse_timestamp_strings(
                timestamps, with_case=True
            )
            if bool(np.asarray(case_ok).all()):
                from evolu_tpu.ops.merkle_ops import (
                    merkle_minute_deltas,
                    minute_deltas_to_dict,
                )

                mask = np.asarray(xor_mask, dtype=bool)
                outs = merkle_minute_deltas(millis, counter, node, mask)
                metrics.inc("evolu_scope_fold_total", route="device")
                return minute_deltas_to_dict(*outs)
        except Exception:  # noqa: BLE001 - the host oracle is always right
            pass
    metrics.inc("evolu_scope_fold_total", route="host")
    deltas, _digest = minute_deltas_host(
        t for t, keep in zip(timestamps, xor_mask) if keep
    )
    return deltas


def _watermark_string(watermark_millis: int) -> str:
    """The raw-string lower bound for a watermark: the sync timestamp
    of that millis (counter 0000, node all-zeros) sorts at-or-before
    every real timestamp of the same millis, and raw-string order is
    the reference's timestamp order."""
    if not watermark_millis:
        return ""
    return timestamp_to_string(create_sync_timestamp(watermark_millis))


class _ScopedTreeCache:
    """Derived scoped trees keyed by (owner, watermark, tags, node),
    validated by EXACT match on the owner's current full-tree text —
    coherent by construction (every ingest rewrites that text). LRU
    past TREE_CACHE_CAP."""

    def __init__(self, cap: int = TREE_CACHE_CAP):
        self._lock = threading.Lock()
        self._cap = cap
        self._entries: "OrderedDict[tuple, Tuple[str, dict, str]]" = OrderedDict()

    def get(self, key: tuple, full_raw: str) -> Optional[Tuple[dict, str]]:
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None and hit[0] == full_raw:
                self._entries.move_to_end(key)
                metrics.inc("evolu_scope_tree_cache_hits_total")
                return hit[1], hit[2]
        metrics.inc("evolu_scope_tree_cache_misses_total")
        return None

    def put(self, key: tuple, full_raw: str, tree: dict, raw: str) -> None:
        with self._lock:
            self._entries[key] = (full_raw, tree, raw)
            self._entries.move_to_end(key)
            while len(self._entries) > self._cap:
                self._entries.popitem(last=False)
                metrics.inc("evolu_scope_tree_cache_evictions_total")

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()


tree_cache = _ScopedTreeCache()


def scoped_tree_for(shard, user_id: str, node_id: str,
                    scope: "protocol.ScopeClause",
                    full_raw: str) -> Tuple[dict, str]:
    """The scoped Merkle subtree for one (owner, scope, node) — from
    the cache when the full tree hasn't moved, else recomputed by the
    masked minute-fold over the candidate rows. `shard` is a RelayStore
    (or anything with `.db`)."""
    tags = frozenset(scope.tags)
    key = (user_id, scope.watermark_millis, tags, node_id)
    hit = tree_cache.get(key, full_raw)
    if hit is not None:
        return hit
    db = shard.db
    wm = _watermark_string(scope.watermark_millis)
    # Candidates: rows past the watermark, PLUS the requester's own
    # rows regardless of watermark (tree membership rule, module doc).
    # The LIKE arm matches author-node suffixes — the same screen the
    # serve paths use (relay.get_messages).
    rows = db.exec_sql_query(
        'SELECT "timestamp" FROM "message" WHERE "userId" = ? AND '
        '("timestamp" >= ? OR "timestamp" LIKE \'%\' || ?) '
        'ORDER BY "timestamp"',
        (user_id, wm, node_id),
    )
    candidates = [r["timestamp"] for r in rows]
    excluded = excluded_timestamps(db, user_id, tags)
    mask = [
        ts.endswith(node_id)
        or (ts >= wm and ts not in excluded)
        for ts in candidates
    ]
    deltas = scoped_minute_deltas(candidates, mask)
    tree = apply_prefix_xors({}, deltas)
    raw = merkle_tree_to_string(tree)
    tree_cache.put(key, full_raw, tree, raw)
    return tree, raw


def _shard_of(store, user_id: str):
    return store.shard_of(user_id) if hasattr(store, "shard_of") else store


def scoped_response(store, request: "protocol.SyncRequest"
                    ) -> "protocol.SyncResponse":
    """Answer one scoped request — RESPOND ONLY (the caller has already
    ingested `request.messages` through its normal path; the full tree
    and every flow-equation terminal are untouched by scoping). Records
    the push's lane assignment, derives the scoped subtree, diffs it
    against the client tree, and serves exactly the in-slice rows after
    the diff minute, counting what the filter withheld (ledger tallies
    `serve.scoped_rows` / `serve.scope_filtered` — egress
    classification, not flow)."""
    scope = request.scope
    assert scope is not None
    user_id, node_id = request.user_id, request.node_id
    shard = _shard_of(store, user_id)
    if scope.push_tags:
        record_push_lanes(
            shard.db, user_id,
            [m.timestamp for m in request.messages], scope.push_tags,
            node_id=node_id,
        )
    metrics.inc("evolu_scope_serves_total")
    full_raw = shard.get_merkle_tree_string(user_id)
    tree, raw = scoped_tree_for(shard, user_id, node_id, scope, full_raw)
    client_tree = merkle_tree_from_string(request.merkle_tree)
    diff = diff_merkle_trees(tree, client_tree)
    if diff is None:
        return protocol.SyncResponse((), raw)
    since = timestamp_to_string(create_sync_timestamp(diff))
    rows = shard.db.exec_sql_query(
        'SELECT "timestamp", "content" FROM "message" '
        'WHERE "userId" = ? AND "timestamp" > ? AND '
        '"timestamp" NOT LIKE \'%\' || ? ORDER BY "timestamp"',
        (user_id, since, node_id),
    )
    wm = _watermark_string(scope.watermark_millis)
    excluded = excluded_timestamps(shard.db, user_id, frozenset(scope.tags))
    kept: List[protocol.EncryptedCrdtMessage] = []
    n_filtered = 0
    for r in rows:
        ts = r["timestamp"]
        if ts >= wm and ts not in excluded:
            kept.append(protocol.EncryptedCrdtMessage(ts, r["content"]))
        else:
            n_filtered += 1
    ledger.count(ledger.SERVE_SCOPED, len(kept), owner=user_id)
    ledger.count(ledger.SERVE_SCOPE_FILTERED, n_filtered, owner=user_id)
    metrics.inc("evolu_scope_served_rows_total", len(kept))
    metrics.inc("evolu_scope_filtered_rows_total", n_filtered)
    return protocol.SyncResponse(tuple(kept), raw)


def serve_scoped(store, request: "protocol.SyncRequest") -> bytes:
    """The full scoped serve recipe for the per-request path
    (relay.serve_single_request): normal ingest through
    `store.add_messages` (the ledger store seam fires exactly as on the
    unscoped path), then the scoped respond. The batched engine paths
    call `scoped_response` directly — their ingest already ran."""
    store.add_messages(request.user_id, request.messages)
    return protocol.encode_sync_response(scoped_response(store, request))


def scoped_snapshot_filter(db, owners: Optional[Sequence[str]],
                           watermark_millis: int,
                           tags: Sequence[str]):
    """Record filter for a SCOPED snapshot capture (server/snapshot.py):
    keep a (timestamp, userId) row iff it is in the slice — past the
    watermark and not in an excluded lane. Returns a predicate; lane
    exclusion sets are loaded once per owner, lazily."""
    wm = _watermark_string(watermark_millis)
    tag_set = frozenset(tags)
    cache: Dict[str, Set[str]] = {}

    def keep(user_id: str, ts: str) -> bool:
        if ts < wm:
            return False
        if not tag_set:
            return True
        if user_id not in cache:
            cache[user_id] = excluded_timestamps(db, user_id, tag_set)
        return ts not in cache[user_id]

    return keep
