"""Snapshot checkpoint & peer bootstrap: O(state) relay cold-start.

No reference equivalent — the reference relay is a single node that
never bootstraps. PR 3's Merkle anti-entropy converges peers in
bandwidth proportional to DIVERGENCE, but its pulls are capped and
minute-ranged: a FRESH relay (or one restoring after disk loss) is
"diverged by the whole history" and must crawl it in O(history)
capped round-trips through `serve_pull`. Production replicated
systems bootstrap from a state snapshot and hand off to the
incremental log at a watermark; this module is that subsystem:

* **Consistent capture** — per shard, inside ONE SQLite read
  transaction, every `message` row and `merkleTree` row streams into a
  framed byte format (explicit lengths everywhere — timestamps and
  owner ids may be any width, contents are ciphertext blobs). The
  native leg `eh_snapshot_rows` packs the whole shard in one C call;
  the stdlib SQL path is the byte-identical oracle (parity-pinned).
  The stream splits into crc32-checked chunks at record boundaries,
  each under the relay body cap, described by a manifest
  (`sync/protocol.py::SnapshotManifest`) carrying per-owner
  watermarks: the Merkle ROOT hash + a crc32 of the owner's serialized
  tree text at capture time.

* **Shipping** — donor endpoints `POST /replicate/snapshot` (manifest;
  capture is cached briefly so resumed pulls see the same bytes) and
  `POST /replicate/snapshot/chunk` (resumable ranged fetch), 404-gated
  with the rest of `/replicate/*` (the manifest enumerates owner ids —
  capabilities on the sync path). An expired snapshot id answers 400;
  the puller aborts its stale install and restarts fresh.

* **Crash-consistent install** (`SnapshotInstaller`) — chunks land in
  side tables (`messageBsnap`/`merkleTreeBsnap`) of the live store,
  one transaction per (chunk, shard), with the chunk watermark
  persisted in a `snapshotBootstrapState` table AFTER the chunk's rows
  commit: a SIGKILL between chunks resumes from the watermark instead
  of re-transferring completed chunks (re-applying the one un-marked
  chunk is idempotent — same PK, INSERT OR IGNORE). When every chunk
  is in, the installer recomputes EVERY owner's Merkle tree from the
  shipped rows and verifies byte-identity against the shipped tree
  text and the manifest digests (golden-parity trees — any mismatch
  aborts the install and leaves the live tables untouched), then swaps
  the tables in atomically — per shard, ONE transaction first folds
  every live row the snapshot lacks (pre-existing local-only rows AND
  client writes accepted during the install) into the side tables
  through the same changes==1 XOR gate the serve path uses, then DROP
  + ALTER RENAME (SQLite DDL is transactional, and the store's lock is
  held for the whole merge+swap, so handler threads never observe a
  half-swapped shard and an acknowledged write can never vanish in the
  swap). After the swap
  the peer's trees EQUAL the donor's at capture time, so normal PR-3
  gossip resumes from exactly the watermark: the first summary
  exchange diffs only post-snapshot writes.

* **Periodic local checkpoints** — `write_checkpoint` reuses the same
  capture path to produce one atomically-replaced file (tmp + fsync +
  rename); `restore_checkpoint` reuses the same install+verify path
  for crash-consistent fast restart. `RelayServer(checkpoint_
  interval_s=...)` runs a `CheckpointWriter` loop.

The relay stays E2EE-blind throughout (rows are plaintext timestamps +
ciphertext; trees are digests of timestamps), and the relay side holds
no device state — the client-side HBM winner cache contract
(`ops/winner_cache.py`) is untouched; any engine jit caches are
shape-keyed, not content-keyed, so a table swap invalidates nothing.

Observability: the `evolu_snap_*` families (docs/OBSERVABILITY.md) and
a `snapshot` section under `/stats` replication.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import uuid
import zlib
from contextlib import contextmanager
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from evolu_tpu.core.merkle import (
    apply_prefix_xors,
    merkle_tree_from_string,
    merkle_tree_to_string,
    minute_deltas_host,
)
from evolu_tpu.obs import ledger, metrics
from evolu_tpu.sync import protocol
from evolu_tpu.utils.log import log

# Chunk sizing: the default rides well under the relay's 20 MB body
# cap; donors clamp puller-requested sizes into [64 KiB, 8 MiB].
SNAPSHOT_CHUNK_BYTES = 4 << 20
SNAPSHOT_MIN_CHUNK_BYTES = 64 << 10
SNAPSHOT_MAX_CHUNK_BYTES = 8 << 20
# How long a donor keeps a captured snapshot servable. Bounds memory
# (max_entries × store size) while giving a puller ample time to drain
# chunks; an expired id answers 400 and the puller restarts fresh.
SNAPSHOT_TTL_S = 600.0

_REC_MESSAGE = 0x4D  # 'M': u32 ts_len‖ts ‖ u32 uid_len‖uid ‖ u32 len‖content
_REC_TREE = 0x54  # 'T': u32 uid_len‖uid ‖ u32 tree_len‖tree

_U32 = struct.Struct("<I")

_MESSAGE_SCHEMA = (
    'CREATE TABLE "messageBsnap" ('
    '"timestamp" TEXT, "userId" TEXT, "content" BLOB, '
    'PRIMARY KEY ("userId", "timestamp")) WITHOUT ROWID'
)
_TREE_SCHEMA = (
    'CREATE TABLE "merkleTreeBsnap" ('
    '"userId" TEXT PRIMARY KEY, "merkleTree" TEXT)'
)


class SnapshotInstallError(Exception):
    """A snapshot failed integrity/parity verification (crc mismatch,
    recomputed tree != shipped tree, owner/count drift). The install
    aborted; the live tables were never touched."""


@contextmanager
def _exclusive_txn(db):
    """A transaction that is guaranteed to be OUR OWN. The store's
    `transaction()` JOINS an already-open transaction, and the batch
    engine's explicit begin/commit protocol releases the db lock
    between statements — joining it would interleave capture reads or
    install/swap DDL into a foreign write transaction (reading
    uncommitted rows into a snapshot, or committing half a swap with
    someone else's batch). Hold the db lock, wait out any open
    transaction, then BEGIN for real. Engine transactions are
    per-batch and bounded, so the wait is short."""
    while True:
        with db._lock:
            conn = getattr(db, "_conn", None)  # PySqliteDatabase
            open_txn = getattr(db, "_in_txn", False) or bool(
                conn is not None and conn.in_transaction
            )
            if not open_txn:
                with db.transaction():
                    yield db
                return
        time.sleep(0.002)


# --- framing ---


def _frame_message(ts: str, uid: str, content: bytes) -> bytes:
    t, u = ts.encode("utf-8"), uid.encode("utf-8")
    return b"".join(
        (bytes((_REC_MESSAGE,)), _U32.pack(len(t)), t, _U32.pack(len(u)), u,
         _U32.pack(len(content)), content)
    )


def _frame_tree(uid: str, tree: str) -> bytes:
    u, tr = uid.encode("utf-8"), tree.encode("utf-8")
    return b"".join(
        (bytes((_REC_TREE,)), _U32.pack(len(u)), u, _U32.pack(len(tr)), tr)
    )


def _take(data: bytes, pos: int) -> Tuple[bytes, int]:
    if pos + 4 > len(data):
        raise ValueError("truncated snapshot record length")
    (n,) = _U32.unpack_from(data, pos)
    pos += 4
    field = data[pos : pos + n]
    if len(field) != n:
        raise ValueError("truncated snapshot record field")
    return field, pos + n


def _next_record(data: bytes, pos: int) -> Tuple[tuple, int]:
    """One framed record at `pos` → (("M", ts, uid, content) |
    ("T", uid, tree), next_pos). ValueError on malformed framing."""
    t = data[pos]
    if t == _REC_MESSAGE:
        ts, pos = _take(data, pos + 1)
        uid, pos = _take(data, pos)
        content, pos = _take(data, pos)
        return ("M", ts.decode("utf-8"), uid.decode("utf-8"), bytes(content)), pos
    if t == _REC_TREE:
        uid, pos = _take(data, pos + 1)
        tree, pos = _take(data, pos)
        return ("T", uid.decode("utf-8"), tree.decode("utf-8")), pos
    raise ValueError(f"unknown snapshot record type {t:#x}")


def iter_records(data: bytes, pos: int = 0):
    """Yield every framed record in `data`; ValueError on malformed
    framing (the installer treats that exactly like a crc failure)."""
    end = len(data)
    while pos < end:
        rec, pos = _next_record(data, pos)
        yield rec


def _scan_stream(stream: bytes, chunk_bytes: int):
    """ONE pass over the framed stream: chunk boundaries (split at
    RECORD boundaries so every chunk parses standalone; at least one
    record per chunk, an oversized record ships as its own chunk),
    the message count, and the per-owner tree records.
    → (chunks, message_count, [(uid, tree_text), ...])."""
    chunks: List[bytes] = []
    trees: List[Tuple[str, str]] = []
    message_count = 0
    pos = start = 0
    end = len(stream)
    while pos < end:
        rec, nxt = _next_record(stream, pos)
        if rec[0] == "M":
            message_count += 1
        else:
            trees.append((rec[1], rec[2]))
        if pos != start and nxt - start > chunk_bytes:
            chunks.append(stream[start:pos])
            start = pos
        pos = nxt
    if pos > start:
        chunks.append(stream[start:pos])
    return chunks, message_count, trees


# --- capture ---


def _capture_shard_py(db) -> bytes:
    """The stdlib oracle: both SELECTs run inside the caller's read
    transaction; ORDER BY matches the native leg (PK order for the
    WITHOUT ROWID message table) so the two paths are byte-identical."""
    out: List[bytes] = []
    for r in db.exec_sql_query(
        'SELECT "timestamp", "userId", "content" FROM "message" '
        'ORDER BY "userId", "timestamp"'
    ):
        content = r["content"]
        out.append(_frame_message(r["timestamp"], r["userId"],
                                  content if content is not None else b""))
    for r in db.exec_sql_query(
        'SELECT "userId", "merkleTree" FROM "merkleTree" ORDER BY "userId"'
    ):
        out.append(_frame_tree(r["userId"], r["merkleTree"]))
    return b"".join(out)


def capture_shard(db) -> bytes:
    """One shard's framed rows — the native one-C-call leg when the
    backend offers it, else the stdlib oracle. Caller holds the read
    transaction (the two SELECTs must see one consistent state)."""
    if hasattr(db, "snapshot_rows"):
        raw = db.snapshot_rows()
        if raw is not None:  # None = stale .so without the symbol
            return raw
    return _capture_shard_py(db)


def _shards_of(store) -> Sequence:
    return getattr(store, "shards", None) or [store]


def _filter_stream(stream: bytes, owners) -> bytes:
    """Keep only `owners`' records (host-side re-frame of the captured
    stream — the fleet's O(moved-owners) transfer: capture cost stays
    O(store), but nothing else is chunked, digested, or shipped)."""
    wanted = set(owners)
    out: List[bytes] = []
    pos = 0
    end = len(stream)
    while pos < end:
        rec, nxt = _next_record(stream, pos)
        uid = rec[2] if rec[0] == "M" else rec[1]
        if uid in wanted:
            out.append(stream[pos:nxt])
        pos = nxt
    return b"".join(out)


def _scope_stream(store, stream: bytes, watermark_millis: int,
                  tags: Tuple[str, ...]) -> bytes:
    """Re-frame a captured stream down to a SLICE (scoped bootstrap —
    SnapshotRequest watermark/tags): keep the message rows the scope
    filter matches (server/scope.py membership: past the watermark,
    lane not provably excluded) and REGENERATE every shipped owner's
    tree record from exactly the kept rows, so the installer's
    golden-parity verify (recomputed-from-rows == shipped text) passes
    unchanged. A scoped snapshot is a thin-client bootstrap — its
    installed trees describe the slice, NOT the owner's full history —
    and must never seed a full replica (docs/PARTIAL_SYNC.md)."""
    from evolu_tpu.server import scope as scope_mod

    wm = scope_mod._watermark_string(watermark_millis)
    tag_set = frozenset(tags)
    excluded_by_owner: Dict[str, set] = {}

    def _excluded(uid: str) -> set:
        if uid not in excluded_by_owner:
            shard = (store.shard_of(uid) if hasattr(store, "shard_of")
                     else _shards_of(store)[0])
            excluded_by_owner[uid] = scope_mod.excluded_timestamps(
                shard.db, uid, tag_set
            )
        return excluded_by_owner[uid]

    out: List[bytes] = []
    kept_ts: Dict[str, List[str]] = {}
    pos, end = 0, len(stream)
    while pos < end:
        rec, nxt = _next_record(stream, pos)
        if rec[0] == "M":
            _kind, ts, uid, _content = rec
            if ts >= wm and (not tag_set or ts not in _excluded(uid)):
                out.append(stream[pos:nxt])
                kept_ts.setdefault(uid, []).append(ts)
        # "T" records are dropped: regenerated from the kept rows below.
        pos = nxt
    for uid in sorted(kept_ts):
        deltas, _digest = minute_deltas_host(kept_ts[uid])
        out.append(_frame_tree(
            uid, merkle_tree_to_string(apply_prefix_xors({}, deltas))
        ))
    return b"".join(out)


def capture_snapshot(
    store, chunk_bytes: int = SNAPSHOT_CHUNK_BYTES,
    snapshot_id: Optional[str] = None,
    owners=None,
    watermark_millis: int = 0,
    tags: Tuple[str, ...] = (),
) -> Tuple[protocol.SnapshotManifest, List[bytes]]:
    """→ (manifest, chunks). Consistency is per shard (one read
    transaction each) — the store's own consistency unit: an owner
    lives wholly inside one shard, so every owner's rows and tree are
    mutually consistent, which is exactly what install verification
    re-derives. `owners` (an iterable) scopes the snapshot to those
    owners only (fleet rebalance); None = the whole store.
    `watermark_millis`/`tags` scope it to a SLICE (thin-client
    bootstrap, `_scope_stream`) — trees ship recomputed over the
    slice."""
    parts: List[bytes] = []
    for shard in _shards_of(store):
        db = shard.db
        with _exclusive_txn(db):
            parts.append(capture_shard(db))
    stream = b"".join(parts)
    if owners is not None:
        stream = _filter_stream(stream, owners)
    if watermark_millis or tags:
        stream = _scope_stream(store, stream, watermark_millis, tuple(tags))
        metrics.inc("evolu_snap_scoped_captures_total")
    chunks, message_count, tree_recs = _scan_stream(stream, chunk_bytes)
    # NB `owner_digests`, not `owners` — that name is the scoping
    # parameter above and must stay readable through the whole body.
    owner_digests: List[Tuple[str, int, int]] = []
    for uid, tree in tree_recs:
        root = merkle_tree_from_string(tree).get("hash") or 0
        owner_digests.append((uid, int(root), zlib.crc32(tree.encode("utf-8"))))
    owner_digests.sort()
    manifest = protocol.SnapshotManifest(
        snapshot_id or uuid.uuid4().hex,
        tuple(len(c) for c in chunks),
        tuple(zlib.crc32(c) for c in chunks),
        tuple(owner_digests),
        message_count,
        len(stream),
    )
    metrics.inc("evolu_snap_captures_total")
    metrics.inc("evolu_snap_capture_rows_total", message_count)
    metrics.inc("evolu_snap_capture_bytes_total", len(stream))
    return manifest, chunks


# --- donor-side snapshot cache + endpoint bodies ---


class SnapshotCache:
    """Keeps recently captured snapshots servable for resumable chunk
    fetches. A fresh-enough unexpired capture with the same chunk size
    is reused (N bootstrapping peers don't force N captures); entries
    expire after `ttl_s` and the registry is capped at `max_entries`
    (oldest evicted). Bounded staleness is fine — post-capture writes
    flow through normal gossip from the watermark."""

    def __init__(self, store, chunk_bytes: int = SNAPSHOT_CHUNK_BYTES,
                 ttl_s: float = SNAPSHOT_TTL_S, max_entries: int = 2,
                 clock=time.monotonic):
        self._store = store
        self.chunk_bytes = int(chunk_bytes)
        self._ttl_s = float(ttl_s)
        self._max_entries = int(max_entries)
        self._clock = clock
        self._lock = threading.Lock()
        # id -> (expires_at, chunk_bytes, owners_key, scope_key,
        #        manifest, chunks)
        self._entries: Dict[str, tuple] = {}

    def _clamp(self, requested: int) -> int:
        cb = requested or self.chunk_bytes
        return max(SNAPSHOT_MIN_CHUNK_BYTES, min(int(cb), SNAPSHOT_MAX_CHUNK_BYTES))

    def manifest(self, requested_chunk_bytes: int = 0,
                 owners=None, watermark_millis: int = 0,
                 tags: Tuple[str, ...] = ()) -> protocol.SnapshotManifest:
        """`owners` scopes the capture (fleet rebalance),
        `watermark_millis`/`tags` scope it to a slice (thin-client
        bootstrap) — entries are keyed by owner set AND scope, so
        differently-scoped snapshots never serve each other's
        chunks."""
        cb = self._clamp(requested_chunk_bytes)
        owners_key = None if owners is None else frozenset(owners)
        scope_key = (int(watermark_millis), frozenset(tags))
        with self._lock:
            now = self._clock()
            self._entries = {
                k: v for k, v in self._entries.items() if v[0] > now
            }
            for _sid, (_exp, entry_cb, entry_ok, entry_sk, manifest,
                       _chunks) in self._entries.items():
                if entry_cb == cb and entry_ok == owners_key \
                        and entry_sk == scope_key:
                    return manifest
        # Capture OUTSIDE the cache lock: chunk() must stay servable
        # while a full-store capture runs, or one peer's manifest miss
        # stalls every other peer's in-flight chunk fetches for the
        # whole capture (long enough at scale to trip their snapshot
        # TTLs). Two racing first-misses may both capture — rare and
        # merely wasteful; both snapshots get registered and served.
        manifest, chunks = capture_snapshot(
            self._store, cb, owners=owners,
            watermark_millis=watermark_millis, tags=tags,
        )
        with self._lock:
            while len(self._entries) >= self._max_entries:
                oldest = min(self._entries, key=lambda k: self._entries[k][0])
                del self._entries[oldest]
            self._entries[manifest.snapshot_id] = (
                self._clock() + self._ttl_s, cb, owners_key, scope_key,
                manifest, chunks,
            )
        return manifest

    def chunk(self, snapshot_id: str, index: int) -> protocol.SnapshotChunk:
        with self._lock:
            entry = self._entries.get(snapshot_id)
            if entry is not None and entry[0] <= self._clock():
                del self._entries[snapshot_id]
                entry = None
            if entry is None:
                # ValueError → the relay answers 400; the puller reads
                # a 400 on the chunk leg as "snapshot gone", drops its
                # stale install state and restarts fresh.
                raise ValueError(f"unknown or expired snapshot {snapshot_id!r}")
            _exp, _cb, _ok, _sk, manifest, chunks = entry
        if not 0 <= index < len(chunks):
            raise ValueError(
                f"snapshot chunk index {index} out of range 0..{len(chunks) - 1}"
            )
        payload = chunks[index]
        return protocol.SnapshotChunk(
            snapshot_id, index, manifest.chunk_crcs[index], payload
        )


def serve_snapshot(store, body: bytes, manager) -> bytes:
    """Handler body for `POST /replicate/snapshot`: capture (or reuse a
    fresh cached capture) and answer the manifest. ValueError only on
    malformed input (wire-decoder contract → 400)."""
    req = protocol.decode_snapshot_request(body)
    manifest = manager.snapshot_cache.manifest(
        req.chunk_bytes, owners=req.owners or None,
        watermark_millis=req.watermark_millis, tags=req.tags,
    )
    metrics.inc("evolu_snap_manifests_served_total")
    return protocol.encode_snapshot_manifest(manifest)


def serve_snapshot_chunk(store, body: bytes, manager) -> bytes:
    """Handler body for `POST /replicate/snapshot/chunk`: one ranged,
    resumable chunk. Unknown/expired snapshot ids and out-of-range
    indices answer 400 via ValueError — the puller's restart signal."""
    req = protocol.decode_snapshot_chunk_request(body)
    chunk = manager.snapshot_cache.chunk(req.snapshot_id, req.index)
    metrics.inc("evolu_snap_chunks_served_total")
    metrics.inc("evolu_snap_chunk_bytes_served_total", len(chunk.payload))
    return protocol.encode_snapshot_chunk(chunk)


# --- crash-consistent install ---


def install_phase(store) -> Optional[str]:
    """The persisted install state machine's phase marker ("fetch" |
    "swap"), or None when no install is in progress. Probes via
    sqlite_master WITHOUT constructing a SnapshotInstaller — a store
    that never bootstrapped must not grow a state table just from
    being health-checked (`GET /health`, server/fleet.py readiness)."""
    shard0 = _shards_of(store)[0]
    have = shard0.db.exec_sql_query(
        "SELECT name FROM sqlite_master WHERE type='table' "
        "AND name='snapshotBootstrapState'"
    )
    if not have:
        return None
    rows = shard0.db.exec_sql_query(
        'SELECT "value" FROM "snapshotBootstrapState" WHERE "key" = ?',
        ("phase",),
    )
    return rows[0]["value"] if rows else None


class SnapshotInstaller:
    """Installs a snapshot into side tables of the LIVE store with a
    persisted chunk watermark, then verifies and atomically swaps.
    All state (side tables + the `snapshotBootstrapState` key/value
    table on shard 0) lives in the store's own SQLite files, so every
    step inherits SQLite's crash consistency: a killed process resumes
    from exactly the last committed watermark."""

    def __init__(self, store):
        self.store = store
        self.shards = _shards_of(store)
        self._state_db = self.shards[0].db
        self._state_db.exec(
            'CREATE TABLE IF NOT EXISTS "snapshotBootstrapState" '
            '("key" TEXT PRIMARY KEY, "value" TEXT)'
        )

    # -- persisted state --

    def _state_get(self) -> Dict[str, str]:
        rows = self._state_db.exec_sql_query(
            'SELECT "key", "value" FROM "snapshotBootstrapState"'
        )
        return {r["key"]: r["value"] for r in rows}

    def _state_set(self, **kv) -> None:
        db = self._state_db
        with _exclusive_txn(db):
            for k, v in kv.items():
                db.run(
                    'INSERT OR REPLACE INTO "snapshotBootstrapState" '
                    '("key", "value") VALUES (?, ?)',
                    (k, str(v)),
                )

    def _state_clear(self) -> None:
        self._state_db.run('DELETE FROM "snapshotBootstrapState"')

    def pending(self) -> Optional[dict]:
        """The persisted install-in-progress, if any: {snapshot_id,
        peer, manifest, next_chunk, phase}. Undecodable state (e.g. a
        half-written row from a pre-crash schema) clears itself."""
        st = self._state_get()
        if not st or "manifest" not in st:
            return None
        try:
            manifest = protocol.decode_snapshot_manifest(
                bytes.fromhex(st["manifest"])
            )
            return {
                "snapshot_id": st["snapshot_id"],
                "peer": st.get("peer", ""),
                "manifest": manifest,
                "next_chunk": int(st.get("next_chunk", 0)),
                "phase": st.get("phase", "fetch"),
            }
        except (ValueError, KeyError):
            self._state_clear()
            return None

    # -- install steps --

    def begin(self, manifest: protocol.SnapshotManifest, peer: str) -> None:
        for shard in self.shards:
            db = shard.db
            with _exclusive_txn(db):
                db.run('DROP TABLE IF EXISTS "messageBsnap"')
                db.run('DROP TABLE IF EXISTS "merkleTreeBsnap"')
                db.run(_MESSAGE_SCHEMA)
                db.run(_TREE_SCHEMA)
        self._state_set(
            snapshot_id=manifest.snapshot_id,
            peer=peer,
            manifest=protocol.encode_snapshot_manifest(manifest).hex(),
            next_chunk=0,
            phase="fetch",
        )

    def _shard_db(self, uid: str):
        if hasattr(self.store, "shard_of"):
            return self.store.shard_of(uid).db
        return self.shards[0].db

    def install_chunk(self, index: int, payload: bytes,
                      expected_crc: Optional[int] = None) -> int:
        """Parse one chunk and commit its rows into the side tables —
        one transaction per destination shard, then the watermark.
        Re-applying a chunk (crash between a shard commit and the
        watermark) is idempotent: same PKs, INSERT OR IGNORE /
        OR REPLACE. Returns the number of message rows."""
        if expected_crc is not None and zlib.crc32(payload) != expected_crc:
            raise SnapshotInstallError(
                f"snapshot chunk {index}: crc mismatch "
                f"({zlib.crc32(payload):08x} != {expected_crc:08x})"
            )
        by_shard: Dict[int, Tuple[list, list]] = {}
        n_msgs = 0
        try:
            for rec in iter_records(payload):
                uid = rec[2] if rec[0] == "M" else rec[1]
                si = (self.store.shard_index(uid)
                      if hasattr(self.store, "shard_index") else 0)
                msgs, trees = by_shard.setdefault(si, ([], []))
                if rec[0] == "M":
                    msgs.append((rec[1], rec[2], rec[3]))
                    n_msgs += 1
                else:
                    trees.append((rec[1], rec[2]))
        except ValueError as e:
            raise SnapshotInstallError(f"snapshot chunk {index}: {e}") from e
        for si, (msgs, trees) in sorted(by_shard.items()):
            db = self.shards[si].db
            with _exclusive_txn(db):
                if msgs:
                    db.run_many(
                        'INSERT OR IGNORE INTO "messageBsnap" '
                        '("timestamp", "userId", "content") VALUES (?, ?, ?)',
                        msgs,
                    )
                if trees:
                    db.run_many(
                        'INSERT OR REPLACE INTO "merkleTreeBsnap" '
                        '("userId", "merkleTree") VALUES (?, ?)',
                        trees,
                    )
        self._state_set(next_chunk=index + 1)
        return n_msgs

    def verify(self, manifest: protocol.SnapshotManifest) -> None:
        """Golden-parity gate: recompute EVERY owner's Merkle tree from
        the installed rows and demand byte-identity with the shipped
        tree text and the manifest watermarks, plus exact owner-set and
        row-count agreement. Any mismatch aborts before the live
        tables are touched."""
        shipped: Dict[str, str] = {}
        total = 0
        for shard in self.shards:
            for r in shard.db.exec_sql_query(
                'SELECT "userId", "merkleTree" FROM "merkleTreeBsnap"'
            ):
                shipped[r["userId"]] = r["merkleTree"]
            total += shard.db.exec_sql_query(
                'SELECT COUNT(*) AS n FROM "messageBsnap"'
            )[0]["n"]
        by_owner = {uid: (root, crc) for uid, root, crc in manifest.owners}
        if set(shipped) != set(by_owner):
            raise SnapshotInstallError(
                f"snapshot owner set mismatch: manifest has "
                f"{len(by_owner)} owners, stream delivered {len(shipped)}"
            )
        if total != manifest.message_count:
            raise SnapshotInstallError(
                f"snapshot row count mismatch: manifest says "
                f"{manifest.message_count}, installed {total}"
            )
        for uid, tree_text in shipped.items():
            db = self._shard_db(uid)
            ts = [
                r["timestamp"]
                for r in db.exec_sql_query(
                    'SELECT "timestamp" FROM "messageBsnap" WHERE "userId" = ?',
                    (uid,),
                )
            ]
            deltas, _digest = minute_deltas_host(ts)
            recomputed = merkle_tree_to_string(apply_prefix_xors({}, deltas))
            root, crc = by_owner[uid]
            if (
                recomputed != tree_text
                or zlib.crc32(recomputed.encode("utf-8")) != crc
                or (merkle_tree_from_string(recomputed).get("hash") or 0) != root
            ):
                metrics.inc("evolu_snap_verify_failures_total")
                raise SnapshotInstallError(
                    f"snapshot tree verification failed for owner {uid!r}: "
                    "recomputed tree is not byte-identical to the manifest "
                    "watermark"
                )

    def _merge_live_rows_locked(self, db) -> int:
        """Inside an ALREADY-HELD exclusive transaction on `db`: fold
        every live row the snapshot lacks into the side tables through
        the relay's own changes==1 XOR gate — a lagging (not empty)
        peer must not lose rows the donor never had, and a client
        write accepted DURING the install must survive the swap
        (running inside the swap's own transaction closes that window:
        no writer can land between this scan and the table rename).
        The swapped-in trees stay exact unions. No-op for an empty
        store."""
        merged = 0
        owners = [
            r["userId"]
            for r in db.exec_sql_query('SELECT DISTINCT "userId" FROM "message"')
        ]
        for uid in owners:
            # Anti-join instead of per-row INSERT+changes probing: ONE
            # SELECT names exactly the rows the snapshot lacks (both
            # tables are PK-unique on (userId, timestamp), so the fresh
            # set IS the inserted set), then ONE bulk insert — this
            # runs inside the swap's exclusive transaction, where a
            # per-row Python loop over a big lagging store would stall
            # every handler thread on the store lock.
            fresh_rows = db.exec_sql_query(
                'SELECT "timestamp", "content" FROM "message" AS m '
                'WHERE "userId" = ? AND NOT EXISTS ('
                'SELECT 1 FROM "messageBsnap" AS b '
                'WHERE b."userId" = m."userId" '
                'AND b."timestamp" = m."timestamp")',
                (uid,),
            )
            if not fresh_rows:
                continue
            db.run_many(
                'INSERT OR IGNORE INTO "messageBsnap" '
                '("timestamp", "userId", "content") VALUES (?, ?, ?)',
                [(r["timestamp"], uid, r["content"]) for r in fresh_rows],
            )
            got = db.exec_sql_query(
                'SELECT "merkleTree" FROM "merkleTreeBsnap" '
                'WHERE "userId" = ?',
                (uid,),
            )
            tree = merkle_tree_from_string(
                got[0]["merkleTree"] if got else "{}"
            )
            deltas, _d = minute_deltas_host(
                [r["timestamp"] for r in fresh_rows]
            )
            db.run(
                'INSERT OR REPLACE INTO "merkleTreeBsnap" '
                '("userId", "merkleTree") VALUES (?, ?)',
                (uid, merkle_tree_to_string(apply_prefix_xors(tree, deltas))),
            )
            merged += len(fresh_rows)
        return merged

    def swap(self) -> None:
        """Mark phase=swap, then swap every shard. The phase marker
        makes a crash between shard swaps resumable: `finish_swap` is
        idempotent (skips shards whose side tables are already gone)."""
        self._state_set(phase="swap")
        self.finish_swap()

    def finish_swap(self) -> None:
        """Per shard, in ONE exclusive transaction: merge live rows
        the snapshot lacks (see `_merge_live_rows_locked`), then
        DROP + RENAME. Everything a client wrote up to the instant the
        rename commits is either in the snapshot or merged here —
        an acknowledged write can never vanish in the swap."""
        merged = 0
        # Ledger: snapshot rows INGRESS this process when they become
        # live (the swap commit), and the live-vs-snapshot overlap is
        # the changes==1-gate classifier — a row the store already had
        # terminates at store.duplicate, the rest at store.inserted.
        # Accumulated into a pending entry posted only after every
        # shard of THIS run swapped (a crash-resume run posts only the
        # shards it swaps itself, so ingress == terminals always).
        entry = ledger.pending()
        for shard in self.shards:
            db = shard.db
            with _exclusive_txn(db):
                have = db.exec_sql_query(
                    "SELECT name FROM sqlite_master WHERE type='table' "
                    "AND name='messageBsnap'"
                )
                if not have:
                    continue  # this shard already swapped (resume)
                snap_total = db.exec_sql_query(
                    'SELECT COUNT(*) AS n FROM "messageBsnap"'
                )[0]["n"]
                overlap = db.exec_sql_query(
                    'SELECT COUNT(*) AS n FROM "message" AS m '
                    'WHERE EXISTS (SELECT 1 FROM "messageBsnap" AS b '
                    'WHERE b."userId" = m."userId" '
                    'AND b."timestamp" = m."timestamp")'
                )[0]["n"]
                entry.count(ledger.INGRESS_SNAPSHOT, snap_total)
                entry.count(ledger.STORE_INSERTED, snap_total - overlap)
                entry.count(ledger.STORE_DUPLICATE, overlap)
                merged += self._merge_live_rows_locked(db)
                db.run('DROP TABLE "message"')
                db.run('ALTER TABLE "messageBsnap" RENAME TO "message"')
                db.run('DROP TABLE "merkleTree"')
                db.run('ALTER TABLE "merkleTreeBsnap" RENAME TO "merkleTree"')
        entry.commit()
        if merged:
            metrics.inc("evolu_snap_local_rows_merged_total", merged)
        self._state_clear()

    def abort(self) -> None:
        for shard in self.shards:
            db = shard.db
            with _exclusive_txn(db):
                db.run('DROP TABLE IF EXISTS "messageBsnap"')
                db.run('DROP TABLE IF EXISTS "merkleTreeBsnap"')
        self._state_clear()


def install_stream(
    store,
    manifest: protocol.SnapshotManifest,
    chunks: Iterable[bytes],
    source: str = "<local>",
) -> None:
    """Install a fully-materialized snapshot (the checkpoint-restore
    path; the network bootstrap drives `SnapshotInstaller` itself so it
    can persist the watermark between fetches)."""
    inst = SnapshotInstaller(store)
    inst.begin(manifest, source)
    t0 = time.perf_counter()
    try:
        for i, payload in enumerate(chunks):
            inst.install_chunk(i, payload, expected_crc=manifest.chunk_crcs[i])
        inst.verify(manifest)
    except BaseException:
        inst.abort()
        raise
    inst.swap()
    metrics.observe("evolu_snap_install_ms", (time.perf_counter() - t0) * 1e3)
    metrics.inc("evolu_snap_installs_total", result="ok")


# --- local checkpoints ---

CHECKPOINT_MAGIC = b"EVOLUSNAP1\n"


def write_checkpoint(store, path: str,
                     chunk_bytes: int = SNAPSHOT_CHUNK_BYTES,
                     barrier=None) -> protocol.SnapshotManifest:
    """Capture the store and atomically replace the checkpoint file
    (tmp + fsync + rename): a crash mid-write leaves the previous
    checkpoint intact — the file is always a complete, crc-covered
    snapshot or absent. `barrier` is an optional context-manager
    factory held across the CAPTURE (PR-11: the write-behind queue's
    `drain_barrier` — a checkpoint is a durable floor, so it must see
    fully committed state, and the drain must not commit underneath
    the capture's read transactions; PR-19: the barrier composes over
    every shard's drain worker, holding all shard locks at once)."""
    if barrier is not None:
        with barrier():
            manifest, chunks = capture_snapshot(store, chunk_bytes)
    else:
        manifest, chunks = capture_snapshot(store, chunk_bytes)
    blob = protocol.encode_snapshot_manifest(manifest)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(CHECKPOINT_MAGIC)
        f.write(_U32.pack(len(blob)))
        f.write(blob)
        for c in chunks:
            f.write(c)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # fsync the parent directory too: without it the rename's directory
    # entry may not survive power loss, and a counted checkpoint could
    # silently revert/vanish — the "complete or absent" claim must hold
    # across power failure, not just process crash.
    dir_fd = os.open(os.path.dirname(os.path.abspath(path)) or ".", os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)
    metrics.inc("evolu_snap_checkpoints_total")
    metrics.set_gauge("evolu_snap_checkpoint_bytes", manifest.total_bytes)
    return manifest


def read_checkpoint(path: str) -> Tuple[protocol.SnapshotManifest, List[bytes]]:
    """→ (manifest, chunks), crc-verified. ValueError on any
    corruption — a torn or tampered checkpoint never half-installs."""
    with open(path, "rb") as f:
        data = f.read()
    if not data.startswith(CHECKPOINT_MAGIC):
        raise ValueError(f"not an evolu snapshot checkpoint: {path!r}")
    pos = len(CHECKPOINT_MAGIC)
    if pos + 4 > len(data):
        raise ValueError("truncated checkpoint header")
    (n,) = _U32.unpack_from(data, pos)
    pos += 4
    manifest = protocol.decode_snapshot_manifest(data[pos : pos + n])
    pos += n
    chunks: List[bytes] = []
    for i, size in enumerate(manifest.chunk_sizes):
        payload = data[pos : pos + size]
        if len(payload) != size:
            raise ValueError(f"truncated checkpoint chunk {i}")
        if zlib.crc32(payload) != manifest.chunk_crcs[i]:
            raise ValueError(f"checkpoint chunk {i} crc mismatch")
        chunks.append(payload)
        pos += size
    if pos != len(data):
        raise ValueError("trailing bytes after the last checkpoint chunk")
    return manifest, chunks


def restore_checkpoint(store, path: str) -> protocol.SnapshotManifest:
    """Rebuild a store from a checkpoint file through the same
    install+verify path a peer bootstrap uses (golden-parity trees or
    the restore aborts). Pre-existing local rows merge through the XOR
    gate, exactly like a lagging-peer bootstrap."""
    manifest, chunks = read_checkpoint(path)
    install_stream(store, manifest, chunks, source=f"checkpoint:{path}")
    return manifest


class CheckpointWriter:
    """Periodic local checkpoints for crash-consistent fast restart
    (`RelayServer(checkpoint_interval_s=...)`). Failures are counted
    and logged, never fatal — the previous checkpoint stays valid."""

    def __init__(self, store, path: str, interval_s: float,
                 chunk_bytes: int = SNAPSHOT_CHUNK_BYTES, barrier=None):
        self.store = store
        self.path = path
        self.interval_s = float(interval_s)
        self.chunk_bytes = int(chunk_bytes)
        self.barrier = barrier  # see write_checkpoint (PR-11 drain barrier)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "CheckpointWriter":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="evolu-checkpoint"
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                write_checkpoint(self.store, self.path, self.chunk_bytes,
                                 barrier=self.barrier)
            except Exception as e:  # noqa: BLE001 - keep checkpointing
                metrics.inc("evolu_snap_checkpoint_failures_total")
                log("server", "checkpoint write failed", path=self.path,
                    error=repr(e))

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
