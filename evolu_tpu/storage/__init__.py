"""Durable storage: real SQLite with reference-identical schema and semantics.

The `Database` interface is the backend boundary the reference exposes
(types.ts:162-176); the TPU merge engine plugs in above it — kernels
decide winners/masks, storage applies them transactionally. Two
implementations: `sqlite.PySqliteDatabase` (stdlib sqlite3 — the real
SQLite C library) and `native.CppSqliteDatabase` (the C++ host layer
driving the SQLite C API, with the batched apply hot paths);
`open_database` selects between them.
"""

from evolu_tpu.storage.sqlite import PySqliteDatabase
from evolu_tpu.storage.native import CppSqliteDatabase, native_available, open_database
from evolu_tpu.storage.schema import (
    init_db_model,
    update_db_schema,
    get_existing_tables,
    delete_all_tables,
)
from evolu_tpu.storage.clock import read_clock, update_clock
from evolu_tpu.storage.apply import apply_messages

__all__ = [
    "PySqliteDatabase",
    "CppSqliteDatabase",
    "native_available",
    "open_database",
    "init_db_model",
    "update_db_schema",
    "get_existing_tables",
    "delete_all_tables",
    "read_clock",
    "update_clock",
    "apply_messages",
]
