"""Write-behind shard drain child: one process per drain worker.

The pure-Python sqlite3 insert leg holds the GIL, so thread-per-shard
workers cannot overlap it — this child is the honest alternative
(fleet-bench style): the parent worker ships each shard batch over a
pipe and blocks in the read (GIL dropped) while THIS process runs the
transaction. File-backed shards only; cross-process safety is the
same WAL + busy_timeout + BEGIN IMMEDIATE discipline the pre-forked
fleet relays run (`sqlite.configure_shared_file_db`).

Frame protocol (stdin → stdout, little-endian u32 lengths):

    request:  u32 header_len | header JSON | u32 blob_len | blob
      header = {"si", "exact", "taint": [owner...],
                "ops": [{"u", "k", "lens": [int...], "tree": str|null}]}
      blob   = all ops' ts_packed (46B/row) concatenated in op order,
               then all ops' content bytes in op order
    response: u32 len | JSON {"ok": true, "tainted": [...],
                              "counts": [[n_new, n_dup]...]}
              or {"ok": false, "error": "..."}

The child posts NOTHING to the observability planes: the ledger is
per-process state and the parent owns it — it posts the terminals
from the returned counts iff the response arrives (a child killed
mid-transaction rolled back; killed post-commit, the parent's retry
re-classifies the committed rows as duplicates — the same rule
SIGKILL replay runs). EOF on stdin is clean shutdown.
"""

from __future__ import annotations

import json
import os
import struct
import sys

# The fold helpers (core.merkle host oracle) are numpy-only; nothing
# on this import path touches jax, so the child starts in ~100ms.
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

from evolu_tpu.storage.sqlite import PySqliteDatabase, configure_shared_file_db
from evolu_tpu.storage.write_behind import apply_shard_ops

_U32 = struct.Struct("<I")


def _read_exact(stream, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = stream.read(n - len(buf))
        if not chunk:
            if buf:
                raise EOFError("torn write-behind shard frame")
            raise EOFError("eof")
        buf += chunk
    return buf


def _get_tree(db):
    def get(owner: str) -> str:
        rows = db.exec_sql_query(
            'SELECT "merkleTree" FROM "merkleTree" WHERE "userId" = ?',
            (owner,),
        )
        return rows[0]["merkleTree"] if rows else "{}"
    return get


def _serve(shard_paths) -> None:
    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer
    dbs = {}
    while True:
        try:
            (hl,) = _U32.unpack(_read_exact(stdin, 4))
        except EOFError:
            break
        header = json.loads(_read_exact(stdin, hl).decode("utf-8"))
        (bl,) = _U32.unpack(_read_exact(stdin, 4))
        blob = _read_exact(stdin, bl)
        try:
            si = int(header["si"])
            db = dbs.get(si)
            if db is None:
                db = dbs[si] = PySqliteDatabase(shard_paths[si])
                configure_shared_file_db(db)
            ops = []
            rows = sum(int(op["k"]) for op in header["ops"])
            ts_off, c_off = 0, rows * 46
            for op in header["ops"]:
                k = int(op["k"])
                lens = np.asarray(op["lens"], dtype=np.int32)
                nb = int(lens.sum())
                ops.append((
                    op["u"], k,
                    blob[ts_off : ts_off + k * 46],
                    blob[c_off : c_off + nb],
                    lens, op["tree"],
                ))
                ts_off += k * 46
                c_off += nb
            tainted, counts = apply_shard_ops(
                db, _get_tree(db), ops,
                bool(header["exact"]), set(header["taint"]),
            )
            body = json.dumps({
                "ok": True,
                "tainted": sorted(tainted),
                "counts": [[int(a), int(b)] for a, b in counts],
            }).encode("utf-8")
        except Exception as e:  # noqa: BLE001 - report, keep serving
            body = json.dumps({"ok": False, "error": repr(e)}).encode("utf-8")
        stdout.write(_U32.pack(len(body)) + body)
        stdout.flush()
    for db in dbs.values():
        db.close()


def main(argv) -> None:
    shard_paths = {}
    it = iter(argv)
    for a in it:
        if a == "--shard":
            si, _, path = next(it).partition("=")
            shard_paths[int(si)] = path
    _serve(shard_paths)


if __name__ == "__main__":
    main(sys.argv[1:])
