"""LWW message application — the merge hot path.

`apply_messages_sequential` reproduces the reference's per-message loop
(applyMessages.ts:26-131) exactly and serves as the correctness oracle:

1. winner lookup: latest __message timestamp for the (table,row,column)
   cell (applyMessages.ts:34-40);
2. if absent or older than the message ⇒ upsert the app table
   (applyMessages.ts:92-103);
3. if the winner differs from the message timestamp ⇒ INSERT OR NOTHING
   into __message and XOR the timestamp hash into the Merkle tree
   (applyMessages.ts:104-122). NB the XOR is NOT gated on the insert
   actually inserting — a re-received non-winning duplicate XORs again
   (client semantics; the server gates on changes==1 instead,
   apps/server/src/index.ts:153-158).

`apply_messages` is the batched path with identical end state: one
winner query for all touched cells, decision masks computed batch-wise
(host here; `evolu_tpu.ops.merge` computes the same masks on device for
large batches), then bulk SQL. Equivalence is property-tested in
tests/test_apply.py.

Typed CRDT cells (counter/awset/list and the tensor family, ISSUEs 7/
14/20) ride the same transaction: `crdt_types.apply_typed_ops` folds
new ops into the `__crdt_*` state tables (tensor: the `__crdt_tensor`
op log) and materializes canonical bytes BEFORE the batch's __message
insert, while `strip_typed_upserts` removes their LWW upserts from the
plan. Packed batches containing ANY typed cell — tensor included —
bounce to this object path BEFORE any side effect (the r5 contract).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from evolu_tpu.core.merkle import (
    apply_prefix_xors,
    insert_into_merkle_tree,
    minute_deltas_host,
)
from evolu_tpu.core.timestamp import timestamp_from_string
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.obs import ledger
from evolu_tpu.storage.sqlite import PySqliteDatabase, quote_ident


# Mask counting shared with the relay's store seam (ONE copy).
_mask_sum = ledger.flag_sum

_SELECT_WINNER = (
    'SELECT "timestamp" FROM "__message" '
    'WHERE "table" = ? AND "row" = ? AND "column" = ? '
    'ORDER BY "timestamp" DESC LIMIT 1'
)
_INSERT_MESSAGE = (
    'INSERT INTO "__message" ("timestamp", "table", "row", "column", "value") '
    "VALUES (?, ?, ?, ?, ?) ON CONFLICT DO NOTHING"
)


def _upsert_sql(table: str, column: str) -> str:
    """Hostile table/column names from the wire must not splice SQL:
    identifiers are quote-doubled (same as the C++ layer)."""
    t, c = quote_ident(table), quote_ident(column)
    # Explicit conflict target: targetless DO UPDATE needs SQLite >=
    # 3.35, the "id" PK spelling works on every 3.24+ (this container
    # runs 3.34). Same text in native/evolu_host.cpp::upsert_sql.
    return (
        f"INSERT INTO {t} (\"id\", {c}) VALUES (?, ?) "
        f"ON CONFLICT(\"id\") DO UPDATE SET {c} = ?"
    )


def apply_messages_sequential(
    db: PySqliteDatabase, merkle_tree: dict, messages: Sequence[CrdtMessage],
    changes=None,
) -> dict:
    """The reference loop, message by message.

    On the C++ backend the whole loop (winner check, upsert, insert)
    runs as one native call returning the XOR mask; on the Python
    backend it is O(n) SQL round trips. `changes` is an optional
    `storage.changes.ChangedSet` implementing the invalidation
    contract (ISSUE 9)."""
    from evolu_tpu.core.crdt_types import apply_typed_ops, load_schema
    from evolu_tpu.storage.changes import record_batch, record_typed_tables

    record_batch(changes, messages)
    schema = load_schema(db)
    typed = (
        [m for m in messages if schema.is_typed(m.table, m.column)]
        if schema else []
    )
    use_native = hasattr(db, "apply_sequential") and not typed and not any(
        "\x00" in m.timestamp or "\x00" in m.table or "\x00" in m.row
        or "\x00" in m.column
        for m in messages
    )  # the C path's char* ABI is NUL-terminated (binds AND winner
    # compares); NUL-bearing wire fields must take the Python loop to
    # bind full bytes like the reference (the batched production path
    # is NUL-exact natively). Typed batches take the Python loop too:
    # the native loop would LWW-upsert raw op values into app tables.
    entry = ledger.pending()
    entry.count(ledger.APPLY_INGRESS, len(messages))
    entry.count(ledger.ROUTE_SEQUENTIAL, len(messages))
    entry.count(ledger.ROUTE_TYPED, len(typed))
    try:
        if use_native:
            xor_mask = db.apply_sequential(messages)
            for m, flagged in zip(messages, xor_mask):
                if flagged:
                    merkle_tree = insert_into_merkle_tree(
                        timestamp_from_string(m.timestamp), merkle_tree
                    )
            # The native loop reports xor flags only: a row that XORed
            # but lost its cell is indistinguishable from a winner here,
            # so the sequential-route split is coarser (inserted = XORed)
            # than the batched routes'. The equation sums still balance.
            n_xor = _mask_sum(xor_mask)
            entry.count(ledger.APPLY_INSERTED, n_xor)
            entry.count(ledger.APPLY_DUPLICATE, len(messages) - n_xor)
            entry.commit()
            return merkle_tree
        if typed:
            # Fold + materialize BEFORE the loop inserts any __message
            # row: the dedup screen must observe pre-batch state (same
            # contract as the batched path). xor/insert semantics below
            # stay the reference's, timestamp-only.
            record_typed_tables(changes)
            apply_typed_ops(db, schema, typed)
        for m in messages:
            rows = db.exec_sql_query(_SELECT_WINNER, (m.table, m.row, m.column))
            t = rows[0]["timestamp"] if rows else None
            won = (t is None or t < m.timestamp)
            if won and not (schema and schema.is_typed(m.table, m.column)):
                db.run(_upsert_sql(m.table, m.column), (m.row, m.value, m.value))
            if t is None or t != m.timestamp:
                db.run(_INSERT_MESSAGE,
                       (m.timestamp, m.table, m.row, m.column, m.value))
                merkle_tree = insert_into_merkle_tree(
                    timestamp_from_string(m.timestamp), merkle_tree
                )
                entry.count(
                    ledger.APPLY_INSERTED if won else ledger.APPLY_LOSING
                )
            else:
                entry.count(ledger.APPLY_DUPLICATE)
        entry.commit()
        return merkle_tree
    except BaseException:
        # The oracle runs statement-at-a-time (no outer transaction
        # here): a mid-loop failure leaves the batch partially applied,
        # and the ledger counts the whole batch as rejected — the
        # conservative classification (route counted above never posts;
        # the pending entry dies with the abort).
        entry.abort()
        ledger.count(ledger.APPLY_INGRESS, len(messages))
        ledger.count(ledger.APPLY_REJECTED, len(messages))
        raise


def fetch_existing_winners(
    db: PySqliteDatabase, cells: Iterable[Tuple[str, str, str]]
) -> Dict[Tuple[str, str, str], str]:
    """Current winner timestamp per cell, one indexed query per cell batch
    via a temp table join (uses the (table,row,column,timestamp) covering
    index, initDbModel.ts:51-56)."""
    cells = list(cells)
    if not cells:
        return {}
    if hasattr(db, "fetch_winners") and len(cells) < 4096:
        # C++ backend: per-cell indexed lookups in one native call —
        # fastest for small batches; above ~4k cells the single
        # temp-table GROUP BY join below wins (one scan vs N probes).
        winners = db.fetch_winners(cells)
        return {c: w for c, w in zip(cells, winners) if w is not None}
    with db.transaction():
        db.exec('CREATE TEMP TABLE IF NOT EXISTS "__cells" ("t" BLOB, "r" BLOB, "c" BLOB)')
        db.run('DELETE FROM "__cells"')
        db.run_many('INSERT INTO "__cells" VALUES (?, ?, ?)', cells)
        rows = db.exec_sql_query(
            'SELECT m."table" AS t, m."row" AS r, m."column" AS c, '
            'MAX(m."timestamp") AS w FROM "__message" m '
            'JOIN "__cells" x ON m."table" = x."t" AND m."row" = x."r" AND m."column" = x."c" '
            "GROUP BY m.\"table\", m.\"row\", m.\"column\""
        )
        db.run('DELETE FROM "__cells"')
    return {(r["t"], r["r"], r["c"]): r["w"] for r in rows}


def plan_batch(
    messages: Sequence[CrdtMessage],
    existing_winners: Dict[Tuple[str, str, str], str],
):
    """Compute merge decisions for a batch on host (pure, no SQL).

    Returns (xor_mask, upserts) where xor_mask[i] says message i's hash
    is XORed into the Merkle tree, and upserts maps cell -> (row, column,
    table, value) for cells whose final winner beats the stored one.
    Mirrors the sequential running-max semantics exactly; the device
    kernel (ops.merge.plan_batch_device) computes the same masks with a
    sort + segmented scan.
    """
    xor_mask: List[bool] = [False] * len(messages)
    running: Dict[Tuple[str, str, str], Optional[str]] = {}
    final: Dict[Tuple[str, str, str], CrdtMessage] = {}
    for i, m in enumerate(messages):
        cell = (m.table, m.row, m.column)
        w = running.get(cell, existing_winners.get(cell))
        xor_mask[i] = w is None or w != m.timestamp
        if w is None or w < m.timestamp:
            running[cell] = m.timestamp
            final[cell] = m
        else:
            running[cell] = w
    upserts = [
        m for cell, m in final.items()
        if (existing_winners.get(cell) is None or existing_winners[cell] < m.timestamp)
    ]
    return xor_mask, upserts


def apply_messages(
    db: PySqliteDatabase,
    merkle_tree: dict,
    messages: Sequence[CrdtMessage],
    planner=None,
    changes=None,
) -> dict:
    """Batched apply with end state identical to the sequential oracle.

    `planner` defaults to the host `plan_batch`; the TPU runtime passes
    a device planner with the same contract. `changes` (optional
    `storage.changes.ChangedSet`) collects the (table, rowId) pairs
    this apply touches — the query-invalidation contract (ISSUE 9):
    recording happens here at the apply level, so EVERY plan route
    (device kernel, winner cache, `merge._host_fallback`, hot-owner,
    host oracle, packed `eh_apply_planned_cells`) reports identically,
    and any unrecognizable batch escalates to conservative full
    invalidation inside `record_batch`.
    """
    if not len(messages):
        return merkle_tree
    planner = planner or plan_batch
    # Conservation ledger (obs/ledger.py): routing + terminal counts
    # accumulate into a pending entry and post ONLY when the
    # transaction commits — a rolled-back batch posts apply.rejected
    # instead, so a retry can never double-count.
    entry = ledger.pending()
    try:
        with db.transaction():  # whole-batch atomicity, like the reference's dbTransaction
            tree = _apply_in_txn(db, merkle_tree, messages, planner, changes,
                                 entry)
        entry.commit()
        return tree
    except BaseException:
        # A planner that mutates its own state at plan time (the HBM
        # winner cache) is now ahead of the rolled-back SQLite; let it
        # resynchronize.
        entry.abort()
        ledger.count(ledger.APPLY_INGRESS, len(messages))
        ledger.count(ledger.APPLY_REJECTED, len(messages))
        _notify_plan_failure(planner)
        raise


def _notify_plan_failure(planner) -> None:
    """Fire the planner's transaction-failure hook, if any. The hook
    may sit on the planner function (select_planner's closure) or on a
    bound method's instance (DeviceWinnerCache.plan_batch)."""
    on_failed = getattr(planner, "on_transaction_failed", None)
    if on_failed is None:
        owner = getattr(planner, "__self__", None)
        on_failed = getattr(owner, "on_transaction_failed", None)
    if on_failed is not None:
        on_failed()


def _apply_in_txn(db, merkle_tree, messages, planner, changes=None,
                  entry=None):
    """Dispatch inside the transaction: a PackedReceive batch (the
    fused receive leg) takes the columnar plan+apply when both the
    planner and the backend support it; everything else — and every
    packed batch the planner bounces (non-canonical case, host-oracle
    shapes, small batches) — materializes to CrdtMessage objects and
    runs the standard path, so behavior and error surfaces are
    identical either way (test-pinned)."""
    from evolu_tpu.core.packed import PackedReceive
    from evolu_tpu.core.crdt_types import load_schema
    from evolu_tpu.obs import metrics
    from evolu_tpu.storage.changes import record_batch

    if entry is None:
        entry = ledger.pending()  # discarded; direct callers are tests
    entry.count(ledger.APPLY_INGRESS, len(messages))
    # Record BEFORE routing: the touched (table, row) set is the same
    # on every route, and recording first means a route that later
    # fails half-way still lands in a superset changed-set.
    record_batch(changes, messages)
    if isinstance(messages, PackedReceive):
        schema = load_schema(db)
        if schema and schema.has_typed(messages.cells):
            # Typed cells in a packed batch bounce to the object path
            # BEFORE any side effect (the r5 packed-receive contract,
            # extended): the packed C cell-apply would LWW-upsert raw
            # op values, and the typed fold needs message objects.
            metrics.inc("evolu_crdt_packed_bounces_total")
            metrics.inc("evolu_apply_packed_bounces_total")
            messages = messages.to_messages()
            metrics.inc("evolu_apply_batches_total", route="object")
            return _apply_messages_in_txn(db, merkle_tree, messages, planner,
                                          changes, entry)
        plan_packed = getattr(planner, "plan_packed", None)
        if plan_packed is not None and hasattr(db, "apply_planned_cells"):
            plan = plan_packed(messages)
            if plan is not None:
                metrics.inc("evolu_apply_batches_total", route="packed")
                xor_mask, upsert_mask, deltas = plan
                db.apply_planned_cells(messages, upsert_mask)
                # Packed terminals from the positional masks (already
                # host numpy — the plan was just applied to SQLite, so
                # no device pull happens here): winners are upserts,
                # XORed non-winners lost, the rest are duplicates.
                n, n_xor, n_win = (len(messages), _mask_sum(xor_mask),
                                   _mask_sum(upsert_mask))
                entry.count(ledger.ROUTE_PACKED, n)
                entry.count(ledger.APPLY_INSERTED, n_win)
                entry.count(ledger.APPLY_LOSING, n_xor - n_win)
                entry.count(ledger.APPLY_DUPLICATE, n - n_xor)
                return apply_prefix_xors(merkle_tree, deltas)
        # The packed batch bounced (non-canonical shape, small batch,
        # hot-owner route, or a backend without the cell apply):
        # materialize and take the object path below.
        metrics.inc("evolu_apply_packed_bounces_total")
        messages = messages.to_messages()
    metrics.inc("evolu_apply_batches_total", route="object")
    return _apply_messages_in_txn(db, merkle_tree, messages, planner, changes,
                                  entry)


def _apply_messages_in_txn(db, merkle_tree, messages, planner, changes=None,
                           entry=None):
    if entry is None:
        entry = ledger.pending()  # discarded; direct callers are tests
    entry.count(ledger.ROUTE_OBJECT, len(messages))
    # `fetches_winners` may sit on the planner function or, for bound
    # methods (DeviceWinnerCache.plan_batch), on the owning instance.
    owner = getattr(planner, "__self__", None)
    fetches = getattr(planner, "fetches_winners",
                      getattr(owner, "fetches_winners", True))
    if fetches:
        cells = {(m.table, m.row, m.column) for m in messages}
        existing = fetch_existing_winners(db, cells)
    else:
        existing = {}  # the planner owns its winner source (HBM cache)
    plan = planner(messages, existing)
    from evolu_tpu.core.crdt_types import apply_typed_ops, load_schema

    schema = load_schema(db)
    typed = (
        [m for m in messages if schema.is_typed(m.table, m.column)]
        if schema else []
    )
    if typed:
        # Typed cells: fold new ops into merge state + materialize
        # (BEFORE the __message insert below — the dedup screen reads
        # pre-batch state), and strip their LWW upserts from whatever
        # planner produced the plan (ONE copy: ops.merge).
        from evolu_tpu.ops.merge import strip_typed_upserts
        from evolu_tpu.storage.changes import record_typed_tables

        record_typed_tables(changes)
        apply_typed_ops(db, schema, typed)
        plan = strip_typed_upserts(plan, messages, schema)
        # Tally station (outside the flow equations): typed messages
        # still ride the object route's __message insert below; their
        # LWW upserts were just stripped, so their terminal split leans
        # on the XOR flag alone.
        entry.count(ledger.ROUTE_TYPED, len(typed))
    if len(plan) == 3:
        # Device planner: masks AND per-minute Merkle deltas in one
        # dispatch (no per-message Python hashing).
        xor_mask, upserts, deltas = plan
    else:
        xor_mask, upserts = plan
        # Merkle deltas: the shared oracle-exact fold (verbatim node
        # case). Computed BEFORE any write so a malformed timestamp
        # rolls the whole batch back — committing messages whose
        # hashes never reach the tree would diverge the digest
        # permanently.
        deltas, _ = minute_deltas_host(
            m.timestamp for i, m in enumerate(messages) if xor_mask[i]
        )

    if hasattr(db, "apply_planned"):
        # C++ backend: upserts + bulk __message insert in one call.
        mask = getattr(plan, "upsert_mask", None)
        if mask is None:
            # Host planners return upserts only; rebuild the
            # positional mask keyed by cell+timestamp, flagging only
            # the FIRST occurrence of each winner key — a duplicate
            # timestamp with a different value must not upsert
            # twice, or the end state would diverge from the Python
            # path, which applies the planner's single chosen
            # winner. (Device planners carry the positional mask,
            # PlannedBatch, skipping this per-message pass.)
            pending = {(m.table, m.row, m.column, m.timestamp) for m in upserts}
            mask = []
            for m in messages:
                key = (m.table, m.row, m.column, m.timestamp)
                mask.append(key in pending)
                pending.discard(key)
        db.apply_planned(messages, mask)
        n_win = _mask_sum(mask)
    else:
        # App tables: only the final winner per cell touches the row.
        for m in upserts:
            db.run(_upsert_sql(m.table, m.column), (m.row, m.value, m.value))

        # __message: bulk insert, PK dedup handles duplicates.
        db.run_many(
            _INSERT_MESSAGE,
            [(m.timestamp, m.table, m.row, m.column, m.value) for m in messages],
        )
        n_win = len(upserts)

    # Terminal classification from masks already on host (never a
    # device pull — device planners return pulled numpy): winners
    # upserted, XORed non-winners lost LWW, the rest exact duplicates.
    n_xor = _mask_sum(xor_mask)
    entry.count(ledger.APPLY_INSERTED, n_win)
    entry.count(ledger.APPLY_LOSING, n_xor - n_win)
    entry.count(ledger.APPLY_DUPLICATE, len(messages) - n_xor)

    # One sparse-tree pass (pure, cannot fail after commit).
    return apply_prefix_xors(merkle_tree, deltas)


def apply_messages_log_only(
    db: PySqliteDatabase,
    merkle_tree: dict,
    messages: Sequence[CrdtMessage],
    changes=None,
) -> dict:
    """Partial replication (ISSUE 18, sync/scope.py): land a batch in
    the __message log and the Merkle tree WITHOUT materializing
    app-table rows — the apply route for out-of-scope tables on a
    scoped client. The log rows and tree deltas are byte-identical to
    a full apply's (convergence and anti-entropy never see the
    difference); only the upsert step is skipped, with every skipped
    message tallied at `apply.deferred_mat` so the deferred frontier is
    counted, never silent. A later `widen()` re-materializes these
    rows from the log in LWW order (runtime/worker.py). Same pending-
    entry/transaction discipline as `apply_messages` — a rolled-back
    batch posts apply.rejected."""
    if not len(messages):
        return merkle_tree
    from evolu_tpu.storage.changes import record_batch

    entry = ledger.pending()
    try:
        with db.transaction():
            entry.count(ledger.APPLY_INGRESS, len(messages))
            entry.count(ledger.ROUTE_OBJECT, len(messages))
            # Recorded even though nothing materializes: invalidation
            # must stay conservative for queries that (wrongly) read a
            # deferred table — they re-run and hit the typed deferral.
            record_batch(changes, messages)
            cells = {(m.table, m.row, m.column) for m in messages}
            existing = fetch_existing_winners(db, cells)
            xor_mask, upserts = plan_batch(messages, existing)
            # Host fold only: deferred batches are out-of-scope tables
            # — rare relative to the hot path, never worth a dispatch.
            deltas, _ = minute_deltas_host(
                m.timestamp for i, m in enumerate(messages) if xor_mask[i]
            )
            db.run_many(
                _INSERT_MESSAGE,
                [(m.timestamp, m.table, m.row, m.column, m.value)
                 for m in messages],
            )
            n_xor = _mask_sum(xor_mask)
            entry.count(ledger.APPLY_INSERTED, len(upserts))
            entry.count(ledger.APPLY_LOSING, n_xor - len(upserts))
            entry.count(ledger.APPLY_DUPLICATE, len(messages) - n_xor)
            entry.count(ledger.APPLY_DEFERRED_MAT, len(messages))
            tree = apply_prefix_xors(merkle_tree, deltas)
        entry.commit()
        return tree
    except BaseException:
        entry.abort()
        ledger.count(ledger.APPLY_INGRESS, len(messages))
        ledger.count(ledger.APPLY_REJECTED, len(messages))
        raise


class ChunkedApplyError(Exception):
    """A chunk failed after earlier chunks committed. `partial_tree`
    reflects every committed chunk and `applied` counts committed
    messages — the caller MUST persist `partial_tree` (e.g. to the
    clock) or the digest permanently diverges from the stored rows."""

    def __init__(self, partial_tree: dict, applied: int, cause: BaseException):
        super().__init__(f"chunked apply failed after {applied} messages: {cause}")
        self.partial_tree = partial_tree
        self.applied = applied
        self.__cause__ = cause


def apply_messages_chunked(
    db: PySqliteDatabase,
    merkle_tree: dict,
    messages: Sequence[CrdtMessage],
    chunk_size: int = 1 << 20,
    planner=None,
    on_chunk=None,
    changes=None,
) -> dict:
    """Blockwise apply for batches too large for one device dispatch.

    The LWW contraction is associative: each chunk's winners become the
    next chunk's stored winners (fetched fresh from SQLite), so folding
    chunks left-to-right is state-identical to one giant batch — the
    "blockwise accumulation over message chunks" strategy for batches
    exceeding HBM (SURVEY.md §5 long-context analog). Each chunk commits
    its own transaction, bounding both device and transaction memory.

    `on_chunk(tree, applied_count)` runs INSIDE the chunk's transaction,
    so the chunk's rows and whatever the callback persists (typically
    the clock with the updated tree) commit atomically — a crash can
    never leave committed __message rows whose hashes missed the
    persisted tree, which would be a permanent digest divergence (the
    re-received winner XORs with xor=false and its hash could never
    re-enter the tree). If a chunk or its callback fails, that whole
    chunk rolls back and `ChunkedApplyError` carries the tree and count
    covering the chunks that DID commit (unlike `apply_messages`,
    failure here is not all-or-nothing — earlier chunks stay committed).
    """
    applied = 0
    for i in range(0, len(messages), chunk_size):
        chunk = messages[i : i + chunk_size]
        try:
            with db.transaction():
                next_tree = apply_messages(db, merkle_tree, chunk, planner,
                                           changes=changes)
                if on_chunk is not None:
                    on_chunk(next_tree, applied + len(chunk))
        except Exception as e:
            # The inner apply_messages only fires the planner's failure
            # hook for exceptions raised inside itself; its transaction
            # JOINS this outer scope, so an `on_chunk` failure rolls
            # the chunk back here AFTER apply returned — the planner
            # (HBM winner cache) must still resynchronize or it keeps
            # phantom winners SQLite never committed (permanent digest
            # divergence on redelivery). Firing twice is harmless: the
            # hook is an idempotent reset.
            _notify_plan_failure(planner or plan_batch)
            raise ChunkedApplyError(merkle_tree, applied, e) from e
        merkle_tree = next_tree
        applied += len(chunk)
    return merkle_tree
