"""The changed-set contract for incremental query invalidation (ISSUE 9).

Every apply path reports the (table, rowId) pairs it touched into a
`ChangedSet`; the worker gates subscribed-query re-execution on it
(`runtime/worker.py::_query` × `storage/deps.py`). The contract is
deliberately asymmetric: the fast path may only ever OVER-approximate —
"don't know" escalates (`mark_unknown`, or a per-table row-set
overflowing to all-rows) so correctness never depends on precision.
Recording happens at the APPLY level (`storage/apply.py`), independent
of which planner produced the plan (device kernel, winner cache,
`merge._host_fallback`, hot-owner shard, host oracle): whatever route a
batch takes, the rows it can touch are exactly its messages' (table,
row) pairs, plus `__message` and — for typed CRDT cells — the
`__crdt_*` state tables, which are recorded where the route knows them.
"""

from __future__ import annotations

from typing import Dict, Optional, Set

# A per-table row set larger than this degrades to "all rows of the
# table" (None): bounds gate-time set intersections and ChangedSet
# memory for huge receive batches, at worst costing re-execution of
# queries row-filtered on that table.
ROW_SET_CAP = 4096

_MISSING = object()


class ChangedSet:
    """Tables and rows touched by one or more applies.

    `rows[table]` is a set of rowIds, or None = "any/unknown rows in
    this table". `conservative=True` means the whole write is
    unattributable — every gated query must re-execute.
    """

    __slots__ = ("tables", "rows", "conservative")

    def __init__(self):
        self.tables: Set[str] = set()
        self.rows: Dict[str, Optional[set]] = {}
        self.conservative = False

    def __bool__(self) -> bool:
        return self.conservative or bool(self.tables)

    def add_cell(self, table: str, row: str) -> None:
        # Lower-cased key: SQLite resolves identifiers case-insensitively,
        # so a wire message's "Todo" writes into the table deps.py knows
        # as "todo" — both sides of the contract fold to one key (folding
        # distinct non-ASCII-case tables together only over-invalidates).
        table = table.lower()
        self.tables.add(table)
        s = self.rows.get(table, _MISSING)
        if s is None:
            return
        if s is _MISSING:
            self.rows[table] = {row}
        elif len(s) >= ROW_SET_CAP:
            self.rows[table] = None
        else:
            s.add(row)

    def add_table(self, table: str) -> None:
        """Table touched with unknown rows."""
        table = table.lower()
        self.tables.add(table)
        self.rows[table] = None

    def mark_unknown(self) -> None:
        """Escalate to conservative full invalidation."""
        self.conservative = True

    def merge(self, other: "ChangedSet") -> None:
        self.conservative = self.conservative or other.conservative
        self.tables |= other.tables
        for t, s in other.rows.items():
            if s is None:
                self.rows[t] = None
                continue
            mine = self.rows.get(t, _MISSING)
            if mine is None:
                continue
            if mine is _MISSING:
                self.rows[t] = set(s)
            else:
                mine |= s
                if len(mine) > ROW_SET_CAP:
                    self.rows[t] = None


def record_batch(changes: Optional[ChangedSet], messages) -> None:
    """Record one apply batch's touched rows: the (table, row) of every
    message, plus `__message` (row-unknown — its rowids are timestamps,
    not app ids). Accepts CrdtMessage sequences and PackedReceive
    columnar batches; anything else — or any failure — escalates to
    conservative."""
    if changes is None:
        return
    try:
        changes.add_table("__message")
        from evolu_tpu.core.packed import PackedReceive

        if isinstance(messages, PackedReceive):
            _ids, cells = messages.touched_cells()
            for table, row, _col in cells:
                changes.add_cell(table, row)
        else:
            for m in messages:
                changes.add_cell(m.table, m.row)
    except Exception:  # noqa: BLE001 - don't know ⇒ full invalidation
        changes.mark_unknown()


def record_typed_tables(changes: Optional[ChangedSet]) -> None:
    """A batch carried typed CRDT ops: their materializers also write
    the `__crdt_*` merge-state tables (rows unknowable here)."""
    if changes is None:
        return
    changes.add_table("__crdt_counter")
    changes.add_table("__crdt_set")
    changes.add_table("__crdt_kill")
    changes.add_table("__crdt_list")
    changes.add_table("__crdt_list_kill")
