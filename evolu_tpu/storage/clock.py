"""CrdtClock persistence in the single-row __clock table.

Reference: packages/evolu/src/readClock.ts, updateClock.ts. The clock
row is the replica's resumable sync cursor: its timestamp is the HLC
high-water mark, its merkleTree the digest of all stored messages.
"""

from __future__ import annotations

from evolu_tpu.core.merkle import merkle_tree_from_string, merkle_tree_to_string
from evolu_tpu.core.timestamp import timestamp_from_string, timestamp_to_string
from evolu_tpu.core.types import CrdtClock
from evolu_tpu.storage.sqlite import PySqliteDatabase


def read_clock(db: PySqliteDatabase) -> CrdtClock:
    """readClock.ts:15-27."""
    row = db.exec_sql_query('SELECT "timestamp", "merkleTree" FROM "__clock" LIMIT 1')[0]
    return CrdtClock(
        timestamp=timestamp_from_string(row["timestamp"]),
        merkle_tree=merkle_tree_from_string(row["merkleTree"]),
    )


def update_clock(db: PySqliteDatabase, clock: CrdtClock) -> None:
    """updateClock.ts:8-26."""
    db.run(
        'UPDATE "__clock" SET "timestamp" = ?, "merkleTree" = ?',
        (timestamp_to_string(clock.timestamp), merkle_tree_to_string(clock.merkle_tree)),
    )
