"""CrdtClock persistence in the single-row __clock table.

Reference: packages/evolu/src/readClock.ts, updateClock.ts. The clock
row is the replica's resumable sync cursor: its timestamp is the HLC
high-water mark, its merkleTree the digest of all stored messages.
"""

from __future__ import annotations

from evolu_tpu.core.merkle import merkle_tree_from_string, merkle_tree_to_string
from evolu_tpu.core.timestamp import timestamp_from_string, timestamp_to_string
from evolu_tpu.core.types import CrdtClock
from evolu_tpu.storage.sqlite import PySqliteDatabase
from evolu_tpu.utils.log import log


def read_clock(db: PySqliteDatabase) -> CrdtClock:
    """readClock.ts:15-27 (logged under clock:read, readClock.ts:26)."""
    row = db.exec_sql_query('SELECT "timestamp", "merkleTree" FROM "__clock" LIMIT 1')[0]
    clock = CrdtClock(
        timestamp=timestamp_from_string(row["timestamp"]),
        merkle_tree=merkle_tree_from_string(row["merkleTree"]),
    )
    log("clock:read", timestamp=row["timestamp"])
    return clock


def update_clock(db: PySqliteDatabase, clock: CrdtClock) -> None:
    """updateClock.ts:8-26 (logged under clock:update, updateClock.ts:24)."""
    ts = timestamp_to_string(clock.timestamp)
    db.run(
        'UPDATE "__clock" SET "timestamp" = ?, "merkleTree" = ?',
        (ts, merkle_tree_to_string(clock.merkle_tree)),
    )
    log("clock:update", timestamp=ts)
