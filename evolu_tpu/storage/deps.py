"""Query dependency extraction for incremental invalidation (ISSUE 9).

The reactive loop re-runs every subscribed query after every mutation
(reference query.ts:31-76). To gate that loop on the merge planner's
changed-set, each subscribed query needs a *sound over-approximation*
of what it reads:

- **Tables** come from SQLite's own compiled program: `EXPLAIN` lists
  every btree cursor the statement opens (`OpenRead`/`ReopenIdx`, with
  the root page in p2), and `sqlite_master.rootpage → tbl_name` maps
  index cursors back to their owning tables — covering indexes, join
  flattening, subqueries and `EXISTS` all fall out of the bytecode for
  free, which a regex over the SQL never could. Anything the walk
  cannot prove (virtual tables, temp/schema cursors, unmappable root
  pages, EXPLAIN itself failing) degrades to `tables=None` = "don't
  know" = the caller must always re-execute. Non-deterministic SQL
  (`random()`, `'now'`, `CURRENT_*`, …) also degrades: its result can
  change with NO table write, so it must never be gated.

- **Row filters** are extracted only where provably sound: a top-level
  AND-conjunct of the WHERE clause of the exact shape `"id" = ?` /
  `"id" IN (?, …)` (optionally table-qualified) restricts every row
  the query can EVER depend on to those bound ids — regardless of
  predicates, aggregates, limits, or new-row inserts. NOTE this is
  deliberately NOT the "rowIds captured from the last result" sketch:
  a write can flip predicate membership for a row *outside* the last
  result (e.g. toggling `isDeleted`), so result-captured row sets are
  unsound. A static id-constraint is the shape that is sound by
  construction, and it is exactly the per-row detail-view subscription
  that dominates at 10^4+ live subscriptions.

Consumed by `runtime/worker.py::DbWorker._query`; the changed-set side
of the contract lives in `storage/changes.py`.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Mapping, Optional, Sequence

# Cursor-opening opcodes whose p2 is a root page in database p3.
_OPEN_OPCODES = frozenset(("OpenRead", "OpenWrite", "ReopenIdx"))
# Virtual-table opcodes: the cursor has no root page; give up.
_VTAB_OPCODES = frozenset(("VOpen", "VFilter", "VUpdate", "VColumn"))

# Substrings whose presence means the result can change without any
# table write (or depends on connection state). Lower-cased match;
# conservative false positives only cost gating for that one query.
_NONDETERMINISTIC = (
    "random",          # random(), randomblob()
    "'now'",           # datetime('now'), julianday('now'), ...
    "current_",        # CURRENT_TIMESTAMP / CURRENT_DATE / CURRENT_TIME
    "changes(",        # changes(), total_changes()
    "last_insert_rowid",
    # Zero-argument date/time functions default to 'now' (review
    # finding): datetime() etc. are clock-dependent with no table
    # write. "time(" also covers "datetime("; strftime('%s') defaults
    # to now in recent SQLite.
    "date(",
    "time(",
    "julianday(",
    "unixepoch(",
    "strftime(",
)

# Internal tables written OUTSIDE the apply layer are invisible to the
# changed-set contract (review finding: `update_clock` UPDATEs
# "__clock" on every Send/Receive with no record_batch in sight).
# Only the tables the contract explicitly records may be gated;
# reading any other "__" table means "always re-execute".
_RECORDED_INTERNAL = frozenset(
    ("__message", "__crdt_counter", "__crdt_set", "__crdt_kill",
     "__crdt_list", "__crdt_list_kill"))


@dataclass(frozen=True)
class QueryDeps:
    """What a compiled query reads. `tables=None` means unknown —
    conservative full invalidation (the query always re-executes).
    `row_filters[table]` is the frozenset of id values the query's
    result can possibly depend on in that table; a table absent from
    the mapping has no such bound (any row write forces re-execution).
    """

    tables: Optional[FrozenSet[str]]
    row_filters: Mapping[str, FrozenSet] = field(default_factory=dict)


UNKNOWN_DEPS = QueryDeps(None, {})


def query_dependencies(db, sql: str, parameters: Sequence = ()) -> QueryDeps:
    """Dependencies of `sql` against `db`'s current schema. Never
    raises: every failure mode (including SQL that would error at
    execution) returns UNKNOWN_DEPS and lets the real execution own
    the error surface."""
    try:
        tables = _explain_read_tables(db, sql, parameters)
    except Exception:  # noqa: BLE001 - any failure = don't know
        return UNKNOWN_DEPS
    if tables is None:
        return UNKNOWN_DEPS
    if any(t.startswith("__") and t not in _RECORDED_INTERNAL
           for t in tables):
        return UNKNOWN_DEPS
    low = sql.lower()
    if any(tok in low for tok in _NONDETERMINISTIC):
        return UNKNOWN_DEPS
    try:
        filters = _id_row_filters(sql, parameters, tables)
    except Exception:  # noqa: BLE001 - row filters are an optimization
        filters = {}
    return QueryDeps(frozenset(tables), filters)


def _root_map(db) -> dict:
    """rootpage → owning table, for both table and index btrees.
    Cached on the connection keyed by `PRAGMA schema_version` (bumps on
    any DDL), so building the dependency index for 10^4 subscriptions
    does not rescan sqlite_master 10^4 times."""
    version = db.exec_sql_query("PRAGMA schema_version")[0]["schema_version"]
    cached = getattr(db, "_deps_root_cache", None)
    if cached is not None and cached[0] == version:
        return cached[1]
    # Lower-cased names: the ChangedSet side of the contract records
    # wire-verbatim table names folded the same way (SQLite identifier
    # resolution is case-insensitive, so "Todo" on the wire writes the
    # table created as "todo" — unfolded names would look disjoint).
    root_map = {
        int(r["rootpage"]): r["tbl_name"].lower()
        for r in db.exec_sql_query(
            'SELECT "tbl_name", "rootpage" FROM "sqlite_master" '
            'WHERE "rootpage" > 0'
        )
    }
    try:
        db._deps_root_cache = (version, root_map)
    except AttributeError:  # __slots__ backend: stay uncached
        pass
    return root_map


def _explain_read_tables(db, sql, parameters) -> Optional[set]:
    """Tables read by the compiled statement, via the VDBE listing.
    None = unverifiable (virtual/temp/schema cursor or unmapped root
    page)."""
    rows = db.exec_sql_query("EXPLAIN " + sql, parameters)
    root_map = _root_map(db)
    tables: set = set()
    for r in rows:
        op = r.get("opcode")
        if op in _VTAB_OPCODES:
            return None
        if op not in _OPEN_OPCODES:
            continue
        if int(r.get("p3") or 0) != 0:
            return None  # temp or attached database: out of scope
        root = int(r.get("p2") or 0)
        name = root_map.get(root)
        if name is None:
            return None  # sqlite_master itself (root 1) or unknown
        tables.add(name)
    return tables


# -- row filters --------------------------------------------------------

_WHERE_END_KEYWORDS = (" group by ", " order by ", " having ", " limit ",
                       " offset ", " window ")
_COMPOUND_KEYWORDS = (" union ", " intersect ", " except ")

_ID_CONJUNCT = re.compile(
    r'^(?:"((?:[^"]|"")+)"\s*\.\s*)?"id"\s+(?:=|in)\s+(.*)$',
    re.IGNORECASE | re.DOTALL,
)
_PLACEHOLDER = re.compile(r"^\?$")
_IN_PLACEHOLDERS = re.compile(r"^\(\s*\?(?:\s*,\s*\?)*\s*\)$")


_WORD_CHARS = frozenset("abcdefghijklmnopqrstuvwxyz0123456789_")


def _keyword_at(low: str, i: int, kw: str) -> bool:
    """Token-wise keyword match. SQLite tokenizes `x=? or"b"=?` with no
    surrounding spaces, so matching ' or ' with mandatory spaces misses
    real operators (review finding)."""
    if not low.startswith(kw, i):
        return False
    if i > 0 and low[i - 1] in _WORD_CHARS:
        return False
    j = i + len(kw)
    return j >= len(low) or low[j] not in _WORD_CHARS


def _top_level_conjuncts(where: str):
    """(start, end) spans of the top-level AND conjuncts of a WHERE
    body, or None when no conjunct is provably top-level. AND binds
    tighter than OR, so in `a OR b AND "id" = ?` the id equality is a
    conjunct of the OR's right arm, not of the WHERE (review finding:
    a write to a row matching `a` changed the result while the gate
    skipped re-execution) — ANY depth-0 OR therefore bails, mirroring
    the _COMPOUND_KEYWORDS bail. Quoted identifiers are skipped so
    their content can neither hide a keyword nor skew paren depth;
    unbalanced parens or an unterminated quote (also what the
    WHERE-end trim leaves when it cut inside one) bail too."""
    low = where.lower()
    if len(low) != len(where):  # non-ASCII case folding moved offsets
        return None
    n = len(low)
    splits = []
    depth = 0
    i = 0
    while i < n:
        ch = low[i]
        if ch == '"':
            j = low.find('"', i + 1)
            while j != -1 and low.startswith('""', j):
                j = low.find('"', j + 2)
            if j == -1:
                return None
            i = j + 1
            continue
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth < 0:
                return None
        elif depth == 0:
            if _keyword_at(low, i, "or"):
                return None
            if _keyword_at(low, i, "between"):
                # BETWEEN's AND is an operand separator, not a conjunct
                # boundary: `"a" BETWEEN ? AND "id" = ?` parses as
                # `("a" BETWEEN ? AND "id") = ?` (review finding —
                # sound today only via the str-only value screen).
                return None
            if _keyword_at(low, i, "and"):
                splits.append(i)
                i += 3
                continue
        i += 1
    if depth != 0:
        return None
    spans = []
    prev = 0
    for s in splits:
        spans.append((prev, s))
        prev = s + 3
    spans.append((prev, n))
    return spans


def _find_depth0(low: str, needle: str, start: int = 0) -> int:
    """First depth-0 occurrence of `needle` in the lower-cased SQL."""
    depth = 0
    i = 0
    n = len(low)
    while i < n:
        ch = low[i]
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif depth == 0 and i >= start and low.startswith(needle, i):
            return i
        i += 1
    return -1


def _from_source_count(low: str, where_pos: int, table: str) -> int:
    """How many times `table` appears as a SOURCE (not a column
    qualifier) in the FROM clause. A self-join opens a second,
    UNCONSTRAINED cursor over the same table — `"t"."id" = ?` then
    bounds only one of them (review finding) — so an id filter is
    sound only when the table is a source exactly once."""
    fs = _find_depth0(low, " from ")
    if fs < 0 or fs > where_pos:
        return 0
    seg = low[fs + 6 : where_pos]
    t = table.lower()
    pat = re.compile(
        '"%s"|\\b%s\\b' % (re.escape(t.replace('"', '""')), re.escape(t)))
    n = 0
    for m in pat.finditer(seg):
        if seg[m.end():].lstrip().startswith("."):
            continue  # qualifier use ("t"."col"), not a source
        n += 1
    return n


def _id_row_filters(sql: str, parameters: Sequence, tables) -> Dict[str, FrozenSet]:
    """`{table: frozenset(ids)}` for top-level `"id" = ?` / `"id" IN
    (?, …)` conjuncts. Empty dict whenever anything is uncertain."""
    if ("'" in sql or '"?"' in sql or "`" in sql or "[" in sql
            or "--" in sql or "/*" in sql):
        # String literals could hide '?' (indexing unmappable); `...`
        # and [...] alternative identifier quoting, and -- or /* ... */
        # comments, could hide keywords or skew the paren/quote scan
        # (a '(' or '"' inside a comment would swallow a real depth-0
        # OR). Give up. ("--" also matches `a - -b` arithmetic: only
        # costs that query its row filter.)
        return {}
    if sql.count("?") != len(parameters):
        return {}  # numbered/named placeholders: positions unmappable
    low = sql.lower()
    if low.count("select") > 1 or "exists" in low:
        # A subquery/EXISTS can read the SAME table through a second,
        # UNCONSTRAINED cursor (e.g. a scalar `(SELECT count(*) FROM
        # "t")` next to `FROM "t" WHERE "id" = ?`) — the id conjunct
        # then bounds only the outer cursor, not the result. Table
        # gating still applies; row filters give up.
        return {}
    if any(_find_depth0(low, k) >= 0 for k in _COMPOUND_KEYWORDS):
        return {}
    ws = _find_depth0(low, " where ")
    if ws < 0:
        return {}
    body_start = ws + len(" where ")
    end = len(sql)
    for kw in _WHERE_END_KEYWORDS:
        p = _find_depth0(low, kw, body_start)
        if 0 <= p < end:
            end = p
    where = sql[body_start:end]
    spans = _top_level_conjuncts(where)
    if spans is None:
        return {}  # depth-0 OR / unparseable structure: no conjunct is sound
    filters: Dict[str, FrozenSet] = {}
    for cstart, cend in spans:
        conj = where[cstart:cend].strip()
        m = _ID_CONJUNCT.match(conj)
        if not m:
            continue
        qualifier, rhs = m.group(1), m.group(2).strip()
        if _PLACEHOLDER.match(rhs):
            count = 1
        elif _IN_PLACEHOLDERS.match(rhs):
            count = rhs.count("?")
        else:
            continue
        if qualifier is not None:
            t = qualifier.replace('""', '"').lower()
            if t not in tables:
                continue  # alias or unknown: cannot attribute soundly
        elif len(tables) == 1:
            t = next(iter(tables))
        else:
            continue  # unqualified id in a join: ambiguous attribution
        if _from_source_count(low, ws, t) != 1:
            continue  # self-join (or unparseable FROM): second cursor
        k = sql[: body_start + cstart].count("?")
        values = frozenset(parameters[k : k + count])
        if any(not isinstance(v, str) for v in values):
            # SQLite's TEXT affinity coerces a non-str bound value at
            # comparison time (id = 5 matches the row whose id is '5'),
            # but the gate compares Python sets against the changed-set's
            # str rowIds — frozenset({5}) would be "disjoint" from
            # {'5'} and wrongly skip. Only str values are sound.
            continue
        # Multiple id-conjuncts on one table only ever narrow further;
        # keep the smallest set.
        prev = filters.get(t)
        if prev is None or len(values) < len(prev):
            filters[t] = values
    return filters
