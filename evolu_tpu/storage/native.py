"""ctypes binding for the C++ SQLite host layer (native/evolu_host.cpp).

`CppSqliteDatabase` implements the same backend boundary as
`PySqliteDatabase` (the reference's `Database` interface,
types.ts:162-176) over our C++ library, which drives the real SQLite C
API directly. The merge hot path — the reference's per-message
applyMessages loop — runs as ONE C call per batch
(`apply_sequential` / `apply_planned`), with winner lookups, app-table
upserts and `__message` inserts all inside C++ (SURVEY.md §2.14, §7
step 3).

The library is built on demand with `make` (g++ + libsqlite3.so.0 are
baked into the image); if the build is impossible the loader returns
None and callers fall back to the Python backend — behavior, end
state, and error surface are identical either way (property-tested in
tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import struct
import threading

import numpy as np
from contextlib import contextmanager
from typing import Iterable, List, Optional, Sequence, Tuple

from evolu_tpu.core.types import NonCanonicalStoreError, UnknownError
from evolu_tpu.utils.native_loader import load_native_library

_SQLITE_ROW = 100
_SQLITE_DONE = 101

# column types
_T_INT, _T_FLOAT, _T_TEXT, _T_BLOB, _T_NULL = 1, 2, 3, 4, 5


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    p, i, i64, d, s, u8p, i32p, i64p, dp = (
        c.c_void_p, c.c_int, c.c_int64, c.c_double, c.c_char_p,
        c.POINTER(c.c_uint8), c.POINTER(c.c_int32), c.POINTER(c.c_int64),
        c.POINTER(c.c_double),
    )
    sp = c.POINTER(s)
    lib.eh_open.restype = p
    lib.eh_open.argtypes = [s]
    lib.eh_close.argtypes = [p]
    lib.eh_errmsg.restype = s
    lib.eh_errmsg.argtypes = [p]
    lib.eh_exec.argtypes = [p, s]
    lib.eh_changes.argtypes = [p]
    lib.eh_total_changes.argtypes = [p]
    lib.eh_prepare.restype = p
    lib.eh_prepare.argtypes = [p, s]
    lib.eh_prepare_single.restype = p
    lib.eh_prepare_single.argtypes = [p, s, c.POINTER(c.c_int)]
    lib.eh_finalize.argtypes = [p]
    lib.eh_step.argtypes = [p]
    lib.eh_reset.argtypes = [p]
    lib.eh_bind.argtypes = [p, i, i, i64, d, s, i]
    lib.eh_column_count.argtypes = [p]
    lib.eh_column_name.restype = s
    lib.eh_column_name.argtypes = [p, i]
    lib.eh_column_type.argtypes = [p, i]
    lib.eh_column_int64.restype = i64
    lib.eh_column_int64.argtypes = [p, i]
    lib.eh_column_double.restype = d
    lib.eh_column_double.argtypes = [p, i]
    lib.eh_column_text.restype = p  # read via column_bytes + string_at (NUL-safe)
    lib.eh_column_text.argtypes = [p, i]
    lib.eh_column_blob.restype = p
    lib.eh_column_blob.argtypes = [p, i]
    lib.eh_column_bytes.argtypes = [p, i]
    lib.eh_fetch_winners.argtypes = [p, i64, sp, sp, sp, c.c_char_p, i64]
    lib.eh_apply_sequential.argtypes = [p, i64, sp, sp, sp, sp, i32p, i64p, dp, sp, i32p, u8p]
    lib.eh_apply_planned_packed.argtypes = [
        p, i64, s, i32p, s, i32p, s, i32p, s, i32p, i32p, i64p, dp, s, i32p, u8p,
    ]
    lib.eh_apply_planned_cells.argtypes = [
        p, i64, s, i64, s, i32p, i32p, u8p, i64p, dp, s, i32p, u8p,
    ]
    lib.eh_relay_insert.argtypes = [p, i64, sp, sp, sp, i32p, u8p]
    lib.eh_relay_insert_packed.argtypes = [p, i64, sp, i64p, s, s, i32p, u8p]
    lib.eh_parse_timestamps.argtypes = [s, i64, i64p, i32p, c.POINTER(c.c_uint64), u8p]
    lib.eh_run_many_tb.argtypes = [p, s, i64, c.c_int32, sp, i32p, i32p]
    lib.eh_get_messages.argtypes = [
        p, s, c.c_int32, s, s, c.c_int32,
        c.POINTER(c.c_char_p), c.POINTER(p), c.POINTER(i32p), c.POINTER(i64),
    ]
    lib.eh_free.argtypes = [p]
    lib.eh_exec_packed.argtypes = [p, c.POINTER(p), i64p, i64p, c.POINTER(i64p)]
    lib.eh_get_messages_wire.argtypes = [
        p, s, c.c_int32, s, s, c.c_int32, c.POINTER(p), i64p, i64p,
    ]
    if hasattr(lib, "eh_snapshot_rows"):  # stale pre-r7 .so lacks it
        lib.eh_snapshot_rows.argtypes = [p, c.POINTER(p), i64p, i64p, i64p]
    return lib


def load_library() -> Optional[ctypes.CDLL]:
    """The shared library, building it on first use; None if unavailable."""
    return load_native_library("libevolu_host.so", _configure)


def native_available() -> bool:
    return load_library() is not None


_PACK_I32 = struct.Struct("<i")
_PACK_I64 = struct.Struct("<q")
_PACK_F64 = struct.Struct("<d")
_PACK_U32 = struct.Struct("<I")


def _parse_packed_header(raw: bytes):
    """→ (column names, position after the header)."""
    (ncols,) = _PACK_I32.unpack_from(raw, 0)
    pos = 4
    cols = []
    for _ in range(ncols):
        (n,) = _PACK_I32.unpack_from(raw, pos)
        pos += 4
        cols.append(raw[pos : pos + n].decode("utf-8"))
        pos += n
    return cols, pos


def _parse_packed_row(raw: bytes, cols, pos: int):
    """One row at `pos` → (dict, next position)."""
    vals = []
    for _ in range(len(cols)):
        t = raw[pos]
        pos += 1
        if t == 1:
            (v,) = _PACK_I64.unpack_from(raw, pos)
            pos += 8
        elif t == 2:
            (v,) = _PACK_F64.unpack_from(raw, pos)
            pos += 8
        elif t == 3:
            (n,) = _PACK_U32.unpack_from(raw, pos)
            pos += 4
            v = raw[pos : pos + n].decode("utf-8")
            pos += n
        elif t == 4:
            (n,) = _PACK_U32.unpack_from(raw, pos)
            pos += 4
            v = raw[pos : pos + n]
            pos += n
        else:
            v = None
        vals.append(v)
    return dict(zip(cols, vals)), pos


def unpack_packed_rows(
    raw: bytes, start: Optional[int] = None, end: Optional[int] = None
) -> List[dict]:
    """`eh_exec_packed` buffer → list of row dicts (the
    `exec_sql_query` contract). Layout documented at the C function.
    `start`/`end` optionally bound the ROW region (byte offsets from
    the per-row offsets array) for partial unpacks."""
    cols, pos = _parse_packed_header(raw)
    if start is not None:
        pos = start
    stop = len(raw) if end is None else end
    rows: List[dict] = []
    while pos < stop:
        d, pos = _parse_packed_row(raw, cols, pos)
        rows.append(d)
    return rows


def unpack_changed_rows(raw, offs, prev_raw, prev_offs, prev_rows) -> List[dict]:
    """Row-granular re-unpack for the reactive query loop (r5,
    VERDICT r4 next #6): the full unpack was 73% of a changed 10k-row
    query's cost while typically only a few rows changed. Rows whose
    packed bytes are unchanged REUSE the previous result's dict
    objects (identity-stable — the differ can shortcut on `is`); only
    changed/new rows parse.

    Alignment: the longest common row PREFIX and SUFFIX by row LENGTH
    (vectorized over the offset arrays), then ONE xor pass +
    `np.add.reduceat` per region decides content equality per row —
    in-place edits, appends, and tail deletions all localize, and the
    residual middle window unpacks fresh. Result is always EXACTLY
    `unpack_packed_rows(raw)` (property-pinned)."""
    n_new = len(offs) - 1
    n_old = len(prev_offs) - 1
    if n_old != len(prev_rows) or n_new == 0 or n_old == 0:
        return unpack_packed_rows(raw)
    h = int(offs[0])
    if h != int(prev_offs[0]) or raw[:h] != prev_raw[:h]:
        return unpack_packed_rows(raw)  # schema/header changed
    len_new = np.diff(offs)
    len_old = np.diff(prev_offs)
    m = min(n_new, n_old)
    neq = len_new[:m] != len_old[:m]
    p = int(np.argmax(neq)) if neq.any() else m
    rev_neq = len_new[n_new - m :][::-1] != len_old[n_old - m :][::-1]
    s = int(np.argmax(rev_neq)) if rev_neq.any() else m
    s = min(s, m - p)

    a = np.frombuffer(raw, np.uint8)
    b = np.frombuffer(prev_raw, np.uint8)

    def region_changed(starts_new, span_a, span_b):
        """Per-row any-byte-differs over an aligned equal-length region."""
        x = a[span_a] != b[span_b]
        if x.size == 0:
            return np.zeros(len(starts_new), bool)
        return np.add.reduceat(x, starts_new) > 0

    changed_pre = region_changed(
        (offs[:p] - h).astype(np.int64),
        slice(h, int(offs[p])), slice(h, int(prev_offs[p])),
    ) if p else np.zeros(0, bool)
    if s:
        ns, os_ = int(offs[n_new - s]), int(prev_offs[n_old - s])
        changed_suf = region_changed(
            (offs[n_new - s : n_new] - ns).astype(np.int64),
            slice(ns, len(raw)), slice(os_, len(prev_raw)),
        )
    else:
        changed_suf = np.zeros(0, bool)

    cols, _hp = _parse_packed_header(raw)
    rows: List[dict] = []
    for i in range(p):
        if changed_pre[i]:
            d, _ = _parse_packed_row(raw, cols, int(offs[i]))
            rows.append(d)
        else:
            rows.append(prev_rows[i])
    rows.extend(unpack_packed_rows(raw, start=int(offs[p]), end=int(offs[n_new - s])))
    for k in range(s):
        if changed_suf[k]:
            d, _ = _parse_packed_row(raw, cols, int(offs[n_new - s + k]))
            rows.append(d)
        else:
            rows.append(prev_rows[n_old - s + k])
    return rows


def _encode_value(v) -> Tuple[int, int, float, Optional[bytes], int]:
    """Python value → (kind, int64, double, bytes, blob_len)."""
    if v is None:
        return 0, 0, 0.0, None, 0
    if isinstance(v, bool):
        return 1, int(v), 0.0, None, 0
    if isinstance(v, int):
        return 1, v, 0.0, None, 0
    if isinstance(v, float):
        return 2, 0, v, None, 0
    if isinstance(v, bytes):
        return 4, 0, 0.0, v, len(v)
    enc = str(v).encode("utf-8")
    return 3, 0, 0.0, enc, len(enc)


def _columnar_values(values) -> Tuple:
    n = len(values)
    kinds = (ctypes.c_int32 * n)()
    ivals = (ctypes.c_int64 * n)()
    dvals = (ctypes.c_double * n)()
    svals = (ctypes.c_char_p * n)()
    blens = (ctypes.c_int32 * n)()
    for j, v in enumerate(values):
        k, iv, dv, sv, bl = _encode_value(v)
        kinds[j], ivals[j], dvals[j], svals[j], blens[j] = k, iv, dv, sv, bl
    return kinds, ivals, dvals, svals, blens


def _str_array(items: Sequence[str]):
    arr = (ctypes.c_char_p * len(items))()
    for j, x in enumerate(items):
        arr[j] = x.encode("utf-8") if isinstance(x, str) else x
    return arr


class CppSqliteDatabase:
    """Single-writer SQLite handle over the C++ host layer.

    Drop-in for `PySqliteDatabase`: exec / exec_script / exec_sql_query /
    run / run_many / changes / transaction / close, plus the batched
    native hot paths (`apply_sequential`, `apply_planned`,
    `fetch_winners`, `relay_insert`).
    """

    def __init__(self, path: str = ":memory:"):
        lib = load_library()
        if lib is None:
            raise UnknownError("native host library unavailable")
        self._lib = lib
        self._db = lib.eh_open(path.encode("utf-8"))
        if not self._db:
            raise UnknownError(f"cannot open database {path!r}")
        self._lock = threading.RLock()
        self._in_txn = False
        self.path = path
        self._begin_sql = b"BEGIN"

    # -- internals --

    def _check_open(self) -> None:
        if not self._db:
            raise UnknownError("Cannot operate on a closed database.")

    def _err(self) -> UnknownError:
        msg = self._lib.eh_errmsg(self._db)
        return UnknownError(msg.decode("utf-8", "replace") if msg else "sqlite error")

    def _read_row(self, st) -> Tuple:
        lib = self._lib
        ncol = lib.eh_column_count(st)
        out = []
        for i in range(ncol):
            t = lib.eh_column_type(st, i)
            if t == _T_INT:
                out.append(lib.eh_column_int64(st, i))
            elif t == _T_FLOAT:
                out.append(lib.eh_column_double(st, i))
            elif t == _T_TEXT:
                nb = lib.eh_column_bytes(st, i)
                ptr = lib.eh_column_text(st, i)
                out.append(ctypes.string_at(ptr, nb).decode("utf-8") if ptr else "")
            elif t == _T_BLOB:
                nb = lib.eh_column_bytes(st, i)
                ptr = lib.eh_column_blob(st, i)
                out.append(ctypes.string_at(ptr, nb) if ptr else b"")
            else:
                out.append(None)
        return tuple(out)

    def _execute(self, sql: str, parameters: Sequence = ()) -> Tuple[List[Tuple], List[str]]:
        lib = self._lib
        self._check_open()
        tail = ctypes.c_int(0)
        st = lib.eh_prepare_single(self._db, sql.encode("utf-8"), ctypes.byref(tail))
        if not st:
            raise self._err()
        if tail.value:
            lib.eh_finalize(st)
            raise UnknownError("You can only execute one statement at a time.")
        try:
            for j, v in enumerate(parameters):
                k, iv, dv, sv, bl = _encode_value(v)
                if lib.eh_bind(st, j + 1, k, iv, dv, sv, bl) != 0:
                    raise self._err()
            cols: List[str] = []
            rows: List[Tuple] = []
            first = True
            while True:
                rc = lib.eh_step(st)
                if rc == _SQLITE_ROW:
                    if first:
                        cols = [
                            (lib.eh_column_name(st, i) or b"").decode("utf-8")
                            for i in range(lib.eh_column_count(st))
                        ]
                        first = False
                    rows.append(self._read_row(st))
                elif rc == _SQLITE_DONE:
                    if first:
                        cols = [
                            (lib.eh_column_name(st, i) or b"").decode("utf-8")
                            for i in range(lib.eh_column_count(st))
                        ]
                    break
                else:
                    raise self._err()
            return rows, cols
        finally:
            lib.eh_finalize(st)

    # -- Database interface (types.ts:162-176) --

    def exec(self, sql: str) -> List[Tuple]:
        with self._lock:
            rows, _ = self._execute(sql)
            return rows

    def exec_script(self, sql: str) -> None:
        with self._lock:
            self._check_open()
            if self._in_txn:
                raise UnknownError("exec_script inside an open transaction")
            if self._lib.eh_exec(self._db, sql.encode("utf-8")) != 0:
                raise self._err()

    def exec_sql_query(self, sql: str, parameters: Sequence = ()) -> List[dict]:
        if hasattr(self._lib, "eh_exec_packed"):
            return unpack_packed_rows(self.exec_sql_query_packed_raw(sql, parameters))
        with self._lock:
            rows, cols = self._execute(sql, parameters)
            return [dict(zip(cols, r)) for r in rows]

    def exec_sql_query_packed_raw(
        self, sql: str, parameters: Sequence = (), with_offsets: bool = False
    ):
        """One C call steps the whole result set into a packed buffer
        (SURVEY hot loop #4: the per-cell ctypes path costs ~65 ms for
        a 10k-row 3-column subscribed query; this is ~1 ms + parse).
        The raw bytes double as a change-detection key: identical bytes
        ⇔ identical result set, so the worker's reactive re-execution
        skips dict materialization and diffing for unchanged queries
        (runtime/worker.py::_query). With `with_offsets`, returns
        (raw, offsets int64[rows+1]) — per-ROW byte spans, the r5
        row-granular change detector's alignment key."""
        lib = self._lib
        with self._lock:
            self._check_open()
            tail = ctypes.c_int(0)
            st = lib.eh_prepare_single(self._db, sql.encode("utf-8"), ctypes.byref(tail))
            if not st:
                raise self._err()
            if tail.value:
                lib.eh_finalize(st)
                raise UnknownError("You can only execute one statement at a time.")
            try:
                for j, v in enumerate(parameters):
                    k, iv, dv, sv, bl = _encode_value(v)
                    if lib.eh_bind(st, j + 1, k, iv, dv, sv, bl) != 0:
                        raise self._err()
                out = ctypes.c_void_p()
                out_len = ctypes.c_int64()
                out_rows = ctypes.c_int64()
                offs_p = ctypes.POINTER(ctypes.c_int64)()
                rc = lib.eh_exec_packed(
                    st, ctypes.byref(out), ctypes.byref(out_len),
                    ctypes.byref(out_rows),
                    ctypes.byref(offs_p) if with_offsets else None,
                )
                if rc != 0:
                    raise self._err()
                try:
                    raw = ctypes.string_at(out.value, out_len.value)
                    if not with_offsets:
                        return raw
                    if not offs_p:
                        # Stale pre-r5 .so (loader's "binary exists, no
                        # make" path): the old 4-arg C ignores the extra
                        # argument and never writes offsets. Degrade to
                        # offsets=None — the worker falls back to the
                        # full unpack, never errors.
                        return raw, None
                    n = out_rows.value
                    offs = np.frombuffer(
                        ctypes.string_at(offs_p, (n + 1) * 8), np.int64
                    )
                    return raw, offs
                finally:
                    lib.eh_free(out)
                    if with_offsets and offs_p:
                        lib.eh_free(ctypes.cast(offs_p, ctypes.c_void_p))
            finally:
                lib.eh_finalize(st)

    def run(self, sql: str, parameters: Sequence = ()) -> int:
        with self._lock:
            self._check_open()
            before = self._lib.eh_total_changes(self._db)
            self._execute(sql, parameters)
            return self._lib.eh_total_changes(self._db) - before

    def run_many(self, sql: str, rows: Iterable[Sequence]) -> int:
        rows = rows if isinstance(rows, list) else list(rows)
        # Fast path: all-text/blob/None rows bind inside ONE C call
        # (the generic path pays ~3us of ctypes per bind).
        if rows and all(
            isinstance(v, (str, bytes)) or v is None for r in rows for v in r
        ):
            return self._run_many_tb(sql, rows)
        lib = self._lib
        with self._lock:
            self._check_open()
            st = lib.eh_prepare(self._db, sql.encode("utf-8"))
            if not st:
                raise self._err()
            before = lib.eh_total_changes(self._db)
            try:
                for row in rows:
                    for j, v in enumerate(row):
                        k, iv, dv, sv, bl = _encode_value(v)
                        if lib.eh_bind(st, j + 1, k, iv, dv, sv, bl) != 0:
                            raise self._err()
                    rc = lib.eh_step(st)
                    if rc not in (_SQLITE_DONE, _SQLITE_ROW):
                        raise self._err()
                    lib.eh_reset(st)
            finally:
                lib.eh_finalize(st)
            return lib.eh_total_changes(self._db) - before

    def _run_many_tb(self, sql: str, rows) -> int:
        lib = self._lib
        nrows, ncols = len(rows), len(rows[0])
        ncells = nrows * ncols
        vals = (ctypes.c_char_p * ncells)()
        lens = (ctypes.c_int32 * ncells)()
        kinds = (ctypes.c_int32 * ncells)()
        i = 0
        for r in rows:
            if len(r) != ncols:
                raise UnknownError("run_many: ragged rows")
            for v in r:
                if v is None:
                    kinds[i] = 0
                elif isinstance(v, bytes):
                    vals[i], lens[i], kinds[i] = v, len(v), 4
                else:
                    b = v.encode("utf-8")
                    vals[i], lens[i], kinds[i] = b, len(b), 3
                i += 1
        with self._lock:
            self._check_open()
            before = lib.eh_total_changes(self._db)
            rc = lib.eh_run_many_tb(
                self._db, sql.encode("utf-8"), nrows, ncols, vals, lens, kinds
            )
            if rc != 0:
                raise self._err()
            return lib.eh_total_changes(self._db) - before

    def changes(self) -> int:
        with self._lock:
            self._check_open()
            return self._lib.eh_total_changes(self._db)

    # Explicit transaction control for the shard-parallel relay ingest:
    # unlike the `transaction()` context manager (which holds this
    # db's lock across its body — correct for the single-writer
    # runtime), these toggle the transaction in one short locked call
    # each, so OTHER threads can run statements inside the open
    # transaction. The caller owns exclusivity: exactly one logical
    # writer per database (the engine assigns one worker per shard).

    def begin(self) -> None:
        with self._lock:
            self._check_open()
            if self._in_txn:
                raise UnknownError("begin inside an open transaction")
            if self._lib.eh_exec(self._db, self._begin_sql) != 0:
                raise self._err()
            self._in_txn = True

    def commit(self) -> None:
        with self._lock:
            self._check_open()
            if not self._in_txn:
                raise UnknownError("commit without an open transaction")
            self._in_txn = False
            if self._lib.eh_exec(self._db, b"COMMIT") != 0:
                raise self._err()

    def rollback(self) -> None:
        with self._lock:
            if not self._db or not self._in_txn:
                return
            self._in_txn = False
            self._lib.eh_exec(self._db, b"ROLLBACK")

    @contextmanager
    def transaction(self):
        with self._lock:
            self._check_open()
            if self._in_txn:
                yield self
                return
            if self._lib.eh_exec(self._db, self._begin_sql) != 0:
                raise self._err()
            self._in_txn = True
            try:
                yield self
            except BaseException:
                self._lib.eh_exec(self._db, b"ROLLBACK")
                raise
            else:
                if self._lib.eh_exec(self._db, b"COMMIT") != 0:
                    raise self._err()
            finally:
                self._in_txn = False

    def set_begin_immediate(self) -> None:
        """See PySqliteDatabase.set_begin_immediate: cross-process
        writers must take the write lock at BEGIN (deferred upgrades
        bypass busy_timeout)."""
        self._begin_sql = b"BEGIN IMMEDIATE"

    def close(self) -> None:
        with self._lock:
            if self._db:
                self._lib.eh_close(self._db)
                self._db = None

    # -- native hot paths --

    def fetch_winners(
        self, cells: Sequence[Tuple[str, str, str]]
    ) -> List[Optional[str]]:
        """Winner timestamp per cell (None = no stored winner)."""
        n = len(cells)
        if n == 0:
            return []
        cap = 64
        out = ctypes.create_string_buffer(n * cap)
        with self._lock:
            self._check_open()
            rc = self._lib.eh_fetch_winners(
                self._db, n,
                _str_array([c[0] for c in cells]),
                _str_array([c[1] for c in cells]),
                _str_array([c[2] for c in cells]),
                out, cap,
            )
        if rc != 0:
            raise self._err()
        res: List[Optional[str]] = []
        for i in range(n):
            raw = out.raw[i * cap : (i + 1) * cap].split(b"\0", 1)[0]
            res.append(raw.decode("utf-8") if raw else None)
        return res

    def apply_sequential(self, messages) -> List[bool]:
        """applyMessages.ts:78-124 for a whole batch in one C call;
        returns the per-message Merkle-XOR mask. Caller manages the
        transaction."""
        n = len(messages)
        if n == 0:
            return []
        kinds, ivals, dvals, svals, blens = _columnar_values([m.value for m in messages])
        out = (ctypes.c_uint8 * n)()
        with self._lock:
            self._check_open()
            rc = self._lib.eh_apply_sequential(
                self._db, n,
                _str_array([m.timestamp for m in messages]),
                _str_array([m.table for m in messages]),
                _str_array([m.row for m in messages]),
                _str_array([m.column for m in messages]),
                kinds, ivals, dvals, svals, blens, out,
            )
        if rc != 0:
            raise self._err()
        return [bool(x) for x in out]

    def apply_planned(self, messages, upsert_mask: Sequence[bool]) -> None:
        """Apply a planner-computed upsert mask + bulk __message insert
        in one C call. Caller manages the transaction.

        Marshalling is packed: one contiguous buffer + int32 lengths
        per string column (`b"".join` at C speed) instead of 100k
        ctypes pointer-array assignments, and every bind carries its
        byte length so embedded NULs round-trip exactly like the
        Python backend."""
        n = len(messages)
        if n == 0:
            return
        i32p = ctypes.POINTER(ctypes.c_int32)

        def packed(items):
            enc = [x.encode("utf-8") for x in items]
            lens = np.fromiter(map(len, enc), np.int32, n)
            return b"".join(enc), lens.ctypes.data_as(i32p), lens

        ts_buf, ts_lens, _k1 = packed([m.timestamp for m in messages])
        tbl_buf, tbl_lens, _k2 = packed([m.table for m in messages])
        row_buf, row_lens, _k3 = packed([m.row for m in messages])
        col_buf, col_lens, _k4 = packed([m.column for m in messages])
        vals = [_encode_value(m.value) for m in messages]
        kinds = np.fromiter((v[0] for v in vals), np.int32, n)
        ivals = np.fromiter((v[1] for v in vals), np.int64, n)
        dvals = np.fromiter((v[2] for v in vals), np.float64, n)
        vlens = np.fromiter((v[4] for v in vals), np.int32, n)
        val_buf = b"".join(v[3] for v in vals if v[3] is not None)
        mask_np = np.ascontiguousarray(np.asarray(upsert_mask, dtype=np.uint8))
        if len(mask_np) != n:  # C reads n bytes; a short buffer would be OOB
            raise ValueError(f"upsert_mask length {len(mask_np)} != messages {n}")
        with self._lock:
            self._check_open()
            rc = self._lib.eh_apply_planned_packed(
                self._db, n,
                ts_buf, ts_lens, tbl_buf, tbl_lens,
                row_buf, row_lens, col_buf, col_lens,
                kinds.ctypes.data_as(i32p),
                ivals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                dvals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                val_buf, vlens.ctypes.data_as(i32p),
                mask_np.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            )
        if rc == 3:
            raise UnknownError("identifier contains NUL")
        if rc != 0:
            raise self._err()

    def apply_planned_cells(self, pb, upsert_mask) -> None:
        """`eh_apply_planned_cells`: apply a planner-computed upsert
        mask + bulk __message insert for a PackedReceive batch in one C
        call — the buffers flow from the C decrypt straight to the C
        apply with zero per-row Python. Caller manages the
        transaction. End state identical to `apply_planned` on the
        materialized batch (test-pinned)."""
        n = pb.n
        if n == 0:
            return
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        cell_id = np.ascontiguousarray(pb.cell_id, np.int32)
        vkinds = np.ascontiguousarray(pb.vkinds, np.uint8)
        ivals = np.ascontiguousarray(pb.ivals, np.int64)
        dvals = np.ascontiguousarray(pb.dvals, np.float64)
        vlens = np.ascontiguousarray(pb.vlens, np.int32)
        cell_lens = np.ascontiguousarray(pb.cell_lens, np.int32)
        # A slice's text payloads occupy a contiguous vblob span
        # starting at its first row's offset (vlens is 0 for non-text).
        base = int(pb.voffs[0])
        vblob = pb.vblob[base : base + int(vlens.sum())]
        mask_np = np.ascontiguousarray(np.asarray(upsert_mask, dtype=np.uint8))
        if len(mask_np) != n:  # C reads n bytes; a short buffer would be OOB
            raise ValueError(f"upsert_mask length {len(mask_np)} != rows {n}")
        with self._lock:
            self._check_open()
            rc = self._lib.eh_apply_planned_cells(
                self._db, n, pb.ts_slab, len(pb.cells), pb.cell_blob,
                cell_lens.ctypes.data_as(i32p),
                cell_id.ctypes.data_as(i32p),
                vkinds.ctypes.data_as(u8p),
                ivals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                dvals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                vblob, vlens.ctypes.data_as(i32p),
                mask_np.ctypes.data_as(u8p),
            )
        if rc == 3:
            raise UnknownError("identifier contains NUL")
        if rc == 2:
            raise UnknownError("apply_planned_cells: cell index out of range")
        if rc != 0:
            raise self._err()

    def snapshot_rows(self) -> Optional[bytes]:
        """Whole-shard snapshot capture in ONE C call: every message
        row + merkleTree row as framed records (server/snapshot.py
        format), byte-identical to the stdlib oracle framing
        (parity-pinned in tests/test_snapshot.py). None on a stale
        pre-r7 .so (loader's "binary exists, no make" path) — the
        caller degrades to the SQL oracle. The caller holds the read
        transaction (consistency across the two internal SELECTs)."""
        lib = self._lib
        if not hasattr(lib, "eh_snapshot_rows"):
            return None
        out = ctypes.c_void_p()
        out_len = ctypes.c_int64()
        n_msgs = ctypes.c_int64()
        n_trees = ctypes.c_int64()
        with self._lock:
            self._check_open()
            rc = lib.eh_snapshot_rows(
                self._db, ctypes.byref(out), ctypes.byref(out_len),
                ctypes.byref(n_msgs), ctypes.byref(n_trees),
            )
        if rc == 3:
            raise UnknownError("snapshot capture failed (out of memory?)")
        if rc != 0:
            raise self._err()
        try:
            return ctypes.string_at(out.value, out_len.value)
        finally:
            lib.eh_free(out)

    def fetch_relay_messages(
        self, user_id: str, since: str, node_id: str
    ) -> List[Tuple[str, bytes]]:
        """The relay's get_messages query with packed outputs: one C
        call, three buffers, no per-row ctypes column reads."""
        lib = self._lib
        ts_buf = ctypes.c_char_p()
        content_buf = ctypes.c_void_p()
        lens_ptr = ctypes.POINTER(ctypes.c_int32)()
        n = ctypes.c_int64(0)
        u = user_id.encode()
        nd = node_id.encode()
        with self._lock:
            self._check_open()
            # Explicit lengths: wire-derived user/node may contain NUL.
            rc = lib.eh_get_messages(
                self._db, u, len(u), since.encode(), nd, len(nd),
                ctypes.byref(ts_buf), ctypes.byref(content_buf),
                ctypes.byref(lens_ptr), ctypes.byref(n),
            )
        if rc == 1:
            raise self._err()
        if rc == 2:
            raise NonCanonicalStoreError("non-canonical timestamp width in relay store")
        if rc != 0:
            raise UnknownError("relay message fetch failed (out of memory?)")
        count = n.value
        try:
            ts_raw = ctypes.string_at(ts_buf, count * 46) if count else b""
            lens = lens_ptr[:count] if count else []
            total = sum(lens)
            content_raw = ctypes.string_at(content_buf, total) if total else b""
        finally:
            lib.eh_free(ts_buf)
            lib.eh_free(content_buf)
            lib.eh_free(ctypes.cast(lens_ptr, ctypes.c_void_p))
        out: List[Tuple[str, bytes]] = []
        off = 0
        for i in range(count):
            ts = ts_raw[i * 46 : (i + 1) * 46].decode("ascii")
            ln = lens[i]
            out.append((ts, content_raw[off : off + ln]))
            off += ln
        return out

    def fetch_relay_messages_wire(
        self, user_id: str, since: str, node_id: str
    ) -> Tuple[bytes, int]:
        """The same query emitted DIRECTLY as the SyncResponse
        `messages` protobuf stream — byte-identical to encoding the
        `fetch_relay_messages` rows with protocol.encode_sync_response,
        with zero per-row Python objects (the relay cold-sync response
        leg was object-construction-bound, docs/BENCHMARKS.md r4).
        → (stream_bytes, row_count)."""
        lib = self._lib
        out = ctypes.c_void_p()
        out_len = ctypes.c_int64()
        n = ctypes.c_int64(0)
        u = user_id.encode()
        nd = node_id.encode()
        with self._lock:
            self._check_open()
            # Explicit lengths: wire-derived user/node may contain NUL.
            rc = lib.eh_get_messages_wire(
                self._db, u, len(u), since.encode(), nd, len(nd),
                ctypes.byref(out), ctypes.byref(out_len), ctypes.byref(n),
            )
        if rc == 1:
            raise self._err()
        if rc == 2:
            raise NonCanonicalStoreError("non-canonical timestamp width in relay store")
        if rc != 0:
            raise UnknownError("relay message fetch failed (out of memory?)")
        try:
            return ctypes.string_at(out.value, out_len.value), n.value
        finally:
            lib.eh_free(out)

    def relay_insert_packed(
        self,
        group_users: Sequence[str],
        group_counts: Sequence[int],
        ts_packed: bytes,
        content_packed: bytes,
        content_lens,
    ):
        """Grouped one-call ingest for the batch reconciler: timestamps
        as ONE fixed-width 46-byte buffer, ciphertexts as ONE packed
        blob buffer. Returns the per-row was-new flags as a numpy bool
        array (in-batch duplicates dedup through the PK, exactly like
        sequential INSERT OR IGNORE)."""
        import numpy as np

        n = len(content_lens)
        if n * 46 != len(ts_packed):
            raise UnknownError("relay_insert_packed: timestamp buffer size mismatch")
        if n == 0:
            return np.zeros(0, bool)
        lens = np.ascontiguousarray(content_lens, dtype=np.int32)
        if int(lens.sum()) != len(content_packed):
            raise UnknownError("relay_insert_packed: content buffer size mismatch")
        counts = np.ascontiguousarray(group_counts, dtype=np.int64)
        out = (ctypes.c_uint8 * n)()
        with self._lock:
            self._check_open()
            rc = self._lib.eh_relay_insert_packed(
                self._db, len(group_users),
                _str_array(group_users),
                counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                ts_packed, content_packed,
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                out,
            )
        if rc != 0:
            raise self._err()
        return np.frombuffer(out, np.uint8).astype(bool)

    def relay_insert(self, rows: Sequence[Tuple[str, str, bytes]]) -> List[bool]:
        """Bulk INSERT OR IGNORE into the relay's message table; returns
        per-row was-new flags (index.ts:148-159 changes()==1 semantics)."""
        n = len(rows)
        if n == 0:
            return []
        contents = (ctypes.c_char_p * n)()
        lens = (ctypes.c_int32 * n)()
        for j, (_, _, content) in enumerate(rows):
            contents[j] = content
            lens[j] = len(content)
        out = (ctypes.c_uint8 * n)()
        with self._lock:
            self._check_open()
            rc = self._lib.eh_relay_insert(
                self._db, n,
                _str_array([r[0] for r in rows]),
                _str_array([r[1] for r in rows]),
                contents, lens, out,
            )
        if rc != 0:
            raise self._err()
        return [bool(x) for x in out]


def open_database(path: str = ":memory:", backend: str = "auto"):
    """Open the storage backend: "native" (C++ layer), "python"
    (stdlib sqlite3), or "auto" (native when buildable)."""
    from evolu_tpu.storage.sqlite import PySqliteDatabase

    if backend == "python":
        return PySqliteDatabase(path)
    if backend == "native":
        return CppSqliteDatabase(path)
    if native_available():
        return CppSqliteDatabase(path)
    return PySqliteDatabase(path)
