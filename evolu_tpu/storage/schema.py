"""System-table bootstrap and app-schema evolution.

Reference: packages/evolu/src/initDbModel.ts (system tables + owner
seed), updateDbSchema.ts (add-only DDL migration), deleteAllTables.ts.
App columns get BLOB affinity on purpose — "no attempt is made to
coerce data from one storage class into another"
(updateDbSchema.ts:72-77) — which is what makes end states comparable
byte-for-byte across implementations.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Set

from evolu_tpu.core.ids import mnemonic_to_owner_id
from evolu_tpu.core.merkle import create_initial_merkle_tree, merkle_tree_to_string
from evolu_tpu.core.mnemonic import generate_mnemonic
from evolu_tpu.core.timestamp import create_initial_timestamp, timestamp_to_string
from evolu_tpu.core.types import Owner, TableDefinition
from evolu_tpu.storage.sqlite import PySqliteDatabase, quote_ident


def init_db_model(db: PySqliteDatabase, mnemonic: Optional[str] = None) -> Owner:
    """Idempotent bootstrap (initDbModel.ts:29-81): __message + covering
    index, __clock seeded with the initial timestamp/empty tree, __owner
    seeded with the (possibly generated) mnemonic identity."""
    initialized = len(db.exec_sql_query("PRAGMA table_info (__message)")) > 0
    if not initialized:
        if mnemonic is None:
            mnemonic = generate_mnemonic()
        timestamp = timestamp_to_string(create_initial_timestamp())
        merkle = merkle_tree_to_string(create_initial_merkle_tree())
        owner_id = mnemonic_to_owner_id(mnemonic)
        with db.transaction():
            db.exec(
                'CREATE TABLE __message ('
                '"timestamp" BLOB PRIMARY KEY, "table" BLOB, "row" BLOB, '
                '"column" BLOB, "value" BLOB)'
            )
            db.exec(
                'CREATE INDEX index__message ON __message '
                '("table", "row", "column", "timestamp")'
            )
            db.exec('CREATE TABLE __clock ("timestamp" BLOB, "merkleTree" BLOB)')
            db.run(
                'INSERT INTO __clock ("timestamp", "merkleTree") VALUES (?, ?)',
                (timestamp, merkle),
            )
            db.exec('CREATE TABLE __owner ("id" BLOB, "mnemonic" BLOB)')
            db.run(
                'INSERT INTO __owner ("id", "mnemonic") VALUES (?, ?)',
                (owner_id, mnemonic),
            )
    row = db.exec_sql_query('SELECT "id", "mnemonic" FROM __owner LIMIT 1')[0]
    return Owner(id=row["id"], mnemonic=row["mnemonic"])


def get_existing_tables(db: PySqliteDatabase) -> Set[str]:
    """Non-system app tables (updateDbSchema.ts:12-28)."""
    rows = db.exec_sql_query("SELECT \"name\" FROM sqlite_schema WHERE type='table'")
    return {r["name"] for r in rows if not r["name"].startswith("__")}


def update_db_schema(db: PySqliteDatabase, table_definitions: Iterable[TableDefinition]) -> None:
    """Add-only migration (updateDbSchema.ts:85-103): CREATE missing
    tables (id TEXT PRIMARY KEY + BLOB columns) or ALTER ... ADD COLUMN.

    CRDT column types (ISSUE 7): a column may be declared with a type
    suffix — `"votes:counter"`, `"tags:awset"` — which strips off for
    the DDL (the stored column is a plain BLOB-affinity column holding
    the MATERIALIZED value) and persists into the `__crdt_schema`
    registry that routes merge semantics (core/crdt_types.py)."""
    from evolu_tpu.core.crdt_types import declare_column_types, parse_column_spec

    existing = get_existing_tables(db)
    declarations = []
    for td in table_definitions:
        parsed = [parse_column_spec(c) for c in td.columns]
        declarations.extend(
            (td.name, name, ctype) for name, ctype in parsed if ctype != "lww"
        )
        names = [name for name, _ in parsed]
        if td.name in existing:
            have = {r["name"] for r in db.exec_sql_query(f"PRAGMA table_info ({quote_ident(td.name)})")}
            for col in names:
                if col not in have:
                    db.run(f"ALTER TABLE {quote_ident(td.name)} ADD COLUMN {quote_ident(col)} BLOB")
        else:
            cols = ", ".join(f"{quote_ident(c)} BLOB" for c in names)
            db.exec(f'CREATE TABLE {quote_ident(td.name)} ("id" TEXT PRIMARY KEY, {cols})')
    if declarations:
        declare_column_types(db, declarations)


def delete_all_tables(db: PySqliteDatabase) -> None:
    """DROP every table (deleteAllTables.ts:6-25) — including the
    `__crdt_*` schema/state tables, whose per-connection cache must
    drop with them (a stale typed registry after resetOwner would
    route merges for tables that no longer exist)."""
    from evolu_tpu.core.crdt_types import invalidate_schema_cache

    rows = db.exec_sql_query("SELECT \"name\" FROM sqlite_schema WHERE type='table'")
    for r in rows:
        db.exec(f"DROP TABLE {quote_ident(r['name'])}")
    invalidate_schema_cache(db)
