"""SQLite `Database` implementation over the stdlib sqlite3 module.

This is real SQLite (the C library), satisfying the byte-identical
end-state contract. The interface mirrors the reference's backend
boundary (types.ts:162-176): exec, changes, exec_sql_query, prepare,
and transaction — one writer, transaction-at-a-time, exactly like the
reference's dbTransaction (initDb.ts:55-80).
"""

from __future__ import annotations

import sqlite3
import threading
from contextlib import contextmanager
from typing import Iterable, List, Optional, Sequence, Tuple

from evolu_tpu.core.types import UnknownError


def quote_ident(name: str) -> str:
    """SQL identifier quoting with embedded quotes doubled — one
    definition shared by the Python paths and matching the C++ layer's
    quote_ident, so hostile names fail identically on both backends."""
    return '"' + str(name).replace('"', '""') + '"'



class PySqliteDatabase:
    """Single-writer SQLite handle.

    All access is serialized through an RLock — the moral equivalent of
    the reference DbWorker's WritableStream queue (db.worker.ts:50-75).
    """

    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.isolation_level = None  # explicit BEGIN/COMMIT
        self._lock = threading.RLock()
        self.path = path
        self._begin_sql = "BEGIN"

    # -- Database interface (types.ts:162-176) --

    def exec(self, sql: str) -> List[Tuple]:
        """Execute a single statement; returns its rows (if any)."""
        with self._lock:
            try:
                return self._conn.execute(sql).fetchall()
            except sqlite3.Error as e:
                raise UnknownError(e) from e

    def exec_script(self, sql: str) -> None:
        """Execute a multi-statement script (DDL bootstrap). Never returns
        rows; must not be called inside a transaction — sqlite3's
        executescript issues an implicit COMMIT first."""
        with self._lock:
            if self._conn.in_transaction:
                raise UnknownError("exec_script inside an open transaction")
            try:
                self._conn.executescript(sql)
            except sqlite3.Error as e:
                raise UnknownError(e) from e

    def exec_sql_query(self, sql: str, parameters: Sequence = ()) -> List[dict]:
        """Parameterized query; rows as column->value dicts (initDb.ts:94-113)."""
        with self._lock:
            try:
                cur = self._conn.execute(sql, tuple(parameters))
                cols = [d[0] for d in cur.description] if cur.description else []
                return [dict(zip(cols, row)) for row in cur.fetchall()]
            except sqlite3.Error as e:
                raise UnknownError(e) from e

    def run(self, sql: str, parameters: Sequence = ()) -> int:
        """Execute a write; returns rowcount (the reference's `changes`)."""
        with self._lock:
            try:
                cur = self._conn.execute(sql, tuple(parameters))
                return cur.rowcount
            except sqlite3.Error as e:
                raise UnknownError(e) from e

    def run_many(self, sql: str, rows: Iterable[Sequence]) -> int:
        with self._lock:
            try:
                cur = self._conn.executemany(sql, rows)
                return cur.rowcount
            except sqlite3.Error as e:
                raise UnknownError(e) from e

    def changes(self) -> int:
        with self._lock:
            return self._conn.total_changes

    @contextmanager
    def transaction(self):
        """BEGIN/COMMIT/ROLLBACK wrapper (initDb.ts:66-80). Reentrant-safe:
        nested use joins the outer transaction."""
        with self._lock:
            if self._conn.in_transaction:
                yield self
                return
            self._conn.execute(self._begin_sql)
            try:
                yield self
            except BaseException:
                self._conn.execute("ROLLBACK")
                raise
            else:
                self._conn.execute("COMMIT")

    def set_begin_immediate(self) -> None:
        """Writers sharing the database FILE with other processes must
        take the write lock at BEGIN: a deferred transaction that
        upgrades to write after a concurrent commit gets SQLITE_BUSY
        immediately — busy_timeout does not apply to that upgrade."""
        self._begin_sql = "BEGIN IMMEDIATE"

    def close(self) -> None:
        with self._lock:
            self._conn.close()


def configure_shared_file_db(db) -> None:
    """Make a FILE-BACKED database safe for concurrent writers across
    processes — the one pragma discipline shared by the pre-forked
    fleet relays and the write-behind's process-per-shard drain
    children. Order matters: busy_timeout FIRST, so the WAL switch
    itself (a write) waits out a concurrent writer instead of failing;
    WAL + synchronous=NORMAL is the durability/perf point the
    checkpoint format assumes; BEGIN IMMEDIATE takes the write lock at
    BEGIN (a deferred upgrade after a concurrent commit gets
    SQLITE_BUSY with no busy_timeout applied). No-op for :memory:
    databases — nothing shares those."""
    if getattr(db, "path", None) in (None, ":memory:"):
        return
    for pragma in ("busy_timeout=5000", "journal_mode=WAL",
                   "synchronous=NORMAL"):
        db.exec_sql_query(f"PRAGMA {pragma}", ())
    db.set_begin_immediate()



