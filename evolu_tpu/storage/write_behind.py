"""Bounded async write-behind materializer: SQLite off the serving path.

PR-11 (ROADMAP #1) inverts the engine's storage architecture. The
serving path (`server/engine.BatchReconciler.run_batch_wire`) answers
sync responses and Merkle questions from in-memory authoritative state
— per-owner trees folded from the device hash kernel's deltas — and
hands SQLite materialization to this queue. The btree (measured wall:
~0.72M rows/s/core, multi-row INSERT already a recorded negative
result) is drained in batches sized for it, off the request path.

PR-19 (ROADMAP #2) parallelizes the drain across owner shards. Owners
never share rows and LWW merge commutes (Merkle-CRDTs,
arXiv:2004.00107), so per-owner-shard transactions need no cross-shard
ordering to reach the same byte-exact end state (arXiv:2203.14518).
Each storage shard gets its OWN drain state — lock, pending deque,
drained watermark, needs-flush taint, consecutive-failure counter —
and one drain worker per shard (configurable down via
`Config.wb_drain_workers`; workers own shards round-robin) drains
engine shard i into btree shard i concurrently:

- thread-per-shard (default): the native `evolu_host` insert leg is a
  plain C ABI called through ctypes, which releases the GIL for the
  duration of every foreign call — N worker threads genuinely overlap
  N shard btree inserts on N cores.
- process-per-shard (`drain_process=True`, pure-Python file-backed
  stores only): each worker delegates its shard transactions to a
  child `python -m evolu_tpu.storage._wb_shard_proc` over a pipe
  (fleet-bench style — the pure-Python insert leg holds the GIL, so
  real processes are the only honest way to scale it). The parent
  blocks in a pipe read (GIL dropped) while the child commits; WAL +
  busy_timeout + BEGIN IMMEDIATE (`sqlite.configure_shared_file_db`,
  the same discipline file-backed RelayStores already run for the
  pre-forked fleet) make the cross-process writes safe, and the
  parent posts all ledger terminals from the child's returned counts
  (the conservation ledger is per-process state).

Durability contract (the "ACKed write is never lost" floor):
- Every appended record is framed (length + crc32) into ONE shared
  append-only log and fsync'd BEFORE `append_batch` returns — the ACK
  point. A torn tail (crash mid-write) fails its crc and is discarded
  on replay; everything before it replays.
- Replay is idempotent and EXACT: message inserts are PK-deduped
  (INSERT OR IGNORE), and replay recomputes every owner tree from the
  per-row was-new flags through the host oracle fold
  (`core.merkle.minute_deltas_host`) — byte-identical to a
  synchronous-apply twin regardless of where the crash landed. Under
  the parallel drain a crash can land with shard k committed and
  shard j not: replay re-applies BOTH, and shard k's rows simply
  re-classify as duplicates (the retry rule, per shard). The torture
  episodes in tests/test_model_check.py are the license.
- The log truncates only once EVERY shard queue is drained AND
  committed; a crash between a shard commit and the truncate just
  replays committed records (no-ops).
- SQLite durability past the drain commit is SQLite's own (WAL +
  synchronous=NORMAL survives process crash; the log covers the
  undrained tail).

Ordering and exactness:
- Records drain strictly in append (seq) order WITHIN each shard; an
  owner's history is only ever appended from the one engine dispatcher
  thread and lands wholly in one shard, so per-owner order stays
  total. Cross-shard interleaving is unobservable: owners partition
  by shard, and every consistency read is either per-owner (its one
  shard) or behind the composed all-shard barrier.
- The engine's serve-time trees are OPTIMISTIC: every in-batch-deduped
  row XORs (it cannot see rows already stored without touching the
  btree). The drain compares against the INSERT's was-new flags: a
  clean record (steady state — all rows new) lands its precomputed
  tree string verbatim; a record with any already-stored row gets its
  owner's tree recomputed exactly from the new rows only, the owner's
  serving cache entry is dropped, and later pending records of that
  owner (whose precomputed trees were folded on the stale optimistic
  base) recompute too, until the serving path has re-read the
  corrected tree (`_needs_flush` handshake).

Barrier composition (the tentpole's consistency surface):
- `flush_owner(owner)` waits ONLY on the owner's shard watermark — a
  slow or failing shard j cannot stall serves for owners on shard k.
- `flush()` waits on every shard's watermark (the composed flush).
- `drain_barrier()` = flush + hold EVERY shard lock (ascending order,
  deadlock-free: workers only ever take their own shard's lock) —
  the whole-store consistency point for snapshot capture,
  checkpoints, replication serves, fleet rebalance installs, and the
  direct per-request write path. `db_lock` IS that composite.
- Per-owner serving reads take `owner_lock(owner)` — just the one
  shard's lock, concurrent with every other shard's drain.

Ledger: terminals post per SHARD transaction through a transactional
`ledger.pending()` entry committed iff that shard's SQLite
transaction committed (obs/ledger.py). A failed shard retries alone —
its committed siblings already popped their slices — so every queued
row still reaches exactly one inserted/duplicate terminal and
`ledger.audit()` stays clean at every barrier.

Backpressure is explicit: a full queue raises `WriteBehindFull` before
mutating anything — the scheduler maps it to its 503 + Retry-After
path (queue-full stalls admission, never drops).
"""

from __future__ import annotations

import json
import os
import struct
import subprocess
import sys
import threading
import time
import zlib
from collections import deque
from contextlib import contextmanager
from typing import Deque, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from evolu_tpu.obs import anatomy, ledger, metrics, trace
from evolu_tpu.utils.log import log

LOG_MAGIC = b"EVOLUWB1\n"
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")

# Histogram buckets for drain batch sizes (rows) — reuse the count scale.
_ROW_BUCKETS = metrics.COUNT_BUCKETS


class WriteBehindFull(Exception):
    """Admission backpressure: the pending queue is at capacity. The
    caller should stall the write (the scheduler answers 503 +
    `retry_after` seconds) — never drop it."""

    def __init__(self, retry_after: float, backlog_rows: int):
        super().__init__(
            f"write-behind queue full ({backlog_rows} rows pending); "
            f"retry after {retry_after}s"
        )
        self.retry_after = retry_after
        self.backlog_rows = backlog_rows


class IngestRecord:
    """One shard's slice of one engine batch: the packed row buffers
    exactly as `engine.start_batch` built them (no repacking), plus the
    optimistic per-owner tree strings computed at serve time. The
    on-disk frame is length+crc-guarded; decode raises ValueError on
    any corruption (the wire-decoder contract)."""

    __slots__ = ("gu", "gc", "ts_packed", "content_packed", "lens", "tree_rows")

    def __init__(self, gu: Sequence[str], gc: Sequence[int], ts_packed: bytes,
                 content_packed: bytes, lens, tree_rows: Sequence[Tuple[str, str]]):
        self.gu = list(gu)
        self.gc = [int(c) for c in gc]
        self.ts_packed = ts_packed
        self.content_packed = content_packed
        self.lens = np.ascontiguousarray(lens, dtype=np.int32)
        self.tree_rows = list(tree_rows)

    @property
    def n_rows(self) -> int:
        return int(len(self.lens))

    def encode(self) -> bytes:
        parts: List[bytes] = [_U32.pack(len(self.gu))]
        for u, c in zip(self.gu, self.gc):
            ub = u.encode("utf-8")
            parts.append(_U16.pack(len(ub)))
            parts.append(ub)
            parts.append(_U32.pack(c))
        parts.append(_U32.pack(len(self.ts_packed)))
        parts.append(self.ts_packed)
        parts.append(_U32.pack(len(self.content_packed)))
        parts.append(self.content_packed)
        lens = self.lens.astype("<i4", copy=False)
        parts.append(_U32.pack(len(lens)))
        parts.append(lens.tobytes())
        parts.append(_U32.pack(len(self.tree_rows)))
        for u, t in self.tree_rows:
            ub, tb = u.encode("utf-8"), t.encode("utf-8")
            parts.append(_U16.pack(len(ub)))
            parts.append(ub)
            parts.append(_U32.pack(len(tb)))
            parts.append(tb)
        return b"".join(parts)

    @staticmethod
    def decode(body: bytes) -> "IngestRecord":
        def take(n: int) -> bytes:
            nonlocal pos
            if pos + n > len(body):
                raise ValueError("truncated write-behind record")
            out = body[pos : pos + n]
            pos += n
            return out

        pos = 0
        (n_groups,) = _U32.unpack(take(4))
        gu: List[str] = []
        gc: List[int] = []
        for _ in range(n_groups):
            (ul,) = _U16.unpack(take(2))
            gu.append(take(ul).decode("utf-8"))
            gc.append(_U32.unpack(take(4))[0])
        (tl,) = _U32.unpack(take(4))
        ts_packed = take(tl)
        (cl,) = _U32.unpack(take(4))
        content_packed = take(cl)
        (nl,) = _U32.unpack(take(4))
        lens = np.frombuffer(take(4 * nl), dtype="<i4").astype(np.int32)
        (n_trees,) = _U32.unpack(take(4))
        tree_rows: List[Tuple[str, str]] = []
        for _ in range(n_trees):
            (ul,) = _U16.unpack(take(2))
            u = take(ul).decode("utf-8")
            (sl,) = _U32.unpack(take(4))
            tree_rows.append((u, take(sl).decode("utf-8")))
        if pos != len(body):
            raise ValueError("trailing bytes after write-behind record")
        if sum(gc) != len(lens) or len(ts_packed) != 46 * len(lens):
            raise ValueError("write-behind record shape mismatch")
        if int(lens.sum()) != len(content_packed):
            raise ValueError("write-behind record content size mismatch")
        return IngestRecord(gu, gc, ts_packed, content_packed, lens, tree_rows)


class _Slice:
    """One (record, owner-group) routed to its shard: the per-shard
    drain unit. Byte ranges are cut at append so a slice carries no
    reference to its record (the log frame is the durable copy)."""

    __slots__ = ("seq", "si", "owner", "k", "ts_b", "content_b", "lens",
                 "tree_s", "t_enqueue")

    def __init__(self, seq, si, owner, k, ts_b, content_b, lens, tree_s,
                 t_enqueue):
        self.seq = seq
        self.si = si
        self.owner = owner
        self.k = k
        self.ts_b = ts_b
        self.content_b = content_b
        self.lens = lens
        self.tree_s = tree_s
        self.t_enqueue = t_enqueue


class _ShardState:
    """Per-shard drain state: the tentpole's unit of independence.
    `lock` serializes that shard's SQLite use between its drain worker
    and per-owner serving reads; `pending`/`rows` are this shard's
    slice queue; `failures`/`err` are ITS consecutive-failure counter
    (one wedged shard trips /health without stalling siblings)."""

    __slots__ = ("si", "lock", "pending", "rows", "failures", "err")

    def __init__(self, si: int):
        self.si = si
        self.lock = threading.RLock()
        self.pending: Deque[_Slice] = deque()
        self.rows = 0
        self.failures = 0
        self.err: Optional[BaseException] = None


class _CompositeLock:
    """All shard locks as one: acquire in ascending shard order
    (workers only ever take their OWN shard's lock, so the fixed order
    cannot deadlock), release in reverse. Reentrant because every
    member is an RLock. This is `db_lock` for multi-shard stores — the
    whole-store barrier the PR-11 callers already hold."""

    def __init__(self, locks: Sequence[threading.RLock]):
        self._locks = tuple(locks)

    def acquire(self) -> None:
        for lk in self._locks:
            lk.acquire()

    def release(self) -> None:
        for lk in reversed(self._locks):
            lk.release()

    def __enter__(self) -> "_CompositeLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def apply_shard_ops(db, get_tree, ops, exact: bool, carry_taint) -> Tuple[
        Set[str], List[Tuple[int, int]]]:
    """Apply one shard's ordered op list in ONE transaction on `db`:
    INSERT OR IGNORE each (owner, rows) group, land precomputed trees
    for clean groups, recompute exactly from the was-new flags for
    tainted/exact ones, upsert the LAST tree per owner. Returns
    (tainted owners, per-op (n_new, n_dup)) — the CALLER posts ledger
    terminals from the counts, because this also runs inside the
    `_wb_shard_proc` child where the parent owns the ledger.

    `ops` items: (owner, k, ts_bytes, content_bytes, lens, tree_s|None).
    `get_tree(owner)` → stored tree TEXT ("{}" when unseen).
    `carry_taint`: owners whose precomputed trees are stale (a prior
    correction the serving path has not re-read past yet)."""
    from evolu_tpu.core.merkle import (
        apply_prefix_xors,
        merkle_tree_from_string,
        merkle_tree_to_string,
        minute_deltas_host,
    )

    tainted: Set[str] = set()
    counts: List[Tuple[int, int]] = []
    with db.transaction():
        # Insert every op in order first; tree decisions are made per
        # OWNER over the whole op list afterwards. The per-op form
        # this replaced was wrong whenever one record carried BOTH a
        # clean op and a duplicate-bearing op for the same owner (a
        # batch holding an owner's fresh push plus a retry
        # redelivery): the record's per-owner tree string is the
        # post-batch OPTIMISTIC tree — it pre-folded the sibling op's
        # duplicate hashes (XOR-cancel), so landing it "verbatim for
        # the clean op" installed a tree missing those rows, and the
        # dup op's recompute then used that poisoned string as its
        # base with zero new rows to fold. Grouping by owner makes
        # the dirty case recompute from the STORED tree with ALL of
        # the owner's new rows — the synchronous-apply semantics.
        per_owner: Dict[str, dict] = {}
        order: List[str] = []
        for (u, k, ts_b, content_b, lens, tree_s) in ops:
            flags = np.asarray(_insert_rows(db, [u], [k], ts_b, content_b, lens))
            n_new = int(flags.sum())
            counts.append((n_new, k - n_new))
            acc = per_owner.get(u)
            if acc is None:
                acc = per_owner[u] = {"clean": True, "tree_s": None,
                                      "new_ts": []}
                order.append(u)
            acc["clean"] = acc["clean"] and bool(flags.all())
            if tree_s is not None:
                # Last record's tree wins: each record's string is the
                # post-THAT-batch tree, so later supersedes earlier.
                acc["tree_s"] = tree_s
            acc["new_ts"] += [
                ts_b[i * 46 : (i + 1) * 46].decode("ascii")
                for i in range(k)
                if bool(flags[i])
            ]
        cur: Dict[str, str] = {}
        for u in order:
            acc = per_owner[u]
            if (not exact and acc["clean"] and u not in carry_taint):
                # Steady state: every row of this owner's ops was new,
                # so the optimistic trees were exact — land the last
                # one verbatim (None for replay-built records: fall
                # through to the fold).
                if acc["tree_s"] is not None:
                    cur[u] = acc["tree_s"]
                    continue
            # Exact path: fold the NEW rows only onto the stored tree
            # — the host oracle fold, the same semantics a synchronous
            # apply would have had. get_tree reads the pre-transaction
            # merkleTree row (upserts land below), which is exact for
            # everything drained before this batch.
            if not acc["clean"] and not exact:
                tainted.add(u)
            if acc["new_ts"]:
                deltas, _d = minute_deltas_host(acc["new_ts"])
                tree = apply_prefix_xors(
                    merkle_tree_from_string(get_tree(u)), deltas
                )
                cur[u] = merkle_tree_to_string(tree)
            # No new rows → the tree is unchanged; writing the
            # read-back base would mint a merkleTree row (e.g. "{}")
            # the synchronous oracle never writes.
        for u, s in cur.items():
            db.run(
                'INSERT OR REPLACE INTO "merkleTree" '
                '("userId", "merkleTree") VALUES (?, ?)',
                (u, s),
            )
    return tainted, counts


def _insert_rows(db, gu, gc, ts_packed, content_packed, lens):
    """INSERT OR IGNORE one record slice → per-row was-new flags.
    Packed C call where available (a plain-C ctypes leg — the GIL
    drops for its duration, which is what lets thread-per-shard
    workers overlap); generic per-row SQL otherwise (replay must work
    on any backend the store opens with)."""
    if hasattr(db, "relay_insert_packed"):
        return db.relay_insert_packed(gu, gc, ts_packed, content_packed, lens)
    flags = np.zeros(int(sum(gc)), bool)
    offs = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
    row = 0
    for u, k in zip(gu, gc):
        for _ in range(k):
            ts = ts_packed[row * 46 : (row + 1) * 46].decode("ascii")
            content = content_packed[offs[row] : offs[row + 1]]
            flags[row] = (
                db.run(
                    'INSERT OR IGNORE INTO "message" '
                    '("timestamp", "userId", "content") VALUES (?, ?, ?)',
                    (ts, u, content),
                )
                == 1
            )
            row += 1
    return flags


class WriteBehindQueue:
    """The bounded, ordered, crash-safe materialization queue for one
    relay store (RelayStore or ShardedRelayStore — records split into
    per-shard slices at APPEND time by the store's stable owner hash;
    replay re-splits by the topology it wakes up under, so it survives
    a shard-count change).

    `drain_workers`: worker thread count (None/0 → one per storage
    shard; clamped to the shard count; workers own shards
    round-robin). `drain_process=True` delegates each shard's
    transactions to `_wb_shard_proc` child processes — pure-Python
    FILE-BACKED stores only; anything else falls back to threads with
    a logged warning (the native backend already scales on threads,
    and :memory: shards cannot be shared across processes).

    `exact_replay` note: materialization runs in two modes. The normal
    drain trusts each record's precomputed tree strings while the
    INSERT's was-new flags say every row was new; replay (and tainted
    owners) recompute trees from the flags through the host oracle
    fold — always exact, never fast-pathed."""

    # Consecutive failed drain batches (per shard) before `failing()`
    # trips the relay's /health readiness gate (the drain itself
    # retries forever).
    _FAILING_AFTER = 3

    def __init__(
        self,
        store,
        log_path: Optional[str] = None,
        max_rows: int = 1 << 20,
        drain_batch_rows: int = 1 << 16,
        fsync: bool = True,
        retry_after_s: float = 1.0,
        drain_workers: Optional[int] = None,
        drain_process: bool = False,
        _drain_delay_s: float = 0.0,
        _shard_delay_s: Optional[Dict[int, float]] = None,
    ):
        self.store = store
        self.log_path = log_path
        self.max_rows = int(max_rows)
        self.drain_batch_rows = int(drain_batch_rows)
        self.fsync = bool(fsync)
        self.retry_after_s = float(retry_after_s)
        self._drain_delay_s = float(_drain_delay_s)  # torture-test hook
        # Per-shard drain stall (test hook): widens one shard's
        # mid-drain window without touching its siblings — the
        # partial-commit kill episodes and the flush_owner isolation
        # test steer with it.
        self._shard_delay_s: Dict[int, float] = dict(_shard_delay_s or {})

        stores, shard_index = self._shards()
        self._shard_states = [_ShardState(si) for si in range(len(stores))]
        if len(self._shard_states) == 1:
            self.db_lock = self._shard_states[0].lock
        else:
            self.db_lock = _CompositeLock(
                [st.lock for st in self._shard_states]
            )
        n = len(self._shard_states)
        if not drain_workers or int(drain_workers) <= 0:
            self.drain_workers = n
        else:
            self.drain_workers = max(1, min(int(drain_workers), n))

        self.drain_mode = "thread"
        if drain_process:
            blockers = [
                si for si, s in enumerate(stores)
                if getattr(s.db, "path", None) in (None, ":memory:")
                or hasattr(s.db, "relay_insert_packed")
            ]
            if blockers:
                log("storage", "write-behind process drain unavailable; "
                    "falling back to threads",
                    shards=blockers,
                    reason="needs pure-Python file-backed shards")
            else:
                self.drain_mode = "process"

        self._cv = threading.Condition()
        self._pending_rows = 0
        self._last_seq = 0
        # seq → outstanding slice count: a record is fully drained when
        # its last slice commits (drives backlog_records + truncation).
        self._seq_slices: Dict[int, int] = {}
        self._owner_seq: Dict[str, int] = {}  # owner → last enqueued seq
        self._owner_shard: Dict[str, int] = {}
        # Serving-state caches, maintained only while the owner has
        # pending records (SQLite is current once fully drained):
        self._trees: Dict[str, Tuple[dict, str]] = {}
        # Owners whose optimistic trees were corrected at drain: the
        # serving path must flush + re-read before trusting anything.
        self._needs_flush: Dict[str, int] = {}  # owner → seq bound
        self._stopping = False

        self._log = None
        self._log_bytes = 0
        # Set when the log file becomes unrecoverable (truncate after
        # a failed append also failed): a configured-but-dead log must
        # REFUSE admission rather than silently mint non-durable ACKs.
        self._log_poisoned = False
        if log_path is not None:
            self._open_log_and_replay()

        # Workers own shards round-robin: shard si → worker si % W.
        # With the default W == shard count that is one worker per
        # shard; a capped W time-slices several shard queues on one
        # thread but keeps every per-shard invariant (each shard still
        # has exactly ONE drainer).
        self._threads: List[threading.Thread] = []
        self._procs: Dict[int, subprocess.Popen] = {}  # worker id → child
        for wid in range(self.drain_workers):
            t = threading.Thread(
                target=self._drain_loop, args=(wid,), daemon=True,
                name=f"evolu-wb-drain-{wid}",
            )
            self._threads.append(t)
            t.start()

    # -- store topology --

    def _shards(self):
        shards = getattr(self.store, "shards", None)
        if shards is not None:
            return shards, self.store.shard_index
        return [self.store], (lambda _u: 0)

    def _worker_shards(self, wid: int) -> List[int]:
        return [st.si for st in self._shard_states
                if st.si % self.drain_workers == wid]

    def owner_lock(self, owner: str):
        """The one shard lock guarding `owner`'s rows — what per-owner
        serving reads hold so they only serialize against THEIR shard's
        drain, never the whole store."""
        _stores, shard_index = self._shards()
        return self._shard_states[shard_index(owner)].lock

    def _record_slices(self, seq: int, rec: IngestRecord,
                       now: float) -> List[_Slice]:
        _stores, shard_index = self._shards()
        offs = np.concatenate([[0], np.cumsum(rec.lens)]).astype(np.int64)
        tree_of = dict(rec.tree_rows)
        out: List[_Slice] = []
        row = 0
        for u, k in zip(rec.gu, rec.gc):
            lo, hi = row, row + k
            out.append(_Slice(
                seq, shard_index(u), u, k,
                rec.ts_packed[lo * 46 : hi * 46],
                rec.content_packed[int(offs[lo]) : int(offs[hi])],
                rec.lens[lo:hi], tree_of.get(u), now,
            ))
            row = hi
        return out

    # -- durable log --

    def _open_log_and_replay(self) -> None:
        path = self.log_path
        existing = b""
        if os.path.exists(path):
            with open(path, "rb") as f:
                existing = f.read()
        records = self._decode_log(existing)
        if records:
            metrics.inc("evolu_wb_replayed_records_total", len(records))
            metrics.inc("evolu_wb_replayed_rows_total",
                        sum(r.n_rows for r in records))
            log("storage", "write-behind log replay",
                records=len(records), path=path)
            # Replay through the always-exact path BEFORE serving (and
            # before any worker starts): an ACKed write is in SQLite by
            # the time this constructor returns. Sequential per shard —
            # replay is a cold-start path, and sequential-exact keeps
            # it deterministic.
            with self.db_lock:
                self._materialize(records, exact=True)
            # Ledger: in THIS process these rows never rode a sync POST
            # — the log replay is their ingress, and _materialize just
            # posted their inserted/duplicate terminals per shard (a
            # record whose rows a pre-crash shard commit already
            # landed reconciles as store.duplicate, never
            # double-counts — the partial-commit crash rule).
            for r in records:
                for o, k in zip(r.gu, r.gc):
                    ledger.count(ledger.INGRESS_REPLAY, k, owner=o)
        self._log = open(path, "wb")
        self._log.write(LOG_MAGIC)
        self._log.flush()
        if self.fsync:
            os.fsync(self._log.fileno())
        self._log_bytes = len(LOG_MAGIC)
        metrics.set_gauge("evolu_wb_log_bytes", self._log_bytes)

    @staticmethod
    def _decode_log(data: bytes) -> List[IngestRecord]:
        """Decode every intact record; a torn/corrupt tail (crash
        mid-append, before the ACK) is discarded — everything before
        it was either ACKed or harmless to re-apply."""
        if not data:
            return []
        if not data.startswith(LOG_MAGIC):
            raise ValueError("not an evolu write-behind log")
        pos = len(LOG_MAGIC)
        out: List[IngestRecord] = []
        while pos < len(data):
            if pos + 8 > len(data):
                break  # torn frame header
            (n,) = _U32.unpack_from(data, pos)
            (crc,) = _U32.unpack_from(data, pos + 4)
            body = data[pos + 8 : pos + 8 + n]
            if len(body) != n or zlib.crc32(body) != crc:
                break  # torn/corrupt tail — pre-ACK, discard
            out.append(IngestRecord.decode(body))
            pos += 8 + n
        return out

    def _log_append(self, records: Sequence[IngestRecord]) -> None:
        if self._log is None:
            return
        start = self._log_bytes
        try:
            for r in records:
                body = r.encode()
                self._log.write(_U32.pack(len(body)))
                self._log.write(_U32.pack(zlib.crc32(body)))
                self._log.write(body)
                self._log_bytes += 8 + len(body)
            self._log.flush()
            if self.fsync:
                os.fsync(self._log.fileno())  # the ACK point
        except BaseException:
            # Roll the file back to the pre-append length: a partial
            # frame left in place would fail its crc at replay and
            # DISCARD every later fsynced (ACKed) record behind it —
            # the exact durability violation this module forbids. If
            # even the truncate fails, poison the log so no further
            # ACKs can be minted over a corrupt tail.
            try:
                self._log.seek(start)
                self._log.truncate()
                self._log.flush()
                if self.fsync:
                    os.fsync(self._log.fileno())
            except BaseException as te:  # noqa: BLE001
                self._log.close()
                self._log = None
                self._log_poisoned = True
                metrics.inc("evolu_wb_log_poisoned_total")
                log("storage", "write-behind log unrecoverable; "
                    "admission refused until restart", error=repr(te))
            self._log_bytes = start
            raise
        metrics.set_gauge("evolu_wb_log_bytes", self._log_bytes)

    def _log_truncate_locked(self) -> None:
        """Called under `_cv` with EVERY shard queue empty: everything
        in the log is committed, so restart replay would be a pure
        no-op — reclaim the file. A crash between the last shard's
        commit and this truncate only re-replays committed records
        (idempotent)."""
        if self._log is None or self._log_bytes == len(LOG_MAGIC):
            return
        self._log.seek(0)
        self._log.truncate()
        self._log.write(LOG_MAGIC)
        self._log.flush()
        if self.fsync:
            os.fsync(self._log.fileno())
        self._log_bytes = len(LOG_MAGIC)
        metrics.set_gauge("evolu_wb_log_bytes", self._log_bytes)

    # -- admission (engine dispatcher thread) --

    def append_batch(
        self,
        records: Sequence[IngestRecord],
        trees: Optional[Dict[str, Tuple[dict, str]]] = None,
    ) -> int:
        """Admit one engine batch (one record per storage shard):
        durable log append + fsync (the ACK), then install the pending
        slices — split per shard here, so each worker's queue is ready
        the moment `notify_all` lands — and the serve-time tree cache
        atomically. Raises `WriteBehindFull` BEFORE mutating anything
        when the new rows would exceed `max_rows` — the serving path's
        trees stay consistent and the client retries after
        `retry_after`."""
        n_rows = sum(r.n_rows for r in records)
        if n_rows == 0:
            return self._last_seq
        with self._cv:
            if self._stopping:
                raise WriteBehindFull(self.retry_after_s, self._pending_rows)
            if self._log_poisoned:
                # A configured durable log that died mid-run must not
                # degrade to memory-only ACKs ("an ACKed write is
                # never lost" would become a lie held until the next
                # crash). Clients keep retrying 503; /health reports
                # failing so the fleet routes around us.
                raise WriteBehindFull(self.retry_after_s, self._pending_rows)
            if self._pending_rows + n_rows > self.max_rows and self._pending_rows:
                metrics.inc("evolu_wb_stalls_total")
                raise WriteBehindFull(self.retry_after_s, self._pending_rows)
            # The log write + ACK fsync runs under _cv — deliberate:
            # it happens once per ENGINE PASS (not per request), and
            # holding the lock is what keeps the drain's truncate
            # (also under _cv) from ever erasing a frame between its
            # fsync and its pending-install. Readers (/health, /stats,
            # serving_tree) stall at most one fsync (~ms).
            self._log_append(records)
            now = time.monotonic()
            touched: Set[int] = set()
            for r in records:
                self._last_seq += 1
                slices = self._record_slices(self._last_seq, r, now)
                if slices:
                    self._seq_slices[self._last_seq] = len(slices)
                for sl in slices:
                    st = self._shard_states[sl.si]
                    st.pending.append(sl)
                    st.rows += sl.k
                    touched.add(sl.si)
                for o in r.gu:
                    self._owner_seq[o] = self._last_seq
                    self._owner_shard[o] = self._shard_states[
                        0 if len(self._shard_states) == 1
                        else self.store.shard_index(o)
                    ].si
            self._pending_rows += n_rows
            if trees:
                self._trees.update(trees)
            metrics.inc("evolu_wb_enqueued_rows_total", n_rows)
            # Ledger checkpoint pair, queued half: these rows are ACKed
            # (fsynced) — `wb.queued == wb.drained + wb.dropped` must
            # hold at every drain barrier. Per-owner so GET /ledger can
            # show one owner's rows parked in the queue.
            for r in records:
                for o, k in zip(r.gu, r.gc):
                    ledger.count(ledger.WB_QUEUED, k, owner=o)
            self._gauges_locked(touched)
            seq = self._last_seq
            self._cv.notify_all()
        return seq

    def _gauges_locked(self, touched=None) -> None:
        metrics.set_gauge("evolu_wb_queue_rows", self._pending_rows)
        metrics.set_gauge("evolu_wb_queue_records", len(self._seq_slices))
        for st in self._shard_states:
            if touched is not None and st.si not in touched:
                continue
            # Shard labels are bounded by the store topology (engine
            # shard counts, single digits to low tens) — far inside
            # the PR-10 512-per-family label cap.
            metrics.set_gauge("evolu_wb_shard_queue_rows", st.rows,
                              shard=str(st.si))
            metrics.set_gauge("evolu_wb_shard_watermark_lag",
                              self._last_seq - self._floor_locked(st),
                              shard=str(st.si))

    # -- serving-state reads (engine dispatcher thread) --

    def serving_tree(self, owner: str) -> Optional[Tuple[dict, str]]:
        """The authoritative serve-time tree for `owner`, or None when
        SQLite is current (no pending history, or a drain-time
        correction forced a flush — in which case this WAITS for the
        owner's SHARD watermark so the subsequent SQLite read is
        exact)."""
        with self._cv:
            bound = self._needs_flush.get(owner)
            if bound is None:
                return self._trees.get(owner)
        self.flush_owner(owner)
        return None

    # -- watermarks / flushes --

    def _floor_locked(self, st: _ShardState) -> int:
        """Shard `st`'s drained watermark (caller holds `_cv`): every
        seq at or below the floor has ITS slices on this shard
        committed. An empty queue floors at the global last seq."""
        return self._last_seq if not st.pending else st.pending[0].seq - 1

    def backlog(self) -> Tuple[int, int]:
        with self._cv:
            return len(self._seq_slices), self._pending_rows

    def saturated(self) -> bool:
        with self._cv:
            return self._pending_rows >= self.max_rows

    def failing(self) -> bool:
        """True once ANY shard's drain has failed `_FAILING_AFTER`
        consecutive batches, or the durable log became unrecoverable
        (admission refused) — persistent, not a transient blip.
        Readiness gate (docs/WRITE_BEHIND.md failure modes); /health
        carries the per-shard split so failover can see WHICH shard
        is wedged."""
        with self._cv:
            return (any(st.failures >= self._FAILING_AFTER
                        for st in self._shard_states)
                    or self._log_poisoned)

    def watermarks(self) -> Tuple[int, int]:
        """(last appended seq, globally drained-and-committed seq —
        the MIN over per-shard floors)."""
        with self._cv:
            return self._last_seq, min(
                self._floor_locked(st) for st in self._shard_states
            )

    def _wait_drained(self, seq: int, timeout: Optional[float],
                      sis: Optional[Sequence[int]] = None) -> None:
        """Wait out the drain on the given shards (default: all) —
        including transient failures (each worker retries with
        backoff; a one-off SQLITE_BUSY must not abort a checkpoint or
        gossip round that would succeed 50ms later). Raise only when a
        relevant worker thread is actually DEAD with work pending, or
        on timeout (carrying the last drain error as the cause either
        way)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        states = (self._shard_states if sis is None
                  else [self._shard_states[si] for si in sis])
        wids = {st.si % self.drain_workers for st in states}
        with self._cv:
            while min(self._floor_locked(st) for st in states) < seq:
                dead = [w for w in wids
                        if not self._threads[w].is_alive()]
                if dead and not self._stopping:
                    err = next(
                        (st.err for st in states if st.err is not None), None
                    )
                    raise RuntimeError(
                        f"write-behind drain worker(s) {dead} died"
                    ) from err
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    lag = {st.si: self._floor_locked(st) for st in states
                           if self._floor_locked(st) < seq}
                    err = next(
                        (st.err for st in states if st.err is not None), None
                    )
                    raise TimeoutError(
                        f"write-behind drain did not reach seq {seq} "
                        f"(shard floors {lag})"
                    ) from err
                self._cv.wait(min(remaining or 1.0, 1.0))

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every record appended so far is committed on
        EVERY shard — the composed flush."""
        metrics.inc("evolu_wb_flushes_total", scope="all")
        with self._cv:
            seq = self._last_seq
        self._wait_drained(seq, timeout)

    def flush_owner(self, owner: str, timeout: Optional[float] = None) -> None:
        """Block until `owner`'s enqueued history is committed — waits
        on the owner's SHARD watermark only, so a backlogged or
        failing sibling shard cannot stall this owner's serves."""
        _stores, shard_index = self._shards()
        si = shard_index(owner)
        with self._cv:
            seq = self._owner_seq.get(owner, 0)
        if seq:
            metrics.inc("evolu_wb_flushes_total", scope="owner")
            self._wait_drained(seq, timeout, sis=[si])
        with self._cv:
            st = self._shard_states[si]
            if self._floor_locked(st) >= self._needs_flush.get(owner, 0):
                self._needs_flush.pop(owner, None)

    @contextmanager
    def drain_barrier(self):
        """Flush every shard, then hold EVERY shard lock (`db_lock` is
        the ascending-order composite) so no drain can restart
        underneath the caller: the whole-store read consistency point
        (snapshot capture, checkpoints, replication serves, fleet
        rebalance installs, the direct per-request write path). Loops
        until every queue is verified EMPTY while already holding the
        locks — a record ACKed in the flush-to-lock window (the
        dispatcher winning a shard lock for a tree read first) must
        not ride through the barrier, or a snapshot swap under it
        would later be overwritten by that record's pre-swap tree
        (review finding). Once empty-under-lock, SQLite alone is the
        truth, so the serve-time tree cache is dropped — any
        concurrent serve then blocks at its base-tree read until the
        barrier releases."""
        while True:
            self.flush()
            self.db_lock.acquire()
            with self._cv:
                if not any(st.pending for st in self._shard_states):
                    self._trees.clear()
                    break
            self.db_lock.release()
        try:
            yield
        finally:
            self.db_lock.release()

    # -- lifecycle --

    def reset(self) -> None:
        """Drop everything pending and truncate the log — the owner
        reset/restore + transaction-rollback semantics for embedders
        (the caller owns resetting whatever device/cache state rode on
        these rows). Takes every shard lock FIRST so in-flight drain
        transactions commit or finish before the drop — without the
        fence, rows being materialized at call time would commit
        AFTER reset() returned, resurrecting state the caller believed
        dropped (review finding)."""
        with self.db_lock, self._cv:
            dropped = self._pending_rows
            for st in self._shard_states:
                st.pending.clear()
                st.rows = 0
            self._seq_slices.clear()
            self._pending_rows = 0
            self._owner_seq.clear()
            self._owner_shard.clear()
            self._trees.clear()
            self._needs_flush.clear()
            self._log_truncate_locked()
            self._gauges_locked()
            if dropped:
                metrics.inc("evolu_wb_reset_dropped_rows_total", dropped)
                # Dropped rows are a flow TERMINAL: they ingressed and
                # were queued, and will never classify at a drain.
                ledger.count(ledger.WB_DROPPED, dropped)
            self._cv.notify_all()

    def close(self, flush: bool = True) -> None:
        if flush:
            try:
                self.flush()
            except Exception as e:  # noqa: BLE001 - still stop the threads
                log("storage", "write-behind close flush failed", error=repr(e))
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=30.0)
        for proc in self._procs.values():
            try:
                proc.stdin.close()
                proc.wait(timeout=10.0)
            except Exception:  # noqa: BLE001 - wedged child: escalate
                proc.kill()
        self._procs.clear()
        if self._log is not None:
            self._log.close()
            self._log = None

    # -- drain (one worker per shard; capped workers own shards
    #    round-robin, each shard still has exactly one drainer) --

    def _drain_loop(self, wid: int) -> None:
        my = self._worker_shards(wid)
        backoff = {si: 0.05 for si in my}
        rr = 0
        while True:
            with self._cv:
                while (not self._stopping
                       and not any(self._shard_states[si].pending
                                   for si in my)):
                    self._cv.wait()
                pick = None
                for off in range(len(my)):
                    si = my[(rr + off) % len(my)]
                    if self._shard_states[si].pending:
                        pick = si
                        rr = (rr + off + 1) % len(my)
                        break
                if pick is None:
                    return  # stopping + all owned shards drained
                st = self._shard_states[pick]
                batch: List[_Slice] = []
                rows = 0
                for sl in st.pending:
                    if batch and rows + sl.k > self.drain_batch_rows:
                        break
                    batch.append(sl)
                    rows += sl.k
                # Snapshot the carry-taint set: owners corrected by an
                # earlier drain batch whose serving path has not yet
                # re-read — their precomputed trees are stale.
                carry_taint = set(self._needs_flush)
            delay = self._drain_delay_s + self._shard_delay_s.get(pick, 0.0)
            if delay:
                time.sleep(delay)  # torture-test kill window
            t0 = time.perf_counter()
            dspan = trace.start_span(
                "wb.drain",
                attrs={"shard": pick, "slices": len(batch), "rows": rows},
            )
            ops = [(sl.owner, sl.k, sl.ts_b, sl.content_b, sl.lens, sl.tree_s)
                   for sl in batch]
            try:
                with dspan, trace.use(dspan.context):
                    with st.lock:
                        tainted = self._materialize_shard(
                            pick, ops, exact=False, carry_taint=carry_taint,
                            wid=wid,
                        )
            except Exception as e:  # noqa: BLE001 - keep draining
                metrics.inc("evolu_wb_drain_failures_total")
                metrics.inc("evolu_wb_shard_drain_failures_total",
                            shard=str(pick))
                log("storage", "write-behind shard drain batch failed; "
                    "retrying", shard=pick, error=repr(e), slices=len(batch))
                with self._cv:
                    st.err = e
                    st.failures += 1
                    self._cv.notify_all()
                if self._stopping:
                    return
                time.sleep(backoff[pick])
                backoff[pick] = min(backoff[pick] * 2, 2.0)
                continue
            backoff[pick] = 0.05
            dt = time.perf_counter() - t0
            now = time.monotonic()
            with self._cv:
                st.err = None
                st.failures = 0
                for sl in batch:
                    # A concurrent reset() may have cleared the deque;
                    # the rows are committed either way.
                    if st.pending and st.pending[0] is sl:
                        st.pending.popleft()
                        st.rows -= sl.k
                        self._pending_rows -= sl.k
                        left = self._seq_slices.get(sl.seq, 0) - 1
                        if left <= 0:
                            self._seq_slices.pop(sl.seq, None)
                        else:
                            self._seq_slices[sl.seq] = left
                    metrics.observe("evolu_wb_apply_lag_ms",
                                    (now - sl.t_enqueue) * 1e3,
                                    exemplar=dspan.trace_id)
                floor = self._floor_locked(st)
                for o in tainted:
                    # The serving path must re-read the corrected tree
                    # before folding anything else on top of it.
                    self._needs_flush[o] = self._owner_seq.get(o, floor)
                    self._trees.pop(o, None)
                # Fully-drained owners OF THIS SHARD fall back to
                # SQLite truth.
                done = [o for o, s in self._owner_seq.items()
                        if self._owner_shard.get(o) == pick and s <= floor]
                for o in done:
                    del self._owner_seq[o]
                    self._owner_shard.pop(o, None)
                    self._trees.pop(o, None)
                    if floor >= self._needs_flush.get(o, 0):
                        self._needs_flush.pop(o, None)
                if not self._seq_slices:
                    self._log_truncate_locked()
                self._gauges_locked({pick})
                self._cv.notify_all()
            metrics.inc("evolu_wb_drained_rows_total", rows)
            # Drained half of the ledger checkpoint pair; the
            # inserted/duplicate terminal split was posted by
            # _materialize_shard as this shard's transaction committed.
            for sl in batch:
                ledger.count(ledger.WB_DRAINED, sl.k, owner=sl.owner)
            metrics.observe("evolu_wb_drain_batch_rows", rows,
                            buckets=_ROW_BUCKETS, exemplar=dspan.trace_id)
            metrics.observe("evolu_wb_drain_ms", dt * 1e3,
                            exemplar=dspan.trace_id)
            metrics.observe("evolu_wb_shard_drain_ms", dt * 1e3,
                            shard=str(pick), exemplar=dspan.trace_id)
            # The host_apply stage seam, per shard: in deferred mode
            # the drain IS engine.finish_batch's btree+tree leg, so
            # the stage anatomy (obs/anatomy.py) prices it here —
            # against the same 720k rows/s/core law — instead of
            # inside the serving pass it left.
            anatomy.record_stage("host_apply", dt, rows=rows, shard=pick)

    # -- materialization --

    def _insert_rows(self, db, gu, gc, ts_packed, content_packed, lens):
        return _insert_rows(db, gu, gc, ts_packed, content_packed, lens)

    def _materialize_shard(self, si: int, ops, exact: bool, carry_taint,
                           wid: Optional[int] = None) -> Set[str]:
        """Commit one shard's ordered op list: ONE transaction, ONE
        transactional ledger entry committed iff the transaction did.
        A shard that fails re-runs ALONE (its committed siblings
        already popped their slices), so per-shard entries still leave
        every queued row at exactly one inserted/duplicate terminal.
        Caller holds the shard's lock. Returns the owners whose
        optimistic trees were corrected (always empty in `exact` mode
        — there is no optimism to correct)."""
        stores, _ = self._shards()
        entry = ledger.pending()
        try:
            if self.drain_mode == "process" and wid is not None:
                tainted, counts = self._child_apply(
                    wid, si, ops, exact, carry_taint
                )
            else:
                tainted, counts = apply_shard_ops(
                    stores[si].db, stores[si].get_merkle_tree_string,
                    ops, exact, carry_taint,
                )
        except BaseException:
            entry.abort()
            raise
        for (u, k, *_rest), (n_new, n_dup) in zip(ops, counts):
            entry.count(ledger.STORE_INSERTED, n_new, owner=u)
            entry.count(ledger.STORE_DUPLICATE, n_dup, owner=u)
        entry.commit()
        if tainted and not exact:
            metrics.inc("evolu_wb_corrected_records_total")
            metrics.inc("evolu_wb_corrected_owners_total", len(tainted))
        return set(tainted)

    def _materialize(self, records: Sequence[IngestRecord],
                     exact: bool = False) -> Set[str]:
        """Split `records` (already in seq order) by the CURRENT shard
        topology and commit them shard by shard — the replay path
        (which is how replay survives a shard-count change: the log
        stores owner groups, not shard assignments). Caller holds
        `db_lock`. Returns the union of corrected owners."""
        per_shard: Dict[int, List[tuple]] = {}
        for rec in records:
            for sl in self._record_slices(0, rec, 0.0):
                per_shard.setdefault(sl.si, []).append(
                    (sl.owner, sl.k, sl.ts_b, sl.content_b, sl.lens,
                     sl.tree_s)
                )
        with self._cv:
            carry_taint = set(self._needs_flush)
        tainted: Set[str] = set()
        for si, ops in per_shard.items():
            tainted |= self._materialize_shard(
                si, ops, exact=exact, carry_taint=carry_taint
            )
        return tainted

    # -- process-per-shard drain (pure-Python file-backed stores) --

    def _child_spawn(self, wid: int) -> subprocess.Popen:
        stores, _ = self._shards()
        args = [sys.executable, "-m", "evolu_tpu.storage._wb_shard_proc"]
        for si in self._worker_shards(wid):
            args += ["--shard", f"{si}={stores[si].db.path}"]
        env = dict(os.environ)
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = (
            repo + (os.pathsep + env["PYTHONPATH"]
                    if env.get("PYTHONPATH") else "")
        )
        proc = subprocess.Popen(
            args, env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        )
        metrics.inc("evolu_wb_shard_proc_spawned_total")
        return proc

    def _child_apply(self, wid: int, si: int, ops, exact: bool,
                     carry_taint) -> Tuple[Set[str], List[Tuple[int, int]]]:
        """One shard batch over the worker's child pipe. The blocking
        pipe read drops the GIL while the child runs the transaction —
        that wait IS the per-core overlap. A dead child is a drain
        failure like any other: the worker restarts it and retries the
        batch; rows the child committed before dying re-classify as
        duplicates on the retry (the same rule SIGKILL replay runs)."""
        proc = self._procs.get(wid)
        if proc is None or proc.poll() is not None:
            proc = self._procs[wid] = self._child_spawn(wid)
        header = json.dumps({
            "si": si,
            "exact": bool(exact),
            "taint": sorted(carry_taint),
            "ops": [
                {"u": u, "k": int(k), "lens": [int(x) for x in lens],
                 "tree": tree_s}
                for (u, k, _ts, _c, lens, tree_s) in ops
            ],
        }).encode("utf-8")
        blob = b"".join(ts for (_u, _k, ts, _c, _l, _t) in ops) + b"".join(
            c for (_u, _k, _ts, c, _l, _t) in ops
        )
        try:
            proc.stdin.write(_U32.pack(len(header)) + header
                             + _U32.pack(len(blob)) + blob)
            proc.stdin.flush()
            raw = proc.stdout.read(4)
            if len(raw) != 4:
                raise RuntimeError("wb shard child closed the pipe")
            (n,) = _U32.unpack(raw)
            resp = json.loads(proc.stdout.read(n).decode("utf-8"))
        except BaseException:
            # Any pipe-level failure orphans the child's state: kill
            # and respawn on the retry (SQLite rolled back anything
            # uncommitted; committed rows dedup on the retry).
            try:
                proc.kill()
            except Exception:  # noqa: BLE001
                pass
            self._procs.pop(wid, None)
            raise
        if not resp.get("ok"):
            raise RuntimeError(
                f"wb shard child failed: {resp.get('error', 'unknown')}"
            )
        return set(resp["tainted"]), [tuple(c) for c in resp["counts"]]

    # -- observability --

    def shard_payloads(self) -> List[dict]:
        """Per-shard backlog/watermark/failure rows for /stats and
        /health — what lets PR-6 failover (and an operator) see WHICH
        shard is backlogged or wedged instead of one blended number."""
        with self._cv:
            last = self._last_seq
            out = []
            for st in self._shard_states:
                floor = self._floor_locked(st)
                out.append({
                    "shard": st.si,
                    "worker": st.si % self.drain_workers,
                    "backlog_slices": len(st.pending),
                    "backlog_rows": st.rows,
                    "drained_floor": floor,
                    "watermark_lag": last - floor,
                    "drain_failures_consecutive": st.failures,
                    "failing": st.failures >= self._FAILING_AFTER,
                })
        return out

    def stats_payload(self) -> dict:
        records, rows = self.backlog()
        last, drained = self.watermarks()
        return {
            "backlog_records": records,
            "backlog_rows": rows,
            "last_seq": last,
            "drained_seq": drained,
            "saturated": rows >= self.max_rows,
            "max_rows": self.max_rows,
            "drain_mode": self.drain_mode,
            "drain_workers": self.drain_workers,
            "shards": self.shard_payloads(),
            "log_bytes": self._log_bytes,
            "log_path": self.log_path,
            "enqueued_rows": metrics.get_counter("evolu_wb_enqueued_rows_total"),
            "drained_rows": metrics.get_counter("evolu_wb_drained_rows_total"),
            "corrected_owners": metrics.get_counter(
                "evolu_wb_corrected_owners_total"
            ),
            "replayed_records": metrics.get_counter(
                "evolu_wb_replayed_records_total"
            ),
            "stalls": metrics.get_counter("evolu_wb_stalls_total"),
            "flushes": (
                metrics.get_counter("evolu_wb_flushes_total", scope="all")
                + metrics.get_counter("evolu_wb_flushes_total", scope="owner")
            ),
            "drain_failures": metrics.get_counter(
                "evolu_wb_drain_failures_total"
            ),
            "apply_lag_ms_p50": metrics.quantile("evolu_wb_apply_lag_ms", 0.50),
            "apply_lag_ms_p99": metrics.quantile("evolu_wb_apply_lag_ms", 0.99),
        }

    def health_payload(self) -> dict:
        records, rows = self.backlog()
        last, drained = self.watermarks()
        shards = self.shard_payloads()
        with self._cv:
            poisoned = self._log_poisoned
        failures = max((s["drain_failures_consecutive"] for s in shards),
                       default=0)
        return {
            "backlog_records": records,
            "backlog_rows": rows,
            "last_seq": last,
            "drained_seq": drained,
            "saturated": rows >= self.max_rows,
            "drain_mode": self.drain_mode,
            "drain_workers": self.drain_workers,
            "shards": shards,
            "drain_failures_consecutive": failures,
            "log_poisoned": poisoned,
            "failing": failures >= self._FAILING_AFTER or poisoned,
        }
