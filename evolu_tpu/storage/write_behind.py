"""Bounded async write-behind materializer: SQLite off the serving path.

PR-11 (ROADMAP #1) inverts the engine's storage architecture. The
serving path (`server/engine.BatchReconciler.run_batch_wire`) answers
sync responses and Merkle questions from in-memory authoritative state
— per-owner trees folded from the device hash kernel's deltas — and
hands SQLite materialization to this queue. The btree (measured wall:
~0.72M rows/s/core, multi-row INSERT already a recorded negative
result) is drained by ONE background thread in batches sized for it,
off the request path.

Durability contract (the "ACKed write is never lost" floor):
- Every appended record is framed (length + crc32) into an append-only
  log and fsync'd BEFORE `append_batch` returns — the ACK point. A
  torn tail (crash mid-write) fails its crc and is discarded on
  replay; everything before it replays.
- Replay is idempotent and EXACT: message inserts are PK-deduped
  (INSERT OR IGNORE), and replay recomputes every owner tree from the
  per-row was-new flags through the host oracle fold
  (`core.merkle.minute_deltas_host`) — byte-identical to a
  synchronous-apply twin regardless of where the crash landed
  (mid-queue, mid-drain, mid-checkpoint; the torture episode in
  tests/test_model_check.py is the license).
- The log truncates only once fully drained AND committed; a crash
  between commit and truncate just replays committed records (no-ops).
- SQLite durability past the drain commit is SQLite's own (WAL +
  synchronous=NORMAL survives process crash; the log covers the
  undrained tail).

Ordering and exactness:
- Records drain strictly in append (seq) order; an owner's history is
  only ever appended from the one engine dispatcher thread, so
  per-owner order is total.
- The engine's serve-time trees are OPTIMISTIC: every in-batch-deduped
  row XORs (it cannot see rows already stored without touching the
  btree). The drain compares against the INSERT's was-new flags: a
  clean record (steady state — all rows new) lands its precomputed
  tree string verbatim; a record with any already-stored row gets its
  owner's tree recomputed exactly from the new rows only, the owner's
  serving cache entry is dropped, and later pending records of that
  owner (whose precomputed trees were folded on the stale optimistic
  base) recompute too, until the serving path has re-read the
  corrected tree (`_needs_flush` handshake). Steady state pays zero
  Python per-row work; duplicate delivery converges to the oracle
  state at drain latency.

Backpressure is explicit: a full queue raises `WriteBehindFull` before
mutating anything — the scheduler maps it to its 503 + Retry-After
path (queue-full stalls admission, never drops).

Concurrency: the drain thread is a second writer on the store's
connections. `db_lock` serializes transactional SQLite use between
the drain and any serving-path read (tree reads, response message
fetches); `drain_barrier()` (flush + hold `db_lock`) is the
whole-store consistency point used by snapshot capture, checkpoints,
replication reads, and the direct per-request write path.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from collections import deque
from contextlib import contextmanager
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from evolu_tpu.obs import ledger, metrics, trace
from evolu_tpu.utils.log import log

LOG_MAGIC = b"EVOLUWB1\n"
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")

# Histogram buckets for drain batch sizes (rows) — reuse the count scale.
_ROW_BUCKETS = metrics.COUNT_BUCKETS


class WriteBehindFull(Exception):
    """Admission backpressure: the pending queue is at capacity. The
    caller should stall the write (the scheduler answers 503 +
    `retry_after` seconds) — never drop it."""

    def __init__(self, retry_after: float, backlog_rows: int):
        super().__init__(
            f"write-behind queue full ({backlog_rows} rows pending); "
            f"retry after {retry_after}s"
        )
        self.retry_after = retry_after
        self.backlog_rows = backlog_rows


class IngestRecord:
    """One shard's slice of one engine batch: the packed row buffers
    exactly as `engine.start_batch` built them (no repacking), plus the
    optimistic per-owner tree strings computed at serve time. The
    on-disk frame is length+crc-guarded; decode raises ValueError on
    any corruption (the wire-decoder contract)."""

    __slots__ = ("gu", "gc", "ts_packed", "content_packed", "lens", "tree_rows")

    def __init__(self, gu: Sequence[str], gc: Sequence[int], ts_packed: bytes,
                 content_packed: bytes, lens, tree_rows: Sequence[Tuple[str, str]]):
        self.gu = list(gu)
        self.gc = [int(c) for c in gc]
        self.ts_packed = ts_packed
        self.content_packed = content_packed
        self.lens = np.ascontiguousarray(lens, dtype=np.int32)
        self.tree_rows = list(tree_rows)

    @property
    def n_rows(self) -> int:
        return int(len(self.lens))

    def encode(self) -> bytes:
        parts: List[bytes] = [_U32.pack(len(self.gu))]
        for u, c in zip(self.gu, self.gc):
            ub = u.encode("utf-8")
            parts.append(_U16.pack(len(ub)))
            parts.append(ub)
            parts.append(_U32.pack(c))
        parts.append(_U32.pack(len(self.ts_packed)))
        parts.append(self.ts_packed)
        parts.append(_U32.pack(len(self.content_packed)))
        parts.append(self.content_packed)
        lens = self.lens.astype("<i4", copy=False)
        parts.append(_U32.pack(len(lens)))
        parts.append(lens.tobytes())
        parts.append(_U32.pack(len(self.tree_rows)))
        for u, t in self.tree_rows:
            ub, tb = u.encode("utf-8"), t.encode("utf-8")
            parts.append(_U16.pack(len(ub)))
            parts.append(ub)
            parts.append(_U32.pack(len(tb)))
            parts.append(tb)
        return b"".join(parts)

    @staticmethod
    def decode(body: bytes) -> "IngestRecord":
        def take(n: int) -> bytes:
            nonlocal pos
            if pos + n > len(body):
                raise ValueError("truncated write-behind record")
            out = body[pos : pos + n]
            pos += n
            return out

        pos = 0
        (n_groups,) = _U32.unpack(take(4))
        gu: List[str] = []
        gc: List[int] = []
        for _ in range(n_groups):
            (ul,) = _U16.unpack(take(2))
            gu.append(take(ul).decode("utf-8"))
            gc.append(_U32.unpack(take(4))[0])
        (tl,) = _U32.unpack(take(4))
        ts_packed = take(tl)
        (cl,) = _U32.unpack(take(4))
        content_packed = take(cl)
        (nl,) = _U32.unpack(take(4))
        lens = np.frombuffer(take(4 * nl), dtype="<i4").astype(np.int32)
        (n_trees,) = _U32.unpack(take(4))
        tree_rows: List[Tuple[str, str]] = []
        for _ in range(n_trees):
            (ul,) = _U16.unpack(take(2))
            u = take(ul).decode("utf-8")
            (sl,) = _U32.unpack(take(4))
            tree_rows.append((u, take(sl).decode("utf-8")))
        if pos != len(body):
            raise ValueError("trailing bytes after write-behind record")
        if sum(gc) != len(lens) or len(ts_packed) != 46 * len(lens):
            raise ValueError("write-behind record shape mismatch")
        if int(lens.sum()) != len(content_packed):
            raise ValueError("write-behind record content size mismatch")
        return IngestRecord(gu, gc, ts_packed, content_packed, lens, tree_rows)

class _Pending:
    __slots__ = ("seq", "record", "t_enqueue")

    def __init__(self, seq: int, record: IngestRecord, t_enqueue: float):
        self.seq = seq
        self.record = record
        self.t_enqueue = t_enqueue


class WriteBehindQueue:
    """The bounded, ordered, crash-safe materialization queue for one
    relay store (RelayStore or ShardedRelayStore — records route to
    shards at DRAIN time by the store's stable owner hash, so replay
    survives a shard-count change).

    `exact_replay` note: materialization runs in two modes. The normal
    drain trusts each record's precomputed tree strings while the
    INSERT's was-new flags say every row was new; replay (and tainted
    owners) recompute trees from the flags through the host oracle
    fold — always exact, never fast-pathed."""

    # Consecutive failed drain batches before `failing()` trips the
    # relay's /health readiness gate (the drain itself retries forever).
    _FAILING_AFTER = 3

    def __init__(
        self,
        store,
        log_path: Optional[str] = None,
        max_rows: int = 1 << 20,
        drain_batch_rows: int = 1 << 16,
        fsync: bool = True,
        retry_after_s: float = 1.0,
        _drain_delay_s: float = 0.0,
    ):
        self.store = store
        self.log_path = log_path
        self.max_rows = int(max_rows)
        self.drain_batch_rows = int(drain_batch_rows)
        self.fsync = bool(fsync)
        self.retry_after_s = float(retry_after_s)
        self._drain_delay_s = float(_drain_delay_s)  # torture-test hook

        self._cv = threading.Condition()
        self.db_lock = threading.RLock()
        self._pending: Deque[_Pending] = deque()
        self._pending_rows = 0
        self._last_seq = 0
        self._drained_seq = 0
        self._owner_seq: Dict[str, int] = {}  # owner → last enqueued seq
        # Serving-state caches, maintained only while the owner has
        # pending records (SQLite is current once fully drained):
        self._trees: Dict[str, Tuple[dict, str]] = {}
        # Owners whose optimistic trees were corrected at drain: the
        # serving path must flush + re-read before trusting anything.
        self._needs_flush: Dict[str, int] = {}  # owner → seq bound
        self._stopping = False
        self._drain_err: Optional[BaseException] = None
        # Consecutive failed drain batches. The drain retries forever
        # (a transient SQLITE_BUSY must not lose records), so a
        # PERSISTENT failure (full disk, poisoned record) must surface
        # through readiness instead: past _FAILING_AFTER the relay's
        # /health answers 503 and fleet failover routes around us.
        self._drain_failures = 0

        self._log = None
        self._log_bytes = 0
        # Set when the log file becomes unrecoverable (truncate after
        # a failed append also failed): a configured-but-dead log must
        # REFUSE admission rather than silently mint non-durable ACKs.
        self._log_poisoned = False
        if log_path is not None:
            self._open_log_and_replay()

        self._thread = threading.Thread(
            target=self._drain_loop, daemon=True, name="evolu-wb-drain"
        )
        self._thread.start()

    # -- store topology --

    def _shards(self):
        shards = getattr(self.store, "shards", None)
        if shards is not None:
            return shards, self.store.shard_index
        return [self.store], (lambda _u: 0)

    # -- durable log --

    def _open_log_and_replay(self) -> None:
        path = self.log_path
        existing = b""
        if os.path.exists(path):
            with open(path, "rb") as f:
                existing = f.read()
        records = self._decode_log(existing)
        if records:
            metrics.inc("evolu_wb_replayed_records_total", len(records))
            metrics.inc("evolu_wb_replayed_rows_total",
                        sum(r.n_rows for r in records))
            log("storage", "write-behind log replay",
                records=len(records), path=path)
            # Replay through the always-exact path BEFORE serving: an
            # ACKed write is in SQLite by the time this constructor
            # returns.
            with self.db_lock:
                self._materialize(records, exact=True)
            # Ledger: in THIS process these rows never rode a sync POST
            # — the log replay is their ingress, and _materialize just
            # posted their inserted/duplicate terminals (a record whose
            # rows pre-crash drains already committed reconciles as
            # store.duplicate, never double-counts).
            for r in records:
                for o, k in zip(r.gu, r.gc):
                    ledger.count(ledger.INGRESS_REPLAY, k, owner=o)
        self._log = open(path, "wb")
        self._log.write(LOG_MAGIC)
        self._log.flush()
        if self.fsync:
            os.fsync(self._log.fileno())
        self._log_bytes = len(LOG_MAGIC)
        metrics.set_gauge("evolu_wb_log_bytes", self._log_bytes)

    @staticmethod
    def _decode_log(data: bytes) -> List[IngestRecord]:
        """Decode every intact record; a torn/corrupt tail (crash
        mid-append, before the ACK) is discarded — everything before
        it was either ACKed or harmless to re-apply."""
        if not data:
            return []
        if not data.startswith(LOG_MAGIC):
            raise ValueError("not an evolu write-behind log")
        pos = len(LOG_MAGIC)
        out: List[IngestRecord] = []
        while pos < len(data):
            if pos + 8 > len(data):
                break  # torn frame header
            (n,) = _U32.unpack_from(data, pos)
            (crc,) = _U32.unpack_from(data, pos + 4)
            body = data[pos + 8 : pos + 8 + n]
            if len(body) != n or zlib.crc32(body) != crc:
                break  # torn/corrupt tail — pre-ACK, discard
            out.append(IngestRecord.decode(body))
            pos += 8 + n
        return out

    def _log_append(self, records: Sequence[IngestRecord]) -> None:
        if self._log is None:
            return
        start = self._log_bytes
        try:
            for r in records:
                body = r.encode()
                self._log.write(_U32.pack(len(body)))
                self._log.write(_U32.pack(zlib.crc32(body)))
                self._log.write(body)
                self._log_bytes += 8 + len(body)
            self._log.flush()
            if self.fsync:
                os.fsync(self._log.fileno())  # the ACK point
        except BaseException:
            # Roll the file back to the pre-append length: a partial
            # frame left in place would fail its crc at replay and
            # DISCARD every later fsynced (ACKed) record behind it —
            # the exact durability violation this module forbids. If
            # even the truncate fails, poison the log so no further
            # ACKs can be minted over a corrupt tail.
            try:
                self._log.seek(start)
                self._log.truncate()
                self._log.flush()
                if self.fsync:
                    os.fsync(self._log.fileno())
            except BaseException as te:  # noqa: BLE001
                self._log.close()
                self._log = None
                self._log_poisoned = True
                metrics.inc("evolu_wb_log_poisoned_total")
                log("storage", "write-behind log unrecoverable; "
                    "admission refused until restart", error=repr(te))
            self._log_bytes = start
            raise
        metrics.set_gauge("evolu_wb_log_bytes", self._log_bytes)

    def _log_truncate_locked(self) -> None:
        """Called under `_cv` with the queue empty: everything in the
        log is committed, so restart replay would be a pure no-op —
        reclaim the file. A crash between the drain commit and this
        truncate only re-replays committed records (idempotent)."""
        if self._log is None or self._log_bytes == len(LOG_MAGIC):
            return
        self._log.seek(0)
        self._log.truncate()
        self._log.write(LOG_MAGIC)
        self._log.flush()
        if self.fsync:
            os.fsync(self._log.fileno())
        self._log_bytes = len(LOG_MAGIC)
        metrics.set_gauge("evolu_wb_log_bytes", self._log_bytes)

    # -- admission (engine dispatcher thread) --

    def append_batch(
        self,
        records: Sequence[IngestRecord],
        trees: Optional[Dict[str, Tuple[dict, str]]] = None,
    ) -> int:
        """Admit one engine batch (one record per storage shard):
        durable log append + fsync (the ACK), then install the pending
        records and the serve-time tree cache atomically. Raises
        `WriteBehindFull` BEFORE mutating anything when the new rows
        would exceed `max_rows` — the serving path's trees stay
        consistent and the client retries after `retry_after`."""
        n_rows = sum(r.n_rows for r in records)
        if n_rows == 0:
            return self._last_seq
        with self._cv:
            if self._stopping:
                raise WriteBehindFull(self.retry_after_s, self._pending_rows)
            if self._log_poisoned:
                # A configured durable log that died mid-run must not
                # degrade to memory-only ACKs ("an ACKed write is
                # never lost" would become a lie held until the next
                # crash). Clients keep retrying 503; /health reports
                # failing so the fleet routes around us.
                raise WriteBehindFull(self.retry_after_s, self._pending_rows)
            if self._pending_rows + n_rows > self.max_rows and self._pending_rows:
                metrics.inc("evolu_wb_stalls_total")
                raise WriteBehindFull(self.retry_after_s, self._pending_rows)
            # The log write + ACK fsync runs under _cv — deliberate:
            # it happens once per ENGINE PASS (not per request), and
            # holding the lock is what keeps the drain's truncate
            # (also under _cv) from ever erasing a frame between its
            # fsync and its pending-install. Readers (/health, /stats,
            # serving_tree) stall at most one fsync (~ms).
            self._log_append(records)
            now = time.monotonic()
            for r in records:
                self._last_seq += 1
                self._pending.append(_Pending(self._last_seq, r, now))
                for o in r.gu:
                    self._owner_seq[o] = self._last_seq
            self._pending_rows += n_rows
            if trees:
                self._trees.update(trees)
            metrics.inc("evolu_wb_enqueued_rows_total", n_rows)
            # Ledger checkpoint pair, queued half: these rows are ACKed
            # (fsynced) — `wb.queued == wb.drained + wb.dropped` must
            # hold at every drain barrier. Per-owner so GET /ledger can
            # show one owner's rows parked in the queue.
            for r in records:
                for o, k in zip(r.gu, r.gc):
                    ledger.count(ledger.WB_QUEUED, k, owner=o)
            metrics.set_gauge("evolu_wb_queue_rows", self._pending_rows)
            metrics.set_gauge("evolu_wb_queue_records", len(self._pending))
            seq = self._last_seq
            self._cv.notify_all()
        return seq

    # -- serving-state reads (engine dispatcher thread) --

    def serving_tree(self, owner: str) -> Optional[Tuple[dict, str]]:
        """The authoritative serve-time tree for `owner`, or None when
        SQLite is current (no pending history, or a drain-time
        correction forced a flush — in which case this WAITS for the
        owner's watermark so the subsequent SQLite read is exact)."""
        with self._cv:
            bound = self._needs_flush.get(owner)
            if bound is None:
                return self._trees.get(owner)
        self.flush_owner(owner)
        return None

    # -- watermarks / flushes --

    def backlog(self) -> Tuple[int, int]:
        with self._cv:
            return len(self._pending), self._pending_rows

    def saturated(self) -> bool:
        with self._cv:
            return self._pending_rows >= self.max_rows

    def failing(self) -> bool:
        """True once the drain has failed `_FAILING_AFTER` consecutive
        batches, or the durable log became unrecoverable (admission
        refused) — persistent, not a transient blip. Readiness gate
        (docs/WRITE_BEHIND.md failure modes)."""
        with self._cv:
            return (self._drain_failures >= self._FAILING_AFTER
                    or self._log_poisoned)

    def watermarks(self) -> Tuple[int, int]:
        """(last appended seq, drained-and-committed seq)."""
        with self._cv:
            return self._last_seq, self._drained_seq

    def _wait_drained(self, seq: int, timeout: Optional[float]) -> None:
        """Wait out the drain — including its transient failures (it
        retries with backoff; a one-off SQLITE_BUSY must not abort a
        checkpoint or gossip round that would succeed 50ms later).
        Raise only when the drain thread is actually DEAD with work
        pending, or on timeout (carrying the last drain error as the
        cause either way)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._drained_seq < seq:
                if not self._thread.is_alive() and not self._stopping:
                    raise RuntimeError(
                        "write-behind drain thread died"
                    ) from self._drain_err
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"write-behind drain did not reach seq {seq} "
                        f"(at {self._drained_seq})"
                    ) from self._drain_err
                self._cv.wait(min(remaining or 1.0, 1.0))

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every record appended so far is committed."""
        metrics.inc("evolu_wb_flushes_total", scope="all")
        with self._cv:
            seq = self._last_seq
        self._wait_drained(seq, timeout)

    def flush_owner(self, owner: str, timeout: Optional[float] = None) -> None:
        """Block until `owner`'s enqueued history is committed — the
        per-owner drain watermark reads that need SQLite wait on."""
        with self._cv:
            seq = self._owner_seq.get(owner, 0)
        if seq:
            metrics.inc("evolu_wb_flushes_total", scope="owner")
            self._wait_drained(seq, timeout)
        with self._cv:
            if self._drained_seq >= self._needs_flush.get(owner, 0):
                self._needs_flush.pop(owner, None)

    @contextmanager
    def drain_barrier(self):
        """Flush everything, then hold `db_lock` so the drain cannot
        restart underneath the caller: the whole-store read consistency
        point (snapshot capture, checkpoints, replication serves, the
        direct per-request write path). Loops until the queue is
        verified EMPTY while already holding the lock — a record ACKed
        in the flush-to-lock window (the dispatcher winning `db_lock`
        for a tree read first) must not ride through the barrier, or a
        snapshot swap under it would later be overwritten by that
        record's pre-swap tree (review finding). Once empty-under-lock,
        SQLite alone is the truth, so the serve-time tree cache is
        dropped — any concurrent serve then blocks at its base-tree
        read until the barrier releases."""
        while True:
            self.flush()
            self.db_lock.acquire()
            with self._cv:
                if not self._pending:
                    self._trees.clear()
                    break
            self.db_lock.release()
        try:
            yield
        finally:
            self.db_lock.release()

    # -- lifecycle --

    def reset(self) -> None:
        """Drop everything pending and truncate the log — the owner
        reset/restore + transaction-rollback semantics for embedders
        (the caller owns resetting whatever device/cache state rode on
        these rows). Takes `db_lock` FIRST so an in-flight drain
        transaction commits or finishes before the drop — without the
        fence, rows being materialized at call time would commit
        AFTER reset() returned, resurrecting state the caller believed
        dropped (review finding)."""
        with self.db_lock, self._cv:
            dropped = self._pending_rows
            self._pending.clear()
            self._pending_rows = 0
            self._drained_seq = self._last_seq
            self._owner_seq.clear()
            self._trees.clear()
            self._needs_flush.clear()
            self._log_truncate_locked()
            metrics.set_gauge("evolu_wb_queue_rows", 0)
            metrics.set_gauge("evolu_wb_queue_records", 0)
            if dropped:
                metrics.inc("evolu_wb_reset_dropped_rows_total", dropped)
                # Dropped rows are a flow TERMINAL: they ingressed and
                # were queued, and will never classify at a drain.
                ledger.count(ledger.WB_DROPPED, dropped)
            self._cv.notify_all()

    def close(self, flush: bool = True) -> None:
        if flush:
            try:
                self.flush()
            except Exception as e:  # noqa: BLE001 - still stop the thread
                log("storage", "write-behind close flush failed", error=repr(e))
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        self._thread.join(timeout=30.0)
        if self._log is not None:
            self._log.close()
            self._log = None

    # -- drain (one background thread) --

    def _drain_loop(self) -> None:
        backoff = 0.05
        while True:
            with self._cv:
                while not self._pending and not self._stopping:
                    self._cv.wait()
                if not self._pending:
                    return  # stopping + drained
                batch: List[_Pending] = []
                rows = 0
                for p in self._pending:
                    if batch and rows + p.record.n_rows > self.drain_batch_rows:
                        break
                    batch.append(p)
                    rows += p.record.n_rows
            t0 = time.perf_counter()
            dspan = trace.start_span(
                "wb.drain", attrs={"records": len(batch), "rows": rows}
            )
            try:
                with dspan, trace.use(dspan.context):
                    with self.db_lock:
                        tainted = self._materialize([p.record for p in batch])
            except Exception as e:  # noqa: BLE001 - keep draining
                metrics.inc("evolu_wb_drain_failures_total")
                log("storage", "write-behind drain batch failed; retrying",
                    error=repr(e), records=len(batch))
                with self._cv:
                    self._drain_err = e
                    self._drain_failures += 1
                    self._cv.notify_all()
                if self._stopping:
                    return
                time.sleep(backoff)
                backoff = min(backoff * 2, 2.0)
                continue
            backoff = 0.05
            now = time.monotonic()
            with self._cv:
                self._drain_err = None
                self._drain_failures = 0
                top = batch[-1].seq
                for p in batch:
                    # A concurrent reset() may have cleared the deque;
                    # the rows are committed either way.
                    if self._pending and self._pending[0] is p:
                        self._pending.popleft()
                        self._pending_rows -= p.record.n_rows
                    metrics.observe("evolu_wb_apply_lag_ms",
                                    (now - p.t_enqueue) * 1e3,
                                    exemplar=dspan.trace_id)
                self._drained_seq = max(self._drained_seq, top)
                for o in tainted:
                    # The serving path must re-read the corrected tree
                    # before folding anything else on top of it.
                    self._needs_flush[o] = self._owner_seq.get(o, top)
                    self._trees.pop(o, None)
                # Fully-drained owners fall back to SQLite truth.
                for o in [o for o, s in self._owner_seq.items() if s <= top]:
                    del self._owner_seq[o]
                    self._trees.pop(o, None)
                    if self._drained_seq >= self._needs_flush.get(o, 0):
                        self._needs_flush.pop(o, None)
                if not self._pending:
                    self._log_truncate_locked()
                metrics.set_gauge("evolu_wb_queue_rows", self._pending_rows)
                metrics.set_gauge("evolu_wb_queue_records", len(self._pending))
                self._cv.notify_all()
            metrics.inc("evolu_wb_drained_rows_total", rows)
            # Drained half of the ledger checkpoint pair; the
            # inserted/duplicate terminal split was posted per shard by
            # _materialize as each transaction committed.
            for p in batch:
                for o, k in zip(p.record.gu, p.record.gc):
                    ledger.count(ledger.WB_DRAINED, k, owner=o)
            metrics.observe("evolu_wb_drain_batch_rows", rows,
                            buckets=_ROW_BUCKETS, exemplar=dspan.trace_id)
            metrics.observe("evolu_wb_drain_ms",
                            (time.perf_counter() - t0) * 1e3,
                            exemplar=dspan.trace_id)

    # -- materialization --

    def _insert_rows(self, db, gu, gc, ts_packed, content_packed, lens):
        """INSERT OR IGNORE one record slice → per-row was-new flags.
        Packed C call where available; generic per-row SQL otherwise
        (replay must work on any backend the store opens with)."""
        if hasattr(db, "relay_insert_packed"):
            return db.relay_insert_packed(gu, gc, ts_packed, content_packed, lens)
        flags = np.zeros(int(sum(gc)), bool)
        offs = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
        row = 0
        for u, k in zip(gu, gc):
            for _ in range(k):
                ts = ts_packed[row * 46 : (row + 1) * 46].decode("ascii")
                content = content_packed[offs[row] : offs[row + 1]]
                flags[row] = (
                    db.run(
                        'INSERT OR IGNORE INTO "message" '
                        '("timestamp", "userId", "content") VALUES (?, ?, ?)',
                        (ts, u, content),
                    )
                    == 1
                )
                row += 1
        return flags

    def _materialize(self, records: Sequence[IngestRecord],
                     exact: bool = False) -> set:
        """Commit `records` (already in seq order) into the store: one
        transaction per touched shard, message inserts per record in
        order, then the LAST tree per owner. Returns the set of owners
        whose optimistic trees were corrected (always empty in `exact`
        mode — there is no optimism to correct). Caller holds db_lock."""
        from evolu_tpu.core.merkle import (
            apply_prefix_xors,
            merkle_tree_from_string,
            merkle_tree_to_string,
            minute_deltas_host,
        )

        stores, shard_index = self._shards()
        # Split each record's owner groups by CURRENT shard topology
        # (replay survives a shard-count change), preserving order.
        per_shard: Dict[int, List[tuple]] = {}
        for rec in records:
            row = 0
            offs = np.concatenate([[0], np.cumsum(rec.lens)]).astype(np.int64)
            tree_of = dict(rec.tree_rows)
            for u, k in zip(rec.gu, rec.gc):
                si = shard_index(u)
                lo, hi = row, row + k
                per_shard.setdefault(si, []).append(
                    (rec, u, k,
                     rec.ts_packed[lo * 46 : hi * 46],
                     rec.content_packed[offs[lo] : offs[hi]],
                     rec.lens[lo:hi],
                     tree_of.get(u))
                )
                row = hi
        tainted: set = set()
        if self._drain_delay_s:
            time.sleep(self._drain_delay_s)  # torture-test kill window
        with self._cv:
            # Owners corrected by an earlier drain batch whose serving
            # path has not yet re-read: their precomputed trees are
            # stale up to the recorded seq bound.
            carry_taint = dict(self._needs_flush)
        # Ledger terminals accumulate into ONE pending entry across all
        # shards, committed only when EVERY shard transaction did: a
        # drain batch that fails on shard k re-runs whole (shards that
        # already committed re-classify their rows as duplicates on the
        # retry), so posting per shard would double-count — posting
        # once per fully-successful materialize keeps each queued row
        # at exactly one terminal (obs/ledger.py).
        entry = ledger.pending()
        for si, ops in per_shard.items():
            db = stores[si].db
            with db.transaction():
                cur: Dict[str, str] = {}  # owner → tree string (in-txn truth)
                for (rec, u, k, ts_b, content_b, lens, tree_s) in ops:
                    flags = np.asarray(
                        self._insert_rows(db, [u], [k], ts_b, content_b, lens)
                    )
                    n_new = int(flags.sum())
                    entry.count(ledger.STORE_INSERTED, n_new, owner=u)
                    entry.count(ledger.STORE_DUPLICATE, k - n_new, owner=u)
                    clean = bool(flags.all())
                    if (not exact and clean and u not in tainted
                            and u not in carry_taint):
                        if tree_s is not None:
                            cur[u] = tree_s
                        continue
                    # Exact path: fold the NEW rows only onto the
                    # current stored tree — the host oracle fold, the
                    # same semantics a synchronous apply would have had.
                    # Correction counters only for LIVE drains: replay
                    # (`exact`) re-applies committed records whose rows
                    # are legitimately not-new — counting those would
                    # read as phantom duplicate-delivery after every
                    # restart (evolu_wb_replayed_* covers replay).
                    if not clean and not exact:
                        tainted.add(u)
                        metrics.inc("evolu_wb_corrected_records_total")
                    base = cur.get(u)
                    if base is None:
                        base = stores[si].get_merkle_tree_string(u)
                    new_ts = [
                        ts_b[i * 46 : (i + 1) * 46].decode("ascii")
                        for i in range(k)
                        if bool(flags[i])
                    ]
                    if new_ts:
                        deltas, _d = minute_deltas_host(new_ts)
                        tree = apply_prefix_xors(
                            merkle_tree_from_string(base), deltas
                        )
                        cur[u] = merkle_tree_to_string(tree)
                    # No new rows → the tree is unchanged; writing the
                    # read-back base would mint a merkleTree row (e.g.
                    # "{}") the synchronous oracle never writes.
                for u, s in cur.items():
                    db.run(
                        'INSERT OR REPLACE INTO "merkleTree" '
                        '("userId", "merkleTree") VALUES (?, ?)',
                        (u, s),
                    )
        entry.commit()
        if tainted:
            metrics.inc("evolu_wb_corrected_owners_total", len(tainted))
        return tainted

    # -- observability --

    def stats_payload(self) -> dict:
        records, rows = self.backlog()
        last, drained = self.watermarks()
        return {
            "backlog_records": records,
            "backlog_rows": rows,
            "last_seq": last,
            "drained_seq": drained,
            "saturated": rows >= self.max_rows,
            "max_rows": self.max_rows,
            "log_bytes": self._log_bytes,
            "log_path": self.log_path,
            "enqueued_rows": metrics.get_counter("evolu_wb_enqueued_rows_total"),
            "drained_rows": metrics.get_counter("evolu_wb_drained_rows_total"),
            "corrected_owners": metrics.get_counter(
                "evolu_wb_corrected_owners_total"
            ),
            "replayed_records": metrics.get_counter(
                "evolu_wb_replayed_records_total"
            ),
            "stalls": metrics.get_counter("evolu_wb_stalls_total"),
            "flushes": (
                metrics.get_counter("evolu_wb_flushes_total", scope="all")
                + metrics.get_counter("evolu_wb_flushes_total", scope="owner")
            ),
            "drain_failures": metrics.get_counter(
                "evolu_wb_drain_failures_total"
            ),
            "apply_lag_ms_p50": metrics.quantile("evolu_wb_apply_lag_ms", 0.50),
            "apply_lag_ms_p99": metrics.quantile("evolu_wb_apply_lag_ms", 0.99),
        }

    def health_payload(self) -> dict:
        records, rows = self.backlog()
        last, drained = self.watermarks()
        with self._cv:
            failures = self._drain_failures
            poisoned = self._log_poisoned
        return {
            "backlog_records": records,
            "backlog_rows": rows,
            "last_seq": last,
            "drained_seq": drained,
            "saturated": rows >= self.max_rows,
            "drain_failures_consecutive": failures,
            "log_poisoned": poisoned,
            "failing": failures >= self._FAILING_AFTER or poisoned,
        }
