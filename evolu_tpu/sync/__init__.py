"""Sync transport: wire protocol, E2EE crypto, and the HTTP client.

Reference: packages/evolu/src/sync.worker.ts (encrypt → protobuf →
HTTP POST → decrypt), protos/protobuf.proto (the wire contract —
unchanged here so TypeScript reference clients interoperate), and
OpenPGP symmetric encryption with the mnemonic as the password.

Crypto stays on the host (SURVEY.md §7): it is not TPU-suitable work.
"""

from evolu_tpu.sync.protocol import (
    EncryptedCrdtMessage,
    SyncRequest,
    SyncResponse,
    decode_sync_request,
    decode_sync_response,
    encode_sync_request,
    encode_sync_response,
)
from evolu_tpu.sync.crypto import encrypt_symmetric, decrypt_symmetric
from evolu_tpu.sync.client import SyncTransport

__all__ = [
    "EncryptedCrdtMessage",
    "SyncRequest",
    "SyncResponse",
    "decode_sync_request",
    "decode_sync_response",
    "encode_sync_request",
    "encode_sync_response",
    "encrypt_symmetric",
    "decrypt_symmetric",
    "SyncTransport",
]
