"""AES-CFB fallback over OpenSSL libcrypto via ctypes.

`sync/crypto.py` (the OpenPGP oracle) uses exactly one primitive from
the `cryptography` package: AES-CFB128 stream ciphers built as
`Cipher(algorithms.AES(key), modes.CFB(iv))`. Containers without that
package (this repo's image bakes in libcrypto for the batched C++
layer but not the Python wheel) would lose the WHOLE sync chain at
import time; this module supplies the same three names over the EVP
ABI instead, so `crypto.py` gates on availability rather than failing
collection for nine test files.

Error semantics mirror `cryptography` where crypto.py depends on them:
bad key/IV SIZES raise ValueError at construction (decrypt_symmetric
translates that to PgpError — the truncated-legacy-SED fuzz case), and
a failed EVP call raises ValueError, never a new exception type.
"""

from __future__ import annotations

import ctypes
import ctypes.util


def load_libcrypto(bind):
    """Probe the candidate libcrypto sonames (images differ: 3 vs 1.1
    vs a loader-path `crypto`) and return the first CDLL that `bind`
    accepts — bind(lib) declares the caller's EVP prototypes and lets
    AttributeError escape on a missing symbol. None when no candidate
    loads+binds. Shared with `_evp_gcm` so the distro-specific probe
    list lives in exactly one place."""
    names = ["libcrypto.so.3", "libcrypto.so.1.1", "libcrypto.so"]
    found = ctypes.util.find_library("crypto")
    if found:
        names.append(found)
    for name in names:
        try:
            lib = ctypes.CDLL(name)
            bind(lib)
            return lib
        except (OSError, AttributeError):
            continue
    return None


def _bind_cfb(lib):
    c = ctypes
    lib.EVP_CIPHER_CTX_new.restype = c.c_void_p
    lib.EVP_CIPHER_CTX_new.argtypes = []
    lib.EVP_CIPHER_CTX_free.restype = None
    lib.EVP_CIPHER_CTX_free.argtypes = [c.c_void_p]
    for sym in ("EVP_aes_128_cfb128", "EVP_aes_192_cfb128",
                "EVP_aes_256_cfb128"):
        fn = getattr(lib, sym)
        fn.restype = c.c_void_p
        fn.argtypes = []
    lib.EVP_CipherInit_ex.restype = c.c_int
    lib.EVP_CipherInit_ex.argtypes = [
        c.c_void_p, c.c_void_p, c.c_void_p,
        c.c_char_p, c.c_char_p, c.c_int,
    ]
    lib.EVP_CipherUpdate.restype = c.c_int
    lib.EVP_CipherUpdate.argtypes = [
        c.c_void_p, c.c_char_p, c.POINTER(c.c_int),
        c.c_char_p, c.c_int,
    ]


_LIB = load_libcrypto(_bind_cfb)
# NB: a missing libcrypto is reported at first USE, not at import —
# this module is imported unconditionally by the import-hygiene walk
# (and speculatively by crypto.py's except branch), and must stay
# importable on machines where the `cryptography` wheel serves AES and
# no loader-path libcrypto exists.


def _require_lib():
    if _LIB is None:  # pragma: no cover - neither wheel nor libcrypto
        raise ImportError(
            "AES-CFB unavailable: install the `cryptography` package or "
            "provide OpenSSL libcrypto for the ctypes fallback"
        )
    return _LIB

_CIPHER_BY_KEYLEN = {
    16: "EVP_aes_128_cfb128", 24: "EVP_aes_192_cfb128", 32: "EVP_aes_256_cfb128",
}


class _CfbStream:
    """One direction of a CFB cipher: update()/finalize(), matching the
    `cryptography` CipherContext surface crypto.py uses. CFB is a
    stream mode — finalize() never emits buffered bytes."""

    def __init__(self, key: bytes, iv: bytes, encrypt: bool):
        _require_lib()
        self._ctx = _LIB.EVP_CIPHER_CTX_new()
        if not self._ctx:
            raise MemoryError("EVP_CIPHER_CTX_new failed")
        cipher = getattr(_LIB, _CIPHER_BY_KEYLEN[len(key)])()
        ok = _LIB.EVP_CipherInit_ex(
            self._ctx, cipher, None, key, iv, 1 if encrypt else 0
        )
        if ok != 1:
            self._free()
            raise ValueError("EVP_CipherInit_ex failed")

    def update(self, data: bytes) -> bytes:
        if self._ctx is None:
            raise ValueError("cipher context already finalized")
        data = bytes(data)
        out = ctypes.create_string_buffer(len(data) + 16)
        outl = ctypes.c_int(0)
        ok = _LIB.EVP_CipherUpdate(
            self._ctx, out, ctypes.byref(outl), data, len(data)
        )
        if ok != 1:
            raise ValueError("EVP_CipherUpdate failed")
        return out.raw[: outl.value]

    def finalize(self) -> bytes:
        self._free()
        return b""

    def _free(self) -> None:
        if self._ctx is not None:
            _LIB.EVP_CIPHER_CTX_free(self._ctx)
            self._ctx = None

    def __del__(self):  # belt-and-braces for abandoned streams
        try:
            self._free()
        except Exception:  # noqa: BLE001,S110 - interpreter teardown
            pass


class algorithms:  # noqa: N801 - mirrors the cryptography namespace
    class AES:
        def __init__(self, key: bytes):
            if len(key) not in _CIPHER_BY_KEYLEN:
                raise ValueError(f"Invalid AES key size: {len(key) * 8} bits")
            self.key = bytes(key)


class modes:  # noqa: N801 - mirrors the cryptography namespace
    class CFB:
        def __init__(self, initialization_vector: bytes):
            if len(initialization_vector) != 16:
                raise ValueError(
                    f"Invalid IV size ({len(initialization_vector)}) for CFB"
                )
            self.initialization_vector = bytes(initialization_vector)


class Cipher:
    def __init__(self, algorithm: "algorithms.AES", mode: "modes.CFB"):
        self._key = algorithm.key
        self._iv = mode.initialization_vector

    def encryptor(self) -> _CfbStream:
        return _CfbStream(self._key, self._iv, encrypt=True)

    def decryptor(self) -> _CfbStream:
        return _CfbStream(self._key, self._iv, encrypt=False)
