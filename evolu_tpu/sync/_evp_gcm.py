"""AES-256-GCM fallback over OpenSSL libcrypto via ctypes.

`sync/aead.py` (the batched-AEAD v2 oracle) uses exactly one primitive
from the `cryptography` package: the `AESGCM` AEAD. Containers without
that wheel (this repo's image bakes in libcrypto for the batched C++
layer but not the Python wheel) get the same seal/open surface over
the EVP ABI instead, mirroring `_evp_cfb.py` for the OpenPGP oracle.

Error semantics mirror what aead.py depends on: a bad key/nonce SIZE
raises ValueError at call time, and an authentication failure raises
`InvalidTag` (defined here, also aliased by aead.py when the wheel
supplies its own) — never a third exception type.
"""

from __future__ import annotations

import ctypes
import ctypes.util

from evolu_tpu.sync._evp_cfb import load_libcrypto

# EVP_CIPHER_CTX_ctrl codes (stable across OpenSSL 1.1 / 3.x; the AEAD
# aliases EVP_CTRL_AEAD_{GET,SET}_TAG share the GCM values).
_CTRL_GCM_GET_TAG = 0x10
_CTRL_GCM_SET_TAG = 0x11
TAG_LEN = 16
NONCE_LEN = 12


class InvalidTag(Exception):
    """GCM authentication failed (tampered ciphertext or wrong key)."""


def _bind_gcm(lib):
    c = ctypes
    lib.EVP_CIPHER_CTX_new.restype = c.c_void_p
    lib.EVP_CIPHER_CTX_new.argtypes = []
    lib.EVP_CIPHER_CTX_free.restype = None
    lib.EVP_CIPHER_CTX_free.argtypes = [c.c_void_p]
    lib.EVP_aes_256_gcm.restype = c.c_void_p
    lib.EVP_aes_256_gcm.argtypes = []
    lib.EVP_CipherInit_ex.restype = c.c_int
    lib.EVP_CipherInit_ex.argtypes = [
        c.c_void_p, c.c_void_p, c.c_void_p,
        c.c_char_p, c.c_char_p, c.c_int,
    ]
    lib.EVP_CipherUpdate.restype = c.c_int
    lib.EVP_CipherUpdate.argtypes = [
        c.c_void_p, c.c_char_p, c.POINTER(c.c_int),
        c.c_char_p, c.c_int,
    ]
    lib.EVP_CipherFinal_ex.restype = c.c_int
    lib.EVP_CipherFinal_ex.argtypes = [
        c.c_void_p, c.c_char_p, c.POINTER(c.c_int),
    ]
    lib.EVP_CIPHER_CTX_ctrl.restype = c.c_int
    lib.EVP_CIPHER_CTX_ctrl.argtypes = [
        c.c_void_p, c.c_int, c.c_int, c.c_void_p,
    ]


_LIB = load_libcrypto(_bind_gcm)
# NB: a missing libcrypto is reported at first USE, not at import —
# same contract as _evp_cfb (the import-hygiene walk imports this
# module unconditionally).


def _require_lib():
    if _LIB is None:  # pragma: no cover - neither wheel nor libcrypto
        raise ImportError(
            "AES-GCM unavailable: install the `cryptography` package or "
            "provide OpenSSL libcrypto for the ctypes fallback"
        )
    return _LIB


class _Gcm:
    """One GCM operation's EVP context (freed eagerly)."""

    def __init__(self, key: bytes, nonce: bytes, encrypt: bool):
        lib = _require_lib()
        if len(key) != 32:
            raise ValueError(f"Invalid AES-256-GCM key size: {len(key)}")
        if len(nonce) != NONCE_LEN:
            raise ValueError(f"Invalid GCM nonce size: {len(nonce)}")
        self._lib = lib
        self._ctx = lib.EVP_CIPHER_CTX_new()
        if not self._ctx:
            raise MemoryError("EVP_CIPHER_CTX_new failed")
        # Default GCM IV length is 12 bytes, so no SET_IVLEN ctrl needed.
        ok = lib.EVP_CipherInit_ex(
            self._ctx, lib.EVP_aes_256_gcm(), None, key, nonce,
            1 if encrypt else 0,
        )
        if ok != 1:
            self.free()
            raise ValueError("EVP_CipherInit_ex (GCM) failed")

    def update(self, data: bytes) -> bytes:
        out = ctypes.create_string_buffer(len(data) + 16)
        outl = ctypes.c_int(0)
        ok = self._lib.EVP_CipherUpdate(
            self._ctx, out, ctypes.byref(outl), data, len(data)
        )
        if ok != 1:
            raise ValueError("EVP_CipherUpdate (GCM) failed")
        return out.raw[: outl.value]

    def ctrl(self, code: int, buf) -> int:
        return self._lib.EVP_CIPHER_CTX_ctrl(self._ctx, code, TAG_LEN, buf)

    def final(self) -> int:
        out = ctypes.create_string_buffer(16)
        outl = ctypes.c_int(0)
        return self._lib.EVP_CipherFinal_ex(self._ctx, out, ctypes.byref(outl))

    def free(self) -> None:
        if self._ctx is not None:
            self._lib.EVP_CIPHER_CTX_free(self._ctx)
            self._ctx = None


class AESGCM:
    """The `cryptography.hazmat.primitives.ciphers.aead.AESGCM` subset
    aead.py uses: encrypt/decrypt with a 12-byte nonce and no AAD."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError(f"Invalid AES-256-GCM key size: {len(key)}")
        self._key = bytes(key)
        _require_lib()

    def encrypt(self, nonce: bytes, data: bytes, aad=None) -> bytes:
        if aad:
            raise ValueError("AAD unsupported by the EVP fallback")
        g = _Gcm(self._key, nonce, encrypt=True)
        try:
            ct = g.update(bytes(data))
            if g.final() != 1:
                raise ValueError("EVP_CipherFinal_ex (GCM encrypt) failed")
            tag = ctypes.create_string_buffer(TAG_LEN)
            if g.ctrl(_CTRL_GCM_GET_TAG, tag) != 1:
                raise ValueError("EVP GCM GET_TAG failed")
            return ct + tag.raw[:TAG_LEN]
        finally:
            g.free()

    def decrypt(self, nonce: bytes, data: bytes, aad=None) -> bytes:
        if aad:
            raise ValueError("AAD unsupported by the EVP fallback")
        data = bytes(data)
        if len(data) < TAG_LEN:
            raise InvalidTag("ciphertext shorter than the GCM tag")
        ct, tag = data[:-TAG_LEN], data[-TAG_LEN:]
        g = _Gcm(self._key, nonce, encrypt=False)
        try:
            pt = g.update(ct)
            if g.ctrl(_CTRL_GCM_SET_TAG, ctypes.create_string_buffer(tag, TAG_LEN)) != 1:
                raise ValueError("EVP GCM SET_TAG failed")
            if g.final() != 1:
                raise InvalidTag("GCM tag mismatch")
            return pt
        finally:
            g.free()
