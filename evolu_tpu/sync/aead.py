"""Batched-AEAD v2 sync payload — the `aead-batch-v1` capability.

The reference wire (sync/crypto.py) pays a FRESH iterated+salted S2K —
a 1KB SHA-256 — per message: ~3µs/msg of irreducible format cost that
caps any implementation near 330k msgs/s/core while the in-kernel
merge runs 282M msgs/s/chip (docs/BENCHMARKS.md; ROADMAP open item
#2 records that "only protocol changes could beat it"). This module is
that protocol change: the key is derived ONCE per (owner, session)
with salted HKDF-SHA-256 from the same owner secret that feeds S2K
today, and each message becomes one small AES-256-GCM record under
that session key.

Record layout (the per-message envelope; all lengths fixed):

    offset 0   magic   0x45 0x32 ("E2") — bit 7 of the first byte is
               CLEAR, so a v2 record can never parse as an OpenPGP
               packet stream (every valid CTB has bit 7 set) and an
               OpenPGP message can never match the magic: the two
               formats are structurally disjoint and records
               self-describe, which is what lets v1 and v2 ciphertexts
               share one store, one Merkle tree, and one decode path.
    offset 2   version 0x01
    offset 3   salt    16 bytes — the HKDF session salt. Carried per
               record (not per leg) because the relay re-serves STORED
               records merged across many past sessions: every record
               must stay decryptable standalone, long after the leg
               that carried it is gone.
    offset 19  nonce   12 bytes, random per record
    offset 31  AES-256-GCM ciphertext ‖ 16-byte tag. The plaintext is
               the same CrdtMessageContent protobuf the v1 literal
               packet carries (protocol.encode_content bytes).

Why per-record tags rather than one envelope tag over the whole batch:
the relay is E2EE-blind but MUST decompose a push into per-message
rows (INSERT OR IGNORE by timestamp, Merkle XOR per row) and later
re-compose responses from rows written by DIFFERENT sessions — a
single ciphertext spanning the batch cannot be split or re-served
without the key. The batch-level saving lives in the KEY SCHEDULE
(one HKDF per session instead of one S2K per message) and in the
batched C leg (native/evolu_crypto.cpp: one call per sync leg, one
AES key schedule per leg). Tamper anywhere in a leg still surfaces as
one PgpError for the leg: decode stops at the first failing record,
exactly like the v1 per-message MDC path.

Error contract (fuzz-pinned, tests/test_wire_v2.py): ValueError for
wire framing (the protobuf layer), PgpError for everything inside the
record — truncation, auth-tag failure, key mismatch. PgpError
subclasses ValueError, so every existing ValueError-keyed caller is
unchanged.

Crypto stays host-side by design (SURVEY.md §5): TPU kernels never see
plaintext, and the relay stores v2 ciphertext as opaquely as v1.
"""

from __future__ import annotations

import hmac as _hmac
import hashlib
import os
import threading
from collections import OrderedDict
from typing import Tuple

from evolu_tpu.obs import metrics
from evolu_tpu.sync.crypto import PgpError, decrypt_symmetric

try:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    from cryptography.exceptions import InvalidTag
except ModuleNotFoundError:
    # No `cryptography` wheel: the one primitive used here is
    # AES-256-GCM, served equally by OpenSSL libcrypto over ctypes
    # (same InvalidTag-on-auth-failure semantics — see _evp_gcm).
    from evolu_tpu.sync._evp_gcm import AESGCM, InvalidTag

MAGIC = b"\x45\x32\x01"  # "E2" + version 1
SALT_LEN = 16
NONCE_LEN = 12
TAG_LEN = 16
RECORD_OVERHEAD = len(MAGIC) + SALT_LEN + NONCE_LEN + TAG_LEN  # = 47
# HKDF-SHA-256 info string — MUST match native/evolu_crypto.cpp's copy
# byte for byte (the C leg derives the same key from (secret, salt)).
HKDF_INFO = b"evolu-tpu aead-batch-v1 key"


def hkdf_sha256(secret: bytes, salt: bytes) -> bytes:
    """RFC 5869 extract+expand for exactly one 32-byte block:
    PRK = HMAC(salt, secret); OKM = HMAC(PRK, info ‖ 0x01)."""
    prk = _hmac.new(salt, secret, hashlib.sha256).digest()
    return _hmac.new(prk, HKDF_INFO + b"\x01", hashlib.sha256).digest()


def derive_key(password: str, salt: bytes) -> bytes:
    metrics.inc("evolu_crypto_session_keys_derived_total")
    return hkdf_sha256(password.encode("utf-8"), salt)


def is_v2_record(content: bytes) -> bool:
    """The ONE dispatch predicate, shared (by value) with the C fast
    path: magic match ⇒ v2 record, else OpenPGP. Never ambiguous —
    see the module docstring on the disjoint first byte."""
    return content[: len(MAGIC)] == MAGIC


class AeadSession:
    """One owner's encrypt-side session: a fresh salt and its derived
    key, minted once per (secret, process) and reused for every leg —
    this is where the per-message S2K cost collapses to one HKDF.
    `used` counts records sealed under the key (see
    SESSION_RECORD_LIMIT)."""

    __slots__ = ("salt", "key", "used")

    def __init__(self, salt: bytes, key: bytes):
        self.salt = salt
        self.key = key
        self.used = 0


_lock = threading.Lock()
_sessions: "OrderedDict[str, AeadSession]" = OrderedDict()  # password → session
_decrypt_keys: "OrderedDict[Tuple[str, bytes], bytes]" = OrderedDict()
_MAX_SESSIONS = 64
_MAX_DECRYPT_KEYS = 512  # decrypt side sees one salt per REMOTE session
# Nonces are random 96-bit per record: NIST SP 800-38D caps random-IV
# GCM at 2^32 invocations per key (collision probability 2^-32 at
# that point). Rotate the session WELL under it — a fresh salt+key is
# one ~70µs HKDF, and records self-describe so retired-session
# records stay decryptable forever.
SESSION_RECORD_LIMIT = 1 << 28


def get_session(password: str, records: int = 0) -> AeadSession:
    """The encrypt-side session for `password`, about to seal
    `records` more records — a session that would cross
    SESSION_RECORD_LIMIT is retired and a fresh salt+key minted
    (the 2^32 random-nonce GCM bound can never be approached)."""
    with _lock:
        s = _sessions.get(password)
        if s is not None and s.used + records <= SESSION_RECORD_LIMIT:
            s.used += records
            _sessions.move_to_end(password)
            return s
    salt = os.urandom(SALT_LEN)
    s = AeadSession(salt, derive_key(password, salt))
    s.used = records
    with _lock:
        _sessions[password] = s
        while len(_sessions) > _MAX_SESSIONS:
            _sessions.popitem(last=False)
    # Seed the decrypt cache too: our own records come back in pull
    # responses and must not pay a second derivation.
    _remember_decrypt_key(password, salt, s.key)
    return s


def reset_sessions() -> None:
    """Drop every cached session/key (tests; also safe any time — the
    next leg simply mints a fresh salt)."""
    with _lock:
        _sessions.clear()
        _decrypt_keys.clear()


def _remember_decrypt_key(password: str, salt: bytes, key: bytes) -> None:
    with _lock:
        _decrypt_keys[(password, salt)] = key
        while len(_decrypt_keys) > _MAX_DECRYPT_KEYS:
            _decrypt_keys.popitem(last=False)


def _decrypt_key(password: str, salt: bytes) -> bytes:
    with _lock:
        k = _decrypt_keys.get((password, salt))
        if k is not None:
            _decrypt_keys.move_to_end((password, salt))
            return k
    k = derive_key(password, salt)
    _remember_decrypt_key(password, salt, k)
    return k


def encrypt_record(key: bytes, salt: bytes, plaintext: bytes) -> bytes:
    """One v2 record under an established session key (pure-Python leg;
    the batched C twin is ehc_aead_encrypt_wire_batch)."""
    nonce = os.urandom(NONCE_LEN)
    return MAGIC + salt + nonce + AESGCM(key).encrypt(nonce, plaintext, None)


def decrypt_record(record: bytes, password: str) -> bytes:
    """→ the CrdtMessageContent plaintext. Raises PgpError ONLY (auth
    failure, truncation, key mismatch — all tamper-shaped outcomes);
    the caller's protobuf decode owns the ValueError surface."""
    if not is_v2_record(record):
        raise PgpError("not an aead-batch-v1 record")
    if len(record) < RECORD_OVERHEAD:
        metrics.inc("evolu_crypto_auth_failures_total")
        raise PgpError("truncated aead-batch-v1 record")
    salt = record[3 : 3 + SALT_LEN]
    nonce = record[3 + SALT_LEN : 3 + SALT_LEN + NONCE_LEN]
    key = _decrypt_key(password, salt)
    try:
        return AESGCM(key).decrypt(nonce, record[3 + SALT_LEN + NONCE_LEN :], None)
    except (InvalidTag, ValueError) as e:
        metrics.inc("evolu_crypto_auth_failures_total")
        raise PgpError(
            "aead-batch-v1 authentication failed (tampered or wrong key?)"
        ) from e


def decrypt_content(content: bytes, password: str) -> bytes:
    """The version dispatch every decrypt path funnels through: stored
    logs mix v1 OpenPGP and v2 records freely (records self-describe),
    so decoding never depends on what was negotiated."""
    if is_v2_record(content):
        return decrypt_record(content, password)
    return decrypt_symmetric(content, password)


def count_v2(messages) -> int:
    """How many of a request's EncryptedCrdtMessages are v2 records —
    the relay's ingest-side observability (it stays E2EE-blind; the
    3-byte magic is framing, not content)."""
    return sum(1 for m in messages if is_v2_record(m.content))
