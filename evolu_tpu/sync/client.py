"""The sync transport — SyncWorker analog.

Reference: packages/evolu/src/sync.worker.ts. One input shape (a sync
request carrying optional fresh messages + the clock), one pipeline
(sync.worker.ts:177-229): encrypt each message's content → protobuf
SyncRequest → HTTP POST octet-stream → parse SyncResponse → decrypt →
hand the result back to the DbWorker as a Receive command.

Network failure is swallowed by design — offline is a normal state,
recovery is the next sync trigger (sync.worker.ts:217-227). Every
round runs under the per-database sync lock, making sync mutually
exclusive across clients of the same database (syncLock.ts:8-12).
"""

from __future__ import annotations

import queue
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Optional

from evolu_tpu.core.timestamp import timestamp_from_string
from evolu_tpu.core.types import CrdtMessage, UnknownError
from evolu_tpu.obs import metrics, trace
from evolu_tpu.runtime.messages import OnError, SyncRequestInput
from evolu_tpu.runtime.synclock import SyncLock
from evolu_tpu.sync import protocol
from evolu_tpu.sync.crypto import decrypt_symmetric, encrypt_symmetric
from evolu_tpu.utils.config import Config
from evolu_tpu.utils.log import log


def encrypt_messages(messages, mnemonic: str):
    """sync.worker.ts:50-91 — per-message protobuf-encode + encrypt;
    the timestamp stays plaintext (the relay orders and diffs by it).
    The transport always encodes with extensions allowed: the wire gate
    (incl. strict interop, Config.wire_extensions=False) is enforced at
    MUTATION time (worker._send), so anything in the log is either
    authored encodable or arrived from a remote peer — and a relay must
    forward remote messages verbatim, never refuse them (refusing here
    would wedge anti-entropy resends forever).

    Hot loop #3 (SURVEY.md): the batched C++ path handles canonical
    values (~8× the pure loop, docs/BENCHMARKS.md); None means some
    value needs the pure loop's error surface, so it re-runs here."""
    if messages:
        from evolu_tpu.sync import native_crypto

        native = native_crypto.encrypt_batch(messages, mnemonic)
        if native is not None:
            return native
    out = []
    for m in messages:
        content = protocol.encode_content(m.table, m.row, m.column, m.value)
        out.append(
            protocol.EncryptedCrdtMessage(m.timestamp, encrypt_symmetric(content, mnemonic))
        )
    return tuple(out)


def encrypt_messages_v2(messages, mnemonic: str):
    """The aead-batch-v1 twin of `encrypt_messages` (sync/aead.py):
    session-keyed GCM records instead of per-message OpenPGP S2K. Only
    the NEGOTIATED push path calls this — the pure loop here is the
    fallback behind the fused C wire leg, and it raises exactly what
    the v1 pure loop raises for unencodable values (encode_content owns
    the TypeError surface in both)."""
    from evolu_tpu.sync import aead

    session = aead.get_session(mnemonic, records=len(messages))
    out = []
    for m in messages:
        content = protocol.encode_content(m.table, m.row, m.column, m.value)
        out.append(
            protocol.EncryptedCrdtMessage(
                m.timestamp, aead.encrypt_record(session.key, session.salt, content)
            )
        )
    return tuple(out)


def decrypt_messages(messages, mnemonic: str):
    """sync.worker.ts:135-173. Canonical rows decrypt on the batched
    C++ path; everything else — including the whole batch when the
    library is unavailable — re-runs through the Python oracle at its
    original position (identical errors, first-failure order)."""
    from evolu_tpu.sync import native_crypto

    return native_crypto.decrypt_batch(messages, mnemonic)


def _accepts_headers(fn) -> bool:
    """Whether an http_post callable takes a `headers` kwarg — the
    trace-context hop is optional so injected 2-arg transports (tests,
    embedders, fault injectors) keep working unchanged. Probed at
    call time (the transport is swappable after construction) but
    memoized per callable: inspect.signature builds a full Signature
    object, far too heavy to re-run on every POST/gossip leg."""
    try:
        return _ACCEPTS_HEADERS_MEMO[fn]
    except TypeError:
        return _accepts_headers_probe(fn)  # unhashable callable
    except KeyError:
        pass
    ok = _accepts_headers_probe(fn)
    try:
        if len(_ACCEPTS_HEADERS_MEMO) > 256:  # unbounded-growth guard
            _ACCEPTS_HEADERS_MEMO.clear()
        _ACCEPTS_HEADERS_MEMO[fn] = ok
    except TypeError:
        pass
    return ok


_ACCEPTS_HEADERS_MEMO: dict = {}


def _accepts_headers_probe(fn) -> bool:
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    params = sig.parameters
    return "headers" in params or any(
        p.kind == p.VAR_KEYWORD for p in params.values()
    )


class SyncTransport:
    """Owns a transport thread; `request_sync` enqueues a round.

    `on_receive(messages, merkle_tree, previous_diff)` is called with
    the decrypted response — typically `Evolu.receive`, closing the
    anti-entropy loop (SURVEY.md §3.3).
    """

    def __init__(
        self,
        config: Config,
        on_receive: Callable[[tuple, str, Optional[int]], None],
        sync_lock: Optional[SyncLock] = None,
        on_error: Optional[Callable[[Exception], None]] = None,
        http_post: Optional[Callable[[str, bytes], bytes]] = None,
        http_probe: Optional[Callable[[str], None]] = None,
        on_reconnect: Optional[Callable[[], None]] = None,
    ):
        self.config = config
        self.on_receive = on_receive
        self.sync_lock = sync_lock or SyncLock()
        self.on_error = on_error or (lambda _e: None)
        self._http_post = http_post or _http_post
        self._http_probe = http_probe or _http_ping
        self.on_reconnect = on_reconnect or (lambda: None)
        self._queue: "queue.Queue[object]" = queue.Queue()
        self._stop = object()
        # Learned owner→relay routes (fleet 307 redirects,
        # server/fleet.py). Touched only on the transport thread.
        # Invalidated by the next 307 (re-learn), a 404 (stale route —
        # the owner moved or the relay left the fleet), or a
        # connection failure on the learned URL (fail back to the
        # configured relay before declaring offline).
        self._routes: dict = {}
        # Negotiated wire capabilities per relay URL (sync/protocol.py
        # capability extension): what the LAST response from that relay
        # echoed back from our advertised set. Empty/absent = a v1 peer
        # — typed CRDT traffic still relays byte-identically (ops are
        # E2EE-opaque), this is the app's signal that the fleet
        # understands them.
        self.negotiated_capabilities: dict = {}
        # Reconnect probing state (db.ts:390-412 analog): offline is
        # entered by a swallowed fetch error, left by the first probe
        # success or successful round — either fires on_reconnect.
        self._probe_lock = threading.Lock()
        self._probe_stop = threading.Event()
        self._prober: Optional[threading.Thread] = None
        self._offline = False
        self._pending_reconnect = False  # transport-thread only
        # Optional push-subscription leg (ISSUE 13, server/push.py):
        # attached by connect() under Config.push_subscribe. Bound
        # lazily from the first successful round (which is where the
        # owner id, the clock's node id, and the owner's PLACED relay
        # become known on this thread).
        self.push_subscriber = None
        self._thread = threading.Thread(target=self._loop, daemon=True, name="evolu-sync")
        self._thread.start()

    def request_sync(self, request: SyncRequestInput) -> None:
        self._queue.put(request)

    def stop(self) -> None:
        if self.push_subscriber is not None:
            self.push_subscriber.stop()
        self._probe_stop.set()
        with self._probe_lock:
            prober = self._prober
        if prober is not None and prober is not threading.current_thread():
            # Bounded: the prober may be mid-GET with a 5s socket
            # timeout; it is a daemon thread that only touches the
            # network, so don't stall dispose() on it.
            prober.join(timeout=0.2)
        self._queue.put(self._stop)
        self._thread.join()

    # -- offline → online transitions --

    def _note_offline(self) -> None:
        """A fetch error was swallowed: start probing GET /ping until
        the transport comes back (unless probing is disabled)."""
        interval = self.config.reconnect_probe_interval
        with self._probe_lock:
            self._offline = True
            if interval is None or self._probe_stop.is_set():
                return
            if self._prober is not None and self._prober.is_alive():
                return
            self._prober = threading.Thread(
                target=self._probe_loop, args=(interval,),
                daemon=True, name="evolu-sync-probe",
            )
            self._prober.start()

    def _probe_loop(self, interval: float) -> None:
        ping_url = _ping_url(self.config.sync_url)
        delay = interval
        try:
            while not self._probe_stop.wait(delay):
                with self._probe_lock:
                    if not self._offline:
                        return  # a successful round beat the probe
                try:
                    self._http_probe(ping_url)
                except urllib.error.HTTPError:
                    # The server ANSWERED (e.g. /ping 404s behind a
                    # path-prefixed deployment): the transport is up —
                    # same classification as _sync_round's.
                    pass
                except Exception:  # noqa: BLE001 - still offline; back
                    # off so an hours-long outage doesn't hammer 1/s
                    delay = min(delay * 2, max(30.0, interval))
                    continue
                self._came_back()
                # Back off after a reconnect attempt too: if /ping
                # succeeds but the sync POST keeps failing (POST-only
                # firewall, MTU blackhole), each probe success fires a
                # doomed round — without this the cycle storms at
                # `interval` forever. A true recovery exits at the next
                # _offline check; the next outage gets a fresh prober
                # starting at `interval` again.
                delay = min(delay * 2, max(30.0, interval))
                # Do NOT return: loop back to the _offline check — a
                # network flap may already have re-marked us offline,
                # and exiting here while _note_offline still saw this
                # thread alive would leave NO prober running.
        finally:
            # Closes the remaining flap window: if offline was re-set
            # between our last check and this exit, restart a fresh
            # prober (suppressed during stop()).
            with self._probe_lock:
                self._prober = None
                restart = self._offline and not self._probe_stop.is_set()
            if restart:
                self._note_offline()

    def _note_online(self) -> None:
        """A round succeeded (or the server answered an error — either
        way the transport is up); if we were offline this IS the
        reconnect. Firing is deferred to the loop, after the sync lock
        is released (see _loop)."""
        with self._probe_lock:
            was_offline = self._offline
            self._offline = False
        if was_offline:
            self._pending_reconnect = True

    def _came_back(self) -> None:
        with self._probe_lock:
            # stop() joins the daemon prober with a short timeout, so a
            # probe can complete mid-dispose — don't fire the reconnect
            # hook into an already-disposed Evolu instance.
            if self._probe_stop.is_set() or not self._offline:
                return
            self._offline = False
        self._fire_reconnect()

    def _fire_reconnect(self) -> None:
        log("sync:reconnect")
        try:
            self.on_reconnect()
        except Exception as e:  # noqa: BLE001 - hook must not kill transport
            self.on_error(UnknownError(e))

    def flush(self) -> None:
        done = threading.Event()
        self._queue.put(done)
        done.wait()

    def _loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is self._stop:
                return
            if isinstance(item, threading.Event):
                item.set()
                continue
            with self.sync_lock.hold():
                received = self._sync_round(item)
            # Everything below runs with the sync lock RELEASED. The
            # worker's _receive skips its anti-entropy resend while the
            # lock is pending/held — handing it the response under the
            # lock would race that gate and silently drop the resend
            # (observed: an offline-born mutation never pushed after
            # reconnect). Same for the reconnect hook's pull round.
            if received is not None:
                try:
                    self.on_receive(*received)
                except Exception as e:  # noqa: BLE001
                    self.on_error(UnknownError(e))
            if self._pending_reconnect:
                self._pending_reconnect = False
                self._fire_reconnect()

    def _aead_negotiated(self, url: str, caps) -> bool:
        """v2 emission gate: we advertise aead-batch-v1 AND the LAST
        response from `url` echoed it back. Everything else — first
        contact, a v1 relay, a failover target we never spoke to —
        gets the v1 wire. Decoding needs no gate (records
        self-describe), so this only ever controls what we WRITE."""
        return (
            protocol.CAP_AEAD_BATCH in caps
            and protocol.CAP_AEAD_BATCH in self.negotiated_capabilities.get(url, ())
        )

    def _scope_negotiated(self, url: str, caps) -> bool:
        """Scope-clause emission gate (the aead gate's twin): we
        advertise sync-scope-v1 AND the last response from `url` echoed
        it. A non-advertising relay never receives a scope clause — it
        gets the full serve instead (over-approximation; the worker's
        materialization filter still applies client-side)."""
        return (
            protocol.CAP_SYNC_SCOPE in caps
            and protocol.CAP_SYNC_SCOPE
            in self.negotiated_capabilities.get(url, ())
        )

    def _drop_negotiated(self, url: str) -> None:
        """Invalidate the cached capability set alongside a route
        invalidation: the relay at `url` is gone/stale, and a failover
        replica must be treated as un-negotiated (v1) until its own
        response says otherwise — never send v2 at a relay that didn't
        advertise it."""
        if self.negotiated_capabilities.pop(url, None) is not None:
            metrics.inc("evolu_crypto_capability_invalidations_total")

    def _encode_push(self, request: SyncRequestInput, node_id: str,
                     caps, use_v2: bool,
                     scope_clause=None) -> bytes:
        """One request body. v1: the fused C wire path (byte-identical
        to the pre-v2 encoder — pinned), pure per-message OpenPGP
        behind it. v2 (negotiated only): ONE session key schedule +
        one GCM record per message (`encode_push_request_aead`), pure
        aead loop behind it. Capabilities append identically on every
        path; absent caps = the v1 wire byte-for-byte. `scope_clause`
        (negotiated only — sync-scope-v1) appends as field 6 the same
        way; None = byte-identical to the unscoped wire."""
        from evolu_tpu.sync import native_crypto

        body = None
        if use_v2 and request.messages:
            from evolu_tpu.sync import aead

            session = aead.get_session(request.owner.mnemonic,
                                       records=len(request.messages))
            body = native_crypto.encode_push_request_aead(
                request.messages, session.key, session.salt,
                request.owner.id, node_id, request.merkle_tree,
            )
            if body is None:
                # (encrypt_messages_v2 re-counts the records against a
                # session it fetches itself — double-counting toward
                # the rotation bound is conservative and harmless.)
                encrypted = encrypt_messages_v2(request.messages, request.owner.mnemonic)
                body = protocol.encode_sync_request(
                    protocol.SyncRequest(encrypted, request.owner.id, node_id,
                                         request.merkle_tree)
                )
        if body is None:
            body = native_crypto.encode_push_request(
                request.messages, request.owner.mnemonic,
                request.owner.id, node_id, request.merkle_tree,
            )
        if body is None:
            encrypted = encrypt_messages(request.messages, request.owner.mnemonic)
            body = protocol.encode_sync_request(
                protocol.SyncRequest(encrypted, request.owner.id, node_id,
                                     request.merkle_tree)
            )
        if caps:
            # Advertise as appended field-5 bytes: identical on the
            # fused C and pure encode paths, absent (v1 wire,
            # byte-identical) when the config advertises nothing.
            body = body + protocol.encode_request_capabilities(caps)
        if scope_clause is not None:
            body = body + protocol.encode_request_scope(scope_clause)
        return body

    def _post_traced(self, url: str, body: bytes) -> bytes:
        """The sync POST with the ambient trace context as a
        traceparent header (headers only — the body bytes are
        untouched). An injected 2-arg http_post (tests, embedders —
        probed at call time, the transport is swappable) is served
        without the header rather than broken."""
        hdrs = trace.inject_headers()
        if hdrs and _accepts_headers(self._http_post):
            return self._http_post(url, body, headers=hdrs)
        return self._http_post(url, body)

    def _sync_round(self, request: SyncRequestInput):
        """One encrypt→POST→decrypt round under the sync lock, traced
        end to end (obs/trace.py): the round span joins the mutation's
        trace when the request carries one (runtime/worker.py mints it
        at Send) and roots a fresh trace for pull-only rounds; the
        POST carries this span's context as its traceparent header.
        Returns what `_sync_round_body` returns."""
        rspan = trace.start_span(
            "sync.round", parent=getattr(request, "trace", None),
            attrs={"messages": len(request.messages)},
        )
        with rspan, trace.use(rspan.context):
            return self._sync_round_body(request)

    def _sync_round_body(self, request: SyncRequestInput):
        """The round itself. Returns the decoded (messages,
        merkle_tree, previous_diff) for the caller to hand to
        on_receive AFTER releasing the lock, or None when there is
        nothing to receive."""
        caps = tuple(self.config.sync_capabilities or ())
        owner_id = request.owner.id
        base = self.config.sync_url
        url = self._routes.get(owner_id, base)
        use_v2 = self._aead_negotiated(url, caps)
        scope = getattr(self.config, "sync_scope", None)
        clause = None
        if scope is not None and not scope.is_noop \
                and self._scope_negotiated(url, caps):
            # The scope clause rides only a negotiated wire; the push
            # lane assignment names each pushed message's table (even
            # out-of-scope tables — the relay's lanes must stay
            # truthful for OTHER scoped clients of this owner).
            clause = scope.wire_clause(
                request.owner.mnemonic,
                push_tables=tuple(m.table for m in request.messages),
            )
        try:
            node_id = timestamp_from_string(request.clock_timestamp).node
            body = self._encode_push(request, node_id, caps, use_v2,
                                     scope_clause=clause)
        except Exception as e:  # noqa: BLE001
            self.on_error(UnknownError(e))
            return None
        metrics.inc("evolu_sync_requests_total")
        metrics.inc("evolu_sync_request_messages_total", len(request.messages))
        metrics.observe("evolu_sync_request_bytes", len(body),
                        buckets=metrics.SIZE_BUCKETS)
        log("sync:request", url=url,
            messages=len(request.messages), bytes=len(body))

        class _Abort(Exception):
            pass

        downgraded = False

        def retarget(new_url: str):
            """Move this round to another relay. If the body was a v2
            envelope but the new target is not negotiated for it,
            silently re-emit the round as v1 — a failover replica must
            NEVER receive v2 records it didn't advertise for (the
            regression this guards: 2-relay fleet failover to a v1
            replica)."""
            nonlocal url, body, use_v2, downgraded, clause
            url = new_url
            need_v1 = use_v2 and not self._aead_negotiated(new_url, caps)
            drop_scope = (clause is not None
                          and not self._scope_negotiated(new_url, caps))
            if not (need_v1 or drop_scope):
                return
            if need_v1:
                use_v2 = False
                downgraded = True
            if drop_scope:
                # The PR-8 retarget lesson, applied to scope: a
                # non-advertising failover target must NEVER receive a
                # scope clause — re-emit unscoped (a full serve is the
                # conservative answer; the worker still filters).
                clause = None
                metrics.inc("evolu_scope_downgrades_total",
                            reason="failover")
            try:
                body = self._encode_push(request, node_id, caps, use_v2,
                                         scope_clause=clause)
            except Exception as e:  # noqa: BLE001 - encode must never
                # kill the transport thread; surface and end the round
                self.on_error(UnknownError(e))
                raise _Abort() from e
            if need_v1:
                metrics.inc("evolu_crypto_v1_fallback_total", reason="failover")
                log("sync:request", "aead downgrade for failover", url=new_url)

        followed = False
        try:
            while True:
                try:
                    response_bytes = self._post_traced(url, body)
                    break
                except urllib.error.HTTPError as e:
                    # A fleet relay answers a non-placed sync POST with
                    # 307 + the authoritative peer URL (server/fleet.py).
                    # Follow AT MOST ONE redirect per request and cache
                    # the learned owner→relay route; each hop's POST
                    # keeps its own full 429/503/connection backoff
                    # schedule inside _http_post, so backpressure at the
                    # redirected relay still backs off normally.
                    location = e.headers.get("Location") if e.headers else None
                    if e.code == 307 and location and not followed:
                        followed = True
                        target = urllib.parse.urljoin(url, location)
                        self._routes[owner_id] = target
                        metrics.inc("evolu_sync_redirects_total")
                        # The redirect hop is a leg of the mutation's
                        # journey: record it into the round's trace so
                        # GET /trace/<id> shows WHERE the client was
                        # bounced (zero-duration event span).
                        trace.record_span(
                            "sync.redirect", trace.current(), time.time(),
                            0.0, {"target": target},
                        )
                        log("sync:request", "fleet redirect", url=target)
                        retarget(target)
                        continue
                    if e.code in (307, 404) and self._routes.pop(owner_id, None):
                        # A second 307 (ring churn) or a 404 (the
                        # learned relay no longer serves this owner):
                        # the cached route is stale — and so is anything
                        # we thought that relay had negotiated.
                        metrics.inc("evolu_sync_route_invalidations_total")
                        self._drop_negotiated(url)
                        if e.code == 404 and url != base:
                            retarget(base)
                            continue
                    # The server answered: that's a real error
                    # (4xx/5xx), not offline — surface it so divergence
                    # isn't silent. The transport is demonstrably UP, so
                    # clear any offline state.
                    metrics.inc("evolu_sync_http_errors_total")
                    self._note_online()
                    self.on_error(UnknownError(e))
                    return None
                except (urllib.error.URLError, OSError):
                    if url != base and self._routes.pop(owner_id, None):
                        # The LEARNED relay is unreachable — that says
                        # nothing about the configured one: drop the
                        # route (and its negotiated capability set) and
                        # fail over to it before declaring offline.
                        metrics.inc("evolu_sync_route_invalidations_total")
                        self._drop_negotiated(url)
                        retarget(base)
                        continue
                    # Offline is not an error (sync.worker.ts:217-227)
                    # — but it arms the reconnect probe.
                    metrics.inc("evolu_sync_offline_rounds_total")
                    self._note_offline()
                    return None
        except _Abort:
            return None
        self._note_online()
        if self.push_subscriber is not None:
            # Bind/retarget the push leg with what this round learned:
            # the owner, the clock's node id (its own-write exclusion
            # key), and the relay that actually served — the placed
            # one, after any 307 follow.
            # A scoped client's subscription carries its lane tags so
            # the hub can skip wakes its filter provably can't see —
            # only when the round's relay negotiated the scope (the
            # same emission gate as the clause itself).
            sub_tags = None
            if scope is not None and scope.tables \
                    and self._scope_negotiated(url, caps):
                from evolu_tpu.sync.scope import derive_scope_tag

                sub_tags = tuple(
                    derive_scope_tag(request.owner.mnemonic, t)
                    for t in scope.tables
                )
            self.push_subscriber.ensure(owner_id, node_id, url,
                                        tags=sub_tags)
        # Push-mix counters AFTER the POST landed: a round that ended
        # offline, errored, or was downgraded mid-flight must count as
        # what actually reached a relay, not what was first encoded
        # (the failover downgrade itself is an event — counted in
        # retarget; `use_v2` here reflects the FINAL body).
        if request.messages:
            if use_v2:
                metrics.inc("evolu_crypto_v2_push_legs_total")
                metrics.inc("evolu_crypto_v2_push_messages_total",
                            len(request.messages))
            elif protocol.CAP_AEAD_BATCH in caps and not downgraded:
                metrics.inc("evolu_crypto_v1_fallback_total",
                            reason="not_negotiated")
        if caps:
            try:
                negotiated = protocol.scan_sync_response_capabilities(response_bytes)
            except ValueError:
                negotiated = ()  # decode error surfaces below, on the real decoder
            self.negotiated_capabilities[url] = negotiated
            metrics.set_gauge(
                "evolu_crdt_capability_negotiated",
                1 if protocol.CAP_CRDT_TYPES in negotiated else 0,
            )
            metrics.set_gauge(
                "evolu_crdt_list_capability_negotiated",
                1 if protocol.CAP_CRDT_LIST in negotiated else 0,
            )
            metrics.set_gauge(
                "evolu_crdt_tensor_capability_negotiated",
                1 if protocol.CAP_CRDT_TENSOR in negotiated else 0,
            )
            metrics.set_gauge(
                "evolu_crypto_aead_negotiated",
                1 if protocol.CAP_AEAD_BATCH in negotiated else 0,
            )
        try:
            from evolu_tpu.sync import native_crypto

            # Fully-fused receive: protobuf parse + decrypt +
            # columnarization in one C call → PackedReceive, feeding
            # the worker's packed apply with zero per-row objects. Any
            # non-canonical shape → the object-path fused decoder →
            # the pure decoder (identical error surfaces down the
            # chain).
            packed = native_crypto.decrypt_response_columns(
                response_bytes, request.owner.mnemonic
            )
            if packed is not None:
                messages, merkle_tree = packed
            else:
                fused = native_crypto.decrypt_response(
                    response_bytes, request.owner.mnemonic
                )
                if fused is not None:
                    messages, merkle_tree = fused
                else:
                    response = protocol.decode_sync_response(response_bytes)
                    messages = decrypt_messages(response.messages, request.owner.mnemonic)
                    merkle_tree = response.merkle_tree
            metrics.inc("evolu_sync_responses_total")
            metrics.inc("evolu_sync_response_messages_total", len(messages))
            metrics.observe("evolu_sync_response_bytes", len(response_bytes),
                            buckets=metrics.SIZE_BUCKETS)
            log("sync:response", messages=len(messages), bytes=len(response_bytes))
            return (messages, merkle_tree, request.previous_diff)
        except Exception as e:  # noqa: BLE001
            self.on_error(UnknownError(e))
            return None


# Transport backoff policy. A sync POST is idempotent (INSERT OR
# IGNORE + pure diff), so retrying a 429/503 or a connection failure is
# always safe. Bounded: after the retries are spent, the original
# error surfaces — a 4xx/5xx to on_error (divergence must not be
# silent), a connection error to the offline/probe machinery (offline
# remains a normal state, not an error). Before this, one queue-full
# 503 from the relay's continuous-batching scheduler surfaced straight
# as UnknownError with no retry.
BACKOFF_RETRIES = 3
BACKOFF_BASE_S = 0.05
BACKOFF_MAX_S = 5.0
RETRYABLE_HTTP = (429, 503)


def _retry_after_seconds(error: urllib.error.HTTPError) -> Optional[float]:
    """Parse a Retry-After header: RFC 7231 delay-seconds (we also
    accept a float — our relay emits sub-second values for local
    deploys). HTTP-date form and garbage fall back to our own backoff
    schedule (None)."""
    raw = error.headers.get("Retry-After") if error.headers else None
    if raw is None:
        return None
    try:
        value = float(raw.strip())
    except ValueError:
        return None
    return value if value >= 0 else None


def _http_post(url: str, body: bytes, *, retries: int = BACKOFF_RETRIES,
               base_delay: float = BACKOFF_BASE_S, max_delay: float = BACKOFF_MAX_S,
               sleep=None, rng=None, headers: Optional[dict] = None) -> bytes:
    """POST with bounded exponential backoff + full jitter on 429/503
    (honoring Retry-After — the relay's backpressure contract) and on
    connection errors. `sleep`/`rng` are injectable for tests.
    `headers` (e.g. the traceparent trace-context hop, obs/trace.py)
    merge over the defaults — context rides HTTP headers only, the
    body bytes are never touched."""
    import random
    import time

    sleep = sleep or time.sleep
    rng = rng or random.random
    attempt = 0
    base_headers = {"Content-Type": "application/octet-stream"}
    if headers:
        base_headers.update(headers)
    while True:
        req = urllib.request.Request(
            url, data=body, headers=base_headers, method="POST"
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.read()
        except urllib.error.HTTPError as e:
            if e.code not in RETRYABLE_HTTP or attempt >= retries:
                raise
            delay = _retry_after_seconds(e)
            if delay is None:
                # Full jitter: delay ∈ [0, base * 2^attempt] — the
                # standard de-synchronizer for a fleet of clients all
                # bounced by the same overloaded relay.
                delay = min(max_delay, base_delay * (2 ** attempt)) * rng()
            metrics.inc("evolu_sync_backoff_retries_total", reason=str(e.code))
            log("sync:request", "backoff retry", code=e.code, delay_s=round(delay, 4))
        except (urllib.error.URLError, OSError):
            if attempt >= retries:
                raise
            delay = min(max_delay, base_delay * (2 ** attempt)) * rng()
            metrics.inc("evolu_sync_backoff_retries_total", reason="connection")
        sleep(min(delay, max_delay))
        attempt += 1


def _ping_url(sync_url: str) -> str:
    """The relay's health endpoint (index.ts:250-252) lives at /ping on
    the same origin as the sync POST endpoint."""
    from urllib.parse import urlsplit, urlunsplit

    parts = urlsplit(sync_url)
    return urlunsplit((parts.scheme, parts.netloc, "/ping", "", ""))


def _http_ping(url: str) -> None:
    """One cheap GET — raises while offline, returns once reachable."""
    with urllib.request.urlopen(url, timeout=5) as resp:
        resp.read()


class _NoRedirect(urllib.request.HTTPRedirectHandler):
    """Surface 3xx as HTTPError instead of auto-following: the push
    loop must LEARN the placed relay from a 307's Location (and cache
    it), not pay a redirect hop on every poll."""

    def redirect_request(self, *a, **k):
        return None


_PUSH_OPENER = urllib.request.build_opener(_NoRedirect)


def _push_get(url: str, timeout: float) -> bytes:
    with _PUSH_OPENER.open(url, timeout=timeout) as resp:
        return resp.read()


class PushSubscriber:
    """The client half of relay-held push subscriptions (ISSUE 13,
    server/push.py): one daemon thread long-polls
    `GET /push/poll?owner&node&cursor` against the owner's placed
    relay and fires `on_wake` — typically `evolu.sync` — whenever the
    relay reports foreign-authored rows. The parked poll replaces the
    polling interval: mutation→visible becomes the push round trip.

    Robustness mirrors the sync transport's: at most one 307 follow
    per poll with the learned route cached (invalidated on 404/error/
    connection failure, failing back to the bound URL), bounded
    exponential backoff + full jitter while the relay is unreachable
    (offline is a normal state), cursor-resume across reconnects (the
    hub answers a conservative wake for a cursor its ring outgrew —
    a wakeup is never missed, ISSUE 13). `ensure` is idempotent and
    re-callable: every successful sync round re-binds the target, so
    the subscription follows fleet placement exactly as the sync leg
    does."""

    def __init__(self, config: Config, on_wake: Callable[[], None],
                 http_get: Optional[Callable[[str, float], bytes]] = None,
                 poll_timeout_s: Optional[float] = None):
        self.config = config
        self.on_wake = on_wake
        self._http_get = http_get or _push_get
        self._poll_timeout_s = (
            float(poll_timeout_s) if poll_timeout_s is not None
            else float(config.push_poll_timeout_s)
        )
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._owner: Optional[str] = None
        self._node: Optional[str] = None
        self._base: Optional[str] = None  # bound by ensure()
        self._route: Optional[str] = None  # learned via 307
        self._tags: Optional[Tuple[str, ...]] = None  # scope lanes
        self.cursor = 0
        self.wakes = 0  # total on_wake firings (tests/bench read it)

    def ensure(self, owner_id: str, node: str, url: str,
               tags: Optional[Tuple[str, ...]] = None) -> None:
        """Bind (or re-bind) the subscription; starts the loop thread
        on first call. Safe from any thread, idempotent. `tags` scopes
        the subscription to those lanes (sync/scope.py — None = wake on
        every foreign write, unchanged)."""
        with self._lock:
            self._owner, self._node = owner_id, node
            self._base = url.rstrip("/")
            self._tags = tuple(tags) if tags else None
            start = self._thread is None and not self._stop.is_set()
            if start:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="evolu-push")
                self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            # Bounded: the loop may be parked in a long poll; it is a
            # daemon thread that only touches the network.
            t.join(timeout=0.2)

    def _target(self) -> Tuple[str, str, str, Optional[Tuple[str, ...]]]:
        with self._lock:
            return (self._route or self._base, self._owner, self._node,
                    self._tags)

    def _loop(self) -> None:
        import json as _json
        import random

        delay = BACKOFF_BASE_S
        attempt = 0
        follows = 0  # consecutive 307s without a successful poll
        while not self._stop.is_set():
            base, owner, node, tags = self._target()
            url = (
                f"{base}/push/poll?owner={urllib.parse.quote(owner)}"
                f"&node={node}&cursor={self.cursor}"
                f"&timeout={self._poll_timeout_s}"
            )
            if tags:
                url += "&tags=" + urllib.parse.quote(",".join(tags))
            try:
                raw = self._http_get(url, self._poll_timeout_s + 10.0)
            except urllib.error.HTTPError as e:
                if e.code == 307:
                    location = e.headers.get("Location") if e.headers else None
                    follows += 1
                    if location and follows <= 1:
                        with self._lock:
                            self._route = urllib.parse.urljoin(
                                base + "/", location).split("/push/", 1)[0]
                        metrics.inc("evolu_push_client_redirects_total")
                        continue
                    # A SECOND consecutive 307 means the relays'
                    # rings disagree (mid-rebalance ping-pong, the
                    # sync transport's one-follow rule): drop the
                    # learned route and back off instead of spinning
                    # a hot redirect loop (review finding).
                    with self._lock:
                        self._route = None
                    if self._stop.wait(min(BACKOFF_MAX_S, delay)):
                        return
                    delay = min(BACKOFF_MAX_S, delay * 2)
                    follows = 0
                    continue
                if e.code in (429, 503):
                    # Flow control (hub full / relay shedding): honor
                    # Retry-After, degrade toward polling cadence.
                    ra = _retry_after_seconds(e)
                    if self._stop.wait(ra if ra is not None else
                                       min(BACKOFF_MAX_S, delay)):
                        return
                    delay = min(BACKOFF_MAX_S, max(delay * 2, BACKOFF_BASE_S))
                    continue
                # Definitive rejection (404: stale route or push-less
                # relay; 400): drop the learned route, fail back, and
                # back off — never spin.
                with self._lock:
                    self._route = None
                metrics.inc("evolu_push_client_errors_total")
                if self._stop.wait(min(BACKOFF_MAX_S, delay)):
                    return
                delay = min(BACKOFF_MAX_S, delay * 2)
                continue
            except Exception:  # noqa: BLE001 - offline: backoff + jitter
                with self._lock:
                    self._route = None
                metrics.inc("evolu_push_client_offline_total")
                jittered = min(BACKOFF_MAX_S,
                               BACKOFF_BASE_S * (2 ** attempt))
                if self._stop.wait(jittered * random.random() + 0.01):
                    return
                attempt = min(attempt + 1, 10)
                continue
            attempt = 0
            delay = BACKOFF_BASE_S
            follows = 0
            metrics.inc("evolu_push_client_polls_total")
            try:
                body = _json.loads(raw)
                cursor = int(body["cursor"])
                wake = bool(body["wake"])
            except (ValueError, KeyError, TypeError):
                metrics.inc("evolu_push_client_errors_total")
                if self._stop.wait(min(BACKOFF_MAX_S, delay)):
                    return
                delay = min(BACKOFF_MAX_S, delay * 2)
                continue
            # ADOPT the relay's cursor, never max() it: cursors are
            # per-hub sequence numbers, and a relay restart (or a
            # retarget to a different relay) legitimately answers a
            # SMALLER one. Clinging to the old epoch's larger value
            # would make qualifies() read fresh events as already-seen
            # — silently missed wakeups until the new hub's seq caught
            # up (review finding; the hub's cursor>seq conservative
            # wake is the server-side half of this fix).
            self.cursor = cursor
            if wake and not self._stop.is_set():
                self.wakes += 1
                metrics.inc("evolu_push_client_wakes_total")
                try:
                    self.on_wake()
                except Exception:  # noqa: BLE001 - the wake hook must
                    pass           # never kill the subscription loop


class PeriodicSyncer:
    """Timer analog of the reference's load/online/focus sync triggers
    (db.ts:390-412): posts a pull-only sync round every `interval`
    seconds until stopped."""

    def __init__(self, evolu, interval: float):
        self._evolu = evolu
        self._interval = interval
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True, name="evolu-autosync")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self._evolu.sync(refresh_queries=False)
            except Exception:  # noqa: BLE001 — never kill the timer
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not threading.current_thread():
            self._thread.join()


def connect(evolu, config: Optional[Config] = None) -> SyncTransport:
    """Wire a client to its relay: transport → Evolu.receive, and
    Evolu's post_sync → transport (db.ts:134-156's channel setup).
    When the config sets `sync_interval`, a periodic pull starts too
    (stopped by `evolu.dispose()`)."""
    cfg = config or evolu.config

    def on_reconnect():
        # The reference's online listener re-syncs immediately
        # (db.ts:390-412); app listeners (the `online` event analog)
        # fire first so they observe the transition itself. The
        # disposed gate closes the straggler-probe race: stop() only
        # joins the prober for 0.2s, so a probe completing mid-dispose
        # may still invoke this hook.
        if getattr(evolu, "_disposed", False):
            return
        evolu._fire_reconnect()
        evolu.sync(refresh_queries=False)

    transport = SyncTransport(
        cfg,
        on_receive=evolu.receive,
        sync_lock=evolu.worker.sync_lock,
        on_error=lambda e: evolu._dispatch_output(OnError(e)),
        on_reconnect=on_reconnect,
    )
    if cfg.push_subscribe:
        # The push leg (ISSUE 13): wake-driven sync rounds instead of
        # a timer. A wake only means "foreign rows may exist" — the
        # sync round it triggers is the same anti-entropy round a
        # timer would fire, so correctness is unchanged and a spurious
        # wake costs one empty round.
        def on_push_wake():
            if getattr(evolu, "_disposed", False):
                return
            evolu.sync(refresh_queries=False)

        transport.push_subscriber = PushSubscriber(cfg, on_push_wake)
    evolu.attach_transport(transport)
    prev = getattr(evolu, "_auto_syncer", None)
    if prev is not None:
        prev.stop()
        evolu._auto_syncer = None
    if cfg.sync_interval:
        evolu._auto_syncer = PeriodicSyncer(evolu, cfg.sync_interval)
    return transport
