"""The sync transport — SyncWorker analog.

Reference: packages/evolu/src/sync.worker.ts. One input shape (a sync
request carrying optional fresh messages + the clock), one pipeline
(sync.worker.ts:177-229): encrypt each message's content → protobuf
SyncRequest → HTTP POST octet-stream → parse SyncResponse → decrypt →
hand the result back to the DbWorker as a Receive command.

Network failure is swallowed by design — offline is a normal state,
recovery is the next sync trigger (sync.worker.ts:217-227). Every
round runs under the per-database sync lock, making sync mutually
exclusive across clients of the same database (syncLock.ts:8-12).
"""

from __future__ import annotations

import queue
import threading
import urllib.error
import urllib.request
from typing import Callable, Optional

from evolu_tpu.core.timestamp import timestamp_from_string
from evolu_tpu.core.types import CrdtMessage, UnknownError
from evolu_tpu.runtime.messages import OnError, SyncRequestInput
from evolu_tpu.runtime.synclock import SyncLock
from evolu_tpu.sync import protocol
from evolu_tpu.sync.crypto import decrypt_symmetric, encrypt_symmetric
from evolu_tpu.utils.config import Config
from evolu_tpu.utils.log import log


def encrypt_messages(messages, mnemonic: str):
    """sync.worker.ts:50-91 — per-message protobuf-encode + encrypt;
    the timestamp stays plaintext (the relay orders and diffs by it)."""
    out = []
    for m in messages:
        content = protocol.encode_content(m.table, m.row, m.column, m.value)
        out.append(
            protocol.EncryptedCrdtMessage(m.timestamp, encrypt_symmetric(content, mnemonic))
        )
    return tuple(out)


def decrypt_messages(messages, mnemonic: str):
    """sync.worker.ts:135-173."""
    out = []
    for m in messages:
        table, row, column, value = protocol.decode_content(
            decrypt_symmetric(m.content, mnemonic)
        )
        out.append(CrdtMessage(m.timestamp, table, row, column, value))
    return tuple(out)


class SyncTransport:
    """Owns a transport thread; `request_sync` enqueues a round.

    `on_receive(messages, merkle_tree, previous_diff)` is called with
    the decrypted response — typically `Evolu.receive`, closing the
    anti-entropy loop (SURVEY.md §3.3).
    """

    def __init__(
        self,
        config: Config,
        on_receive: Callable[[tuple, str, Optional[int]], None],
        sync_lock: Optional[SyncLock] = None,
        on_error: Optional[Callable[[Exception], None]] = None,
        http_post: Optional[Callable[[str, bytes], bytes]] = None,
    ):
        self.config = config
        self.on_receive = on_receive
        self.sync_lock = sync_lock or SyncLock()
        self.on_error = on_error or (lambda _e: None)
        self._http_post = http_post or _http_post
        self._queue: "queue.Queue[object]" = queue.Queue()
        self._stop = object()
        self._thread = threading.Thread(target=self._loop, daemon=True, name="evolu-sync")
        self._thread.start()

    def request_sync(self, request: SyncRequestInput) -> None:
        self._queue.put(request)

    def stop(self) -> None:
        self._queue.put(self._stop)
        self._thread.join()

    def flush(self) -> None:
        done = threading.Event()
        self._queue.put(done)
        done.wait()

    def _loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is self._stop:
                return
            if isinstance(item, threading.Event):
                item.set()
                continue
            with self.sync_lock.hold():
                self._sync_round(item)

    def _sync_round(self, request: SyncRequestInput) -> None:
        try:
            encrypted = encrypt_messages(request.messages, request.owner.mnemonic)
            node_id = timestamp_from_string(request.clock_timestamp).node
            body = protocol.encode_sync_request(
                protocol.SyncRequest(encrypted, request.owner.id, node_id, request.merkle_tree)
            )
        except Exception as e:  # noqa: BLE001
            self.on_error(UnknownError(e))
            return
        log("sync:request", url=self.config.sync_url,
            messages=len(request.messages), bytes=len(body))
        try:
            response_bytes = self._http_post(self.config.sync_url, body)
        except urllib.error.HTTPError as e:
            # The server answered: that's a real error (4xx/5xx), not
            # offline — surface it so divergence isn't silent.
            self.on_error(UnknownError(e))
            return
        except (urllib.error.URLError, OSError):
            return  # offline is not an error (sync.worker.ts:217-227)
        try:
            response = protocol.decode_sync_response(response_bytes)
            messages = decrypt_messages(response.messages, request.owner.mnemonic)
            log("sync:response", messages=len(messages), bytes=len(response_bytes))
            self.on_receive(messages, response.merkle_tree, request.previous_diff)
        except Exception as e:  # noqa: BLE001
            self.on_error(UnknownError(e))


def _http_post(url: str, body: bytes) -> bytes:
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/octet-stream"}, method="POST"
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.read()


class PeriodicSyncer:
    """Timer analog of the reference's load/online/focus sync triggers
    (db.ts:390-412): posts a pull-only sync round every `interval`
    seconds until stopped."""

    def __init__(self, evolu, interval: float):
        self._evolu = evolu
        self._interval = interval
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True, name="evolu-autosync")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self._evolu.sync(refresh_queries=False)
            except Exception:  # noqa: BLE001 — never kill the timer
                pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not threading.current_thread():
            self._thread.join()


def connect(evolu, config: Optional[Config] = None) -> SyncTransport:
    """Wire a client to its relay: transport → Evolu.receive, and
    Evolu's post_sync → transport (db.ts:134-156's channel setup).
    When the config sets `sync_interval`, a periodic pull starts too
    (stopped by `evolu.dispose()`)."""
    cfg = config or evolu.config
    transport = SyncTransport(
        cfg,
        on_receive=evolu.receive,
        sync_lock=evolu.worker.sync_lock,
        on_error=lambda e: evolu._dispatch_output(OnError(e)),
    )
    evolu.attach_transport(transport)
    prev = getattr(evolu, "_auto_syncer", None)
    if prev is not None:
        prev.stop()
        evolu._auto_syncer = None
    if cfg.sync_interval:
        evolu._auto_syncer = PeriodicSyncer(evolu, cfg.sync_interval)
    return transport
