"""OpenPGP symmetric message encryption (RFC 4880 subset).

Reference: packages/evolu/src/sync.worker.ts:59-91 encrypts each
CrdtMessageContent with OpenPGP.js v5 `encrypt({passwords: mnemonic,
config: {s2kIterationCountByte: 0}})`. This module produces and
consumes the same wire format so ciphertexts interoperate:

- SKESK packet (tag 3), v4: AES-256, iterated+salted S2K with SHA-256
  and count byte 0 (= 1024 octets hashed — the speed-over-KDF-hardness
  choice the reference makes; security rests on the 128-bit mnemonic
  entropy, not the KDF).
- SEIPD packet (tag 18), v1: AES-256-CFB over
  (16 random bytes ‖ last-2-repeat ‖ Literal-Data packet ‖ MDC),
  zero IV, with the SHA-1 MDC (tag 19) integrity trailer.

Decryption accepts any definite/partial-length new- or old-format
packet stream with an uncompressed, ZIP, or ZLIB compressed payload —
the shapes OpenPGP.js can emit for these small messages.

Crypto is host-side work by design (SURVEY.md §5): the TPU kernels
never see plaintext values, mirroring the E2EE-blind relay.

The ~3µs/msg S2K here is the measured per-message floor of this wire
format (docs/BENCHMARKS.md). `sync/aead.py` is the negotiated escape
hatch — session-keyed AES-256-GCM records under the `aead-batch-v1`
capability — and `aead.decrypt_content` is the dispatch that lets
stored logs mix both formats; this module stays the reference-parity
format and the only one un-negotiated peers ever receive.
"""

from __future__ import annotations

import hashlib
import os
import struct
import zlib
from typing import List, Optional, Tuple

try:
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes
except ModuleNotFoundError:
    # No `cryptography` wheel in this environment: the only primitive
    # used here is AES-CFB128, served equally by OpenSSL libcrypto over
    # ctypes (same ValueError size-check semantics — see _evp_cfb).
    from evolu_tpu.sync._evp_cfb import Cipher, algorithms, modes

SYM_AES256 = 9
HASH_SHA256 = 8
_S2K_COUNT_BYTE = 0  # sync.worker.ts:77-78


def _s2k_count(count_byte: int) -> int:
    return (16 + (count_byte & 15)) << ((count_byte >> 4) + 6)


def _s2k_iterated_salted(password: bytes, salt: bytes, count_byte: int, key_len: int) -> bytes:
    """RFC 4880 §3.7.1.3. SHA-256 emits 32 bytes = AES-256 key length,
    so a single hash context suffices (no preloaded-zero contexts)."""
    count = _s2k_count(count_byte)
    data = salt + password
    h = hashlib.sha256()
    full, rem = divmod(max(count, len(data)), len(data))
    h.update(data * full + data[:rem])
    return h.digest()[:key_len]


def _new_packet(tag: int, body: bytes) -> bytes:
    """New-format packet header with a definite length (RFC 4880 §4.2.2)."""
    if len(body) < 192:
        length = bytes([len(body)])
    elif len(body) < 8384:
        n = len(body) - 192
        length = bytes([192 + (n >> 8), n & 0xFF])
    else:
        length = b"\xff" + struct.pack(">I", len(body))
    return bytes([0xC0 | tag]) + length + body


def _aes_cfb(key: bytes):
    return Cipher(algorithms.AES(key), modes.CFB(b"\x00" * 16))


def encrypt_symmetric(plaintext: bytes, password: str) -> bytes:
    """→ SKESK ‖ SEIPD, decryptable by OpenPGP.js with the same password."""
    salt = os.urandom(8)
    key = _s2k_iterated_salted(password.encode("utf-8"), salt, _S2K_COUNT_BYTE, 32)
    skesk = _new_packet(3, bytes([4, SYM_AES256, 3, HASH_SHA256]) + salt + bytes([_S2K_COUNT_BYTE]))

    literal = _new_packet(11, b"b" + b"\x00" + b"\x00\x00\x00\x00" + plaintext)
    prefix = os.urandom(16)
    body = prefix + prefix[14:16] + literal
    mdc = hashlib.sha1(body + b"\xd3\x14").digest()
    body += b"\xd3\x14" + mdc
    enc = _aes_cfb(key).encryptor()
    seipd = _new_packet(18, b"\x01" + enc.update(body) + enc.finalize())
    return skesk + seipd


class PgpError(ValueError):
    pass


def _read_packets(data: bytes) -> List[Tuple[int, bytes]]:
    """Parse a packet stream → [(tag, body)]. Handles new-format
    (one/two/five-octet + partial lengths) and old-format headers."""
    packets: List[Tuple[int, bytes]] = []
    pos = 0
    while pos < len(data):
        ctb = data[pos]
        pos += 1
        if not ctb & 0x80:
            raise PgpError("bad packet header")
        if ctb & 0x40:  # new format
            tag = ctb & 0x3F
            body = bytearray()
            while True:
                first = data[pos]
                pos += 1
                if first < 192:
                    length, partial = first, False
                elif first < 224:
                    length = ((first - 192) << 8) + data[pos] + 192
                    pos += 1
                    partial = False
                elif first == 255:
                    length = struct.unpack(">I", data[pos : pos + 4])[0]
                    pos += 4
                    partial = False
                else:
                    length, partial = 1 << (first & 0x1F), True
                body += data[pos : pos + length]
                pos += length
                if not partial:
                    break
        else:  # old format
            tag = (ctb >> 2) & 0x0F
            ltype = ctb & 3
            if ltype == 0:
                length = data[pos]
                pos += 1
            elif ltype == 1:
                length = struct.unpack(">H", data[pos : pos + 2])[0]
                pos += 2
            elif ltype == 2:
                length = struct.unpack(">I", data[pos : pos + 4])[0]
                pos += 4
            else:
                length = len(data) - pos  # indeterminate: to end of input
            body = data[pos : pos + length]
            pos += length
        packets.append((tag, bytes(body)))
    return packets


def _unwrap_literal(body: bytes) -> bytes:
    """Literal Data packet (tag 11) → its data bytes."""
    name_len = body[1]
    return body[2 + name_len + 4 :]


def _unwrap_payload(packets: List[Tuple[int, bytes]]) -> bytes:
    for tag, body in packets:
        if tag == 11:
            return _unwrap_literal(body)
        if tag == 8:  # Compressed Data
            algo, payload = body[0], body[1:]
            if algo == 0:
                inner = payload
            elif algo == 1:  # ZIP (raw deflate)
                inner = zlib.decompress(payload, wbits=-15)
            elif algo == 2:  # ZLIB
                inner = zlib.decompress(payload)
            else:
                raise PgpError(f"unsupported compression algo {algo}")
            return _unwrap_payload(_read_packets(inner))
    raise PgpError("no literal data packet")


def decrypt_symmetric(message: bytes, password: str) -> bytes:
    """Inverse of `encrypt_symmetric`; verifies the MDC. ANY malformed
    input raises PgpError (truncated packet grammar otherwise escapes
    as IndexError/struct.error — found by fuzzing)."""
    try:
        return _decrypt_symmetric(message, password)
    except PgpError:
        raise
    except (IndexError, ValueError, struct.error, zlib.error) as e:
        # ValueError covers the cryptography layer too (e.g. a
        # truncated legacy-SED body yields an invalid CFB IV size).
        raise PgpError(f"malformed OpenPGP message: {e}") from e


def _decrypt_symmetric(message: bytes, password: str) -> bytes:
    skesk: Optional[bytes] = None
    seipd: Optional[bytes] = None
    sed: Optional[bytes] = None
    for tag, body in _read_packets(message):
        if tag == 3 and skesk is None:
            skesk = body
        elif tag == 18 and seipd is None:
            seipd = body
        elif tag == 9 and sed is None:
            sed = body  # legacy SED (no MDC) — accepted, not produced
    if skesk is None or (seipd is None and sed is None):
        raise PgpError("not a symmetrically encrypted OpenPGP message")

    version, sym_algo, s2k_type = skesk[0], skesk[1], skesk[2]
    if version != 4 or sym_algo != SYM_AES256:
        raise PgpError(f"unsupported SKESK version/algo {version}/{sym_algo}")
    if s2k_type == 3:
        hash_algo, salt, count_byte = skesk[3], skesk[4:12], skesk[12]
        if hash_algo != HASH_SHA256:
            raise PgpError(f"unsupported S2K hash {hash_algo}")
        key = _s2k_iterated_salted(password.encode("utf-8"), salt, count_byte, 32)
    elif s2k_type == 1:  # salted: ONE hash of salt‖password (RFC 4880 §3.7.1.2)
        if skesk[3] != HASH_SHA256:
            raise PgpError(f"unsupported S2K hash {skesk[3]}")
        salt = skesk[4:12]
        key = hashlib.sha256(salt + password.encode("utf-8")).digest()
    elif s2k_type == 0:  # simple: hash of the password alone (§3.7.1.1)
        if skesk[3] != HASH_SHA256:
            raise PgpError(f"unsupported S2K hash {skesk[3]}")
        key = hashlib.sha256(password.encode("utf-8")).digest()
    else:
        raise PgpError(f"unsupported S2K type {s2k_type}")

    if seipd is not None:
        if seipd[0] != 1:
            raise PgpError(f"unsupported SEIPD version {seipd[0]}")
        dec = _aes_cfb(key).decryptor()
        body = dec.update(seipd[1:]) + dec.finalize()
        prefix, repeat, rest = body[:16], body[16:18], body[18:]
        if repeat != prefix[14:16]:
            raise PgpError("session key check failed (wrong password?)")
        if rest[-22:-20] != b"\xd3\x14":
            raise PgpError("missing MDC")
        if hashlib.sha1(body[:-20]).digest() != rest[-20:]:
            raise PgpError("MDC integrity check failed")
        return _unwrap_payload(_read_packets(rest[:-22]))

    # Legacy SED: CFB with resync (RFC 4880 §13.9).
    block = 16
    dec = _aes_cfb(key).decryptor()
    head = dec.update(sed[: block + 2])
    if head[block : block + 2] != head[block - 2 : block]:
        raise PgpError("session key check failed (wrong password?)")
    resync = Cipher(algorithms.AES(key), modes.CFB(sed[2 : block + 2])).decryptor()
    rest = resync.update(sed[block + 2 :]) + resync.finalize()
    return _unwrap_payload(_read_packets(rest))
