"""ctypes binding for the batched OpenPGP layer (native/evolu_crypto.cpp).

SURVEY.md ranks the per-message encrypt/decrypt loop hot loop #3
(reference packages/evolu/src/sync.worker.ts:50-91,135-173). The pure
Python implementation (`sync/crypto.py`) stays the semantic oracle —
correct for every wire shape and the sole producer of error strings —
while this layer batches the canonical shapes into one C call per sync
leg. Measured r4 (1-core host): ~29k msgs/s encrypt / ~26k decrypt
pure → see docs/BENCHMARKS.md for the native numbers.

Fallback contract (exact-behavior preserving):
- `encrypt_batch` returns None when any message needs the Python path
  (unencodable value types, out-of-range ints); the caller then runs
  the pure loop, which raises the canonical TypeError.
- `decrypt_batch` takes per-message statuses from C++: status 0 rows
  were fully verified (prefix + MDC) and decoded on the canonical
  path; every other row — old-format headers, partial lengths,
  compression, legacy SED, wrong password, MDC failure, non-canonical
  protobuf — re-runs through the Python oracle at its original
  position, so error types, messages, and first-failure order are
  byte-identical to the pure path. UTF-8 validation happens here (the
  `.decode()` below), with invalid rows demoted to the oracle too.
"""

from __future__ import annotations

import ctypes
import struct
from typing import List, Optional, Sequence, Tuple

from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.sync import protocol
from evolu_tpu.sync.crypto import decrypt_symmetric
from evolu_tpu.utils.native_loader import load_native_library

_INT64_LO, _INT64_HI = -(1 << 63), (1 << 63) - 1


def _configure(lib: ctypes.CDLL) -> Optional[ctypes.CDLL]:
    c = ctypes
    u8p = c.POINTER(c.c_uint8)
    lib.ehc_available.restype = c.c_int
    lib.ehc_encrypt_batch.restype = c.c_int
    lib.ehc_encrypt_batch.argtypes = [
        c.c_int64, c.c_char_p, c.POINTER(c.c_int32), c.POINTER(c.c_int8),
        c.POINTER(c.c_int64), c.POINTER(c.c_double), c.c_char_p, c.c_int32,
        c.POINTER(c.c_void_p), c.POINTER(c.c_int64),
    ]
    lib.ehc_decrypt_batch.restype = c.c_int
    lib.ehc_decrypt_batch.argtypes = [
        c.c_int64, c.c_char_p, c.POINTER(c.c_int32), c.c_char_p, c.c_int32,
        u8p, c.POINTER(c.c_void_p), c.POINTER(c.c_int64),
    ]
    lib.ehc_free.argtypes = [c.c_void_p]
    if not lib.ehc_available():
        return None
    return lib


def load_library() -> Optional[ctypes.CDLL]:
    return load_native_library("libevolu_crypto.so", _configure)


def native_available() -> bool:
    return load_library() is not None


def encrypt_batch(messages: Sequence, password: str):
    """→ tuple[EncryptedCrdtMessage] or None (Python path required).

    Mirrors `encrypt_symmetric(encode_content(...))` per message
    (crypto.py:70-83) with batch-level S2K/AES/MDC in C++. Returns
    None — never raises — when any value needs the oracle's error
    surface."""
    lib = load_library()
    if lib is None:
        return None
    n = len(messages)
    parts: List[bytes] = []
    lens = (ctypes.c_int32 * (4 * n))()
    vkinds = (ctypes.c_int8 * n)()
    ivals = (ctypes.c_int64 * n)()
    dvals = (ctypes.c_double * n)()
    for j, m in enumerate(messages):
        t = m.table.encode("utf-8")
        r = m.row.encode("utf-8")
        col = m.column.encode("utf-8")
        parts += (t, r, col)
        v = m.value
        base = 4 * j
        lens[base], lens[base + 1], lens[base + 2] = len(t), len(r), len(col)
        lens[base + 3] = -1
        if v is None:
            vkinds[j] = 0
        elif isinstance(v, bool):
            vkinds[j], ivals[j] = 2, int(v)
        elif isinstance(v, str):
            sv = v.encode("utf-8")
            parts.append(sv)
            vkinds[j], lens[base + 3] = 1, len(sv)
        elif isinstance(v, int):
            if not _INT64_LO <= v <= _INT64_HI:
                return None  # oracle raises the canonical TypeError
            vkinds[j], ivals[j] = 2, v
        elif isinstance(v, float):
            vkinds[j], dvals[j] = 3, v
        else:
            return None  # unencodable → oracle raises
    blob = b"".join(parts)
    pw = password.encode("utf-8")
    out_p = ctypes.c_void_p()
    out_len = ctypes.c_int64()
    rc = lib.ehc_encrypt_batch(
        n, blob, lens, vkinds, ivals, dvals, pw, len(pw),
        ctypes.byref(out_p), ctypes.byref(out_len),
    )
    if rc != 0:
        return None
    try:
        raw = ctypes.string_at(out_p.value, out_len.value)
    finally:
        lib.ehc_free(out_p)
    out = []
    pos = 0
    for m in messages:
        (ct_len,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        out.append(protocol.EncryptedCrdtMessage(m.timestamp, raw[pos : pos + ct_len]))
        pos += ct_len
    if pos != len(raw):
        return None  # size accounting drift — distrust the whole batch
    return tuple(out)


_REC_HEAD = struct.Struct("<iiiib q d")


def decrypt_batch(messages: Sequence, password: str) -> Tuple[CrdtMessage, ...]:
    """→ tuple[CrdtMessage]; raises exactly what the pure path raises.

    C++ handles canonical rows; every status≠0 row re-runs through the
    Python oracle IN ORDER, so the first failing message raises the
    same error the pure loop would have."""
    lib = load_library()
    if lib is None:
        return _pure(messages, password)
    n = len(messages)
    ct_blob = b"".join(m.content for m in messages)
    ct_lens = (ctypes.c_int32 * n)(*(len(m.content) for m in messages))
    statuses = (ctypes.c_uint8 * n)()
    pw = password.encode("utf-8")
    out_p = ctypes.c_void_p()
    out_len = ctypes.c_int64()
    rc = lib.ehc_decrypt_batch(
        n, ct_blob, ct_lens, pw, len(pw), statuses,
        ctypes.byref(out_p), ctypes.byref(out_len),
    )
    if rc != 0:
        return _pure(messages, password)
    try:
        raw = ctypes.string_at(out_p.value, out_len.value)
    finally:
        lib.ehc_free(out_p)

    out: List[CrdtMessage] = []
    pos = 0
    for j, m in enumerate(messages):
        if statuses[j] != 0:
            out.append(_pure_one(m, password))
            continue
        tl, rl, cl, vl, vkind, ival, dval = _REC_HEAD.unpack_from(raw, pos)
        pos += _REC_HEAD.size
        try:
            table = raw[pos : pos + tl].decode("utf-8")
            pos += tl
            row = raw[pos : pos + rl].decode("utf-8")
            pos += rl
            column = raw[pos : pos + cl].decode("utf-8")
            pos += cl
            if vkind == 0:
                value = None
            elif vkind == 1:
                value = raw[pos : pos + vl].decode("utf-8")
                pos += vl
            elif vkind == 2:
                value = ival
            else:
                value = dval
        except UnicodeDecodeError:
            # Invalid UTF-8 in a string field: skip this record's
            # remaining bytes are already consumed above up to the
            # failing field — demote to the oracle for the canonical
            # ValueError. (pos may sit mid-record; recompute.)
            return _pure(messages, password)
        out.append(CrdtMessage(m.timestamp, table, row, column, value))
    return tuple(out)


def _pure_one(m, password: str) -> CrdtMessage:
    table, row, column, value = protocol.decode_content(
        decrypt_symmetric(m.content, password)
    )
    return CrdtMessage(m.timestamp, table, row, column, value)


def _pure(messages: Sequence, password: str) -> Tuple[CrdtMessage, ...]:
    return tuple(_pure_one(m, password) for m in messages)
