"""ctypes binding for the batched OpenPGP layer (native/evolu_crypto.cpp).

SURVEY.md ranks the per-message encrypt/decrypt loop hot loop #3
(reference packages/evolu/src/sync.worker.ts:50-91,135-173). The pure
Python implementation (`sync/crypto.py`) stays the semantic oracle —
correct for every wire shape and the sole producer of error strings —
while this layer batches the canonical shapes into one C call per sync
leg. Measured r4 (1-core host): ~29k msgs/s encrypt / ~26k decrypt
pure → see docs/BENCHMARKS.md for the native numbers.

Fallback contract (exact-behavior preserving):
- `encrypt_batch` returns None when any message needs the Python path
  (unencodable value types, out-of-range ints); the caller then runs
  the pure loop, which raises the canonical TypeError.
- `decrypt_batch` takes per-message statuses from C++: status 0 rows
  were fully verified (prefix + MDC) and decoded on the canonical
  path; every other row — old-format headers, partial lengths,
  compression, legacy SED, wrong password, MDC failure, non-canonical
  protobuf — re-runs through the Python oracle at its original
  position, so error types, messages, and first-failure order are
  byte-identical to the pure path. UTF-8 validation happens here (the
  `.decode()` below), with invalid rows demoted to the oracle too.
"""

from __future__ import annotations

import ctypes
import struct
from array import array
from typing import List, Optional, Sequence, Tuple

from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.sync import protocol
from evolu_tpu.sync.aead import decrypt_content
from evolu_tpu.utils.native_loader import load_native_library

_INT64_LO, _INT64_HI = -(1 << 63), (1 << 63) - 1
_AEAD_NATIVE = False  # set by _configure when the built .so has the v2 leg


def _configure(lib: ctypes.CDLL) -> Optional[ctypes.CDLL]:
    c = ctypes
    u8p = c.POINTER(c.c_uint8)
    lib.ehc_available.restype = c.c_int
    lib.ehc_encrypt_batch.restype = c.c_int
    lib.ehc_encrypt_batch.argtypes = [
        c.c_int64, c.c_char_p, c.POINTER(c.c_int32), c.POINTER(c.c_int8),
        c.POINTER(c.c_int64), c.POINTER(c.c_double), c.c_char_p, c.c_int32,
        c.POINTER(c.c_void_p), c.POINTER(c.c_int64),
    ]
    lib.ehc_encrypt_wire_batch.restype = c.c_int
    lib.ehc_encrypt_wire_batch.argtypes = [
        c.c_int64, c.c_char_p, c.POINTER(c.c_int32), c.c_char_p,
        c.POINTER(c.c_int32), c.POINTER(c.c_int8), c.POINTER(c.c_int64),
        c.POINTER(c.c_double), c.c_char_p, c.c_int32,
        c.POINTER(c.c_void_p), c.POINTER(c.c_int64),
    ]
    lib.ehc_decrypt_batch.restype = c.c_int
    lib.ehc_decrypt_batch.argtypes = [
        c.c_int64, c.c_char_p, c.POINTER(c.c_int32), c.c_char_p, c.c_int32,
        u8p, c.POINTER(c.c_void_p), c.POINTER(c.c_int64),
    ]
    lib.ehc_decrypt_response.restype = c.c_int
    lib.ehc_decrypt_response.argtypes = [
        c.c_char_p, c.c_int64, c.c_char_p, c.c_int32,
        c.POINTER(c.c_void_p), c.POINTER(c.c_int64),
    ]
    lib.ehc_decrypt_response_columns.restype = c.c_int
    lib.ehc_decrypt_response_columns.argtypes = [
        c.c_char_p, c.c_int64, c.c_char_p, c.c_int32,
        c.POINTER(c.c_void_p), c.POINTER(c.c_int64),
    ]
    lib.ehc_free.argtypes = [c.c_void_p]
    # aead-batch-v1 leg (ISSUE 8). Guarded: a stale binary without the
    # symbol (no toolchain to rebuild) must not veto the whole v1
    # library — the v2 entry points then answer None (pure path).
    global _AEAD_NATIVE
    try:
        lib.ehc_aead_encrypt_wire_batch.restype = c.c_int
        lib.ehc_aead_encrypt_wire_batch.argtypes = [
            c.c_int64,
            c.c_char_p, c.POINTER(c.c_int32),  # timestamps
            c.c_char_p, c.POINTER(c.c_int32),  # tables
            c.c_char_p, c.POINTER(c.c_int32),  # rows
            c.c_char_p, c.POINTER(c.c_int32),  # columns
            c.c_char_p, c.POINTER(c.c_int32),  # string values
            c.POINTER(c.c_int8), c.POINTER(c.c_int64), c.POINTER(c.c_double),
            c.c_char_p, c.c_char_p,  # key32, salt16
            c.POINTER(c.c_void_p), c.POINTER(c.c_int64),
        ]
        _AEAD_NATIVE = True
    except AttributeError:
        _AEAD_NATIVE = False
    if not lib.ehc_available():
        return None
    return lib


def load_library() -> Optional[ctypes.CDLL]:
    return load_native_library("libevolu_crypto.so", _configure)


def native_available() -> bool:
    return load_library() is not None


def _pack_values(messages: Sequence):
    """Columnar packing shared by both encrypt entry points; None when
    any value needs the Python oracle's error surface."""
    n = len(messages)
    parts: List[bytes] = []
    lens = (ctypes.c_int32 * (4 * n))()
    vkinds = (ctypes.c_int8 * n)()
    ivals = (ctypes.c_int64 * n)()
    dvals = (ctypes.c_double * n)()
    for j, m in enumerate(messages):
        t = m.table.encode("utf-8")
        r = m.row.encode("utf-8")
        col = m.column.encode("utf-8")
        parts += (t, r, col)
        v = m.value
        base = 4 * j
        lens[base], lens[base + 1], lens[base + 2] = len(t), len(r), len(col)
        lens[base + 3] = -1
        if v is None:
            vkinds[j] = 0
        elif isinstance(v, bool):
            vkinds[j], ivals[j] = 2, int(v)
        elif isinstance(v, str):
            sv = v.encode("utf-8")
            parts.append(sv)
            vkinds[j], lens[base + 3] = 1, len(sv)
        elif isinstance(v, int):
            if not _INT64_LO <= v <= _INT64_HI:
                return None  # oracle raises the canonical TypeError
            vkinds[j], ivals[j] = 2, v
        elif isinstance(v, float):
            vkinds[j], dvals[j] = 3, v
        else:
            return None  # unencodable → oracle raises
    return b"".join(parts), lens, vkinds, ivals, dvals


def encrypt_batch(messages: Sequence, password: str):
    """→ tuple[EncryptedCrdtMessage] or None (Python path required).

    Mirrors `encrypt_symmetric(encode_content(...))` per message
    (crypto.py:70-83) with batch-level S2K/AES/MDC in C++. Returns
    None — never raises — when any value needs the oracle's error
    surface."""
    lib = load_library()
    if lib is None:
        return None
    packed = _pack_values(messages)
    if packed is None:
        return None
    blob, lens, vkinds, ivals, dvals = packed
    pw = password.encode("utf-8")
    out_p = ctypes.c_void_p()
    out_len = ctypes.c_int64()
    rc = lib.ehc_encrypt_batch(
        len(messages), blob, lens, vkinds, ivals, dvals, pw, len(pw),
        ctypes.byref(out_p), ctypes.byref(out_len),
    )
    if rc != 0:
        return None
    try:
        raw = ctypes.string_at(out_p.value, out_len.value)
    finally:
        lib.ehc_free(out_p)
    out = []
    pos = 0
    for m in messages:
        (ct_len,) = struct.unpack_from("<I", raw, pos)
        pos += 4
        out.append(protocol.EncryptedCrdtMessage(m.timestamp, raw[pos : pos + ct_len]))
        pos += ct_len
    if pos != len(raw):
        return None  # size accounting drift — distrust the whole batch
    return tuple(out)


def encode_push_request(
    messages: Sequence, password: str, user_id: str, node_id: str,
    merkle_tree: str,
) -> Optional[bytes]:
    """The whole SyncRequest body with ZERO per-message Python:
    `ehc_encrypt_wire_batch` emits the encrypted `messages` field-1
    stream byte-compatibly with `protocol.encode_sync_request`, and
    the three scalar fields append here. None → pure path."""
    lib = load_library()
    if lib is None:
        return None
    packed = _pack_values(messages)
    if packed is None:
        return None
    blob, lens, vkinds, ivals, dvals = packed
    n = len(messages)
    ts_parts = []
    ts_lens = (ctypes.c_int32 * n)()
    for j, m in enumerate(messages):
        ts = m.timestamp.encode("utf-8")
        ts_parts.append(ts)
        ts_lens[j] = len(ts)
    pw = password.encode("utf-8")
    out_p = ctypes.c_void_p()
    out_len = ctypes.c_int64()
    rc = lib.ehc_encrypt_wire_batch(
        n, b"".join(ts_parts), ts_lens, blob, lens, vkinds, ivals, dvals,
        pw, len(pw), ctypes.byref(out_p), ctypes.byref(out_len),
    )
    if rc != 0:
        return None
    try:
        stream = ctypes.string_at(out_p.value, out_len.value)
    finally:
        lib.ehc_free(out_p)
    return (
        stream
        + protocol._string(2, user_id)
        + protocol._string(3, node_id)
        + protocol._string(4, merkle_tree)
    )


# Exact-type → wire kind for the columnar packer. 4 = not packable
# (bytes, str/int subclasses, anything exotic) → the Python oracle owns
# the error surface. bool IS exact here (2: varint like int); a bool in
# an array("q") slot is its 0/1 int value by the buffer protocol.
_VKIND_OF = {type(None): 0, str: 1, bool: 2, int: 2, float: 3}


def _pack_columns(messages: Sequence):
    """Columnar packing for the aead wire leg — one blob + length array
    PER FIELD instead of the v1 interleave. The per-message Python
    share is the binding cost of the v2 leg (the C side dropped to one
    GCM per record), so every pass here is a comprehension or a map —
    no per-message interpreter loop with method-call dispatch (that
    shape measured ~2× slower). int64 range policing is delegated to
    `array("q")`'s own OverflowError: one C-level check instead of two
    Python comparisons per message.
    None when any value needs the Python oracle's error surface."""
    enc = str.encode
    try:
        tsb = [enc(m.timestamp) for m in messages]
        tb = [enc(m.table) for m in messages]
        rb = [enc(m.row) for m in messages]
        cb = [enc(m.column) for m in messages]
    except (TypeError, AttributeError):
        return None  # non-string field → oracle raises canonically
    kind_of = _VKIND_OF
    vals = [m.value for m in messages]
    kinds = [kind_of.get(type(v), 4) for v in vals]
    if 4 in kinds:
        return None  # unencodable somewhere → oracle raises
    try:
        ivals = array("q", [v if k == 2 else 0 for k, v in zip(kinds, vals)])
    except OverflowError:
        return None  # beyond int64 → oracle raises the canonical TypeError
    dvals = array("d", [v if k == 3 else 0.0 for k, v in zip(kinds, vals)])
    sparts = [enc(v) if k == 1 else b"" for k, v in zip(kinds, vals)]
    join = b"".join
    i32 = ctypes.c_int32
    lens = array("i", map(len, tsb)) + array("i", map(len, tb)) \
        + array("i", map(len, rb)) + array("i", map(len, cb)) \
        + array("i", map(len, sparts))
    n = len(tsb)
    la = (i32 * len(lens)).from_buffer(lens)
    return (
        join(tsb), la, join(tb), n, join(rb), join(cb), join(sparts),
        (ctypes.c_int8 * n).from_buffer(array("b", kinds)),
        (ctypes.c_int64 * n).from_buffer(ivals),
        (ctypes.c_double * n).from_buffer(dvals),
    )


_PY_PUSH = False  # resolved lazily: False=untried, None=unavailable


def _py_push_fn():
    """The CPython-ABI encode lane (`ehc_aead_encrypt_push_py` via
    ctypes.PyDLL — PyDLL keeps the GIL, which the extraction phase
    requires; the C side drops it itself for the seal loop so other
    threads overlap the crypto). Enabled only after `ehc_py_abi_probe`
    validates the
    self-declared PyObject layout against a live str on THIS
    interpreter — any drift (debug build, free-threading, future
    CPython) silently falls back to the blob packer. None when
    unavailable."""
    global _PY_PUSH
    if _PY_PUSH is not False:
        return _PY_PUSH
    _PY_PUSH = None
    if load_library() is None or not _AEAD_NATIVE:
        return None
    import os

    from evolu_tpu.utils.native_loader import NATIVE_DIR

    try:
        c = ctypes
        plib = c.PyDLL(os.path.join(NATIVE_DIR, "libevolu_crypto.so"))
        probe = plib.ehc_py_abi_probe
        probe.restype = c.c_int
        probe.argtypes = [c.py_object]
        if probe("x") != 0:
            return None
        fn = plib.ehc_aead_encrypt_push_py
        fn.restype = c.c_int
        fn.argtypes = [
            c.py_object, c.c_int64, c.c_char_p, c.c_char_p,
            c.POINTER(c.c_void_p), c.POINTER(c.c_int64),
        ]
        _PY_PUSH = fn
    except (OSError, AttributeError, ctypes.ArgumentError):
        _PY_PUSH = None
    return _PY_PUSH


def encode_push_request_aead(
    messages: Sequence, key: bytes, salt: bytes, user_id: str, node_id: str,
    merkle_tree: str,
) -> Optional[bytes]:
    """The v2 twin of `encode_push_request`: the whole SyncRequest body
    with ONE session key schedule and one GCM per message, byte-
    compatible with `protocol.encode_sync_request` over
    `aead.encrypt_record` contents. Two native lanes: the CPython-ABI
    extraction (`ehc_aead_encrypt_push_py`, zero per-message Python)
    and the columnar blob ABI (`ehc_aead_encrypt_wire_batch`) behind
    it. None → pure path (library or symbol unavailable, or a value
    that needs the oracle's error surface)."""
    lib = load_library()
    if lib is None or not _AEAD_NATIVE:
        return None
    fn = _py_push_fn()
    if fn is not None:
        if not isinstance(messages, (tuple, list)):
            messages = tuple(messages)
        out_p = ctypes.c_void_p()
        out_len = ctypes.c_int64()
        rc = fn(messages, len(messages), key, salt,
                ctypes.byref(out_p), ctypes.byref(out_len))
        if rc == 0:
            try:
                stream = ctypes.string_at(out_p.value, out_len.value)
            finally:
                lib.ehc_free(out_p)
            return (
                stream
                + protocol._string(2, user_id)
                + protocol._string(3, node_id)
                + protocol._string(4, merkle_tree)
            )
        # rc != 0: shape demotion — the blob packer (then the oracle)
        # owns the canonical error surface.
    packed = _pack_columns(messages)
    if packed is None:
        return None
    ts_blob, lens, t_blob, n, r_blob, c_blob, s_blob, vkinds, ivals, dvals = packed
    p32 = ctypes.POINTER(ctypes.c_int32)
    base = ctypes.cast(lens, p32)
    out_p = ctypes.c_void_p()
    out_len = ctypes.c_int64()
    rc = lib.ehc_aead_encrypt_wire_batch(
        n, ts_blob, base,
        t_blob, ctypes.cast(ctypes.byref(lens, 4 * n), p32),
        r_blob, ctypes.cast(ctypes.byref(lens, 8 * n), p32),
        c_blob, ctypes.cast(ctypes.byref(lens, 12 * n), p32),
        s_blob, ctypes.cast(ctypes.byref(lens, 16 * n), p32),
        vkinds, ivals, dvals, key, salt,
        ctypes.byref(out_p), ctypes.byref(out_len),
    )
    if rc != 0:
        return None
    try:
        stream = ctypes.string_at(out_p.value, out_len.value)
    finally:
        lib.ehc_free(out_p)
    return (
        stream
        + protocol._string(2, user_id)
        + protocol._string(3, node_id)
        + protocol._string(4, merkle_tree)
    )


_REC_HEAD = struct.Struct("<iiiib q d")


def _parse_record(raw: bytes, pos: int):
    """ONE parser for the C decoded-content record layout
    (append_content_record) — both decrypt entry points use it, so the
    format can never drift between them. → (table, row, column, value,
    next_pos); raises UnicodeDecodeError on invalid UTF-8 (callers
    demote to the pure oracle)."""
    tl, rl, cl, vl, vkind, ival, dval = _REC_HEAD.unpack_from(raw, pos)
    pos += _REC_HEAD.size
    table = raw[pos : pos + tl].decode("utf-8")
    pos += tl
    row = raw[pos : pos + rl].decode("utf-8")
    pos += rl
    column = raw[pos : pos + cl].decode("utf-8")
    pos += cl
    if vkind == 0:
        value = None
    elif vkind == 1:
        value = raw[pos : pos + vl].decode("utf-8")
        pos += vl
    elif vkind == 2:
        value = ival
    else:
        value = dval
    return table, row, column, value, pos


def decrypt_batch(messages: Sequence, password: str) -> Tuple[CrdtMessage, ...]:
    """→ tuple[CrdtMessage]; raises exactly what the pure path raises.

    C++ handles canonical rows; every status≠0 row re-runs through the
    Python oracle IN ORDER, so the first failing message raises the
    same error the pure loop would have."""
    lib = load_library()
    if lib is None:
        return _pure(messages, password)
    n = len(messages)
    ct_blob = b"".join(m.content for m in messages)
    ct_lens = (ctypes.c_int32 * n)(*(len(m.content) for m in messages))
    statuses = (ctypes.c_uint8 * n)()
    pw = password.encode("utf-8")
    out_p = ctypes.c_void_p()
    out_len = ctypes.c_int64()
    rc = lib.ehc_decrypt_batch(
        n, ct_blob, ct_lens, pw, len(pw), statuses,
        ctypes.byref(out_p), ctypes.byref(out_len),
    )
    if rc != 0:
        return _pure(messages, password)
    try:
        raw = ctypes.string_at(out_p.value, out_len.value)
    finally:
        lib.ehc_free(out_p)

    out: List[CrdtMessage] = []
    pos = 0
    for j, m in enumerate(messages):
        if statuses[j] != 0:
            out.append(_pure_one(m, password))
            continue
        try:
            table, row, column, value, pos = _parse_record(raw, pos)
        except UnicodeDecodeError:
            # Invalid UTF-8 in a string field: demote the whole batch
            # to the oracle for the canonical ValueError.
            return _pure(messages, password)
        out.append(CrdtMessage(m.timestamp, table, row, column, value))
    return tuple(out)


def decrypt_response(response_bytes: bytes, password: str):
    """Fused `decode_sync_response` + `decrypt_messages`: → (messages
    tuple, merkle_tree str), or None when the WIRE shape needs the
    pure decoder (whole-batch fallback preserves its exact ValueError
    surface; per-message crypto fallbacks re-run the oracle at their
    position). Raises what the pure path raises."""
    lib = load_library()
    if lib is None:
        return None
    pw = password.encode("utf-8")
    out_p = ctypes.c_void_p()
    out_len = ctypes.c_int64()
    rc = lib.ehc_decrypt_response(
        response_bytes, len(response_bytes), pw, len(pw),
        ctypes.byref(out_p), ctypes.byref(out_len),
    )
    if rc != 0:
        return None  # rc 2: non-canonical wire → pure decoder wholesale
    try:
        raw = ctypes.string_at(out_p.value, out_len.value)
    finally:
        lib.ehc_free(out_p)
    # Pass 1 — decode EVERY wire-derived string (timestamps, decoded
    # records, the tree) before any fallback decrypt runs: the pure
    # path fully parses the response, THEN decrypts in order, so a
    # bad-UTF-8 tree must surface before a bad ciphertext (fuzz-found
    # ordering divergence). Any UnicodeDecodeError → None, the pure
    # decoder owns that exact error.
    try:
        (n,) = struct.unpack_from("<q", raw, 0)
        (tree_len,) = struct.unpack_from("<I", raw, 8)
        pos = 12
        items: List[tuple] = []  # (timestamp, decoded CrdtMessage | ct span)
        for _ in range(n):
            status = raw[pos]
            pos += 1
            (ts_len,) = struct.unpack_from("<I", raw, pos)
            pos += 4
            timestamp = raw[pos : pos + ts_len].decode("utf-8")
            pos += ts_len
            if status != 0:
                (ct_off,) = struct.unpack_from("<q", raw, pos)
                pos += 8
                (ct_len,) = struct.unpack_from("<I", raw, pos)
                pos += 4
                items.append((timestamp, (ct_off, ct_len)))
                continue
            table, row, column, value, pos = _parse_record(raw, pos)
            items.append((timestamp, CrdtMessage(timestamp, table, row, column, value)))
        tree = raw[pos : pos + tree_len].decode("utf-8")
    except UnicodeDecodeError:
        return None

    # Pass 2 — oracle re-runs for demoted rows, in wire order (their
    # PgpError/ValueError fires exactly where the pure loop's would).
    out: List[CrdtMessage] = []
    for timestamp, item in items:
        if isinstance(item, CrdtMessage):
            out.append(item)
            continue
        ct_off, ct_len = item
        ct = response_bytes[ct_off : ct_off + ct_len]
        table, row, column, value = protocol.decode_content(
            decrypt_content(ct, password)
        )
        out.append(CrdtMessage(timestamp, table, row, column, value))
    return tuple(out), tree


def decrypt_response_columns(response_bytes: bytes, password: str):
    """The fully-fused receive decode: SyncResponse protobuf walk +
    decrypt + columnarization in ONE C call → (PackedReceive, tree) —
    zero per-row Python objects, interned cells, a 46-wide timestamp
    slab, bind-ready value columns. None whenever ANY row needs the
    object path (demoted crypto, non-46 timestamp, invalid UTF-8,
    non-canonical wire) — the caller then runs `decrypt_response` /
    the pure decoder, which own the exact error surface. Success here
    implies the object path would have produced the same batch
    (pinned by tests), so behavior is identical either way."""
    lib = load_library()
    if lib is None:
        return None
    pw = password.encode("utf-8")
    out_p = ctypes.c_void_p()
    out_len = ctypes.c_int64()
    rc = lib.ehc_decrypt_response_columns(
        response_bytes, len(response_bytes), pw, len(pw),
        ctypes.byref(out_p), ctypes.byref(out_len),
    )
    if rc != 0:
        return None
    try:
        raw = ctypes.string_at(out_p.value, out_len.value)
    finally:
        lib.ehc_free(out_p)
    from evolu_tpu.core.packed import PackedReceive

    try:
        return PackedReceive.from_blob(raw)
    except UnicodeDecodeError:  # defense in depth: C validated UTF-8
        return None


def _pure_one(m, password: str) -> CrdtMessage:
    # decrypt_content dispatches v1 OpenPGP vs aead-batch-v1 records by
    # the self-describing magic — the oracle reads BOTH unconditionally
    # (negotiation gates emission, never decoding).
    table, row, column, value = protocol.decode_content(
        decrypt_content(m.content, password)
    )
    return CrdtMessage(m.timestamp, table, row, column, value)


def _pure(messages: Sequence, password: str) -> Tuple[CrdtMessage, ...]:
    return tuple(_pure_one(m, password) for m in messages)
