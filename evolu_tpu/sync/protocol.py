"""The protobuf wire contract, hand-rolled.

Reference: packages/evolu/protos/protobuf.proto (field numbers are the
contract — a TypeScript reference client must be able to talk to this
framework's relay and vice versa):

    CrdtMessageContent { table=1 row=2 column=3
                         oneof value { stringValue=4 numberValue=5 } }
    EncryptedCrdtMessage { timestamp=1 content=2 }
    SyncRequest  { messages=1 userId=2 nodeId=3 merkleTree=4 }
    SyncResponse { messages=1 merkleTree=2 }

This module implements exactly the proto3 subset those messages need
(varint, length-delimited, 64-bit) with no codegen dependency.

Float values: the reference's value oneof is string|int32
(protobuf.proto:5-13); floats only survive its lax TS encoder. Here
non-integer numbers travel in an extension field `doubleValue=6` (wire
type I64) and 64-bit ints in `int64Value=7` — lossless between
evolu_tpu peers; a reference TS client skips the unknown fields and
sees null, which is the honest reading of a value its schema cannot
express. When an owner is shared with reference TS peers that silent
drop is itself the hazard, so `encode_content(extensions=False)` —
`Config.wire_extensions = False` — refuses such values at encode time
instead (strict interop mode: everything that leaves the client is
expressible in the reference schema, and reference-range traffic is
byte-identical either way, pinned by the protoc fixture).
"""

from __future__ import annotations

import functools
import struct
from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

from evolu_tpu.core.types import CrdtValue

_INT32_MIN, _INT32_MAX = -(2**31), 2**31 - 1


def _wire_decoder(fn):
    """Typed error contract for the public decoders: ANY malformed
    input raises ValueError (wire-type mismatches otherwise surface as
    AttributeError/TypeError from e.g. `int.decode`, found by fuzzing).
    The relay's handler and the sync client both key off ValueError."""

    @functools.wraps(fn)
    def wrapper(data: bytes):
        try:
            return fn(data)
        except ValueError:
            raise
        except (AttributeError, TypeError, IndexError, OverflowError,
                struct.error, UnicodeDecodeError) as e:
            raise ValueError(f"malformed {fn.__name__[7:]} message: {e}") from e

    return wrapper


# --- primitive writers ---


def _varint(value: int) -> bytes:
    if value < 0:  # proto3 int32: negatives are 10-byte two's-complement varints
        value += 1 << 64
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field_number: int, wire_type: int) -> bytes:
    return _varint((field_number << 3) | wire_type)


def _len_delimited(field_number: int, data: bytes) -> bytes:
    return _tag(field_number, 2) + _varint(len(data)) + data


def _string(field_number: int, s: str) -> bytes:
    return _len_delimited(field_number, s.encode("utf-8"))


# --- primitive readers ---


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _read_field(data: bytes, pos: int) -> Tuple[int, int, Union[int, bytes], int]:
    """→ (field_number, wire_type, value, next_pos). Length-delimited
    values come back as bytes, varints/fixed as ints."""
    key, pos = _read_varint(data, pos)
    field_number, wire_type = key >> 3, key & 7
    if wire_type == 0:
        value, pos = _read_varint(data, pos)
    elif wire_type == 1:
        if pos + 8 > len(data):
            raise ValueError("truncated fixed64 field")
        value = int.from_bytes(data[pos : pos + 8], "little")
        pos += 8
    elif wire_type == 2:
        length, pos = _read_varint(data, pos)
        value = data[pos : pos + length]
        if len(value) != length:
            raise ValueError("truncated length-delimited field")
        pos += length
    elif wire_type == 5:
        if pos + 4 > len(data):
            raise ValueError("truncated fixed32 field")
        value = int.from_bytes(data[pos : pos + 4], "little")
        pos += 4
    else:
        raise ValueError(f"unsupported wire type {wire_type}")
    return field_number, wire_type, value, pos


# --- CrdtMessageContent (proto:5-13) ---


def encode_content(
    table: str, row: str, column: str, value: CrdtValue, *, extensions: bool = True
) -> bytes:
    out = _string(1, table) + _string(2, row) + _string(3, column)
    if value is None:
        pass  # oneofKind undefined → no value field (sync.worker.ts:40-48)
    elif isinstance(value, str):
        out += _string(4, value)
    elif isinstance(value, bool):  # bools are stored cast to 0/1 upstream
        out += _tag(5, 0) + _varint(int(value))
    elif isinstance(value, int) and _INT32_MIN <= value <= _INT32_MAX:
        out += _tag(5, 0) + _varint(value)
    elif isinstance(value, int):
        if not -(2**63) <= value < 2**63:
            raise TypeError(f"integer exceeds int64: {value!r}")
        if not extensions:
            raise TypeError(
                f"integer exceeds the reference's int32 value schema: {value!r} "
                "(strict interop mode — a reference peer would silently drop "
                "field 7; set Config.wire_extensions=True to allow it)"
            )
        out += _tag(7, 0) + _varint(value)  # int64 extension — exact
    elif isinstance(value, float):
        if not extensions:
            raise TypeError(
                f"float is outside the reference's string|int32 value schema: "
                f"{value!r} (strict interop mode — a reference peer would "
                "silently drop field 6; set Config.wire_extensions=True, or "
                "store it as a string)"
            )
        out += _tag(6, 1) + struct.pack("<d", value)
    else:
        raise TypeError(f"unencodable CrdtValue: {value!r}")
    return out


def assert_wire_encodable(value: CrdtValue, extensions: bool = True) -> None:
    """Mutation-time wire gate, applied BEFORE a value enters the local
    log — enforcing at transport-encode time would be too late: the
    value would already be committed and every later anti-entropy
    resend batch containing it would fail to encode, wedging sync for
    the owner permanently. With extensions, anything `encode_content`
    can express passes (str|int64|double|bool|None — e.g. bytes never
    can, SQLite accepts them happily); strict mode
    (Config.wire_extensions=False) narrows to the reference's
    string|int32 oneof.

    Implemented BY the encoder (a throwaway encode of the value alone)
    so gate and encoder can never drift apart — drift would recreate
    the wedge: a value the gate passed but the encoder later rejects."""
    if isinstance(value, str):
        return  # skip encoding arbitrarily large strings just to gate
    encode_content("", "", "", value, extensions=extensions)


@_wire_decoder
def decode_content(data: bytes) -> Tuple[str, str, str, CrdtValue]:
    table = row = column = ""
    value: CrdtValue = None
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 1:
            table = v.decode("utf-8")
        elif num == 2:
            row = v.decode("utf-8")
        elif num == 3:
            column = v.decode("utf-8")
        elif num == 4:
            value = v.decode("utf-8")
        elif num == 5:
            # int32: sign-extended 64-bit varint on the wire; truncate
            # to int32 like every conformant decoder.
            value = ((v & 0xFFFFFFFF) ^ 0x80000000) - 0x80000000
        elif num == 6:
            value = struct.unpack("<d", int(v).to_bytes(8, "little"))[0]
        elif num == 7:
            value = v - (1 << 64) if v >= 1 << 63 else v  # int64 extension
    return table, row, column, value


# --- EncryptedCrdtMessage (proto:15-18) ---


@dataclass(frozen=True)
class EncryptedCrdtMessage:
    timestamp: str  # stays plaintext — the relay orders/diffs by it
    content: bytes  # OpenPGP ciphertext of encode_content


def encode_encrypted_message(m: EncryptedCrdtMessage) -> bytes:
    return _string(1, m.timestamp) + _len_delimited(2, m.content)


@_wire_decoder
def decode_encrypted_message(data: bytes) -> EncryptedCrdtMessage:
    timestamp, content = "", b""
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 1:
            timestamp = v.decode("utf-8")
        elif num == 2:
            if wt != 2:
                # A varint here would make bytes(v) ALLOCATE v zero
                # bytes — a remote memory-DoS; only length-delimited
                # content is valid (fuzz finding).
                raise ValueError(f"content field has wire type {wt}")
            content = bytes(v)
    return EncryptedCrdtMessage(timestamp, content)


# --- SyncRequest (proto:20-25) / SyncResponse (proto:27-30) ---
#
# Capability extension (ISSUE 7 — CRDT column types): SyncRequest
# field 5 / SyncResponse field 3 carry repeated capability-name
# strings. Negotiation is advisory, not a format fork: typed CRDT ops
# ride the existing E2EE-opaque message stream (a relay never
# interprets values), so a peer that doesn't speak the capability
# still relays typed traffic byte-identically. The fields are emitted
# ONLY when non-empty, so the capability-less wire stays byte-for-byte
# the v1 wire (protoc-fixture-pinned); an unknown-capability peer's
# decoder skips the field (proto3 unknown-field rule — the fused C
# parsers already do, native/evolu_crypto.cpp:510). A relay answers
# with the INTERSECTION of the request's capabilities and its own, so
# a client can tell whether its fleet understands typed snapshots and
# surface it (sync/client.py records the negotiated set per relay).

CAP_CRDT_TYPES = "crdt-types-v1"
# RGA sequence CRDT (ISSUE 14, core/crdt_list.py): advisory like
# crdt-types-v1 — list ops are ordinary E2EE-opaque messages, so a
# non-advertising peer relays them byte-identically; the capability
# only surfaces fleet support (e.g. to gate enabling `"col:list"`
# columns for an owner shared with reference TS peers).
CAP_CRDT_LIST = "crdt-list-v1"
# Batched-AEAD v2 sync payload (ISSUE 8, sync/aead.py): a NEGOTIATED
# pair replaces per-message OpenPGP S2K with session-keyed AES-256-GCM
# records. Unlike crdt-types-v1 this capability GATES emission: a
# client only sends v2 records to a relay whose LAST response echoed
# it back (sync/client.py), and any failover to a relay that didn't
# advertise re-encodes the round as v1. Decoding is unconditional —
# records self-describe via a magic prefix — so negotiation only
# controls what gets written, never what can be read.
CAP_AEAD_BATCH = "aead-batch-v1"
# Partial replication (ISSUE 18, sync/scope.py + server/scope.py): a
# NEGOTIATED scope clause on SyncRequest (field 6) asks the relay to
# serve only the slice matching a timestamp watermark and/or a set of
# opaque lane tags, answered from a derived scoped Merkle subtree.
# Like aead-batch-v1 this capability GATES emission: a client only
# attaches the clause to a relay whose LAST response echoed it back,
# and failover to a non-advertising relay re-encodes without it
# (sync/client.py retarget). Decoding is unconditional; a relay that
# does not SERVE the capability ignores the clause (full serve — the
# over-approximation-only stance: serving more is always sound).
CAP_SYNC_SCOPE = "sync-scope-v1"
# Tensor-valued CRDT columns (ISSUE 20, core/crdt_tensor.py): advisory
# like crdt-types-v1 — tensor ops are ordinary E2EE-opaque messages,
# so a non-advertising peer relays them byte-identically; the
# capability only surfaces fleet support (e.g. to gate enabling
# `"col:tensor:…"` columns for an owner shared with reference TS
# peers, whose apply would LWW the op strings).
CAP_CRDT_TENSOR = "crdt-tensor-v1"
KNOWN_CAPABILITIES = (CAP_CRDT_TYPES, CAP_CRDT_LIST, CAP_CRDT_TENSOR,
                      CAP_AEAD_BATCH, CAP_SYNC_SCOPE)
_MAX_CAPABILITIES = 64  # decode bound: a hostile body must not mint unbounded strings
# Scope-clause decode bounds (satellite: lane-cardinality hardening).
# A hostile client must not mint unbounded per-scope state on the
# relay: requested tags are hard-capped at decode time; PUSH tag
# assignments are capped by the message count they annotate (validated
# after the field walk). Server-side per-owner lane tracking has its
# own cap with a conservative overflow lane (server/scope.py).
_MAX_SCOPE_TAGS = 16
_MAX_SCOPE_TAG_LEN = 128


@dataclass(frozen=True)
class ScopeClause:
    """The wire form of a sync scope (SyncRequest field 6).

    `watermark_millis`: HLC-millis lower bound — the relay serves only
    rows at or after this minute frontier (timestamps are plaintext, so
    this needs zero wire trust). 0 = no watermark.
    `tags`: opaque lane tags (client-side HMACs of table/document names
    under the owner key — sync/scope.py) whose lanes the client wants;
    the relay partitions rows into lanes without learning what a tag
    names, and rows in no known lane are served conservatively.
    `push_tags`: lane assignment for THIS request's pushed messages,
    parallel to `messages` ("" = untagged). Empty = no assignment.
    """

    watermark_millis: int = 0
    tags: Tuple[str, ...] = ()
    push_tags: Tuple[str, ...] = ()


@dataclass(frozen=True)
class SyncRequest:
    messages: Tuple[EncryptedCrdtMessage, ...]
    user_id: str
    node_id: str
    merkle_tree: str
    capabilities: Tuple[str, ...] = ()
    # Optional partial-replication scope (sync-scope-v1). None on every
    # v1 request — the encoder emits field 6 only when present, so
    # capability-less traffic stays byte-identical.
    scope: Optional["ScopeClause"] = None


@dataclass(frozen=True)
class SyncResponse:
    messages: Tuple[EncryptedCrdtMessage, ...]
    merkle_tree: str
    capabilities: Tuple[str, ...] = ()


def encode_request_capabilities(capabilities: Tuple[str, ...]) -> bytes:
    """SyncRequest field-5 bytes — appendable to an already-encoded
    request body (proto3 field order is free), which is how the fused C
    wire path gains the extension without touching the C encoder."""
    return b"".join(_string(5, c) for c in capabilities)


def encode_response_capabilities(capabilities: Tuple[str, ...]) -> bytes:
    """SyncResponse field-3 bytes — appended by the relay AFTER the
    serve path produced the response (fused C or object path alike)."""
    return b"".join(_string(3, c) for c in capabilities)


def _decode_capability(v, caps: List[str]) -> None:
    if len(caps) >= _MAX_CAPABILITIES:
        raise ValueError("too many capability entries")
    caps.append(v.decode("utf-8"))


def encode_scope_clause(s: "ScopeClause") -> bytes:
    """The nested scope message: watermarkMillis=1 (varint), tags=2
    (repeated string), pushTags=3 (repeated string)."""
    out = b""
    if s.watermark_millis:
        out += _tag(1, 0) + _varint(s.watermark_millis)
    out += b"".join(_string(2, t) for t in s.tags)
    out += b"".join(_string(3, t) for t in s.push_tags)
    return out


def encode_request_scope(s: Optional["ScopeClause"]) -> bytes:
    """SyncRequest field-6 bytes — appendable to an already-encoded
    request body exactly like `encode_request_capabilities`, which is
    how the fused C wire path gains the clause without touching the C
    encoder. b"" when no scope: unscoped requests stay byte-identical."""
    if s is None:
        return b""
    return _len_delimited(6, encode_scope_clause(s))


def _decode_scope_tag(v, wt: int, tags: List[str], what: str) -> None:
    if wt != 2:
        raise ValueError(f"scope {what} field has wire type {wt}")
    if len(tags) >= _MAX_SCOPE_TAGS:
        raise ValueError(f"too many scope {what} entries")
    if len(v) > _MAX_SCOPE_TAG_LEN:
        raise ValueError(f"scope {what} too long ({len(v)} bytes)")
    tags.append(v.decode("utf-8"))


@_wire_decoder
def decode_scope_clause(data: bytes) -> ScopeClause:
    watermark = 0
    tags: List[str] = []
    push_tags: List[str] = []
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 1:
            watermark = int(v)
            # Varints are unsigned on the wire: a two's-complement
            # negative int64 arrives as a value in [2^63, 2^64).
            if watermark >= 1 << 63:
                raise ValueError("scope watermark must be non-negative")
        elif num == 2:
            _decode_scope_tag(v, wt, tags, "tag")
        elif num == 3:
            # push_tags may legitimately exceed _MAX_SCOPE_TAGS entries
            # (one per pushed message, "" for untagged) but each entry
            # is still length-bounded; the entry-count bound is the
            # message count, validated by decode_sync_request after the
            # walk.
            if wt != 2:
                raise ValueError(f"scope push tag field has wire type {wt}")
            if len(v) > _MAX_SCOPE_TAG_LEN:
                raise ValueError(f"scope push tag too long ({len(v)} bytes)")
            push_tags.append(v.decode("utf-8"))
    return ScopeClause(watermark, tuple(tags), tuple(push_tags))


def encode_sync_request(r: SyncRequest) -> bytes:
    out = b"".join(_len_delimited(1, encode_encrypted_message(m)) for m in r.messages)
    out += _string(2, r.user_id) + _string(3, r.node_id) + _string(4, r.merkle_tree)
    return out + encode_request_capabilities(r.capabilities) \
        + encode_request_scope(r.scope)


@_wire_decoder
def decode_sync_request(data: bytes) -> SyncRequest:
    messages: List[EncryptedCrdtMessage] = []
    user_id = node_id = merkle_tree = ""
    capabilities: List[str] = []
    scope: Optional[ScopeClause] = None
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 1:
            messages.append(decode_encrypted_message(v))
        elif num == 2:
            user_id = v.decode("utf-8")
        elif num == 3:
            node_id = v.decode("utf-8")
        elif num == 4:
            merkle_tree = v.decode("utf-8")
        elif num == 5:
            _decode_capability(v, capabilities)
        elif num == 6:
            if wt != 2:
                raise ValueError(f"scope clause field has wire type {wt}")
            scope = decode_scope_clause(v)
    if scope is not None and scope.push_tags and \
            len(scope.push_tags) != len(messages):
        raise ValueError(
            f"scope push tags ({len(scope.push_tags)}) do not match the "
            f"message count ({len(messages)})"
        )
    return SyncRequest(tuple(messages), user_id, node_id, merkle_tree,
                       tuple(capabilities), scope)


def encode_sync_response(r: SyncResponse) -> bytes:
    out = b"".join(_len_delimited(1, encode_encrypted_message(m)) for m in r.messages)
    return out + _string(2, r.merkle_tree) + encode_response_capabilities(r.capabilities)


@_wire_decoder
def scan_sync_response_capabilities(data: bytes) -> Tuple[str, ...]:
    """Top-level walk collecting ONLY field-3 capability strings — the
    client calls this on the raw response bytes before the fused C
    decrypt paths (which skip the field), so negotiation works
    identically on every receive route. ValueError-only like every
    decoder here."""
    caps: List[str] = []
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 3:
            _decode_capability(v, caps)
    return tuple(caps)


# --- relay↔relay replication messages (extension — no reference
# equivalent; the reference relay is a single node). Same hand-rolled
# proto3 subset, same decoder error contract (ValueError only), and the
# same E2EE-blindness: nothing here ever carries plaintext — owners are
# ids, trees are JSON digests of timestamps, messages stay
# (timestamp, ciphertext). See evolu_tpu/server/replicate.py. ---
#
#     OwnerTree           { userId=1 merkleTree=2 }
#     ReplicaSummary      { owners=1 (repeated OwnerTree) replicaId=2 }
#     OwnerPull           { userId=1 since=2 }
#     ReplicaPull         { pulls=1 (repeated OwnerPull) replicaId=2 }
#     OwnerMessages       { userId=1 messages=2 (repeated
#                           EncryptedCrdtMessage) merkleTree=3 }
#     ReplicaPullResponse { chunks=1 (repeated OwnerMessages) }


@dataclass(frozen=True)
class ReplicaSummary:
    """One side of a gossip exchange: every owner this relay stores,
    with its serialized Merkle tree. Sent as the `/replicate/summary`
    request body (the caller's summary) AND returned as its response
    (the callee's) — divergence is computable from either side.

    `peer_url` (field 3, fleet extension): the CALLER's advertised base
    URL. A fleet relay (server/fleet.py) scopes its response to owners
    placed on that URL, dropping gossip traffic from O(fleet) to O(R).
    Empty (the pre-fleet wire and non-fleet relays) means "answer
    everything" — old and new peers interoperate unchanged. Like
    `replica_id` it is untrusted input: it selects a SUBSET of the
    response and is never minted into metric labels."""

    trees: Tuple[Tuple[str, str], ...]  # (owner id, merkle tree string)
    replica_id: str
    peer_url: str = ""


@dataclass(frozen=True)
class ReplicaPull:
    """Ranged fetch: per owner, every message strictly after `since`
    (a 46-char sync timestamp at the diverged minute). No node
    exclusion — a relay is not a message author; it needs all rows."""

    pulls: Tuple[Tuple[str, str], ...]  # (owner id, since timestamp string)
    replica_id: str


@dataclass(frozen=True)
class OwnerMessages:
    user_id: str
    messages: Tuple[EncryptedCrdtMessage, ...]
    merkle_tree: str  # the serving relay's tree at fetch time


@dataclass(frozen=True)
class ReplicaPullResponse:
    chunks: Tuple[OwnerMessages, ...]


def encode_replica_summary(s: ReplicaSummary) -> bytes:
    out = b"".join(
        _len_delimited(1, _string(1, uid) + _string(2, tree)) for uid, tree in s.trees
    )
    out += _string(2, s.replica_id)
    if s.peer_url:
        out += _string(3, s.peer_url)
    return out


@_wire_decoder
def _decode_owner_tree(data: bytes) -> Tuple[str, str]:
    uid = tree = ""
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 1:
            uid = v.decode("utf-8")
        elif num == 2:
            tree = v.decode("utf-8")
    return uid, tree


@_wire_decoder
def decode_replica_summary(data: bytes) -> ReplicaSummary:
    trees: List[Tuple[str, str]] = []
    replica_id = peer_url = ""
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 1:
            if wt != 2:
                raise ValueError(f"owner tree field has wire type {wt}")
            trees.append(_decode_owner_tree(v))
        elif num == 2:
            replica_id = v.decode("utf-8")
        elif num == 3:
            peer_url = v.decode("utf-8")
    return ReplicaSummary(tuple(trees), replica_id, peer_url)


def encode_replica_pull(p: ReplicaPull) -> bytes:
    out = b"".join(
        _len_delimited(1, _string(1, uid) + _string(2, since)) for uid, since in p.pulls
    )
    return out + _string(2, p.replica_id)


@_wire_decoder
def decode_replica_pull(data: bytes) -> ReplicaPull:
    pulls: List[Tuple[str, str]] = []
    replica_id = ""
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 1:
            if wt != 2:
                raise ValueError(f"owner pull field has wire type {wt}")
            pulls.append(_decode_owner_tree(v))  # same (string=1, string=2) shape
        elif num == 2:
            replica_id = v.decode("utf-8")
    return ReplicaPull(tuple(pulls), replica_id)


def encode_owner_messages(om: OwnerMessages) -> bytes:
    out = _string(1, om.user_id)
    out += b"".join(_len_delimited(2, encode_encrypted_message(m)) for m in om.messages)
    return out + _string(3, om.merkle_tree)


@_wire_decoder
def decode_owner_messages(data: bytes) -> OwnerMessages:
    uid = tree = ""
    messages: List[EncryptedCrdtMessage] = []
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 1:
            uid = v.decode("utf-8")
        elif num == 2:
            if wt != 2:
                raise ValueError(f"messages field has wire type {wt}")
            messages.append(decode_encrypted_message(v))
        elif num == 3:
            tree = v.decode("utf-8")
    return OwnerMessages(uid, tuple(messages), tree)


def encode_replica_pull_response(r: ReplicaPullResponse) -> bytes:
    return b"".join(_len_delimited(1, encode_owner_messages(c)) for c in r.chunks)


@_wire_decoder
def decode_replica_pull_response(data: bytes) -> ReplicaPullResponse:
    chunks: List[OwnerMessages] = []
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 1:
            if wt != 2:
                raise ValueError(f"owner messages field has wire type {wt}")
            chunks.append(decode_owner_messages(v))
    return ReplicaPullResponse(tuple(chunks))


# --- snapshot checkpoint & peer bootstrap messages (extension — no
# reference equivalent; see evolu_tpu/server/snapshot.py). Same
# hand-rolled proto3 subset, same ValueError-only decoder contract,
# same E2EE-blindness (the framed row stream carries exactly what the
# relay already stores: plaintext timestamps + ciphertext blobs). ---
#
#     SnapshotRequest      { replicaId=1 chunkBytes=2 owners=3 (repeated) }
#     SnapshotOwner        { userId=1 rootHash=2 treeCrc=3 }
#     SnapshotManifest     { snapshotId=1 chunkSizes=2 (repeated)
#                            chunkCrcs=3 (repeated)
#                            owners=4 (repeated SnapshotOwner)
#                            messageCount=5 totalBytes=6 }
#     SnapshotChunkRequest { snapshotId=1 index=2 replicaId=3 }
#     SnapshotChunk        { snapshotId=1 index=2 crc=3 payload=4 }


@dataclass(frozen=True)
class SnapshotRequest:
    """Asks a donor relay for a consistent snapshot manifest.
    `chunk_bytes` is the puller's preferred chunk size (0 = donor
    default; the donor clamps it under its body cap either way).
    `owners` (field 3, fleet extension): non-empty scopes the capture
    to exactly those owners — the O(moved-owners) transfer the fleet
    rebalance needs instead of a full-store ship. Empty = everything
    (the whole-store bootstrap, and what pre-fleet donors — whose
    decoders skip the unknown field — always serve; pullers keep a
    client-side record filter for exactly that downgrade).

    `watermark_millis` (field 4) + `tags` (field 5, partial-replication
    extension, ISSUE 18): a non-zero watermark / non-empty tag set
    scopes the capture to the matching slice — rows at or after the
    watermark minute whose lane is requested or unknown — and the
    manifest trees are recomputed from the SHIPPED rows, so the
    installer's byte-identity verify holds for the slice. A scoped
    snapshot bootstraps a thin client, never a full replica
    (docs/PARTIAL_SYNC.md). Pre-scope donors skip the unknown fields
    and ship everything: serving more is always sound."""

    replica_id: str
    chunk_bytes: int = 0
    owners: Tuple[str, ...] = ()
    watermark_millis: int = 0
    tags: Tuple[str, ...] = ()


@dataclass(frozen=True)
class SnapshotManifest:
    """The snapshot contract: chunk sizes + crc32s for resumable ranged
    fetches, and per-owner watermarks — the Merkle ROOT hash (JS signed
    int32) plus a crc32 of the owner's serialized tree text at capture
    time. After install the puller recomputes every owner's tree from
    the shipped rows and verifies byte-identity against the shipped
    tree text AND these digests; gossip then resumes from exactly this
    watermark (trees equal ⇒ the first summary exchange diffs only
    post-snapshot writes)."""

    snapshot_id: str
    chunk_sizes: Tuple[int, ...]
    chunk_crcs: Tuple[int, ...]
    owners: Tuple[Tuple[str, int, int], ...]  # (owner, root_hash, tree_crc)
    message_count: int
    total_bytes: int


@dataclass(frozen=True)
class SnapshotChunkRequest:
    snapshot_id: str
    index: int
    replica_id: str = ""


@dataclass(frozen=True)
class SnapshotChunk:
    snapshot_id: str
    index: int
    crc: int  # crc32 of payload — checked against the manifest too
    payload: bytes


def encode_snapshot_request(r: SnapshotRequest) -> bytes:
    out = _string(1, r.replica_id)
    if r.chunk_bytes:
        out += _tag(2, 0) + _varint(r.chunk_bytes)
    for uid in r.owners:
        out += _string(3, uid)
    if r.watermark_millis:
        out += _tag(4, 0) + _varint(r.watermark_millis)
    for t in r.tags:
        out += _string(5, t)
    return out


@_wire_decoder
def decode_snapshot_request(data: bytes) -> SnapshotRequest:
    replica_id, chunk_bytes, watermark = "", 0, 0
    owners: List[str] = []
    tags: List[str] = []
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 1:
            replica_id = v.decode("utf-8")
        elif num == 2:
            chunk_bytes = int(v)
        elif num == 3:
            if wt != 2:
                raise ValueError(f"owners field has wire type {wt}")
            owners.append(v.decode("utf-8"))
        elif num == 4:
            watermark = int(v)
            if watermark < 0:
                raise ValueError("snapshot watermark must be non-negative")
        elif num == 5:
            _decode_scope_tag(v, wt, tags, "tag")
    return SnapshotRequest(replica_id, chunk_bytes, tuple(owners),
                           watermark, tuple(tags))


def encode_snapshot_manifest(m: SnapshotManifest) -> bytes:
    out = _string(1, m.snapshot_id)
    out += b"".join(_tag(2, 0) + _varint(s) for s in m.chunk_sizes)
    out += b"".join(_tag(3, 0) + _varint(c) for c in m.chunk_crcs)
    for uid, root_hash, tree_crc in m.owners:
        inner = _string(1, uid) + _tag(2, 0) + _varint(root_hash)
        inner += _tag(3, 0) + _varint(tree_crc)
        out += _len_delimited(4, inner)
    out += _tag(5, 0) + _varint(m.message_count)
    out += _tag(6, 0) + _varint(m.total_bytes)
    return out


@_wire_decoder
def _decode_snapshot_owner(data: bytes) -> Tuple[str, int, int]:
    uid, root_hash, tree_crc = "", 0, 0
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 1:
            uid = v.decode("utf-8")
        elif num == 2:
            # Merkle root hashes are JS signed int32 (core/merkle.py);
            # negatives ride as 10-byte two's-complement varints like
            # the int32 value field — truncate identically on decode.
            root_hash = ((int(v) & 0xFFFFFFFF) ^ 0x80000000) - 0x80000000
        elif num == 3:
            tree_crc = int(v) & 0xFFFFFFFF
    return uid, root_hash, tree_crc


@_wire_decoder
def decode_snapshot_manifest(data: bytes) -> SnapshotManifest:
    snapshot_id = ""
    chunk_sizes: List[int] = []
    chunk_crcs: List[int] = []
    owners: List[Tuple[str, int, int]] = []
    message_count = total_bytes = 0
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 1:
            snapshot_id = v.decode("utf-8")
        elif num == 2:
            chunk_sizes.append(int(v))
        elif num == 3:
            chunk_crcs.append(int(v) & 0xFFFFFFFF)
        elif num == 4:
            if wt != 2:
                raise ValueError(f"snapshot owner field has wire type {wt}")
            owners.append(_decode_snapshot_owner(v))
        elif num == 5:
            message_count = int(v)
        elif num == 6:
            total_bytes = int(v)
    if len(chunk_sizes) != len(chunk_crcs):
        raise ValueError(
            f"snapshot manifest chunk sizes ({len(chunk_sizes)}) and crcs "
            f"({len(chunk_crcs)}) disagree"
        )
    return SnapshotManifest(
        snapshot_id, tuple(chunk_sizes), tuple(chunk_crcs), tuple(owners),
        message_count, total_bytes,
    )


def encode_snapshot_chunk_request(r: SnapshotChunkRequest) -> bytes:
    return (
        _string(1, r.snapshot_id)
        + _tag(2, 0) + _varint(r.index)
        + _string(3, r.replica_id)
    )


@_wire_decoder
def decode_snapshot_chunk_request(data: bytes) -> SnapshotChunkRequest:
    snapshot_id = replica_id = ""
    index = 0
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 1:
            snapshot_id = v.decode("utf-8")
        elif num == 2:
            index = int(v)
        elif num == 3:
            replica_id = v.decode("utf-8")
    return SnapshotChunkRequest(snapshot_id, index, replica_id)


def encode_snapshot_chunk(c: SnapshotChunk) -> bytes:
    return (
        _string(1, c.snapshot_id)
        + _tag(2, 0) + _varint(c.index)
        + _tag(3, 0) + _varint(c.crc)
        + _len_delimited(4, c.payload)
    )


@_wire_decoder
def decode_snapshot_chunk(data: bytes) -> SnapshotChunk:
    snapshot_id = ""
    index = crc = 0
    payload = b""
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 1:
            snapshot_id = v.decode("utf-8")
        elif num == 2:
            index = int(v)
        elif num == 3:
            crc = int(v) & 0xFFFFFFFF
        elif num == 4:
            if wt != 2:
                # A varint here would make bytes(v) ALLOCATE v zero
                # bytes — same remote memory-DoS shape as the content
                # field of EncryptedCrdtMessage.
                raise ValueError(f"payload field has wire type {wt}")
            payload = bytes(v)
    return SnapshotChunk(snapshot_id, index, crc, payload)


# --- fleet routing envelope (extension — no reference equivalent; see
# evolu_tpu/server/fleet.py). A relay in forward mode wraps a sync POST
# body it is not placed for and relays it to the authoritative peer's
# `POST /fleet/forward`; the response is the raw sync response bytes,
# relayed back verbatim. `hops` is the loop guard, enforced at both
# ends: forwarders send hops=1, the serving handler 400-rejects any
# other value AND never forwards again (ring disagreement during a
# config reload must degrade to local service + gossip heal, not a
# forward cycle).
# Same ValueError-only decoder contract; the payload stays E2EE-blind
# (it IS the client's encrypted SyncRequest, untouched). ---
#
#     FleetForward { payload=1 origin=2 hops=3 }


@dataclass(frozen=True)
class FleetForward:
    payload: bytes  # the original encoded SyncRequest body, verbatim
    origin: str  # forwarding relay's base URL (observability only)
    hops: int = 1


def encode_fleet_forward(f: FleetForward) -> bytes:
    return (
        _len_delimited(1, f.payload)
        + _string(2, f.origin)
        + _tag(3, 0) + _varint(f.hops)
    )


@_wire_decoder
def decode_fleet_forward(data: bytes) -> FleetForward:
    payload = b""
    origin = ""
    hops = 0
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 1:
            if wt != 2:
                # A varint here would make bytes(v) ALLOCATE v zero
                # bytes — same remote memory-DoS shape as the content
                # field of EncryptedCrdtMessage.
                raise ValueError(f"payload field has wire type {wt}")
            payload = bytes(v)
        elif num == 2:
            origin = v.decode("utf-8")
        elif num == 3:
            hops = int(v)
    return FleetForward(payload, origin, hops)


@_wire_decoder
def decode_sync_response(data: bytes) -> SyncResponse:
    messages: List[EncryptedCrdtMessage] = []
    merkle_tree = ""
    capabilities: List[str] = []
    pos = 0
    while pos < len(data):
        num, wt, v, pos = _read_field(data, pos)
        if num == 1:
            messages.append(decode_encrypted_message(v))
        elif num == 2:
            merkle_tree = v.decode("utf-8")
        elif num == 3:
            _decode_capability(v, capabilities)
    return SyncResponse(tuple(messages), merkle_tree, tuple(capabilities))
