"""Partial replication: the client-side sync-scope model (ISSUE 18).

A `SyncScope` declares the slice of an owner's log a thin client wants
to converge on, along two E2EE-compatible axes the relay can evaluate
blind:

- **timestamp watermark** (`watermark_millis`): HLC-millis lower bound
  — "recent history only". Timestamps are already plaintext on the
  wire, so this leaks nothing new and needs zero wire trust.
- **scope tags** (`tables` → HMAC lanes): the client names plaintext
  tables/documents; on the wire each becomes an opaque HMAC of the
  name under a key derived from the owner mnemonic, so the relay can
  partition rows into lanes without learning what any lane names.

Convergence stance (Merkle-CRDTs, arXiv:2004.00107): a scoped client
converges byte-identically WITHIN its slice because the relay answers
from a scoped Merkle subtree derived from the same filter; everything
outside the filter is provably deferred, never silently dropped —
rows the relay cannot attribute to a lane are served conservatively
(over-approximation only, the PR-13 push-granularity stance), and the
client records the remainder as a counted deferred frontier
(runtime/worker.py).

Escalation: `widen()` relaxes the scope (lower watermark and/or more
tables); the next ordinary anti-entropy round catches up incrementally
— no special protocol. NARROWING an established scope is unsupported:
a client whose local tree already holds out-of-scope rows would
permanently diverge from the scoped server subtree (the livelock guard
would surface it as a SyncError). See docs/PARTIAL_SYNC.md.
"""

from __future__ import annotations

import hashlib
import hmac
from dataclasses import dataclass
from typing import Optional, Tuple

from evolu_tpu.sync import protocol

# Tag length on the wire: 16 hex chars (64 bits) — collision-safe for
# per-owner table counts while staying far under the protocol's
# per-tag byte bound.
SCOPE_TAG_HEX_LEN = 16
_SCOPE_KEY_INFO = b"evolu-scope-v1"


def derive_scope_tag(mnemonic: str, name: str) -> str:
    """The opaque lane tag for a table/document name: HMAC-SHA256 of
    the name under a scope key derived from the owner mnemonic,
    truncated to 16 hex chars. Deterministic per (owner, name) so every
    device of an owner lands rows in the same lane; meaningless to the
    relay (E2EE-blind lane partitioning)."""
    scope_key = hmac.new(
        mnemonic.encode("utf-8"), _SCOPE_KEY_INFO, hashlib.sha256
    ).digest()
    digest = hmac.new(scope_key, name.encode("utf-8"), hashlib.sha256)
    return digest.hexdigest()[:SCOPE_TAG_HEX_LEN]


@dataclass(frozen=True)
class SyncScope:
    """A client's declared slice. `watermark_millis` = 0 means no time
    bound; empty `tables` means no table filter (every table in scope).
    Both empty would be a no-op scope — treat as unscoped."""

    watermark_millis: int = 0
    tables: Tuple[str, ...] = ()

    def __post_init__(self):
        if self.watermark_millis < 0:
            raise ValueError("scope watermark must be non-negative")
        if len(self.tables) > protocol._MAX_SCOPE_TAGS:
            raise ValueError(
                f"scope declares {len(self.tables)} tables; the wire caps "
                f"requested lanes at {protocol._MAX_SCOPE_TAGS}"
            )

    @property
    def is_noop(self) -> bool:
        return not self.watermark_millis and not self.tables

    def table_in_scope(self, table: str) -> bool:
        """Client-side materialization filter: with no table filter
        everything materializes; system tables (``__``-prefixed) are
        always in scope — the log/clock substrate must stay whole."""
        if not self.tables or table.startswith("__"):
            return True
        return table in self.tables

    def widen(self, watermark_millis: Optional[int] = None,
              tables: Tuple[str, ...] = ()) -> "SyncScope":
        """Escalation: a strictly-wider scope (lower/equal watermark,
        superset tables). Raises on any attempt to narrow — narrowing
        an established scope breaks slice convergence (module doc)."""
        new_wm = self.watermark_millis if watermark_millis is None \
            else watermark_millis
        if new_wm > self.watermark_millis:
            raise ValueError("widen() cannot raise the watermark")
        if self.tables:
            new_tables = self.tables + tuple(
                t for t in tables if t not in self.tables
            )
        else:
            # No table filter = all tables already in scope; adding
            # names would NARROW it.
            if tables:
                raise ValueError(
                    "widen() cannot add a table filter to an unfiltered scope"
                )
            new_tables = ()
        return SyncScope(new_wm, new_tables)

    def wire_clause(self, mnemonic: str,
                    push_tables: Tuple[str, ...] = ()
                    ) -> Optional[protocol.ScopeClause]:
        """The capability-gated wire form: requested lane tags derived
        from `tables`, plus a lane assignment for this round's pushed
        messages (`push_tables`, one plaintext table name per pushed
        message — tagged even when the table is outside this scope, so
        the relay's lanes stay truthful for OTHER scoped clients).
        None for a no-op scope (unscoped wire, byte-identical)."""
        if self.is_noop:
            return None
        tags = tuple(derive_scope_tag(mnemonic, t) for t in self.tables)
        push_tags: Tuple[str, ...] = ()
        if push_tables and tags:
            push_tags = tuple(
                derive_scope_tag(mnemonic, t) for t in push_tables
            )
        return protocol.ScopeClause(self.watermark_millis, tags, push_tags)


class ScopeDeferred(Exception):
    """Typed "this answer would lie" marker: a Query touched a table
    whose rows are (partly) outside the local scope — the store holds a
    counted deferred frontier for it, so honest behavior is to surface
    the deferral, never to answer silently-empty rows. Carries what the
    caller needs to decide between widening the scope (escalation) and
    rendering a placeholder."""

    def __init__(self, tables: Tuple[str, ...], deferred_rows: int):
        super().__init__(
            f"query touches out-of-scope table(s) {', '.join(tables)}: "
            f"{deferred_rows} row(s) deferred by the sync scope"
        )
        self.tables = tables
        self.deferred_rows = deferred_rows
