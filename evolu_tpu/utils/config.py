"""Runtime configuration (reference: packages/evolu/src/config.ts).

Unlike the reference's mutable module singleton, config is passed
explicitly to the runtime (`create_evolu(schema, config=Config(...))`);
a module-level default exists for parity with `setConfig`.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple, Union


@dataclass
class Config:
    sync_url: str = "http://localhost:4000"
    log: Union[bool, str, List[str]] = False
    max_drift: int = 60000  # config.ts:9
    reload_url: str = "/"
    # TPU-native extensions (no reference equivalent):
    # Periodic pull interval in seconds (None = only explicit sync()).
    # The reference syncs on load/online/focus browser events
    # (db.ts:390-412); a headless process needs a timer instead.
    sync_interval: "float | None" = None
    backend: str = "auto"  # "cpu" | "tpu" | "auto" — merge kernel backend
    # Receive batches above this size apply blockwise (bounded device
    # and transaction memory; the Merkle tree and clock persist per
    # chunk, so a mid-sync crash resumes instead of replaying).
    # None = whole-batch transactions always (reference semantics).
    receive_chunk_size: "int | None" = 1 << 20
    min_device_batch: int = 1024  # below this, the CPU oracle path is faster than dispatch
    # A single-owner batch at/above this size shards by CELL RANGES over
    # every local device (parallel/hot_owner.py) instead of planning on
    # one device — the "hot owner" path (SURVEY.md §5). Only engages
    # when >1 device is visible. None disables.
    hot_owner_min_batch: "int | None" = 1 << 18
    # LWW plan formulation (ops/scatter_merge.py): "sort" = the r5
    # sort+scan pipeline, "scatter" = the dense scatter-argmax plan,
    # "auto" = by backend (scatter on CPU where it measured up to ~13×
    # faster at 1M rows; sort on TPU where the recorded cost model
    # prices serialized scatters/gathers far above one sort —
    # docs/BENCHMARKS.md r6). EVOLU_MERGE_PLAN overrides.
    merge_plan: str = "auto"
    # Keep per-cell stored winners HBM-resident across batches
    # (ops/winner_cache.py) instead of streaming them from SQLite per
    # batch — measured +19% (tunneled TPU) / ~+30% (CPU) steady-state
    # end-to-end on the config-2 shape (benchmarks/winner_cache.py).
    # Ignored for backend "cpu".
    winner_cache: bool = True
    # Wire-protocol extension fields 6 (double) / 7 (int64) beyond the
    # reference's string|int32 value oneof (protobuf.proto:5-13).
    # False = strict interop: AUTHORING such a value raises at mutation
    # time (before it enters the log) instead of later producing a
    # field a reference TS peer would silently drop. Remote messages
    # always relay verbatim, and reference-range traffic is
    # byte-identical either way.
    wire_extensions: bool = True
    # Wire capabilities advertised in every sync request (field 5 —
    # sync/protocol.py capability extension, ISSUE 7). The relay echoes
    # the intersection with its own set; () sends the v1 wire
    # byte-identically. `crdt-types-v1` / `crdt-list-v1` /
    # `crdt-tensor-v1` (ISSUEs 7, 14, 20) are advisory (typed CRDT ops
    # are E2EE-opaque and relay through v1 peers unchanged; the echo
    # only SURFACES fleet support). `aead-batch-v1` (ISSUE 8, sync/aead.py)
    # GATES emission: only after a relay echoes it does the client send
    # session-keyed GCM records instead of per-message OpenPGP — the
    # ~10× crypto-ceiling lift (docs/WIRE_V2.md). Every client of this
    # framework DECODES v2 records unconditionally; drop the capability
    # here for owners shared with reference OpenPGP.js peers, which
    # cannot (the same interop dial as wire_extensions).
    # `sync-scope-v1` (ISSUE 18, sync/scope.py) likewise GATES
    # emission: a scope clause (Config.sync_scope) rides the wire only
    # after the relay echoes it — an unscoped or unnegotiated round
    # stays byte-identical to v1.
    sync_capabilities: Tuple[str, ...] = (
        "crdt-types-v1", "crdt-list-v1", "crdt-tensor-v1",
        "aead-batch-v1", "sync-scope-v1")
    # Partial replication (ISSUE 18, sync/scope.py::SyncScope): the
    # slice of the owner's log this client converges on — an HLC-millis
    # watermark ("recent history only") and/or a table filter (opaque
    # HMAC lanes on the wire). None = full replica (everything
    # unchanged). Out-of-scope rows land in the log but skip
    # materialization; queries touching them raise ScopeDeferred
    # (honest partial answers, runtime/worker.py); widen the scope to
    # escalate. Narrowing an established scope is unsupported.
    sync_scope: "object | None" = None
    # -- relay fleet knobs (no reference equivalent). These are LIVE
    # defaults: `RelayServer` / `ReplicationManager` resolve any
    # constructor arg left at None from the process `default_config`
    # (set_config before constructing relays), so embedders can tune a
    # fleet in one place without threading kwargs everywhere. --
    # serve_pull response budgets: at most this many messages per owner
    # and per response in one anti-entropy pull answer. None = the
    # server defaults (8192 / 65536, `replicate.PULL_MESSAGES_PER_*`).
    # Smaller values bound gossip-round latency; the snapshot-bootstrap
    # bench sweeps them honestly (benchmarks/snapshot_bootstrap.py).
    pull_messages_per_owner: "int | None" = None
    pull_messages_per_response: "int | None" = None
    # Snapshot bootstrap trigger (server/snapshot.py): a relay whose
    # store is empty — or lacking at least this many owners a peer
    # advertises — installs a full snapshot instead of crawling history
    # through capped pulls. None disables (incremental-only, the PR-3
    # behavior).
    bootstrap_lag_owners: "int | None" = None
    # Periodic local snapshot checkpoints for crash-consistent fast
    # restart (RelayServer(checkpoint_interval_s=...) →
    # snapshot.CheckpointWriter). None disables.
    checkpoint_interval_s: "float | None" = None
    # Changed-set-gated incremental query invalidation (ISSUE 9,
    # runtime/worker.py::_query × storage/deps.py × storage/changes.py):
    # subscribed queries whose read tables are disjoint from a
    # mutation's changed set skip re-execution entirely, and queries
    # with a static `"id" = ?` constraint skip row-disjoint writes.
    # Patch streams are byte-identical to the re-run-everything path
    # (conservative full invalidation on every "don't know"); False
    # restores the reference's unconditional re-execution.
    query_invalidation: bool = True
    # Bound on the worker's per-query caches (rows/raw bytes/dependency
    # index/seen-epoch): least-recently-executed entries are evicted
    # past this many distinct queries, so churned one-shot query
    # strings cannot grow the worker without bound. An evicted-but-
    # still-subscribed query self-heals on its next run via a
    # root-replace patch (correct against any client state). None =
    # unbounded (the pre-r9 behavior).
    query_cache_max: "int | None" = 32768
    # PR-11 storage inversion (storage/write_behind.py): serve sync
    # responses and Merkle answers from device-derived in-memory state
    # and demote SQLite to a bounded async write-behind materializer
    # drained off the serving path. Opt-in (default OFF — every
    # existing byte-identity pin stays on the synchronous path until
    # the torture bar is green in a deployment); EVOLU_WRITE_BEHIND=1
    # overrides at the relay. Durability floor: fsync'd record log +
    # exact idempotent replay (docs/WRITE_BEHIND.md).
    write_behind: bool = False
    # Admission bound for the write-behind queue (rows). Queue-full
    # stalls admission via the scheduler's 503 + Retry-After path —
    # never drops. ~150 bytes/row in-memory for typical ciphertexts.
    write_behind_max_rows: int = 1 << 20
    # Drain transaction sizing (rows per btree commit).
    write_behind_drain_rows: int = 1 << 16
    # PR-19 parallel owner-sharded drain: worker count for the
    # write-behind drain (0 = one worker per storage shard, the
    # default; clamped to the shard count; workers own shards
    # round-robin). Owners never share rows and LWW merge commutes, so
    # per-shard transactions need no cross-shard ordering — the end
    # state stays byte-identical at any worker count.
    # EVOLU_WB_DRAIN_WORKERS overrides at the relay.
    wb_drain_workers: int = 0
    # Delegate each drain worker's shard transactions to a child
    # process (storage/_wb_shard_proc.py) instead of running them on
    # the worker thread. Only honest for pure-Python FILE-BACKED
    # shards (the sqlite3 leg holds the GIL; the native C leg already
    # drops it, so threads scale there) — anything else falls back to
    # threads with a logged warning. EVOLU_WB_DRAIN_PROCESS=1
    # overrides at the relay.
    wb_drain_process: bool = False
    # PR-12 mesh-sharded engine (parallel/mesh.py::MeshContext): one
    # pjit/shard_map pass reconciles every owner across the device mesh
    # with STABLE owner->device placement (crc32, like the fleet ring)
    # instead of per-batch LPT, so device-resident per-owner state
    # (sharded winner-cache slot arrays, write-behind serving trees fed
    # from sharded deltas) stays placement-consistent across batches.
    # Default OFF until the parity gate (benchmarks/mesh_engine.py,
    # tests/test_mesh_engine.py: responses + SQLite end state
    # byte-identical to the single-device engine) is green in a
    # deployment; EVOLU_MESH_ENGINE=1 overrides at the relay.
    mesh_engine: bool = False
    # Cap the mesh at this many devices (None = all visible). The
    # placement hash is computed over the CAPPED size, so changing it
    # re-places owners (fine: the engine holds no per-owner device
    # state that outlives a batch without the cache-reset hooks).
    mesh_devices: "int | None" = None
    # After a swallowed offline sync failure, probe the relay's
    # GET /ping starting at this cadence in seconds (backing off 2x per
    # failure up to 30s); the first success fires the reconnect hook
    # and an immediate pull round — the headless analog of the
    # reference's online/focus re-sync listeners (db.ts:390-412).
    # None disables probing.
    reconnect_probe_interval: "float | None" = 1.0
    # PR-13 connection tier (server/conn.py): "threaded" = the
    # reference-shaped ThreadingHTTPServer (one thread per connection,
    # the default and every pin's baseline until event-loop parity is
    # proven in a deployment); "eventloop" = one selectors loop owns
    # every socket, complete requests run on a BOUNDED handler pool,
    # and push long-polls park the bare connection — 10^4-10^5 idle
    # subscriptions cost file descriptors, not threads.
    # EVOLU_CONN_TIER overrides at the relay.
    connection_tier: str = "threaded"
    # Event-tier bounds (flow control + slow-client hardening — see
    # docs/PUSH.md): handler-pool size (the only threads request
    # handling ever uses), in-flight dispatch bound past which the
    # loop sheds 503 + Retry-After itself, the ABSOLUTE budget a
    # request must fully arrive within (slowloris can't trickle past
    # it), the no-progress write stall budget, and the header cap
    # (431 past it).
    conn_handler_threads: int = 8
    conn_max_pending: int = 512
    conn_read_timeout_s: float = 30.0
    conn_write_timeout_s: float = 30.0
    conn_max_header_bytes: int = 16384
    # PR-13 push subscriptions (server/push.py): relay-held long-poll
    # subscriptions woken by a mutation's changed set at the
    # granularity E2EE exposes (owner + author-node row metadata) —
    # mutation→client-visible drops from the polling interval to the
    # push round trip. Relay default-on (a new GET endpoint, zero
    # effect on existing responses); push_subscribe wires the CLIENT
    # leg in connect(): wake-driven sync rounds instead of (or on top
    # of) the sync_interval timer.
    push_subscriptions: bool = True
    push_subscribe: bool = False
    push_poll_timeout_s: float = 25.0
    push_max_subscriptions: int = 1 << 17


default_config = Config()


def set_config(c: Config) -> None:
    global default_config
    default_config = c


@dataclass(frozen=True)
class FleetConfig:
    """Shared fleet placement configuration (server/fleet.py — no
    reference equivalent; the reference relay is a single node).

    Every relay in a fleet must hold the SAME FleetConfig: the
    owner→relay placement ring is a pure function of (relays,
    virtual_nodes, replication_factor, seed), so agreement on this
    object IS agreement on who serves whom. Distribution is static
    config (constructor arg or `POST /fleet/reload`), deliberately not
    a consensus protocol: a fleet is operated, membership changes are
    deploys. `version` is a monotonic operator counter so a relay can
    refuse a stale reload racing a newer one."""

    relays: Tuple[str, ...]  # member base URLs (the ring membership)
    replication_factor: int = 2  # R: replicas (incl. primary) per owner
    virtual_nodes: int = 64  # ring points per relay (placement smoothness)
    seed: int = 0  # shared hash seed — all members must agree
    version: int = 0  # monotonic config generation (reload ordering)
    # Routing mode for a request landing on a non-placed relay:
    # False = 307 redirect carrying the authoritative peer URL (the
    # client follows and caches the route — sync/client.py); True =
    # proxy-forward through the relay (one extra hop, but works for
    # clients that cannot follow redirects).
    forward: bool = False

    def __post_init__(self):
        object.__setattr__(
            self, "relays", tuple(u.rstrip("/") for u in self.relays)
        )

    def to_json(self) -> dict:
        return {
            "relays": list(self.relays),
            "replication_factor": self.replication_factor,
            "virtual_nodes": self.virtual_nodes,
            "seed": self.seed,
            "version": self.version,
            "forward": self.forward,
        }

    @classmethod
    def from_json(cls, d: dict) -> "FleetConfig":
        """Decode a `/fleet/reload` body. Raises ValueError on any
        malformed shape (the relay maps it to HTTP 400, matching the
        wire-decoder contract)."""
        try:
            raw = d["relays"]
            # A bare string iterates character-by-character into a ring
            # of one-character "URLs" — an easy templating mistake that
            # would 200 and then 307 every request to nonsense. Demand
            # a real list.
            if isinstance(raw, (str, bytes)) or not isinstance(raw, (list, tuple)):
                raise ValueError('fleet config "relays" must be a list of URLs')
            relays = tuple(str(u) for u in raw)
            if not relays:
                raise ValueError("fleet config needs at least one relay")
            if len(relays) > 1024:
                raise ValueError(f"fleet config lists {len(relays)} relays "
                                 "(max 1024)")
            vnodes = int(d.get("virtual_nodes", 64))
            if not 1 <= vnodes <= 4096:
                # The ring builds relays × vnodes hash points; an
                # absurd value from a reload body is a CPU/memory DoS,
                # not a tuning choice.
                raise ValueError(
                    f"virtual_nodes={vnodes} outside 1..4096")
            return cls(
                relays=relays,
                replication_factor=int(d.get("replication_factor", 2)),
                virtual_nodes=vnodes,
                seed=int(d.get("seed", 0)),
                version=int(d.get("version", 0)),
                forward=bool(d.get("forward", False)),
            )
        except (KeyError, TypeError) as e:
            raise ValueError(f"malformed fleet config: {e!r}") from e
