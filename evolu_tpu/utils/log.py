"""Structured logging + kernel timing (reference: packages/evolu/src/log.ts).

The reference gates console logs on `config.log` with targets
`clock:read | clock:update | sync:request | sync:response | dev`
(types.ts:21-26) and carries a commented-out duration profiler
(log.ts:16-37). This module keeps the exact target names and gating
semantics (`log: true` enables all targets; a string or list enables a
subset), and realizes the profiler as `span(target)` — a context
manager recording wall-clock durations, used for per-kernel timing
(SURVEY.md §5 "structured event log + per-kernel timing keyed by the
same target names").

Events also land in a bounded in-memory ring (`recent_events`) so
tests and embedders can observe the runtime without scraping stdout.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple, Union

# Reference targets (types.ts:21-26) + TPU-native kernel targets.
TARGETS = (
    "clock:read",
    "clock:update",
    "sync:request",
    "sync:response",
    "dev",
    "kernel:merge",
    "kernel:merkle",
    "kernel:reconcile",
)

# jax.profiler trace annotations keyed by the SAME span target names
# (VERDICT #7): when enabled, every `span(target, message)` also opens
# a `jax.profiler.TraceAnnotation("<target>|<message>")`, so a captured
# trace (jax.profiler.trace / benchmarks/kernel_trace.py) shows the
# host-side spans interleaved with the device timeline under the names
# the log/metrics surfaces already use. OFF by default and lazily
# imported — this module must never touch jax at import time (the obs
# import-hygiene contract), and a disabled span stays allocation-free.
_trace_annotation_cls = None


def enable_trace_annotations(flag: bool = True) -> None:
    """Turn profiler span annotations on/off (also honored at import
    time via EVOLU_TRACE_ANNOTATIONS=1)."""
    global _trace_annotation_cls
    if not flag:
        _trace_annotation_cls = None
        return
    from jax.profiler import TraceAnnotation  # lazy: only when opted in

    _trace_annotation_cls = TraceAnnotation


if os.environ.get("EVOLU_TRACE_ANNOTATIONS") == "1":
    enable_trace_annotations(True)


@dataclass
class LogEvent:
    target: str
    message: str
    t: float
    duration_ms: Optional[float] = None
    fields: Dict[str, object] = field(default_factory=dict)


def _obs():
    """Lazy (obs.flight, obs.metrics, obs.trace, obs.anatomy) tuple —
    obs imports LogEvent from this module, so the reverse edge must
    resolve at call time. Cached after the first call; one tuple check
    per event afterwards."""
    global _obs_pair
    if _obs_pair is None:
        from evolu_tpu.obs import anatomy, flight, metrics, trace

        _obs_pair = (flight, metrics, trace, anatomy)
    return _obs_pair


_obs_pair = None


class Logger:
    """Target-gated logger with a bounded event ring.

    `enabled` follows config.log semantics: True = every target,
    False = nothing, str/list = those targets only (log.ts:5-14).
    """

    def __init__(self, enabled: Union[bool, str, List[str]] = False, capacity: int = 1024):
        self._lock = threading.Lock()
        self._ring: Deque[LogEvent] = deque(maxlen=capacity)
        # target -> (count, total_ms, max_ms): O(1) running aggregates,
        # never a per-call list (long-lived workers span per batch).
        self._durations: Dict[str, Tuple[int, float, float]] = {}
        self.configure(enabled)

    def configure(self, enabled: Union[bool, str, List[str]]) -> None:
        if isinstance(enabled, str):
            enabled = [enabled]
        self._enabled = enabled

    def is_enabled(self, target: str) -> bool:
        if self._enabled is True:
            return True
        if not self._enabled:
            return False
        return target in self._enabled

    def log(self, target: str, message: str = "", *, _flight: bool = True,
            **fields) -> None:
        """log(target)(message) analog (log.ts:5-14): console + ring.
        The flight recorder (obs.flight) mirrors the event even when the
        target's console output is disabled — post-mortems need exactly
        the events nobody was watching (host-fallback warnings, sync
        rounds); the console gating stays ring/print-only. High-volume
        chatter (per-request HTTP access lines) passes `_flight=False`
        so it cannot evict the sparse events the bounded ring exists to
        preserve. The event is built only if some consumer is active —
        a fully-disabled call stays allocation-free."""
        recorder = _obs()[0].recorder
        flight_on = _flight and recorder.enabled
        console_on = self.is_enabled(target)
        if not (flight_on or console_on):
            return
        ev = LogEvent(target=target, message=message, t=time.time(), fields=fields)
        if flight_on:
            recorder.record_event(ev)
        if not console_on:
            return
        with self._lock:
            self._ring.append(ev)
        extra = (" " + " ".join(f"{k}={v}" for k, v in fields.items())) if fields else ""
        print(f"[{target}] {message}{extra}")

    @contextmanager
    def span(self, target: str, message: str = "", **fields):
        """Duration measurement (the reference's commented-out
        createLogDuration, log.ts:16-37). Records even when console
        output for the target is disabled so kernel timings are always
        queryable via `duration_stats`. With trace annotations enabled
        (`enable_trace_annotations`), the span also opens a
        jax.profiler.TraceAnnotation under "<target>|<message>" so a
        captured trace carries the same names the log/metrics surfaces
        use."""
        annotation = None
        if _trace_annotation_cls is not None:
            annotation = _trace_annotation_cls(
                f"{target}|{message}" if message else target
            )
            annotation.__enter__()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if annotation is not None:
                annotation.__exit__(None, None, None)
            ms = (time.perf_counter() - t0) * 1e3
            ev = LogEvent(target=target, message=message, t=time.time(),
                          duration_ms=ms, fields=fields)
            with self._lock:
                cnt, tot, mx = self._durations.get(target, (0, 0.0, 0.0))
                self._durations[target] = (cnt + 1, tot + ms, max(mx, ms))
                self._ring.append(ev)
            # Span aggregates feed observability: the duration lands in
            # the per-target latency histogram (percentiles via
            # `duration_summary` / the relay's /metrics) and the event
            # in the flight ring. Host-side values only — the span
            # wraps dispatch+pull, it never adds one. With an ambient
            # trace context (obs.trace — e.g. the scheduler's batch
            # span active around the engine pass), the same interval
            # also lands in the distributed trace under its kernel:*
            # name, so the chrome export interleaves host and kernel
            # spans on one timebase.
            flight, metrics, trace, anatomy = _obs()
            metrics.observe("evolu_kernel_span_ms", ms, target=target)
            if target.startswith("kernel:"):
                # Stage-anatomy fold (ISSUE 16): kernel spans become
                # evolu_stage_* series keyed by their target, with the
                # span's n= field as the row count so the per-stage fit
                # separates fixed RTT from slope. Bounded label set —
                # targets come from TARGETS, never request data.
                anatomy.record_span(target, ms, rows=fields.get("n", 0))
            flight.recorder.record_event(ev)
            tctx = trace.current()
            if tctx is not None:
                trace.record_span(
                    target if not message else f"{target}|{message}",
                    tctx, ev.t - ms / 1e3, ms, fields or None,
                )
            if self.is_enabled(target):
                extra = (" " + " ".join(f"{k}={v}" for k, v in fields.items())) if fields else ""
                print(f"[{target}] {message} {ms:.3f}ms{extra}")

    def recent_events(self, target: Optional[str] = None) -> List[LogEvent]:
        with self._lock:
            evs = list(self._ring)
        if target is None:
            return evs
        return [e for e in evs if e.target == target]

    def duration_stats(self, target: str) -> Optional[Tuple[int, float, float]]:
        """(count, total_ms, max_ms) for a span target, or None."""
        with self._lock:
            return self._durations.get(target)

    def duration_summary(
        self, target: str, percentiles: Tuple[int, ...] = (50, 90, 99)
    ) -> Optional[Dict[str, float]]:
        """Mean/max/percentile summary for a span target, or None if it
        never fired. count/mean/max come from the exact O(1) aggregates;
        percentiles are estimated from the log-bucketed span histogram
        (obs.metrics), so they carry bucket-resolution error. The
        histogram is process-global, so percentiles are attached only
        on the module singleton — a scoped Logger's aggregates would
        otherwise be paired with percentiles that include every OTHER
        logger's spans for the target (internally inconsistent)."""
        with self._lock:
            stats = self._durations.get(target)
        if stats is None:
            return None
        cnt, tot, mx = stats
        out: Dict[str, float] = {
            "count": cnt, "total_ms": tot, "mean_ms": tot / cnt, "max_ms": mx,
        }
        if globals().get("logger") is self:
            metrics = _obs()[1]
            for p in percentiles:
                q = metrics.quantile("evolu_kernel_span_ms", p / 100.0, target=target)
                if q is not None:
                    out[f"p{p}_ms"] = q
        return out

    def clear(self) -> None:
        """Reset the ring + duration aggregates. On the MODULE SINGLETON
        (`logger`) this also resets the process metrics registry,
        flight recorder, and trace span ring — one call returns the
        whole observability surface to a clean slate (test isolation).
        Scoped Logger
        instances clear only their own state: an embedder emptying a
        private ring must not zero the counters the relay is serving
        at GET /metrics (Prometheus counters are monotonic)."""
        with self._lock:
            self._ring.clear()
            self._durations.clear()
        if globals().get("logger") is self:
            flight, metrics, trace, anatomy = _obs()
            metrics.reset()
            flight.recorder.clear()
            trace.recorder.clear()
            anatomy.reset()


# Module-level default, mirroring the reference's module singleton. The
# runtime re-configures it from Config at init (setConfig analog).
logger = Logger()


def log(target: str, message: str = "", **fields) -> None:
    logger.log(target, message, **fields)


def span(target: str, message: str = "", **fields):
    return logger.span(target, message, **fields)
