"""Structured logging + kernel timing (reference: packages/evolu/src/log.ts).

The reference gates console logs on `config.log` with targets
`clock:read | clock:update | sync:request | sync:response | dev`
(types.ts:21-26) and carries a commented-out duration profiler
(log.ts:16-37). This module keeps the exact target names and gating
semantics (`log: true` enables all targets; a string or list enables a
subset), and realizes the profiler as `span(target)` — a context
manager recording wall-clock durations, used for per-kernel timing
(SURVEY.md §5 "structured event log + per-kernel timing keyed by the
same target names").

Events also land in a bounded in-memory ring (`recent_events`) so
tests and embedders can observe the runtime without scraping stdout.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple, Union

# Reference targets (types.ts:21-26) + TPU-native kernel targets.
TARGETS = (
    "clock:read",
    "clock:update",
    "sync:request",
    "sync:response",
    "dev",
    "kernel:merge",
    "kernel:merkle",
    "kernel:reconcile",
)


@dataclass
class LogEvent:
    target: str
    message: str
    t: float
    duration_ms: Optional[float] = None
    fields: Dict[str, object] = field(default_factory=dict)


class Logger:
    """Target-gated logger with a bounded event ring.

    `enabled` follows config.log semantics: True = every target,
    False = nothing, str/list = those targets only (log.ts:5-14).
    """

    def __init__(self, enabled: Union[bool, str, List[str]] = False, capacity: int = 1024):
        self._lock = threading.Lock()
        self._ring: Deque[LogEvent] = deque(maxlen=capacity)
        # target -> (count, total_ms, max_ms): O(1) running aggregates,
        # never a per-call list (long-lived workers span per batch).
        self._durations: Dict[str, Tuple[int, float, float]] = {}
        self.configure(enabled)

    def configure(self, enabled: Union[bool, str, List[str]]) -> None:
        if isinstance(enabled, str):
            enabled = [enabled]
        self._enabled = enabled

    def is_enabled(self, target: str) -> bool:
        if self._enabled is True:
            return True
        if not self._enabled:
            return False
        return target in self._enabled

    def log(self, target: str, message: str = "", **fields) -> None:
        """log(target)(message) analog (log.ts:5-14): console + ring."""
        if not self.is_enabled(target):
            return
        ev = LogEvent(target=target, message=message, t=time.time(), fields=fields)
        with self._lock:
            self._ring.append(ev)
        extra = (" " + " ".join(f"{k}={v}" for k, v in fields.items())) if fields else ""
        print(f"[{target}] {message}{extra}")

    @contextmanager
    def span(self, target: str, message: str = "", **fields):
        """Duration measurement (the reference's commented-out
        createLogDuration, log.ts:16-37). Records even when console
        output for the target is disabled so kernel timings are always
        queryable via `duration_stats`."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            ms = (time.perf_counter() - t0) * 1e3
            with self._lock:
                cnt, tot, mx = self._durations.get(target, (0, 0.0, 0.0))
                self._durations[target] = (cnt + 1, tot + ms, max(mx, ms))
                self._ring.append(
                    LogEvent(target=target, message=message, t=time.time(),
                             duration_ms=ms, fields=fields)
                )
            if self.is_enabled(target):
                extra = (" " + " ".join(f"{k}={v}" for k, v in fields.items())) if fields else ""
                print(f"[{target}] {message} {ms:.3f}ms{extra}")

    def recent_events(self, target: Optional[str] = None) -> List[LogEvent]:
        with self._lock:
            evs = list(self._ring)
        if target is None:
            return evs
        return [e for e in evs if e.target == target]

    def duration_stats(self, target: str) -> Optional[Tuple[int, float, float]]:
        """(count, total_ms, max_ms) for a span target, or None."""
        with self._lock:
            return self._durations.get(target)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._durations.clear()


# Module-level default, mirroring the reference's module singleton. The
# runtime re-configures it from Config at init (setConfig analog).
logger = Logger()


def log(target: str, message: str = "", **fields) -> None:
    logger.log(target, message, **fields)


def span(target: str, message: str = "", **fields):
    return logger.span(target, message, **fields)
