"""Shared build-on-demand ctypes loader for the native/ libraries.

Both native bindings (`storage/native.py` over libevolu_host.so,
`sync/native_crypto.py` over libevolu_crypto.so) follow the same
contract: build the specific make target on first use (g++ and the
versioned system sonames are baked into the image), load via ctypes,
run the module's `configure` (argtypes + optional runtime probe), and
cache the result — including failure, so an unbuildable environment
costs one attempt, not one per call. Failure always means "caller
falls back to its pure-Python path", never an exception.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Dict, Optional

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)

_lock = threading.Lock()
_cache: Dict[str, Optional[ctypes.CDLL]] = {}  # so_name → lib (None = failed)


def load_native_library(
    so_name: str,
    configure: Callable[[ctypes.CDLL], Optional[ctypes.CDLL]],
) -> Optional[ctypes.CDLL]:
    """The shared library named `so_name` (also its make target),
    built on first use; None if unavailable. `configure` sets argtypes
    and may return None to veto (e.g. a failing runtime probe)."""
    with _lock:
        if so_name in _cache:
            return _cache[so_name]
        path = os.path.join(NATIVE_DIR, so_name)
        if not os.path.exists(path):
            try:
                subprocess.run(
                    ["make", "-s", so_name], cwd=NATIVE_DIR,
                    check=True, capture_output=True, timeout=120,
                )
            except Exception:
                _cache[so_name] = None
                return None
        try:
            lib = configure(ctypes.CDLL(path))
        except OSError:
            lib = None
        _cache[so_name] = lib
        return lib
