"""Shared build-on-demand ctypes loader for the native/ libraries.

Both native bindings (`storage/native.py` over libevolu_host.so,
`sync/native_crypto.py` over libevolu_crypto.so) follow the same
contract: build the specific make target on first use (g++ and the
versioned system sonames are baked into the image), load via ctypes,
run the module's `configure` (argtypes + optional runtime probe), and
cache the result — including failure, so an unbuildable environment
costs one attempt, not one per call. Failure always means "caller
falls back to its pure-Python path", never an exception.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Dict, Optional

NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)

_lock = threading.Lock()
_cache: Dict[str, Optional[ctypes.CDLL]] = {}  # so_name → lib (None = failed)


def load_native_library(
    so_name: str,
    configure: Callable[[ctypes.CDLL], Optional[ctypes.CDLL]],
) -> Optional[ctypes.CDLL]:
    """The shared library named `so_name` (also its make target),
    built on first use; None if unavailable. `configure` sets argtypes
    and may return None to veto (e.g. a failing runtime probe)."""
    with _lock:
        if so_name in _cache:
            return _cache[so_name]
        path = os.path.join(NATIVE_DIR, so_name)
        # Run make UNCONDITIONALLY (an up-to-date target is a ~50 ms
        # no-op): a stale binary from an older checkout would dlopen
        # fine but lack newly added symbols, and re-dlopen after a
        # rebuild returns the already-loaded stale handle — so the
        # rebuild must happen BEFORE the first load.
        try:
            subprocess.run(
                ["make", "-s", so_name], cwd=NATIVE_DIR,
                check=True, capture_output=True, timeout=120,
            )
        except Exception:
            if not os.path.exists(path):
                _cache[so_name] = None
                return None
            # make unavailable but a binary exists: try it as-is.
        try:
            lib = configure(ctypes.CDLL(path))
        except (OSError, AttributeError):
            # AttributeError = a symbol this build of the bindings
            # needs is missing (stale binary + no toolchain): fall
            # back to the pure-Python paths instead of crashing.
            lib = None
        _cache[so_name] = lib
        return lib
