"""Cross-process reload signal (reference: src/reloadAllTabs.ts).

The reference coordinates same-device browser tabs with a localStorage
write + storage event: resetOwner/restoreOwner in one tab makes every
other tab reload (reloadAllTabs.ts:6-14, db.ts:183-186). The analog
here is processes sharing one database file: a nonce file next to the
DB is bumped by the signalling process; watchers poll its mtime+nonce
and fire their callback, after which the embedder is expected to
reopen its Evolu handle (the "reload").

In-process listeners still use `Evolu.on_reload`; this adds the
cross-process leg. Polling is cheap (one stat per interval) and has no
platform dependencies — the durability story does not rest on it, it
is purely a UX signal, exactly like the reference's.
"""

from __future__ import annotations

import os
import threading
import uuid
from typing import Callable, Optional


def _signal_path(db_path: str) -> str:
    return db_path + ".reload"


def notify_reload(db_path: str) -> Optional[str]:
    """Bump the signal file (the localStorage setItem analog).

    Returns the written nonce so the originating process can tell its
    own watcher to ignore it (a browser tab never receives the storage
    event for its own setItem)."""
    if db_path == ":memory:":
        return None
    path = _signal_path(db_path)
    nonce = uuid.uuid4().hex
    tmp = f"{path}.{uuid.uuid4().hex}"
    with open(tmp, "w") as f:
        f.write(nonce)
    os.replace(tmp, path)  # atomic on POSIX
    return nonce


class ReloadWatcher:
    """Polls the signal file; fires `callback` on each bump."""

    def __init__(self, db_path: str, callback: Callable[[], None], interval: float = 0.5):
        self._path = _signal_path(db_path)
        self._callback = callback
        self._interval = interval
        self._stop = threading.Event()
        self._own_lock = threading.Lock()
        self._own: set = set()  # self-originated nonces to skip
        self._last = self._read()
        self._thread: Optional[threading.Thread] = None
        if db_path != ":memory:":
            self._thread = threading.Thread(target=self._loop, daemon=True, name="evolu-reload")
            self._thread.start()

    def _read(self) -> Optional[str]:
        try:
            with open(self._path) as f:
                return f.read()
        except OSError:
            return None

    def ignore(self, nonce: Optional[str]) -> None:
        """Mark a nonce as self-originated: observing it updates state
        without firing the callback (no storage event for your own
        setItem)."""
        if nonce is not None:
            with self._own_lock:
                self._own.add(nonce)

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            cur = self._read()
            if cur is not None and cur != self._last:
                self._last = cur
                with self._own_lock:
                    own = cur in self._own
                    self._own.discard(cur)
                if not own:
                    self._callback()

    def stop(self) -> None:
        self._stop.set()
        # Callbacks run on the watcher thread; a callback that tears the
        # client down (dispose -> stop) must not self-join.
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join()
