"""Pod-scale relay: the WHOLE server spanning a jax.distributed
cluster (`engine.reconcile_pod` — reference apps/server/src/index.ts
at the BASELINE "one pod pass" scale).

Each process owns the storage shards of the owners the stable crc32
hash assigns to it; the Merkle device leg runs as ONE SPMD dispatch
over the global mesh (DCN carries collectives, never rows), and the
XOR digest all-reduce lets every process verify the pod agreed on the
batch. Identical request batches must reach every process (the
broadcast-ingest model — e.g. a front-end fanning out, or a shared
queue).

Single process (degenerates to the plain engine, byte-identically):

    python examples/pod_server.py

Two processes on one machine (4 virtual CPU devices each → an
8-device global mesh; same flags a real multi-host pod would use,
with real addresses):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
      python examples/pod_server.py --nproc 2 --pid 0 &
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
      python examples/pod_server.py --nproc 2 --pid 1
"""

import argparse
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nproc", type=int, default=1)
    ap.add_argument("--pid", type=int, default=0)
    ap.add_argument("--coordinator", default="127.0.0.1:9765")
    ap.add_argument("--store", default=":memory:")
    args = ap.parse_args()

    if args.nproc > 1:
        from evolu_tpu.parallel.multihost import initialize_multihost

        mesh = initialize_multihost(args.coordinator, args.nproc, args.pid)
    else:
        from evolu_tpu.parallel.mesh import create_mesh

        mesh = create_mesh()

    from evolu_tpu.core.merkle import (
        apply_prefix_xors,
        merkle_tree_to_string,
        minute_deltas_host,
    )
    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.server.engine import reconcile_pod
    from evolu_tpu.server.relay import ShardedRelayStore
    from evolu_tpu.sync import protocol

    # Namespace file-backed stores per process: owner→shard (crc32 % 4)
    # is independent of owner→process, so a shared path would have two
    # OS processes writing the same SQLite files.
    path = args.store if args.store == ":memory:" or args.nproc == 1 else (
        f"{args.store}.p{args.pid}"
    )
    store = ShardedRelayStore(path, shards=4)

    # A demo batch: 8 owners pushing their own new messages with their
    # post-apply trees (the steady-state shape). In production this
    # batch arrives from the ingest fabric, identical on every process.
    base = 1_700_000_000_000
    requests = []
    for o in range(8):
        msgs = [
            protocol.EncryptedCrdtMessage(
                timestamp_to_string(Timestamp(base + (o * 997 + i) * 60_000, i % 4,
                                              f"{o + 1:016x}")),
                b"ciphertext-%d-%d" % (o, i),
            )
            for i in range(5 + o)
        ]
        deltas, _ = minute_deltas_host(m.timestamp for m in msgs)
        tree = merkle_tree_to_string(apply_prefix_xors({}, deltas))
        requests.append(protocol.SyncRequest(tuple(msgs), f"owner{o}", "f" * 16, tree))

    # wire=True: a server only forwards response BYTES, so the serve
    # path skips the per-message object layer entirely (r5; the bytes
    # are exactly encode_sync_response of the object-mode responses).
    responses, digest = reconcile_pod(mesh, store, tuple(requests), wire=True)
    mine = [i for i, r in enumerate(responses) if r is not None]
    served = sum(len(r) for r in responses if r is not None)
    print(
        f"proc {args.pid}/{args.nproc}: answered {len(mine)}/{len(requests)} "
        f"requests {mine} ({served} response bytes), "
        f"pod digest 0x{digest & 0xFFFFFFFF:08x}"
    )
    store.close()


if __name__ == "__main__":
    main()
