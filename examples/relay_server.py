"""Deployable relay server (reference: examples/server-nodejs/src/index.ts).

A single HTTP endpoint `POST /` taking a protobuf SyncRequest and
returning a SyncResponse, plus `GET /ping`; storage is one SQLite file.
The relay is E2EE-blind — it sees timestamps and ciphertext only.

    python examples/relay_server.py [--db relay.db] [--port 4000]

PORT may also come from the environment (index.ts:254-256).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from evolu_tpu.server.relay import RelayServer, RelayStore


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--db", default="relay.db")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=int(os.environ.get("PORT", 4000)))
    ap.add_argument("--checkpoint-interval", type=float, default=None,
                    help="write a local snapshot checkpoint every N seconds "
                         "(crash-consistent fast restart; server/snapshot.py)")
    args = ap.parse_args()

    server = RelayServer(RelayStore(args.db), host=args.host, port=args.port,
                         checkpoint_interval_s=args.checkpoint_interval)
    server.start()
    print(f"relay listening on {server.url} (db: {args.db})")
    try:
        import threading

        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()


if __name__ == "__main__":
    main()
