"""TodoMVC-equivalent demo app (reference: examples/nextjs/pages/index.tsx).

Same schema and operations as the reference demo — `todo` +
`todoCategory` tables, create / toggle / rename / categorize /
soft-delete, owner mnemonic restore — driven from a CLI instead of
React. The reactive layer is the same: the row list re-renders from a
query subscription, not from command handlers.

Run a relay first (examples/relay_server.py), then:

    python examples/todo_cli.py --db /tmp/a.db --sync-url http://127.0.0.1:4000/

Commands:
    add <title>            create a todo (config-1 write path)
    cat <name>             create a category
    assign <n> <category>  set todo #n's category
    toggle <n>             flip isCompleted
    rename <n> <title>     change title
    rm <n>                 soft-delete (isDeleted=1, like the reference)
    ls                     list (excluding soft-deleted)
    sync                   explicit sync round (also runs on start)
    owner                  print the mnemonic (restore with --mnemonic)
    quit
"""

from __future__ import annotations

import argparse
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from evolu_tpu.api.model import validate_non_empty_string_1000
from evolu_tpu.api.query import table
from evolu_tpu.runtime.client import Evolu
from evolu_tpu.sync.client import connect
from evolu_tpu.utils.config import Config

SCHEMA = {
    "todo": ("title", "isCompleted", "categoryId"),
    "todoCategory": ("name",),
}

TODOS = (
    table("todo")
    .select("id", "title", "isCompleted", "categoryId")
    .where_is_deleted(False)
    .order_by("createdAt")
)
CATEGORIES = (
    table("todoCategory")
    .select("id", "name")
    .where_is_deleted(False)
    .order_by("createdAt")
)


class TodoApp:
    def __init__(self, db_path: str, sync_url: str, mnemonic: str | None = None):
        self.evolu = Evolu(
            db_path=db_path,
            config=Config(sync_url=sync_url),
            mnemonic=mnemonic,
        )
        self.evolu.update_db_schema(SCHEMA)
        self.evolu.subscribe_error(lambda e: print(f"! error: {e}", file=sys.stderr))
        self.transport = connect(self.evolu)
        # Reactive rendering: the subscription drives the list, exactly
        # like useQuery → useSyncExternalStore in the reference demo.
        self._unsub = self.evolu.subscribe_query(TODOS, listener=self.render)
        self.evolu.subscribe_query(CATEGORIES)
        self.sync()

    # -- reactive view --

    def rows(self):
        return self.evolu.get_query_rows(TODOS)

    def categories(self):
        return self.evolu.get_query_rows(CATEGORIES)

    def render(self) -> None:
        cats = {c["id"]: c["name"] for c in self.categories()}
        print("-- todos --")
        for i, r in enumerate(self.rows(), 1):
            mark = "x" if r["isCompleted"] else " "
            cat = f"  [{cats.get(r['categoryId'], '?')}]" if r["categoryId"] else ""
            print(f" {i:2d}. [{mark}] {r['title']}{cat}")

    # -- commands --

    def _nth(self, n: str):
        rows = self.rows()
        i = int(n) - 1
        if not 0 <= i < len(rows):
            raise IndexError(f"no todo #{n}")
        return rows[i]

    def add(self, title: str) -> None:
        self.evolu.create("todo", {"title": validate_non_empty_string_1000(title),
                                   "isCompleted": False})

    def cat(self, name: str) -> None:
        self.evolu.create("todoCategory", {"name": validate_non_empty_string_1000(name)})

    def assign(self, n: str, category: str) -> None:
        match = [c for c in self.categories() if c["name"] == category]
        if not match:
            raise ValueError(f"no category {category!r}")
        self.evolu.update("todo", self._nth(n)["id"], {"categoryId": match[0]["id"]})

    def toggle(self, n: str) -> None:
        row = self._nth(n)
        self.evolu.update("todo", row["id"], {"isCompleted": not row["isCompleted"]})

    def rename(self, n: str, title: str) -> None:
        self.evolu.update("todo", self._nth(n)["id"],
                          {"title": validate_non_empty_string_1000(title)})

    def rm(self, n: str) -> None:
        # Soft delete (CommonColumns.isDeleted, types.ts:194-201).
        self.evolu.update("todo", self._nth(n)["id"], {"isDeleted": True})

    def sync(self) -> None:
        self.evolu.sync()
        self.evolu.worker.flush()
        self.transport.flush()
        self.evolu.worker.flush()

    def owner(self) -> str:
        return self.evolu.owner.mnemonic

    def close(self) -> None:
        self._unsub()
        self.transport.stop()
        self.evolu.dispose()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--db", default=":memory:")
    ap.add_argument("--sync-url", default="http://127.0.0.1:4000/")
    ap.add_argument("--mnemonic", default=None, help="restore an existing owner")
    args = ap.parse_args()

    app = TodoApp(args.db, args.sync_url, args.mnemonic)
    print(f"owner: {app.evolu.owner.id}  (type 'owner' for the mnemonic)")
    app.render()
    try:
        for line in sys.stdin:
            parts = line.strip().split(None, 1)
            if not parts:
                continue
            cmd, rest = parts[0], parts[1] if len(parts) > 1 else ""
            try:
                if cmd == "add":
                    app.add(rest)
                elif cmd == "cat":
                    app.cat(rest)
                elif cmd == "assign":
                    n, category = rest.split(None, 1)
                    app.assign(n, category)
                elif cmd == "toggle":
                    app.toggle(rest)
                elif cmd == "rename":
                    n, title = rest.split(None, 1)
                    app.rename(n, title)
                elif cmd == "rm":
                    app.rm(rest)
                elif cmd == "ls":
                    app.render()
                elif cmd == "sync":
                    app.sync()
                    app.render()
                elif cmd == "owner":
                    print(app.owner())
                elif cmd in ("quit", "exit"):
                    break
                else:
                    print(f"? unknown command {cmd!r}")
            except (ValueError, IndexError) as e:
                print(f"! {e}")
    finally:
        app.sync()
        app.close()


if __name__ == "__main__":
    main()
