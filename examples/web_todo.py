"""Web TodoMVC — the visual demo, matching the reference's
examples/nextjs/pages/index.tsx capabilities: todos (add, rename,
toggle complete, soft-delete, assign to category), categories (add,
rename, soft-delete), owner (show mnemonic, restore, reset), reactive
updates, optional relay sync.

The reference demo is React over the in-browser framework; this
framework is host-side, so the demo is the thin inversion: the client
runtime runs in this process and the browser is a view — a single
vanilla-JS page long-polling `/api/state` (the useSyncExternalStore
analog: one monotonically increasing version bumped by `Evolu.listen`).

Run:  python examples/web_todo.py [--port 8321] [--db todo.db]
      [--sync-url http://relay:4000]   then open http://127.0.0.1:8321
Two processes with --sync-url against examples/relay_server.py (and the
second started with --restore "<mnemonic of the first>") converge live.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from evolu_tpu import connect, create_hooks, table
from evolu_tpu.utils.config import Config

SCHEMA = {
    "todo": ("title", "isCompleted", "categoryId"),
    "todoCategory": ("name",),
}

PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>evolu_tpu TodoMVC</title>
<style>
  :root { color-scheme: light dark; }
  body { font: 15px/1.5 system-ui, sans-serif; max-width: 620px; margin: 2rem auto; padding: 0 1rem; }
  h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.6rem; }
  ul { list-style: none; padding: 0; } li { display: flex; gap: .5rem; align-items: center; padding: .15rem 0; }
  li .t { flex: 1; cursor: pointer; } li.done .t { text-decoration: line-through; opacity: .6; }
  button { font: inherit; } input, select { font: inherit; padding: .15rem .3rem; }
  .muted { opacity: .65; font-size: .85rem; } .row { display: flex; gap: .5rem; margin: .5rem 0; }
  #mnemonic { user-select: all; word-break: break-word; }
</style></head><body>
<h1>evolu_tpu TodoMVC</h1>
<p class="muted" id="status">loading…</p>
<div class="row">
  <input id="newTitle" placeholder="What needs to be done?" style="flex:1">
  <select id="newCat"><option value="">no category</option></select>
  <button id="add">Add</button>
</div>
<ul id="todos"></ul>
<h2>Categories</h2>
<div class="row"><input id="newCatName" placeholder="New category" style="flex:1"><button id="addCat">Add</button></div>
<ul id="cats"></ul>
<h2>Owner</h2>
<p class="muted">Mnemonic (restores this data on any device):</p>
<p id="mnemonic" class="muted"></p>
<div class="row">
  <button id="restore">Restore owner…</button>
  <button id="reset">Reset owner (delete all)</button>
  <button id="sync">Sync now</button>
</div>
<script>
const $ = (id) => document.getElementById(id);
let version = -1, state = {todos: [], categories: [], owner: {}};

async function api(path, body) {
  const r = await fetch(path, body === undefined ? {} :
    {method: "POST", headers: {"content-type": "application/json"}, body: JSON.stringify(body)});
  if (!r.ok) { alert(await r.text()); throw new Error(path); }
  return r.json();
}
const mutate = (tbl, values) => api("/api/mutate", {table: tbl, values});

function render() {
  $("status").textContent = `${state.todos.length} todos · ${state.categories.length} categories` +
    (state.first_data_loaded ? "" : " · loading…");
  $("mnemonic").textContent = state.owner.mnemonic || "";
  const sel = $("newCat"), had = sel.value;
  sel.innerHTML = '<option value="">no category</option>' +
    state.categories.map(c => `<option value="${esc(c.id)}">${esc(c.name)}</option>`).join("");
  sel.value = had;
  $("todos").innerHTML = state.todos.map(t => `
    <li class="${t.isCompleted ? "done" : ""}" data-id="${esc(t.id)}">
      <input type="checkbox" ${t.isCompleted ? "checked" : ""} data-a="toggle">
      <span class="t" data-a="rename" title="click to rename">${esc(t.title)}</span>
      <select data-a="cat"><option value="">—</option>${
        state.categories.map(c => `<option value="${esc(c.id)}" ${c.id === t.categoryId ? "selected" : ""}>${esc(c.name)}</option>`).join("")}
      </select>
      <button data-a="del">×</button>
    </li>`).join("");
  $("cats").innerHTML = state.categories.map(c => `
    <li data-id="${esc(c.id)}"><span class="t" data-a="renameCat" title="click to rename">${esc(c.name)}</span>
    <button data-a="delCat">×</button></li>`).join("");
}
const esc = (s) => String(s ?? "").replace(/[&<>"]/g, ch => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;"}[ch]));

document.body.addEventListener("click", async (e) => {
  const a = e.target.dataset.a, li = e.target.closest("li"), id = li && li.dataset.id;
  if (a === "toggle") {
    const t = state.todos.find(t => t.id === id);
    if (t) await mutate("todo", {id, isCompleted: !t.isCompleted});  // stale row: next poll re-renders
  }
  else if (a === "del") await mutate("todo", {id, isDeleted: true});
  else if (a === "delCat") await mutate("todoCategory", {id, isDeleted: true});
  else if (a === "rename") { const v = prompt("New title?"); if (v) await mutate("todo", {id, title: v}); }
  else if (a === "renameCat") { const v = prompt("New name?"); if (v) await mutate("todoCategory", {id, name: v}); }
});
document.body.addEventListener("change", async (e) => {
  if (e.target.dataset.a === "cat") {
    const id = e.target.closest("li").dataset.id;
    await mutate("todo", {id, categoryId: e.target.value || null});
  }
});
$("add").onclick = async () => {
  const title = $("newTitle").value.trim(); if (!title) return;
  await mutate("todo", {title, isCompleted: false, categoryId: $("newCat").value || null});
  $("newTitle").value = "";
};
$("newTitle").onkeydown = (e) => { if (e.key === "Enter") $("add").click(); };
$("addCat").onclick = async () => {
  const name = $("newCatName").value.trim(); if (!name) return;
  await mutate("todoCategory", {name}); $("newCatName").value = "";
};
$("restore").onclick = async () => {
  const m = prompt("Mnemonic?"); if (m) { await api("/api/restore", {mnemonic: m}); location.reload(); }
};
$("reset").onclick = async () => {
  if (confirm("Delete ALL local data?")) { await api("/api/reset", {}); location.reload(); }
};
$("sync").onclick = () => api("/api/sync", {});

(async function poll() {
  for (;;) {
    try {
      const s = await api(`/api/state?since=${version}`);
      version = s.version; state = s; render();
    } catch (err) { await new Promise(r => setTimeout(r, 1000)); }
  }
})();
</script></body></html>"""


class DemoApp:
    """Owns the framework client and a change-versioned state snapshot."""

    def __init__(self, db_path=":memory:", sync_url=None, mnemonic=None):
        # With a relay, auto-pull every 2s — the headless analog of the
        # reference's load/online/focus sync triggers (db.ts:390-412);
        # without it an idle instance would never see remote changes.
        config = Config(sync_url=sync_url, sync_interval=2.0) if sync_url else Config()
        self.hooks = create_hooks(
            SCHEMA, db_path=db_path, config=config, mnemonic=mnemonic
        )
        self.evolu = self.hooks.evolu
        self.synced = False
        if sync_url:
            connect(self.evolu)
            self.synced = True
        self._version = 0
        self._cond = threading.Condition()
        # The useQuery analog: two live subscriptions; any change bumps
        # the version and wakes long-polls.
        self.todos = self.hooks.use_query(
            lambda t: t("todo")
            .select("id", "title", "isCompleted", "categoryId")
            .where_is_deleted(False)
            .order_by("createdAt")
        )
        self.cats = self.hooks.use_query(
            lambda t: t("todoCategory")
            .select("id", "name")
            .where_is_deleted(False)
            .order_by("createdAt")
        )
        self.todos.subscribe(self._bump)
        self.cats.subscribe(self._bump)
        self.evolu.worker.flush()

    def _bump(self):
        with self._cond:
            self._version += 1
            self._cond.notify_all()

    def state(self, since: int, timeout: float = 25.0) -> dict:
        with self._cond:
            if since == self._version:
                self._cond.wait(timeout)
            owner = self.evolu.owner
            return {
                "version": self._version,
                "todos": self.todos.rows,
                "categories": self.cats.rows,
                "owner": {"id": owner.id, "mnemonic": owner.mnemonic},
                "first_data_loaded": self.hooks.use_evolu_first_data_are_loaded(),
                "synced": self.synced,
            }

    def mutate(self, tbl: str, values: dict) -> str:
        row_id = self.evolu.mutate(tbl, values)
        self.evolu.worker.flush()
        return row_id

    def restore(self, mnemonic: str) -> None:
        self.evolu.restore_owner(mnemonic)
        self.evolu.worker.flush()
        self.evolu.update_db_schema(SCHEMA)  # the reference re-runs it on reload
        self.evolu.worker.flush()
        if self.synced:
            self.evolu.sync()
        self._bump()

    def reset(self) -> None:
        self.evolu.reset_owner()
        self.evolu.worker.flush()
        self.evolu.update_db_schema(SCHEMA)
        self.evolu.worker.flush()
        self._bump()

    def dispose(self):
        self.todos.dispose()
        self.cats.dispose()
        self.evolu.dispose()


class _Handler(BaseHTTPRequestHandler):
    app: DemoApp

    def log_message(self, *a):
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/" or self.path.startswith("/index"):
            body = PAGE.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path.startswith("/api/state"):
            query = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
            try:
                since = int(query.get("since", ["-1"])[0])
            except ValueError:
                since = -1
            self._json(self.app.state(since))
        else:
            self.send_error(404)

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        try:
            body = json.loads(self.rfile.read(length) or b"{}")
            if self.path == "/api/mutate":
                self._json({"id": self.app.mutate(body["table"], body["values"])})
            elif self.path == "/api/restore":
                self.app.restore(body["mnemonic"])
                self._json({"ok": True})
            elif self.path == "/api/reset":
                self.app.reset()
                self._json({"ok": True})
            elif self.path == "/api/sync":
                self.app.evolu.sync()
                self._json({"ok": True})
            else:
                self.send_error(404)
        except Exception as e:  # noqa: BLE001 - surface to the page
            self._json({"error": str(e)}, code=400)


class DemoServer:
    def __init__(self, app: DemoApp, host="127.0.0.1", port=0):
        handler = type("BoundHandler", (_Handler,), {"app": app})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.app = app
        self._thread = None

    @property
    def url(self):
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="web-todo"
        )
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        if self._thread:
            self._thread.join()
        self._httpd.server_close()
        self.app.dispose()


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--port", type=int, default=8321)
    p.add_argument("--db", default=":memory:")
    p.add_argument("--sync-url", default=None)
    p.add_argument("--restore", default=None, metavar="MNEMONIC")
    args = p.parse_args()
    app = DemoApp(db_path=args.db, sync_url=args.sync_url, mnemonic=args.restore)
    server = DemoServer(app, port=args.port).start()
    print(f"TodoMVC at {server.url}  (owner {app.evolu.owner.id})")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
