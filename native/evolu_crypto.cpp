// libevolu_crypto.so — batched OpenPGP symmetric crypto for the sync
// hot loop (SURVEY.md hot loop #3; reference
// packages/evolu/src/sync.worker.ts:50-91,135-173).
//
// The Python implementation (evolu_tpu/sync/crypto.py) is the
// semantic oracle: correct for every wire shape, but per-message
// Python (~35us/msg, measured r4 — S2K + EVP context churn + packet
// assembly dominate). This layer batches the common path into ONE C
// call per sync leg: protobuf CrdtMessageContent encode, S2K
// (iterated+salted SHA-256), AES-256-CFB, SHA-1 MDC, and packet
// assembly all run in C++ over packed buffers (NUL-safe by
// construction — wire fields may contain NUL, so nothing here is
// char*-terminated). Decrypt handles the canonical shapes this
// framework and OpenPGP.js v5 emit (new-format definite lengths,
// SKESK v4 AES-256 S2K type 0/1/3 SHA-256, SEIPD v1, uncompressed
// literal, canonical content wire types); ANYTHING else — old-format
// headers, partial lengths, compression, legacy SED, wrong password,
// MDC failure, non-canonical protobuf — sets that message's status to
// 1 and the Python oracle re-runs it, preserving the exact error
// surface (PgpError/ValueError) byte for byte.
//
// OpenSSL: the image ships libcrypto.so.3 without dev headers, so the
// needed EVP/RAND prototypes are declared here (stable ABI) and the
// Makefile links the versioned soname directly, mirroring its
// libsqlite3 pattern.

#include <array>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include "wire.h"

// ---- OpenSSL 3 ABI (self-declared; no headers in the image) ----

extern "C" {
typedef struct evp_cipher_ctx_st EVP_CIPHER_CTX;
typedef struct evp_cipher_st EVP_CIPHER;
typedef struct evp_md_ctx_st EVP_MD_CTX;
typedef struct evp_md_st EVP_MD;
typedef struct engine_st ENGINE;

EVP_CIPHER_CTX *EVP_CIPHER_CTX_new(void);
void EVP_CIPHER_CTX_free(EVP_CIPHER_CTX *);
const EVP_CIPHER *EVP_aes_256_cfb128(void);
int EVP_EncryptInit_ex(EVP_CIPHER_CTX *, const EVP_CIPHER *, ENGINE *,
                       const unsigned char *, const unsigned char *);
int EVP_EncryptUpdate(EVP_CIPHER_CTX *, unsigned char *, int *,
                      const unsigned char *, int);
int EVP_DecryptInit_ex(EVP_CIPHER_CTX *, const EVP_CIPHER *, ENGINE *,
                       const unsigned char *, const unsigned char *);
int EVP_DecryptUpdate(EVP_CIPHER_CTX *, unsigned char *, int *,
                      const unsigned char *, int);
// AEAD leg (aead-batch-v1, sync/aead.py): AES-256-GCM + ctrl/final.
const EVP_CIPHER *EVP_aes_256_gcm(void);
int EVP_CIPHER_CTX_ctrl(EVP_CIPHER_CTX *, int, int, void *);
int EVP_EncryptFinal_ex(EVP_CIPHER_CTX *, unsigned char *, int *);
int EVP_DecryptFinal_ex(EVP_CIPHER_CTX *, unsigned char *, int *);

EVP_MD_CTX *EVP_MD_CTX_new(void);
void EVP_MD_CTX_free(EVP_MD_CTX *);
const EVP_MD *EVP_sha256(void);
const EVP_MD *EVP_sha1(void);
int EVP_DigestInit_ex(EVP_MD_CTX *, const EVP_MD *, ENGINE *);
int EVP_DigestUpdate(EVP_MD_CTX *, const void *, size_t);
int EVP_DigestFinal_ex(EVP_MD_CTX *, unsigned char *, unsigned int *);

int RAND_bytes(unsigned char *, int);
}

namespace {

// ---- small helpers ----

// proto3 varint of a (two's-complement) 64-bit value; negatives emit
// the 10-byte form — bit-exact with crypto.py's _varint. ONE shared
// implementation with libevolu_host (wire.h).
using ::wire_varint_size;

// New-format OpenPGP packet header length octets (RFC 4880 §4.2.2).
inline size_t pkt_len_size(size_t n) { return n < 192 ? 1 : (n < 8384 ? 2 : 5); }
inline uint8_t *put_pkt_hdr(uint8_t *p, int tag, size_t n) {
  *p++ = uint8_t(0xC0 | tag);
  if (n < 192) {
    *p++ = uint8_t(n);
  } else if (n < 8384) {
    size_t m = n - 192;
    *p++ = uint8_t(192 + (m >> 8));
    *p++ = uint8_t(m & 0xFF);
  } else {
    *p++ = 0xFF;
    *p++ = uint8_t(n >> 24); *p++ = uint8_t(n >> 16);
    *p++ = uint8_t(n >> 8);  *p++ = uint8_t(n);
  }
  return p;
}

// EVP_CIPHER_CTX_ctrl codes (stable across OpenSSL 1.1 / 3.x; the
// AEAD aliases share the GCM values).
constexpr int CTRL_GCM_GET_TAG = 0x10, CTRL_GCM_SET_TAG = 0x11;

struct Ctxs {
  EVP_CIPHER_CTX *cipher = nullptr;
  EVP_MD_CTX *md = nullptr;
  const EVP_CIPHER *aes = nullptr;
  const EVP_MD *sha256 = nullptr;
  const EVP_MD *sha1 = nullptr;
  // aead-batch-v1 state: a dedicated GCM context so the CFB context's
  // reuse pattern is untouched. `gcm_keyed` tracks whether gcm_ctx
  // currently holds `gcm_key` with its AES key schedule expanded — a
  // leg under ONE session key then pays the schedule once and each
  // record re-inits with the nonce alone (the whole point of the
  // per-session key schedule).
  EVP_CIPHER_CTX *gcm_ctx = nullptr;
  const EVP_CIPHER *gcm = nullptr;
  bool gcm_keyed = false;
  uint8_t gcm_key[32] = {0};
  // Per-call HKDF cache: one derivation per distinct session salt per
  // leg (the Python side keeps the cross-call cache). `last_salt` is
  // the hot lane: a leg's records virtually always share ONE session
  // salt, so the per-record cost is a 16-byte compare, not a string
  // key + map probe.
  std::unordered_map<std::string, std::array<uint8_t, 32>> aead_keys;
  uint8_t last_salt[16] = {0};
  uint8_t last_key[32] = {0};
  bool has_last_salt = false;
  bool ok() const { return cipher && md && aes && sha256 && sha1 && gcm_ctx && gcm; }
  Ctxs() {
    cipher = EVP_CIPHER_CTX_new();
    md = EVP_MD_CTX_new();
    aes = EVP_aes_256_cfb128();
    sha256 = EVP_sha256();
    sha1 = EVP_sha1();
    gcm_ctx = EVP_CIPHER_CTX_new();
    gcm = EVP_aes_256_gcm();
  }
  ~Ctxs() {
    if (cipher) EVP_CIPHER_CTX_free(cipher);
    if (md) EVP_MD_CTX_free(md);
    if (gcm_ctx) EVP_CIPHER_CTX_free(gcm_ctx);
  }
};

// RFC 4880 §3.7.1.3 iterated+salted S2K (SHA-256 → exactly the 32-byte
// AES-256 key, single context). Incremental so an adversarial wire
// count byte (up to ~65MB of hashing) never materializes a buffer.
bool s2k_iterated(Ctxs &cx, const uint8_t *pw, size_t pw_len,
                  const uint8_t *salt, int count_byte, uint8_t key_out[32]) {
  uint64_t count = uint64_t(16 + (count_byte & 15)) << ((count_byte >> 4) + 6);
  std::vector<uint8_t> data(8 + pw_len);
  memcpy(data.data(), salt, 8);
  memcpy(data.data() + 8, pw, pw_len);
  uint64_t total = count > data.size() ? count : data.size();
  if (!EVP_DigestInit_ex(cx.md, cx.sha256, nullptr)) return false;
  uint64_t full = total / data.size(), rem = total % data.size();
  for (uint64_t i = 0; i < full; i++)
    if (!EVP_DigestUpdate(cx.md, data.data(), data.size())) return false;
  if (rem && !EVP_DigestUpdate(cx.md, data.data(), size_t(rem))) return false;
  unsigned int out_len = 0;
  uint8_t digest[32];
  if (!EVP_DigestFinal_ex(cx.md, digest, &out_len) || out_len != 32) return false;
  memcpy(key_out, digest, 32);
  return true;
}

// §3.7.1.2 salted / §3.7.1.1 simple (accepted on decrypt, never produced).
bool s2k_salted(Ctxs &cx, const uint8_t *pw, size_t pw_len,
                const uint8_t *salt /* null = simple */, uint8_t key_out[32]) {
  if (!EVP_DigestInit_ex(cx.md, cx.sha256, nullptr)) return false;
  if (salt && !EVP_DigestUpdate(cx.md, salt, 8)) return false;
  if (!EVP_DigestUpdate(cx.md, pw, pw_len)) return false;
  unsigned int out_len = 0;
  uint8_t digest[32];
  if (!EVP_DigestFinal_ex(cx.md, digest, &out_len) || out_len != 32) return false;
  memcpy(key_out, digest, 32);
  return true;
}

bool sha1_oneshot(Ctxs &cx, const uint8_t *data, size_t n, uint8_t out[20]) {
  if (!EVP_DigestInit_ex(cx.md, cx.sha1, nullptr)) return false;
  if (!EVP_DigestUpdate(cx.md, data, n)) return false;
  unsigned int out_len = 0;
  if (!EVP_DigestFinal_ex(cx.md, out, &out_len) || out_len != 20) return false;
  return true;
}

// ---- aead-batch-v1 (sync/aead.py — the v2 record format) ----
//
//   [0]  magic 0x45 0x32 0x01 ("E2" + version; bit 7 of byte 0 is
//        clear, so v2 records and OpenPGP packet streams are
//        structurally disjoint — decrypt_one dispatches on it)
//   [3]  salt[16] (HKDF session salt)  [19] nonce[12]
//   [31] AES-256-GCM ciphertext ‖ tag[16]
// Plaintext = the CrdtMessageContent protobuf (same bytes the v1
// literal packet carries).

constexpr size_t AEAD_SALT = 16, AEAD_NONCE = 12, AEAD_TAG = 16;
constexpr size_t AEAD_OVERHEAD = 3 + AEAD_SALT + AEAD_NONCE + AEAD_TAG;  // 47
// MUST match sync/aead.py::HKDF_INFO byte for byte.
constexpr char AEAD_HKDF_INFO[] = "evolu-tpu aead-batch-v1 key";

inline bool is_aead_record(const uint8_t *d, size_t n) {
  return n >= 3 && d[0] == 0x45 && d[1] == 0x32 && d[2] == 0x01;
}

// HMAC-SHA-256 over (m1 ‖ m2), hand-rolled on the digest ABI (the
// legacy HMAC() one-shot is deprecated in OpenSSL 3 and the EVP_MAC
// API does not exist in 1.1 — the block construction is version-proof).
bool hmac_sha256(Ctxs &cx, const uint8_t *key, size_t key_len,
                 const uint8_t *m1, size_t l1, const uint8_t *m2, size_t l2,
                 uint8_t out[32]) {
  uint8_t k0[64] = {0};
  if (key_len > 64) {
    unsigned int dl = 0;
    if (!EVP_DigestInit_ex(cx.md, cx.sha256, nullptr) ||
        !EVP_DigestUpdate(cx.md, key, key_len) ||
        !EVP_DigestFinal_ex(cx.md, k0, &dl) || dl != 32)
      return false;
  } else {
    memcpy(k0, key, key_len);
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; i++) { ipad[i] = k0[i] ^ 0x36; opad[i] = k0[i] ^ 0x5C; }
  uint8_t inner[32];
  unsigned int dl = 0;
  if (!EVP_DigestInit_ex(cx.md, cx.sha256, nullptr) ||
      !EVP_DigestUpdate(cx.md, ipad, 64) ||
      (l1 && !EVP_DigestUpdate(cx.md, m1, l1)) ||
      (l2 && !EVP_DigestUpdate(cx.md, m2, l2)) ||
      !EVP_DigestFinal_ex(cx.md, inner, &dl) || dl != 32)
    return false;
  if (!EVP_DigestInit_ex(cx.md, cx.sha256, nullptr) ||
      !EVP_DigestUpdate(cx.md, opad, 64) ||
      !EVP_DigestUpdate(cx.md, inner, 32) ||
      !EVP_DigestFinal_ex(cx.md, out, &dl) || dl != 32)
    return false;
  return true;
}

// RFC 5869, one 32-byte block: PRK = HMAC(salt, secret);
// OKM = HMAC(PRK, info ‖ 0x01). Bit-identical to aead.hkdf_sha256.
bool hkdf_sha256(Ctxs &cx, const uint8_t *secret, size_t secret_len,
                 const uint8_t *salt16, uint8_t out[32]) {
  uint8_t prk[32];
  if (!hmac_sha256(cx, salt16, AEAD_SALT, secret, secret_len, nullptr, 0, prk))
    return false;
  static const uint8_t one = 1;
  return hmac_sha256(cx, prk, 32,
                     reinterpret_cast<const uint8_t *>(AEAD_HKDF_INFO),
                     sizeof(AEAD_HKDF_INFO) - 1, &one, 1, out);
}

// Session key for a record's salt, HKDF'd once per distinct salt per
// call (the cross-call cache lives in Python, keyed the same way).
bool aead_key_for(Ctxs &cx, const uint8_t *pw, size_t pw_len,
                  const uint8_t *salt16, uint8_t out[32]) {
  if (cx.has_last_salt && memcmp(cx.last_salt, salt16, AEAD_SALT) == 0) {
    memcpy(out, cx.last_key, 32);
    return true;
  }
  std::string k(reinterpret_cast<const char *>(salt16), AEAD_SALT);
  auto it = cx.aead_keys.find(k);
  if (it == cx.aead_keys.end()) {
    std::array<uint8_t, 32> key;
    if (!hkdf_sha256(cx, pw, pw_len, salt16, key.data())) return false;
    it = cx.aead_keys.emplace(std::move(k), key).first;
  }
  memcpy(cx.last_salt, salt16, AEAD_SALT);
  memcpy(cx.last_key, it->second.data(), 32);
  cx.has_last_salt = true;
  memcpy(out, it->second.data(), 32);
  return true;
}

// (Re)key the GCM context only when the session key changes; records
// under the current key re-init with the nonce alone (no AES key
// schedule). `enc` selects direction — a call only ever runs one.
bool gcm_ready(Ctxs &cx, const uint8_t key[32], const uint8_t *nonce, bool enc) {
  if (!cx.gcm_keyed || memcmp(cx.gcm_key, key, 32) != 0) {
    int ok = enc ? EVP_EncryptInit_ex(cx.gcm_ctx, cx.gcm, nullptr, key, nonce)
                 : EVP_DecryptInit_ex(cx.gcm_ctx, cx.gcm, nullptr, key, nonce);
    if (!ok) return false;
    memcpy(cx.gcm_key, key, 32);
    cx.gcm_keyed = true;
    return true;
  }
  return enc ? EVP_EncryptInit_ex(cx.gcm_ctx, nullptr, nullptr, nullptr, nonce)
             : EVP_DecryptInit_ex(cx.gcm_ctx, nullptr, nullptr, nullptr, nonce);
}

// Decrypt + verify ONE v2 record into `plain` (resized to the content
// length). false = demote to the Python oracle (which owns the exact
// PgpError surface for truncation/auth failure).
bool aead_open_record(Ctxs &cx, const uint8_t *msg, size_t clen,
                      const uint8_t *password, size_t pw_len,
                      std::vector<uint8_t> &plain) {
  if (clen < AEAD_OVERHEAD) return false;
  const uint8_t *salt = msg + 3, *nonce = msg + 3 + AEAD_SALT;
  const uint8_t *ct = msg + 3 + AEAD_SALT + AEAD_NONCE;
  size_t ct_len = clen - AEAD_OVERHEAD;
  uint8_t key[32], tag[AEAD_TAG];
  memcpy(tag, msg + clen - AEAD_TAG, AEAD_TAG);
  if (!aead_key_for(cx, password, pw_len, salt, key)) return false;
  if (!gcm_ready(cx, key, nonce, /*enc=*/false)) { cx.gcm_keyed = false; return false; }
  plain.resize(ct_len ? ct_len : 1);
  int len = 0, fl = 0;
  if (ct_len && !EVP_DecryptUpdate(cx.gcm_ctx, plain.data(), &len, ct,
                                   int(ct_len))) {
    cx.gcm_keyed = false;
    return false;
  }
  if (EVP_CIPHER_CTX_ctrl(cx.gcm_ctx, CTRL_GCM_SET_TAG, AEAD_TAG, tag) != 1 ||
      EVP_DecryptFinal_ex(cx.gcm_ctx, plain.data() + len, &fl) != 1 ||
      size_t(len + fl) != ct_len) {
    // A failed final leaves ctx state undefined enough that the next
    // record must re-run the full keyed init.
    cx.gcm_keyed = false;
    return false;
  }
  plain.resize(ct_len);
  return true;
}

// Seal ONE content plaintext as a v2 record into dst (sized c + 47).
bool aead_seal_record(Ctxs &cx, const uint8_t key[32], const uint8_t *salt16,
                      const uint8_t *nonce12, const uint8_t *pt, size_t c,
                      uint8_t *dst) {
  dst[0] = 0x45; dst[1] = 0x32; dst[2] = 0x01;
  memcpy(dst + 3, salt16, AEAD_SALT);
  memcpy(dst + 3 + AEAD_SALT, nonce12, AEAD_NONCE);
  uint8_t *ct = dst + 3 + AEAD_SALT + AEAD_NONCE;
  if (!gcm_ready(cx, key, nonce12, /*enc=*/true)) { cx.gcm_keyed = false; return false; }
  int len = 0, fl = 0;
  if (c && !EVP_EncryptUpdate(cx.gcm_ctx, ct, &len, pt, int(c))) {
    cx.gcm_keyed = false;
    return false;
  }
  if (EVP_EncryptFinal_ex(cx.gcm_ctx, ct + len, &fl) != 1 ||
      size_t(len + fl) != c ||
      EVP_CIPHER_CTX_ctrl(cx.gcm_ctx, CTRL_GCM_GET_TAG, AEAD_TAG, ct + c) != 1) {
    cx.gcm_keyed = false;
    return false;
  }
  return true;
}

// ---- CrdtMessageContent protobuf encode (protocol.py:139-172) ----

// vkind: 0 = None, 1 = str (in blob), 2 = int/bool (ival), 3 = double.
constexpr int64_t INT32_LO = -(int64_t(1) << 31), INT32_HI = (int64_t(1) << 31) - 1;

size_t content_size(const int32_t lens[4], int8_t vkind, int64_t ival) {
  size_t n = 0;
  for (int f = 0; f < 3; f++)
    n += 1 + wire_varint_size(uint64_t(lens[f])) + size_t(lens[f]);
  if (vkind == 1) {
    n += 1 + wire_varint_size(uint64_t(lens[3])) + size_t(lens[3]);
  } else if (vkind == 2) {
    n += 1 + wire_varint_size(uint64_t(ival));  // field 5 or 7, same wire size
  } else if (vkind == 3) {
    n += 1 + 8;
  }
  return n;
}

uint8_t *put_content(uint8_t *p, const uint8_t *strs, const int32_t lens[4],
                     int8_t vkind, int64_t ival, double dval) {
  const uint8_t *s = strs;
  for (int f = 0; f < 3; f++) {
    *p++ = uint8_t(((f + 1) << 3) | 2);
    p = wire_put_varint(p, uint64_t(lens[f]));
    memcpy(p, s, size_t(lens[f]));
    p += lens[f]; s += lens[f];
  }
  if (vkind == 1) {
    *p++ = uint8_t((4 << 3) | 2);
    p = wire_put_varint(p, uint64_t(lens[3]));
    memcpy(p, s, size_t(lens[3]));
    p += lens[3];
  } else if (vkind == 2) {
    *p++ = uint8_t(ival >= INT32_LO && ival <= INT32_HI ? (5 << 3) : (7 << 3));
    p = wire_put_varint(p, uint64_t(ival));
  } else if (vkind == 3) {
    *p++ = uint8_t((6 << 3) | 1);
    uint64_t bits;
    memcpy(&bits, &dval, 8);
    for (int i = 0; i < 8; i++) *p++ = uint8_t(bits >> (8 * i));
  }
  return p;
}

// Per-column twins of content_size/put_content for the aead wire leg
// (its Python packer ships one blob per column — b"".join of per-field
// comprehensions is measurably cheaper than interleaving in a Python
// loop, and the per-message Python share is the binding cost there).
size_t content_size_cols(int32_t tl, int32_t rl, int32_t cl, int32_t sl,
                         int8_t vkind, int64_t ival) {
  size_t n = 1 + wire_varint_size(uint64_t(tl)) + size_t(tl) +
             1 + wire_varint_size(uint64_t(rl)) + size_t(rl) +
             1 + wire_varint_size(uint64_t(cl)) + size_t(cl);
  if (vkind == 1) {
    n += 1 + wire_varint_size(uint64_t(sl)) + size_t(sl);
  } else if (vkind == 2) {
    n += 1 + wire_varint_size(uint64_t(ival));
  } else if (vkind == 3) {
    n += 1 + 8;
  }
  return n;
}

uint8_t *put_str_field(uint8_t *p, int field, const uint8_t *s, int32_t len) {
  *p++ = uint8_t((field << 3) | 2);
  p = wire_put_varint(p, uint64_t(len));
  memcpy(p, s, size_t(len));
  return p + len;
}

uint8_t *put_content_cols(uint8_t *p, const uint8_t *t, int32_t tl,
                          const uint8_t *r, int32_t rl, const uint8_t *c,
                          int32_t cl, const uint8_t *s, int32_t sl,
                          int8_t vkind, int64_t ival, double dval) {
  p = put_str_field(p, 1, t, tl);
  p = put_str_field(p, 2, r, rl);
  p = put_str_field(p, 3, c, cl);
  if (vkind == 1) {
    p = put_str_field(p, 4, s, sl);
  } else if (vkind == 2) {
    *p++ = uint8_t(ival >= INT32_LO && ival <= INT32_HI ? (5 << 3) : (7 << 3));
    p = wire_put_varint(p, uint64_t(ival));
  } else if (vkind == 3) {
    *p++ = uint8_t((6 << 3) | 1);
    uint64_t bits;
    memcpy(&bits, &dval, 8);
    for (int i = 0; i < 8; i++) *p++ = uint8_t(bits >> (8 * i));
  }
  return p;
}

// Exact SKESK‖SEIPD size for a content of `c` bytes.
size_t message_size(size_t c) {
  size_t lit_body = 6 + c;
  size_t lit_pkt = 1 + pkt_len_size(lit_body) + lit_body;
  size_t plain = 18 + lit_pkt + 22;
  size_t seipd_body = 1 + plain;
  return 15 + 1 + pkt_len_size(seipd_body) + seipd_body;
}

// Encrypt ONE CrdtMessageContent into dst (must hold message_size(c)).
// rnd24 = 8 salt + 16 prefix bytes. Returns false on OpenSSL failure.
bool emit_message(Ctxs &cx, const uint8_t *password, size_t pw_len,
                  const uint8_t *rnd24, const uint8_t *strs,
                  const int32_t L[4], int8_t vkind, int64_t ival, double dval,
                  size_t c, std::vector<uint8_t> &plainbuf, uint8_t *dst) {
  static const uint8_t zero_iv[16] = {0};
  const uint8_t *salt = rnd24, *prefix = rnd24 + 8;
  uint8_t key[32];
  if (!s2k_iterated(cx, password, pw_len, salt, 0, key)) return false;

  uint8_t *q = dst;
  // SKESK (tag 3): v4, AES-256, iterated+salted SHA-256, count 0.
  *q++ = 0xC3; *q++ = 13; *q++ = 4; *q++ = 9; *q++ = 3; *q++ = 8;
  memcpy(q, salt, 8); q += 8;
  *q++ = 0;

  // Plaintext body: prefix ‖ repeat ‖ literal ‖ d3 14 ‖ SHA1(MDC).
  size_t lit_body = 6 + c;
  size_t plain = 18 + (1 + pkt_len_size(lit_body) + lit_body) + 22;
  plainbuf.resize(plain);
  uint8_t *b = plainbuf.data();
  memcpy(b, prefix, 16); b += 16;
  b[0] = prefix[14]; b[1] = prefix[15]; b += 2;
  b = put_pkt_hdr(b, 11, lit_body);
  *b++ = 'b'; *b++ = 0; memset(b, 0, 4); b += 4;
  b = put_content(b, strs, L, vkind, ival, dval);
  *b++ = 0xD3; *b++ = 0x14;
  uint8_t mdc[20];
  if (!sha1_oneshot(cx, plainbuf.data(), size_t(b - plainbuf.data()), mdc))
    return false;
  memcpy(b, mdc, 20);

  // SEIPD (tag 18): 0x01 ‖ AES-256-CFB(zero IV) of the body.
  size_t seipd_body = 1 + plain;
  q = put_pkt_hdr(q, 18, seipd_body);
  *q++ = 0x01;
  int enc_len = 0;
  if (!EVP_EncryptInit_ex(cx.cipher, cx.aes, nullptr, key, zero_iv) ||
      !EVP_EncryptUpdate(cx.cipher, q, &enc_len, plainbuf.data(), int(plain)) ||
      size_t(enc_len) != plain)
    return false;
  // Size accounting must be EXACT: the caller sized this slot with
  // message_size(c); any drift between the two is heap corruption,
  // not a recoverable condition — fail the batch cleanly instead.
  return size_t(q + plain - dst) == message_size(c);
}

}  // namespace

// ---- public ABI ----

extern "C" {

void ehc_free(void *p) { free(p); }

// Probe: 1 if OpenSSL primitives are usable in this process.
int ehc_available(void) {
  Ctxs cx;
  return cx.ok() ? 1 : 0;
}

// Encrypt a batch of CrdtMessageContents into OpenPGP SKESK‖SEIPD
// streams (crypto.py:70-83, bit-compatible modulo the random salt and
// prefix). Inputs are packed columns; output is one malloc'd blob of
// per-message records [u32 ct_len][ct bytes], freed with ehc_free.
// Returns 0 on success, nonzero on any failure (caller falls back to
// the Python path wholesale).
int ehc_encrypt_batch(int64_t n, const uint8_t *str_blob, const int32_t *lens4,
                      const int8_t *vkinds, const int64_t *ivals,
                      const double *dvals, const uint8_t *password,
                      int32_t pw_len, uint8_t **out_blob, int64_t *out_len) {
  Ctxs cx;
  if (!cx.ok() || n < 0 || pw_len < 0) return 1;

  // Sizes are exactly computable: SKESK is 15 bytes; the SEIPD body is
  // 1 + 18 + literal_packet + 22.
  std::vector<size_t> clen(static_cast<size_t>(n)), total(static_cast<size_t>(n));
  size_t out_total = 0;
  for (int64_t i = 0; i < n; i++) {
    const int32_t *L = lens4 + 4 * i;
    if (L[0] < 0 || L[1] < 0 || L[2] < 0 || (vkinds[i] == 1 && L[3] < 0)) return 1;
    size_t c = content_size(L, vkinds[i], ivals[i]);
    clen[size_t(i)] = c;
    total[size_t(i)] = message_size(c);
    out_total += 4 + total[size_t(i)];
  }

  uint8_t *out = static_cast<uint8_t *>(malloc(out_total ? out_total : 1));
  if (!out) return 1;
  // One RNG call for the whole batch: 8 salt + 16 prefix per message.
  std::vector<uint8_t> rnd(size_t(n) * 24);
  if (n && !RAND_bytes(rnd.data(), int(rnd.size()))) { free(out); return 1; }

  std::vector<uint8_t> plainbuf;
  const uint8_t *strs = str_blob;
  uint8_t *p = out;
  for (int64_t i = 0; i < n; i++) {
    const int32_t *L = lens4 + 4 * i;
    size_t msg = total[size_t(i)];
    *p++ = uint8_t(msg); *p++ = uint8_t(msg >> 8);
    *p++ = uint8_t(msg >> 16); *p++ = uint8_t(msg >> 24);
    if (!emit_message(cx, password, size_t(pw_len), rnd.data() + 24 * i, strs,
                      L, vkinds[i], ivals[i], dvals[i], clen[size_t(i)], plainbuf,
                      p)) {
      free(out);
      return 1;
    }
    p += msg;
    strs += L[0] + L[1] + L[2] + (vkinds[i] == 1 ? L[3] : 0);
  }
  *out_blob = out;
  *out_len = int64_t(out_total);
  return 0;
}

// Encrypt a batch STRAIGHT INTO SyncRequest wire form: the output is
// the concatenated `messages` field-1 stream of protobuf.proto's
// SyncRequest — per message `0x0A varint(inner)` wrapping
// `EncryptedCrdtMessage{ timestamp=1, content=2 }` — byte-identical
// to protocol.encode_sync_request's messages section. The caller
// appends the userId/nodeId/merkleTree fields (2/3/4) and has the
// whole request body with ZERO per-message Python (sync hot path;
// ts_blob/ts_lens carry the plaintext timestamps).
int ehc_encrypt_wire_batch(int64_t n, const uint8_t *ts_blob,
                           const int32_t *ts_lens, const uint8_t *str_blob,
                           const int32_t *lens4, const int8_t *vkinds,
                           const int64_t *ivals, const double *dvals,
                           const uint8_t *password, int32_t pw_len,
                           uint8_t **out_blob, int64_t *out_len) {
  Ctxs cx;
  if (!cx.ok() || n < 0 || pw_len < 0) return 1;
  std::vector<size_t> clen(static_cast<size_t>(n)), ctsz(static_cast<size_t>(n)),
      inner(static_cast<size_t>(n));
  size_t out_total = 0;
  for (int64_t i = 0; i < n; i++) {
    const int32_t *L = lens4 + 4 * i;
    if (L[0] < 0 || L[1] < 0 || L[2] < 0 || ts_lens[i] < 0 ||
        (vkinds[i] == 1 && L[3] < 0))
      return 1;
    size_t c = content_size(L, vkinds[i], ivals[i]);
    size_t ct = message_size(c);
    size_t in = 1 + wire_varint_size(uint64_t(ts_lens[i])) + size_t(ts_lens[i]) +
                1 + wire_varint_size(ct) + ct;
    clen[size_t(i)] = c;
    ctsz[size_t(i)] = ct;
    inner[size_t(i)] = in;
    out_total += 1 + wire_varint_size(in) + in;
  }
  uint8_t *out = static_cast<uint8_t *>(malloc(out_total ? out_total : 1));
  if (!out) return 1;
  std::vector<uint8_t> rnd(size_t(n) * 24);
  if (n && !RAND_bytes(rnd.data(), int(rnd.size()))) { free(out); return 1; }

  std::vector<uint8_t> plainbuf;
  const uint8_t *strs = str_blob;
  const uint8_t *ts = ts_blob;
  uint8_t *p = out;
  for (int64_t i = 0; i < n; i++) {
    const int32_t *L = lens4 + 4 * i;
    *p++ = 0x0A;  // SyncRequest.messages, field 1, wt 2
    p = wire_put_varint(p, uint64_t(inner[size_t(i)]));
    *p++ = 0x0A;  // EncryptedCrdtMessage.timestamp
    p = wire_put_varint(p, uint64_t(ts_lens[i]));
    memcpy(p, ts, size_t(ts_lens[i]));
    p += ts_lens[i];
    ts += ts_lens[i];
    *p++ = 0x12;  // EncryptedCrdtMessage.content, field 2, wt 2
    p = wire_put_varint(p, uint64_t(ctsz[size_t(i)]));
    if (!emit_message(cx, password, size_t(pw_len), rnd.data() + 24 * i, strs,
                      L, vkinds[i], ivals[i], dvals[i], clen[size_t(i)], plainbuf,
                      p)) {
      free(out);
      return 1;
    }
    p += ctsz[size_t(i)];
    strs += L[0] + L[1] + L[2] + (vkinds[i] == 1 ? L[3] : 0);
  }
  if (size_t(p - out) != out_total) { free(out); return 1; }
  *out_blob = out;
  *out_len = int64_t(out_total);
  return 0;
}

// aead-batch-v1 push leg: encrypt a batch STRAIGHT INTO SyncRequest
// wire form under ONE session key — the v2 twin of
// ehc_encrypt_wire_batch. The key schedule runs once (key32/salt16
// come from the Python-side AeadSession, HKDF'd once per owner per
// session); each message costs one nonce + one small GCM. Inputs are
// per-column blobs (timestamps, tables, rows, columns, string values)
// with per-column length arrays; vkinds/ivals/dvals as in the v1 ABI
// (s_lens[i] is only read when vkinds[i]==1). Output: the concatenated
// `messages` field-1 stream, caller appends fields 2/3/4 (+5).
// Returns 0 on success, nonzero on any failure (→ pure Python path).
int ehc_aead_encrypt_wire_batch(
    int64_t n, const uint8_t *ts_blob, const int32_t *ts_lens,
    const uint8_t *t_blob, const int32_t *t_lens, const uint8_t *r_blob,
    const int32_t *r_lens, const uint8_t *c_blob, const int32_t *c_lens,
    const uint8_t *s_blob, const int32_t *s_lens, const int8_t *vkinds,
    const int64_t *ivals, const double *dvals, const uint8_t *key32,
    const uint8_t *salt16, uint8_t **out_blob, int64_t *out_len) {
  Ctxs cx;
  if (!cx.ok() || n < 0) return 1;
  std::vector<size_t> clen(static_cast<size_t>(n)), inner(static_cast<size_t>(n));
  size_t out_total = 0;
  for (int64_t i = 0; i < n; i++) {
    if (t_lens[i] < 0 || r_lens[i] < 0 || c_lens[i] < 0 || ts_lens[i] < 0 ||
        (vkinds[i] == 1 && s_lens[i] < 0))
      return 1;
    size_t c = content_size_cols(t_lens[i], r_lens[i], c_lens[i],
                                 vkinds[i] == 1 ? s_lens[i] : 0, vkinds[i],
                                 ivals[i]);
    size_t ct = c + AEAD_OVERHEAD;
    size_t in = 1 + wire_varint_size(uint64_t(ts_lens[i])) + size_t(ts_lens[i]) +
                1 + wire_varint_size(ct) + ct;
    clen[size_t(i)] = c;
    inner[size_t(i)] = in;
    out_total += 1 + wire_varint_size(in) + in;
  }
  uint8_t *out = static_cast<uint8_t *>(malloc(out_total ? out_total : 1));
  if (!out) return 1;
  // One RNG call for the whole batch: a 12-byte nonce per record.
  std::vector<uint8_t> rnd(size_t(n) * AEAD_NONCE);
  if (n && !RAND_bytes(rnd.data(), int(rnd.size()))) { free(out); return 1; }

  std::vector<uint8_t> plainbuf;
  const uint8_t *ts = ts_blob, *t = t_blob, *r = r_blob, *cc = c_blob,
                *s = s_blob;
  uint8_t *p = out;
  for (int64_t i = 0; i < n; i++) {
    size_t c = clen[size_t(i)];
    *p++ = 0x0A;  // SyncRequest.messages, field 1, wt 2
    p = wire_put_varint(p, uint64_t(inner[size_t(i)]));
    *p++ = 0x0A;  // EncryptedCrdtMessage.timestamp
    p = wire_put_varint(p, uint64_t(ts_lens[i]));
    memcpy(p, ts, size_t(ts_lens[i]));
    p += ts_lens[i];
    ts += ts_lens[i];
    *p++ = 0x12;  // EncryptedCrdtMessage.content, field 2, wt 2
    p = wire_put_varint(p, uint64_t(c + AEAD_OVERHEAD));
    int32_t sl = vkinds[i] == 1 ? s_lens[i] : 0;
    plainbuf.resize(c ? c : 1);
    uint8_t *end = put_content_cols(plainbuf.data(), t, t_lens[i], r, r_lens[i],
                                    cc, c_lens[i], s, sl, vkinds[i], ivals[i],
                                    dvals[i]);
    if (size_t(end - plainbuf.data()) != c ||
        !aead_seal_record(cx, key32, salt16, rnd.data() + AEAD_NONCE * i,
                          plainbuf.data(), c, p)) {
      free(out);
      return 1;
    }
    p += c + AEAD_OVERHEAD;
    t += t_lens[i]; r += r_lens[i]; cc += c_lens[i];
    if (vkinds[i] == 1) s += s_lens[i];
  }
  if (size_t(p - out) != out_total) { free(out); return 1; }
  *out_blob = out;
  *out_len = int64_t(out_total);
  return 0;
}

}  // extern "C"

// ---- CPython ABI fast lane (aead push encode) ----
//
// Self-declared like the OpenSSL ABI at the top of this file: the .so
// is only ever dlopen'd from inside a CPython process, so these
// symbols resolve from the already-loaded interpreter. The binding
// side (sync/native_crypto.py) calls through ctypes.PyDLL so the GIL
// is HELD for the whole call — mandatory for every function below.
// Why: the Python-side columnar packer costs ~0.9µs/msg (attr access,
// per-string encode, length arrays — more than the ENTIRE C crypto
// leg after the S2K removal). Extracting fields here instead reads
// each str's cached UTF-8 in place (zero-copy for ASCII), turning the
// residual Python share into ~5 C-API calls per message.
// Safety: `ehc_py_abi_probe` verifies the assumed PyObject layout
// (ob_type at offset 8, non-debug non-free-threaded build) against a
// live str before the lane is enabled; any drift disables it and the
// blob ABI above stays the path. Exact types only — a str/int
// subclass or any error demotes the whole batch (return 2) to the
// Python packer, which owns the canonical error surface.

extern "C" {
struct PyObj {
  long long ob_refcnt;  // Py_ssize_t (union in 3.12+, same size/offset)
  void *ob_type;
};
PyObj *PySequence_GetItem(PyObj *, long long);
PyObj *PyObject_GetAttr(PyObj *, PyObj *);
PyObj *PyUnicode_FromString(const char *);
const char *PyUnicode_AsUTF8AndSize(PyObj *, long long *);
long long PyLong_AsLongLong(PyObj *);
double PyFloat_AsDouble(PyObj *);
void Py_DecRef(PyObj *);
PyObj *PyErr_Occurred(void);
void PyErr_Clear(void);
void *PyEval_SaveThread(void);
void PyEval_RestoreThread(void *);
extern char PyUnicode_Type, PyLong_Type, PyFloat_Type, PyBool_Type;
extern char _Py_NoneStruct;
}

namespace {

struct PyRefs {
  std::vector<PyObj *> refs;
  ~PyRefs() {
    for (PyObj *o : refs) Py_DecRef(o);
  }
  PyObj *keep(PyObj *o) {
    if (o) refs.push_back(o);
    return o;
  }
};

// Drop the GIL for a pure-C region (the seal loop touches no Python
// state — only Row fields and the strs' cached UTF-8 buffers, pinned
// alive by PyRefs; str is immutable, so concurrent threads can't
// move the bytes out from under us). Scoped so EVERY exit path —
// including the error returns inside the loop — restores the GIL
// before PyRefs' Py_DecRefs run (reverse destruction order).
struct GilScope {
  void *tstate;
  GilScope() : tstate(PyEval_SaveThread()) {}
  ~GilScope() { PyEval_RestoreThread(tstate); }
};

// Exact-str extraction: → utf8 pointer + BYTE length (the interned
// rep CPython caches on the object — no copy for compact ASCII).
inline bool py_str(PyObj *o, const char **s, long long *n) {
  if (!o || o->ob_type != static_cast<void *>(&PyUnicode_Type)) return false;
  *s = PyUnicode_AsUTF8AndSize(o, n);
  if (!*s) { PyErr_Clear(); return false; }  // lone surrogates etc.
  return true;
}

}  // namespace

extern "C" {

// Layout sanity gate for the self-declared CPython ABI: called with a
// known one-char str; any mismatch (debug build, free-threaded
// layout, future drift) returns nonzero and the binding never uses
// the lane. MUST be called via PyDLL (GIL held).
int ehc_py_abi_probe(PyObj *sample) {
  if (!sample || sample->ob_type != static_cast<void *>(&PyUnicode_Type))
    return 1;
  long long n = 0;
  const char *s = PyUnicode_AsUTF8AndSize(sample, &n);
  if (!s) { PyErr_Clear(); return 2; }
  return (n == 1 && s[0] == 'x') ? 0 : 3;
}

// aead-batch-v1 push leg over the message OBJECTS: extraction +
// content assembly + seal in one GIL-held call. `messages` is the
// CrdtMessage sequence; key32/salt16 from the Python AeadSession.
// Output: the SyncRequest field-1 stream (caller appends fields
// 2/3/4). Returns 0 ok; 2 = shape demotion (any non-exact type,
// int64 overflow, surrogate) → caller falls back to the blob packer.
int ehc_aead_encrypt_push_py(PyObj *messages, int64_t n,
                             const uint8_t *key32, const uint8_t *salt16,
                             uint8_t **out_blob, int64_t *out_len) {
  Ctxs cx;
  if (!cx.ok() || n < 0 || !messages) return 1;
  PyRefs names;
  PyObj *a_ts = names.keep(PyUnicode_FromString("timestamp"));
  PyObj *a_t = names.keep(PyUnicode_FromString("table"));
  PyObj *a_r = names.keep(PyUnicode_FromString("row"));
  PyObj *a_c = names.keep(PyUnicode_FromString("column"));
  PyObj *a_v = names.keep(PyUnicode_FromString("value"));
  if (!a_ts || !a_t || !a_r || !a_c || !a_v) { PyErr_Clear(); return 1; }

  struct Row {
    const char *ts, *t, *r, *c, *s;
    long long tsl, tl, rl, cl, sl;
    int8_t vkind;
    int64_t ival;
    double dval;
  };
  std::vector<Row> rows(static_cast<size_t>(n));
  PyRefs held;  // every attr value stays alive until assembly is done
  held.refs.reserve(static_cast<size_t>(n) * 5 + 1);
  size_t out_total = 0;
  std::vector<size_t> clen(static_cast<size_t>(n)), inner(static_cast<size_t>(n));
  for (int64_t i = 0; i < n; i++) {
    PyObj *m = held.keep(PySequence_GetItem(messages, i));
    if (!m) { PyErr_Clear(); return 2; }
    Row &w = rows[size_t(i)];
    if (!py_str(held.keep(PyObject_GetAttr(m, a_ts)), &w.ts, &w.tsl) ||
        !py_str(held.keep(PyObject_GetAttr(m, a_t)), &w.t, &w.tl) ||
        !py_str(held.keep(PyObject_GetAttr(m, a_r)), &w.r, &w.rl) ||
        !py_str(held.keep(PyObject_GetAttr(m, a_c)), &w.c, &w.cl)) {
      PyErr_Clear();
      return 2;
    }
    PyObj *v = held.keep(PyObject_GetAttr(m, a_v));
    if (!v) { PyErr_Clear(); return 2; }
    void *vt = v->ob_type;
    w.s = nullptr; w.sl = 0; w.ival = 0; w.dval = 0.0;
    if (static_cast<void *>(v) == static_cast<void *>(&_Py_NoneStruct)) {
      w.vkind = 0;
    } else if (vt == static_cast<void *>(&PyUnicode_Type)) {
      if (!py_str(v, &w.s, &w.sl)) return 2;
      w.vkind = 1;
    } else if (vt == static_cast<void *>(&PyLong_Type) ||
               vt == static_cast<void *>(&PyBool_Type)) {
      w.ival = PyLong_AsLongLong(v);
      if (w.ival == -1 && PyErr_Occurred()) { PyErr_Clear(); return 2; }
      w.vkind = 2;
    } else if (vt == static_cast<void *>(&PyFloat_Type)) {
      w.dval = PyFloat_AsDouble(v);
      w.vkind = 3;
    } else {
      return 2;  // exotic value → the Python packer/oracle decides
    }
    size_t c = content_size_cols(int32_t(w.tl), int32_t(w.rl), int32_t(w.cl),
                                 int32_t(w.sl), w.vkind, w.ival);
    size_t ct = c + AEAD_OVERHEAD;
    size_t in = 1 + wire_varint_size(uint64_t(w.tsl)) + size_t(w.tsl) +
                1 + wire_varint_size(ct) + ct;
    clen[size_t(i)] = c;
    inner[size_t(i)] = in;
    out_total += 1 + wire_varint_size(in) + in;
  }

  uint8_t *out = static_cast<uint8_t *>(malloc(out_total ? out_total : 1));
  if (!out) return 1;
  // Extraction is done: the seal loop below is pure C (the Rows point
  // into strs PyRefs keeps alive), so other Python threads may run.
  GilScope gil;
  std::vector<uint8_t> rnd(size_t(n) * AEAD_NONCE);
  if (n && !RAND_bytes(rnd.data(), int(rnd.size()))) { free(out); return 1; }
  std::vector<uint8_t> plainbuf;
  uint8_t *p = out;
  for (int64_t i = 0; i < n; i++) {
    const Row &w = rows[size_t(i)];
    size_t c = clen[size_t(i)];
    *p++ = 0x0A;  // SyncRequest.messages, field 1, wt 2
    p = wire_put_varint(p, uint64_t(inner[size_t(i)]));
    *p++ = 0x0A;  // EncryptedCrdtMessage.timestamp
    p = wire_put_varint(p, uint64_t(w.tsl));
    memcpy(p, w.ts, size_t(w.tsl));
    p += w.tsl;
    *p++ = 0x12;  // EncryptedCrdtMessage.content, field 2, wt 2
    p = wire_put_varint(p, uint64_t(c + AEAD_OVERHEAD));
    plainbuf.resize(c ? c : 1);
    uint8_t *end = put_content_cols(
        plainbuf.data(), reinterpret_cast<const uint8_t *>(w.t), int32_t(w.tl),
        reinterpret_cast<const uint8_t *>(w.r), int32_t(w.rl),
        reinterpret_cast<const uint8_t *>(w.c), int32_t(w.cl),
        reinterpret_cast<const uint8_t *>(w.s), int32_t(w.sl), w.vkind,
        w.ival, w.dval);
    if (size_t(end - plainbuf.data()) != c ||
        !aead_seal_record(cx, key32, salt16, rnd.data() + AEAD_NONCE * i,
                          plainbuf.data(), c, p)) {
      free(out);
      return 1;
    }
    p += c + AEAD_OVERHEAD;
  }
  if (size_t(p - out) != out_total) { free(out); return 1; }
  *out_blob = out;
  *out_len = int64_t(out_total);
  return 0;
}

}  // extern "C"

extern "C" {

namespace {

// New-format definite-length packet walk. Returns false on anything
// the fast path doesn't cover (old format, partial lengths, bounds).
struct Pkt { int tag; const uint8_t *body; size_t len; };

bool read_packets(const uint8_t *d, size_t n, std::vector<Pkt> &out) {
  size_t pos = 0;
  while (pos < n) {
    uint8_t ctb = d[pos++];
    if (!(ctb & 0x80) || !(ctb & 0x40)) return false;
    int tag = ctb & 0x3F;
    if (pos >= n) return false;
    uint8_t first = d[pos++];
    size_t len;
    if (first < 192) {
      len = first;
    } else if (first < 224) {
      if (pos >= n) return false;
      len = (size_t(first - 192) << 8) + d[pos++] + 192;
    } else if (first == 255) {
      if (pos + 4 > n) return false;
      len = (size_t(d[pos]) << 24) | (size_t(d[pos + 1]) << 16) |
            (size_t(d[pos + 2]) << 8) | size_t(d[pos + 3]);
      pos += 4;
    } else {
      return false;  // partial length → Python oracle
    }
    if (len > n - pos) return false;  // overflow-safe: pos <= n
    out.push_back({tag, d + pos, len});
    pos += len;
  }
  return true;
}

// Strict UTF-8 validation matching CPython's decoder: rejects bare
// continuations, overlong encodings, surrogates (U+D800..U+DFFF), and
// code points above U+10FFFF. The columnar receive path commits these
// bytes to SQLite with explicit lengths; anything Python's .decode()
// would reject must bounce the batch to the object path instead.
static bool utf8_ok(const uint8_t *s, size_t n) {
  size_t i = 0;
  while (i < n) {
    uint8_t b = s[i];
    if (b < 0x80) { i++; continue; }
    if (b < 0xC2) return false;  // continuation byte or overlong 2-byte
    if (b < 0xE0) {
      if (i + 1 >= n || (s[i + 1] & 0xC0) != 0x80) return false;
      i += 2;
    } else if (b < 0xF0) {
      if (i + 2 >= n) return false;
      uint8_t b1 = s[i + 1], b2 = s[i + 2];
      if ((b1 & 0xC0) != 0x80 || (b2 & 0xC0) != 0x80) return false;
      if (b == 0xE0 && b1 < 0xA0) return false;   // overlong
      if (b == 0xED && b1 >= 0xA0) return false;  // surrogate
      i += 3;
    } else if (b < 0xF5) {
      if (i + 3 >= n) return false;
      uint8_t b1 = s[i + 1], b2 = s[i + 2], b3 = s[i + 3];
      if ((b1 & 0xC0) != 0x80 || (b2 & 0xC0) != 0x80 || (b3 & 0xC0) != 0x80)
        return false;
      if (b == 0xF0 && b1 < 0x90) return false;   // overlong
      if (b == 0xF4 && b1 >= 0x90) return false;  // > U+10FFFF
      i += 4;
    } else {
      return false;
    }
  }
  return true;
}

// Top-level SyncResponse field-3 (capability) validation, shared by
// both fused response walkers. The pure decoder (_decode_capability)
// decodes every capability entry as strict UTF-8 and raises past 64
// entries; the C walkers used to SKIP field 3 entirely — so a
// response whose capability bytes the pure path rejects decoded
// "successfully" on the fused path (the pinned
// tests/fixtures/fuzz_divergent_response.bin divergence). Returns
// false on exactly the shapes the pure decoder raises for; the caller
// demotes the whole response to the pure decoder, which owns the
// exact ValueError surface. Well-formed capabilities stay skipped
// (the client scans them separately, pre-decrypt).
static bool capability_ok(const uint8_t *body, size_t blen, int &n_caps) {
  if (n_caps >= 64) return false;  // protocol._MAX_CAPABILITIES
  n_caps++;
  return utf8_ok(body, blen);
}

// Canonical-wire-type CrdtMessageContent decode (protocol.py:194-217).
// Any deviation (unexpected wire type on a known field, truncation)
// → false → Python oracle reproduces the exact lenient/strict result.
struct Content {
  const uint8_t *t = nullptr, *r = nullptr, *c = nullptr, *s = nullptr;
  size_t tl = 0, rl = 0, cl = 0, sl = 0;
  int8_t vkind = 0;  // 0 none, 1 str, 2 int, 3 double
  int64_t ival = 0;
  double dval = 0;
};

bool read_varint64(const uint8_t *d, size_t n, size_t &pos, uint64_t &v) {
  v = 0;
  int shift = 0;
  while (true) {
    if (pos >= n) return false;
    uint8_t b = d[pos++];
    // The Python oracle (_read_varint) keeps UNBOUNDED precision: a
    // 10th byte may carry bits ≥ 2^64 into the decoded int, or a
    // continuation that raises "varint too long". Wrapping mod 2^64
    // here would silently diverge (overflowed field keys remapping to
    // real fields, overflowed lengths decoding "successfully") — any
    // 10th byte beyond the single value bit 63 demotes to the oracle.
    if (shift == 63 && (b & 0xFE)) return false;
    v |= uint64_t(b & 0x7F) << shift;
    if (!(b & 0x80)) return true;
    shift += 7;
    if (shift > 63) return false;
  }
}

bool decode_content(const uint8_t *d, size_t n, Content &out) {
  size_t pos = 0;
  while (pos < n) {
    uint64_t key;
    if (!read_varint64(d, n, pos, key)) return false;
    uint64_t field = key >> 3;
    int wt = int(key & 7);
    uint64_t iv = 0;
    const uint8_t *bytes = nullptr;
    size_t blen = 0;
    if (wt == 0) {
      if (!read_varint64(d, n, pos, iv)) return false;
    } else if (wt == 1) {
      if (pos + 8 > n) return false;
      for (int i = 7; i >= 0; i--) iv = (iv << 8) | d[pos + i];
      pos += 8;
    } else if (wt == 2) {
      uint64_t len;
      if (!read_varint64(d, n, pos, len)) return false;
      // Overflow-safe (pos <= n): a 10-byte varint can carry bit 63,
      // and `pos + len` would wrap past the check (r4 review finding —
      // heap over-read on untrusted input).
      if (len > n - pos) return false;
      bytes = d + pos; blen = size_t(len); pos += size_t(len);
    } else if (wt == 5) {
      if (pos + 4 > n) return false;
      pos += 4;
    } else {
      return false;
    }
    switch (field) {
      case 1: if (wt != 2) return false; out.t = bytes; out.tl = blen; break;
      case 2: if (wt != 2) return false; out.r = bytes; out.rl = blen; break;
      case 3: if (wt != 2) return false; out.c = bytes; out.cl = blen; break;
      case 4: if (wt != 2) return false;
        out.vkind = 1; out.s = bytes; out.sl = blen; break;
      case 5: if (wt != 0) return false;
        // int32 truncation exactly as decode_content: low 32 bits,
        // sign-extended.
        out.vkind = 2; out.ival = int64_t(int32_t(uint32_t(iv))); break;
      case 6: if (wt != 1) return false; {
        out.vkind = 3;
        uint64_t bits = iv;
        memcpy(&out.dval, &bits, 8);
        break;
      }
      case 7: if (wt != 0) return false;
        out.vkind = 2; out.ival = int64_t(iv); break;
      default: break;  // unknown fields skipped, any wire type
    }
  }
  return true;
}

// Decrypt ONE canonical SKESK‖SEIPD stream + decode its content.
// false = demote this message to the Python oracle.
bool decrypt_one(Ctxs &cx, const uint8_t *msg, size_t clen,
                 const uint8_t *password, size_t pw_len,
                 std::vector<uint8_t> &plain, std::vector<Pkt> &pkts,
                 std::vector<Pkt> &inner, Content &c) {
  if (is_aead_record(msg, clen)) {
    // aead-batch-v1 record: session-keyed GCM instead of per-message
    // S2K. Every decrypt entry point (batch, fused response, fused
    // columns) gains v2 through this one dispatch; any failure —
    // truncation, bad tag — demotes to the Python oracle, which owns
    // the exact PgpError surface.
    if (!aead_open_record(cx, msg, clen, password, pw_len, plain))
      return false;
    return decode_content(plain.data(), plain.size(), c);
  }
  static const uint8_t zero_iv[16] = {0};
  pkts.clear();
  if (!read_packets(msg, clen, pkts)) return false;
  const Pkt *skesk = nullptr, *seipd = nullptr;
  bool sed = false;
  for (const Pkt &p : pkts) {
    if (p.tag == 3 && !skesk) skesk = &p;
    else if (p.tag == 18 && !seipd) seipd = &p;
    else if (p.tag == 9) sed = true;
  }
  if (!skesk || !seipd || sed) return false;  // legacy SED → oracle

  const uint8_t *sk = skesk->body;
  if (skesk->len < 4 || sk[0] != 4 || sk[1] != 9) return false;
  uint8_t key[32];
  if (sk[2] == 3) {
    if (skesk->len < 13 || sk[3] != 8) return false;
    if (!s2k_iterated(cx, password, pw_len, sk + 4, sk[12], key)) return false;
  } else if (sk[2] == 1) {
    if (skesk->len < 12 || sk[3] != 8) return false;
    if (!s2k_salted(cx, password, pw_len, sk + 4, key)) return false;
  } else if (sk[2] == 0) {
    if (sk[3] != 8) return false;
    if (!s2k_salted(cx, password, pw_len, nullptr, key)) return false;
  } else {
    return false;
  }

  if (seipd->len < 1 + 18 + 22 || seipd->body[0] != 1) return false;
  size_t blen = seipd->len - 1;
  plain.resize(blen);
  int dec_len = 0;
  if (!EVP_DecryptInit_ex(cx.cipher, cx.aes, nullptr, key, zero_iv) ||
      !EVP_DecryptUpdate(cx.cipher, plain.data(), &dec_len, seipd->body + 1,
                         int(blen)) ||
      size_t(dec_len) != blen)
    return false;
  const uint8_t *b = plain.data();
  if (b[16] != b[14] || b[17] != b[15]) return false;  // wrong password → oracle
  if (b[blen - 22] != 0xD3 || b[blen - 21] != 0x14) return false;
  uint8_t mdc[20];
  if (!sha1_oneshot(cx, b, blen - 20, mdc)) return false;
  if (memcmp(mdc, b + blen - 20, 20) != 0) return false;

  inner.clear();
  if (!read_packets(b + 18, blen - 18 - 22, inner)) return false;
  const Pkt *lit = nullptr;
  for (const Pkt &p : inner) {
    if (p.tag == 11) { lit = &p; break; }
    if (p.tag == 8) return false;  // compressed → oracle
  }
  if (!lit || lit->len < 2) return false;
  size_t name_len = lit->body[1];
  if (2 + name_len + 4 > lit->len) return false;
  return decode_content(lit->body + 2 + name_len + 4,
                        lit->len - 2 - name_len - 4, c);
}

// Append a decoded-content record to `out` (the decrypt_batch record
// layout — the Python side shares one parser for both entry points).
void append_content_record(std::string &out, const Content &c) {
  auto put_i32 = [&out](int64_t v) {
    for (int k = 0; k < 4; k++) out.push_back(char(uint64_t(v) >> (8 * k)));
  };
  put_i32(int64_t(c.tl)); put_i32(int64_t(c.rl)); put_i32(int64_t(c.cl));
  put_i32(c.vkind == 1 ? int64_t(c.sl) : -1);
  out.push_back(char(c.vkind));
  for (int k = 0; k < 8; k++) out.push_back(char(uint64_t(c.ival) >> (8 * k)));
  uint64_t dbits;
  memcpy(&dbits, &c.dval, 8);
  for (int k = 0; k < 8; k++) out.push_back(char(dbits >> (8 * k)));
  if (c.tl) out.append(reinterpret_cast<const char *>(c.t), c.tl);
  if (c.rl) out.append(reinterpret_cast<const char *>(c.r), c.rl);
  if (c.cl) out.append(reinterpret_cast<const char *>(c.c), c.cl);
  if (c.vkind == 1 && c.sl) out.append(reinterpret_cast<const char *>(c.s), c.sl);
}

}  // namespace

// Decrypt a batch of OpenPGP streams (packed [len]+bytes via ct_lens)
// on the canonical fast path. statuses[i]: 0 = decoded (record
// appended to out_blob), 1 = fall back to the Python oracle for this
// message. Record layout (unaligned, little-endian):
//   [i32 tlen][i32 rlen][i32 clen][i32 vlen][i8 vkind][i64 ival]
//   [f64 dval][table bytes][row bytes][column bytes][str value bytes]
// vkind: 0 none, 1 str, 2 int, 3 double. Returns 0 unless allocation
// or OpenSSL setup fails entirely (→ caller falls back wholesale).
int ehc_decrypt_batch(int64_t n, const uint8_t *ct_blob, const int32_t *ct_lens,
                      const uint8_t *password, int32_t pw_len,
                      uint8_t *statuses, uint8_t **out_blob, int64_t *out_len) {
  Ctxs cx;
  if (!cx.ok() || n < 0 || pw_len < 0) return 1;
  std::string out;
  out.reserve(size_t(n) * 128);
  std::vector<uint8_t> plain;
  std::vector<Pkt> pkts, inner;
  const uint8_t *ct = ct_blob;

  for (int64_t i = 0; i < n; i++) {
    size_t clen = size_t(ct_lens[i]);
    const uint8_t *msg = ct;
    ct += clen;
    Content c;
    if (decrypt_one(cx, msg, clen, password, size_t(pw_len), plain, pkts,
                    inner, c)) {
      append_content_record(out, c);
      statuses[i] = 0;
    } else {
      statuses[i] = 1;  // → Python oracle at this position
    }
  }

  uint8_t *blob = static_cast<uint8_t *>(malloc(out.size() ? out.size() : 1));
  if (!blob) return 1;
  if (!out.empty()) memcpy(blob, out.data(), out.size());
  *out_blob = blob;
  *out_len = int64_t(out.size());
  return 0;
}

// Parse a whole SyncResponse protobuf AND decrypt its messages in one
// call (the client receive leg: decode_sync_response +
// decrypt_messages fused — per-message Python eliminated for
// canonical rows). Output blob:
//   [i64 n_messages][u32 tree_len]
//   per message: [u8 status][u32 ts_len][ts bytes] then
//     status 0: a decoded-content record (decrypt_batch layout)
//     status 1: [i64 ct_off][u32 ct_len] — the ciphertext span inside
//       `resp` for the Python oracle to re-do at this position.
//   then the merkleTree bytes (tree_len of them) at the TAIL.
// Returns 0 ok; 2 = non-canonical WIRE shape (unknown/unexpected wire
// types, truncation — the caller falls back to the pure decoder
// wholesale, preserving its exact ValueError surface); 1 = internal.
int ehc_decrypt_response(const uint8_t *resp, int64_t resp_len,
                         const uint8_t *password, int32_t pw_len,
                         uint8_t **out_blob, int64_t *out_len) {
  Ctxs cx;
  if (!cx.ok() || resp_len < 0 || pw_len < 0) return 1;
  size_t n_ = size_t(resp_len);
  std::string out(12, '\0');  // n + tree_len placeholders
  int64_t n_msgs = 0;
  const uint8_t *tree = nullptr;
  size_t tree_len = 0;
  std::vector<uint8_t> plain;
  std::vector<Pkt> pkts, inner;

  int n_caps = 0;
  size_t pos = 0;
  while (pos < n_) {
    uint64_t key;
    if (!read_varint64(resp, n_, pos, key)) return 2;
    uint64_t field = key >> 3;
    int wt = int(key & 7);
    if (wt != 2) return 2;  // canonical SyncResponse is all wt-2
    uint64_t len;
    if (!read_varint64(resp, n_, pos, len)) return 2;
    // Overflow-safe (pos <= n_): see decode_content — a 10-byte varint
    // can carry bit 63 and wrap `pos + len` past a naive check,
    // spanning reads beyond the response buffer (r4 review finding).
    if (len > n_ - pos) return 2;
    const uint8_t *body = resp + pos;
    size_t blen = size_t(len);
    pos += blen;
    if (field == 2) {
      tree = body;  // last wins, like the Python decoder
      tree_len = blen;
      continue;
    }
    if (field == 3) {
      // Capabilities: the pure decoder PARSES these (raising on bad
      // UTF-8 / >64 entries); skipping them unvalidated is the pinned
      // fused/pure divergence — reject exactly what it rejects.
      if (!capability_ok(body, blen, n_caps)) return 2;
      continue;
    }
    if (field != 1) continue;  // unknown length-delimited field: skip

    // EncryptedCrdtMessage { timestamp=1, content=2 } — last wins.
    const uint8_t *ts = nullptr, *ct = nullptr;
    size_t ts_len = 0, ct_len = 0;
    size_t mp = 0;
    while (mp < blen) {
      uint64_t mkey;
      if (!read_varint64(body, blen, mp, mkey)) return 2;
      uint64_t mf = mkey >> 3;
      int mwt = int(mkey & 7);
      if (mwt != 2) return 2;  // incl. the varint-content DoS shape
      uint64_t mlen;
      if (!read_varint64(body, blen, mp, mlen)) return 2;
      if (mlen > blen - mp) return 2;  // overflow-safe: mp <= blen
      if (mf == 1) { ts = body + mp; ts_len = size_t(mlen); }
      else if (mf == 2) { ct = body + mp; ct_len = size_t(mlen); }
      mp += size_t(mlen);
    }
    n_msgs++;
    out.push_back('\0');  // status placeholder
    size_t status_at = out.size() - 1;
    uint32_t tl32 = uint32_t(ts_len);
    out.append(reinterpret_cast<const char *>(&tl32), 4);
    if (ts_len) out.append(reinterpret_cast<const char *>(ts), ts_len);
    Content c;
    if (ct && decrypt_one(cx, ct, ct_len, password, size_t(pw_len), plain,
                          pkts, inner, c)) {
      append_content_record(out, c);
    } else {
      out[status_at] = 1;
      int64_t off = ct ? int64_t(ct - resp) : 0;
      uint32_t cl32 = uint32_t(ct_len);
      out.append(reinterpret_cast<const char *>(&off), 8);
      out.append(reinterpret_cast<const char *>(&cl32), 4);
    }
  }
  memcpy(&out[0], &n_msgs, 8);
  uint32_t tl = uint32_t(tree_len);
  memcpy(&out[8], &tl, 4);
  if (tree_len) out.append(reinterpret_cast<const char *>(tree), tree_len);

  uint8_t *blob = static_cast<uint8_t *>(malloc(out.size() ? out.size() : 1));
  if (!blob) return 1;
  memcpy(blob, out.data(), out.size());
  *out_blob = blob;
  *out_len = int64_t(out.size());
  return 0;
}

// (utf8_ok lives in the anonymous namespace above, next to the
// response walkers' shared capability validation.)

// Columnar twin of ehc_decrypt_response for the fused receive→apply
// path (reference sync.worker.ts:135-173 → receive.ts:144 →
// applyMessages.ts:78 as ONE leg). Succeeds ONLY when every message
// decrypts on the canonical fast path, every timestamp is exactly 46
// ASCII bytes, and every string field (incl. the tree) is strict
// UTF-8 — the Python side then feeds the batch straight into the
// planner and the packed SQLite apply with ZERO per-row objects.
// Cells (table,row,column) are interned in first-appearance order
// (parity with host_parse.intern_cells) so only k unique triples ever
// become Python strings.
// Returns 0 ok; 2 non-canonical wire; 3 some row needs the object
// path (the caller falls back to ehc_decrypt_response, whose per-row
// oracle demotion owns the exact error surface); 1 internal.
// Output blob layout (little-endian, naturally aligned):
//   [i64 n][i64 k][i64 tree_len][i64 vblob_len][i64 cell_blob_len]
//   ivals i64[n]; dvals f64[n];
//   cell_id i32[n]; vlens i32[n]; cell_lens i32[3k];
//   vkinds u8[n] (SQLite bind encoding: 0 null, 1 int, 2 double, 3 text)
//   ts_slab u8[46*n]; vblob; cell_blob; tree
int ehc_decrypt_response_columns(const uint8_t *resp, int64_t resp_len,
                                 const uint8_t *password, int32_t pw_len,
                                 uint8_t **out_blob, int64_t *out_len) {
  Ctxs cx;
  if (!cx.ok() || resp_len < 0 || pw_len < 0) return 1;
  size_t n_ = size_t(resp_len);
  const uint8_t *tree = nullptr;
  size_t tree_len = 0;
  std::vector<uint8_t> plain;
  std::vector<Pkt> pkts, inner;

  std::vector<int64_t> ivals;
  std::vector<double> dvals;
  std::vector<int32_t> cell_ids, vlens, cell_lens;
  std::string vkinds, ts_slab, vblob, cell_blob;
  std::unordered_map<std::string, int32_t> intern;
  // Cold syncs intern ~one cell per row: pre-size for the worst case
  // (a v2 record is ≥90 wire bytes) so the map never rehashes
  // mid-batch — rehash churn measured as a visible share of the
  // unique-cell decode.
  intern.reserve(size_t(resp_len / 90) + 8);
  std::string keybuf;

  int n_caps = 0;
  size_t pos = 0;
  while (pos < n_) {
    uint64_t key;
    if (!read_varint64(resp, n_, pos, key)) return 2;
    uint64_t field = key >> 3;
    int wt = int(key & 7);
    if (wt != 2) return 2;  // canonical SyncResponse is all wt-2
    uint64_t len;
    if (!read_varint64(resp, n_, pos, len)) return 2;
    if (len > n_ - pos) return 2;  // overflow-safe: pos <= n_
    const uint8_t *body = resp + pos;
    size_t blen = size_t(len);
    pos += blen;
    if (field == 2) {
      tree = body;  // last wins, like the Python decoder
      tree_len = blen;
      continue;
    }
    if (field == 3) {
      // Same capability validation as ehc_decrypt_response — the pure
      // decoder raises on bad UTF-8 / >64 entries, so the fused path
      // must never succeed on those shapes.
      if (!capability_ok(body, blen, n_caps)) return 2;
      continue;
    }
    if (field != 1) continue;  // unknown length-delimited field: skip

    // EncryptedCrdtMessage { timestamp=1, content=2 } — last wins.
    const uint8_t *ts = nullptr, *ct = nullptr;
    size_t ts_len = 0, ct_len = 0;
    size_t mp = 0;
    while (mp < blen) {
      uint64_t mkey;
      if (!read_varint64(body, blen, mp, mkey)) return 2;
      uint64_t mf = mkey >> 3;
      int mwt = int(mkey & 7);
      if (mwt != 2) return 2;
      uint64_t mlen;
      if (!read_varint64(body, blen, mp, mlen)) return 2;
      if (mlen > blen - mp) return 2;  // overflow-safe: mp <= blen
      if (mf == 1) { ts = body + mp; ts_len = size_t(mlen); }
      else if (mf == 2) { ct = body + mp; ct_len = size_t(mlen); }
      mp += size_t(mlen);
    }
    // The packed apply path assumes fixed-width canonical timestamps;
    // ASCII also guarantees the (rare) later string materialization
    // decodes losslessly.
    if (ts_len != 46) return 3;
    for (size_t j = 0; j < 46; j++)
      if (ts[j] >= 0x80) return 3;
    Content c;
    if (!ct || !decrypt_one(cx, ct, ct_len, password, size_t(pw_len), plain,
                            pkts, inner, c))
      return 3;  // any demoted row → whole batch takes the object path

    // Intern the cell; validate UTF-8 once per unique triple.
    keybuf.clear();
    uint32_t tl32 = uint32_t(c.tl), rl32 = uint32_t(c.rl);
    keybuf.append(reinterpret_cast<const char *>(&tl32), 4);
    keybuf.append(reinterpret_cast<const char *>(&rl32), 4);
    if (c.tl) keybuf.append(reinterpret_cast<const char *>(c.t), c.tl);
    if (c.rl) keybuf.append(reinterpret_cast<const char *>(c.r), c.rl);
    if (c.cl) keybuf.append(reinterpret_cast<const char *>(c.c), c.cl);
    // try_emplace hashes once for both the hit and the miss lane
    // (find+emplace double-hashed every unique cell).
    auto ins = intern.try_emplace(keybuf, int32_t(intern.size()));
    int32_t cid = ins.first->second;
    if (ins.second) {  // newly interned triple
      if (!utf8_ok(c.t, c.tl) || !utf8_ok(c.r, c.rl) || !utf8_ok(c.c, c.cl))
        return 3;  // whole batch → object path; the map dies with us
      cell_lens.push_back(int32_t(c.tl));
      cell_lens.push_back(int32_t(c.rl));
      cell_lens.push_back(int32_t(c.cl));
      if (c.tl) cell_blob.append(reinterpret_cast<const char *>(c.t), c.tl);
      if (c.rl) cell_blob.append(reinterpret_cast<const char *>(c.r), c.rl);
      if (c.cl) cell_blob.append(reinterpret_cast<const char *>(c.c), c.cl);
    }
    cell_ids.push_back(cid);
    ts_slab.append(reinterpret_cast<const char *>(ts), 46);
    // Content vkind (0 none, 1 str, 2 int, 3 double) → the SQLite bind
    // encoding shared with eh_apply_planned_packed (0 null, 1 int,
    // 2 double, 3 text).
    switch (c.vkind) {
      case 1:
        if (!utf8_ok(c.s, c.sl)) return 3;
        vkinds.push_back(char(3));
        vlens.push_back(int32_t(c.sl));
        if (c.sl) vblob.append(reinterpret_cast<const char *>(c.s), c.sl);
        break;
      case 2: vkinds.push_back(char(1)); vlens.push_back(0); break;
      case 3: vkinds.push_back(char(2)); vlens.push_back(0); break;
      default: vkinds.push_back(char(0)); vlens.push_back(0); break;
    }
    ivals.push_back(c.ival);
    dvals.push_back(c.dval);
  }
  if (tree_len && !utf8_ok(tree, tree_len)) return 3;

  int64_t n = int64_t(cell_ids.size());
  int64_t k = int64_t(intern.size());
  int64_t header[5] = {n, k, int64_t(tree_len), int64_t(vblob.size()),
                       int64_t(cell_blob.size())};
  size_t total = sizeof(header) + size_t(n) * (8 + 8 + 4 + 4 + 1) +
                 size_t(k) * 12 + ts_slab.size() + vblob.size() +
                 cell_blob.size() + tree_len;
  uint8_t *blob = static_cast<uint8_t *>(malloc(total ? total : 1));
  if (!blob) return 1;
  uint8_t *p = blob;
  auto put = [&p](const void *src, size_t len) {
    if (len) memcpy(p, src, len);
    p += len;
  };
  put(header, sizeof(header));
  put(ivals.data(), size_t(n) * 8);
  put(dvals.data(), size_t(n) * 8);
  put(cell_ids.data(), size_t(n) * 4);
  put(vlens.data(), size_t(n) * 4);
  put(cell_lens.data(), size_t(k) * 12);
  put(vkinds.data(), vkinds.size());
  put(ts_slab.data(), ts_slab.size());
  put(vblob.data(), vblob.size());
  put(cell_blob.data(), cell_blob.size());
  put(tree, tree_len);
  *out_blob = blob;
  *out_len = int64_t(total);
  return 0;
}

}  // extern "C"
