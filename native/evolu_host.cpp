// evolu_host — C++ SQLite host layer for the TPU framework.
//
// The reference's only native code is SQLite itself (vendored twice:
// wa-sqlite in the browser, better-sqlite3 on the server — SURVEY.md
// §2.14). This library plays the same role for the Python runtime: the
// storage engine is the real SQLite C library driven from C++, and the
// merge hot path — the reference's per-message applyMessages loop
// (packages/evolu/src/applyMessages.ts:26-131) — runs entirely inside
// one C call per batch, with prepared-statement caching like the
// reference's per-SQL cache (applyMessages.ts:46-73).
//
// The image ships libsqlite3.so.0 but no sqlite3.h, so the handful of
// C-API entry points used here are declared directly; the SQLite C ABI
// is stable and these signatures match https://sqlite.org/c3ref.
//
// Exported surface (C ABI, driven from Python via ctypes):
//   eh_open/eh_close/eh_errmsg/eh_exec/eh_changes/eh_total_changes
//   eh_prepare/eh_finalize/eh_bind_*/eh_step/eh_reset/eh_column_*
//   eh_fetch_winners   — batched per-cell winner lookup
//   eh_apply_sequential — the reference loop (winner check + app-table
//                         upsert + __message insert), masks out
//   eh_apply_planned_packed — apply a device-computed plan (upsert mask)
//
// Value passing: each message value arrives as (kind, int64, double,
// text, blob_len) where kind ∈ {0:null, 1:int64, 2:double, 3:text,
// 4:blob} — no string round-trip for numerics, preserving SQLite
// storage classes byte-for-byte vs the Python backend.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>
#include <vector>

#include "wire.h"

extern "C" {

// --- SQLite C ABI (subset) ---
typedef struct sqlite3 sqlite3;
typedef struct sqlite3_stmt sqlite3_stmt;
typedef int64_t sqlite3_int64;

int sqlite3_open_v2(const char *filename, sqlite3 **db, int flags, const char *vfs);
int sqlite3_close_v2(sqlite3 *);
int sqlite3_exec(sqlite3 *, const char *sql, int (*cb)(void *, int, char **, char **),
                 void *, char **errmsg);
void sqlite3_free(void *);
int sqlite3_prepare_v2(sqlite3 *, const char *sql, int nbyte, sqlite3_stmt **, const char **tail);
int sqlite3_finalize(sqlite3_stmt *);
int sqlite3_step(sqlite3_stmt *);
int sqlite3_reset(sqlite3_stmt *);
int sqlite3_clear_bindings(sqlite3_stmt *);
int sqlite3_bind_null(sqlite3_stmt *, int);
int sqlite3_bind_int64(sqlite3_stmt *, int, sqlite3_int64);
int sqlite3_bind_double(sqlite3_stmt *, int, double);
int sqlite3_bind_text(sqlite3_stmt *, int, const char *, int n, void (*)(void *));
int sqlite3_bind_blob(sqlite3_stmt *, int, const void *, int n, void (*)(void *));
int sqlite3_column_count(sqlite3_stmt *);
const char *sqlite3_column_name(sqlite3_stmt *, int);
int sqlite3_column_type(sqlite3_stmt *, int);
sqlite3_int64 sqlite3_column_int64(sqlite3_stmt *, int);
double sqlite3_column_double(sqlite3_stmt *, int);
const unsigned char *sqlite3_column_text(sqlite3_stmt *, int);
const void *sqlite3_column_blob(sqlite3_stmt *, int);
int sqlite3_column_bytes(sqlite3_stmt *, int);
int sqlite3_changes(sqlite3 *);
int sqlite3_total_changes(sqlite3 *);
const char *sqlite3_errmsg(sqlite3 *);

}  // extern "C"

#define SQLITE_OK 0
#define SQLITE_ROW 100
#define SQLITE_DONE 101
#define SQLITE_OPEN_READWRITE 0x00000002
#define SQLITE_OPEN_CREATE 0x00000004
#define SQLITE_OPEN_URI 0x00000040
#define SQLITE_TRANSIENT ((void (*)(void *))(intptr_t)-1)
#define SQLITE_INTEGER 1
#define SQLITE_FLOAT 2
#define SQLITE_TEXT 3
#define SQLITE_BLOB 4
#define SQLITE_NULL 5
// For the batched entry points the caller's buffers outlive the whole
// C call (ctypes arrays hold them), so SQLITE_STATIC avoids a copy per
// bind; each row is stepped and reset before buffers change.
#define SQLITE_STATIC ((void (*)(void *))0)

namespace {

// Bind one (kind, int, real, text/blob bytes, byte_len) value at `pos`.
// TEXT uses the explicit byte length too — values may contain NUL
// bytes, which must round-trip identically to the Python backend.
int bind_value(sqlite3_stmt *st, int pos, int kind, int64_t iv, double dv,
               const char *sv, int byte_len) {
  switch (kind) {
    case 1: return sqlite3_bind_int64(st, pos, iv);
    case 2: return sqlite3_bind_double(st, pos, dv);
    case 3: return sqlite3_bind_text(st, pos, sv, byte_len, SQLITE_TRANSIENT);
    case 4: return sqlite3_bind_blob(st, pos, sv, byte_len, SQLITE_TRANSIENT);
    default: return sqlite3_bind_null(st, pos);
  }
}

// Like bind_value, but the caller's buffers outlive the statement step
// (packed batch entry points), so SQLITE_STATIC skips the copy.
int bind_value_static(sqlite3_stmt *st, int pos, int kind, int64_t iv, double dv,
                      const char *sv, int byte_len) {
  switch (kind) {
    case 1: return sqlite3_bind_int64(st, pos, iv);
    case 2: return sqlite3_bind_double(st, pos, dv);
    case 3: return sqlite3_bind_text(st, pos, sv, byte_len, SQLITE_STATIC);
    case 4: return sqlite3_bind_blob(st, pos, sv, byte_len, SQLITE_STATIC);
    default: return sqlite3_bind_null(st, pos);
  }
}

// Per-batch prepared-statement cache keyed by SQL — the reference's
// cacheGet/cacheRelease (applyMessages.ts:46-73), scoped to one call.
struct StmtCache {
  sqlite3 *db;
  std::map<std::string, sqlite3_stmt *> cache;
  explicit StmtCache(sqlite3 *d) : db(d) {}
  ~StmtCache() {
    for (auto &kv : cache) sqlite3_finalize(kv.second);
  }
  sqlite3_stmt *get(const std::string &sql) {
    auto it = cache.find(sql);
    if (it != cache.end()) return it->second;
    sqlite3_stmt *st = nullptr;
    if (sqlite3_prepare_v2(db, sql.c_str(), -1, &st, nullptr) != SQLITE_OK) return nullptr;
    cache.emplace(sql, st);
    return st;
  }
};

std::string quote_ident(const char *name) {
  // "name" with embedded quotes doubled (identifiers come from the
  // app schema; quoting matches the Python backend's _upsert_sql).
  std::string out = "\"";
  for (const char *p = name; *p; ++p) {
    out += *p;
    if (*p == '"') out += '"';
  }
  out += '"';
  return out;
}

std::string upsert_sql(const char *table, const char *column) {
  // applyMessages.ts:92-103
  std::string t = quote_ident(table), c = quote_ident(column);
  // Explicit conflict target: targetless DO UPDATE needs SQLite >=
  // 3.35; ON CONFLICT("id") works on every 3.24+. Same text in
  // storage/apply.py::_upsert_sql.
  return "INSERT INTO " + t + " (\"id\", " + c + ") VALUES (?, ?) "
         "ON CONFLICT(\"id\") DO UPDATE SET " + c + " = ?";
}

constexpr const char *kSelectWinner =
    "SELECT \"timestamp\" FROM \"__message\" "
    "WHERE \"table\" = ? AND \"row\" = ? AND \"column\" = ? "
    "ORDER BY \"timestamp\" DESC LIMIT 1";

constexpr const char *kInsertMessage =
    "INSERT INTO \"__message\" (\"timestamp\", \"table\", \"row\", \"column\", \"value\") "
    "VALUES (?, ?, ?, ?, ?) ON CONFLICT DO NOTHING";

int step_done(sqlite3_stmt *st) {
  int rc = sqlite3_step(st);
  sqlite3_reset(st);
  sqlite3_clear_bindings(st);
  return rc == SQLITE_DONE || rc == SQLITE_ROW ? SQLITE_OK : rc;
}

}  // namespace

extern "C" {

sqlite3 *eh_open(const char *path) {
  sqlite3 *db = nullptr;
  int flags = SQLITE_OPEN_READWRITE | SQLITE_OPEN_CREATE | SQLITE_OPEN_URI;
  if (sqlite3_open_v2(path, &db, flags, nullptr) != SQLITE_OK) {
    if (db) sqlite3_close_v2(db);
    return nullptr;
  }
  return db;
}

int eh_close(sqlite3 *db) { return sqlite3_close_v2(db); }

const char *eh_errmsg(sqlite3 *db) { return sqlite3_errmsg(db); }

int eh_exec(sqlite3 *db, const char *sql) {
  return sqlite3_exec(db, sql, nullptr, nullptr, nullptr);
}

int eh_changes(sqlite3 *db) { return sqlite3_changes(db); }
int eh_total_changes(sqlite3 *db) { return sqlite3_total_changes(db); }

// --- generic prepared-statement surface (cold paths, driven from Python) ---

sqlite3_stmt *eh_prepare(sqlite3 *db, const char *sql) {
  sqlite3_stmt *st = nullptr;
  if (sqlite3_prepare_v2(db, sql, -1, &st, nullptr) != SQLITE_OK) return nullptr;
  return st;
}

// Like eh_prepare but rejects trailing statements: *tail_nonempty is
// set when anything but whitespace/semicolons follows the first
// statement (PySqliteDatabase's execute raises there too).
sqlite3_stmt *eh_prepare_single(sqlite3 *db, const char *sql, int *tail_nonempty) {
  sqlite3_stmt *st = nullptr;
  const char *tail = nullptr;
  *tail_nonempty = 0;
  if (sqlite3_prepare_v2(db, sql, -1, &st, &tail) != SQLITE_OK) return nullptr;
  // Skip whitespace, ';', and SQL comments ("--...\n", "/*...*/") —
  // Python's sqlite3.execute accepts those after the statement too.
  const char *p = tail ? tail : "";
  while (*p) {
    if (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r' || *p == ';') {
      ++p;
    } else if (p[0] == '-' && p[1] == '-') {
      while (*p && *p != '\n') ++p;
    } else if (p[0] == '/' && p[1] == '*') {
      p += 2;
      while (*p && !(p[0] == '*' && p[1] == '/')) ++p;
      if (*p) p += 2;
    } else {
      *tail_nonempty = 1;
      break;
    }
  }
  return st;
}

int eh_finalize(sqlite3_stmt *st) { return sqlite3_finalize(st); }
int eh_step(sqlite3_stmt *st) { return sqlite3_step(st); }
int eh_reset(sqlite3_stmt *st) {
  int rc = sqlite3_reset(st);
  sqlite3_clear_bindings(st);
  return rc;
}

int eh_bind(sqlite3_stmt *st, int pos, int kind, int64_t iv, double dv,
            const char *sv, int blob_len) {
  return bind_value(st, pos, kind, iv, dv, sv, blob_len);
}

int eh_column_count(sqlite3_stmt *st) { return sqlite3_column_count(st); }
const char *eh_column_name(sqlite3_stmt *st, int i) { return sqlite3_column_name(st, i); }
int eh_column_type(sqlite3_stmt *st, int i) { return sqlite3_column_type(st, i); }
int64_t eh_column_int64(sqlite3_stmt *st, int i) { return sqlite3_column_int64(st, i); }
double eh_column_double(sqlite3_stmt *st, int i) { return sqlite3_column_double(st, i); }
const unsigned char *eh_column_text(sqlite3_stmt *st, int i) { return sqlite3_column_text(st, i); }
const void *eh_column_blob(sqlite3_stmt *st, int i) { return sqlite3_column_blob(st, i); }
int eh_column_bytes(sqlite3_stmt *st, int i) { return sqlite3_column_bytes(st, i); }

// --- hot path 1: batched winner lookup ---
//
// For each distinct cell i, writes the current winner timestamp into
// out[i] (caller-provided buffer of size out_cap, 0-terminated; empty
// string = no winner). Timestamps are 46 ASCII chars, so out_cap=47.
int eh_fetch_winners(sqlite3 *db, int64_t n, const char *const *tables,
                     const char *const *rows, const char *const *cols,
                     char *out, int64_t out_cap) {
  sqlite3_stmt *st = nullptr;
  if (sqlite3_prepare_v2(db, kSelectWinner, -1, &st, nullptr) != SQLITE_OK) return 1;
  for (int64_t i = 0; i < n; ++i) {
    sqlite3_bind_text(st, 1, tables[i], -1, SQLITE_TRANSIENT);
    sqlite3_bind_text(st, 2, rows[i], -1, SQLITE_TRANSIENT);
    sqlite3_bind_text(st, 3, cols[i], -1, SQLITE_TRANSIENT);
    int rc = sqlite3_step(st);
    char *dst = out + i * out_cap;
    if (rc == SQLITE_ROW) {
      // NULL is possible despite the PK (SQLite's legacy non-INTEGER
      // BLOB PRIMARY KEY quirk allows NULL in tampered/corrupt DBs);
      // treat it as no-winner rather than reading a null pointer.
      const unsigned char *t = sqlite3_column_text(st, 0);
      if (t == nullptr) {
        dst[0] = '\0';
      } else {
        std::strncpy(dst, reinterpret_cast<const char *>(t), out_cap - 1);
        dst[out_cap - 1] = '\0';
      }
    } else if (rc == SQLITE_DONE) {
      dst[0] = '\0';
    } else {
      sqlite3_finalize(st);
      return 1;
    }
    sqlite3_reset(st);
    sqlite3_clear_bindings(st);
  }
  sqlite3_finalize(st);
  return 0;
}

// --- hot path 2: the reference loop, one C call per batch ---
//
// Exactly applyMessages.ts:78-124 per message, inside the caller's
// transaction: winner SELECT; upsert the app table when the message
// beats it; INSERT OR NOTHING into __message and flag the Merkle XOR
// when the winner differs. out_xor[i]=1 marks messages whose hash the
// caller XORs into the tree (host-side sparse trie update).
int eh_apply_sequential(sqlite3 *db, int64_t n, const char *const *timestamps,
                        const char *const *tables, const char *const *rows,
                        const char *const *cols, const int32_t *kinds,
                        const int64_t *ivals, const double *dvals,
                        const char *const *svals, const int32_t *blob_lens,
                        uint8_t *out_xor) {
  StmtCache cache(db);
  sqlite3_stmt *sel = cache.get(kSelectWinner);
  sqlite3_stmt *ins = cache.get(kInsertMessage);
  if (!sel || !ins) return 1;

  for (int64_t i = 0; i < n; ++i) {
    sqlite3_bind_text(sel, 1, tables[i], -1, SQLITE_TRANSIENT);
    sqlite3_bind_text(sel, 2, rows[i], -1, SQLITE_TRANSIENT);
    sqlite3_bind_text(sel, 3, cols[i], -1, SQLITE_TRANSIENT);
    int rc = sqlite3_step(sel);
    bool has_winner = rc == SQLITE_ROW;
    if (!has_winner && rc != SQLITE_DONE) return 1;
    std::string winner;
    if (has_winner) {
      const unsigned char *w = sqlite3_column_text(sel, 0);
      if (w == nullptr)  // tampered DB: NULL in the BLOB PK column
        has_winner = false;
      else
        winner = reinterpret_cast<const char *>(w);
    }
    sqlite3_reset(sel);
    sqlite3_clear_bindings(sel);

    bool newer = !has_winner || winner.compare(timestamps[i]) < 0;
    if (newer) {  // applyMessages.ts:92-103
      sqlite3_stmt *up = cache.get(upsert_sql(tables[i], cols[i]));
      if (!up) return 1;
      sqlite3_bind_text(up, 1, rows[i], -1, SQLITE_TRANSIENT);
      bind_value(up, 2, kinds[i], ivals[i], dvals[i], svals[i], blob_lens[i]);
      bind_value(up, 3, kinds[i], ivals[i], dvals[i], svals[i], blob_lens[i]);
      if (step_done(up) != SQLITE_OK) return 1;
    }
    bool differs = !has_winner || winner.compare(timestamps[i]) != 0;
    out_xor[i] = differs ? 1 : 0;
    if (differs) {  // applyMessages.ts:104-122
      sqlite3_bind_text(ins, 1, timestamps[i], -1, SQLITE_TRANSIENT);
      sqlite3_bind_text(ins, 2, tables[i], -1, SQLITE_TRANSIENT);
      sqlite3_bind_text(ins, 3, rows[i], -1, SQLITE_TRANSIENT);
      sqlite3_bind_text(ins, 4, cols[i], -1, SQLITE_TRANSIENT);
      bind_value(ins, 5, kinds[i], ivals[i], dvals[i], svals[i], blob_lens[i]);
      if (step_done(ins) != SQLITE_OK) return 1;
    }
  }
  return 0;
}

// --- hot path 3: apply a device-computed plan ---
//
// The TPU planner already decided the final winner per cell
// (upsert_mask) and the Merkle XOR set; this applies the SQL side —
// upserts for flagged rows, then the bulk __message insert for ALL
// rows (PK dedup) — inside the caller's transaction.
// Packed variant: each string column arrives as ONE contiguous buffer
// plus per-row byte lengths — no per-row pointer marshalling on the
// Python side, and every bind carries its explicit byte length, so
// embedded NUL bytes in table/row/column round-trip exactly like the
// Python backend (the pointer variant above truncates at NUL).
// Returns 0 ok, 1 SQLite error, 3 NUL inside an upserted identifier
// (the Python backend's quote_ident raises there; whole batch aborts).
int eh_apply_planned_packed(sqlite3 *db, int64_t n,
                            const char *ts_buf, const int32_t *ts_lens,
                            const char *tbl_buf, const int32_t *tbl_lens,
                            const char *row_buf, const int32_t *row_lens,
                            const char *col_buf, const int32_t *col_lens,
                            const int32_t *kinds, const int64_t *ivals,
                            const double *dvals, const char *val_buf,
                            const int32_t *val_lens,
                            const uint8_t *upsert_mask) {
  StmtCache cache(db);
  sqlite3_stmt *ins = cache.get(kInsertMessage);
  if (!ins) return 1;
  int64_t ts_o = 0, tbl_o = 0, row_o = 0, col_o = 0, val_o = 0;
  for (int64_t i = 0; i < n; ++i) {
    const char *ts = ts_buf + ts_o;
    const char *tbl = tbl_buf + tbl_o;
    const char *row = row_buf + row_o;
    const char *col = col_buf + col_o;
    const char *val = val_buf + val_o;
    const int tsl = ts_lens[i], tbll = tbl_lens[i], rowl = row_lens[i],
              coll = col_lens[i], vall = val_lens[i];
    ts_o += tsl; tbl_o += tbll; row_o += rowl; col_o += coll;
    if (kinds[i] == 3 || kinds[i] == 4) val_o += vall;
    if (upsert_mask[i]) {
      if (memchr(tbl, 0, tbll) || memchr(col, 0, coll)) return 3;
      std::string tname(tbl, tbll), cname(col, coll);
      sqlite3_stmt *up = cache.get(upsert_sql(tname.c_str(), cname.c_str()));
      if (!up) return 1;
      sqlite3_bind_text(up, 1, row, rowl, SQLITE_STATIC);
      bind_value_static(up, 2, kinds[i], ivals[i], dvals[i], val, vall);
      bind_value_static(up, 3, kinds[i], ivals[i], dvals[i], val, vall);
      if (step_done(up) != SQLITE_OK) return 1;
    }
    sqlite3_bind_text(ins, 1, ts, tsl, SQLITE_STATIC);
    sqlite3_bind_text(ins, 2, tbl, tbll, SQLITE_STATIC);
    sqlite3_bind_text(ins, 3, row, rowl, SQLITE_STATIC);
    sqlite3_bind_text(ins, 4, col, coll, SQLITE_STATIC);
    bind_value_static(ins, 5, kinds[i], ivals[i], dvals[i], val, vall);
    if (step_done(ins) != SQLITE_OK) return 1;
  }
  return 0;
}


// --- hot path 3b: apply a device-computed plan from INTERNED columns ---
//
// The fused receive leg (ehc_decrypt_response_columns) emits the batch
// as a fixed-width 46-byte timestamp slab plus k unique
// (table,row,column) cells and per-row cell indices; this applies the
// plan straight from those buffers — no per-row string expansion on
// the Python side at all. Semantics are identical to
// eh_apply_planned_packed (upserts for masked rows, bulk __message
// insert for all rows, explicit byte lengths everywhere so embedded
// NULs round-trip). kinds use the bind encoding (0 null, 1 int,
// 2 double, 3 text). Returns 0 ok, 1 SQLite error, 2 bad cell index,
// 3 NUL inside an upserted identifier.
int eh_apply_planned_cells(sqlite3 *db, int64_t n, const char *ts_slab,
                           int64_t k, const char *cell_blob,
                           const int32_t *cell_lens, const int32_t *cell_ids,
                           const uint8_t *kinds, const int64_t *ivals,
                           const double *dvals, const char *val_blob,
                           const int32_t *val_lens,
                           const uint8_t *upsert_mask) {
  StmtCache cache(db);
  sqlite3_stmt *ins = cache.get(kInsertMessage);
  if (!ins) return 1;
  // Per-cell field offsets into cell_blob (k is small: unique cells).
  std::vector<int64_t> coff(size_t(k) * 3 + 1);
  int64_t o = 0;
  for (int64_t j = 0; j < k * 3; ++j) {
    coff[size_t(j)] = o;
    o += cell_lens[j];
  }
  coff[size_t(k) * 3] = o;
  // Upsert statements resolved once per cell, not per row (lazy: most
  // cells in a steady-state batch never win).
  std::vector<sqlite3_stmt *> up_stmt(size_t(k), nullptr);
  std::vector<int8_t> up_state(size_t(k), 0);  // 0 unresolved, 1 ok, 3 NUL

  int64_t val_o = 0;
  for (int64_t i = 0; i < n; ++i) {
    int32_t cid = cell_ids[i];
    if (cid < 0 || int64_t(cid) >= k) return 2;
    const char *tbl = cell_blob + coff[size_t(cid) * 3];
    const char *row = cell_blob + coff[size_t(cid) * 3 + 1];
    const char *col = cell_blob + coff[size_t(cid) * 3 + 2];
    const int tbll = cell_lens[cid * 3], rowl = cell_lens[cid * 3 + 1],
              coll = cell_lens[cid * 3 + 2];
    const char *val = val_blob + val_o;
    const int vall = val_lens[i];
    if (kinds[i] == 3) val_o += vall;
    if (upsert_mask[i]) {
      if (up_state[size_t(cid)] == 0) {
        if (memchr(tbl, 0, tbll) || memchr(col, 0, coll)) {
          up_state[size_t(cid)] = 3;
        } else {
          std::string tname(tbl, tbll), cname(col, coll);
          up_stmt[size_t(cid)] = cache.get(upsert_sql(tname.c_str(), cname.c_str()));
          up_state[size_t(cid)] = up_stmt[size_t(cid)] ? 1 : 2;
        }
      }
      if (up_state[size_t(cid)] == 3) return 3;
      if (up_state[size_t(cid)] != 1) return 1;
      sqlite3_stmt *up = up_stmt[size_t(cid)];
      sqlite3_bind_text(up, 1, row, rowl, SQLITE_STATIC);
      bind_value_static(up, 2, kinds[i], ivals[i], dvals[i], val, vall);
      bind_value_static(up, 3, kinds[i], ivals[i], dvals[i], val, vall);
      if (step_done(up) != SQLITE_OK) return 1;
    }
    sqlite3_bind_text(ins, 1, ts_slab + i * 46, 46, SQLITE_STATIC);
    sqlite3_bind_text(ins, 2, tbl, tbll, SQLITE_STATIC);
    sqlite3_bind_text(ins, 3, row, rowl, SQLITE_STATIC);
    sqlite3_bind_text(ins, 4, col, coll, SQLITE_STATIC);
    bind_value_static(ins, 5, kinds[i], ivals[i], dvals[i], val, vall);
    if (step_done(ins) != SQLITE_OK) return 1;
  }
  return 0;
}

// --- relay hot path: bulk (timestamp, userId, content) insert with
// per-row "was new" flags (INSERT OR IGNORE changes()==1 semantics,
// apps/server/src/index.ts:148-159). content is a blob. ---
// --- packed fixed-width timestamp parse ---
//
// The host-side batch columnarization (ops/host_parse.py) is the same
// loop in numpy; this is its native twin for the hot server/client
// paths (one pass over the packed 46-byte records instead of ~40
// vectorized passes). Validation is identical: exact separators,
// digit ranges with real calendar rules, hex fields accepting both
// cases. out_case_ok[i] = 1 iff the row uses the canonical encoder's
// case (UPPERCASE counter / lowercase node). Returns 0, or 1 on any
// malformed row (callers abort the batch, like the numpy path).

static inline int64_t days_from_civil(int64_t y, int m, int d) {
  y -= m <= 2;
  int64_t era = (y >= 0 ? y : y - 399) / 400;
  int yoe = (int)(y - era * 400);
  int doy = (153 * (m + (m > 2 ? -3 : 9)) + 2) / 5 + d - 1;
  int doe = yoe * 365 + yoe / 4 - yoe / 100 + doy;
  return era * 146097 + doe - 719468;
}

static inline bool is_leap(int64_t y) {
  return (y % 4 == 0 && y % 100 != 0) || y % 400 == 0;
}

int eh_parse_timestamps(const char *ts_packed, int64_t n, int64_t *out_millis,
                        int32_t *out_counter, uint64_t *out_node,
                        uint8_t *out_case_ok) {
  static const int month_days[13] = {0, 31, 28, 31, 30, 31, 30,
                                     31, 31, 30, 31, 30, 31};
  for (int64_t i = 0; i < n; ++i) {
    const unsigned char *t =
        reinterpret_cast<const unsigned char *>(ts_packed) + i * 46;
    if (t[4] != '-' || t[7] != '-' || t[10] != 'T' || t[13] != ':' ||
        t[16] != ':' || t[19] != '.' || t[23] != 'Z' || t[24] != '-' ||
        t[29] != '-')
      return 1;
    int64_t nums[7];  // y, mo, d, hh, mi, ss, ms
    static const int spans[7][2] = {{0, 4},   {5, 7},   {8, 10},  {11, 13},
                                    {14, 16}, {17, 19}, {20, 23}};
    for (int f = 0; f < 7; ++f) {
      int64_t v = 0;
      for (int j = spans[f][0]; j < spans[f][1]; ++j) {
        if (t[j] < '0' || t[j] > '9') return 1;
        v = v * 10 + (t[j] - '0');
      }
      nums[f] = v;
    }
    int64_t y = nums[0];
    int mo = (int)nums[1], d = (int)nums[2];
    if (y < 1 || mo < 1 || mo > 12 || d < 1) return 1;
    int dim = month_days[mo] + ((mo == 2 && is_leap(y)) ? 1 : 0);
    if (d > dim || nums[3] > 23 || nums[4] > 59 || nums[5] > 59) return 1;
    out_millis[i] =
        ((days_from_civil(y, mo, d) * 86400 + nums[3] * 3600 + nums[4] * 60 +
          nums[5]) *
         1000) +
        nums[6];
    bool canonical = true;
    uint32_t counter = 0;
    for (int j = 25; j < 29; ++j) {
      unsigned char c = t[j];
      uint32_t nib;
      if (c >= '0' && c <= '9') nib = c - '0';
      else if (c >= 'A' && c <= 'F') nib = c - 'A' + 10;
      else if (c >= 'a' && c <= 'f') { nib = c - 'a' + 10; canonical = false; }
      else return 1;
      counter = (counter << 4) | nib;
    }
    out_counter[i] = (int32_t)counter;
    uint64_t node = 0;
    for (int j = 30; j < 46; ++j) {
      unsigned char c = t[j];
      uint64_t nib;
      if (c >= '0' && c <= '9') nib = c - '0';
      else if (c >= 'a' && c <= 'f') nib = c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') { nib = c - 'A' + 10; canonical = false; }
      else return 1;
      node = (node << 4) | nib;
    }
    out_node[i] = node;
    out_case_ok[i] = canonical ? 1 : 0;
  }
  return 0;
}

// Packed, grouped variant of eh_relay_insert: the batch reconciler's
// one-call ingest. Timestamps arrive as ONE fixed-width 46-byte
// buffer and contents as ONE packed blob buffer with per-row lengths;
// rows are grouped per requesting user (group_users/group_counts), so
// the host passes n_groups pointers instead of n. In-batch duplicates
// dedup through the PK exactly like sequential INSERT OR IGNORE: the
// first occurrence reports was-new, later ones don't (index.ts:148-159
// changes()==1 semantics).
//
// Threading contract (PR-19 parallel sharded drain): this function
// touches only its `db` handle and caller-owned buffers — no globals,
// no Python API — so ctypes calls it with the GIL RELEASED and the
// write-behind queue runs one drain worker PER SHARD concurrently,
// each on its own sqlite3 handle (SQLite objects are never shared
// across the workers; serialization is per shard via the shard lock).
// Keep it that way: any global/static state added here would race the
// parallel drain.
int eh_relay_insert_packed(sqlite3 *db, int64_t n_groups,
                           const char *const *group_users,
                           const int64_t *group_counts,
                           const char *ts_packed,
                           const unsigned char *content_packed,
                           const int32_t *content_lens, uint8_t *out_new) {
  sqlite3_stmt *st = nullptr;
  const char *sql =
      "INSERT OR IGNORE INTO \"message\" (\"timestamp\", \"userId\", \"content\") "
      "VALUES (?, ?, ?)";
  if (sqlite3_prepare_v2(db, sql, -1, &st, nullptr) != SQLITE_OK) return 1;
  int64_t i = 0;
  int64_t content_off = 0;
  for (int64_t g = 0; g < n_groups; ++g) {
    const char *user = group_users[g];
    for (int64_t k = 0; k < group_counts[g]; ++k, ++i) {
      sqlite3_bind_text(st, 1, ts_packed + i * 46, 46, SQLITE_STATIC);
      sqlite3_bind_text(st, 2, user, -1, SQLITE_STATIC);
      sqlite3_bind_blob(st, 3, content_packed + content_off, content_lens[i],
                        SQLITE_STATIC);
      content_off += content_lens[i];
      int rc = sqlite3_step(st);
      sqlite3_reset(st);
      if (rc != SQLITE_DONE) {
        sqlite3_finalize(st);
        return 1;
      }
      out_new[i] = sqlite3_changes(db) == 1 ? 1 : 0;
    }
  }
  sqlite3_finalize(st);
  return 0;
}

int eh_relay_insert(sqlite3 *db, int64_t n, const char *const *timestamps,
                    const char *const *user_ids, const char *const *contents,
                    const int32_t *content_lens, uint8_t *out_new) {
  sqlite3_stmt *st = nullptr;
  const char *sql =
      "INSERT OR IGNORE INTO \"message\" (\"timestamp\", \"userId\", \"content\") "
      "VALUES (?, ?, ?)";
  if (sqlite3_prepare_v2(db, sql, -1, &st, nullptr) != SQLITE_OK) return 1;
  for (int64_t i = 0; i < n; ++i) {
    sqlite3_bind_text(st, 1, timestamps[i], -1, SQLITE_STATIC);
    sqlite3_bind_text(st, 2, user_ids[i], -1, SQLITE_STATIC);
    sqlite3_bind_blob(st, 3, contents[i], content_lens[i], SQLITE_STATIC);
    int rc = sqlite3_step(st);
    sqlite3_reset(st);
    sqlite3_clear_bindings(st);
    if (rc != SQLITE_DONE) {
      sqlite3_finalize(st);
      return 1;
    }
    out_new[i] = sqlite3_changes(db) == 1 ? 1 : 0;
  }
  sqlite3_finalize(st);
  return 0;
}

}  // extern "C"

extern "C" {

// --- generic bulk insert for text/blob/null rows ---
//
// One C call per statement batch: `kinds` is per CELL (nrows * ncols),
// 0 = null, 3 = text, 4 = blob; `vals`/`lens` are the flat cell
// buffers. Covers the relay's temp-table joins and message inserts
// (the ctypes per-bind path costs ~3us/bind; this is one call).
int eh_run_many_tb(sqlite3 *db, const char *sql, int64_t nrows, int32_t ncols,
                   const char *const *vals, const int32_t *lens,
                   const int32_t *kinds) {
  sqlite3_stmt *st = nullptr;
  if (sqlite3_prepare_v2(db, sql, -1, &st, nullptr) != SQLITE_OK) return 1;
  for (int64_t r = 0; r < nrows; ++r) {
    for (int32_t c = 0; c < ncols; ++c) {
      int64_t i = r * ncols + c;
      int rc;
      if (kinds[i] == 3)
        rc = sqlite3_bind_text(st, c + 1, vals[i], lens[i], SQLITE_STATIC);
      else if (kinds[i] == 4)
        rc = sqlite3_bind_blob(st, c + 1, vals[i], lens[i], SQLITE_STATIC);
      else
        rc = sqlite3_bind_null(st, c + 1);
      if (rc != SQLITE_OK) {
        sqlite3_finalize(st);
        return 1;
      }
    }
    int rc = sqlite3_step(st);
    sqlite3_reset(st);
    sqlite3_clear_bindings(st);
    if (rc != SQLITE_DONE && rc != SQLITE_ROW) {
      sqlite3_finalize(st);
      return 1;
    }
  }
  sqlite3_finalize(st);
  return 0;
}

// --- relay hot path: fetch a user's messages after `since`, excluding
// the requester's node (index.ts:173-202), packed into three buffers
// the caller frees with eh_free: fixed-width 46-byte timestamps,
// concatenated contents, and per-row content lengths. Avoids the
// per-row ctypes column reads (~10us/row) of the generic path. ---
int eh_get_messages(sqlite3 *db, const char *user, int32_t user_len,
                    const char *since, const char *node, int32_t node_len,
                    char **out_ts, unsigned char **out_content,
                    int32_t **out_lens, int64_t *out_n) {
  const char *sql =
      "SELECT \"timestamp\", \"content\" FROM \"message\" "
      "WHERE \"userId\" = ? AND \"timestamp\" > ? AND \"timestamp\" NOT LIKE '%' || ? "
      "ORDER BY \"timestamp\"";
  sqlite3_stmt *st = nullptr;
  if (sqlite3_prepare_v2(db, sql, -1, &st, nullptr) != SQLITE_OK) return 1;
  // Wire-derived user/node may contain NUL: explicit lengths (r4 —
  // the char* form truncated and could serve divergent rows vs the
  // Python backend).
  sqlite3_bind_text(st, 1, user, user_len, SQLITE_TRANSIENT);
  sqlite3_bind_text(st, 2, since, -1, SQLITE_TRANSIENT);
  sqlite3_bind_text(st, 3, node, node_len, SQLITE_TRANSIENT);

  std::string ts_buf;
  std::string content_buf;
  std::vector<int32_t> lens;
  int rc;
  while ((rc = sqlite3_step(st)) == SQLITE_ROW) {
    const unsigned char *ts = sqlite3_column_text(st, 0);
    int ts_len = sqlite3_column_bytes(st, 0);
    // Timestamps are the fixed 46-char encoding; anything else would
    // desync the fixed-width unpacking — fail loudly.
    if (ts_len != 46) {
      sqlite3_finalize(st);
      return 2;
    }
    ts_buf.append(reinterpret_cast<const char *>(ts), 46);
    const void *blob = sqlite3_column_blob(st, 1);
    int blen = sqlite3_column_bytes(st, 1);
    if (blen > 0) content_buf.append(static_cast<const char *>(blob), blen);
    lens.push_back(blen);
  }
  sqlite3_finalize(st);
  if (rc != SQLITE_DONE) return 1;

  *out_n = static_cast<int64_t>(lens.size());
  char *ts_out = static_cast<char *>(malloc(ts_buf.size() ? ts_buf.size() : 1));
  unsigned char *content_out =
      static_cast<unsigned char *>(malloc(content_buf.size() ? content_buf.size() : 1));
  int32_t *lens_out = static_cast<int32_t *>(malloc(lens.size() ? lens.size() * 4 : 4));
  if (!ts_out || !content_out || !lens_out) {
    free(ts_out);
    free(content_out);
    free(lens_out);
    return 3;  // allocation failure: surfaced, never a segfault
  }
  memcpy(ts_out, ts_buf.data(), ts_buf.size());
  memcpy(content_out, content_buf.data(), content_buf.size());
  memcpy(lens_out, lens.data(), lens.size() * 4);
  *out_ts = ts_out;
  *out_content = content_out;
  *out_lens = lens_out;
  return 0;
}

// --- relay response fast path: the same query as eh_get_messages,
// emitted DIRECTLY as the SyncResponse `messages` field-1 protobuf
// stream (per row: 0x0A varint(inner) ‖ 0x0A 0x2E ts46 ‖ 0x12
// varint(clen) content) — byte-identical to
// protocol.encode_sync_response's messages section, with zero per-row
// Python objects. The caller appends the merkleTree field 2. ---

int eh_get_messages_wire(sqlite3 *db, const char *user, int32_t user_len,
                         const char *since, const char *node,
                         int32_t node_len, unsigned char **out,
                         int64_t *out_len, int64_t *out_n) {
  const char *sql =
      "SELECT \"timestamp\", \"content\" FROM \"message\" "
      "WHERE \"userId\" = ? AND \"timestamp\" > ? AND \"timestamp\" NOT LIKE '%' || ? "
      "ORDER BY \"timestamp\"";
  sqlite3_stmt *st = nullptr;
  if (sqlite3_prepare_v2(db, sql, -1, &st, nullptr) != SQLITE_OK) return 1;
  // user/node come off the WIRE and may contain NUL — explicit lengths
  // (the char* convention would truncate and serve divergent rows vs
  // the Python backend; CLAUDE.md NUL invariant). `since` is a
  // canonical 46-char timestamp, NUL-free by construction.
  sqlite3_bind_text(st, 1, user, user_len, SQLITE_TRANSIENT);
  sqlite3_bind_text(st, 2, since, -1, SQLITE_TRANSIENT);
  sqlite3_bind_text(st, 3, node, node_len, SQLITE_TRANSIENT);

  std::string buf;
  int64_t rows = 0;
  int rc;
  while ((rc = sqlite3_step(st)) == SQLITE_ROW) {
    const unsigned char *ts = sqlite3_column_text(st, 0);
    if (sqlite3_column_bytes(st, 0) != 46) {  // fixed-width invariant
      sqlite3_finalize(st);
      return 2;
    }
    const void *blob = sqlite3_column_blob(st, 1);
    size_t clen = size_t(sqlite3_column_bytes(st, 1));
    size_t inner = 2 + 46 + 1 + wire_varint_size(clen) + clen;
    buf.push_back(char(0x0A));
    wire_put_varint(buf, inner);
    buf.push_back(char(0x0A));
    buf.push_back(char(46));
    buf.append(reinterpret_cast<const char *>(ts), 46);
    buf.push_back(char(0x12));
    wire_put_varint(buf, clen);
    if (clen) buf.append(static_cast<const char *>(blob), clen);
    rows++;
  }
  sqlite3_finalize(st);
  if (rc != SQLITE_DONE) return 1;
  unsigned char *p =
      static_cast<unsigned char *>(malloc(buf.size() ? buf.size() : 1));
  if (!p) return 3;
  memcpy(p, buf.data(), buf.size());
  *out = p;
  *out_len = static_cast<int64_t>(buf.size());
  *out_n = rows;
  return 0;
}

// --- snapshot capture (server/snapshot.py) ---
//
// Every `message` row and `merkleTree` row of one shard, packed into
// ONE malloc'd buffer of framed records the caller frees with eh_free:
//   'M' (0x4D): u32 ts_len‖ts ‖ u32 uid_len‖uid ‖ u32 len‖content
//   'T' (0x54): u32 uid_len‖uid ‖ u32 tree_len‖tree
// (little-endian lengths, explicit everywhere — timestamps/ids may be
// any width, contents are ciphertext blobs with possible NULs). Rows
// stream in PK order (userId, timestamp) and trees by userId, exactly
// matching the stdlib oracle `snapshot._capture_shard_py`, so the two
// paths are byte-identical (parity-pinned). The caller wraps this in
// a read transaction — the two SELECTs must see one consistent state.
int eh_snapshot_rows(sqlite3 *db, unsigned char **out, int64_t *out_len,
                     int64_t *out_msgs, int64_t *out_trees) {
  std::string buf;
  auto put_u32 = [&buf](uint32_t v) {
    buf.append(reinterpret_cast<const char *>(&v), 4);
  };
  sqlite3_stmt *st = nullptr;
  const char *msg_sql =
      "SELECT \"timestamp\", \"userId\", \"content\" FROM \"message\" "
      "ORDER BY \"userId\", \"timestamp\"";
  if (sqlite3_prepare_v2(db, msg_sql, -1, &st, nullptr) != SQLITE_OK) return 1;
  int64_t msgs = 0;
  int rc;
  while ((rc = sqlite3_step(st)) == SQLITE_ROW) {
    const unsigned char *ts = sqlite3_column_text(st, 0);
    uint32_t ts_len = uint32_t(sqlite3_column_bytes(st, 0));
    const unsigned char *uid = sqlite3_column_text(st, 1);
    uint32_t uid_len = uint32_t(sqlite3_column_bytes(st, 1));
    const void *blob = sqlite3_column_blob(st, 2);
    uint32_t blen = uint32_t(sqlite3_column_bytes(st, 2));
    buf.push_back(char(0x4D));
    put_u32(ts_len);
    if (ts_len) buf.append(reinterpret_cast<const char *>(ts), ts_len);
    put_u32(uid_len);
    if (uid_len) buf.append(reinterpret_cast<const char *>(uid), uid_len);
    put_u32(blen);
    if (blen) buf.append(static_cast<const char *>(blob), blen);
    msgs++;
  }
  sqlite3_finalize(st);
  if (rc != SQLITE_DONE) return 1;

  const char *tree_sql =
      "SELECT \"userId\", \"merkleTree\" FROM \"merkleTree\" "
      "ORDER BY \"userId\"";
  if (sqlite3_prepare_v2(db, tree_sql, -1, &st, nullptr) != SQLITE_OK) return 1;
  int64_t trees = 0;
  while ((rc = sqlite3_step(st)) == SQLITE_ROW) {
    const unsigned char *uid = sqlite3_column_text(st, 0);
    uint32_t uid_len = uint32_t(sqlite3_column_bytes(st, 0));
    const unsigned char *tr = sqlite3_column_text(st, 1);
    uint32_t tr_len = uint32_t(sqlite3_column_bytes(st, 1));
    buf.push_back(char(0x54));
    put_u32(uid_len);
    if (uid_len) buf.append(reinterpret_cast<const char *>(uid), uid_len);
    put_u32(tr_len);
    if (tr_len) buf.append(reinterpret_cast<const char *>(tr), tr_len);
    trees++;
  }
  sqlite3_finalize(st);
  if (rc != SQLITE_DONE) return 1;

  unsigned char *p =
      static_cast<unsigned char *>(malloc(buf.size() ? buf.size() : 1));
  if (!p) return 3;
  memcpy(p, buf.data(), buf.size());
  *out = p;
  *out_len = static_cast<int64_t>(buf.size());
  *out_msgs = msgs;
  *out_trees = trees;
  return 0;
}

// --- packed query reader (SURVEY hot loop #4) ---
//
// Step an already-bound statement to completion and pack every row
// into ONE malloc'd buffer the caller frees with eh_free. The generic
// per-cell path costs ~4 ctypes calls per cell (~65 ms for a 10k-row
// 3-column subscribed query, measured r4); this is one call, and the
// raw bytes double as a cache key — identical bytes mean the
// subscribed query did not change, so the worker skips dict
// materialization and diffing entirely.
//
// Buffer layout (little-endian, unaligned):
//   [i32 ncols][ncols x (i32 name_len, name bytes)]
//   per row: ncols x ([u8 type] + payload) where type/payload is
//     1 int (i64), 2 float (f64), 3 text (u32 len + bytes),
//     4 blob (u32 len + bytes), 5 null (no payload)
// `out_offsets` (nullable): malloc'd int64[rows+1] — byte offset of each
// row's start within `out`, with offsets[0] = header size and
// offsets[rows] = total length. The worker's row-granular change
// detection diffs consecutive result sets per ROW span and unpacks only
// changed rows (runtime/worker.py::_query, r5).
int eh_exec_packed(sqlite3_stmt *st, unsigned char **out, int64_t *out_len,
                   int64_t *out_rows, int64_t **out_offsets) {
  std::string buf;
  std::vector<int64_t> offsets;
  int ncols = sqlite3_column_count(st);
  auto put_i32 = [&buf](int32_t v) {
    buf.append(reinterpret_cast<const char *>(&v), 4);
  };
  put_i32(ncols);
  for (int c = 0; c < ncols; ++c) {
    const char *name = sqlite3_column_name(st, c);
    int32_t n = name ? static_cast<int32_t>(strlen(name)) : 0;
    put_i32(n);
    if (n) buf.append(name, n);
  }
  int64_t rows = 0;
  int rc;
  while ((rc = sqlite3_step(st)) == SQLITE_ROW) {
    rows++;
    if (out_offsets) offsets.push_back(int64_t(buf.size()));
    for (int c = 0; c < ncols; ++c) {
      int t = sqlite3_column_type(st, c);
      if (t == SQLITE_INTEGER) {
        buf.push_back(1);
        int64_t v = sqlite3_column_int64(st, c);
        buf.append(reinterpret_cast<const char *>(&v), 8);
      } else if (t == SQLITE_FLOAT) {
        buf.push_back(2);
        double v = sqlite3_column_double(st, c);
        buf.append(reinterpret_cast<const char *>(&v), 8);
      } else if (t == SQLITE_TEXT) {
        buf.push_back(3);
        const unsigned char *v = sqlite3_column_text(st, c);
        uint32_t n = static_cast<uint32_t>(sqlite3_column_bytes(st, c));
        buf.append(reinterpret_cast<const char *>(&n), 4);
        if (n) buf.append(reinterpret_cast<const char *>(v), n);
      } else if (t == SQLITE_BLOB) {
        buf.push_back(4);
        const void *v = sqlite3_column_blob(st, c);
        uint32_t n = static_cast<uint32_t>(sqlite3_column_bytes(st, c));
        buf.append(reinterpret_cast<const char *>(&n), 4);
        if (n) buf.append(static_cast<const char *>(v), n);
      } else {
        buf.push_back(5);
      }
    }
  }
  if (rc != SQLITE_DONE) return 1;
  unsigned char *p =
      static_cast<unsigned char *>(malloc(buf.size() ? buf.size() : 1));
  if (!p) return 3;
  memcpy(p, buf.data(), buf.size());
  if (out_offsets) {
    offsets.push_back(int64_t(buf.size()));  // [rows] = total length
    int64_t *op = static_cast<int64_t *>(malloc(offsets.size() * 8));
    if (!op) {
      free(p);
      return 3;
    }
    memcpy(op, offsets.data(), offsets.size() * 8);
    *out_offsets = op;
  }
  *out = p;
  *out_len = static_cast<int64_t>(buf.size());
  *out_rows = rows;
  return 0;
}

void eh_free(void *p) { free(p); }

}  // extern "C"
