// Shared protobuf wire primitives for the native layer — ONE varint
// implementation for libevolu_host (relay response stream) and
// libevolu_crypto (SyncRequest stream / response parse), so the wire
// encoding can never drift between the two .so files.
#pragma once

#include <cstddef>
#include <cstdint>
#include <string>

inline size_t wire_varint_size(uint64_t v) {
  size_t n = 1;
  while (v >= 0x80) { v >>= 7; n++; }
  return n;
}

inline void wire_put_varint(std::string &buf, uint64_t v) {
  while (v >= 0x80) { buf.push_back(char(uint8_t(v) | 0x80)); v >>= 7; }
  buf.push_back(char(uint8_t(v)));
}

inline uint8_t *wire_put_varint(uint8_t *p, uint64_t v) {
  while (v >= 0x80) { *p++ = uint8_t(v) | 0x80; v >>= 7; }
  *p++ = uint8_t(v);
  return p;
}
