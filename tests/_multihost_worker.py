"""Worker process for the multi-process cluster test (not collected by
pytest — launched by tests/test_multihost_cluster.py).

Joins a jax.distributed cluster (the DCN control-plane leg,
parallel/multihost.py), then runs the owner-fleet reconcile over the
GLOBAL mesh: every process builds the same host-side column layout,
feeds only its addressable shards, and the XOR digest all-reduce makes
the whole-batch digest visible on every process while each process
owns only its shards' plans — exactly the multi-host topology
SURVEY.md §2.15 prescribes.
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

# Must run before anything touches the XLA backend.
from evolu_tpu.parallel.multihost import (  # noqa: E402
    initialize_multihost,
    is_multihost,
    local_owners,
    local_shard_indices,
)

mesh = initialize_multihost(f"127.0.0.1:{port}", nproc, pid)

import numpy as np  # noqa: E402

from evolu_tpu.core.merkle import minute_deltas_host  # noqa: E402
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string  # noqa: E402
from evolu_tpu.core.types import CrdtMessage  # noqa: E402
from evolu_tpu.ops import to_host  # noqa: E402
from evolu_tpu.parallel.mesh import assign_owners_to_shards  # noqa: E402
from evolu_tpu.parallel.reconcile import (  # noqa: E402
    build_owner_columns,
    reconcile_columns_sharded,
)

assert is_multihost(), "expected a >1-process cluster"

BASE = 1_700_000_000_000
owner_batches = {
    f"owner{o:02d}": tuple(
        CrdtMessage(
            timestamp_to_string(Timestamp(BASE + (o * 997 + i) * 60_000, i % 3, f"{o + 1:016x}")),
            "todo", f"r{o}-{i}", "title", f"v{i}",
        )
        for i in range(10 + o * 3)
    )
    for o in range(8)
}

cols, index, host_owners = build_owner_columns(mesh, owner_batches, {})
assert not host_owners
outs = reconcile_columns_sharded(mesh, cols)
xor_local = to_host(outs[0])  # addressable shards only on this process
digest = int(np.asarray(outs[-1]))  # replicated via the XOR all-reduce

# Oracle: unique cells + no stored winners => every message XORs; the
# batch digest is the XOR fold over every owner's timestamps.
expect_digest = 0
for msgs in owner_batches.values():
    _, d = minute_deltas_host(m.timestamp for m in msgs)
    expect_digest ^= d
assert digest == expect_digest, (digest, expect_digest)

# This process's shards hold exactly its owners' messages (pad rows
# are masked by the kernel).
shards = assign_owners_to_shards(
    {o: len(b) for o, b in owner_batches.items()}, mesh.devices.size
)
mine = local_owners(mesh, shards)
expect_local = sum(len(owner_batches[o]) for o in mine)
assert int(xor_local.sum()) == expect_local, (int(xor_local.sum()), expect_local)

print(
    f"proc {pid}: devices={mesh.devices.size} local_shards={local_shard_indices(mesh)} "
    f"digest=0x{digest & 0xFFFFFFFF:08x} local_msgs={expect_local} OK",
    flush=True,
)
