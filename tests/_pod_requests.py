"""Deterministic request batches shared by the pod-server worker
processes AND the in-test single-process reference — the
broadcast-ingest model requires every process to see the identical
batch, and the test requires the reference to see it too."""

from evolu_tpu.core.merkle import (
    apply_prefix_xors,
    merkle_tree_to_string,
    minute_deltas_host,
)
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.sync import protocol

BASE = 1_700_000_000_000


def build_batches():
    """→ (push_batch, cold_batch). The push round: 12 owners push their
    own new messages with their post-apply trees (steady-state shape,
    responses empty), incl. one owner with an in-batch duplicate (the
    was-new recompute path) and one owner split across two requests.
    The cold round: every owner syncs from a fresh device (empty tree,
    different node) and must receive its full history."""
    reqs = []
    for o in range(12):
        user = f"owner{o:02d}"
        msgs = [
            protocol.EncryptedCrdtMessage(
                timestamp_to_string(
                    Timestamp(BASE + (o * 977 + i) * 60_000, i % 4, f"{o + 1:016x}")
                ),
                b"ct-%d-%d" % (o, i),
            )
            for i in range(6 + o)
        ]
        if o == 3:
            msgs.append(msgs[0])  # in-batch duplicate → was_new=False row
        deltas, _ = minute_deltas_host(
            m.timestamp for j, m in enumerate(msgs) if not (o == 3 and j == len(msgs) - 1)
        )
        tree = merkle_tree_to_string(apply_prefix_xors({}, deltas))
        if o == 7:  # one owner split across two requests
            reqs.append(protocol.SyncRequest(tuple(msgs[:3]), user, "f" * 16, tree))
            reqs.append(protocol.SyncRequest(tuple(msgs[3:]), user, "f" * 16, tree))
        else:
            reqs.append(protocol.SyncRequest(tuple(msgs), user, "f" * 16, tree))
    cold = tuple(
        protocol.SyncRequest((), f"owner{o:02d}", "e" * 16, "{}") for o in range(12)
    )
    return tuple(reqs), cold
