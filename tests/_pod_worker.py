"""Worker process for the pod-server test (launched by
tests/test_multihost_cluster.py, not collected by pytest).

Joins the jax.distributed cluster, builds the SAME request batches as
every other process (the broadcast-ingest model), runs TWO
`engine.reconcile_pod` passes over its OWN ShardedRelayStore — a push
round, then a cold-sync round (empty trees pulling full history) —
and prints each locally-answered response as base64 protobuf so the
parent can byte-compare the union against the single-process
BatchReconciler reference.
"""

import base64
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

pid, nproc, port, store_dir = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]

from evolu_tpu.parallel.multihost import initialize_multihost  # noqa: E402

mesh = initialize_multihost(f"127.0.0.1:{port}", nproc, pid)

from evolu_tpu.server import engine  # noqa: E402
from evolu_tpu.server.relay import ShardedRelayStore  # noqa: E402
from tests._pod_requests import build_batches  # noqa: E402

push, cold = build_batches()
store = ShardedRelayStore(f"{store_dir}/proc{pid}", shards=4)

# "replay" re-pushes the identical batch: every row is a store
# duplicate (was_new all False) → the per-owner host re-fold runs and
# must leave trees untouched.
for rnd, batch in (("push", push), ("replay", push), ("cold", cold)):
    responses, digest = engine.reconcile_pod(mesh, store, batch)
    for i, resp in enumerate(responses):
        if resp is not None:
            from evolu_tpu.sync.protocol import encode_sync_response

            b64 = base64.b64encode(encode_sync_response(resp)).decode()
            print(f"RESP {rnd} {i} {b64}", flush=True)
    print(f"DIGEST {rnd} proc={pid} digest=0x{digest & 0xFFFFFFFF:08x}", flush=True)

store.close()
print(f"proc {pid}: OK", flush=True)
