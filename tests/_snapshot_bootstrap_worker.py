"""Subprocess worker for the SIGKILL-mid-bootstrap crash test
(tests/test_snapshot.py::test_sigkill_between_chunks_resumes_from_watermark).

Runs ONE snapshot bootstrap of a file-backed relay store against a
donor relay URL, printing a `CHUNK <i>` line after each chunk's rows +
watermark COMMIT (and then sleeping `delay_s`, so the parent can
SIGKILL this process deterministically BETWEEN chunks). On completion
prints `DONE crc=<state crc>` — the parent compares it against the
donor's own state crc for byte-identity.

    python tests/_snapshot_bootstrap_worker.py <donor_url> <db_path> <delay_s>
"""

import os
import sys
import time
import zlib

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    donor_url, db_path, delay_s = sys.argv[1], sys.argv[2], float(sys.argv[3])

    from evolu_tpu.server import snapshot
    from evolu_tpu.server.relay import RelayStore
    from evolu_tpu.server.replicate import ReplicationManager
    from evolu_tpu.sync.client import _http_post

    orig_install = snapshot.SnapshotInstaller.install_chunk

    def traced_install(self, index, payload, expected_crc=None):
        n = orig_install(self, index, payload, expected_crc)
        # The watermark for `index` is COMMITTED at this point: a kill
        # during the sleep below is exactly "between snapshot chunks".
        print(f"CHUNK {index}", flush=True)
        if delay_s:
            time.sleep(delay_s)
        return n

    snapshot.SnapshotInstaller.install_chunk = traced_install

    store = RelayStore(db_path)
    mgr = ReplicationManager(
        store, [donor_url], replica_id="kill-victim",
        bootstrap_lag_owners=1, snapshot_chunk_bytes=64 * 1024,
        http_post=lambda u, d: _http_post(u, d, retries=0),
    )
    mgr.run_once()

    crc = 0
    for u in sorted(store.user_ids()):
        crc = zlib.crc32(store.get_merkle_tree_string(u).encode(), crc)
        for m in store.replica_messages(u, ""):
            crc = zlib.crc32(m.timestamp.encode(), crc)
            crc = zlib.crc32(m.content, crc)
    print(f"DONE crc={crc:08x}", flush=True)
    mgr.stop()
    store.close()


if __name__ == "__main__":
    main()
