"""Subprocess worker for the write-behind SIGKILL torture episode
(tests/test_model_check.py::test_write_behind_sigkill_torture).

Mode `ingest`: opens a file-backed RelayStore + WriteBehindQueue
(durable log) + BatchReconciler, generates `batches` seeded request
batches (the SAME generator the parent's oracle twin uses), serves
each through the write-behind path, and prints `ACK <i>` after the
batch's response is produced (i.e. after the record log fsync — the
durability promise under test). Every 4th batch it also writes a
checkpoint behind the drain barrier, so a kill can land mid-checkpoint
too. The drain is artificially slowed (`drain_delay`) to widen the
mid-queue/mid-drain kill windows. The parent SIGKILLs this process at
an arbitrary ACK count.

Mode `finish`: reopens the store + queue (constructor replays the
log through the always-exact path), flushes, and prints
`DONE crc=<state crc>` — the parent compares it against synchronous
oracle twins of the ACKed prefix (and prefix+1: a kill can land
between the log fsync and the ACK print).

    python tests/_write_behind_worker.py ingest <db_path> <seed> <batches> <drain_delay> [shards] [workers]
    python tests/_write_behind_worker.py finish <db_path> [shards] [workers]

`shards` > 1 opens a ShardedRelayStore with that many shard files and
`workers` parallel drain workers (0 = one per shard) — the PR-19
sharded-torture shape, where a kill can land with shard k's
transaction committed and shard j's still pending; replay must heal
the partial commit exactly (committed rows re-classify as
duplicates)."""

import os
import sys
import zlib

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASE = 1700000000000


def seeded_batches(seed: int, n_batches: int):
    """Deterministic request batches — ONE implementation imported by
    both this worker and the parent's oracle twin. Distinct owners per
    batch (the scheduler contract), occasional duplicate redelivery of
    an earlier batch's rows (the retry shape the drain must correct
    exactly), all timestamps canonical. Clients send their IN-SYNC
    post-push tree (the steady-state hot shape, computed through a
    deterministic embedded oracle) so fresh pushes never force a
    serve-side flush — the kill windows stay mid-queue/mid-drain."""
    import random

    from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
    from evolu_tpu.obs import ledger
    from evolu_tpu.server.relay import RelayStore
    from evolu_tpu.sync import protocol

    rng = random.Random(seed)
    owners = [f"owner{i}" for i in range(5)]
    nodes = {o: f"{i + 1:016x}" for i, o in enumerate(owners)}
    history = {o: [] for o in owners}
    batches = []
    # The embedded tree oracle is a REFERENCE computation, not traffic:
    # its add_messages posts store.inserted/duplicate terminals with no
    # ingress, which broke the episode-end conservation audit in every
    # process that both generates batches and audits (the parent of the
    # sigkill torture — the "flaky seeds 3/17/71", actually a
    # deterministic server-flow violation once PR-15 added the audit).
    with ledger.quarantine():
        tree_oracle = RelayStore()
        for b in range(n_batches):
            reqs = []
            for o in rng.sample(owners, rng.randrange(1, 4)):
                msgs = []
                if history[o] and rng.random() < 0.3:
                    # Redeliver a few already-sent rows (client retry).
                    msgs.extend(rng.sample(history[o], min(3, len(history[o]))))
                for j in range(rng.randrange(1, 9)):
                    ts = timestamp_to_string(
                        Timestamp(BASE + (b * 1000 + j) * 60000, rng.randrange(4),
                                  nodes[o])
                    )
                    m = protocol.EncryptedCrdtMessage(ts, b"ct-%d-%s" % (b, o.encode()))
                    msgs.append(m)
                    history[o].append(m)
                tree = tree_oracle.add_messages(o, msgs)
                from evolu_tpu.core.merkle import merkle_tree_to_string

                reqs.append(protocol.SyncRequest(
                    tuple(msgs), o, nodes[o], merkle_tree_to_string(tree)
                ))
            batches.append(reqs)
        tree_oracle.close()
    return batches


def state_crc(store) -> int:
    crc = 0
    for u in sorted(store.user_ids()):
        crc = zlib.crc32(store.get_merkle_tree_string(u).encode(), crc)
        for m in store.replica_messages(u, ""):
            crc = zlib.crc32(m.timestamp.encode(), crc)
            crc = zlib.crc32(m.content, crc)
    return crc


def _open_store(db_path: str, shards: int):
    from evolu_tpu.server.relay import RelayStore, ShardedRelayStore

    if shards > 1:
        return ShardedRelayStore(db_path, shards=shards)
    return RelayStore(db_path)


def main() -> None:
    mode, db_path = sys.argv[1], sys.argv[2]

    from evolu_tpu.server.engine import BatchReconciler
    from evolu_tpu.storage.write_behind import WriteBehindQueue

    if mode == "finish":
        shards = int(sys.argv[3]) if len(sys.argv) > 3 else 1
        workers = int(sys.argv[4]) if len(sys.argv) > 4 else 0
        store = _open_store(db_path, shards)
        wb = WriteBehindQueue(store, log_path=db_path + ".wblog",
                              drain_workers=workers)
        wb.flush()
        print(f"DONE crc={state_crc(store):08x}", flush=True)
        wb.close()
        store.close()
        return

    seed, n_batches, drain_delay = (
        int(sys.argv[3]), int(sys.argv[4]), float(sys.argv[5])
    )
    shards = int(sys.argv[6]) if len(sys.argv) > 6 else 1
    workers = int(sys.argv[7]) if len(sys.argv) > 7 else 0
    from evolu_tpu.server import snapshot

    store = _open_store(db_path, shards)
    wb = WriteBehindQueue(
        store, log_path=db_path + ".wblog", drain_batch_rows=8,
        drain_workers=workers, _drain_delay_s=drain_delay,
    )
    eng = BatchReconciler(store, write_behind=wb)
    for i, reqs in enumerate(seeded_batches(seed, n_batches)):
        eng.run_batch_wire(reqs)
        print(f"ACK {i}", flush=True)
        if i and i % 4 == 0:
            snapshot.write_checkpoint(
                store, db_path + ".ckpt", barrier=wb.drain_barrier
            )
            print(f"CKPT {i}", flush=True)
    wb.flush()
    print(f"DONE crc={state_crc(store):08x}", flush=True)
    wb.close()
    eng.close()
    store.close()


if __name__ == "__main__":
    main()
