"""Test env: force JAX onto a virtual 8-device CPU mesh.

Tests must never claim the real TPU chip — that's reserved for
bench.py. Two layers of defense:

1. If the axon TPU-tunnel env (`PALLAS_AXON_POOL_IPS`) is present,
   re-exec pytest with it stripped so the interpreter's sitecustomize
   hook doesn't register the TPU PJRT plugin (registration serializes
   on the pool's grant and can block every python process on the
   machine while another process holds the chip). The re-exec happens
   in pytest_configure with global capture stopped, so the child
   pytest inherits the real stdout/stderr, not the capture tempfile.
2. Force `JAX_PLATFORMS=cpu` with 8 virtual host devices before any
   jax backend initializes; sharding tests validate mesh semantics on
   the virtual mesh.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    env = dict(os.environ)
    for var in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
        env.pop(var, None)
    os.execve(sys.executable, [sys.executable, "-m", "pytest"] + sys.argv[1:], env)


def relay_store_dump(store):
    """Byte-identity parity dump of a relay store (message + merkleTree
    rows per shard) — ONE copy shared by every end-state parity gate
    (test_mesh_engine, test_model_check's oracle-twin episodes)."""
    return [
        (s.db.exec('SELECT * FROM "message" ORDER BY "timestamp", "userId"'),
         s.db.exec('SELECT * FROM "merkleTree" ORDER BY "userId"'))
        for s in store.shards
    ]
