"""Regenerate the GnuPG-produced golden interop fixtures.

The reference encrypts sync payloads with OpenPGP.js v5 symmetric
encryption (packages/evolu/src/sync.worker.ts:59-91, AES-256 SKESK +
SEIPD/MDC, iterated+salted SHA-256 S2K with s2kIterationCountByte: 0 =
1024 octets). OpenPGP.js itself cannot run in this environment (no
Node runtime), so the fixtures are produced by GnuPG — an independent,
interoperable RFC 4880 implementation — with the exact same packet
parameters. A ciphertext gpg produces and OpenPGP.js produces for
these parameters differ only in random salt/prefix; the packet grammar
our decoder must consume is identical.

Run: python tests/fixtures/make_gpg_fixtures.py
Requires: gpg >= 2.1 on PATH. Output is committed; tests read the
frozen bytes and do NOT regenerate.
"""

from __future__ import annotations

import pathlib
import subprocess
import sys
import tempfile

HERE = pathlib.Path(__file__).resolve().parent
sys.path.insert(0, str(HERE.parent.parent))

from evolu_tpu.sync.protocol import encode_content  # noqa: E402

# Matches the shape a reference client encrypts: one CrdtMessageContent.
PASSWORD = "legal winner thank year wave sausage worth useful legal winner thank yellow"
PLAINTEXT = encode_content(
    "todo", "B4UsGiFxpnc7SQaBSNy1u", "title", "Buy milk ✓ café"
)

VARIANTS = {
    # The reference's exact parameters: AES-256, S2K iterated+salted
    # SHA-256 count 1024 (count byte 0), no compression.
    "gpg_aes256_s2k1024_none.pgp": ["--compress-algo", "none"],
    # OpenPGP.js may emit compressed payloads; gpg's zip/zlib exercise
    # the same Compressed Data packet paths (tags 8/1 and 8/2).
    "gpg_aes256_s2k1024_zip.pgp": ["--compress-algo", "zip"],
    "gpg_aes256_s2k1024_zlib.pgp": ["--compress-algo", "zlib"],
}


def main() -> None:
    (HERE / "gpg_plaintext.bin").write_bytes(PLAINTEXT)
    (HERE / "gpg_password.txt").write_text(PASSWORD + "\n")
    with tempfile.TemporaryDirectory() as home:
        for name, extra in VARIANTS.items():
            out = HERE / name
            out.unlink(missing_ok=True)
            subprocess.run(
                [
                    "gpg", "--homedir", home, "--batch", "--yes",
                    "--pinentry-mode", "loopback", "--passphrase", PASSWORD,
                    "--symmetric", "--cipher-algo", "AES256",
                    "--s2k-mode", "3", "--s2k-digest-algo", "SHA256",
                    "--s2k-count", "1024", *extra,
                    "--output", str(out), str(HERE / "gpg_plaintext.bin"),
                ],
                check=True,
                capture_output=True,
            )
            print(f"wrote {out.name} ({out.stat().st_size} bytes)")


if __name__ == "__main__":
    main()
