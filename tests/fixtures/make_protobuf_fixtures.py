"""Regenerate the protoc-runtime-produced SyncRequest golden fixture.

The reference wire format is produced by protobuf-ts
(packages/evolu/protos/protobuf.proto, generated protobuf.ts). That
codegen cannot run here (no Node runtime), so the fixture bytes come
from the google.protobuf runtime parsing the same schema — both are
conformant proto3 encoders that serialize scalar fields in
field-number order, so for these messages (no maps, no packed arrays)
the bytes are the canonical encoding a protobuf-ts client emits.

Run: python tests/fixtures/make_protobuf_fixtures.py
Output is committed; tests read the frozen bytes.
"""

from __future__ import annotations

import pathlib
import sys

HERE = pathlib.Path(__file__).resolve().parent
sys.path.insert(0, str(HERE.parent.parent))


def build_classes():
    from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

    pool = descriptor_pool.DescriptorPool()
    f = descriptor_pb2.FileDescriptorProto()
    f.name = "evolu.proto"
    f.syntax = "proto3"

    content = f.message_type.add()
    content.name = "CrdtMessageContent"
    for i, (name, type_) in enumerate(
        [("table", 9), ("row", 9), ("column", 9), ("stringValue", 9), ("numberValue", 5)],
        start=1,
    ):
        fld = content.field.add()
        fld.name, fld.number, fld.type, fld.label = name, i, type_, 1

    enc = f.message_type.add()
    enc.name = "EncryptedCrdtMessage"
    t = enc.field.add()
    t.name, t.number, t.type, t.label = "timestamp", 1, 9, 1
    c = enc.field.add()
    c.name, c.number, c.type, c.label = "content", 2, 12, 1

    req = f.message_type.add()
    req.name = "SyncRequest"
    msgs = req.field.add()
    msgs.name, msgs.number, msgs.type, msgs.label = "messages", 1, 11, 3
    msgs.type_name = ".EncryptedCrdtMessage"
    for i, name in enumerate(["userId", "nodeId", "merkleTree"], start=2):
        fld = req.field.add()
        fld.name, fld.number, fld.type, fld.label = name, i, 9, 1

    pool.Add(f)
    mk = lambda n: message_factory.GetMessageClass(pool.FindMessageTypeByName(n))
    return mk("CrdtMessageContent"), mk("EncryptedCrdtMessage"), mk("SyncRequest")


def main() -> None:
    Content, Encrypted, Request = build_classes()
    content = Content(
        table="todo", row="B4UsGiFxpnc7SQaBSNy1u", column="title", stringValue="hello"
    ).SerializeToString()
    req = Request(
        messages=[
            Encrypted(
                timestamp="2024-01-31T10:20:30.444Z-0000-a1b2c3d4e5f60718",
                content=content,
            ),
            Encrypted(
                timestamp="2024-01-31T10:20:30.444Z-0001-a1b2c3d4e5f60718",
                content=b"\x01\x02\x03",
            ),
        ],
        userId="9f3c2b1a0d4e5f60718293a",
        nodeId="a1b2c3d4e5f60718",
        merkleTree='{"hash":12345,"2":{"hash":12345}}',
    )
    out = HERE / "protoc_sync_request.bin"
    out.write_bytes(req.SerializeToString())
    print(f"wrote {out.name} ({out.stat().st_size} bytes)")


if __name__ == "__main__":
    main()
