"""Batched apply == sequential oracle: byte-identical SQLite end state.

The property the whole TPU design rests on: plan_batch's masks give the
same database bytes and the same Merkle tree as the reference's
per-message loop, on adversarial workloads (cell contention, duplicate
delivery, interleaved batches).
"""

import random

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtMessage, TableDefinition
from evolu_tpu.storage import (
    apply_messages,
    init_db_model,
    open_database,
    update_db_schema,
)
from evolu_tpu.storage.apply import apply_messages_sequential

MNEMONIC = "legal winner thank year wave sausage worth useful legal winner thank yellow"
TABLES = [TableDefinition.of("todo", ["title", "isCompleted"]),
          TableDefinition.of("todoCategory", ["name"])]


def make_db():
    db = open_database()
    init_db_model(db, MNEMONIC)
    update_db_schema(db, TABLES)
    return db


def dump(db):
    out = {}
    for t in ("__message", "todo", "todoCategory"):
        out[t] = db.exec_sql_query(f'SELECT * FROM "{t}" ORDER BY 1, 2')
    return out


def random_messages(rng, n, n_nodes=4, n_rows=6, millis_range=(1656873700000, 1656873700000 + 3_600_000)):
    cols = {"todo": ["title", "isCompleted"], "todoCategory": ["name"]}
    msgs = []
    for _ in range(n):
        table = rng.choice(list(cols))
        row = f"row{rng.randrange(n_rows):017d}ab"  # 21 chars
        column = rng.choice(cols[table])
        node = f"{rng.randrange(n_nodes):016x}"
        ts = Timestamp(rng.randrange(*millis_range), rng.randrange(0, 4), node)
        value = rng.choice([None, "x", rng.randrange(100), 1.5])
        msgs.append(CrdtMessage(timestamp_to_string(ts), table, row, column, value))
    return msgs


def check_equivalence(batches):
    db_seq, db_bat = make_db(), make_db()
    tree_seq, tree_bat = {}, {}
    for batch in batches:
        tree_seq = apply_messages_sequential(db_seq, tree_seq, batch)
        tree_bat = apply_messages(db_bat, tree_bat, batch)
    assert dump(db_seq) == dump(db_bat)
    assert tree_seq == tree_bat


def test_equivalence_random_workloads():
    for seed in range(8):
        rng = random.Random(seed)
        batches = [random_messages(rng, rng.randrange(1, 120)) for _ in range(4)]
        check_equivalence(batches)


def test_equivalence_high_contention_same_cell():
    # 64 nodes fighting over the same cells — HLC (counter, node) tie-break.
    rng = random.Random(99)
    msgs = []
    for node_i in range(64):
        for _ in range(10):
            ts = Timestamp(1656873700000, rng.randrange(0, 3), f"{node_i:016x}")
            msgs.append(CrdtMessage(
                timestamp_to_string(ts), "todo", "r" * 21, "title", f"v{node_i}"
            ))
    rng.shuffle(msgs)
    check_equivalence([msgs])


def test_equivalence_duplicate_redelivery():
    # A non-winning duplicate re-received in a later batch double-XORs on
    # the client path (applyMessages.ts:104-122) — both paths must agree.
    old = CrdtMessage(
        timestamp_to_string(Timestamp(1656873700000, 0, "a" * 16)),
        "todo", "r" * 21, "title", "old",
    )
    new = CrdtMessage(
        timestamp_to_string(Timestamp(1656873800000, 0, "b" * 16)),
        "todo", "r" * 21, "title", "new",
    )
    check_equivalence([[old, new], [old], [old]])


def test_equivalence_winner_duplicate_skipped():
    # Re-receiving the *current winner* skips both upsert and XOR.
    m = CrdtMessage(
        timestamp_to_string(Timestamp(1656873700000, 0, "a" * 16)),
        "todo", "r" * 21, "title", "v",
    )
    check_equivalence([[m], [m], [m, m]])


def test_batch_updates_clock_tree_consistency():
    # The batched tree must equal inserting exactly the xor-masked subset.
    rng = random.Random(7)
    msgs = random_messages(rng, 200)
    db = make_db()
    tree = apply_messages(db, {}, msgs)
    db2 = make_db()
    tree2 = apply_messages_sequential(db2, {}, msgs)
    assert tree == tree2


def test_hostile_identifiers_cannot_splice_sql():
    """A wire message naming table 'todo\" (x\"); DROP TABLE ...' must not
    execute injected SQL; both backends fail identically (missing
    table), leaving state untouched."""
    import pytest

    from evolu_tpu.core.types import CrdtMessage, EvoluError
    from evolu_tpu.storage.apply import apply_messages
    from evolu_tpu.storage.native import open_database
    from evolu_tpu.storage.schema import init_db_model

    hostile = 'todo" ("x"); DROP TABLE "__message"; --'
    ts = "2024-01-01T00:00:00.000Z-0000-" + "a" * 16
    for backend in ("python", "native"):
        db = open_database(backend=backend)
        init_db_model(db, mnemonic=None)
        db.exec('CREATE TABLE "todo" ("id" TEXT PRIMARY KEY, "title" BLOB)')
        with pytest.raises(EvoluError):
            apply_messages(db, {}, [CrdtMessage(ts, hostile, "r", "title", "v")])
        # __message survives and nothing was inserted.
        assert db.exec('SELECT COUNT(*) FROM "__message"') == [(0,)]
        db.close()
