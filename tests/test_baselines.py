"""Bench-baseline drift gate (benchmarks/compare_baselines.py,
ISSUE 15 satellite): normalization splits numerics from exact-match
gates, relative drift flags beyond tolerance, `--smoke` keeps drift
advisory while gates stay hard, and the checked-in baselines parse."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmarks"))

import compare_baselines as cb  # noqa: E402


RECORD = {
    "metric": "demo_msgs_per_sec",
    "value": 1000.0,
    "pass_gate": True,
    "detail": {
        "digest": "0x4f3d0d7b",
        "batch": 4096,
        "method": "two-point slope",  # ignored identity text
        "platform": "cpu",
    },
}


def test_normalize_splits_values_gates_and_platform():
    n = cb.normalize(RECORD, "demo")
    assert n["platform"] == "cpu"
    assert n["values"] == {"value": 1000.0, "detail.batch": 4096.0}
    assert n["gates"] == {
        "metric": "demo_msgs_per_sec",
        "pass_gate": True,
        "detail.digest": "0x4f3d0d7b",
    }
    # "detail." must NOT be swallowed by the "tail" ignore word (exact
    # segment matching — the bug class the first draft had).
    assert "detail.batch" in n["values"]


def test_compare_flags_drift_and_gates():
    base = cb.normalize(RECORD, "demo")
    ok = dict(RECORD, value=1100.0)  # +10% < 25% tolerance
    gates, drifts = cb.compare(base, cb.normalize(ok, "demo"))
    assert gates == [] and drifts == []
    slow = dict(RECORD, value=400.0)  # -60%
    gates, drifts = cb.compare(base, cb.normalize(slow, "demo"))
    assert gates == [] and len(drifts) == 1 and drifts[0][0] == "value"
    broken = json.loads(json.dumps(RECORD))
    broken["detail"]["digest"] = "0xdeadbeef"
    gates, _ = cb.compare(base, cb.normalize(broken, "demo"))
    assert gates and gates[0][0] == "detail.digest"


def _run(args, stdin_text):
    return subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "compare_baselines.py")] + args,
        input=stdin_text, capture_output=True, text=True,
    )


def test_cli_update_check_smoke_roundtrip(tmp_path):
    bdir = str(tmp_path / "baselines")
    line = json.dumps(RECORD)
    r = _run(["--update", "demo", "--baseline-dir", bdir], line)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(os.path.join(bdir, "demo.cpu.json"))
    # Identical run: clean pass.
    assert _run(["--check", "demo", "--baseline-dir", bdir],
                line).returncode == 0
    # 60% regression: hard fail without --smoke, advisory with it.
    slow = json.dumps(dict(RECORD, value=400.0))
    assert _run(["--check", "demo", "--baseline-dir", bdir],
                slow).returncode == 1
    assert _run(["--check", "demo", "--baseline-dir", bdir, "--smoke"],
                slow).returncode == 0
    # Gate (checksum) mismatch: hard fail EVEN under --smoke.
    broken = json.loads(json.dumps(RECORD))
    broken["detail"]["digest"] = "0xdeadbeef"
    assert _run(["--check", "demo", "--baseline-dir", bdir, "--smoke"],
                json.dumps(broken)).returncode == 1
    # Unknown platform baseline: advisory pass (first run on new HW).
    other = json.loads(json.dumps(RECORD))
    other["detail"]["platform"] = "tpu"
    assert _run(["--check", "demo", "--baseline-dir", bdir, "--smoke"],
                json.dumps(other)).returncode == 0


def test_checked_in_baselines_parse_and_roundtrip():
    for name in os.listdir(cb.BASELINE_DIR):
        with open(os.path.join(cb.BASELINE_DIR, name)) as f:
            b = json.load(f)
        assert b["bench"] and "values" in b and "gates" in b
        # A baseline must be self-consistent: comparing it to itself
        # yields no drift and no gate failures.
        assert cb.compare(b, b) == ([], [])
