"""bench.py graph-liveness fence (VERDICT r3 weak #3).

The r2/early-r3 measurement bug: the fori_loop checksum consumed only
the masks + digest, so XLA dead-code-eliminated the whole Merkle
minute-segment stage from the timed graph and the bench silently timed
a smaller pipeline (under-reported 2.3×). bench.py now folds EVERY
kernel output into the carry; this test pins that property so the bug
class can never return: for each of the 9 `_shard_kernel` outputs,
perturbing just that output must change the checksum. If a future edit
drops an output from the fold, its perturbation becomes invisible and
the test fails — i.e. "stub any pipeline stage and nothing fails" is
now false by construction.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bench
from evolu_tpu.parallel.mesh import create_mesh, sharding
from evolu_tpu.parallel.reconcile import _shard_kernel, scatter_shard_kernel

N_OUTPUTS = 9  # xor_s, upsert_s, i_s, owner/minute/seg_end/seg_xor/valid, digest

# The scatter plan kernel (ISSUE 4) shares the 9-output contract; its
# table covers the fence's perturbed cell range (cells < 128, one
# fence iteration XORs bit 18 at most — i=0 only, so no relabel).
_KERNELS = {
    "sort": _shard_kernel,
    "scatter": scatter_shard_kernel(1 << 19),
}


def _perturbing_kernel(base_kernel, j):
    """The real kernel with output j nudged by one unit/flip — the
    minimal observable change a live fold must propagate."""

    def kernel(*args):
        outs = list(base_kernel(*args))
        # Fail loudly on arity drift: a 10th output would silently
        # escape the fence otherwise.
        assert len(outs) == N_OUTPUTS, f"kernel grew to {len(outs)} outputs"
        o = outs[j]
        if o.ndim == 0:
            outs[j] = o + jnp.ones((), o.dtype) if o.dtype != jnp.bool_ else ~o
        elif o.dtype == jnp.bool_:
            outs[j] = o.at[0].set(~o[0])
        else:
            outs[j] = o.at[0].add(jnp.ones((), o.dtype))
        return tuple(outs)

    return kernel


@pytest.fixture(scope="module")
def tiny_setup():
    mesh = create_mesh()
    n_dev = mesh.devices.size
    cols, _ = bench.shard_layout(
        bench.build_columns(n=512, owners=16, stored_winners=True), n_dev
    )
    shd = sharding(mesh)
    names = ("cell_id", "k1", "k2", "ex_k1", "ex_k2", "owner_ix")
    with jax.enable_x64(True):
        args = [jax.device_put(cols[k], shd) for k in names]
    return mesh, args


@pytest.mark.parametrize("variant", list(_KERNELS))
def test_every_kernel_output_is_live_in_the_checksum(tiny_setup, variant):
    mesh, args = tiny_setup
    base_kernel = _KERNELS[variant]
    # iters=1: with more fused iterations a bool-flip perturbation's
    # ±1 checksum delta could cancel across iterations (flipped element
    # True in one, False in the next) and falsely report a live output
    # as dead; a single iteration makes every perturbation's delta
    # nonzero by construction.
    with jax.enable_x64(True):
        base = int(bench.make_loop(mesh, 1, kernel=base_kernel)(*args))
        dead = []
        for j in range(N_OUTPUTS):
            loop = bench.make_loop(mesh, 1, kernel=_perturbing_kernel(base_kernel, j))
            if int(loop(*args)) == base:
                dead.append(j)
    assert dead == [], (
        f"[{variant}] outputs {dead} do not feed the bench checksum — XLA is "
        f"free to DCE their producing stages out of the timed graph"
    )


def test_metrics_do_not_touch_the_bench_graph(tiny_setup):
    """Instrumentation is host-side by contract: flipping the metrics
    registry on/off must leave the bench checksum bit-identical AND
    cause zero additional jit compilations (a recompile would mean an
    instrumentation value leaked into a traced graph as a constant, or
    an op was inserted into the fused pipeline)."""
    from evolu_tpu.obs import metrics

    mesh, args = tiny_setup
    loop = bench.make_loop(mesh, 1)
    with jax.enable_x64(True):
        metrics.set_enabled(False)
        try:
            base = int(loop(*args))
            cache_size = loop._cache_size()
            metrics.set_enabled(True)
            with_metrics = int(loop(*args))
            cache_size_after = loop._cache_size()
        finally:
            metrics.set_enabled(True)
    assert with_metrics == base, "metrics changed the bench checksum"
    assert cache_size_after == cache_size, (
        "enabling metrics added jit cache misses (recompiles) to the "
        "timed pipeline"
    )


def test_tracing_does_not_touch_the_bench_graph(tiny_setup):
    """ISSUE 10's twin of the metrics fence: with tracing enabled at
    100% sampling AND an active ambient span around the timed loop
    (the worst case — every log-span mirror fires), the bench checksum
    must stay bit-identical and the jit cache-miss count flat. Spans
    are host-side bookkeeping by contract; a recompile here would mean
    a trace value leaked into a traced graph."""
    from evolu_tpu.obs import trace

    mesh, args = tiny_setup
    loop = bench.make_loop(mesh, 1)
    with jax.enable_x64(True):
        trace.set_enabled(False)
        try:
            base = int(loop(*args))
            cache_size = loop._cache_size()
            trace.set_enabled(True)
            trace.set_sample_rate(1.0)
            root = trace.start_span("bench.guard")
            with root, trace.use(root.context):
                with_tracing = int(loop(*args))
            cache_size_after = loop._cache_size()
        finally:
            trace.set_enabled(True)
    assert with_tracing == base, "tracing changed the bench checksum"
    assert cache_size_after == cache_size, (
        "enabling tracing added jit cache misses (recompiles) to the "
        "timed pipeline"
    )


def test_ledger_does_not_touch_the_bench_graph(tiny_setup):
    """ISSUE 15's twin of the metrics/tracing fences: with the
    conservation ledger HOT (enabled, counts posting around and
    between loop invocations, a pending entry committing mid-flight),
    the bench checksum must stay bit-identical and both the loop's jit
    cache-miss count and the engine's `merkle_jit_cache_size()` flat.
    The ledger is host-side dict arithmetic by contract — a recompile
    here would mean a count leaked into a traced graph."""
    from evolu_tpu.obs import ledger
    from evolu_tpu.server import engine as eng_mod

    mesh, args = tiny_setup
    loop = bench.make_loop(mesh, 1)
    with jax.enable_x64(True):
        ledger.set_enabled(False)
        try:
            base = int(loop(*args))
            cache_size = loop._cache_size()
            engine_cache = eng_mod.merkle_jit_cache_size()
            ledger.set_enabled(True)
            ledger.count(ledger.INGRESS_SYNC, 512, owner="bench-owner")
            entry = ledger.pending()
            entry.count(ledger.STORE_INSERTED, 512, owner="bench-owner")
            with_ledger = int(loop(*args))
            entry.commit()
            assert ledger.audit(at_barrier=True) == []
            cache_size_after = loop._cache_size()
            engine_cache_after = eng_mod.merkle_jit_cache_size()
        finally:
            ledger.set_enabled(True)
            ledger.reset()
    assert with_ledger == base, "the ledger changed the bench checksum"
    assert cache_size_after == cache_size, (
        "enabling the ledger added jit cache misses (recompiles) to the "
        "timed pipeline"
    )
    assert engine_cache_after == engine_cache, (
        "the ledger moved the engine's merkle jit cache"
    )


def _stage_anatomy():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks"))
    import stage_anatomy as sa

    return sa


def _anatomy_device_stages():
    return list(_stage_anatomy().DEVICE_STAGES)


@pytest.mark.parametrize("stage", _anatomy_device_stages())
def test_every_truncated_variant_output_is_live(tiny_setup, stage):
    """ISSUE 16: the stage-anatomy harness times TRUNCATED pipeline
    variants, so the DCE fence must hold per variant, not just for the
    full kernel — for the variant ending at `stage`, perturbing each
    output that stage ADDED must move the variant's checksum. (Earlier
    stages' outputs are pinned by their own variant's case.)"""
    sa = _stage_anatomy()
    mesh, args = tiny_setup
    kernel = sa.build_variant(stage)
    arity = sa.variant_arity(stage)
    with jax.enable_x64(True):
        base = int(sa.make_variant_loop(mesh, 1, kernel)(*args))
        dead = []
        for j in sa.stage_output_indices(stage):
            loop = sa.make_variant_loop(
                mesh, 1, sa.perturbing_kernel(kernel, j, arity))
            if int(loop(*args)) == base:
                dead.append(j)
    assert dead == [], (
        f"[{stage}] outputs {dead} do not feed the variant checksum — the "
        f"anatomy harness would time a DCE'd (smaller) pipeline"
    )


def test_anatomy_does_not_touch_the_bench_graph(tiny_setup):
    """ISSUE 16's twin of the metrics/tracing/ledger fences: with the
    stage-anatomy accountant HOT (platform set, stage records posting
    around and between loop invocations — the engine seams call it per
    batch), the bench checksum must stay bit-identical and the jit
    cache-miss count flat. Stage accounting is host-side float/dict
    arithmetic by contract."""
    from evolu_tpu.obs import anatomy, metrics

    mesh, args = tiny_setup
    loop = bench.make_loop(mesh, 1)
    prev_platform = anatomy.get_platform()
    with jax.enable_x64(True):
        metrics.set_enabled(False)
        try:
            base = int(loop(*args))
            cache_size = loop._cache_size()
            metrics.set_enabled(True)
            anatomy.set_platform("tpu")
            anatomy.record_stage("device_dispatch", 0.105, rows=512)
            with_anatomy = int(loop(*args))
            anatomy.record_stage("host_apply", 0.002, rows=512)
            anatomy.record_stage("pull_wave", 0.001, nbytes=4096)
            cache_size_after = loop._cache_size()
        finally:
            metrics.set_enabled(True)
            anatomy.set_platform(prev_platform)
            anatomy.reset()
    assert with_anatomy == base, "stage accounting changed the bench checksum"
    assert cache_size_after == cache_size, (
        "stage accounting added jit cache misses (recompiles) to the "
        "timed pipeline"
    )


def test_checksum_depends_on_the_data():
    """Same loop, different input data → different checksum (guards a
    degenerate fold that collapses to a constant)."""
    mesh = create_mesh()
    n_dev = mesh.devices.size
    shd = sharding(mesh)
    names = ("cell_id", "k1", "k2", "ex_k1", "ex_k2", "owner_ix")
    with jax.enable_x64(True):
        loop = bench.make_loop(mesh, 2)
        vals = []
        for seed in (7, 8):
            cols, _ = bench.shard_layout(
                bench.build_columns(n=512, owners=16, seed=seed, stored_winners=True),
                n_dev,
            )
            vals.append(int(loop(*[jax.device_put(cols[k], shd) for k in names])))
    assert vals[0] != vals[1]
