"""Event-loop connection tier (evolu_tpu/server/conn.py — ISSUE 13).

Ground truth #1 — byte-identity: the event tier drives the UNCHANGED
relay handler over an in-memory socket, so every endpoint's raw HTTP
response (status line, headers, body) must equal the threaded tier's
for the same request against the same store state, modulo only the
Date header. The twin-relay oracle below drives one request sequence
at both tiers over raw sockets and compares everything, then compares
SQLite end state.

Ground truth #2 — threads don't grow with connections: idle and
parked connections are loop-owned; only the bounded handler pool ever
runs request code. Asserted directly against threading.active_count.

Ground truth #3 — slow-client hardening: a request must fully arrive
within the read budget (absolute — a trickle can't slide it), headers
are capped, oversized bodies are never buffered, a hung client can't
pin anything. Raw-socket shapes for each.
"""

import json
import re
import socket
import threading
import time
import urllib.request

import pytest

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.server.relay import MAX_BODY_BYTES, RelayServer, RelayStore
from evolu_tpu.sync import protocol

BASE = 1_700_000_000_000
NODE_A = "a" * 16
NODE_B = "b" * 16
FRESH = "f" * 16


def _msgs(node: str, start: int, n: int):
    return tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(Timestamp(BASE + (start + i) * 1000, 0, node)),
            b"ct-%d" % (start + i),
        )
        for i in range(n)
    )


def _raw_request(method: str, path: str, body: bytes = b"",
                 headers=()) -> bytes:
    lines = [f"{method} {path} HTTP/1.0",
             "Content-Length: " + str(len(body))]
    lines += [f"{k}: {v}" for k, v in headers]
    return ("\r\n".join(lines) + "\r\n\r\n").encode() + body


def _exchange(addr, raw: bytes, timeout: float = 30.0) -> bytes:
    """Send one raw request, read the FULL raw response to EOF."""
    with socket.create_connection(addr, timeout=timeout) as s:
        s.sendall(raw)
        out = bytearray()
        while True:
            chunk = s.recv(65536)
            if not chunk:
                return bytes(out)
            out += chunk


_DATE_RE = re.compile(rb"\r\nDate: [^\r\n]*")


def _normalize(resp: bytes) -> bytes:
    """Drop the only legitimately nondeterministic header."""
    return _DATE_RE.sub(b"\r\nDate: -", resp)


def _dump_store(store: RelayStore):
    msgs = store.db.exec_sql_query(
        'SELECT "timestamp", "userId", "content" FROM "message" '
        'ORDER BY "userId", "timestamp"', ())
    trees = store.db.exec_sql_query(
        'SELECT "userId", "merkleTree" FROM "merkleTree" ORDER BY "userId"',
        ())
    return (
        [(r["timestamp"], r["userId"], bytes(r["content"])) for r in msgs],
        [(r["userId"], r["merkleTree"]) for r in trees],
    )


def _sync_body(owner: str, node: str, messages, tree: str = "{}") -> bytes:
    return protocol.encode_sync_request(
        protocol.SyncRequest(messages, owner, node, tree))


def test_twin_relay_oracle_byte_identity():
    """One request sequence, two tiers, every response byte-identical
    (modulo Date) and both stores ending byte-identical."""
    from evolu_tpu.server.replicate import ReplicationManager

    def _twin(tier):
        store = RelayStore()
        # Pin the replica id: the gossip surface echoes it, and a
        # random per-manager id would fail the byte compare for
        # reasons that have nothing to do with the tier.
        repl = ReplicationManager(store, [], replica_id="twin-relay")
        return RelayServer(store, replication=repl,
                           connection_tier=tier).start()

    twins = [_twin(tier) for tier in ("threaded", "eventloop")]
    try:
        addrs = [s._httpd.server_address[:2] for s in twins]
        requests = [
            _raw_request("GET", "/ping"),
            _raw_request("GET", "/health"),
            # push rows for two owners, then pulls (cold + warm)
            _raw_request("POST", "/", _sync_body("ow-1", NODE_A,
                                                 _msgs(NODE_A, 0, 8))),
            _raw_request("POST", "/", _sync_body("ow-2", NODE_B,
                                                 _msgs(NODE_B, 100, 5))),
            _raw_request("POST", "/", _sync_body("ow-1", FRESH, ())),
            # duplicate delivery (idempotent ingest)
            _raw_request("POST", "/", _sync_body("ow-1", NODE_A,
                                                 _msgs(NODE_A, 0, 8))),
            # capability-advertising request (negotiated echo appended)
            _raw_request("POST", "/", _sync_body("ow-1", FRESH, ())
                         + protocol.encode_request_capabilities(
                             ("aead-batch-v1",))),
            # malformed body → 500 shape; bad/negative Content-Length → 400
            _raw_request("POST", "/", b"\xff\xfe\xfd"),
            b"POST / HTTP/1.0\r\nContent-Length: nope\r\n\r\n",
            b"POST / HTTP/1.0\r\nContent-Length: -5\r\n\r\n",
            # oversized declaration → 413 without a body ever sent
            b"POST / HTTP/1.0\r\nContent-Length: "
            + str(MAX_BODY_BYTES + 1).encode() + b"\r\n\r\n",
            # unknown path / method
            _raw_request("GET", "/nope"),
            _raw_request("PUT", "/"),
            # replication listener surface: malformed → 400, valid
            # summary and pull from an empty peer
            _raw_request("POST", "/replicate/summary", b"\xff\xff"),
            _raw_request("POST", "/replicate/summary",
                         protocol.encode_replica_summary(
                             protocol.ReplicaSummary((), "twin-peer"))),
            _raw_request("POST", "/replicate/pull",
                         protocol.encode_replica_pull(
                             protocol.ReplicaPull(
                                 (("ow-1", timestamp_to_string(
                                     Timestamp(0, 0, "0" * 16))),),
                                 "twin-peer"))),
            _raw_request("POST", "/replicate/nope", b""),
            # fleet surface without fleet: 404
            _raw_request("POST", "/fleet/forward", b""),
            _raw_request("GET", "/fleet"),
            # push poll (immediate lanes only — parked polls are
            # timing, not bytes): malformed query → 400, zero timeout
            _raw_request("GET", "/push/poll?owner=ow-1&node=zz&cursor=0"),
            _raw_request("GET", "/push/poll?owner=ow-1&node=" + FRESH
                         + "&cursor=0&timeout=0"),
            # stale cursor after the writes above → immediate wake
            _raw_request("GET", "/push/poll?owner=ow-1&node=" + FRESH
                         + "&cursor=-999&timeout=0"),
        ]
        for i, raw in enumerate(requests):
            got = [_normalize(_exchange(a, raw)) for a in addrs]
            assert got[0] == got[1], (
                f"request #{i} diverged between tiers:\n"
                f"threaded:  {got[0][:400]!r}\n"
                f"eventloop: {got[1][:400]!r}"
            )
        assert _dump_store(twins[0].store) == _dump_store(twins[1].store)
        d = _dump_store(twins[0].store)
        assert len(d[0]) == 13 and len(d[1]) == 2  # 8+5 rows, 2 owners
    finally:
        for s in twins:
            s.stop()


def test_twin_oracle_observability_endpoints():
    """/stats and /metrics between the tiers: same structure, same
    deterministic fields (timing histograms and the tiers' own
    counters differ by construction — the registry is process-global
    and self-observing, so raw bytes cannot match; what must match is
    that the tier serves the same payload shape unaltered)."""
    twins = [
        RelayServer(RelayStore(), connection_tier=tier).start()
        for tier in ("threaded", "eventloop")
    ]
    try:
        for srv in twins:
            body = _sync_body("ow-s", NODE_A, _msgs(NODE_A, 0, 3))
            with urllib.request.urlopen(
                    urllib.request.Request(srv.url + "/", data=body),
                    timeout=10) as r:
                assert r.status == 200
        stats = []
        for srv in twins:
            with urllib.request.urlopen(srv.url + "/stats", timeout=10) as r:
                stats.append(json.loads(r.read()))
        for st in stats:
            assert st["messages"] == 3 and st["users"] == 1
            assert "push" in st
        assert "conn" in stats[1] and stats[1]["conn"]["tier"] == "eventloop"
        assert "conn" not in stats[0]
        proms = []
        for srv in twins:
            with urllib.request.urlopen(srv.url + "/metrics", timeout=10) as r:
                assert r.headers["Content-Type"].startswith("text/plain")
                proms.append(r.read().decode())
        fams = [set(ln.split("{")[0].split(" ")[0] for ln in p.splitlines()
                    if ln and not ln.startswith("#")) for p in proms]
        assert fams[0] == fams[1]
    finally:
        for s in twins:
            s.stop()


# -- slow-client hardening (raw sockets) --


@pytest.fixture()
def fast_timeout_server():
    from evolu_tpu.utils import config as cfg_mod

    old = cfg_mod.default_config
    c = cfg_mod.Config(conn_read_timeout_s=0.5, conn_write_timeout_s=0.5,
                       conn_max_header_bytes=2048)
    cfg_mod.set_config(c)
    srv = RelayServer(RelayStore(), connection_tier="eventloop").start()
    try:
        yield srv
    finally:
        srv.stop()
        cfg_mod.set_config(old)


def test_partial_header_times_out(fast_timeout_server):
    addr = fast_timeout_server._httpd.server_address[:2]
    with socket.create_connection(addr, timeout=10) as s:
        s.sendall(b"GET /ping HT")  # never finishes the request line
        s.settimeout(5)
        t0 = time.monotonic()
        assert s.recv(100) == b""  # server closes, no response
        assert 0.3 < time.monotonic() - t0 < 4.0
    # The server is still healthy afterwards.
    with urllib.request.urlopen(fast_timeout_server.url + "/ping",
                                timeout=5) as r:
        assert r.read() == b"ok"


def test_partial_body_times_out(fast_timeout_server):
    addr = fast_timeout_server._httpd.server_address[:2]
    with socket.create_connection(addr, timeout=10) as s:
        s.sendall(b"POST / HTTP/1.0\r\nContent-Length: 1000\r\n\r\nonly-a-bit")
        s.settimeout(5)
        assert s.recv(100) == b""


def test_slow_trickle_cannot_slide_the_deadline(fast_timeout_server):
    """The read budget is ABSOLUTE: byte-per-100ms progress must not
    keep the connection alive past it (the slowloris shape)."""
    addr = fast_timeout_server._httpd.server_address[:2]
    with socket.create_connection(addr, timeout=10) as s:
        s.settimeout(0.1)
        t0 = time.monotonic()
        closed_at = None
        payload = b"GET /ping HTTP/1.0\r\nX-Slow: " + b"x" * 500
        i = 0
        while time.monotonic() - t0 < 4.0:
            try:
                s.sendall(payload[i:i + 1])
                i = min(i + 1, len(payload) - 1)
            except OSError:
                closed_at = time.monotonic() - t0
                break
            try:
                if s.recv(100) == b"":
                    closed_at = time.monotonic() - t0
                    break
            except socket.timeout:
                pass
        assert closed_at is not None and closed_at < 3.0, \
            "trickling client outlived the absolute read budget"


def test_header_overflow_answers_431(fast_timeout_server):
    addr = fast_timeout_server._httpd.server_address[:2]
    raw = b"GET /ping HTTP/1.0\r\nX-Big: " + b"x" * 4096 + b"\r\n\r\n"
    resp = _exchange(addr, raw, timeout=10)
    assert resp.startswith(b"HTTP/1.0 431")


def test_mid_response_hangup_is_cleaned_up(fast_timeout_server):
    """Client vanishes after sending a full request: the tier serves
    into a dead socket, observes the failure, and stays healthy."""
    addr = fast_timeout_server._httpd.server_address[:2]
    for _ in range(8):
        s = socket.create_connection(addr, timeout=10)
        s.sendall(_raw_request("GET", "/ping"))
        s.close()  # hang up before reading
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        with urllib.request.urlopen(fast_timeout_server.url + "/stats",
                                    timeout=5) as r:
            st = json.loads(r.read())
        if st["conn"]["open_connections"] == 1:  # just this scrape
            break
        time.sleep(0.05)
    assert st["conn"]["open_connections"] == 1


def test_idle_connections_do_not_grow_threads():
    """The tentpole's core claim at test scale (the bench drives 10^4):
    hundreds of parked long-polls add ZERO threads, and every one of
    them still gets its wakeup."""
    srv = RelayServer(RelayStore(), connection_tier="eventloop").start()
    try:
        addr = srv._httpd.server_address[:2]
        # Warm the pool: a couple of real requests.
        for _ in range(3):
            with urllib.request.urlopen(srv.url + "/ping", timeout=5):
                pass
        baseline = threading.active_count()
        socks = []
        n = 256
        for i in range(n):
            s = socket.create_connection(addr, timeout=10)
            s.sendall(_raw_request(
                "GET", f"/push/poll?owner=ow-idle&node={NODE_B}"
                       f"&cursor=0&timeout=30"))
            socks.append(s)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if srv.push_hub.stats_payload()["subscriptions"] == n:
                break
            time.sleep(0.02)
        assert srv.push_hub.stats_payload()["subscriptions"] == n
        grown = threading.active_count() - baseline
        assert grown <= 0, f"{grown} threads grew with {n} idle connections"
        # One mutation wakes them all (authored by a different node).
        body = _sync_body("ow-idle", NODE_A, _msgs(NODE_A, 0, 1))
        with urllib.request.urlopen(
                urllib.request.Request(srv.url + "/", data=body),
                timeout=10) as r:
            assert r.status == 200
        woken = 0
        for s in socks:
            s.settimeout(10)
            resp = bytearray()
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                resp += chunk
            head, _, payload = bytes(resp).partition(b"\r\n\r\n")
            assert head.startswith(b"HTTP/1.0 200")
            if json.loads(payload)["wake"]:
                woken += 1
            s.close()
        assert woken == n
    finally:
        srv.stop()


def test_dispatch_admission_sheds_503():
    """Past max_pending in-flight dispatches the LOOP answers 503 +
    Retry-After itself — a request flood can't buffer without bound."""
    from evolu_tpu.utils import config as cfg_mod

    old = cfg_mod.default_config
    cfg_mod.set_config(cfg_mod.Config(conn_handler_threads=1,
                                      conn_max_pending=2))
    srv = RelayServer(RelayStore(), connection_tier="eventloop").start()
    try:
        addr = srv._httpd.server_address[:2]
        # Stall the single handler thread with a parked threaded-style
        # request? No — fill the pipeline with real posts instead: one
        # slow-ish body each; with 1 worker and max_pending=2 a burst
        # must shed some 503s while still serving the rest.
        results = []
        lock = threading.Lock()

        def one(i):
            raw = _raw_request("POST", "/", _sync_body(
                f"ow-{i}", NODE_A, _msgs(NODE_A, i * 10, 4)))
            resp = _exchange(addr, raw, timeout=30)
            with lock:
                results.append(resp.split(b" ", 2)[1])

        threads = [threading.Thread(target=one, args=(i,)) for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        codes = {c: sum(1 for x in results if x == c) for c in set(results)}
        assert codes.get(b"200", 0) >= 1
        assert set(codes) <= {b"200", b"503"}, codes
        # Whatever shed carried the backpressure contract.
        if codes.get(b"503"):
            from evolu_tpu.obs import metrics

            assert metrics.get_counter("evolu_conn_shed_total") > 0
    finally:
        srv.stop()
        cfg_mod.set_config(old)


def test_scheduler_batching_rides_the_event_tier():
    """The PR-2 admission path unchanged underneath: a batching relay
    on the event tier serves concurrent distinct-owner posts through
    fused engine passes, byte-identical to the per-request oracle."""
    oracle = RelayServer(RelayStore(), connection_tier="threaded").start()
    srv = RelayServer(RelayStore(), batching=True,
                      connection_tier="eventloop").start()
    try:
        bodies = {f"ow-{i}": _sync_body(f"ow-{i}", NODE_A,
                                        _msgs(NODE_A, i * 100, 6))
                  for i in range(12)}
        expect = {}
        for owner, body in bodies.items():
            with urllib.request.urlopen(
                    urllib.request.Request(oracle.url + "/", data=body),
                    timeout=30) as r:
                expect[owner] = r.read()
        got = {}
        lock = threading.Lock()

        def post(owner, body):
            with urllib.request.urlopen(
                    urllib.request.Request(srv.url + "/", data=body),
                    timeout=30) as r:
                data = r.read()
            with lock:
                got[owner] = data

        threads = [threading.Thread(target=post, args=kv)
                   for kv in bodies.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert got == expect
        assert _dump_store(srv.store) == _dump_store(oracle.store)
    finally:
        srv.stop()
        oracle.stop()


# -- review-fix regressions --


def test_push_poll_with_huge_content_length_does_not_pin_the_pool():
    """A GET /push/poll declaring an absurd Content-Length must still
    park IN-LOOP (never ride the headers-only 413 dispatch into the
    bounded pool, where poll_blocking would pin a handler thread)."""
    from evolu_tpu.utils import config as cfg_mod

    old = cfg_mod.default_config
    cfg_mod.set_config(cfg_mod.Config(conn_handler_threads=1))
    srv = RelayServer(RelayStore(), connection_tier="eventloop").start()
    try:
        addr = srv._httpd.server_address[:2]
        socks = []
        for _ in range(4):  # 4 > the single pool thread
            s = socket.create_connection(addr, timeout=10)
            s.sendall(
                b"GET /push/poll?owner=ow&node=" + NODE_B.encode()
                + b"&cursor=0&timeout=20 HTTP/1.0\r\n"
                  b"Content-Length: 99999999999\r\n\r\n")
            socks.append(s)
        deadline = time.monotonic() + 5
        while srv.push_hub.stats_payload()["subscriptions"] != 4:
            assert time.monotonic() < deadline, \
                srv.push_hub.stats_payload()
            time.sleep(0.02)
        # The single pool thread is free: a normal request answers.
        with urllib.request.urlopen(srv.url + "/ping", timeout=5) as r:
            assert r.read() == b"ok"
        # And the parks resolve on notify like any other poll.
        body = _sync_body("ow", NODE_A, _msgs(NODE_A, 0, 1))
        with urllib.request.urlopen(
                urllib.request.Request(srv.url + "/", data=body),
                timeout=10) as r:
            assert r.status == 200
        for s in socks:
            s.settimeout(10)
            resp = bytearray()
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                resp += chunk
            assert b'"wake": true' in bytes(resp)
            s.close()
    finally:
        srv.stop()
        cfg_mod.set_config(old)


def test_parked_connection_cannot_buffer_unbounded_bytes():
    """Bytes streamed AFTER a complete request are discarded, and a
    flood past the post-request allowance closes the connection and
    frees its subscription."""
    srv = RelayServer(RelayStore(), connection_tier="eventloop").start()
    try:
        addr = srv._httpd.server_address[:2]
        s = socket.create_connection(addr, timeout=10)
        s.sendall(_raw_request(
            "GET", f"/push/poll?owner=ow&node={NODE_B}&cursor=0&timeout=30"))
        deadline = time.monotonic() + 5
        while srv.push_hub.stats_payload()["subscriptions"] != 1:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        # Flood ~1 MB of garbage down the parked connection.
        closed = False
        try:
            for _ in range(16):
                s.sendall(b"x" * 65536)
                time.sleep(0.01)
        except OSError:
            closed = True
        deadline = time.monotonic() + 5
        while srv.push_hub.stats_payload()["subscriptions"] != 0:
            assert time.monotonic() < deadline, \
                "flooding parked subscription was not cancelled"
            time.sleep(0.02)
        s.close()
        assert closed or True  # send() may succeed into the RST window
        # Relay healthy after.
        with urllib.request.urlopen(srv.url + "/ping", timeout=5) as r:
            assert r.read() == b"ok"
    finally:
        srv.stop()
