"""N-replica convergence + high-contention tie-break properties.

The reference never tests multi-node convergence (SURVEY.md §4 "the
multi-node story is untested"); these tests close that gap against the
BASELINE configs:

- config 1: two replicas, todo schema, 1k CrdtMessages through the
  full client+relay stack — byte-identical SQLite end state.
- config 4: 64 replicas editing the same 100 rows — HLC (counter,
  node) tie-break exactness; every delivery order converges to the
  oracle's winner.
- property: applying one message SET in any order/partition yields an
  identical end state (the LWW CRDT property the whole design rests
  on), on both storage backends and with the device planner.
"""

import os
import random

import pytest

from evolu_tpu.core.timestamp import (
    Timestamp,
    receive_timestamp,
    send_timestamp,
    timestamp_to_string,
)
from evolu_tpu.core.types import CrdtMessage
from evolu_tpu.core.merkle import merkle_tree_to_string
from evolu_tpu.storage.apply import apply_messages, apply_messages_sequential
from evolu_tpu.storage.native import native_available, open_database
from evolu_tpu.storage.schema import init_db_model


def fresh_db(backend="python"):
    db = open_database(backend=backend)
    init_db_model(db, mnemonic=None)
    db.exec(
        'CREATE TABLE IF NOT EXISTS "todo" ('
        '"id" TEXT PRIMARY KEY, "title" BLOB, "n" BLOB)'
    )
    return db


def dump(db):
    return {
        "todo": db.exec('SELECT * FROM "todo" ORDER BY "id"'),
        "__message": db.exec('SELECT * FROM "__message" ORDER BY "timestamp"'),
    }


def make_contention_workload(n_replicas=64, n_rows=100, writes_per_replica=40, seed=4):
    """Config 4: every replica hammers the same rows; HLC clocks advance
    per replica with realistic receive() merges so counters collide."""
    rng = random.Random(seed)
    base = 1_700_000_000_000
    clocks = [Timestamp(base, 0, f"{i:016x}") for i in range(n_replicas)]
    messages = []
    for step in range(writes_per_replica):
        order = list(range(n_replicas))
        rng.shuffle(order)
        for r in order:
            # Frozen wall clock ⇒ counters increment ⇒ (counter, node)
            # tie-breaks dominate (the config-4 stress).
            now = base + (step // 8)
            clocks[r] = send_timestamp(clocks[r], now=now)
            row = f"row{rng.randrange(n_rows)}"
            messages.append(
                CrdtMessage(
                    timestamp_to_string(clocks[r]), "todo", row,
                    rng.choice(["title", "n"]),
                    f"r{r}s{step}",
                )
            )
            # Occasionally gossip clocks so replicas entangle.
            if rng.random() < 0.1:
                other = rng.randrange(n_replicas)
                if other != r:
                    clocks[other] = receive_timestamp(
                        clocks[other], clocks[r], now=now
                    )
    return messages


def lww_oracle(messages):
    """Pure-Python ground truth: winner per cell = max timestamp string."""
    winners = {}
    for m in messages:
        cell = (m.table, m.row, m.column)
        cur = winners.get(cell)
        if cur is None or cur.timestamp < m.timestamp:
            winners[cell] = m
    return {cell: m.value for cell, m in winners.items()}


def db_cells(db):
    out = {}
    for row in db.exec_sql_query('SELECT "id", "title", "n" FROM "todo"'):
        for col in ("title", "n"):
            if row[col] is not None:
                out[("todo", row["id"], col)] = row[col]
    return out


def test_config4_high_contention_64_replicas_100_rows():
    messages = make_contention_workload()
    oracle = lww_oracle(messages)

    # Three adversarial delivery orders, two backends.
    rng = random.Random(99)
    orders = [
        list(messages),
        list(reversed(messages)),
        rng.sample(messages, len(messages)),
    ]
    backends = ["python"] + (["native"] if native_available() else [])
    dumps = []
    for backend in backends:
        for order in orders:
            db = fresh_db(backend)
            apply_messages(db, {}, order)
            assert db_cells(db) == oracle
            d = dump(db)
            # __message content must also be identical (same set stored).
            dumps.append(d["__message"])
            db.close()
    assert all(d == dumps[0] for d in dumps), "replicas diverged on __message"


def test_convergence_under_partitioned_delivery():
    """Split the message set into random partitions applied as separate
    batches in different orders — state must still converge (models
    incremental anti-entropy rounds)."""
    messages = make_contention_workload(n_replicas=8, n_rows=20, writes_per_replica=25)
    oracle = lww_oracle(messages)
    rng = random.Random(5)
    final_dumps = []
    for trial in range(4):
        order = rng.sample(messages, len(messages))
        db = fresh_db()
        tree = {}
        i = 0
        while i < len(order):
            k = rng.randrange(1, 60)
            tree = apply_messages(db, tree, order[i : i + k])
            i += k
        assert db_cells(db) == oracle, trial
        final_dumps.append((dump(db), merkle_tree_to_string(tree)))
        db.close()
    trees = {t for _, t in final_dumps}
    assert len(trees) == 1, "merkle trees diverged across delivery orders"
    assert all(d == final_dumps[0][0] for d, _ in final_dumps)


def test_device_planner_matches_oracle_under_contention():
    """The TPU planner path (plan_batch_device) on the config-4 workload
    must produce the sequential oracle's exact end state."""
    from evolu_tpu.ops.merge import plan_batch_device

    messages = make_contention_workload(n_replicas=16, n_rows=10, writes_per_replica=12)
    a = fresh_db()
    with a.transaction():
        apply_messages_sequential(a, {}, messages)
    b = fresh_db()
    apply_messages(b, {}, messages, planner=plan_batch_device)
    assert dump(a) == dump(b)
    a.close(), b.close()


def test_config1_two_replicas_1k_messages_full_stack(tmp_path):
    """Config 1 shape: two clients, todo schema, ~1k messages through
    the real relay; byte-identical SQLite end state on both replicas."""
    from evolu_tpu.runtime.client import Evolu
    from evolu_tpu.server.relay import RelayServer, RelayStore
    from evolu_tpu.sync.client import connect
    from evolu_tpu.utils.config import Config

    server = RelayServer(RelayStore(str(tmp_path / "relay.db"))).start()
    try:
        cfg = Config(sync_url=server.url + "/")
        schema = {"todo": ("title", "n")}
        a = Evolu(db_path=str(tmp_path / "a.db"), config=cfg)
        a.update_db_schema(schema)
        connect(a)
        b = Evolu(db_path=str(tmp_path / "b.db"), config=cfg, mnemonic=a.owner.mnemonic)
        b.update_db_schema(schema)
        connect(b)

        rng = random.Random(11)
        ids = []
        # ~1k messages: 180 creates (x3 cols incl auto) + updates (x2).
        for i in range(180):
            client = a if rng.random() < 0.5 else b
            with client.batching():
                ids.append(client.create("todo", {"title": f"t{i}", "n": i}))
        def settle():
            for _ in range(6):
                for c in (a, b):
                    c.sync()
                    c.worker.flush(); c._transport.flush(); c.worker.flush()
        settle()
        for i in range(200):
            client = a if rng.random() < 0.5 else b
            client.update("todo", rng.choice(ids), {"n": 1000 + i})
        settle()

        dump_a = a.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
        dump_b = b.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
        assert len(dump_a) >= 900
        assert dump_a == dump_b, "replicas not byte-identical"
        rows_a = a.db.exec('SELECT * FROM "todo" ORDER BY "id"')
        rows_b = b.db.exec('SELECT * FROM "todo" ORDER BY "id"')
        assert rows_a == rows_b
        a.dispose(), b.dispose()
    finally:
        server.stop()


def test_chunked_apply_matches_single_batch():
    from evolu_tpu.storage.apply import apply_messages_chunked

    messages = make_contention_workload(n_replicas=6, n_rows=15, writes_per_replica=20)
    a, b = fresh_db(), fresh_db()
    tree_a = apply_messages(a, {}, messages)
    tree_b = apply_messages_chunked(b, {}, messages, chunk_size=37)
    assert dump(a) == dump(b)
    assert merkle_tree_to_string(tree_a) == merkle_tree_to_string(tree_b)
    a.close(), b.close()


def test_chunked_apply_callback_failure_rolls_back_chunk_atomically():
    """The chunk's rows and whatever on_chunk persists (the clock)
    commit atomically: a callback failure — simulating a crash between
    apply and clock persist — rolls back the WHOLE chunk, so committed
    __message rows can never outrun the persisted tree (which would be
    a permanent digest divergence on resync)."""
    from evolu_tpu.storage.apply import ChunkedApplyError, apply_messages_chunked

    msgs = make_contention_workload(n_replicas=4, n_rows=5, writes_per_replica=5)
    half = len(msgs) // 2
    db = fresh_db()
    calls = []

    def persist_then_crash(tree, n):
        calls.append(n)
        if len(calls) == 2:
            raise RuntimeError("crash before clock persist")

    with pytest.raises(ChunkedApplyError) as ei:
        apply_messages_chunked(db, {}, msgs, chunk_size=half, on_chunk=persist_then_crash)
    err = ei.value
    assert calls == [half, len(msgs)] and err.applied == half
    # The failed chunk's rows rolled back with the callback: end state ==
    # first chunk only, and the error's tree covers exactly those rows.
    fresh = fresh_db()
    expect_tree = apply_messages(fresh, {}, msgs[:half])
    assert dump(db) == dump(fresh)
    assert merkle_tree_to_string(err.partial_tree) == merkle_tree_to_string(expect_tree)
    db.close(), fresh.close()


def test_chunked_apply_failure_carries_partial_tree():
    from evolu_tpu.storage.apply import ChunkedApplyError, apply_messages_chunked

    good = make_contention_workload(n_replicas=4, n_rows=5, writes_per_replica=5)
    bad = CrdtMessage("not-a-timestamp", "todo", "r", "title", "x")
    db = fresh_db()
    seen = []
    with pytest.raises(ChunkedApplyError) as ei:
        apply_messages_chunked(
            db, {}, good + [bad], chunk_size=len(good),
            on_chunk=lambda tree, n: seen.append(n),
        )
    err = ei.value
    # First chunk committed and reported; its deltas survive in the error.
    assert seen == [len(good)] and err.applied == len(good)
    fresh = fresh_db()
    expect = apply_messages(fresh, {}, good)
    assert merkle_tree_to_string(err.partial_tree) == merkle_tree_to_string(expect)
    db.close(), fresh.close()
