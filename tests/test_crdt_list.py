"""RGA sequence CRDT — the `"col:list"` column type (ISSUE 14).

Layers under test, host-oracle-first (the PR-7 playbook):
1. op codecs (ValueError-only fuzz) + hand-model golden fixtures
   (tests/fixtures/crdt_list_golden.json — computed BY HAND, pinned,
   never updated) under arbitrary permutation/partition/redelivery on
   both storage backends;
2. the pure linearization oracle against an INDEPENDENT literal
   replay-the-inserts model, plus orphan/dangling-origin determinism;
3. the device twin (`ops/crdt_list_merge.py`) bit-identical to the
   oracle on random forests — batch core, Pallas-interpret scan route,
   and the reconcile-shaped shard core over the shared
   `pack_owner_cell_key` layout;
4. apply routing: list cells never LWW-upsert, batched == sequential
   oracle, device-routed materialization == host-routed, redelivery
   idempotence, late declaration, owner reset;
5. client API (drain-before-observe) + end-to-end: 2-relay
   anti-entropy + snapshot checkpoint carrying a MIXED
   counter/awset/list log crc-identically, `crdt-list-v1` negotiated.
"""

import json
import random
import time
import zlib
from pathlib import Path

import numpy as np
import pytest

from evolu_tpu.core import crdt_list as cl
from evolu_tpu.core import crdt_types as ct
from evolu_tpu.core.merkle import create_initial_merkle_tree
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtMessage, TableDefinition
from evolu_tpu.obs import metrics
from evolu_tpu.storage.apply import apply_messages, apply_messages_sequential
from evolu_tpu.storage.native import native_available, open_database
from evolu_tpu.storage.schema import init_db_model, update_db_schema
from evolu_tpu.utils.config import Config

MN = "legal winner thank year wave sausage worth useful legal winner thank yellow"
GOLDEN = json.loads(
    (Path(__file__).parent / "fixtures" / "crdt_list_golden.json").read_text())

SCHEMA_DEF = TableDefinition.of("doc", ("title", "body:list"))
BACKENDS = ["python"] + (["native"] if native_available() else [])


def _mk_db(backend="python"):
    db = open_database(":memory:", backend)
    init_db_model(db, MN)
    update_db_schema(db, [SCHEMA_DEF])
    return db


def _golden_msgs(section):
    t, r, c = section["cell"]
    return [CrdtMessage(op["timestamp"], t, r, c, op["value"])
            for op in section["ops"]]


def _app_value(db, column, row="r1", table="doc"):
    rows = db.exec_sql_query(
        f'SELECT "{column}" AS v FROM "{table}" WHERE "id" = ?', (row,))
    return rows[0]["v"] if rows else None


def _dump_all(db):
    return (
        db.exec_sql_query('SELECT * FROM "__message" ORDER BY "timestamp"'),
        db.exec_sql_query('SELECT * FROM "doc" ORDER BY "id"'),
        db.exec_sql_query('SELECT * FROM "__crdt_list" ORDER BY "tag"'),
        db.exec_sql_query('SELECT * FROM "__crdt_list_kill" ORDER BY "tag"'),
    )


# --- 1. codecs: ValueError-only ---


def test_list_op_codecs_roundtrip():
    v = cl.list_insert_value("hi")
    assert cl.decode_list_op(v) == ("i", "", '"hi"')
    v = cl.list_insert_value(7, after="tagA")
    assert cl.decode_list_op(v) == ("i", "tagA", "7")
    assert cl.decode_list_op(cl.list_delete_value("tagB")) == ("d", "tagB", "")
    # None `after` is the head, same bytes as an explicit "".
    assert cl.list_insert_value("x", after=None) == cl.list_insert_value("x", after="")


def test_list_op_codec_valueerror_only_fuzz():
    """ISSUE 14 satellite: field-level fuzz — anything malformed raises
    ValueError and nothing else (the wire-decoder contract, so a
    hostile peer's garbage is always classifiable and droppable)."""
    rng = random.Random(14)
    corpus = [
        None, 5, 1.5, b"x", "", "{", "[]", '["x",1]', '["i"]', '["i",""]',
        '["i","",1,2]', '["d"]', '["d","a","b"]', '["d",5]', '["i",5,"v"]',
        '["i","",true]', '["i","",[1]]', '["i","",{"k":1}]', '["i",null,"v"]',
        '["d",null]', '["i","' + "x" * 300 + '","v"]', '["d","' + "y" * 300 + '"]',
    ]
    corpus += ["".join(chr(rng.randrange(32, 127))
                       for _ in range(rng.randrange(0, 60)))
               for _ in range(300)]
    for c in corpus:
        try:
            cl.decode_list_op(c)
        except ValueError:
            pass  # the ONLY permitted error type
    with pytest.raises(ValueError):
        cl.list_insert_value(object())
    with pytest.raises(ValueError):
        cl.list_insert_value("v", after=5)
    with pytest.raises(ValueError):
        cl.list_delete_value(None)
    # Valid ops survive the same decoder.
    ins, dels, bad = cl.decode_list_batch([
        CrdtMessage("t1", "doc", "r", "body", cl.list_insert_value("a")),
        CrdtMessage("t2", "doc", "r", "body", "garbage"),
        CrdtMessage("t3", "doc", "r", "body", cl.list_delete_value("t1")),
    ])
    assert len(ins) == 1 and len(dels) == 1 and bad == 1


def test_column_spec_accepts_list():
    assert ct.parse_column_spec("body:list") == ("body", "list")
    with pytest.raises(ValueError):
        ct.parse_column_spec("body:rga")


# --- 2. the oracle vs an independent literal replay model ---


def _literal_replay(inserts):
    """The INDEPENDENT reference model: replay inserts in ascending
    raw-string timestamp order, each placed immediately after its
    origin (or at the head when the origin is absent/not yet placed) —
    O(n²), written the naive way on purpose."""
    order = []
    for tag, origin in sorted(inserts):
        at = order.index(origin) + 1 if origin in order else 0
        order.insert(at, tag)
    return order


@pytest.mark.parametrize("seed", [0, 7, 101, 2024])
def test_linearize_matches_literal_replay(seed):
    rng = random.Random(seed)
    n = rng.randrange(1, 120)
    tags = sorted({f"t{rng.randrange(10**9):010d}" for _ in range(n)})
    inserts = []
    for i, t in enumerate(tags):
        roll = rng.random()
        if roll < 0.25 or i == 0:
            o = ""
        elif roll < 0.85:
            o = tags[rng.randrange(i)]  # an already-delivered element
        elif roll < 0.95:
            o = "zzzz-dangling"  # never an element
        else:
            o = tags[rng.randrange(i, len(tags))]  # origin AFTER self (hostile)
        inserts.append((t, o))
    expect = _literal_replay(inserts)
    pos = cl.linearize([t for t, _ in inserts], [o for _, o in inserts])
    got = [t for _, t in sorted(zip(pos, [t for t, _ in inserts]))]
    assert got == expect
    # Permutation invariance: linearize is a function of the SET.
    perm = list(range(len(inserts)))
    rng.shuffle(perm)
    pos_p = cl.linearize([inserts[i][0] for i in perm],
                         [inserts[i][1] for i in perm])
    assert [pos_p[perm.index(i)] for i in range(len(inserts))] == pos


def test_linearize_rejects_duplicate_tags():
    with pytest.raises(ValueError):
        cl.linearize(["a", "a"], ["", ""])


# --- 3. golden fixtures (hand model; never update) ---


@pytest.mark.parametrize("backend", BACKENDS)
@pytest.mark.parametrize("section", ["list", "same_anchor", "delete_before_insert"])
@pytest.mark.parametrize("seed", [0, 7, 23])
def test_golden_any_order_any_partition(backend, section, seed):
    g = GOLDEN[section]
    row = g["cell"][1]
    msgs = _golden_msgs(g)
    msgs += [msgs[i] for i in g["redeliver"]]
    rng = random.Random(seed)
    rng.shuffle(msgs)
    db = _mk_db(backend)
    tree = create_initial_merkle_tree()
    i = 0
    while i < len(msgs):  # random partition into batches
        j = i + rng.randrange(1, len(msgs) - i + 1)
        tree = apply_messages(db, tree, msgs[i:j])
        i = j
    assert _app_value(db, "body", row) == g["expected_value"]
    # Stored document order (tombstones included) matches the hand model.
    rows = db.exec_sql_query(
        'SELECT "tag", "origin", "alive" FROM "__crdt_list" WHERE "row" = ?',
        (row,))
    pos = cl.linearize([r["tag"] for r in rows], [r["origin"] for r in rows])
    ordered = [r["tag"] for _, r in sorted(zip(pos, rows), key=lambda x: x[0])]
    assert ordered == g["expected_order_tags"]
    dead = {r["tag"] for r in rows if not r["alive"]}
    assert dead == set(g["expected_dead_tags"])
    # Redelivering EVERYTHING changes nothing (op-set semantics).
    apply_messages(db, tree, msgs)
    assert _app_value(db, "body", row) == g["expected_value"]


# --- 4. device twin: bit-identical to the oracle ---


def _random_forest(rng, n_cells, max_elems):
    """(cell_id, parent_ix, alive, spans, tags, origins) in the device
    layout: ascending (cell, tag), parents resolved per the oracle's
    rule (dangling/hostile origins → −1)."""
    cell_id, parent, alive, tags, origins, spans = [], [], [], [], [], []
    base = 0
    for c in range(n_cells):
        n = rng.randrange(1, max_elems)
        ctags = sorted({f"c{c}-{rng.randrange(10**9):010d}" for _ in range(n)})
        for j, t in enumerate(ctags):
            roll = rng.random()
            if roll < 0.3 or j == 0:
                o = ""
            elif roll < 0.9:
                o = ctags[rng.randrange(j)]
            else:
                o = "zzzz-dangling"
            p = base + ctags.index(o) if (o in ctags and o < t) else -1
            cell_id.append(c)
            parent.append(p)
            alive.append(rng.randrange(2))
            tags.append(t)
            origins.append(o)
        spans.append((base, len(ctags)))
        base += len(ctags)
    return (np.array(cell_id, np.int32), np.array(parent, np.int32),
            np.array(alive, np.int32), spans, tags, origins)


@pytest.mark.parametrize("seed", [3, 31, 555])
def test_rga_order_kernel_matches_oracle(seed):
    from evolu_tpu.ops.crdt_list_merge import rga_order

    rng = random.Random(seed)
    cell_id, parent, alive, spans, tags, origins = _random_forest(
        rng, rng.randrange(1, 8), 80)
    pos_d, slot_d = rga_order(cell_id, parent, alive)
    for b, n in spans:
        pos_h = cl.linearize(tags[b:b + n], origins[b:b + n])
        assert list(pos_d[b:b + n]) == pos_h
        # Alive slots are the alive-prefix in document order; dead = −1.
        expect_slot = {}
        s = 0
        for i in sorted(range(n), key=lambda i: pos_h[i]):
            expect_slot[i] = s if alive[b + i] else -1
            s += int(alive[b + i])
        assert [int(slot_d[b + i]) for i in range(n)] \
            == [expect_slot[i] for i in range(n)]


def test_rga_order_pallas_interpret_bit_identical():
    """The acceptance-criteria route: the alive-slot scan through the
    single-pass Pallas kernel (interpret mode) returns bit-identical
    (pos, slot) to the XLA-routed production path."""
    from evolu_tpu.ops.crdt_list_merge import rga_order

    rng = random.Random(8)
    cell_id, parent, alive, _spans, _t, _o = _random_forest(rng, 3, 60)
    a = rga_order(cell_id, parent, alive)
    b = rga_order(cell_id, parent, alive, interpret_pallas=True)
    assert np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])


def test_rga_order_deep_chain_and_bounds():
    from evolu_tpu.ops.crdt_list_merge import rga_order

    # A pure chain (every element inserted after the previous one — the
    # worst case for the pointer-jumping depth) linearizes exactly.
    n = 1000
    cell = np.zeros(n, np.int32)
    parent = np.arange(-1, n - 1, dtype=np.int32)
    alive = np.ones(n, np.int32)
    pos, slot = rga_order(cell, parent, alive)
    assert np.array_equal(pos, np.arange(n)) and np.array_equal(slot, np.arange(n))
    # Oversized batches refuse (the wrapper contract; the materializer
    # routes them to the host oracle instead of calling in).
    with pytest.raises(ValueError):
        rga_order(np.zeros(cl.DEVICE_MAX_ELEMS + 1, np.int32),
                  np.full(cl.DEVICE_MAX_ELEMS + 1, -1, np.int32),
                  np.ones(cl.DEVICE_MAX_ELEMS + 1, np.int32))


def test_list_shard_order_core_groups_by_owner_cell():
    """The reconcile-shaped shard kernel: (owner, cell) grouping via
    the SHARED pack_owner_cell_key layout — per-group positions equal
    the per-group oracle."""
    import jax
    import jax.numpy as jnp

    from evolu_tpu.ops.crdt_list_merge import list_shard_order_core

    rng = np.random.default_rng(12)
    n = 600
    owner = np.sort(rng.integers(0, 5, n)).astype(np.int64)
    cells = rng.integers(0, 7, n).astype(np.int32)
    parent = np.full(n, -1, np.int32)
    alive = rng.integers(0, 2, n).astype(np.int32)
    groups = {}
    for i in range(n):
        lst = groups.setdefault((int(owner[i]), int(cells[i])), [])
        if lst and rng.random() < 0.7:
            parent[i] = lst[int(rng.integers(0, len(lst)))]
        lst.append(i)
    with jax.enable_x64(True):
        pos, slot = jax.jit(list_shard_order_core)(
            jnp.asarray(owner), jnp.asarray(cells), jnp.asarray(parent),
            jnp.asarray(alive))
    pos, slot = np.asarray(pos), np.asarray(slot)
    for g, members in groups.items():
        tags = [f"{i:06d}" for i in members]
        origins = ["" if parent[i] < 0 else f"{parent[i]:06d}" for i in members]
        assert [int(pos[i]) for i in members] == cl.linearize(tags, origins), g
        alive_sorted = [i for i in sorted(members, key=lambda i: pos[i])
                        if alive[i]]
        assert [int(slot[i]) for i in alive_sorted] == list(range(len(alive_sorted)))


def test_device_routed_materialization_equals_host(monkeypatch):
    """Force the device route at a tiny threshold: the materialized
    app bytes and every state row must equal the host-routed twin."""
    msgs = _random_list_log(99, n=500)
    db_host, db_dev = _mk_db(), _mk_db()
    apply_messages(db_host, create_initial_merkle_tree(), msgs)
    monkeypatch.setattr(ct, "DEVICE_FOLD_MIN", 1)
    before = metrics.get_counter("evolu_crdt_list_linearize_total", path="device")
    apply_messages(db_dev, create_initial_merkle_tree(), msgs)
    assert metrics.get_counter(
        "evolu_crdt_list_linearize_total", path="device") > before
    assert _dump_all(db_host) == _dump_all(db_dev)


# --- 5. apply routing ---


def _random_list_log(seed, n=300, table="doc", column="body"):
    """A hostile mixed log: inserts (incl. same-anchor races and
    dangling origins), deletes (incl. delete-before-insert), malformed
    ops, LWW traffic on a sibling column, and redelivery."""
    rng = random.Random(seed)
    nodes = ["aaaaaaaaaaaaaaa1", "bbbbbbbbbbbbbbb2", "ccccccccccccccc3"]
    msgs, tag_pool = [], []
    for i in range(n):
        ts = timestamp_to_string(
            Timestamp(1_700_000_000_000 + i * 977, i % 3, rng.choice(nodes)))
        roll = rng.random()
        row = f"r{rng.randrange(5)}"
        if roll < 0.45:
            after = rng.choice(tag_pool) if tag_pool and rng.random() < 0.7 else None
            if rng.random() < 0.05:
                after = "2099-dangling-origin"
            msgs.append(CrdtMessage(ts, table, row, column,
                                    cl.list_insert_value(f"v{i}", after=after)))
            tag_pool.append(ts)
        elif roll < 0.60 and tag_pool:
            # Delete a random tag — sometimes one whose insert sits
            # LATER in the shuffled delivery (delete-before-insert).
            msgs.append(CrdtMessage(ts, table, row, column,
                                    cl.list_delete_value(rng.choice(tag_pool))))
        elif roll < 0.70:
            msgs.append(CrdtMessage(ts, table, row, column, rng.choice(
                ["not json", 5, '["x"]', '["i"]', '["d",7]'])))
        else:
            msgs.append(CrdtMessage(ts, table, row, "title", f"t{i}"))
    msgs += rng.sample(msgs, min(len(msgs), 40))
    return msgs


@pytest.mark.parametrize("backend", BACKENDS)
@pytest.mark.parametrize("seed", [5, 42])
def test_batched_equals_sequential_oracle(backend, seed):
    msgs = _random_list_log(seed)
    db_a, db_b = _mk_db(backend), _mk_db(backend)
    with db_a.transaction():
        apply_messages_sequential(db_a, create_initial_merkle_tree(), msgs)
    apply_messages(db_b, create_initial_merkle_tree(), msgs)
    assert _dump_all(db_a) == _dump_all(db_b)


@pytest.mark.parametrize("seed", [11, 77])
def test_convergence_under_arbitrary_schedules(seed):
    """Two replicas, the same op set in UNRELATED orders/partitions →
    byte-identical state, and the materialized value equals the pure
    host-oracle replay of the log (the model-check invariant)."""
    msgs = _random_list_log(seed, n=200)
    rng = random.Random(seed + 1)
    dbs = []
    for _rep in range(2):
        sh = msgs[:]
        rng.shuffle(sh)
        db = _mk_db()
        tree = create_initial_merkle_tree()
        i = 0
        while i < len(sh):
            j = i + rng.randrange(1, len(sh) - i + 1)
            tree = apply_messages(db, tree, sh[i:j])
            i = j
        dbs.append(db)
    assert _dump_all(dbs[0]) == _dump_all(dbs[1])
    expected = cl.replay_log(
        [m for m in msgs if m.column == "body"])
    for (t, row, _c), val in expected.items():
        assert _app_value(dbs[0], "body", row) == val


def test_list_cells_never_lww_upsert():
    """The largest-timestamp op here is a DELETE; the cell must read
    the materialized array, never the raw op JSON."""
    base = 1_700_000_000_000
    mk = lambda i, v: CrdtMessage(  # noqa: E731
        timestamp_to_string(Timestamp(base + i * 1000, 0, "aaaaaaaaaaaaaaa1")),
        "doc", "r1", "body", v)
    t0 = timestamp_to_string(Timestamp(base, 0, "aaaaaaaaaaaaaaa1"))
    t1 = timestamp_to_string(Timestamp(base + 1000, 0, "aaaaaaaaaaaaaaa1"))
    msgs = [mk(0, cl.list_insert_value("a")), mk(1, cl.list_insert_value("b", after=t0)),
            mk(2, cl.list_delete_value(t1))]
    db = _mk_db()
    apply_messages(db, create_initial_merkle_tree(), msgs)
    assert _app_value(db, "body") == '["a"]'


def test_malformed_ops_counted_and_ignored():
    metrics.reset()
    base = 1_700_000_000_000
    mk = lambda i, v: CrdtMessage(  # noqa: E731
        timestamp_to_string(Timestamp(base + i * 1000, 0, "aaaaaaaaaaaaaaa1")),
        "doc", "r1", "body", v)
    msgs = [mk(0, cl.list_insert_value("x")), mk(1, "not-json"), mk(2, 5)]
    db = _mk_db()
    apply_messages(db, create_initial_merkle_tree(), msgs)
    assert _app_value(db, "body") == '["x"]'
    assert metrics.get_counter("evolu_crdt_malformed_ops_total", type="list") == 2
    assert len(db.exec_sql_query('SELECT * FROM "__message"')) == 3


def test_late_declaration_folds_predeclaration_ops():
    """Rolling upgrade: list ops that reached __message while the
    column was still LWW fold at declaration time — both replicas
    materialize identically regardless of declaration timing."""
    base = 1_700_000_000_000
    t0 = timestamp_to_string(Timestamp(base, 0, "aaaaaaaaaaaaaaa1"))
    mk = lambda i, v: CrdtMessage(  # noqa: E731
        timestamp_to_string(Timestamp(base + i * 1000, 0, "aaaaaaaaaaaaaaa1")),
        "doc", "r1", "body", v)
    ops = [mk(0, cl.list_insert_value("a")),
           mk(1, cl.list_insert_value("b", after=t0))]

    late = open_database(":memory:", "python")
    init_db_model(late, MN)
    update_db_schema(late, [TableDefinition.of("doc", ("title", "body"))])
    apply_messages(late, create_initial_merkle_tree(), ops)
    assert _app_value(late, "body") == ops[1].value  # LWW winner, pre-upgrade
    update_db_schema(late, [SCHEMA_DEF])  # the upgrade declares the type

    early = _mk_db()
    apply_messages(early, create_initial_merkle_tree(), ops)
    for db in (late, early):
        assert _app_value(db, "body") == '["a","b"]'
    assert _dump_all(late)[2:] == _dump_all(early)[2:]
    # Later ops keep folding incrementally on both.
    more = [mk(10, cl.list_delete_value(t0))]
    for db in (late, early):
        apply_messages(db, create_initial_merkle_tree(), more)
        assert _app_value(db, "body") == '["b"]'


def test_rebuild_state_matches_incremental():
    msgs = _random_list_log(123, n=200)
    db = _mk_db()
    apply_messages(db, create_initial_merkle_tree(), msgs)
    before = _dump_all(db)
    ct.rebuild_state(db, ct.load_schema(db))
    assert _dump_all(db) == before


def test_reset_owner_drops_list_state():
    from evolu_tpu.runtime.client import create_evolu

    e = create_evolu({"doc": ("body:list",)}, config=Config(backend="cpu"))
    try:
        row = e.create("doc", {})
        e.list_append("doc", row, "body", "x")
        e.worker.flush()
        assert e.db.exec_sql_query('SELECT * FROM "__crdt_list"')
        e.reset_owner()
        e.worker.flush()
        e.update_db_schema({"doc": ("body:list",)})
        e.worker.flush()
        assert ct.load_schema(e.db).column_type("doc", "body") == "list"
        assert e.db.exec_sql_query('SELECT * FROM "__crdt_list"') == []
    finally:
        e.dispose()


# --- 6. client API: drain-before-observe ---


def test_client_api_interleaved_inserts_and_deletes():
    from evolu_tpu.runtime.client import create_evolu

    e = create_evolu({"doc": ("body:list",)}, config=Config(backend="cpu"))
    try:
        row = e.create("doc", {})
        # Two appends with NO flush between them: the drain inside
        # list_append must observe the first before anchoring the
        # second (the set_remove lesson — without it they'd reverse).
        e.list_append("doc", row, "body", "a")
        e.list_append("doc", row, "body", "b")
        elems = e.list_elements("doc", row, "body")
        assert [v for _t, v in elems] == ["a", "b"]
        e.list_insert("doc", row, "body", "mid", after=elems[0][0])
        e.list_insert("doc", row, "body", "head")  # after=None = head
        e.list_delete("doc", row, "body", elems[1][0])
        got = e.list_elements("doc", row, "body")
        assert [v for _t, v in got] == ["head", "a", "mid"]
        assert _app_value(e.db, "body", row) == '["head","a","mid"]'
    finally:
        e.dispose()


def test_winner_cache_contract_list_cells():
    """List cells keep slot == MAX(timestamp) (the xor gate) while the
    app value is the linearized materialization."""
    from evolu_tpu.runtime.client import create_evolu

    e = create_evolu({"doc": ("body:list",)},
                     config=Config(backend="tpu", min_device_batch=1))
    try:
        e.worker._planner.cache.adaptive = False
        row = e.create("doc", {})
        for v in ("x", "y"):
            e.list_append("doc", row, "body", v)
        e.worker.flush()
        cache = e.worker._planner.cache
        assert cache is not None and cache._slots
        w1 = np.asarray(cache._w1)
        w2 = np.asarray(cache._w2)
        checked_list = 0
        schema = ct.load_schema(e.db)
        for (table, r, col), slot in cache._slots.items():
            got = e.db.exec_sql_query(
                'SELECT MAX("timestamp") AS m FROM "__message" '
                'WHERE "table" = ? AND "row" = ? AND "column" = ?',
                (table, r, col))[0]["m"]
            k1, k2 = int(w1[slot]), int(w2[slot])
            cached_ts = timestamp_to_string(
                Timestamp(k1 >> 16, k1 & 0xFFFF, f"{k2:016x}"))
            assert cached_ts == got, (table, r, col)
            if schema.column_type(table, col) == "list":
                checked_list += 1
        assert checked_list >= 1
        assert _app_value(e.db, "body", row) == '["x","y"]'
    finally:
        e.dispose()


# --- 7. end-to-end: mixed 3-type log through relay + snapshot ---


def _converge(replicas, deadline_s=30.0):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        for r in replicas:
            r.sync()
            r.worker.flush()
        dumps = [r.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
                 for r in replicas]
        if all(d == dumps[0] for d in dumps):
            return
        time.sleep(0.05)
    raise AssertionError("replicas did not converge in time")


def test_mixed_typed_log_relay_replication_snapshot_crc(tmp_path):
    """ISSUE 14 satellite: counter/awset/list ops in ONE batch ride
    relay, replication, and snapshot unchanged — relay B converges
    byte-identically through Merkle anti-entropy, a checkpoint of A
    restores crc-identically, fresh clients on every relay materialize
    the same three typed values, and `crdt-list-v1` is negotiated."""
    from evolu_tpu.runtime.client import create_evolu
    from evolu_tpu.server import snapshot
    from evolu_tpu.server.relay import RelayServer, RelayStore
    from evolu_tpu.sync import protocol
    from evolu_tpu.sync.client import connect

    schema = {"doc": ("title", "clicks:counter", "tags:awset", "body:list")}
    a = RelayServer(RelayStore(), peers=[]).start()
    b = c = None
    e1 = e2 = e3 = None
    try:
        e1 = create_evolu(schema, config=Config(sync_url=a.url))
        connect(e1)
        row = e1.create("doc", {"title": "page"})
        e1.list_append("doc", row, "body", "H")
        # ONE Send carrying all three op kinds (the mixed batch). The
        # list op anchors at the head (in-batch elements are unstamped,
        # so there is nothing to observe — documented contract).
        with e1.batching():
            e1.increment("doc", row, "clicks", 5)
            e1.set_add("doc", row, "tags", "red")
            e1.list_insert("doc", row, "body", "i")
        e1.worker.flush()
        e1.list_append("doc", row, "body", "!")
        # Document order is now [i, H, !]; delete the H in the middle.
        elems = e1.list_elements("doc", row, "body")
        assert [v for _t, v in elems] == ["i", "H", "!"]
        e1.list_delete("doc", row, "body", elems[1][0])
        e1.worker.flush()
        e1.sync()
        e1.worker.flush()
        e1._transport.flush()
        caps = e1._transport.negotiated_capabilities
        assert any(protocol.CAP_CRDT_LIST in v for v in caps.values()), caps

        owner = e1.owner.id
        state = lambda store: (  # noqa: E731
            store.get_merkle_tree_string(owner),
            store.replica_messages(owner, ""),
        )
        b = RelayServer(RelayStore(), peers=[a.url],
                        replication_interval_s=0.1).start()
        deadline = time.time() + 20
        while time.time() < deadline:
            if state(b.store) == state(a.store) and state(a.store)[1]:
                break
            time.sleep(0.05)
        assert state(b.store) == state(a.store)

        path = str(tmp_path / "a.checkpoint")
        snapshot.write_checkpoint(a.store, path)
        fresh = RelayStore()
        snapshot.restore_checkpoint(fresh, path)
        crc = lambda store: zlib.crc32(repr(state(store)).encode())  # noqa: E731
        assert crc(fresh) == crc(a.store)
        c = RelayServer(fresh).start()

        e2 = create_evolu(schema, config=Config(sync_url=b.url),
                          mnemonic=e1.owner.mnemonic)
        e3 = create_evolu(schema, config=Config(sync_url=c.url),
                          mnemonic=e1.owner.mnemonic)
        connect(e2)
        connect(e3)
        _converge([e1, e2])
        _converge([e1, e3])
        for e in (e1, e2, e3):
            r = e.db.exec_sql_query(
                'SELECT "clicks", "tags", "body" FROM "doc"')[0]
            assert (r["clicks"], r["tags"], r["body"]) \
                == (5, '["red"]', '["i","!"]')
        dumps = [e.db.exec_sql_query('SELECT * FROM "__crdt_list" ORDER BY "tag"')
                 for e in (e1, e2, e3)]
        assert dumps[0] == dumps[1] == dumps[2]
    finally:
        for e in (e1, e2, e3):
            if e is not None:
                e.dispose()
        for s in (a, b, c):
            if s is not None:
                s.stop()
