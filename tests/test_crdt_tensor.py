"""Tensor-valued CRDT columns (ISSUE 20).

Layers under test, host-oracle-first:
1. type-string + op codecs (ValueError-only) and the byte cap;
2. hand-model golden fixtures (tests/fixtures/crdt_tensor_golden.json
   — computed BY HAND, pinned, never updated) under every delivery
   permutation / partition / redelivery, both storage backends;
3. device twin (`ops/crdt_tensor_merge.py`) bit-identical to the
   pure-numpy host fold for every monoid (incl. the overwrite∘delta
   semidirect composition), Pallas interpret-mode parity, packed AND
   wide shard variants, jit-cache fence flat within batch buckets;
4. apply routing: tensor cells never LWW-upsert, batched ==
   sequential oracle with malformed traffic mixed in, late
   declaration folds pre-declaration ops, rebuild_state identical;
5. winner-cache contract (slot == MAX(timestamp), value == fold) and
   the client API's drain-before-observe reads.
"""

import base64
import json
import random
from pathlib import Path

import numpy as np
import pytest

from evolu_tpu.core import crdt_tensor as tz
from evolu_tpu.core import crdt_types as ct
from evolu_tpu.core.merkle import create_initial_merkle_tree
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtMessage, TableDefinition
from evolu_tpu.obs import metrics
from evolu_tpu.storage.apply import apply_messages, apply_messages_sequential
from evolu_tpu.storage.native import native_available, open_database
from evolu_tpu.storage.schema import init_db_model, update_db_schema
from evolu_tpu.utils.config import Config

MN = "legal winner thank year wave sausage worth useful legal winner thank yellow"
GOLDEN = json.loads(
    (Path(__file__).parent / "fixtures" / "crdt_tensor_golden.json").read_text())

SCHEMA_DEF = TableDefinition.of(
    "models",
    ("name", "weights:tensor:sum:f32:2", "avg:tensor:mean:f32:2",
     "peak:tensor:max:f32:2", "grad:tensor:sum:bf16:3"))

BACKENDS = ["python"] + (["native"] if native_available() else [])


def _mk_db(backend="python"):
    db = open_database(":memory:", backend)
    init_db_model(db, MN)
    update_db_schema(db, [SCHEMA_DEF])
    return db


def _golden_msgs(section):
    t, r, c = section["cell"]
    return [CrdtMessage(op["timestamp"], t, r, c, op["value"])
            for op in section["ops"]]


def _golden_expected(section):
    cfg = tz.parse_tensor_type(section["column_type"])
    return np.asarray(section["expected_elements"],
                      np.float64).astype(tz._np_dtype(cfg))


def _ts(i, node="aaaaaaaaaaaaaaa1", base=1_700_000_000_000):
    return timestamp_to_string(Timestamp(base + i * 1000, 0, node))


# --- 1. type strings + codecs ---


def test_tensor_type_parsing():
    cfg = tz.parse_tensor_type("tensor:sum:f32:4x8")
    assert (cfg.monoid, cfg.dtype, cfg.shape) == ("sum", "f32", (4, 8))
    assert cfg.size == 32 and cfg.nbytes == 128
    assert tz.parse_tensor_type("tensor:mean:bf16:3").nbytes == 6
    assert tz.tensor_type("max", "f32", (2, 3)) == "tensor:max:f32:2x3"
    assert tz.is_tensor_type("tensor:sum:f32:1")
    assert not tz.is_tensor_type("counter")
    for bad in (
        "tensor", "tensor:sum", "tensor:sum:f32", "tensor:sum:f32:",
        "tensor:bogus:f32:2", "tensor:sum:f64:2", "tensor:sum:f32:0",
        "tensor:sum:f32:2x", "tensor:sum:f32:x2", "tensor:sum:f32:02",
        "tensor:sum:f32:-2", "tensor:sum:f32:2x3:extra",
        "tensor:sum:f32:" + "x".join(["2"] * 9),  # > _MAX_DIMS
        "tensor:sum:f32:65536",  # f32 nbytes over TENSOR_MAX_BYTES
    ):
        with pytest.raises(ValueError):
            tz.parse_tensor_type(bad)
    # The byte cap is dtype-aware: 32768 f32 elements = 128KiB > cap,
    # but the same element count in bf16 is exactly AT the 64KiB cap.
    with pytest.raises(ValueError):
        tz.parse_tensor_type("tensor:sum:f32:32768")
    assert tz.parse_tensor_type("tensor:sum:bf16:32768").nbytes == \
        tz.TENSOR_MAX_BYTES


def test_column_spec_routes_tensor_types():
    assert ct.parse_column_spec("weights:tensor:sum:f32:2x3") == \
        ("weights", "tensor:sum:f32:2x3")
    for bad in ("weights:tensor:sum:f32:nope", "weights:tensor", "a:b:c",
                ":tensor:sum:f32:2"):
        with pytest.raises(ValueError):
            ct.parse_column_spec(bad)


def test_tensor_op_codecs_valueerror_only():
    cfg = tz.parse_tensor_type("tensor:sum:f32:2")
    v = tz.tensor_delta_value(cfg, [1.5, -2.0])
    assert tz.decode_tensor_op(cfg, v) == (
        "d", np.asarray([1.5, -2.0], np.float32).tobytes(), 1)
    s = tz.tensor_set_value(cfg, [3.0, 4.0])
    assert tz.decode_tensor_op(cfg, s)[0] == "s"
    cfgm = tz.parse_tensor_type("tensor:mean:f32:2")
    vm = tz.tensor_delta_value(cfgm, [1.0, 2.0], count=7)
    assert tz.decode_tensor_op(cfgm, vm)[2] == 7
    # Encoder-side screens.
    with pytest.raises(ValueError):
        tz.tensor_delta_value(cfg, [1.0])  # wrong element count
    with pytest.raises(ValueError):
        tz.tensor_delta_value(cfg, [np.inf, 0.0])
    with pytest.raises(ValueError):
        tz.tensor_delta_value(cfg, [40000.0, 0.0])  # |v| > 2^15
    with pytest.raises(ValueError):
        tz.tensor_delta_value(cfgm, [1.0, 2.0], count=0)
    with pytest.raises(ValueError):
        tz.tensor_delta_value(cfgm, [1.0, 2.0], count=tz._COUNT_MAX + 1)
    # max skips the magnitude cap (no lattice quantization).
    cfgx = tz.parse_tensor_type("tensor:max:f32:2")
    big = tz.tensor_delta_value(cfgx, [1e30, -1e30])
    assert tz.decode_tensor_op(cfgx, big)[0] == "d"
    # Decoder: the count slot is mean's weight ONLY.
    three = json.dumps(["d", base64.b64encode(
        np.zeros(2, np.float32).tobytes()).decode(), 2])
    with pytest.raises(ValueError):
        tz.decode_tensor_op(cfg, three)  # sum rejects 3-element form
    assert tz.decode_tensor_op(cfgm, three)[2] == 2
    rng = random.Random(20)
    ok64 = base64.b64encode(np.zeros(2, np.float32).tobytes()).decode()
    corpus = [
        None, 5, 1.5, b"x", "", "{", "[]", '["d"]', '["x","%s"]' % ok64,
        '["d","not-base64!!"]', '["d","%s",1,2]' % ok64, '["d",5]',
        '["s","%s","2"]' % ok64, '["d","%s",true]' % ok64,
        '["d","%s",-1]' % ok64, '["d","' + "A" * 200000 + '"]',
        json.dumps(["d", base64.b64encode(b"abc").decode()]),  # bad length
        json.dumps(["d", base64.b64encode(
            np.asarray([np.nan, 0], np.float32).tobytes()).decode()]),
        json.dumps(["d", base64.b64encode(
            np.asarray([4e4, 0], np.float32).tobytes()).decode()]),
    ]
    corpus += ["".join(chr(rng.randrange(32, 127))
                       for _ in range(rng.randrange(0, 60)))
               for _ in range(200)]
    for cfg_i in (cfg, cfgm, cfgx):
        for c in corpus:
            try:
                tz.decode_tensor_op(cfg_i, c)
            except ValueError:
                pass  # the ONLY permitted error type


def test_schema_registry_tensor_conflicts():
    db = _mk_db()
    schema = ct.load_schema(db)
    assert schema.column_type("models", "weights") == "tensor:sum:f32:2"
    assert schema.has_typed([("models", "rX", "weights")])
    # Same full type string is idempotent; ANY parameter change raises.
    ct.declare_column_types(db, [("models", "weights", "tensor:sum:f32:2")])
    for other in ("tensor:max:f32:2", "tensor:sum:bf16:2",
                  "tensor:sum:f32:3", "counter"):
        with pytest.raises(ValueError):
            ct.declare_column_types(db, [("models", "weights", other)])


# --- 2. goldens (hand model; never update) ---


@pytest.mark.parametrize("backend", BACKENDS)
@pytest.mark.parametrize("seed", [0, 7, 23])
@pytest.mark.parametrize("section", [k for k in GOLDEN if k != "_comment"])
def test_golden_any_order_any_partition(backend, seed, section):
    g = GOLDEN[section]
    msgs = _golden_msgs(g)
    msgs += [msgs[i] for i in g["redeliver"]]
    rng = random.Random(seed)
    rng.shuffle(msgs)
    db = _mk_db(backend)
    tree = create_initial_merkle_tree()
    i = 0
    while i < len(msgs):  # random partition into batches
        j = i + rng.randrange(1, len(msgs) - i + 1)
        tree = apply_messages(db, tree, msgs[i:j])
        i = j
    table, row, column = g["cell"]
    expected = _golden_expected(g)
    got = tz.tensor_state(db, table, row, column)
    assert got is not None and np.array_equal(got, expected), (got, expected)
    # Redelivering EVERYTHING changes nothing (op-set semantics).
    apply_messages(db, tree, msgs)
    assert np.array_equal(tz.tensor_state(db, table, row, column), expected)


@pytest.mark.parametrize("section", [k for k in GOLDEN if k != "_comment"])
def test_golden_pure_fold_oracle(section):
    """fold_cell alone (no SQL) reproduces every golden under every
    permutation — the oracle the device twin is then pinned against."""
    g = GOLDEN[section]
    cfg = tz.parse_tensor_type(g["column_type"])
    ops = []
    for op in g["ops"]:
        kind, payload, count = tz.decode_tensor_op(cfg, op["value"])
        ops.append((op["timestamp"], kind, count, payload))
    expected = _golden_expected(g).tobytes()
    rng = random.Random(99)
    for _ in range(6):
        shuffled = ops + [ops[i] for i in g["redeliver"]]
        rng.shuffle(shuffled)
        assert tz.fold_cell(cfg, shuffled) == expected


def test_golden_max_plus_zero_wins():
    """-0.0 orders strictly below +0.0 in the monotone key space: the
    materialized element is +0.0 bit-exactly."""
    g = GOLDEN["tensor_max"]
    cfg = tz.parse_tensor_type(g["column_type"])
    ops = [(op["timestamp"],) + tuple(
        tz.decode_tensor_op(cfg, op["value"])[i] for i in (0, 2, 1))
        for op in g["ops"]]
    out = np.frombuffer(tz.fold_cell(cfg, ops), np.float32)
    assert out[1] == 0.0 and not np.signbit(out[1])


# --- 3. device twin: bit parity, every monoid, packed + wide shards ---


def _random_cell_ops(rng, cfg, n_cells, max_ops):
    """{cell index: [(tag, kind, count, payload)]} with random set/delta
    mixes — raw material for both the host oracle and the device twin."""
    per_cell = {}
    t = 0
    for c in range(n_cells):
        ops = []
        for _ in range(rng.integers(1, max_ops + 1)):
            vals = (rng.random(cfg.size) * 64.0 - 32.0).astype(np.float32)
            payload = vals.astype(tz._np_dtype(cfg)).tobytes()
            kind = "s" if rng.random() < 0.25 else "d"
            count = int(rng.integers(1, 9)) if cfg.monoid == "mean" else 1
            ops.append((_ts(t), kind, count, payload))
            t += 1
        per_cell[c] = ops
    return per_cell


@pytest.mark.parametrize("type_string", [
    "tensor:sum:f32:4", "tensor:mean:bf16:3", "tensor:max:f32:5"])
@pytest.mark.parametrize("seed", [2, 17])
def test_tensor_cell_folds_match_oracle(type_string, seed):
    from evolu_tpu.ops.crdt_tensor_merge import tensor_cell_folds

    cfg = tz.parse_tensor_type(type_string)
    rng = np.random.default_rng(seed)
    n_cells = int(rng.integers(3, 40))
    per_cell = _random_cell_ops(rng, cfg, n_cells, 12)
    plans = {c: tz.contributing_ops(ops) for c, ops in per_cell.items()}
    cell_id, rows = [], []
    for c, contribs in plans.items():
        for _kind, count, payload in contribs:
            if cfg.monoid == "max":
                rows.append(tz.monotone_key(cfg, payload).astype(np.uint64))
            else:
                k = count if cfg.monoid == "mean" else 1
                rows.append(tz.quantize(cfg, payload).view(np.uint64)
                            * np.uint64(k))
            cell_id.append(c)
    cell_id = np.asarray(cell_id, np.int32)
    contrib = np.stack(rows)
    table = tensor_cell_folds(cell_id, contrib, n_cells, cfg.monoid)
    # Permutation invariance is BIT-exact (modular u64 / integer max).
    perm = rng.permutation(len(cell_id))
    table_p = tensor_cell_folds(cell_id[perm], contrib[perm], n_cells,
                                cfg.monoid)
    assert np.array_equal(table, table_p)
    for c, contribs in plans.items():
        dens = sum(k for _, k, _ in contribs) if cfg.monoid == "mean" else 1
        host = tz._fold_contributions(cfg, contribs)
        dev = tz._finalize(cfg, table[c], dens)
        assert host == dev, (type_string, c)


@pytest.mark.parametrize("variant", ["packed", "wide"])
def test_tensor_shard_sums_both_variants_match_oracle(variant):
    from evolu_tpu.ops import crdt_tensor_merge as tm

    metrics.reset()
    rng = np.random.default_rng(11)
    n, width = 2048, 3
    owner = rng.integers(0, 6, n).astype(np.int64)
    # Cell ids are globally interned (unique per owner) — the wide
    # variant's by-cell-alone segmentation contract.
    cell = (rng.integers(0, 40, n) * 6 + owner).astype(np.int64)
    if variant == "wide":
        cell = cell + (1 << 26)  # past the packed 2^25 cell budget
    contrib = rng.integers(0, 1 << 40, (n, width)).astype(np.uint64)
    got = tm.tensor_shard_sums(owner, cell, contrib)
    expect = {}
    for o, c, v in zip(owner, cell, contrib):
        key = (int(o), int(c))
        expect[key] = expect.get(key, np.zeros(width, np.uint64)) + v
    assert set(got) == set(expect)
    for key in expect:
        assert np.array_equal(got[key], expect[key].view(np.int64)), key
    assert metrics.get_counter(
        "evolu_crdt_tensor_kernel_total", variant=variant) == 1
    other = "wide" if variant == "packed" else "packed"
    assert metrics.get_counter(
        "evolu_crdt_tensor_kernel_total", variant=other) == 0
    # Partition invariance: two halves accumulate to the one-shot totals
    # (modular add — the cross-chunk contract the 2^24 chunker relies on).
    cut = n // 2
    g1 = tm.tensor_shard_sums(owner[:cut], cell[:cut], contrib[:cut])
    g2 = tm.tensor_shard_sums(owner[cut:], cell[cut:], contrib[cut:])
    for key in expect:
        acc = np.zeros(width, np.uint64)
        for g in (g1, g2):
            if key in g:
                acc += g[key].view(np.uint64)
        assert np.array_equal(acc.view(np.int64), got[key]), key


@pytest.mark.parametrize("n", [255, 4096])
def test_tensor_flat_layout_pallas_interpret_parity(n):
    """The d-major flattened scan layout produces identical u64 planes
    through the blocked XLA scan and the single-pass Pallas kernel in
    interpret mode — the same pinning discipline as test_pallas.py,
    applied to the tensor fold's tiled-flag formulation."""
    import jax

    from evolu_tpu.ops.crdt_merge import segmented_sum_scan
    from evolu_tpu.ops.pallas_scan import (
        PALLAS_AVAILABLE, segmented_max_scan_pallas, segmented_sum_scan_pallas)

    if not PALLAS_AVAILABLE:
        pytest.skip("pallas unavailable")
    width = 3
    rng = np.random.default_rng(n)
    c_s = np.sort(rng.integers(0, 37, n)).astype(np.int32)
    seg = np.concatenate([[True], c_s[1:] != c_s[:-1]])
    flags = np.tile(seg, width)
    flat = rng.integers(0, 1 << 48, n * width).astype(np.uint64)
    with jax.enable_x64(True):
        blocked = np.asarray(segmented_sum_scan(
            np.asarray(flags), np.asarray(flat)))
        pal = np.asarray(segmented_sum_scan_pallas(
            np.asarray(flags), np.asarray(flat), interpret=True))
    assert np.array_equal(blocked, pal)
    from evolu_tpu.ops.merge import _segmented_max_scan
    with jax.enable_x64(True):
        m_blocked = np.asarray(_segmented_max_scan(
            np.asarray(flags), np.asarray(flat),
            np.asarray(np.zeros_like(flat)))[0])
        m_pal = np.asarray(segmented_max_scan_pallas(
            np.asarray(flags), np.asarray(flat),
            np.asarray(np.zeros_like(flat)), interpret=True)[0])
    assert np.array_equal(m_blocked, m_pal)


def test_tensor_jit_cache_flat_within_buckets():
    """Batch-bucket fence: same-bucket tensor dispatches reuse the ONE
    compiled core; only a new (bucket, width, monoid) key may add an
    entry. Guards the batch-bucket-stable-shapes invariant for the big
    fused pipeline."""
    from evolu_tpu.ops import crdt_tensor_merge as tm

    cfg = tz.parse_tensor_type("tensor:sum:f32:4")
    rng = np.random.default_rng(5)

    def _dispatch(n_ops, n_cells):
        cell_id = rng.integers(0, n_cells, n_ops).astype(np.int32)
        contrib = rng.integers(0, 1 << 40, (n_ops, 4)).astype(np.uint64)
        tm.tensor_cell_folds(cell_id, contrib, n_cells, cfg.monoid)

    _dispatch(100, 9)  # warm the (128-bucket, 16-bucket) entry
    warm = tm.tensor_cell_fold_core._cache_size()
    _dispatch(70, 12)   # same op bucket (128), same cell bucket (16)
    _dispatch(128, 16)  # exactly at the bucket edges
    assert tm.tensor_cell_fold_core._cache_size() == warm
    _dispatch(300, 9)   # new op bucket → exactly one new entry
    assert tm.tensor_cell_fold_core._cache_size() == warm + 1


@pytest.mark.parametrize("backend", BACKENDS)
def test_device_routing_equals_host_routing_end_to_end(backend, monkeypatch):
    """Force the device fold on one replica and the host fold on the
    other: the materialized app bytes and every state table must be
    IDENTICAL — the bit-parity acceptance bar, exercised through the
    full apply path."""
    msgs = _random_tensor_log(4242)
    db_host, db_dev = _mk_db(backend), _mk_db(backend)
    monkeypatch.setattr(ct, "DEVICE_FOLD_MIN", 10**12)
    apply_messages(db_host, create_initial_merkle_tree(), msgs)
    monkeypatch.setattr(ct, "DEVICE_FOLD_MIN", 1)
    apply_messages(db_dev, create_initial_merkle_tree(), msgs)
    assert _dump_all(db_host) == _dump_all(db_dev)


def test_oversized_cell_falls_back_to_host(monkeypatch):
    """A single cell wider than one dispatch budget folds on the host
    oracle (counted) — and still lands the exact same bytes."""
    metrics.reset()
    monkeypatch.setattr(ct, "DEVICE_FOLD_MIN", 1)
    monkeypatch.setattr(tz, "DEVICE_MAX_FLAT", 8)
    cfg = tz.parse_tensor_type("tensor:sum:f32:2")
    db = _mk_db()
    msgs = [CrdtMessage(_ts(i), "models", "r1", "weights",
                        tz.tensor_delta_value(cfg, [float(i), 1.0]))
            for i in range(8)]  # 8 ops × 2 elems > 8 flat budget
    apply_messages(db, create_initial_merkle_tree(), msgs)
    assert metrics.get_counter("evolu_crdt_tensor_oversized_host_folds_total") == 1
    expect = np.asarray([sum(range(8)), 8.0], np.float32)
    assert np.array_equal(tz.tensor_state(db, "models", "r1", "weights"), expect)


# --- 4. apply routing: batched == sequential, malformed, rebuild ---


def _random_tensor_log(seed, n=160):
    """Mixed tensor + LWW traffic with malformed tensor ops sprinkled
    in, across every declared monoid/dtype, plus redelivery."""
    rng = random.Random(seed)
    nodes = ["aaaaaaaaaaaaaaa1", "bbbbbbbbbbbbbbb2"]
    cols = {
        "weights": tz.parse_tensor_type("tensor:sum:f32:2"),
        "avg": tz.parse_tensor_type("tensor:mean:f32:2"),
        "peak": tz.parse_tensor_type("tensor:max:f32:2"),
        "grad": tz.parse_tensor_type("tensor:sum:bf16:3"),
    }
    msgs = []
    for i in range(n):
        ts = timestamp_to_string(
            Timestamp(1_700_000_000_000 + i * 977, i % 3, rng.choice(nodes)))
        row = f"r{rng.randrange(4)}"
        roll = rng.random()
        if roll < 0.12:
            msgs.append(CrdtMessage(ts, "models", row, "name", f"n{i}"))
        elif roll < 0.24:  # malformed tensor ops: ignored identically
            col = rng.choice(list(cols))
            val = rng.choice(["junk", '["d","bad!"]', 5, '["s"]',
                              '["d","%s",3]' % base64.b64encode(
                                  np.zeros(2, np.float32).tobytes()).decode()])
            msgs.append(CrdtMessage(ts, "models", row, col, val))
        else:
            col = rng.choice(list(cols))
            cfg = cols[col]
            vals = [rng.uniform(-30, 30) for _ in range(cfg.size)]
            kind = tz.tensor_set_value if rng.random() < 0.3 \
                else tz.tensor_delta_value
            count = rng.randrange(1, 6) if cfg.monoid == "mean" else 1
            msgs.append(CrdtMessage(ts, "models", row, col,
                                    kind(cfg, vals, count=count)))
    msgs += rng.sample(msgs, min(len(msgs), 30))
    return msgs


def _dump_all(db):
    return (
        db.exec_sql_query('SELECT * FROM "__message" ORDER BY "timestamp"'),
        db.exec_sql_query('SELECT * FROM "models" ORDER BY "id"'),
        db.exec_sql_query(
            'SELECT * FROM "__crdt_tensor" ORDER BY "tag", "column"'),
    )


@pytest.mark.parametrize("backend", BACKENDS)
@pytest.mark.parametrize("seed", [5, 42])
def test_batched_equals_sequential_oracle_tensor(backend, seed):
    msgs = _random_tensor_log(seed)
    db_a, db_b = _mk_db(backend), _mk_db(backend)
    with db_a.transaction():
        apply_messages_sequential(db_a, create_initial_merkle_tree(), msgs)
    apply_messages(db_b, create_initial_merkle_tree(), msgs)
    assert _dump_all(db_a) == _dump_all(db_b)


@pytest.mark.parametrize("backend", BACKENDS)
def test_apply_matches_replay_oracle(backend):
    """End state == the pure replay_log oracle for every tensor cell —
    the same oracle the model-check episode asserts against."""
    msgs = _random_tensor_log(77, n=220)
    db = _mk_db(backend)
    apply_messages(db, create_initial_merkle_tree(), msgs)
    types = {("models", c): t for c, t in (
        ("weights", "tensor:sum:f32:2"), ("avg", "tensor:mean:f32:2"),
        ("peak", "tensor:max:f32:2"), ("grad", "tensor:sum:bf16:3"))}
    oracle = tz.replay_log(types, msgs)
    assert oracle  # the log generator must actually produce tensor cells
    for (table, row, column), expected in oracle.items():
        got = tz.tensor_state(db, table, row, column)
        assert got is not None and got.tobytes() == expected, (row, column)


def test_tensor_cells_never_lww_upsert():
    """The LARGEST-timestamp op carries a tiny delta; the app value
    must read the FOLD (base + deltas), not that op's raw payload."""
    cfg = tz.parse_tensor_type("tensor:sum:f32:2")
    msgs = [
        CrdtMessage(_ts(0), "models", "r1", "weights",
                    tz.tensor_set_value(cfg, [10.0, 20.0])),
        CrdtMessage(_ts(1), "models", "r1", "weights",
                    tz.tensor_delta_value(cfg, [0.5, -0.5])),
    ]
    db = _mk_db()
    apply_messages(db, create_initial_merkle_tree(), msgs)
    got = tz.tensor_state(db, "models", "r1", "weights")
    assert np.array_equal(got, np.asarray([10.5, 19.5], np.float32))


def test_malformed_tensor_ops_counted_and_ignored():
    metrics.reset()
    cfg = tz.parse_tensor_type("tensor:sum:f32:2")
    msgs = [
        CrdtMessage(_ts(0), "models", "r1", "weights",
                    tz.tensor_delta_value(cfg, [1.0, 2.0])),
        CrdtMessage(_ts(1), "models", "r1", "weights", "garbage"),
        CrdtMessage(_ts(2), "models", "r1", "weights", '["d","bad64!"]'),
    ]
    db = _mk_db()
    apply_messages(db, create_initial_merkle_tree(), msgs)
    assert np.array_equal(tz.tensor_state(db, "models", "r1", "weights"),
                          np.asarray([1.0, 2.0], np.float32))
    assert metrics.get_counter(
        "evolu_crdt_malformed_ops_total", type="tensor") == 2
    assert metrics.get_counter("evolu_crdt_ops_total", type="tensor") == 1
    assert metrics.get_counter(
        "evolu_crdt_tensor_ops_total", kind="delta") == 1
    # All three are in the transport log regardless (semantics untouched).
    assert len(db.exec_sql_query('SELECT * FROM "__message"')) == 3


def test_late_declaration_folds_predeclaration_tensor_ops():
    """Ops that reached __message BEFORE the tensor declaration
    (rolling upgrade) fold at declaration time — both replicas land
    identical bytes (anti-entropy could never heal a divergence)."""
    cfg = tz.parse_tensor_type("tensor:sum:f32:2")
    ops = [CrdtMessage(_ts(0), "models", "r1", "weights",
                       tz.tensor_set_value(cfg, [4.0, 8.0])),
           CrdtMessage(_ts(1), "models", "r1", "weights",
                       tz.tensor_delta_value(cfg, [1.0, -1.0]))]
    late = open_database(":memory:", "python")
    init_db_model(late, MN)
    update_db_schema(late, [TableDefinition.of("models", ("name", "weights"))])
    apply_messages(late, create_initial_merkle_tree(), ops)
    update_db_schema(late, [SCHEMA_DEF])  # the upgrade declares the type
    early = _mk_db()
    apply_messages(early, create_initial_merkle_tree(), ops)
    expect = np.asarray([5.0, 7.0], np.float32)
    for db in (late, early):
        got = tz.tensor_state(db, "models", "r1", "weights")
        assert np.array_equal(got, expect)
    # Later ops keep folding incrementally on both.
    more = [CrdtMessage(_ts(10), "models", "r1", "weights",
                        tz.tensor_delta_value(cfg, [0.5, 0.5]))]
    for db in (late, early):
        apply_messages(db, create_initial_merkle_tree(), more)
        assert np.array_equal(
            tz.tensor_state(db, "models", "r1", "weights"),
            np.asarray([5.5, 7.5], np.float32))


def test_rebuild_state_matches_incremental_tensor():
    msgs = _random_tensor_log(123, n=140)
    db = _mk_db()
    apply_messages(db, create_initial_merkle_tree(), msgs)
    before = _dump_all(db)
    ct.rebuild_state(db, ct.load_schema(db))
    assert _dump_all(db) == before


# --- 5. winner cache + client API ---


def test_winner_cache_contract_tensor_cells():
    """Tensor cells keep slot == MAX(timestamp) (the xor gate) while
    the app value is the monoid fold — same contract as the other
    typed families (test_crdt_types.py owns the counter/awset legs)."""
    from evolu_tpu.runtime.client import create_evolu

    e = create_evolu({"models": ("name", "weights:tensor:sum:f32:2")},
                     config=Config(backend="tpu", min_device_batch=1))
    try:
        e.worker._planner.cache.adaptive = False
        row = e.create("models", {"name": "m"})
        e.worker.flush()
        e.tensor_set("models", row, "weights", [10.0, 20.0])
        e.tensor_delta("models", row, "weights", [0.25, -0.25])
        e.tensor_delta("models", row, "weights", [0.25, -0.25])
        e.worker.flush()
        cache = e.worker._planner.cache
        assert cache is not None and cache._slots
        w1 = np.asarray(cache._w1)
        w2 = np.asarray(cache._w2)
        checked = 0
        for (table, r, col), slot in cache._slots.items():
            got = e.db.exec_sql_query(
                'SELECT MAX("timestamp") AS m FROM "__message" '
                'WHERE "table" = ? AND "row" = ? AND "column" = ?',
                (table, r, col))[0]["m"]
            k1, k2 = int(w1[slot]), int(w2[slot])
            cached_ts = timestamp_to_string(
                Timestamp(k1 >> 16, k1 & 0xFFFF, f"{k2:016x}"))
            assert cached_ts == got, (table, r, col)
            if col == "weights":
                checked += 1
        assert checked == 1
        got = e.tensor_value("models", row, "weights")
        assert np.array_equal(got, np.asarray([10.5, 19.5], np.float32))
    finally:
        e.dispose()


def test_client_tensor_api_drains_before_observe():
    """tensor_value drains the worker queue first: a just-queued delta
    is visible without an explicit flush (same review finding as
    set_remove-covers-queued-add)."""
    from evolu_tpu.runtime.client import create_evolu

    e = create_evolu({"models": ("name", "avg:tensor:mean:f32:2")},
                     config=Config(backend="cpu"))
    try:
        row = e.create("models", {"name": "m"})
        e.tensor_set("models", row, "avg", [100.0, 200.0], count=2)
        e.tensor_delta("models", row, "avg", [5.0, 8.0], count=3)
        got = e.tensor_value("models", row, "avg")  # no flush between
        assert np.array_equal(got, np.asarray([43.0, 84.8], np.float32))
        # An undeclared column fails loudly instead of silently LWWing.
        with pytest.raises(ValueError):
            e.tensor_delta("models", row, "name", [1.0, 2.0])
    finally:
        e.dispose()


def test_reset_owner_drops_tensor_state():
    from evolu_tpu.runtime.client import create_evolu

    e = create_evolu({"models": ("weights:tensor:sum:f32:2",)},
                     config=Config(backend="cpu"))
    try:
        row = e.create("models", {})
        e.tensor_delta("models", row, "weights", [1.0, 2.0])
        e.worker.flush()
        assert e.db.exec_sql_query('SELECT * FROM "__crdt_tensor"')
        e.reset_owner()
        e.worker.flush()
        e.update_db_schema({"models": ("weights:tensor:sum:f32:2",)})
        e.worker.flush()
        assert e.db.exec_sql_query('SELECT * FROM "__crdt_tensor"') == []
    finally:
        e.dispose()
