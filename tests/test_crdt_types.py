"""CRDT column types beyond the LWW register (ISSUE 7).

Layers under test, host-oracle-first:
1. op codecs + hand-model golden fixtures (tests/fixtures/crdt_golden.json
   — computed BY HAND, pinned, never updated);
2. device kernels (`ops/crdt_merge.py`) bit-identical to the host folds
   on property-sampled op logs (permutation + partition invariance);
3. apply routing: typed cells never LWW-upsert, fold+materialize inside
   the apply transaction, batched == sequential-oracle end state on both
   storage backends, redelivery idempotence;
4. winner-cache contract per type (slot == MAX(timestamp); app value ==
   merge-state fold);
5. end-to-end: 2-relay anti-entropy + snapshot checkpoint carrying
   typed ops crc-identically, capability negotiated.
"""

import json
import random
import time
import zlib
from pathlib import Path

import numpy as np
import pytest

from evolu_tpu.core import crdt_types as ct
from evolu_tpu.core.merkle import create_initial_merkle_tree
from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.core.types import CrdtMessage, TableDefinition
from evolu_tpu.obs import metrics
from evolu_tpu.ops import crdt_merge as cm
from evolu_tpu.storage.apply import apply_messages, apply_messages_sequential
from evolu_tpu.storage.native import native_available, open_database
from evolu_tpu.storage.schema import init_db_model, update_db_schema
from evolu_tpu.utils.config import Config

MN = "legal winner thank year wave sausage worth useful legal winner thank yellow"
GOLDEN = json.loads((Path(__file__).parent / "fixtures" / "crdt_golden.json").read_text())

SCHEMA_DEF = TableDefinition.of(
    "metrics", ("name", "clicks:counter", "tags:awset", "items:list"))


def _mk_db(backend="python"):
    db = open_database(":memory:", backend)
    init_db_model(db, MN)
    update_db_schema(db, [SCHEMA_DEF])
    return db


def _golden_msgs(section):
    cell = section.get("cell")
    out = []
    for op in section["ops"]:
        t, r, c = (op.get("table"), op.get("row"), op.get("column")) if cell is None \
            else cell
        out.append(CrdtMessage(op["timestamp"], op.get("table", t), op.get("row", r),
                               op.get("column", c), op["value"]))
    return out


def _app_value(db, column, row="r1"):
    rows = db.exec_sql_query(
        f'SELECT "{column}" AS v FROM "metrics" WHERE "id" = ?', (row,)
    )
    return rows[0]["v"] if rows else None


# --- 1. codecs ---


def test_column_spec_parsing():
    assert ct.parse_column_spec("title") == ("title", "lww")
    assert ct.parse_column_spec("clicks:counter") == ("clicks", "counter")
    assert ct.parse_column_spec("tags:awset") == ("tags", "awset")
    for bad in ("clicks:bogus", ":counter", "a:b:c"):
        with pytest.raises(ValueError):
            ct.parse_column_spec(bad)


def test_op_codecs_valueerror_only():
    """Typed-op codec fuzz (ISSUE 7 satellite): anything malformed
    raises ValueError and nothing else — mirroring the wire decoder
    contract, so a hostile peer's garbage is always classifiable."""
    assert ct.counter_delta(-5) == -5
    for bad in (True, False, None, "5", 1.5, 2**31, -(2**31), [], {}):
        with pytest.raises(ValueError):
            ct.counter_delta(bad)
    v = ct.set_add_value("red")
    assert ct.decode_set_op(v) == ("a", '"red"', ())
    rv = ct.set_remove_value(7, ["t2", "t1", "t1"])
    assert ct.decode_set_op(rv) == ("r", "7", ("t1", "t2"))
    rng = random.Random(5)
    corpus = [
        None, 5, 1.5, b"x", "", "{", "[]", '["x",1]', '["a"]', '["a",1,2]',
        '["r","e"]', '["r","e","x"]', '["r","e",[5]]', '["a",true]',
        '["a",[1]]', '["a",{"k":1}]', '["r",null,[]]' ,
    ]
    corpus += ["".join(chr(rng.randrange(32, 127)) for _ in range(rng.randrange(0, 40)))
               for _ in range(200)]
    for c in corpus:
        try:
            ct.decode_set_op(c)
        except ValueError:
            pass  # the ONLY permitted error type
    with pytest.raises(ValueError):
        ct.set_add_value(object())
    with pytest.raises(ValueError):
        ct.set_remove_value("e", [1])


def test_schema_registry_persistence_and_conflict():
    db = _mk_db()
    schema = ct.load_schema(db)
    assert schema.column_type("metrics", "clicks") == "counter"
    assert schema.column_type("metrics", "tags") == "awset"
    assert schema.column_type("metrics", "name") == "lww"
    assert schema.has_typed([("metrics", "rX", "clicks")])
    assert not schema.has_typed([("metrics", "rX", "name")])
    # Redeclaration with the same type is idempotent; a DIFFERENT type raises.
    ct.declare_column_types(db, [("metrics", "clicks", "counter")])
    with pytest.raises(ValueError):
        ct.declare_column_types(db, [("metrics", "clicks", "awset")])
    # Cache invalidation: a new declaration is visible immediately.
    ct.declare_column_types(db, [("metrics", "votes", "counter")])
    assert ct.load_schema(db).column_type("metrics", "votes") == "counter"


# --- 2. golden fixtures (hand model; never update) ---


@pytest.mark.parametrize("backend", ["python"] + (["native"] if native_available() else []))
@pytest.mark.parametrize("seed", [0, 7, 23])
def test_golden_counter_any_order_any_partition(backend, seed):
    g = GOLDEN["counter"]
    msgs = _golden_msgs(g)
    msgs += [msgs[i] for i in g["redeliver"]]
    rng = random.Random(seed)
    rng.shuffle(msgs)
    db = _mk_db(backend)
    tree = create_initial_merkle_tree()
    i = 0
    while i < len(msgs):  # random partition into batches
        j = i + rng.randrange(1, len(msgs) - i + 1)
        tree = apply_messages(db, tree, msgs[i:j])
        i = j
    assert _app_value(db, "clicks") == g["expected_value"]
    state = db.exec_sql_query('SELECT "pos", "neg" FROM "__crdt_counter"')
    assert (state[0]["pos"], state[0]["neg"]) == (g["expected_pos"], g["expected_neg"])
    # Redelivering EVERYTHING changes nothing (op-set semantics).
    tree = apply_messages(db, tree, msgs)
    assert _app_value(db, "clicks") == g["expected_value"]


@pytest.mark.parametrize("backend", ["python"] + (["native"] if native_available() else []))
@pytest.mark.parametrize("seed", [1, 13, 99])
def test_golden_awset_any_order_any_partition(backend, seed):
    g = GOLDEN["awset"]
    msgs = _golden_msgs(g)
    msgs += [msgs[i] for i in g["redeliver"]]
    rng = random.Random(seed)
    rng.shuffle(msgs)
    db = _mk_db(backend)
    tree = create_initial_merkle_tree()
    i = 0
    while i < len(msgs):
        j = i + rng.randrange(1, len(msgs) - i + 1)
        tree = apply_messages(db, tree, msgs[i:j])
        i = j
    assert _app_value(db, "tags") == g["expected_value"]
    alive = {r["tag"] for r in db.exec_sql_query(
        'SELECT "tag" FROM "__crdt_set" WHERE "alive" = 1')}
    assert alive == set(g["expected_alive_tags"])
    dead_known = {r["tag"] for r in db.exec_sql_query(
        'SELECT "tag" FROM "__crdt_set" WHERE "alive" = 0')}
    # Every hand-model dead tag is either a dead stored add or a
    # tombstone-only kill (the not-yet-seen-add case).
    kills = {r["tag"] for r in db.exec_sql_query('SELECT "tag" FROM "__crdt_kill"')}
    for t in g["expected_dead_tags"]:
        assert t in dead_known or t in kills


def test_golden_mixed_lww_untouched():
    """LWW columns in a table WITH typed columns keep exact reference
    semantics (winner upsert, raw value)."""
    g = GOLDEN["mixed_lww"]
    msgs = _golden_msgs(g) + _golden_msgs(GOLDEN["counter"])
    db = _mk_db()
    apply_messages(db, create_initial_merkle_tree(), msgs)
    assert _app_value(db, "name") == g["expected_value"]
    assert _app_value(db, "clicks") == GOLDEN["counter"]["expected_value"]


# --- 3. device twins: bit-identical, permutation/partition invariant ---


@pytest.mark.parametrize("seed", [2, 17, 4040])
def test_counter_kernel_matches_oracle_and_invariances(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 20_000))
    k = int(rng.integers(1, 200))
    cell = rng.integers(0, k, n).astype(np.int32)
    delta = rng.integers(-(2**31) + 1, 2**31, n).astype(np.int64)
    pos, neg = cm.pn_counter_sums(cell, delta, k)
    hp = np.zeros(k, np.int64)
    hn = np.zeros(k, np.int64)
    np.add.at(hp, cell, np.where(delta > 0, delta, 0))
    np.add.at(hn, cell, np.where(delta < 0, -delta, 0))
    assert np.array_equal(pos, hp) and np.array_equal(neg, hn)
    # Permutation invariance.
    perm = rng.permutation(n)
    pos_p, neg_p = cm.pn_counter_sums(cell[perm], delta[perm], k)
    assert np.array_equal(pos_p, pos) and np.array_equal(neg_p, neg)
    # Partition invariance (chunked accumulation == one batch).
    cut = n // 3
    p1, n1 = cm.pn_counter_sums(cell[:cut], delta[:cut], k)
    p2, n2 = cm.pn_counter_sums(cell[cut:], delta[cut:], k)
    assert np.array_equal(p1 + p2, pos) and np.array_equal(n1 + n2, neg)


@pytest.mark.parametrize("seed", [3, 31])
def test_awset_kernel_matches_oracle(seed):
    rng = random.Random(seed)
    tags = [f"tag{i:05d}" for i in range(rng.randrange(1, 3000))]
    kills = {t for t in tags if rng.random() < 0.3} | {f"phantom{i}" for i in range(7)}
    state_killed = {t for t in tags if rng.random() < 0.1} | {"elsewhere"}
    host = ct.alive_add_flags(tags, kills, state_killed)
    dev = cm.awset_alive_flags(tags, kills, state_killed)
    assert host == dev
    # Membership fold: order-free, duplicate-safe scatter-OR.
    pairs = np.array([rng.randrange(40) for _ in tags], np.int32)
    alive = np.array(host, bool)
    member = cm.awset_membership(pairs, alive, 40)
    expect = np.zeros(40, np.int32)
    np.maximum.at(expect, pairs, alive.astype(np.int32))
    assert np.array_equal(member, expect)
    perm = np.array(rng.sample(range(len(tags)), len(tags)))
    assert np.array_equal(cm.awset_membership(pairs[perm], alive[perm], 40), expect)


@pytest.mark.parametrize("n", [1, 255, 256, 8192, 40_000])
def test_segmented_sum_scan_formulations_agree(n):
    """Blocked two-level == associative_scan reference == Pallas
    (interpret mode) for the sum monoid — same pinning discipline as
    the lex-max scan (tests/test_pallas.py)."""
    import jax

    rng = np.random.default_rng(n)
    flags = rng.random(n) < 0.1
    flags[0] = True
    vals = rng.integers(0, 2**33, n).astype(np.uint64)
    with jax.enable_x64(True):
        ref = np.asarray(cm._segmented_sum_scan_reference(
            np.asarray(flags), np.asarray(vals)))
        blocked = np.asarray(cm.segmented_sum_scan(np.asarray(flags), np.asarray(vals)))
    assert np.array_equal(ref, blocked)
    from evolu_tpu.ops.pallas_scan import PALLAS_AVAILABLE, segmented_sum_scan_pallas

    if PALLAS_AVAILABLE and n <= 8192:  # interpret mode is slow; bound it
        with jax.enable_x64(True):
            pal = np.asarray(segmented_sum_scan_pallas(
                np.asarray(flags), np.asarray(vals), interpret=True))
        assert np.array_equal(ref, pal)


def test_counter_shard_sums_core_groups_by_owner_cell():
    """The reconcile-shaped sharded fold: (owner, cell) segments via
    the SHARED pack_owner_cell_key layout — totals at seg-end rows
    equal the per-(owner, cell) oracle sums."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(8)
    n = 4096
    owner = rng.integers(0, 5, n).astype(np.int64)
    cell = rng.integers(0, 50, n).astype(np.int32)
    delta = rng.integers(-100, 100, n).astype(np.int64)
    with jax.enable_x64(True):
        grp, seg_end, pos_sum, neg_sum = jax.jit(cm.counter_shard_sums_core)(
            jnp.asarray(owner), jnp.asarray(cell), jnp.asarray(delta)
        )
    grp, seg_end = np.asarray(grp), np.asarray(seg_end)
    pos_sum, neg_sum = np.asarray(pos_sum), np.asarray(neg_sum)
    got = {}
    for g, e, p, q in zip(grp, seg_end, pos_sum, neg_sum):
        if e:
            got[int(g)] = (int(p), int(q))
    expect = {}
    for o, c, d in zip(owner, cell, delta):
        key = (int(o) << 25 | int(c))
        p, q = expect.get(key, (0, 0))
        expect[key] = (p + max(d, 0), q + max(-d, 0))
    assert got == {k: v for k, v in expect.items()}


# --- 4. apply routing: batched == sequential oracle, both backends ---


def _random_mixed_log(seed, n=300):
    from evolu_tpu.core import crdt_list as cl

    rng = random.Random(seed)
    nodes = ["aaaaaaaaaaaaaaa1", "bbbbbbbbbbbbbbb2"]
    msgs = []
    tag_pool = []
    elem_pool = []  # list element tags (insert op timestamps)
    for i in range(n):
        ts = timestamp_to_string(
            Timestamp(1_700_000_000_000 + i * 977, i % 3, rng.choice(nodes))
        )
        roll = rng.random()
        row = f"r{rng.randrange(6)}"
        if roll < 0.25:
            msgs.append(CrdtMessage(ts, "metrics", row, "clicks",
                                    rng.randrange(-50, 50)))
        elif roll < 0.38:
            msgs.append(CrdtMessage(ts, "metrics", row, "tags",
                                    ct.set_add_value(rng.choice("abcde"))))
            tag_pool.append(ts)
        elif roll < 0.46 and tag_pool:
            observed = rng.sample(tag_pool, min(len(tag_pool), rng.randrange(0, 4)))
            msgs.append(CrdtMessage(ts, "metrics", row, "tags",
                                    ct.set_remove_value(rng.choice("abcde"), observed)))
        elif roll < 0.58:
            after = rng.choice(elem_pool) if elem_pool and rng.random() < 0.7 \
                else None
            msgs.append(CrdtMessage(ts, "metrics", row, "items",
                                    cl.list_insert_value(f"e{i}", after=after)))
            elem_pool.append(ts)
        elif roll < 0.64 and elem_pool:
            msgs.append(CrdtMessage(ts, "metrics", row, "items",
                                    cl.list_delete_value(rng.choice(elem_pool))))
        elif roll < 0.72:
            # Malformed typed ops: must be ignored identically everywhere.
            col, val = rng.choice([("clicks", "oops"), ("clicks", 2**40),
                                   ("tags", "{not json"), ("tags", 5),
                                   ("items", "nope"), ("items", '["i"]')])
            msgs.append(CrdtMessage(ts, "metrics", row, col, val))
        else:
            msgs.append(CrdtMessage(ts, "metrics", row, "name", f"n{i}"))
    # Redeliver a sample (dedup must hold).
    msgs += rng.sample(msgs, min(len(msgs), 40))
    return msgs


def _dump_all(db):
    return (
        db.exec_sql_query('SELECT * FROM "__message" ORDER BY "timestamp"'),
        db.exec_sql_query('SELECT * FROM "metrics" ORDER BY "id"'),
        db.exec_sql_query('SELECT * FROM "__crdt_counter" ORDER BY "table", "row", "column"'),
        db.exec_sql_query('SELECT * FROM "__crdt_set" ORDER BY "tag"'),
        db.exec_sql_query('SELECT * FROM "__crdt_kill" ORDER BY "tag"'),
        db.exec_sql_query('SELECT * FROM "__crdt_list" ORDER BY "tag"'),
        db.exec_sql_query('SELECT * FROM "__crdt_list_kill" ORDER BY "tag"'),
    )


@pytest.mark.parametrize("backend", ["python"] + (["native"] if native_available() else []))
@pytest.mark.parametrize("seed", [5, 42])
def test_batched_equals_sequential_oracle_mixed(backend, seed):
    msgs = _random_mixed_log(seed)
    db_a, db_b = _mk_db(backend), _mk_db(backend)
    with db_a.transaction():
        apply_messages_sequential(db_a, create_initial_merkle_tree(), msgs)
    apply_messages(db_b, create_initial_merkle_tree(), msgs)
    assert _dump_all(db_a) == _dump_all(db_b)


@pytest.mark.parametrize("backend", ["python"] + (["native"] if native_available() else []))
def test_device_planner_equals_host_for_typed(backend):
    """The device full-plan (and its typed upsert strip) produces the
    same end state as the host planner on a typed batch."""
    from evolu_tpu.ops.merge import plan_batch_device_full

    msgs = _random_mixed_log(77, n=400)
    db_a, db_b = _mk_db(backend), _mk_db(backend)
    apply_messages(db_a, create_initial_merkle_tree(), msgs)
    apply_messages(db_b, create_initial_merkle_tree(), msgs,
                   planner=plan_batch_device_full)
    assert _dump_all(db_a) == _dump_all(db_b)


def test_typed_cells_never_lww_upsert():
    """A counter cell's app value is NEVER the raw winning op value:
    the largest-timestamp op here carries delta -1, and the cell must
    read the SUM, not -1."""
    base = 1_700_000_000_000
    msgs = [
        CrdtMessage(timestamp_to_string(Timestamp(base + i * 1000, 0,
                                                  "aaaaaaaaaaaaaaa1")),
                    "metrics", "r1", "clicks", d)
        for i, d in enumerate([10, 20, -1])
    ]
    db = _mk_db()
    apply_messages(db, create_initial_merkle_tree(), msgs)
    assert _app_value(db, "clicks") == 29


def test_malformed_ops_counted_and_ignored():
    metrics.reset()
    base = 1_700_000_000_000
    mk = lambda i, col, v: CrdtMessage(  # noqa: E731
        timestamp_to_string(Timestamp(base + i * 1000, 0, "aaaaaaaaaaaaaaa1")),
        "metrics", "r1", col, v)
    msgs = [mk(0, "clicks", 5), mk(1, "clicks", "garbage"),
            mk(2, "tags", ct.set_add_value("x")), mk(3, "tags", "not-json")]
    db = _mk_db()
    apply_messages(db, create_initial_merkle_tree(), msgs)
    assert _app_value(db, "clicks") == 5
    assert _app_value(db, "tags") == '["x"]'
    assert metrics.get_counter("evolu_crdt_malformed_ops_total", type="counter") == 1
    assert metrics.get_counter("evolu_crdt_malformed_ops_total", type="awset") == 1
    # All four ops are in the log (transport semantics untouched).
    assert len(db.exec_sql_query('SELECT * FROM "__message"')) == 4


# --- 5. winner-cache contract per type ---


def test_winner_cache_contract_typed_cells():
    """Typed cells keep slot == MAX(timestamp) (the xor gate) while the
    app value is the merge-state fold — the per-type cache contract."""
    from evolu_tpu.runtime.client import create_evolu

    e = create_evolu({"metrics": ("name", "clicks:counter", "tags:awset")},
                     config=Config(backend="tpu", min_device_batch=1))
    try:
        # Pin the static cached path: the adaptive gate would stream
        # these all-new-cell micro-batches (dropping the slots this
        # test reads); the contract under test is the slot invariant,
        # not the gating policy (tests/test_winner_cache.py owns that).
        e.worker._planner.cache.adaptive = False
        row = e.create("metrics", {"name": "n"})
        e.worker.flush()
        for d in (4, -1, 9):
            e.increment("metrics", row, "clicks", d)
        e.set_add("metrics", row, "tags", "t1")
        e.worker.flush()
        cache = e.worker._planner.cache
        assert cache is not None and cache._slots
        w1 = np.asarray(cache._w1)
        w2 = np.asarray(cache._w2)
        checked_typed = 0
        schema = ct.load_schema(e.db)
        for (table, r, col), slot in cache._slots.items():
            got = e.db.exec_sql_query(
                'SELECT MAX("timestamp") AS m FROM "__message" '
                'WHERE "table" = ? AND "row" = ? AND "column" = ?',
                (table, r, col))[0]["m"]
            k1, k2 = int(w1[slot]), int(w2[slot])
            cached_ts = timestamp_to_string(
                Timestamp(k1 >> 16, k1 & 0xFFFF, f"{k2:016x}"))
            assert cached_ts == got, (table, r, col)
            if schema.is_typed(table, col):
                checked_typed += 1
        assert checked_typed >= 2  # clicks + tags slots were exercised
        assert _app_value(e.db, "clicks", row) == 12
        assert _app_value(e.db, "tags", row) == '["t1"]'
    finally:
        e.dispose()


# --- 6. end-to-end: anti-entropy + snapshot carry typed state ---


def _converge(replicas, deadline_s=30.0):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        for r in replicas:
            r.sync()
            r.worker.flush()
        dumps = [r.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
                 for r in replicas]
        if all(d == dumps[0] for d in dumps):
            return
        time.sleep(0.05)
    raise AssertionError("replicas did not converge in time")


def test_two_relay_antientropy_and_snapshot_carry_typed_state(tmp_path):
    """Typed ops ride replication + snapshot unchanged: relay B pulls
    relay A's typed traffic through Merkle anti-entropy; a checkpoint
    of A restores into a fresh relay byte-identically (crc-pinned);
    clients hanging off EVERY relay materialize identical typed values;
    and the capability is negotiated along the way."""
    from evolu_tpu.runtime.client import create_evolu
    from evolu_tpu.server import snapshot
    from evolu_tpu.server.relay import RelayServer, RelayStore
    from evolu_tpu.sync import protocol
    from evolu_tpu.sync.client import connect

    schema = {"metrics": ("name", "clicks:counter", "tags:awset")}
    a = RelayServer(RelayStore(), peers=[]).start()
    b = None
    c = None
    e1 = e2 = e3 = None
    try:
        e1 = create_evolu(schema, config=Config(sync_url=a.url))
        connect(e1)
        row = e1.create("metrics", {"name": "page"})
        for d in (5, -2, 7):
            e1.increment("metrics", row, "clicks", d)
        e1.set_add("metrics", row, "tags", "red")
        e1.set_add("metrics", row, "tags", "blue")
        e1.worker.flush()
        e1.set_remove("metrics", row, "tags", "blue")
        e1.worker.flush()
        e1.sync()
        e1.worker.flush()
        e1._transport.flush()
        # Capability negotiated with the live relay.
        caps = e1._transport.negotiated_capabilities
        assert any(protocol.CAP_CRDT_TYPES in v for v in caps.values()), caps

        # Relay B converges through anti-entropy (byte-level replica
        # state: stored tree text + every (timestamp, content) row).
        owner = e1.owner.id
        state = lambda store: (  # noqa: E731
            store.get_merkle_tree_string(owner),
            store.replica_messages(owner, ""),
        )
        b = RelayServer(RelayStore(), peers=[a.url],
                        replication_interval_s=0.1).start()
        deadline = time.time() + 20
        while time.time() < deadline:
            if state(b.store) == state(a.store) and state(a.store)[1]:
                break
            time.sleep(0.05)
        assert state(b.store) == state(a.store)

        # Snapshot checkpoint of A restores crc-identically into C.
        path = str(tmp_path / "a.checkpoint")
        snapshot.write_checkpoint(a.store, path)
        fresh = RelayStore()
        snapshot.restore_checkpoint(fresh, path)
        crc = lambda store: zlib.crc32(repr(state(store)).encode())  # noqa: E731
        assert crc(fresh) == crc(a.store)
        c = RelayServer(fresh).start()

        # A fresh client against EACH relay materializes the same values.
        e2 = create_evolu(schema, config=Config(sync_url=b.url),
                          mnemonic=e1.owner.mnemonic)
        e3 = create_evolu(schema, config=Config(sync_url=c.url),
                          mnemonic=e1.owner.mnemonic)
        connect(e2)
        connect(e3)
        _converge([e1, e2])
        _converge([e1, e3])
        for e in (e1, e2, e3):
            rows = e.db.exec_sql_query(
                'SELECT "clicks", "tags" FROM "metrics"')
            assert (rows[0]["clicks"], rows[0]["tags"]) == (10, '["red"]')
        # Typed state tables converge byte-identically too.
        dumps = [_dump_all(e.db) for e in (e1, e2, e3)]
        assert dumps[0] == dumps[1] == dumps[2]
    finally:
        for e in (e1, e2, e3):
            if e is not None:
                e.dispose()
        for s in (a, b, c):
            if s is not None:
                s.stop()


def test_rebuild_state_matches_incremental():
    """The order-free fold rebuilt from the full log equals the
    incrementally maintained state (the integrity-check invariant)."""
    msgs = _random_mixed_log(123, n=250)
    db = _mk_db()
    apply_messages(db, create_initial_merkle_tree(), msgs)
    before = _dump_all(db)
    ct.rebuild_state(db, ct.load_schema(db))
    assert _dump_all(db) == before


def test_reset_owner_drops_typed_state():
    from evolu_tpu.runtime.client import create_evolu

    e = create_evolu({"metrics": ("clicks:counter",)}, config=Config(backend="cpu"))
    try:
        row = e.create("metrics", {})
        e.increment("metrics", row, "clicks", 3)
        e.worker.flush()
        assert e.db.exec_sql_query('SELECT * FROM "__crdt_counter"')
        e.reset_owner()
        e.worker.flush()
        # Schema cache dropped with the tables: a fresh declare works.
        e.update_db_schema({"metrics": ("clicks:counter",)})
        e.worker.flush()
        assert ct.load_schema(e.db).column_type("metrics", "clicks") == "counter"
        assert e.db.exec_sql_query('SELECT * FROM "__crdt_counter"') == []
    finally:
        e.dispose()


def test_late_declaration_folds_predeclaration_ops():
    """Review finding: ops that reached __message BEFORE the column was
    declared typed (rolling upgrade) must fold at declaration time —
    otherwise this replica materializes a different value than a
    replica that declared before syncing, forever (anti-entropy is
    timestamp-only and cannot heal it)."""
    base = 1_700_000_000_000
    mk = lambda i, col, v: CrdtMessage(  # noqa: E731
        timestamp_to_string(Timestamp(base + i * 1000, 0, "aaaaaaaaaaaaaaa1")),
        "metrics", "r1", col, v)
    ops = [mk(0, "clicks", 5), mk(1, "clicks", 7),
           mk(2, "tags", ct.set_add_value("x"))]

    # Replica L: receives the ops while the columns are still UNDECLARED
    # (plain LWW schema), then upgrades.
    late = open_database(":memory:", "python")
    init_db_model(late, MN)
    update_db_schema(late, [TableDefinition.of("metrics", ("name", "clicks", "tags"))])
    apply_messages(late, create_initial_merkle_tree(), ops)
    assert _app_value(late, "clicks") == 7  # LWW winner, pre-upgrade
    update_db_schema(late, [SCHEMA_DEF])  # the upgrade declares the types

    # Replica E: declared first, then synced.
    early = _mk_db()
    apply_messages(early, create_initial_merkle_tree(), ops)

    for db in (late, early):
        assert _app_value(db, "clicks") == 12, "fold must cover pre-declaration ops"
        assert _app_value(db, "tags") == '["x"]'
    assert _dump_all(late)[2:] == _dump_all(early)[2:]  # identical __crdt_* state

    # Later ops keep folding incrementally on both.
    more = [mk(10, "clicks", -2)]
    for db in (late, early):
        apply_messages(db, create_initial_merkle_tree(), more)
        assert _app_value(db, "clicks") == 10


def test_set_remove_covers_just_queued_add():
    """Review finding: add-then-remove on ONE replica without an
    explicit flush must still remove the element — set_remove drains
    the worker before reading its observation."""
    from evolu_tpu.runtime.client import create_evolu

    e = create_evolu({"metrics": ("tags:awset",)}, config=Config(backend="cpu"))
    try:
        row = e.create("metrics", {})
        e.set_add("metrics", row, "tags", "ghost")
        e.set_remove("metrics", row, "tags", "ghost")  # no flush between
        e.worker.flush()
        assert _app_value(e.db, "tags", row) == "[]"
    finally:
        e.dispose()


def test_load_schema_raises_on_transient_error_instead_of_caching_empty():
    """Review finding: a transient load error must FAIL the apply (safe
    rollback), never cache an empty schema that would route typed cells
    through the LWW path forever."""
    db = _mk_db()
    ct.invalidate_schema_cache(db)
    orig = db.exec_sql_query

    def flaky(sql, params=()):
        if "__crdt_schema" in sql:
            raise RuntimeError("database is locked")
        return orig(sql, params)

    db.exec_sql_query = flaky
    with pytest.raises(RuntimeError):
        ct.load_schema(db)
    db.exec_sql_query = orig
    assert ct.load_schema(db).column_type("metrics", "clicks") == "counter"
    # Missing table (pure-LWW db) still caches the empty schema.
    plain = open_database(":memory:", "python")
    init_db_model(plain, MN)
    assert not ct.load_schema(plain)
    assert getattr(plain, "_crdt_schema_cache", None) is not None
