"""Failure detection + fault injection.

The reference's failure story (SURVEY.md §5): offline tolerance
(FetchError swallowed), sync-livelock detection via a repeated Merkle
diff ⇒ SyncError (receive.ts:99-104), transactional rollback. The
reference has no fault-injection tests; these add them: livelock
surfacing, convergence under a flaky transport, and thread-safety
under concurrent mutators (races the reference prevents only by
browser architecture).
"""

import os
import random
import threading

import pytest

from evolu_tpu.core.types import SyncError
from evolu_tpu.runtime.client import Evolu, create_evolu
from evolu_tpu.server.relay import RelayServer, RelayStore
from evolu_tpu.sync import client as sync_client
from evolu_tpu.utils.config import Config


def test_sync_livelock_raises_sync_error():
    """A server diff identical to previous_diff must surface SyncError
    (receive.ts:99-104, types.ts:371-378) instead of looping forever."""
    evolu = create_evolu({"todo": ("title",)})
    try:
        errors = []
        evolu.subscribe_error(errors.append)
        evolu.create("todo", {"title": "x"})
        evolu.worker.flush()

        # A server tree that differs from ours (empty) produces a diff D.
        # Replaying the same response with previous_diff=D simulates the
        # server still diverged at the same minute => livelock.
        from evolu_tpu.core.merkle import (
            create_initial_merkle_tree,
            diff_merkle_trees,
            insert_into_merkle_tree,
            merkle_tree_to_string,
        )
        from evolu_tpu.core.timestamp import Timestamp

        server_tree = insert_into_merkle_tree(
            Timestamp(1_700_000_000_000, 0, "b" * 16), create_initial_merkle_tree()
        )
        from evolu_tpu.storage.clock import read_clock

        local = read_clock(evolu.db).merkle_tree
        diff = diff_merkle_trees(server_tree, local)
        assert diff is not None

        evolu.receive((), merkle_tree_to_string(server_tree), previous_diff=diff)
        evolu.worker.flush()
        assert errors and isinstance(errors[0], SyncError)
    finally:
        evolu.dispose()


def test_convergence_with_flaky_transport(tmp_path):
    """30% of HTTP posts fail (connection errors): clients stay up
    (offline tolerance, sync.worker.ts:217-227) and converge once
    enough rounds get through."""
    server = RelayServer(RelayStore(str(tmp_path / "relay.db"))).start()
    try:
        cfg = Config(sync_url=server.url + "/")
        rng = random.Random(17)
        real_post = sync_client._http_post

        def flaky_post(url, body):
            if rng.random() < 0.3:
                raise OSError("injected network failure")
            return real_post(url, body)

        def mk(path, mnemonic=None):
            e = Evolu(db_path=str(tmp_path / path), config=cfg, mnemonic=mnemonic)
            e.update_db_schema({"todo": ("title",)})
            t = sync_client.SyncTransport(
                cfg, on_receive=e.receive, sync_lock=e.worker.sync_lock,
                http_post=flaky_post,
            )
            e.attach_transport(t)
            return e, t

        a, ta = mk("a.db")
        b, tb = mk("b.db", a.owner.mnemonic)
        for i in range(30):
            (a if i % 2 else b).create("todo", {"title": f"t{i}"})

        # Injected failures make any fixed round count probabilistic:
        # poll until both replicas converge (or a generous deadline).
        import time as _time

        deadline = _time.time() + 60
        while _time.time() < deadline:
            for c, t in ((a, ta), (b, tb)):
                c.sync()
                c.worker.flush(); t.flush(); c.worker.flush()
            rows_a = a.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
            rows_b = b.db.exec('SELECT * FROM "__message" ORDER BY "timestamp"')
            if len(rows_a) == len(rows_b) == 90 and rows_a == rows_b:
                break
        assert len(rows_a) == len(rows_b) == 90  # 30 creates x 3 columns
        assert rows_a == rows_b
        a.dispose(), b.dispose()
    finally:
        server.stop()


def test_concurrent_mutators_thread_safety():
    """16 threads hammer one client: every mutation must land exactly
    once and the worker's single-writer discipline must hold."""
    evolu = create_evolu({"todo": ("title", "n")})
    try:
        n_threads, per_thread = 16, 25
        errors = []
        evolu.subscribe_error(errors.append)

        def writer(t):
            for i in range(per_thread):
                evolu.create("todo", {"title": f"t{t}-{i}", "n": t * 1000 + i})

        threads = [threading.Thread(target=writer, args=(t,)) for t in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        evolu.worker.flush()

        rows = evolu.query_once('SELECT "title" FROM "todo"')
        assert len(rows) == n_threads * per_thread
        assert len({r["title"] for r in rows}) == n_threads * per_thread
        assert not errors, errors[:3]
    finally:
        evolu.dispose()


def test_transaction_rollback_on_mid_batch_failure():
    """A batch containing a poisoned message must roll back whole —
    the reference's per-command dbTransaction semantics
    (db.worker.ts:71-73); no partial rows, no partial __message."""
    from evolu_tpu.core.types import CrdtMessage
    from evolu_tpu.storage.apply import apply_messages
    from evolu_tpu.storage.schema import init_db_model
    from evolu_tpu.storage.native import open_database

    for backend in ("python", "native"):
        db = open_database(backend=backend)
        init_db_model(db, mnemonic=None)
        db.exec('CREATE TABLE "todo" ("id" TEXT PRIMARY KEY, "title" BLOB)')
        good = CrdtMessage(
            "2024-01-01T00:00:00.000Z-0000-" + "a" * 16, "todo", "r1", "title", "ok"
        )
        bad = CrdtMessage("garbage-timestamp", "todo", "r2", "title", "boom")
        with pytest.raises(Exception):
            apply_messages(db, {}, [good, bad])
        assert db.exec('SELECT COUNT(*) FROM "__message"') == [(0,)]
        assert db.exec('SELECT COUNT(*) FROM "todo"') == [(0,)]
        db.close()


def test_reconnect_probe_fires_immediate_sync(tmp_path):
    """Partition, mutate (push swallowed), heal — WITHOUT any manual or
    interval sync, the transport's /ping probe must notice the healed
    network, fire the app reconnect hook, and run an immediate round
    that lands the pending state on the relay (the reference re-syncs
    on online/focus/visibilitychange, db.ts:390-412)."""
    import time

    from evolu_tpu.runtime.messages import OnError

    server = RelayServer(RelayStore(str(tmp_path / "relay.db"))).start()
    try:
        cfg = Config(sync_url=server.url + "/", reconnect_probe_interval=0.05)
        partitioned = threading.Event()
        real_post, real_ping = sync_client._http_post, sync_client._http_ping

        def post(url, body):
            if partitioned.is_set():
                raise OSError("partitioned")
            return real_post(url, body)

        def probe(url):
            if partitioned.is_set():
                raise OSError("partitioned")
            real_ping(url)

        a = Evolu(db_path=str(tmp_path / "a.db"), config=cfg)
        a.update_db_schema({"todo": ("title",)})
        reconnects = []
        a.subscribe_reconnect(lambda: reconnects.append(True))

        def on_reconnect():
            a._fire_reconnect()
            a.sync(refresh_queries=False)

        ta = sync_client.SyncTransport(
            cfg, on_receive=a.receive, sync_lock=a.worker.sync_lock,
            http_post=post, http_probe=probe, on_reconnect=on_reconnect,
        )
        a.attach_transport(ta)

        partitioned.set()
        a.create("todo", {"title": "offline-born"})
        a.worker.flush()
        ta.flush()
        assert not reconnects  # swallowed, still offline

        # Heal. The probe (50ms cadence) must do the rest on its own.
        partitioned.clear()
        deadline = time.time() + 10
        while time.time() < deadline and not reconnects:
            time.sleep(0.02)
        assert reconnects, "reconnect hook never fired after heal"

        # The immediate round must push the offline-born mutation: a
        # fresh replica of the same owner pulls it from the relay.
        b = Evolu(db_path=str(tmp_path / "b.db"), config=cfg, mnemonic=a.owner.mnemonic)
        b.update_db_schema({"todo": ("title",)})
        tb = sync_client.SyncTransport(
            cfg, on_receive=b.receive, sync_lock=b.worker.sync_lock,
        )
        b.attach_transport(tb)
        deadline = time.time() + 10
        rows = []
        while time.time() < deadline:
            b.sync()
            b.worker.flush(); tb.flush(); b.worker.flush()
            rows = b.db.exec('SELECT "title" FROM "todo"')
            if rows:
                break
        assert rows == [("offline-born",)]
        a.dispose(), b.dispose()
    finally:
        server.stop()
