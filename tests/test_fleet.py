"""Owner-sharded relay fleet (server/fleet.py): placement ring
determinism/balance/stability, request routing (307 redirect, proxy
forward with the hop guard, not-ready 503), client route learning and
invalidation, placement-scoped gossip, snapshot-driven rebalancing
with watermark cutover, readiness-probed failover, and the
FleetForward / ReplicaSummary.peer_url wire codec."""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from evolu_tpu.core.timestamp import Timestamp, timestamp_to_string
from evolu_tpu.server.fleet import FleetManager, FleetNotReady, HashRing
from evolu_tpu.server.relay import RelayServer, RelayStore
from evolu_tpu.sync import protocol
from evolu_tpu.sync.client import _http_post
from evolu_tpu.utils.config import FleetConfig

BASE = 1_700_000_000_000


def _msgs(k, n, t0=0):
    node = f"{k + 1:016x}"
    return tuple(
        protocol.EncryptedCrdtMessage(
            timestamp_to_string(Timestamp(BASE + (t0 + j) * 1000, 0, node)),
            b"ct-%d-%d" % (k, t0 + j),
        )
        for j in range(n)
    )


def _sync_body(owner, messages=(), tree="{}"):
    return protocol.encode_sync_request(
        protocol.SyncRequest(messages, owner, "00000000000000bb", tree)
    )


def _owner_for(ring, url, prefix="o", avoid=()):
    """A deterministic owner id whose primary under `ring` is `url`."""
    i = 0
    while True:
        uid = f"{prefix}{i:04d}"
        if uid not in avoid and ring.primary(uid) == url.rstrip("/"):
            return uid
        i += 1


# --- placement ring ---


def test_ring_deterministic_r_distinct_and_clamped():
    cfg = FleetConfig(relays=("http://a:1", "http://b:2", "http://c:3"),
                      replication_factor=2, seed=7)
    r1, r2 = HashRing(cfg), HashRing(cfg)
    for i in range(200):
        p = r1.placement(f"owner{i}")
        assert p == r2.placement(f"owner{i}")  # pure function of config
        assert len(p) == 2 and len(set(p)) == 2
        assert all(u in cfg.relays for u in p)
    # R larger than the fleet clamps to the member count.
    big = HashRing(FleetConfig(relays=("http://a:1",), replication_factor=3))
    assert big.placement("x") == ("http://a:1",)


def test_ring_balance_and_seed_sensitivity():
    urls = tuple(f"http://relay{i}:400{i}" for i in range(3))
    ring = HashRing(FleetConfig(relays=urls, replication_factor=1))
    counts = {u: 0 for u in urls}
    owners = [f"owner{i:05d}" for i in range(3000)]
    for uid in owners:
        counts[ring.primary(uid)] += 1
    # 64 vnodes each: no relay should hold less than half its fair
    # share or more than double (loose — this is smoothness, not
    # perfection).
    for u, n in counts.items():
        assert 1000 / 2 <= n <= 1000 * 2, counts
    other = HashRing(FleetConfig(relays=urls, replication_factor=1, seed=1))
    moved = sum(1 for uid in owners if other.primary(uid) != ring.primary(uid))
    assert moved > len(owners) / 3  # a different seed is a different ring


def test_ring_join_moves_only_the_new_arc():
    urls = tuple(f"http://relay{i}:400{i}" for i in range(3))
    before = HashRing(FleetConfig(relays=urls, replication_factor=1))
    after = HashRing(FleetConfig(relays=urls + ("http://relay3:4003",),
                                 replication_factor=1))
    owners = [f"owner{i:05d}" for i in range(3000)]
    moved = [uid for uid in owners
             if after.primary(uid) != before.primary(uid)]
    # Consistent hashing: a 3→4 join should move ~1/4 of owners, and
    # every move should land ON the joiner (nothing shuffles between
    # surviving members).
    assert len(moved) / len(owners) < 0.45
    assert all(after.primary(uid) == "http://relay3:4003" for uid in moved)


# --- wire codec ---


def test_fleet_forward_codec_roundtrip():
    env = protocol.FleetForward(b"\x00payload\xffbytes", "http://a:1", 1)
    out = protocol.decode_fleet_forward(protocol.encode_fleet_forward(env))
    assert out == env


def test_replica_summary_peer_url_roundtrip_and_compat():
    s = protocol.ReplicaSummary((("o1", "{}"),), "r1", "http://me:4000")
    assert protocol.decode_replica_summary(
        protocol.encode_replica_summary(s)) == s
    # The pre-fleet wire (no field 3) decodes with peer_url == "".
    old = protocol.encode_replica_summary(
        protocol.ReplicaSummary((("o1", "{}"),), "r1"))
    got = protocol.decode_replica_summary(old)
    assert got.peer_url == "" and got.trees == (("o1", "{}"),)


def test_fleet_decoders_raise_value_error_only():
    import random

    rng = random.Random(1234)
    for fn in (protocol.decode_fleet_forward, protocol.decode_replica_summary):
        for _ in range(300):
            blob = bytes(rng.randrange(256) for _ in range(rng.randrange(40)))
            try:
                fn(blob)
            except ValueError:
                pass  # the only allowed error type
    # The memory-DoS shape: a varint payload field must not allocate.
    bad = protocol._tag(1, 0) + protocol._varint(1 << 40)
    with pytest.raises(ValueError):
        protocol.decode_fleet_forward(bad)


def test_snapshot_request_owners_roundtrip_and_compat():
    r = protocol.SnapshotRequest("rid", 1024, ("o1", "o2"))
    assert protocol.decode_snapshot_request(
        protocol.encode_snapshot_request(r)) == r
    # Pre-fleet wire (no field 3) decodes with owners == ().
    old = protocol.encode_snapshot_request(protocol.SnapshotRequest("rid"))
    assert protocol.decode_snapshot_request(old).owners == ()


def test_owner_scoped_snapshot_serves_only_wanted_owners():
    """The fleet rebalance's O(moved-owners) transfer: a SnapshotRequest
    naming owners gets a manifest/chunks covering exactly those, and
    the scoped capture never aliases the full-store cache entry."""
    from evolu_tpu.server import snapshot as snap

    donor = RelayServer(RelayStore(), peers=[],
                        replication_interval_s=30).start()
    try:
        owners = [f"z{i:04d}" for i in range(6)]
        for k, uid in enumerate(owners):
            donor.store.add_messages(uid, _msgs(k, 4))
        wanted = tuple(owners[:2])
        body = protocol.encode_snapshot_request(
            protocol.SnapshotRequest("probe", 0, wanted))
        manifest = protocol.decode_snapshot_manifest(
            _http_post(donor.url + "/replicate/snapshot", body))
        assert tuple(uid for uid, _r, _c in manifest.owners) == wanted
        assert manifest.message_count == 8
        seen = set()
        for i in range(len(manifest.chunk_sizes)):
            chunk = protocol.decode_snapshot_chunk(_http_post(
                donor.url + "/replicate/snapshot/chunk",
                protocol.encode_snapshot_chunk_request(
                    protocol.SnapshotChunkRequest(manifest.snapshot_id, i)),
            ))
            for rec in snap.iter_records(chunk.payload):
                seen.add(rec[2] if rec[0] == "M" else rec[1])
        assert seen == set(wanted)
        # A FULL request afterwards is a DIFFERENT snapshot covering
        # everything (cache keyed by owner set, not just chunk size).
        full = protocol.decode_snapshot_manifest(_http_post(
            donor.url + "/replicate/snapshot",
            protocol.encode_snapshot_request(
                protocol.SnapshotRequest("probe"))))
        assert full.snapshot_id != manifest.snapshot_id
        assert len(full.owners) == 6
    finally:
        donor.stop()


# --- routing through real relays ---


@pytest.fixture()
def two_relay_fleet():
    a = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    b = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    cfg = FleetConfig(relays=(a.url, b.url), replication_factor=1, version=1)
    a.enable_fleet(cfg)
    b.enable_fleet(cfg)
    try:
        yield a, b, cfg
    finally:
        a.stop()
        b.stop()


def test_redirect_for_non_placed_owner(two_relay_fleet):
    a, b, _cfg = two_relay_fleet
    owner_b = _owner_for(a.fleet.ring, b.url)
    with pytest.raises(urllib.error.HTTPError) as e:
        _http_post(a.url + "/", _sync_body(owner_b, _msgs(0, 2)))
    assert e.value.code == 307
    assert e.value.headers.get("Location") == b.url + "/"
    # The redirect carried no side effect: nothing landed on A.
    assert a.store.user_ids() == []
    # Served at the authoritative relay, response is the normal wire.
    out = _http_post(b.url + "/", _sync_body(owner_b, _msgs(0, 2)))
    assert protocol.decode_sync_response(out).merkle_tree != "{}"


def test_forward_mode_proxies_and_matches_direct_serve(two_relay_fleet):
    a, b, cfg = two_relay_fleet
    fwd = FleetConfig(relays=cfg.relays, replication_factor=1, version=2,
                      forward=True)
    for s in (a, b):
        body = json.dumps(fwd.to_json()).encode()
        req = urllib.request.Request(s.url + "/fleet/reload", data=body,
                                     method="POST")
        with urllib.request.urlopen(req) as r:
            assert json.loads(r.read())["ring_version"] == 2
    owner_b = _owner_for(a.fleet.ring, b.url)
    out = _http_post(a.url + "/", _sync_body(owner_b, _msgs(0, 3)))
    # The forwarded response is byte-identical to asking B directly
    # with the same (now converged) tree — rows landed on B only.
    assert b.store.user_ids() == [owner_b]
    assert a.store.user_ids() == []
    direct = _http_post(b.url + "/", _sync_body(owner_b, _msgs(0, 3)))
    assert protocol.decode_sync_response(out).merkle_tree == \
        protocol.decode_sync_response(direct).merkle_tree


def test_not_ready_owner_answers_503_retry_after(two_relay_fleet):
    a, _b, _cfg = two_relay_fleet
    owner_a = _owner_for(a.fleet.ring, a.url)
    with a.fleet._lock:
        a.fleet._installing.add(owner_a)
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _http_post(a.url + "/", _sync_body(owner_a), retries=0)
        assert e.value.code == 503
        assert float(e.value.headers.get("Retry-After")) > 0
    finally:
        with a.fleet._lock:
            a.fleet._installing.discard(owner_a)
    # Ready again: serves.
    _http_post(a.url + "/", _sync_body(owner_a, _msgs(1, 1)))
    assert a.store.user_ids() == [owner_a]


def test_stale_reload_rejected_with_400(two_relay_fleet):
    a, _b, cfg = two_relay_fleet
    stale = FleetConfig(relays=cfg.relays, replication_factor=1, version=0)
    body = json.dumps(stale.to_json()).encode()
    req = urllib.request.Request(a.url + "/fleet/reload", data=body,
                                 method="POST")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req)
    assert e.value.code == 400
    assert a.fleet.config.version == 1  # untouched


def test_reload_rejects_malformed_and_dos_configs(two_relay_fleet):
    a, _b, cfg = two_relay_fleet
    for bad in (
        {"relays": "http://a:4000", "version": 5},  # bare string
        {"relays": list(cfg.relays), "version": 5, "virtual_nodes": 10**8},
        {"relays": [f"http://r{i}:1" for i in range(2000)], "version": 5},
        {"version": 5},  # no relays at all
    ):
        req = urllib.request.Request(
            a.url + "/fleet/reload", data=json.dumps(bad).encode(),
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req)
        assert e.value.code == 400, bad
    assert a.fleet.config.version == 1


def test_reload_token_gate(two_relay_fleet, monkeypatch):
    """With EVOLU_FLEET_RELOAD_TOKEN set, the control-plane mutation
    demands the matching header — a client-reachable sync port must
    not accept ring hijacks."""
    import os as _os

    a, _b, cfg = two_relay_fleet
    monkeypatch.setitem(_os.environ, "EVOLU_FLEET_RELOAD_TOKEN", "s3cret")
    new = FleetConfig(relays=cfg.relays, replication_factor=1, version=3)
    body = json.dumps(new.to_json()).encode()
    req = urllib.request.Request(a.url + "/fleet/reload", data=body,
                                 method="POST")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req)
    assert e.value.code == 403
    assert a.fleet.config.version == 1
    req = urllib.request.Request(
        a.url + "/fleet/reload", data=body, method="POST",
        headers={"X-Evolu-Fleet-Token": "s3cret"})
    with urllib.request.urlopen(req) as r:
        assert json.loads(r.read())["ring_version"] == 3


def test_health_reports_install_in_progress():
    server = RelayServer(RelayStore()).start()
    try:
        with urllib.request.urlopen(server.url + "/health") as r:
            assert r.status == 200
            assert json.loads(r.read())["status"] == "serving"
        from evolu_tpu.server.snapshot import SnapshotInstaller

        inst = SnapshotInstaller(server.store)
        manifest = protocol.SnapshotManifest("snap1", (), (), (), 0, 0)
        inst.begin(manifest, "peer")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(server.url + "/health")
        assert e.value.code == 503
        detail = json.loads(e.value.read())
        assert detail == {"status": "installing", "install_phase": "fetch"}
        inst.abort()
        with urllib.request.urlopen(server.url + "/health") as r:
            assert r.status == 200
    finally:
        server.stop()
    # A batching relay also reports its admission-queue depth — the
    # saturation signal for operators / load-aware probing.
    server = RelayServer(RelayStore(), batching=True).start()
    try:
        with urllib.request.urlopen(server.url + "/health") as r:
            assert json.loads(r.read())["queue_depth"] == 0
    finally:
        server.stop()


# --- client transport: follow-one-307 + route cache ---


SCHEMA = {"todo": ("title", "isCompleted")}


class _Status404(BaseHTTPRequestHandler):
    def do_POST(self):  # a reused port / path-prefixed deploy: 404s
        self.rfile.read(int(self.headers.get("Content-Length", "0")))
        self.send_error(404)

    def log_message(self, *a):
        pass


def test_client_follows_one_redirect_caches_and_invalidates():
    from evolu_tpu.obs import metrics
    from evolu_tpu.runtime.client import create_evolu
    from evolu_tpu.sync.client import connect
    from evolu_tpu.utils.config import Config

    a = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    b = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    cfg = FleetConfig(relays=(a.url, b.url), replication_factor=1, version=1)
    a.enable_fleet(cfg)
    b.enable_fleet(cfg)
    stub = HTTPServer(("127.0.0.1", 0), _Status404)
    stub_thread = threading.Thread(target=stub.serve_forever, daemon=True)
    stub_thread.start()
    evolu = None
    try:
        evolu = create_evolu(SCHEMA, config=Config(sync_url=a.url))
        connect(evolu)
        owner = evolu.owner.id
        primary = evolu._transport  # noqa: F841 - keep a handle
        home = a if a.fleet.ring.primary(owner) == a.url else b
        away = b if home is a else a
        # Point the client at the NON-primary relay: the first round
        # must 307-redirect exactly once and land on the primary.
        evolu.config.sync_url = away.url
        evolu._transport.config.sync_url = away.url
        before = metrics.get_counter("evolu_sync_redirects_total")
        evolu.create("todo", {"title": "t1", "isCompleted": False})
        evolu.worker.flush()
        evolu.sync()
        evolu.worker.flush()
        evolu._transport.flush()
        assert metrics.get_counter("evolu_sync_redirects_total") == before + 1
        assert evolu._transport._routes.get(owner) == home.url + "/"
        assert home.store.user_ids() == [owner]
        # Second round rides the cached route: no new redirect.
        evolu.create("todo", {"title": "t2", "isCompleted": False})
        evolu.worker.flush()
        evolu.sync()
        evolu.worker.flush()
        evolu._transport.flush()
        assert metrics.get_counter("evolu_sync_redirects_total") == before + 1
        # A stale learned route (404s now): invalidated, SAME round
        # retried at the configured relay — no sync error, no loss.
        evolu._transport._routes[owner] = f"http://127.0.0.1:{stub.server_address[1]}/"
        errors = []
        evolu.subscribe_error(errors.append)
        evolu.create("todo", {"title": "t3", "isCompleted": False})
        evolu.worker.flush()
        evolu.sync()
        evolu.worker.flush()
        evolu._transport.flush()
        evolu.worker.flush()
        assert not errors
        n = home.store.db.exec_sql_query(
            'SELECT COUNT(*) AS n FROM "message"')[0]["n"]
        assert n >= 3  # t3 arrived despite the stale route
    finally:
        if evolu is not None:
            evolu.dispose()
        stub.shutdown()
        stub.server_close()
        a.stop()
        b.stop()


# --- placement-scoped gossip ---


def test_gossip_scoped_to_placement():
    relays = []
    try:
        for _ in range(3):
            relays.append(
                RelayServer(RelayStore(), peers=[],
                            replication_interval_s=30).start()
            )
        cfg = FleetConfig(relays=tuple(s.url for s in relays),
                          replication_factor=2, version=1)
        for s in relays:
            s.enable_fleet(cfg)
            for t in relays:
                if t is not s:
                    s.replication.add_peer(t.url)
        a = relays[0]
        # Owners on A: some placed on peer1, some not.
        owners = [f"g{i:04d}" for i in range(24)]
        for k, uid in enumerate(owners):
            a.store.add_messages(uid, _msgs(k, 3))
        sent = {}  # peer url -> summary trees sent

        orig_post = a.replication._post

        def recording_post(url, body):
            if url.endswith("/replicate/summary"):
                s = protocol.decode_replica_summary(body)
                sent[url.rsplit("/replicate/", 1)[0]] = s
            return orig_post(url, body)

        a.replication._post = recording_post
        a.replication.run_once()
        assert len(sent) == 2
        for peer_url, summary in sent.items():
            advertised = {uid for uid, _t in summary.trees}
            placed = {uid for uid in owners
                      if a.fleet.placed_on(uid, peer_url)}
            assert advertised == placed  # exactly the peer's placement
            assert summary.peer_url == a.fleet.self_url
        # R=2 over 3 relays: the union of both scoped summaries must
        # NOT be "everything to everyone" — each owner reaches only
        # its replica (O(R) fan-out, minus self).
        total_sent = sum(len(s.trees) for s in sent.values())
        assert total_sent < 2 * len(owners)
        # Transfer happens on the PULLER's round: once each peer runs
        # one, every owner lives on all R of its placed relays (strays
        # drained to their placement).
        for s in relays[1:]:
            s.replication.run_once()
        for uid in owners:
            for target in a.fleet.placement(uid):
                srv = next(s for s in relays if s.url == target)
                if srv is a:
                    continue
                assert srv.store.get_merkle_tree_string(uid) == \
                    a.store.get_merkle_tree_string(uid), uid
    finally:
        for s in relays:
            s.stop()


def test_serve_summary_scopes_response_to_caller_url():
    relays = []
    try:
        for _ in range(2):
            relays.append(
                RelayServer(RelayStore(), peers=[],
                            replication_interval_s=30).start()
            )
        a, b = relays
        cfg = FleetConfig(relays=(a.url, b.url), replication_factor=1,
                          version=1)
        a.enable_fleet(cfg)
        b.enable_fleet(cfg)
        owners = [f"s{i:04d}" for i in range(16)]
        for k, uid in enumerate(owners):
            a.store.add_messages(uid, _msgs(k, 2))
        # Ask with b's URL: only owners placed on b come back.
        body = protocol.encode_replica_summary(
            protocol.ReplicaSummary((), "probe", b.url))
        resp = protocol.decode_replica_summary(
            _http_post(a.url + "/replicate/summary", body))
        got = {uid for uid, _t in resp.trees}
        assert got == {uid for uid in owners if a.fleet.placed_on(uid, b.url)}
        assert resp.peer_url == a.url
        # An EMPTY peer_url (pre-fleet peer / the bench's oracle read)
        # still gets the full map — interop unchanged.
        body = protocol.encode_replica_summary(
            protocol.ReplicaSummary((), "probe"))
        resp = protocol.decode_replica_summary(
            _http_post(a.url + "/replicate/summary", body))
        assert {uid for uid, _t in resp.trees} == set(owners)
    finally:
        for s in relays:
            s.stop()


# --- rebalancing ---


def test_join_rebalance_moves_owners_at_watermark():
    from evolu_tpu.obs import metrics

    a = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    b = None
    try:
        a.enable_fleet(FleetConfig(relays=(a.url,), replication_factor=1,
                                   version=1))
        owners = [f"m{i:04d}" for i in range(20)]
        for k, uid in enumerate(owners):
            a.store.add_messages(uid, _msgs(k, 10))
        # peers=[] (listener) so the joiner's own gossip loop cannot
        # race the snapshot sweep and drain moved owners via ranged
        # pulls first (both paths converge — this test pins the
        # SNAPSHOT path deterministically; fleet BEFORE start() so any
        # later gossip is born scoped).
        b = RelayServer(RelayStore(), peers=[],
                        replication_interval_s=30)
        cfg2 = FleetConfig(relays=(a.url, b.url), replication_factor=1,
                           version=2)
        fb = b.enable_fleet(cfg2)
        b.start()
        moved = [uid for uid in owners if fb.ring.primary(uid) == b.url]
        assert moved, "ring change moved nothing — vnode layout broke"
        body = json.dumps(cfg2.to_json()).encode()
        req = urllib.request.Request(a.url + "/fleet/reload", data=body,
                                     method="POST")
        with urllib.request.urlopen(req) as r:
            assert json.loads(r.read())["rebalancing"] is True
        v0 = metrics.get_counter("evolu_fleet_cutover_verified_total")
        assert fb.rebalance_once() == len(moved)
        # Counter-asserted snapshot cutover at the Merkle watermark:
        # every moved owner verified byte-identical to the donor's
        # capture-time tree before it started being served.
        assert metrics.get_counter(
            "evolu_fleet_cutover_verified_total") == v0 + len(moved)
        for uid in moved:
            assert b.store.get_merkle_tree_string(uid) == \
                a.store.get_merkle_tree_string(uid)
            assert b.store.replica_messages(uid, "") == \
                a.store.replica_messages(uid, "")
        # A (after its reload) now redirects moved owners to B.
        with pytest.raises(urllib.error.HTTPError) as e:
            _http_post(a.url + "/", _sync_body(moved[0]))
        assert e.value.code == 307
        assert e.value.headers.get("Location") == b.url + "/"
        # Unmoved owners stay where they were.
        kept = [uid for uid in owners if uid not in moved]
        assert all(b.fleet.ring.primary(uid) == a.url for uid in kept)
        # Re-running the sweep is a no-op (idempotent).
        assert fb.rebalance_once() == 0
    finally:
        if b is not None:
            b.stop()
        a.stop()


def test_rebalance_survives_concurrent_acked_writes():
    """A write ACKed by the DONOR after capture must still reach the
    gaining relay (scoped gossip heals the post-watermark tail) — the
    zero-lost-ACKed-writes property, in miniature."""
    a = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    b = None
    try:
        a.enable_fleet(FleetConfig(relays=(a.url,), replication_factor=1,
                                   version=1))
        owners = [f"w{i:04d}" for i in range(12)]
        for k, uid in enumerate(owners):
            a.store.add_messages(uid, _msgs(k, 6))
        # peers=[] so the joiner's gossip loop cannot pre-drain moved
        # owners before the snapshot sweep (see the join test above);
        # the donor is added as a gossip peer for the heal phase only.
        b = RelayServer(RelayStore(), peers=[],
                        replication_interval_s=30)
        cfg2 = FleetConfig(relays=(a.url, b.url), replication_factor=1,
                           version=2)
        fb = b.enable_fleet(cfg2)
        b.start()
        moved = [uid for uid in owners if fb.ring.primary(uid) == b.url]
        # The donor ACKs one more write AFTER B computed its gain set
        # but BEFORE B's snapshot install finishes — emulated by
        # writing between the sweep's summary leg and cutover via the
        # snapshot-request hook.
        straggler = moved[0]
        orig_post = fb._post

        def post_with_straggler(url, body):
            if url.endswith("/replicate/snapshot"):
                # Landed on the donor pre-capture: included in the
                # snapshot — or post-capture: healed by gossip. Both
                # must converge; this exercises the window.
                a.store.add_messages(
                    straggler, _msgs(owners.index(straggler), 2, t0=100))
            return orig_post(url, body)

        fb._post = post_with_straggler
        a.fleet.apply_config(cfg2, rebalance=False)
        assert fb.rebalance_once() == len(moved)
        # Heal the tail through normal scoped gossip.
        b.replication.add_peer(a.url)
        deadline = time.time() + 10
        while time.time() < deadline:
            b.replication.run_once()
            if all(
                b.store.get_merkle_tree_string(u)
                == a.store.get_merkle_tree_string(u)
                for u in moved
            ):
                break
            time.sleep(0.05)
        for uid in moved:
            assert b.store.replica_messages(uid, "") == \
                a.store.replica_messages(uid, ""), uid
    finally:
        if b is not None:
            b.stop()
        a.stop()


# --- failover ---


def test_down_primary_fails_over_to_next_replica():
    relays = []
    try:
        for _ in range(3):
            relays.append(
                RelayServer(RelayStore(), peers=[],
                            replication_interval_s=30).start()
            )
        cfg = FleetConfig(relays=tuple(s.url for s in relays),
                          replication_factor=2, version=1)
        for s in relays:
            s.enable_fleet(cfg)
        # An owner whose primary is relays[p] and replica relays[q]; a
        # THIRD relay routes requests for it.
        ring = relays[0].fleet.ring
        uid = "f0000"
        i = 0
        while True:
            uid = f"f{i:04d}"
            p = ring.placement(uid)
            if len(p) == 2:
                break
            i += 1
        primary = next(s for s in relays if s.url == p[0])
        replica = next(s for s in relays if s.url == p[1])
        third = next(s for s in relays if s.url not in p)
        action, target = third.fleet.route(uid)
        assert (action, target) == ("redirect", primary.url)
        # Primary goes down; the probe cache expires and the next
        # route fails over to the ring replica.
        primary.stop()
        third.fleet._probe_cache.clear()
        action, target = third.fleet.route(uid)
        assert (action, target) == ("redirect", replica.url)
        from evolu_tpu.obs import metrics

        assert metrics.get_counter("evolu_fleet_failovers_total") >= 1
        # The replica, being placed, serves.
        out = _http_post(replica.url + "/", _sync_body(uid, _msgs(9, 2)))
        assert protocol.decode_sync_response(out).merkle_tree != "{}"
        relays.remove(primary)
    finally:
        for s in relays:
            s.stop()


def test_forwarded_request_never_reforwarded():
    """The hop guard: a /fleet/forward landing on a relay that (per a
    diverged mid-reload ring) is NOT placed for the owner is served
    locally, never bounced again."""
    a = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    try:
        a.enable_fleet(FleetConfig(relays=(a.url, "http://127.0.0.1:1"),
                                   replication_factor=1, version=1,
                                   forward=True))
        ring = a.fleet.ring
        uid = _owner_for(ring, "http://127.0.0.1:1", prefix="h")
        # A direct POST / in forward mode with NO placed relay passing
        # the readiness probe sheds 503 + Retry-After instead of
        # pinning a handler thread on a POST to a known-down peer.
        with pytest.raises(urllib.error.HTTPError) as e:
            _http_post(a.url + "/", _sync_body(uid), retries=0)
        assert e.value.code == 503
        assert float(e.value.headers.get("Retry-After")) > 0
        # The envelope path must serve locally instead.
        env = protocol.encode_fleet_forward(
            protocol.FleetForward(_sync_body(uid, _msgs(3, 2)),
                                  "http://origin:1", 1))
        out = _http_post(a.url + "/fleet/forward", env)
        assert protocol.decode_sync_response(out).merkle_tree != "{}"
        assert a.store.user_ids() == [uid]
        # The hop guard is enforced on the wire too: anything but a
        # single-hop envelope answers 400 before any side effect.
        bad = protocol.encode_fleet_forward(
            protocol.FleetForward(_sync_body(uid), "http://origin:1", 2))
        with pytest.raises(urllib.error.HTTPError) as e:
            _http_post(a.url + "/fleet/forward", bad)
        assert e.value.code == 400
    finally:
        a.stop()


def test_forward_to_non_fleet_peer_answers_502_not_503():
    """A peer that DEFINITIVELY rejects the forward (404: not
    fleet-enabled / older build) must surface as 502 + errors_total,
    not be masked as retry-forever flow control."""
    from evolu_tpu.obs import metrics

    plain = RelayServer(RelayStore()).start()  # no fleet: /fleet/* 404s
    a = RelayServer(RelayStore(), peers=[], replication_interval_s=30).start()
    try:
        a.enable_fleet(FleetConfig(relays=(a.url, plain.url),
                                   replication_factor=1, version=1,
                                   forward=True))
        uid = _owner_for(a.fleet.ring, plain.url, prefix="p")
        errs = metrics.get_counter("evolu_relay_errors_total")
        with pytest.raises(urllib.error.HTTPError) as e:
            _http_post(a.url + "/", _sync_body(uid, _msgs(5, 1)), retries=0)
        assert e.value.code == 502
        # The forwarder counted it (the peer's bare 404 does not inc
        # the shared registry): definitive rejection IS an error-rate
        # event, unlike the 503 flow-control path.
        assert metrics.get_counter("evolu_relay_errors_total") == errs + 1
    finally:
        a.stop()
        plain.stop()
