"""Mechanical guard for the module-level-jnp-constant invariant.

CLAUDE.md: a concrete jnp array created at import time initializes the
XLA backend and breaks `jax.distributed.initialize` (the multi-host
join must run before any backend touch). Until now the rule lived in
comments; this test enforces it for EVERY `evolu_tpu` module — current
and future (including the jax-free `obs/` package) — by importing each
one in a subprocess whose jax backend is stubbed out: `JAX_PLATFORMS`
names a platform that does not exist, so the import itself succeeds
(jax import never touches a backend) but ANY import-time concrete
array / device lookup raises. A module that imports cleanly there is
proven backend-free at import.
"""

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SCRIPT = r"""
import importlib, json, pkgutil
import evolu_tpu

names = sorted(
    {"evolu_tpu"}
    | {m.name for m in pkgutil.walk_packages(evolu_tpu.__path__, "evolu_tpu.")}
)
bad = {}
for name in names:
    try:
        importlib.import_module(name)
    except Exception as e:  # noqa: BLE001 - report every offender at once
        bad[name] = f"{type(e).__name__}: {e}"
print("RESULT:" + json.dumps(bad))
"""


def test_no_module_initializes_the_xla_backend_at_import():
    env = dict(os.environ)
    # A platform that cannot exist: backend init raises, import machinery
    # does not. Strip the axon tunnel vars like conftest does.
    env["JAX_PLATFORMS"] = "evolu_import_guard_no_such_platform"
    for var in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE"):
        env.pop(var, None)
    env["PYTHONPATH"] = _REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
    )
    proc = subprocess.run(
        [sys.executable, "-c", _SCRIPT],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, f"guard subprocess died:\n{proc.stderr[-3000:]}"
    line = [l for l in proc.stdout.splitlines() if l.startswith("RESULT:")][-1]
    bad = json.loads(line[len("RESULT:"):])
    assert bad == {}, (
        "modules touch the XLA backend at import time (module-level jnp "
        f"constant or device lookup — breaks jax.distributed.initialize): {bad}"
    )


def test_obs_package_never_imports_jax():
    """The observability package records host-side Python values only;
    the cheap mechanical proxy is that importing it (alone) must not
    pull jax into the process at all. (The package import covers
    obs.trace too — it is re-exported from obs/__init__.py.)"""
    proc = subprocess.run(
        [sys.executable, "-c",
         "import sys; import evolu_tpu.obs; "
         "print('JAX_LOADED' if 'jax' in sys.modules else 'CLEAN')"],
        env={**os.environ, "PYTHONPATH": _REPO},
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "CLEAN" in proc.stdout, "evolu_tpu.obs transitively imported jax"


def test_anatomy_module_never_imports_jax_and_prices_without_a_backend():
    """ISSUE 16's explicit pin for the stage-anatomy module ALONE:
    importing, setting the platform, pricing floors, recording stages,
    fingerprinting the registry, and rendering the /stats payload must
    never pull jax into the process — the plane runs on relays that
    serve pure-host workloads and must stay jax-free (the platform is
    PUSHED in from parallel/mesh.py on jax-side paths)."""
    script = (
        "import sys; from evolu_tpu.obs import anatomy; "
        "anatomy.set_platform('tpu'); "
        "assert anatomy.floor_ms('key_sort', rows=1_000_000) > 0; "
        "anatomy.record_stage('host_apply', 0.01, rows=7200); "
        "assert len(anatomy.registry_digest()) == 8; "
        "p = anatomy.stages_payload(); "
        "assert p['stages']['host_apply']['count'] == 1; "
        "print('JAX_LOADED' if 'jax' in sys.modules else 'CLEAN')"
    )
    proc = subprocess.run(
        [sys.executable, "-c", script],
        env={**os.environ, "PYTHONPATH": _REPO},
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "CLEAN" in proc.stdout, "evolu_tpu.obs.anatomy transitively imported jax"


def test_trace_module_never_imports_jax_and_never_touches_a_backend():
    """ISSUE 10's explicit pin for the tracing module ALONE (not just
    via the package import): importing, minting spans, parsing and
    formatting headers, and exporting must neither pull jax into the
    process nor touch any backend — tracing runs on relays that never
    load jax at all."""
    script = (
        "import sys; from evolu_tpu.obs import trace; "
        "s = trace.start_span('t', attrs={'k': 1}); "
        "ctx = s.context; s.end(); "
        "assert trace.parse_traceparent(trace.format_traceparent(ctx)); "
        "trace.serve_trace(ctx.trace_id); trace.export_chrome(); "
        "print('JAX_LOADED' if 'jax' in sys.modules else 'CLEAN')"
    )
    proc = subprocess.run(
        [sys.executable, "-c", script],
        env={**os.environ, "PYTHONPATH": _REPO},
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "CLEAN" in proc.stdout, "evolu_tpu.obs.trace transitively imported jax"
